"""Failure-hardening tier-1 tests (ISSUE 2 robustness).

Covers, WITHOUT subprocesses or real sleeps:

- every fault-injection mode of ``utils.faults`` (fail-N, always-fail,
  delay, corrupt-bytes, env grammar);
- the backoff schedule against a fake clock, and ``call_with_retries``
  attempt counting through ``utils.profiler``;
- durable-checkpoint failure paths: transient write faults healed by retry,
  checksum corruption detected + version fallback, missing chunks named,
  atomic pytree saves, validated pytree loads;
- the non-finite training guard (eager + compiled; params frozen, skip
  counters device-side);
- bootstrap bring-up retry and idempotent finalize.

The SIGKILL crash-recovery test lives in tests/test_chaos.py (chaos lane).
"""

import importlib.util
import json
import os
import re
import sys
import zlib

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.core import io as htio
from heat_tpu.utils import faults, profiler


@pytest.fixture(autouse=True)
def _clean_counters():
    faults.reset_trips()
    profiler.reset_counters()
    yield


# fast retry policy for tests: no real backoff sleeps in tier-1
FAST_RETRY = {"retries": 4, "base_delay": 0.0, "max_delay": 0.0, "jitter": 0.0}


@pytest.fixture
def fast_io_retry(monkeypatch):
    monkeypatch.setattr(htio, "IO_RETRY", FAST_RETRY)


class TestFaultModes:
    def test_disarmed_site_is_noop(self):
        faults.fire("io.write")
        assert faults.trip_count("io.write") == 0

    def test_fail_n_times(self):
        with faults.inject("io.write", fail=2):
            for _ in range(2):
                with pytest.raises(faults.TransientFault):
                    faults.fire("io.write")
            faults.fire("io.write")  # healed
        assert faults.trip_count("io.write") == 3
        faults.fire("io.write")  # disarmed again outside the block
        assert faults.trip_count("io.write") == 3

    def test_always_fail(self):
        with faults.inject("comm.host_fetch", fail=-1):
            for _ in range(5):
                with pytest.raises(faults.TransientFault):
                    faults.fire("comm.host_fetch")

    def test_custom_exception(self):
        class Boom(faults.InjectedFault):
            pass

        with faults.inject("dist.init", fail=1, exc=Boom):
            with pytest.raises(Boom):
                faults.fire("dist.init")

    def test_delay(self):
        import time

        with faults.inject("io.write", delay=0.05):
            t0 = time.perf_counter()
            faults.fire("io.write")
            assert time.perf_counter() - t0 >= 0.05

    def test_corrupt_flips_one_byte(self, tmp_path):
        p = str(tmp_path / "blob")
        payload = bytes(range(64))
        with open(p, "wb") as fh:
            fh.write(payload)
        with faults.inject("io.write", corrupt=1):
            faults.fire("io.write", path=p)
            faults.fire("io.write", path=p)  # countdown exhausted: no-op
        with open(p, "rb") as fh:
            got = fh.read()
        diff = [i for i in range(64) if got[i] != payload[i]]
        assert diff == [32]  # exactly one byte, at the middle offset
        assert got[32] == payload[32] ^ 0xFF

    def test_transient_fault_is_oserror(self):
        # real-world `except OSError` handling must catch injected faults
        assert issubclass(faults.TransientFault, OSError)

    def test_env_grammar(self):
        specs = faults.parse_spec("io.write:delay=0.25,fail=2; dist.init:fail=-1")
        assert specs["io.write"].delay == 0.25
        assert specs["io.write"].fail == 2
        assert specs["dist.init"].fail == -1
        with pytest.raises(ValueError):
            faults.parse_spec("io.write:explode=1")
        assert faults.parse_spec("") == {}

    def test_scheduler_sites_armable(self):
        """ISSUE 10 satellite: the serving fault sites (``sched.dispatch``,
        ``sched.journal.write``) parse from the env grammar — the chaos
        lane's SIGKILL-mid-queue arming — and fire like any other site.
        The scheduler-side behavior (retry/deadline-trip/journal-refusal)
        lives in tests/test_scheduler.py."""
        specs = faults.parse_spec(
            "sched.dispatch:exit=4;sched.journal.write:fail=1"
        )
        assert specs["sched.dispatch"].exit == 4
        assert specs["sched.journal.write"].fail == 1
        with faults.inject("sched.dispatch", fail=1):
            with pytest.raises(faults.TransientFault):
                faults.fire("sched.dispatch")
        with faults.inject("sched.journal.write", hang=0, fail=1):
            with pytest.raises(faults.TransientFault):
                faults.fire("sched.journal.write")


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# a fault-site literal at an arming/firing call: fire("..."), _fire("..."),
# inject("..."), trip_count("...") — the textual surface HT113 also checks
_SITE_CALL = re.compile(r"""(?:fire|inject|trip_count)\(\s*(['"])([^'"]+)\1""")


def _fresh_faults(name):
    """An independently spec-loaded twin of utils/faults.py — what a
    standalone chaos-campaign host or a replayed rank actually gets."""
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "heat_tpu", "utils", "faults.py")
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


class TestCatalog:
    def test_catalog_shape(self):
        cat = faults.catalog()
        sites = faults.catalog_sites()
        assert len(cat) == len(sites) >= 10
        for entry in cat:
            assert set(entry) >= {"site", "modes", "layer", "fires"}
            assert entry["modes"], f"{entry['site']}: no meaningful modes"
            for m in entry["modes"]:
                assert m in faults.MODES

    def test_catalog_returns_copies(self):
        faults.catalog()[0]["site"] = "mutated"
        assert "mutated" not in faults.catalog_sites()

    def test_every_fault_site_literal_in_repo_is_cataloged(self):
        """ISSUE 20 satellite: grep the whole repo for fault-site string
        literals at arming/firing sites — every one must be a catalog
        member (a typo'd site silently never fires), and every catalog
        member must actually be armed or fired somewhere (a dead entry
        would let the campaign claim coverage it cannot have)."""
        known = faults.catalog_sites()
        found = {}
        for root in ("heat_tpu", "scripts", "tests", "benchmarks",
                     "tutorials"):
            base = os.path.join(REPO, root)
            if not os.path.isdir(base):
                continue
            for dirpath, _, files in os.walk(base):
                for fname in files:
                    if not fname.endswith(".py"):
                        continue
                    path = os.path.join(dirpath, fname)
                    with open(path, encoding="utf-8") as fh:
                        src = fh.read()
                    for m in _SITE_CALL.finditer(src):
                        found.setdefault(m.group(2), set()).add(
                            os.path.relpath(path, REPO)
                        )
        # placeholder prose ("...") and the deliberately-misspelled
        # examples HT113's docs and fixtures demonstrate the bug with
        bogus = {"...", "io.wrte", "bogus.site"}
        unknown = {
            s: sorted(ps) for s, ps in found.items()
            if s not in known and s not in bogus
        }
        assert not unknown, (
            f"fault-site literals not in faults.catalog(): {unknown}"
        )
        dead = known - set(found)
        assert not dead, f"catalog sites never fired or armed anywhere: {dead}"

    def test_render_spec_round_trip(self):
        text = "io.write:delay=0.25,fail=2;sched.dispatch:exit=4"
        specs = faults.parse_spec(text)
        rendered = faults.render_spec(specs)
        again = faults.parse_spec(rendered)
        assert faults.render_spec(again) == rendered
        assert again["io.write"].delay == 0.25
        assert again["io.write"].fail == 2
        assert again["sched.dispatch"].exit == 4
        assert faults.render_spec({}) == ""

    def test_trips_accessor(self):
        with faults.inject("io.write", fail=1):
            with pytest.raises(faults.TransientFault):
                faults.fire("io.write")
        faults.fire("io.read")  # disarmed: no trip recorded
        assert faults.trips() == {"io.write": 1}


class TestDeterministicJitter:
    def test_jitter_unit_deterministic_across_loads(self):
        """ISSUE 20 satellite: the backoff jitter is a pure function of
        ``(site, attempt)`` — two independently loaded ranks (or a replayed
        chaos schedule) derive identical sleep sequences."""
        a = _fresh_faults("_faults_twin_a")
        b = _fresh_faults("_faults_twin_b")
        try:
            for site in ("io.write", "comm.collective", "sched.dispatch"):
                for attempt in range(6):
                    u = faults.jitter_unit(site, attempt)
                    assert 0.0 <= u < 1.0
                    assert a.jitter_unit(site, attempt) == u
                    assert b.jitter_unit(site, attempt) == u
        finally:
            del sys.modules["_faults_twin_a"], sys.modules["_faults_twin_b"]

    def test_jitter_decorrelates_sites_and_attempts(self):
        # the reason jitter exists: concurrent retriers must spread out
        assert faults.jitter_unit("io.write", 0) != faults.jitter_unit(
            "io.read", 0
        )
        draws = {faults.jitter_unit("io.write", i) for i in range(8)}
        assert len(draws) == 8

    def test_backoff_default_uses_seeded_jitter(self):
        want = [
            min(2.0, 0.1 * 2.0**i) * (1.0 + 0.5 * faults.jitter_unit("io.write", i))
            for i in range(4)
        ]
        got = list(
            faults.backoff_schedule(4, base_delay=0.1, jitter=0.5,
                                    site="io.write")
        )
        np.testing.assert_allclose(got, want)
        # and the schedule is reproducible call-to-call (no process entropy)
        assert got == list(
            faults.backoff_schedule(4, base_delay=0.1, jitter=0.5,
                                    site="io.write")
        )


class TestBackoff:
    def test_schedule_exponential_and_capped(self):
        delays = list(
            faults.backoff_schedule(
                5, base_delay=0.1, factor=2.0, max_delay=0.5, jitter=0.0, rand=lambda: 1.0
            )
        )
        np.testing.assert_allclose(delays, [0.1, 0.2, 0.4, 0.5, 0.5])

    def test_schedule_jitter_bounds(self):
        lo = list(faults.backoff_schedule(3, base_delay=0.1, jitter=0.5, rand=lambda: 0.0))
        hi = list(faults.backoff_schedule(3, base_delay=0.1, jitter=0.5, rand=lambda: 1.0))
        for a, b in zip(lo, hi):
            assert b == pytest.approx(a * 1.5)

    def test_retries_follow_schedule_fake_clock(self):
        slept = []
        with faults.inject("io.write", fail=3):
            out = faults.call_with_retries(
                lambda: faults.fire("io.write") or "done",
                "io.write",
                retries=4,
                base_delay=0.1,
                jitter=0.0,
                sleep=slept.append,
                rand=lambda: 0.0,
            )
        assert out == "done"
        np.testing.assert_allclose(slept, [0.1, 0.2, 0.4])
        assert profiler.counters()["retry.io.write"] == 3

    def test_retry_exhaustion_reraises(self):
        slept = []
        with faults.inject("io.write", fail=-1):
            with pytest.raises(faults.TransientFault):
                faults.call_with_retries(
                    lambda: faults.fire("io.write"),
                    "io.write",
                    retries=2,
                    sleep=slept.append,
                )
        assert len(slept) == 2  # retried exactly `retries` times, then gave up

    def test_retry_if_narrows(self):
        calls = []

        def fn():
            calls.append(1)
            raise FileNotFoundError("gone")

        with pytest.raises(FileNotFoundError):
            faults.call_with_retries(
                fn, "io.read", retries=3, sleep=lambda _: None,
                retry_if=lambda e: not isinstance(e, FileNotFoundError),
            )
        assert len(calls) == 1  # not retried: absence is not transient


class TestDurableArrayCheckpoint:
    def test_transient_write_faults_healed_by_backoff(self, ht, tmp_path, fast_io_retry):
        d = np.arange(32, dtype=np.float32)
        ckpt = str(tmp_path / "ckpt")
        with faults.inject("io.write", fail=2):
            ht.save_array_checkpoint(ht.array(d, split=0), ckpt)
        back = ht.load_array_checkpoint(ckpt)
        np.testing.assert_array_equal(back.numpy(), d)
        # acceptance: backoff attempts visible in utils.profiler counters
        assert profiler.counters()["retry.io.write"] == 2

    def test_fsync_fault_retried(self, ht, tmp_path, fast_io_retry):
        d = np.arange(16, dtype=np.float32)
        ckpt = str(tmp_path / "fs")
        with faults.inject("io.fsync", fail=1):
            ht.save_array_checkpoint(ht.array(d, split=0), ckpt)
        np.testing.assert_array_equal(ht.load_array_checkpoint(ckpt).numpy(), d)

    def test_write_fault_exhaustion_keeps_previous_version(self, ht, tmp_path, fast_io_retry):
        d1 = np.arange(16, dtype=np.float32)
        d2 = d1 + 100
        ckpt = str(tmp_path / "boom")
        ht.save_array_checkpoint(ht.array(d1, split=0), ckpt)
        with faults.inject("io.write", fail=-1):
            with pytest.raises(faults.TransientFault):
                ht.save_array_checkpoint(ht.array(d2, split=0), ckpt)
        # the failed save never flipped LATEST: previous version loads intact
        np.testing.assert_array_equal(ht.load_array_checkpoint(ckpt).numpy(), d1)

    def test_meta_records_checksums(self, ht, tmp_path):
        d = np.arange(24, dtype=np.float32)
        ckpt = str(tmp_path / "sums")
        ht.save_array_checkpoint(ht.array(d, split=0), ckpt)
        vdir = os.path.join(ckpt, open(os.path.join(ckpt, "LATEST")).read().strip())
        meta = json.load(open(os.path.join(vdir, "meta.json")))
        assert set(meta["checksums"]) == {str(s) for s in meta["starts"]}
        for s in meta["starts"]:
            path = os.path.join(vdir, f"chunk_{s}.npy")
            payload = open(path, "rb").read()
            assert zlib.crc32(payload) == meta["checksums"][str(s)]
            assert len(payload) == meta["chunk_bytes"][str(s)]

    def test_corrupted_chunk_detected(self, ht, tmp_path):
        d = np.arange(40, dtype=np.float32)
        ckpt = str(tmp_path / "rot")
        # corruption injected at write time (post-checksum, models bit rot)
        with faults.inject("io.write", corrupt=1):
            ht.save_array_checkpoint(ht.array(d, split=0), ckpt)
        with pytest.raises(htio.CheckpointCorruptionError, match="checksum"):
            ht.load_array_checkpoint(ckpt)

    def test_corruption_falls_back_to_previous_version(self, ht, tmp_path):
        d1 = np.arange(40, dtype=np.float32)
        d2 = d1 * 2
        ckpt = str(tmp_path / "fb")
        ht.save_array_checkpoint(ht.array(d1, split=0), ckpt)
        with faults.inject("io.write", corrupt=1):
            ht.save_array_checkpoint(ht.array(d2, split=0), ckpt, keep_versions=2)
        # acceptance: checksum detects the flip, loader degrades to v0 (d1)
        with pytest.warns(UserWarning, match="falling back"):
            back = ht.load_array_checkpoint(ckpt)
        np.testing.assert_array_equal(back.numpy(), d1)

    def test_missing_chunk_named(self, ht, tmp_path):
        d = np.arange(64, dtype=np.float32)
        ckpt = str(tmp_path / "gone")
        ht.save_array_checkpoint(ht.array(d, split=0), ckpt)
        vdir = os.path.join(ckpt, open(os.path.join(ckpt, "LATEST")).read().strip())
        victims = sorted(f for f in os.listdir(vdir) if f.startswith("chunk_"))
        os.remove(os.path.join(vdir, victims[1]))
        with pytest.raises(htio.CheckpointCorruptionError, match=victims[1]):
            ht.load_array_checkpoint(ckpt)

    def test_keep_versions_retains_history(self, ht, tmp_path):
        ckpt = str(tmp_path / "hist")
        for k in range(3):
            ht.save_array_checkpoint(
                ht.array(np.full(8, k, np.float32), split=0), ckpt, keep_versions=2
            )
        versions = sorted(v for v in os.listdir(ckpt) if v.startswith("v"))
        assert versions == ["v1", "v2"]
        np.testing.assert_array_equal(
            ht.load_array_checkpoint(ckpt).numpy(), np.full(8, 2, np.float32)
        )

    def test_missing_directory_clear_error(self, ht, tmp_path):
        with pytest.raises(FileNotFoundError, match="nowhere"):
            ht.load_array_checkpoint(str(tmp_path / "nowhere"))

    def test_empty_directory_clear_error(self, ht, tmp_path):
        empty = tmp_path / "empty"
        empty.mkdir()
        with pytest.raises(FileNotFoundError, match="no checkpoint versions"):
            ht.load_array_checkpoint(str(empty))

    def test_host_fetch_transient_fault_retried(self, ht):
        x = ht.arange(16, dtype=ht.float32, split=0)
        with faults.inject("comm.host_fetch", fail=1):
            got = x.numpy()
        np.testing.assert_array_equal(got, np.arange(16, dtype=np.float32))
        assert profiler.counters()["retry.comm.host_fetch"] == 1


class TestDurablePytreeCheckpoint:
    def _tree(self):
        import jax.numpy as jnp

        return {"w": jnp.arange(12, dtype=jnp.float32).reshape(4, 3),
                "b": jnp.zeros(3, jnp.float32)}

    def test_missing_file_clear_error(self, ht, tmp_path):
        with pytest.raises(FileNotFoundError, match="ckpt.npz"):
            ht.core.io.load_checkpoint(self._tree(), str(tmp_path / "ckpt"))

    def test_truncated_npz_clear_error(self, ht, tmp_path):
        p = str(tmp_path / "trunc.npz")
        with open(p, "wb") as fh:
            fh.write(b"PK\x03\x04 definitely not a whole archive")
        with pytest.raises(htio.CheckpointCorruptionError, match="trunc.npz"):
            ht.core.io.load_checkpoint(self._tree(), p)

    def test_foreign_npz_clear_error(self, ht, tmp_path):
        p = str(tmp_path / "foreign.npz")
        np.savez(p, something=np.zeros(3))
        with pytest.raises(htio.CheckpointCorruptionError, match="__keys__"):
            ht.core.io.load_checkpoint(self._tree(), p)

    def test_reshaped_leaf_refused(self, ht, tmp_path):
        import jax.numpy as jnp

        p = str(tmp_path / "shape")
        ht.core.io.save_checkpoint(self._tree(), p)
        reshaped = dict(self._tree(), w=jnp.zeros((3, 4), jnp.float32))
        with pytest.raises(ValueError, match=r"\(4, 3\)"):
            ht.core.io.load_checkpoint(reshaped, p)

    def test_wrong_dtype_refused(self, ht, tmp_path):
        import jax.numpy as jnp

        p = str(tmp_path / "dt")
        ht.core.io.save_checkpoint(self._tree(), p)
        cast = dict(self._tree(), b=jnp.zeros(3, jnp.int32))
        with pytest.raises(ValueError, match="dtype"):
            ht.core.io.load_checkpoint(cast, p)

    def test_atomic_save_preserves_existing_on_crash(self, ht, tmp_path, fast_io_retry):
        import jax.numpy as jnp

        p = str(tmp_path / "atomic")
        tree = self._tree()
        ht.core.io.save_checkpoint(tree, p)
        bigger = {"w": tree["w"] + 1, "b": tree["b"] + 1}
        with faults.inject("io.write", fail=-1):
            with pytest.raises(faults.TransientFault):
                ht.core.io.save_checkpoint(bigger, p)
        # the in-place seed writer would have destroyed the only copy here
        back = ht.core.io.load_checkpoint(tree, p)
        np.testing.assert_array_equal(np.asarray(back["w"]), np.asarray(tree["w"]))

    def test_roundtrip_still_works(self, ht, tmp_path):
        p = str(tmp_path / "rt")
        tree = self._tree()
        ht.core.io.save_checkpoint(tree, p)
        back = ht.core.io.load_checkpoint(tree, p)
        for a, b in zip(np.asarray(back["w"]), np.asarray(tree["w"])):
            np.testing.assert_array_equal(a, b)
        # tmp sibling (now .npz.tmp.<pid>, per-process unique) renamed away
        assert not any(".tmp" in f for f in os.listdir(tmp_path))


class TestNonFiniteGuard:
    def _setup(self):
        import jax

        m = ht.nn.Sequential(ht.nn.Linear(4, 4))
        opt = ht.optim.DataParallelOptimizer("sgd", lr=0.1)
        p = m.init(jax.random.key(0))
        opt.init_state(p)
        return m, opt, p

    def test_eager_nan_step_skipped(self):
        import jax
        import jax.numpy as jnp

        _, opt, p = self._setup()
        nan_g = jax.tree.map(lambda q: jnp.full_like(q, jnp.nan), p)
        p2 = opt.step(p, nan_g)
        for a, b in zip(jax.tree_util.tree_leaves(p), jax.tree_util.tree_leaves(p2)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert opt.guard_stats() == {"steps": 1, "skipped": 1}
        # a finite step afterwards applies normally (state not poisoned)
        ones_g = jax.tree.map(jnp.ones_like, p)
        p3 = opt.step(p2, ones_g)
        assert not np.allclose(
            np.asarray(jax.tree_util.tree_leaves(p3)[0]),
            np.asarray(jax.tree_util.tree_leaves(p2)[0]),
        )
        assert opt.guard_stats() == {"steps": 2, "skipped": 1}

    def test_inf_also_skipped(self):
        import jax
        import jax.numpy as jnp

        _, opt, p = self._setup()
        inf_g = jax.tree.map(lambda q: jnp.full_like(q, jnp.inf), p)
        opt.step(p, inf_g)
        assert opt.guard_stats()["skipped"] == 1

    def test_guard_opt_out(self):
        import jax
        import jax.numpy as jnp

        m = ht.nn.Sequential(ht.nn.Linear(4, 4))
        opt = ht.optim.DataParallelOptimizer("sgd", lr=0.1, guard_nonfinite=False)
        p = m.init(jax.random.key(0))
        opt.init_state(p)
        nan_g = jax.tree.map(lambda q: jnp.full_like(q, jnp.nan), p)
        p2 = opt.step(p, nan_g)
        assert np.isnan(np.asarray(jax.tree_util.tree_leaves(p2)[0])).any()

    def test_compiled_data_parallel_step_skips_on_device(self):
        """Acceptance: a NaN batch through the jitted DataParallel step
        leaves params bit-identical, bumps the device-side skip counter, and
        the step emits no host sync (its outputs stay jax.Arrays)."""
        import jax
        import jax.numpy as jnp

        model = ht.nn.Sequential(ht.nn.Linear(8, 4))
        opt = ht.optim.DataParallelOptimizer("sgd", lr=0.1)
        dp = ht.nn.DataParallel(model, optimizer=opt)
        params = dp.init(jax.random.key(0))
        state = opt.init_state(params)
        step = dp.make_train_step(lambda pred, y: jnp.mean((pred - y) ** 2),
                                  donate=False)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)
        y = jnp.asarray(rng.standard_normal((16, 4)), jnp.float32)
        params1, state1, loss1 = step(params, state, x, y)
        y_nan = y.at[0, 0].set(jnp.nan)
        params2, state2, loss2 = step(params1, state1, x, y_nan)
        assert isinstance(loss2, jax.Array)  # async: no float() in the path
        for a, b in zip(jax.tree_util.tree_leaves(params1),
                        jax.tree_util.tree_leaves(params2)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert opt.guard_stats(state2) == {"steps": 2, "skipped": 1}

    def test_daso_nan_step_skipped_with_counters(self):
        """Acceptance: DASO step with an injected NaN gradient — params
        unchanged, skip counter (device-resident, in the opt state)
        incremented, loss returned as an async array."""
        import jax
        import jax.numpy as jnp

        from heat_tpu.optim.dp_optimizer import DASO, DataParallelOptimizer

        if len(jax.devices()) % 2:
            pytest.skip("DASO needs an even device count")
        daso = DASO(DataParallelOptimizer("sgd", lr=0.1), warmup_steps=0,
                    global_skip=1000)
        model = ht.nn.Sequential(ht.nn.Linear(8, 4))
        daso.init(model, key=jax.random.key(0))
        loss_fn = lambda pred, y: jnp.mean((pred - y) ** 2)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)
        y = jnp.asarray(rng.standard_normal((16, 4)), jnp.float32)
        daso.step(loss_fn, x, y)
        assert daso.skip_stats() == {"steps": 1, "skipped": 0}
        snap = jax.device_get(daso._params)
        out = daso.step(loss_fn, x, y.at[0, 0].set(jnp.nan))
        assert isinstance(out, jax.Array)
        after = jax.device_get(daso._params)
        for a, b in zip(jax.tree_util.tree_leaves(snap),
                        jax.tree_util.tree_leaves(after)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        stats = daso.skip_stats()
        assert stats["steps"] == 2 and stats["skipped"] >= 1
        # surfaced through the profiler provider (name unique per instance)
        assert profiler.counters()[f"{daso.profiler_key}.skipped_steps"] == stats["skipped"]

    def test_daso_auto_checkpoint_and_resume(self, tmp_path):
        import jax
        import jax.numpy as jnp

        from heat_tpu.optim.dp_optimizer import DASO, DataParallelOptimizer

        if len(jax.devices()) % 2:
            pytest.skip("DASO needs an even device count")
        d = str(tmp_path / "daso")
        model = ht.nn.Sequential(ht.nn.Linear(8, 4))
        loss_fn = lambda pred, y: jnp.mean((pred - y) ** 2)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)
        y = jnp.asarray(rng.standard_normal((16, 4)), jnp.float32)

        daso = DASO(DataParallelOptimizer("sgd", lr=0.1), warmup_steps=0,
                    global_skip=1000, checkpoint_every=2, checkpoint_dir=d)
        daso.init(model, key=jax.random.key(0))
        assert not daso.resume()  # nothing saved yet
        for _ in range(4):
            daso.step(loss_fn, x, y)
        want = jax.device_get(daso._params)

        fresh = DASO(DataParallelOptimizer("sgd", lr=0.1), warmup_steps=0,
                     global_skip=1000, checkpoint_every=2, checkpoint_dir=d)
        fresh.init(model, key=jax.random.key(42))  # different init: must be overwritten
        assert fresh.resume()
        assert fresh._step_count == 4
        got = jax.device_get(fresh._params)
        for a, b in zip(jax.tree_util.tree_leaves(want),
                        jax.tree_util.tree_leaves(got)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # the restored optimizer keeps training from where it left off
        fresh.step(loss_fn, x, y)
        assert fresh._step_count == 5

    def test_checkpoint_every_requires_dir(self):
        from heat_tpu.optim.dp_optimizer import DASO, DataParallelOptimizer

        with pytest.raises(ValueError, match="checkpoint_dir"):
            DASO(DataParallelOptimizer("sgd", lr=0.1), checkpoint_every=5)

    @staticmethod
    def _trained_daso(d, steps=4, ck_every=2, **daso_kw):
        import jax
        import jax.numpy as jnp

        from heat_tpu.optim.dp_optimizer import DASO, DataParallelOptimizer

        model = ht.nn.Sequential(ht.nn.Linear(8, 4))
        loss_fn = lambda pred, y: jnp.mean((pred - y) ** 2)  # noqa: E731
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)
        y = jnp.asarray(rng.standard_normal((16, 4)), jnp.float32)
        daso = DASO(DataParallelOptimizer("sgd", lr=0.1), warmup_steps=0,
                    global_skip=1000, checkpoint_every=ck_every,
                    checkpoint_dir=d, **daso_kw)
        daso.init(model, key=jax.random.key(0))
        for _ in range(steps):
            daso.step(loss_fn, x, y)
        return model, loss_fn, daso

    def test_daso_checkpoint_writes_world_meta_sidecar(self, tmp_path):
        """The sidecar records step + world shape — the restart-with-resume
        contract's pre-load validation input (ISSUE 5 satellite)."""
        import jax
        import json

        if len(jax.devices()) % 2:
            pytest.skip("DASO needs an even device count")
        d = str(tmp_path / "daso")
        _, _, daso = self._trained_daso(d)
        with open(os.path.join(d, "daso_state.meta.json")) as fh:
            meta = json.load(fh)
        assert meta["step"] == 4
        assert meta["n_groups"] == daso.n_groups
        assert meta["ici"] == daso.ici_size
        assert meta["devices"] == len(jax.devices())
        # the previous durable state is preserved for the fallback chain
        assert os.path.exists(os.path.join(d, "daso_state.prev.npz"))

    def test_daso_resume_world_size_mismatch_clear_error(self, tmp_path):
        """A restarted world with a different topology must get a CLEAR
        error naming both worlds — not a shape crash deep in the loader."""
        import jax

        from heat_tpu.optim.dp_optimizer import DASO, DataParallelOptimizer

        if len(jax.devices()) < 4 or len(jax.devices()) % 4:
            pytest.skip("needs >= 4 devices for two distinct topologies")
        d = str(tmp_path / "daso")
        model, _, daso = self._trained_daso(d, total_local_comm_size=2)
        other = DASO(DataParallelOptimizer("sgd", lr=0.1), warmup_steps=0,
                     global_skip=1000, checkpoint_every=2, checkpoint_dir=d,
                     total_local_comm_size=4)
        other.init(model, key=jax.random.key(1))
        assert other.n_groups != daso.n_groups
        with pytest.raises(ValueError, match="different world"):
            other.resume()

    def test_daso_resume_corrupted_latest_falls_back(self, tmp_path):
        """Corrupted-LATEST fallback chain: a torn/corrupt newest checkpoint
        degrades (with a warning and a ``health.resume.fallbacks`` counter)
        to the preserved previous state instead of failing the resume."""
        import warnings

        import jax

        from heat_tpu.optim.dp_optimizer import DASO, DataParallelOptimizer
        from heat_tpu.utils import health

        if len(jax.devices()) % 2:
            pytest.skip("DASO needs an even device count")
        d = str(tmp_path / "daso")
        model, _, _ = self._trained_daso(d, steps=4, ck_every=2)
        # newest checkpoint (step 4) gets torn; prev (step 2) must verify
        with open(os.path.join(d, "daso_state.npz"), "r+b") as fh:
            fh.truncate(100)
        base = health.counters().get("health.resume.fallbacks", 0)
        fresh = DASO(DataParallelOptimizer("sgd", lr=0.1), warmup_steps=0,
                     global_skip=1000, checkpoint_every=2, checkpoint_dir=d)
        fresh.init(model, key=jax.random.key(42))
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            assert fresh.resume()
        assert fresh._step_count == 2  # the previous durable state
        assert any("falling back" in str(x.message) for x in w)
        assert health.counters()["health.resume.fallbacks"] == base + 1
        # training continues from the restored state
        assert fresh._pending is None

    def test_daso_resume_both_corrupt_raises(self, tmp_path):
        """When nothing verifies, the corruption error surfaces (the end of
        the fallback chain is an error, never silent garbage)."""
        import jax

        from heat_tpu.core.io import CheckpointCorruptionError
        from heat_tpu.optim.dp_optimizer import DASO, DataParallelOptimizer

        if len(jax.devices()) % 2:
            pytest.skip("DASO needs an even device count")
        d = str(tmp_path / "daso")
        model, _, _ = self._trained_daso(d, steps=4, ck_every=2)
        for name in ("daso_state.npz", "daso_state.prev.npz"):
            with open(os.path.join(d, name), "r+b") as fh:
                fh.truncate(50)
        fresh = DASO(DataParallelOptimizer("sgd", lr=0.1), warmup_steps=0,
                     global_skip=1000, checkpoint_every=2, checkpoint_dir=d)
        fresh.init(model, key=jax.random.key(42))
        with pytest.raises(CheckpointCorruptionError):
            fresh.resume()

    def test_two_dasos_do_not_shadow_counters(self):
        from heat_tpu.optim.dp_optimizer import DASO, DataParallelOptimizer

        a = DASO(DataParallelOptimizer("sgd", lr=0.1))
        b = DASO(DataParallelOptimizer("sgd", lr=0.1))
        assert a.profiler_key != b.profiler_key
        c = profiler.counters()
        assert f"{a.profiler_key}.steps" in c and f"{b.profiler_key}.steps" in c

    def test_guard_stats_on_donated_state_clear_error(self):
        """make_train_step's donate=True default consumes the eagerly
        tracked opt state: the no-arg guard_stats() must say so instead of
        surfacing a bare deleted-buffer RuntimeError."""
        import jax
        import jax.numpy as jnp

        model = ht.nn.Sequential(ht.nn.Linear(4, 2))
        opt = ht.optim.DataParallelOptimizer("sgd", lr=0.1)
        dp = ht.nn.DataParallel(model, optimizer=opt)
        params = dp.init(jax.random.key(0))
        state = opt.init_state(params)
        step = dp.make_train_step(lambda p, y: jnp.mean((p - y) ** 2))  # donates
        x = jnp.zeros((8, 4), jnp.float32)
        y = jnp.zeros((8, 2), jnp.float32)
        params, state, _ = step(params, state, x, y)
        with pytest.raises(RuntimeError, match="donated to the train step"):
            opt.guard_stats()
        assert opt.guard_stats(state)["steps"] == 1  # the rebound state works


class TestBootstrapRobustness:
    def test_retrying_initialize_heals_coordinator_lag(self):
        from heat_tpu.core import bootstrap

        calls = []

        def flaky(**kw):
            calls.append(kw)
            if len(calls) < 3:
                raise RuntimeError("coordinator connect failed: connection refused")

        bootstrap._retrying_initialize(flaky, {"num_processes": 2},
                                       retries=4, sleep=lambda _: None)
        assert len(calls) == 3
        assert profiler.counters()["retry.dist.init"] == 2

    def test_misconfiguration_not_retried(self):
        from heat_tpu.core import bootstrap

        calls = []

        def bad(**kw):
            calls.append(kw)
            raise RuntimeError("process_id 7 out of range for num_processes 2")

        with pytest.raises(RuntimeError, match="out of range"):
            bootstrap._retrying_initialize(bad, {}, retries=4, sleep=lambda _: None)
        assert len(calls) == 1

    def test_already_initialized_is_success(self):
        from heat_tpu.core import bootstrap

        def already(**kw):
            raise RuntimeError("jax.distributed is already initialized")

        bootstrap._retrying_initialize(already, {}, retries=0, sleep=lambda _: None)

    def test_dist_init_fault_site_fires_per_attempt(self):
        from heat_tpu.core import bootstrap

        with faults.inject("dist.init", fail=2):
            bootstrap._retrying_initialize(lambda **kw: None, {},
                                           retries=3, sleep=lambda _: None)
        assert faults.trip_count("dist.init") == 3

    def test_finalize_distributed_idempotent(self, ht):
        # single-controller: shutdown without init must be a no-op, twice
        ht.core.bootstrap.finalize_distributed()
        ht.core.bootstrap.finalize_distributed()
