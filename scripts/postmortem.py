"""Cross-rank collective post-mortem: name the diverging rank, seq, straggler.

    python scripts/postmortem.py FLIGHT_DIR [--heartbeats DIR]
                                 [--telemetry DIR] [--json OUT] [--context N]

Merges the per-rank flight-recorder rings (``flight_rank<k>.ring``,
written crash-durably by ``heat_tpu.utils.flightrec``) — optionally
joined with the heartbeat beacons and the telemetry JSONL exports — into
ONE verdict:

- ``desync``   — the first sequence number where rank fingerprints
  ``(op, gshape, dtype, src/dst split, wire bytes)`` differ: the classic
  SPMD divergence (a rank-conditional extra/missing collective).  Ranks
  are grouped by fingerprint; a minority group is named as deviating.
- ``straggler`` — fingerprints agree on the common window but one rank's
  sequence stops short: that rank is stuck at its last staged collective
  while its peers moved on.  Wait-time evidence (the ``comm.<name>.wait``
  histograms exported through telemetry) is attached when available.
- ``clean``    — identical streams AND every rank's ring ends in a
  ``shutdown`` record (written by ``bootstrap.finalize_distributed``).
- ``inconclusive`` — no rings, no collective records, or identical
  streams without shutdown markers (a global stall looks like this:
  every rank stuck at the SAME collective).

Deliberately stdlib-only and standalone-loadable (the supervisor loads
this file via ``spec_from_file_location`` from a process that never
imports jax); the ring-format reader is borrowed from
``heat_tpu/utils/flightrec.py``, itself loaded standalone, so there is
exactly one parser for the on-disk format.

Exit code: 0 when a verdict was produced (including ``clean``), 1 when
no rings were found/readable.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FLIGHTREC_PATH = os.path.join(_REPO, "heat_tpu", "utils", "flightrec.py")
_flightrec = None


def _flightrec_mod():
    """The ring-format reader, loaded standalone (never imports heat_tpu)."""
    global _flightrec
    if _flightrec is None:
        in_pkg = sys.modules.get("heat_tpu.utils.flightrec")
        if in_pkg is not None:  # already imported (in-process tests)
            _flightrec = in_pkg
            return _flightrec
        spec = importlib.util.spec_from_file_location(
            "heat_postmortem_flightrec", _FLIGHTREC_PATH
        )
        mod = importlib.util.module_from_spec(spec)
        sys.modules[spec.name] = mod
        spec.loader.exec_module(mod)
        _flightrec = mod
    return _flightrec


# ---------------------------------------------------------------------- #
# loading
# ---------------------------------------------------------------------- #
def load_rings(flight_dir: str) -> Dict[int, dict]:
    """rank → parsed ring (unreadable files are skipped, not fatal — the
    black box must yield whatever it can after any crash)."""
    fr = _flightrec_mod()
    rings: Dict[int, dict] = {}
    for path in fr.find_ring_files(flight_dir):
        try:
            ring = fr.read_ring(path)
        except (OSError, ValueError):
            continue
        rings[int(ring["rank"])] = ring
    return rings


def load_heartbeats(hb_dir: Optional[str]) -> Dict[int, dict]:
    """rank → last heartbeat payload (+ file age in ``age_s``)."""
    import glob
    import time

    out: Dict[int, dict] = {}
    if not hb_dir:
        return out
    for path in sorted(glob.glob(os.path.join(hb_dir, "rank*.json"))):
        base = os.path.basename(path)[len("rank") : -len(".json")]
        try:
            rank = int(base)
        except ValueError:
            continue
        try:
            with open(path) as fh:
                rec = json.load(fh)
            rec["age_s"] = round(time.time() - os.path.getmtime(path), 1)
        except (OSError, ValueError):
            continue
        out[rank] = rec
    return out


def load_wait_hists(telemetry_dir: Optional[str]) -> Dict[int, Dict[str, dict]]:
    """rank → {histogram name → summary} for the ``*.wait`` histograms in
    the per-rank telemetry JSONL exports (last snapshot wins within a
    rank, like the telemetry merge)."""
    import glob

    out: Dict[int, Dict[str, dict]] = {}
    if not telemetry_dir:
        return out
    for path in sorted(glob.glob(os.path.join(telemetry_dir, "rank*.jsonl"))):
        try:
            with open(path) as fh:
                lines = fh.readlines()
        except OSError:
            continue
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("type") != "hist" or not str(rec.get("name", "")).endswith(
                ".wait"
            ):
                continue
            rank = int(rec.get("rank", 0))
            out.setdefault(rank, {})[rec["name"]] = {
                "count": int(rec.get("count", 0)),
                "total_s": round(float(rec.get("total_s", 0.0)), 3),
                "max_s": round(float(rec.get("max_s", 0.0)), 3),
            }
    return out


# ---------------------------------------------------------------------- #
# analysis
# ---------------------------------------------------------------------- #
_FP_FIELDS = ("op", "gshape", "dtype", "src", "dst", "wire")


def _fingerprint(rec: dict, fields: Tuple[str, ...] = _FP_FIELDS) -> Tuple:
    return tuple(
        tuple(v) if isinstance(v := rec.get(f), list) else v for f in fields
    )


def _common_fp_fields(recs: List[dict]) -> Tuple[str, ...]:
    """The fingerprint fields comparable across ``recs``: when any record
    was truncated (``trunc=1`` — the ring writer shed its bulky fields,
    e.g. ``gshape``, to fit the slot), a field absent from SOME records is
    dropped from the comparison rather than read as a divergence — slot
    truncation is per-rank (payload byte lengths differ by rank) and must
    never convict an innocent rank of desync."""
    if not any(rec.get("trunc") for rec in recs):
        return _FP_FIELDS
    return tuple(f for f in _FP_FIELDS if all(f in rec for rec in recs))


def _coll_by_seq(ring: dict) -> Dict[int, dict]:
    return {
        int(r["seq"]): r
        for r in ring.get("records", [])
        if r.get("k") == "coll" and "seq" in r
    }


def _fp_brief(rec: Optional[dict]) -> Optional[dict]:
    if rec is None:
        return None
    out = {f: rec.get(f) for f in _FP_FIELDS if rec.get(f) is not None}
    out["seq"] = rec.get("seq")
    return out


def analyze(
    rings: Dict[int, dict],
    heartbeats: Optional[Dict[int, dict]] = None,
    waits: Optional[Dict[int, Dict[str, dict]]] = None,
    expected_ranks: Optional[List[int]] = None,
) -> dict:
    """Merge per-rank rings into one verdict (see the module docstring for
    the taxonomy).

    ``expected_ranks`` is the world the caller launched (the supervisor
    and the mp launcher pass it): a rank whose ring is MISSING can then
    never hide inside a ``clean`` verdict — a lost black box on a known
    rank is itself the finding.  Independent of it, a rank whose ring
    exists but holds no collective records while peers progressed is
    named a straggler stuck at seq 0 (died/wedged before its first
    collective) instead of being silently dropped from the analysis."""
    verdict: dict = {
        "verdict": "inconclusive",
        "ranks": sorted(rings),
        "last_seq": {},
        "first_divergent_seq": None,
        "divergence": None,
        "straggler": None,
        "detail": "",
    }
    missing = (
        sorted(set(int(r) for r in expected_ranks) - set(rings))
        if expected_ranks is not None
        else []
    )
    if missing:
        verdict["missing_ranks"] = missing
    if heartbeats:
        verdict["heartbeats"] = {
            str(r): {
                k: hb.get(k)
                for k in ("step", "seq", "collective", "status", "age_s")
                if hb.get(k) is not None
            }
            for r, hb in sorted(heartbeats.items())
        }
    if not rings:
        verdict["detail"] = "no flight-recorder ring files found" + (
            f" for rank(s) {missing}" if missing else ""
        )
        return verdict
    # torn/unparseable slots the reader skipped (flightrec.read_ring
    # counts them per ring): on every verdict path, so a lossy ring can
    # never pass for a complete stream — a "clean" verdict over a ring
    # with holes is not clean evidence
    skipped = {
        str(r): rings[r].get("slots_skipped", 0)
        for r in sorted(rings)
        if rings[r].get("slots_skipped")
    }
    if skipped:
        verdict["slots_skipped"] = skipped
    colls = {r: _coll_by_seq(ring) for r, ring in rings.items()}
    with_colls = [r for r in sorted(colls) if colls[r]]
    coll_less = [r for r in sorted(colls) if not colls[r]]
    if with_colls:
        last_seq = {r: max(colls[r]) for r in with_colls}
        verdict["last_seq"] = {str(r): last_seq[r] for r in with_colls}

    # ---- oom: an explicit allocation-failure dump (memory-ledger ``mem``
    # record with oom=1, written by utils.memledger.dump_oom before the
    # error re-raised) is a CAUSE, not a symptom — it outranks every
    # stream heuristic below.  Earliest dump wins when several ranks blew
    # up; the membuf records that follow it carry the dominant live
    # buffers with their minting provenance. ----------------------------- #
    oom_hits = []
    for r in sorted(rings):
        for rec in rings[r].get("records", []):
            if rec.get("k") == "mem" and rec.get("oom"):
                oom_hits.append((rec.get("t", 0), r, rec))
    if oom_hits:
        oom_hits.sort(key=lambda x: x[0])
        _, r, rec = oom_hits[0]
        # ONLY the membuf records of THIS dump: they follow their oom
        # record contiguously (the ledger writes them in one burst), and a
        # ring may hold several dumps (an earlier end-of-step attestation,
        # a second OOM) whose rows must not interleave stale duplicates
        top = []
        seen_i = set()
        collecting = False
        for x in rings[r].get("records", []):
            if x is rec:
                collecting = True
                continue
            if not collecting:
                continue
            if x.get("k") == "membuf":
                i = x.get("i")
                if i in seen_i:
                    break  # a LATER dump's burst restarted its index
                seen_i.add(i)
                top.append(x)
            elif x.get("k") == "mem" and (x.get("oom") or x.get("att")):
                # the next DUMP's header (a second OOM, or an attestation
                # written by dump_to_ring — tagged att=1): its rows belong
                # to it, not this failure — even when THIS dump wrote zero
                # membuf rows (every live buffer under the dispatch
                # threshold), the later burst must not be absorbed
                break
            # anything else — a racing coalesced "d" record, or a
            # concurrent thread's peak-WATERMARK "mem" record landing
            # mid-burst (the dump's rows are separate unlocked appends) —
            # interleaves without ending the collection
        top.sort(key=lambda x: (x.get("i", 1 << 30), -(x.get("nb") or 0)))
        verdict["verdict"] = "oom"
        verdict["oom"] = {
            "rank": r,
            "req_bytes": rec.get("req"),
            "where": rec.get("where"),
            "live_bytes": rec.get("live"),
            "peak_bytes": rec.get("peak"),
            "error": rec.get("err"),
            "top_buffers": top[:8],
        }
        head = top[0] if top else None
        verdict["detail"] = (
            f"rank {r} failed a {rec.get('req', '?')}-byte device allocation "
            f"at {rec.get('where', '?')} with {rec.get('live', '?')} bytes "
            "live"
            + (
                f"; dominant live buffer: {head.get('op')} "
                f"({head.get('nb')} B, {head.get('cat')})"
                if head
                else ""
            )
        )
        return verdict

    if not with_colls:
        verdict["detail"] = "rings contain no collective records"
        return verdict
    first_seq = {r: min(colls[r]) for r in with_colls}

    # ---- desync: first seq (inside the window every ring still holds)
    # where the rank fingerprints differ ------------------------------- #
    lo = max(first_seq.values())
    hi = min(last_seq.values())
    for s in range(lo, hi + 1):
        present = {r: colls[r].get(s) for r in with_colls}
        held = [rec for rec in present.values() if rec is not None]
        fields = _common_fp_fields(held)
        groups: Dict[Tuple, List[int]] = {}
        for r, rec in present.items():
            if rec is not None:
                groups.setdefault(_fingerprint(rec, fields), []).append(r)
        if len(groups) > 1:
            # minority group deviates (ties — e.g. 2 ranks — name all)
            sizes = sorted(len(v) for v in groups.values())
            minority = [
                r for fp, rs in groups.items() if len(rs) == sizes[0] for r in rs
            ]
            majority_possible = sizes[0] < sizes[-1]
            verdict["verdict"] = "desync"
            verdict["first_divergent_seq"] = s
            verdict["divergence"] = {
                str(r): _fp_brief(present[r]) for r in with_colls
            }
            verdict["deviating_ranks"] = sorted(minority) if majority_possible else sorted(
                with_colls
            )
            ops = ", ".join(
                f"rank {r}: {present[r].get('op')}" for r in sorted(present)
                if present[r] is not None
            )
            verdict["detail"] = (
                f"rank fingerprints diverge at seq {s} ({ops})"
                + (
                    f"; minority rank(s) {sorted(minority)} deviate"
                    if majority_possible
                    else "; 2-way split — cannot vote on the deviant"
                )
            )
            return verdict

    # ---- straggler: identical window, but someone's stream stops short.
    # A ring with NO collective records while peers progressed is the
    # extreme case — that rank died or wedged before its first collective
    # (seq 0), and silently dropping it would let a clean verdict lie. #
    global_max = max(last_seq.values())
    behind = sorted(r for r in with_colls if last_seq[r] < global_max)
    if coll_less or behind:
        if coll_less:
            worst, worst_seq, stuck = min(coll_less), 0, None
            behind = sorted(set(behind) | set(coll_less))
        else:
            worst = min(behind, key=lambda r: last_seq[r])
            worst_seq = last_seq[worst]
            stuck = colls[worst][worst_seq]
        verdict["verdict"] = "straggler"
        verdict["straggler"] = {
            "rank": worst,
            "ranks_behind": behind,
            "seq": worst_seq,
            "op": stuck.get("op") if stuck else None,
            "fingerprint": _fp_brief(stuck),
            "lag": global_max - worst_seq,
            "peers_at": global_max,
        }
        if waits and waits.get(worst):
            top = sorted(
                waits[worst].items(), key=lambda kv: -kv[1]["total_s"]
            )[:3]
            verdict["straggler"]["wait"] = dict(top)
        if stuck is not None:
            verdict["detail"] = (
                f"rank {worst} stuck at seq {worst_seq} "
                f"{stuck.get('op')} while peers reached seq {global_max} "
                f"(lag {global_max - worst_seq})"
            )
        else:
            verdict["detail"] = (
                f"rank {worst} staged no collectives (stuck at seq 0) "
                f"while peers reached seq {global_max}"
            )
        if waits:
            verdict["wait_per_rank"] = {
                str(r): w for r, w in sorted(waits.items())
            }
        return verdict

    # ---- identical streams: clean iff every ring ends in shutdown ----- #
    def _has_shutdown(ring: dict) -> bool:
        return any(r.get("k") == "shutdown" for r in ring.get("records", []))

    if missing:
        # a lost black box on a known rank can never hide inside `clean`:
        # the surviving streams agree, but the world's story is incomplete
        verdict["detail"] = (
            f"rank(s) {missing} left no ring file while the surviving "
            f"rank(s) agree through seq {global_max} — cannot attest clean"
        )
    elif all(_has_shutdown(rings[r]) for r in with_colls):
        verdict["verdict"] = "clean"
        verdict["detail"] = (
            f"all {len(with_colls)} rank(s) agree through seq {global_max} "
            "and recorded a clean shutdown"
        )
    else:
        stuck = colls[with_colls[0]][global_max]
        verdict["detail"] = (
            f"all ranks at seq {global_max} ({stuck.get('op')}) with no "
            "shutdown record — global stall, or the run was cut before "
            "teardown"
        )
    return verdict


def analyze_dir(
    flight_dir: str,
    heartbeat_dir: Optional[str] = None,
    telemetry_dir: Optional[str] = None,
    expected_ranks: Optional[List[int]] = None,
) -> dict:
    """Load + analyze one run's artifacts."""
    rings = load_rings(flight_dir)
    return analyze(
        rings,
        heartbeats=load_heartbeats(heartbeat_dir),
        waits=load_wait_hists(telemetry_dir),
        expected_ranks=expected_ranks,
    )


def verdict_rank(verdict: dict) -> Optional[int]:
    """The single rank a verdict convicts, or None when it names none
    (inconclusive) or cannot narrow to one (a multi-rank desync tie).
    The chaos blame oracle cross-checks this against the schedule's
    injected victim: a verdict naming the WRONG rank is a diagnosis
    failure even when the run otherwise recovered."""
    kind = verdict.get("verdict")
    if kind == "straggler":
        r = (verdict.get("straggler") or {}).get("rank")
        return int(r) if r is not None else None
    if kind == "oom":
        r = (verdict.get("oom") or {}).get("rank")
        return int(r) if r is not None else None
    if kind == "desync":
        ranks = verdict.get("deviating_ranks") or []
        if len(ranks) == 1:
            return int(ranks[0])
    return None


def summary_line(verdict: dict, epoch: Optional[int] = None) -> str:
    """The one-line form launchers print (``POSTMORTEM verdict=…``)."""
    parts = ["POSTMORTEM"]
    if epoch is not None:
        parts.append(f"epoch={epoch}")
    parts.append(f"verdict={verdict.get('verdict')}")
    s = verdict.get("straggler")
    o = verdict.get("oom")
    if o:
        parts.append(f"rank={o['rank']} req={o.get('req_bytes')} "
                     f"where={o.get('where')}")
        top = o.get("top_buffers") or []
        if top:
            parts.append(f"top={top[0].get('op')}:{top[0].get('nb')}")
    elif s:
        parts.append(f"rank={s['rank']} seq={s['seq']} op={s['op']} lag={s['lag']}")
    elif verdict.get("first_divergent_seq") is not None:
        parts.append(f"seq={verdict['first_divergent_seq']}")
        dev = verdict.get("deviating_ranks")
        if dev:
            parts.append("ranks=" + ",".join(str(r) for r in dev))
    return " ".join(parts)


# ---------------------------------------------------------------------- #
# rendering
# ---------------------------------------------------------------------- #
def render_grid(
    rings: Dict[int, dict], around: Optional[int] = None, context: int = 5
) -> str:
    """seq × rank grid of collective fingerprints, centered on ``around``
    (or the tail).  ``*`` marks rows where ranks disagree; ``·`` marks a
    rank with no record at that seq."""
    colls = {r: _coll_by_seq(ring) for r, ring in sorted(rings.items())}
    ranks = [r for r in sorted(colls) if colls[r]]
    if not ranks:
        return "(no collective records)"
    lo = min(min(c) for c in colls.values() if c)
    hi = max(max(c) for c in colls.values() if c)
    if around is None:
        around = hi
    s0 = max(lo, around - context)
    s1 = min(hi, around + context)

    def cell(rec: Optional[dict]) -> str:
        if rec is None:
            return "·"
        bits = [str(rec.get("op"))]
        if rec.get("gshape") is not None:
            bits.append("x".join(str(v) for v in rec["gshape"]))
        if rec.get("wire") is not None:
            bits.append(f"{rec['wire']}B")
        return " ".join(bits)

    header = ["seq"] + [f"rank{r}" for r in ranks] + [""]
    rows = []
    for s in range(s0, s1 + 1):
        recs = [colls[r].get(s) for r in ranks]
        held = [rec for rec in recs if rec is not None]
        fields = _common_fp_fields(held)
        fps = {_fingerprint(rec, fields) for rec in held}
        mark = "*" if (len(fps) > 1 or any(rec is None for rec in recs)) else ""
        rows.append([str(s)] + [cell(rec) for rec in recs] + [mark])
    widths = [max(len(r[i]) for r in [header] + rows) for i in range(len(header))]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    lines = [fmt.format(*header), fmt.format(*("-" * w for w in widths))]
    lines += [fmt.format(*row) for row in rows]
    return "\n".join(lines)


def render(verdict: dict, rings: Optional[Dict[int, dict]] = None) -> str:
    out = [summary_line(verdict), verdict.get("detail", "")]
    if verdict.get("missing_ranks"):
        out.append(
            "rank(s) with NO ring file: "
            + ", ".join(str(r) for r in verdict["missing_ranks"])
        )
    # verdict dicts key ranks by str() (JSON round-trip safety): sort the
    # report numerically or rank 10 renders before rank 2 at pod scale
    by_rank = lambda kv: int(kv[0])  # noqa: E731
    if verdict.get("slots_skipped"):
        out.append(
            "torn/unparseable ring slot(s) skipped — the stream(s) below "
            "have holes: "
            + ", ".join(
                f"rank {r}: {n}"
                for r, n in sorted(verdict["slots_skipped"].items(), key=by_rank)
            )
        )
    if verdict.get("last_seq"):
        out.append(
            "last staged seq per rank: "
            + ", ".join(
                f"rank {r}: {s}"
                for r, s in sorted(verdict["last_seq"].items(), key=by_rank)
            )
        )
    hbs = verdict.get("heartbeats")
    if hbs:
        for r, hb in sorted(hbs.items(), key=by_rank):
            fields = " ".join(f"{k}={v}" for k, v in hb.items())
            out.append(f"heartbeat rank {r}: {fields}")
    o = verdict.get("oom")
    if o:
        out.append(
            f"rank {o['rank']} OOM at {o.get('where')}: requested "
            f"{o.get('req_bytes')} B with {o.get('live_bytes')} B live "
            f"(peak {o.get('peak_bytes')} B); dominant live buffers:"
        )
        for b in o.get("top_buffers") or []:
            prov = f"op={b.get('op')} cat={b.get('cat')}"
            if b.get("span"):
                prov += f" span={b['span']}"
            if b.get("tid"):
                prov += f" trace={b['tid']}"
            out.append(f"  {b.get('nb')} B  {prov}")
    s = verdict.get("straggler")
    if s and s.get("wait"):
        out.append(f"rank {s['rank']} blocking-wait evidence:")
        for name, w in s["wait"].items():
            out.append(
                f"  {name}: n={w['count']} total={w['total_s']}s max={w['max_s']}s"
            )
    if rings:
        around = verdict.get("first_divergent_seq")
        if around is None and s:
            around = s.get("seq")
        out.append("")
        out.append("-- collective timeline (seq × rank) --")
        out.append(render_grid(rings, around=around))
    return "\n".join(line for line in out if line is not None)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("flight_dir", help="directory holding flight_rank*.ring files")
    ap.add_argument("--heartbeats", default=None, help="heartbeat beacon dir")
    ap.add_argument("--telemetry", default=None, help="telemetry jsonl export dir")
    ap.add_argument("--json", default=None, help="also write the verdict here")
    ap.add_argument("--context", type=int, default=5,
                    help="grid rows either side of the point of interest")
    ap.add_argument("--expected-ranks", type=int, default=None, metavar="N",
                    help="world size launched: a rank 0..N-1 whose ring is "
                         "missing blocks a clean verdict")
    args = ap.parse_args(argv)

    rings = load_rings(args.flight_dir)
    verdict = analyze(
        rings,
        heartbeats=load_heartbeats(args.heartbeats),
        waits=load_wait_hists(args.telemetry),
        expected_ranks=(
            list(range(args.expected_ranks))
            if args.expected_ranks is not None
            else None
        ),
    )
    print(render(verdict, rings))
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(verdict, fh, indent=1)
        print(f"\nverdict JSON written to {args.json}")
    if not rings:
        print(f"no flight_rank*.ring files under {args.flight_dir}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
