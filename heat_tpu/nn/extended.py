"""LPPool, alpha dropouts, EmbeddingBag, Fold/Unfold — the torch.nn
mirror's long tail (SURVEY §2.5; round-5 completion).

Unfold is ``lax.conv_general_dilated_patches`` (whose channel ordering —
(C, kh, kw) — matches torch's im2col exactly, verified by oracle); Fold
is its VJP, which IS col2im.  EmbeddingBag reduces over
``jax.ops.segment_sum``-style segments.  Oracle tests live in
``tests/test_nn_padshuffle.py``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .modules import Module, _pair

__all__ = [
    "AlphaDropout", "EmbeddingBag", "FeatureAlphaDropout", "Fold",
    "LPPool1d", "LPPool2d", "LPPool3d", "MaxUnpool1d", "MaxUnpool2d",
    "MaxUnpool3d", "Unfold",
]


# ---------------------------------------------------------------------- #
# LP pooling: (sum |x|^p over window)^(1/p) — torch computes sum of x^p
# (sign-carrying for odd p); we follow torch's formula exactly
# ---------------------------------------------------------------------- #
class _LPPool(Module):
    spatial: int = 1

    def __init__(self, norm_type: float, kernel_size, stride=None):
        n = self.spatial

        def _tup(v):
            return v if isinstance(v, tuple) else (v,) * n

        self.norm_type = float(norm_type)
        self.kernel_size = _tup(kernel_size)
        self.stride = _tup(stride if stride is not None else kernel_size)

    def apply(self, params, x, **kw):
        p = self.norm_type
        s = jax.lax.reduce_window(
            x ** p, 0.0, jax.lax.add,
            window_dimensions=(1, 1) + self.kernel_size,
            window_strides=(1, 1) + self.stride,
            padding="VALID",
        )
        # torch semantics exactly (ADVICE r5 #1): the signed window sum goes
        # straight into the root — norm_type=1 returns the signed sum, and a
        # negative sum at fractional 1/p yields NaN, just like torch.pow
        return s ** (1.0 / p)


class LPPool1d(_LPPool):
    spatial = 1


class LPPool2d(_LPPool):
    spatial = 2


class LPPool3d(_LPPool):
    spatial = 3


# ---------------------------------------------------------------------- #
# alpha dropouts (SELU-preserving)
# ---------------------------------------------------------------------- #
_ALPHA_PRIME = -1.7580993408473766  # -selu_scale * selu_alpha


class AlphaDropout(Module):
    """Dropout that preserves SELU self-normalizing statistics: dropped
    units take the SELU saturation value alpha' and the output is affinely
    rescaled so mean/var stay (0, 1) (torch formula)."""

    def __init__(self, p: float = 0.5):
        self.p = p

    def _mask_shape(self, x):
        return x.shape

    def apply(self, params, x, *, train: bool = False, key=None):
        if not train or self.p == 0.0:
            return x
        if key is None:
            raise ValueError("AlphaDropout in train mode requires a PRNG key")
        keep = 1.0 - self.p
        a = (keep + _ALPHA_PRIME**2 * keep * (1 - keep)) ** -0.5
        b = -a * _ALPHA_PRIME * (1 - keep)
        mask = jax.random.bernoulli(key, keep, self._mask_shape(x))
        return a * jnp.where(mask, x, _ALPHA_PRIME) + b


class FeatureAlphaDropout(AlphaDropout):
    """AlphaDropout over whole channels ((N, C) mask broadcast over the
    spatial dims, like Dropout2d vs Dropout)."""

    def _mask_shape(self, x):
        return x.shape[:2] + (1,) * (x.ndim - 2)


# ---------------------------------------------------------------------- #
# EmbeddingBag
# ---------------------------------------------------------------------- #
class EmbeddingBag(Module):
    """Sum/mean/max reduction of embedding rows per bag (torch call
    shapes: 2-D ``(B, L)`` indices without offsets, or 1-D indices with a
    1-D ``offsets`` tensor of bag starts).  ``per_sample_weights`` is
    supported for mode='sum' like torch."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 mode: str = "mean"):
        if mode not in ("sum", "mean", "max"):
            raise ValueError(f"mode must be sum/mean/max, got {mode!r}")
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.mode = mode

    def init(self, key):
        return {"weight": jax.random.normal(
            key, (self.num_embeddings, self.embedding_dim))}

    def apply(self, params, idx, offsets=None, per_sample_weights=None, **kw):
        w = params["weight"]
        if per_sample_weights is not None and self.mode != "sum":
            raise ValueError("per_sample_weights requires mode='sum' (torch)")
        idx = jnp.asarray(idx)
        if offsets is None:
            if idx.ndim != 2:
                raise ValueError("without offsets, indices must be 2-D (B, L)")
            rows = w[idx]  # (B, L, D)
            if per_sample_weights is not None:
                rows = rows * jnp.asarray(per_sample_weights)[..., None]
            if self.mode == "sum":
                return rows.sum(axis=1)
            if self.mode == "mean":
                return rows.mean(axis=1)
            return rows.max(axis=1)
        if idx.ndim != 1:
            raise ValueError("with offsets, indices must be 1-D")
        offsets = jnp.asarray(offsets)
        from .modules import _concrete_int

        # traced offsets (inside jit): the guard can't fire — _concrete_int
        # returns None there
        first = _concrete_int(offsets[0]) if offsets.shape[0] else 0
        if first not in (0, None):
            raise ValueError("offsets[0] has to be 0 (torch contract) — "
                             "leading indices would silently fall outside "
                             "every bag")
        n_bags = offsets.shape[0]
        # bag id of each index: how many offsets are <= position
        pos = jnp.arange(idx.shape[0])
        seg = jnp.searchsorted(offsets, pos, side="right") - 1
        rows = w[idx]
        if per_sample_weights is not None:
            rows = rows * jnp.asarray(per_sample_weights)[:, None]
        counts = jax.ops.segment_sum(jnp.ones_like(idx, jnp.float32), seg,
                                     num_segments=n_bags)
        if self.mode == "max":
            mx = jax.ops.segment_max(rows, seg, num_segments=n_bags)
            # empty bags: torch returns 0, segment_max's identity is -inf
            return jnp.where(counts[:, None] > 0, mx, 0.0)
        sums = jax.ops.segment_sum(rows, seg, num_segments=n_bags)
        if self.mode == "sum":
            return sums
        return sums / jnp.maximum(counts, 1.0)[:, None]


# ---------------------------------------------------------------------- #
# Fold / Unfold (im2col / col2im)
# ---------------------------------------------------------------------- #
class Unfold(Module):
    """im2col: (N, C, H, W) -> (N, C·kh·kw, L) patches (torch layout —
    ``lax.conv_general_dilated_patches`` orders patch channels (C, kh, kw)
    exactly like torch, verified by the oracle test)."""

    def __init__(self, kernel_size, dilation=1, padding=0, stride=1):
        self.kernel_size = _pair(kernel_size)
        self.dilation = _pair(dilation)
        self.padding = _pair(padding)
        self.stride = _pair(stride)

    def apply(self, params, x, **kw):
        p = jax.lax.conv_general_dilated_patches(
            x, filter_shape=self.kernel_size, window_strides=self.stride,
            padding=[(q, q) for q in self.padding],
            rhs_dilation=self.dilation,
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )
        return p.reshape(p.shape[0], p.shape[1], -1)


class Fold(Module):
    """col2im: the exact inverse-scatter of :class:`Unfold` — implemented
    as Unfold's VJP, which IS the column-to-image accumulation (overlaps
    sum, torch semantics)."""

    def __init__(self, output_size, kernel_size, dilation=1, padding=0,
                 stride=1):
        self.output_size = _pair(output_size)
        self._unfold = Unfold(kernel_size, dilation, padding, stride)

    def apply(self, params, cols, **kw):
        n = cols.shape[0]
        # infer C from the patch-channel extent
        kh, kw = self._unfold.kernel_size
        c = cols.shape[1] // (kh * kw)
        x0 = jnp.zeros((n, c) + self.output_size, cols.dtype)
        _, vjp = jax.vjp(lambda x: self._unfold.apply((), x), x0)
        (out,) = vjp(cols.reshape(n, cols.shape[1], -1))
        return out


# ---------------------------------------------------------------------- #
# MaxUnpool: scatter pooled values back to their argmax positions
# ---------------------------------------------------------------------- #
class _MaxUnpool(Module):
    """Inverse of ``MaxPoolNd(return_indices=True)``: values land at their
    recorded flat indices, everything else is 0 (torch semantics).  Default
    output extent per dim is ``(i-1)·stride + kernel``; pass
    ``output_size=`` at call time to disambiguate (torch contract)."""

    spatial: int = 2

    def __init__(self, kernel_size, stride=None):
        n = self.spatial

        def _tup(v):
            return v if isinstance(v, tuple) else (v,) * n

        self.kernel_size = _tup(kernel_size)
        self.stride = _tup(stride if stride is not None else kernel_size)

    def apply(self, params, x, indices=None, output_size=None, **kw):
        if indices is None:
            raise ValueError("MaxUnpool requires the indices from "
                             "MaxPool(return_indices=True)")
        n = self.spatial
        if output_size is None:
            output_size = tuple(
                (i - 1) * s + k
                for i, s, k in zip(x.shape[2:], self.stride, self.kernel_size)
            )
        output_size = tuple(output_size)
        if len(output_size) == x.ndim:  # torch also accepts the full shape
            output_size = output_size[2:]
        if len(output_size) != n:
            raise ValueError(
                f"output_size must have {n} (spatial) or {n + 2} (full shape) "
                f"entries, got {len(output_size)}"
            )
        for d, (o, i, s, k) in enumerate(
            zip(output_size, x.shape[2:], self.stride, self.kernel_size)
        ):
            default = (i - 1) * s + k
            # torch's strict ±stride band (_unpool_output_size):
            # min_size < o < max_size with min/max = default ∓ stride
            if not default - s < o < default + s:
                raise ValueError(
                    f"invalid output_size {tuple(output_size)}: dim {d} must "
                    f"be between {default - s} and {default + s}"
                )
        N, C = x.shape[:2]
        from math import prod

        L = prod(output_size)
        vals = x.reshape(N, C, -1)
        idx = jnp.asarray(indices).reshape(N, C, -1)
        # recorded indices may exceed a smaller-than-default output plane
        # (and negatives are out-of-bounds in drop-mode scatter); torch
        # raises for both, and silent relocation is never acceptable —
        # validate eagerly when concrete (ONE fused device fetch for both
        # bounds), scatter with mode='drop' under trace so out-of-range
        # indices vanish instead of clipping to L-1
        if idx.size:
            try:
                mn, mx = (int(v) for v in jnp.stack([idx.min(), idx.max()]))
            except (jax.errors.TracerIntegerConversionError,
                    jax.errors.ConcretizationTypeError, TypeError):
                mn = mx = 0  # traced: drop-mode scatter is the guard
            if mx >= L:
                raise ValueError(
                    f"found an invalid max index {mx} for output size "
                    f"{tuple(output_size)} (flat plane {L})"
                )
            if mn < 0:
                raise ValueError(f"found an invalid (negative) index {mn}")
        out = jnp.zeros((N, C, L), x.dtype)
        out = out.at[
            jnp.arange(N)[:, None, None], jnp.arange(C)[None, :, None], idx
        ].set(vals, mode="drop")
        return out.reshape(N, C, *output_size)


class MaxUnpool1d(_MaxUnpool):
    spatial = 1


class MaxUnpool2d(_MaxUnpool):
    spatial = 2


class MaxUnpool3d(_MaxUnpool):
    spatial = 3
