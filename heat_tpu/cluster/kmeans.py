"""KMeans (reference: ``heat/cluster/kmeans.py``; BASELINE workload, SURVEY §3.4).

M-step = segment-sum over the sharded sample axis; XLA emits the two small
Allreduces (sums, counts) the reference issues by hand.
"""

from __future__ import annotations

from typing import Optional, Union

import jax
import jax.numpy as jnp

from ._kcluster import _KCluster

__all__ = ["KMeans"]


class KMeans(_KCluster):
    """K-Means clustering with the reference's API.

    Parameters mirror ``heat.cluster.KMeans``: n_clusters, init
    ('kmeans++' | 'random' | array), max_iter, tol, random_state.
    """

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, object] = "kmeans++",
        max_iter: int = 300,
        tol: float = 1e-4,
        random_state: Optional[int] = None,
    ):
        super().__init__(
            metric=lambda x, y: None, n_clusters=n_clusters, init=init,
            max_iter=max_iter, tol=tol, random_state=random_state,
        )

    @staticmethod
    def _update(jx, labels, centers):
        k = centers.shape[0]
        n = jx.shape[0]

        def block_stats(xb, lb):
            onehot = (lb[:, None] == jnp.arange(k)[None, :]).astype(xb.dtype)
            return onehot.T @ xb, jnp.sum(onehot, axis=0)  # MXU GEMM + implicit Allreduce

        blk = _KCluster._ASSIGN_BLOCK
        if n <= blk:
            sums, counts = block_stats(jx, labels)
        else:
            # accumulate per-block (k, d)/(k,) stats so no n×k one-hot buffer
            # ever materializes — scales the M-step to BASELINE's 1e8 rows;
            # remainder rows are folded in as one tail block
            body = (n // blk) * blk

            def scan_body(carry, xs):
                s, c = carry
                xb, lb = xs
                bs, bc = block_stats(xb, lb)
                return (s + bs, c + bc), None

            (sums, counts), _ = jax.lax.scan(
                scan_body,
                (jnp.zeros((k, jx.shape[1]), jx.dtype), jnp.zeros((k,), jx.dtype)),
                (jx[:body].reshape(n // blk, blk, jx.shape[1]), labels[:body].reshape(n // blk, blk)),
            )
            if body < n:
                ts, tc = block_stats(jx[body:], labels[body:])
                sums, counts = sums + ts, counts + tc
        safe = jnp.maximum(counts, 1.0)
        new = sums / safe[:, None]
        # empty clusters keep their previous center (reference behavior)
        return jnp.where(counts[:, None] > 0, new, centers)
