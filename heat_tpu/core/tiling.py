"""Tile views over distributed arrays (reference: ``heat/core/tiling.py``).

The reference's ``SplitTiles``/``SquareDiagTiles`` give per-tile
``(rank, row, col)`` addressing with async tile send/recv — infrastructure
for its blocked QR/matmul.  Under XLA, cross-shard tile motion is implicit,
so these classes reduce to *index algebra* over the global array: a tile is
a slice, reads/writes are sharded gathers/scatters.  The API (tile_locations,
tile_dimensions, ``__getitem__``/``__setitem__``) is kept for parity and for
algorithms that want explicit block addressing; ``SquareDiagTiles`` drives the
blocked triangular substitution in ``linalg.solve_triangular`` (the same role
it plays for the reference's blocked solvers).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from .dndarray import DNDarray

__all__ = ["SplitTiles", "SquareDiagTiles"]


class SplitTiles:
    """One tile per mesh shard along every axis (reference semantics)."""

    def __init__(self, arr: DNDarray):
        self.__arr = arr
        comm = arr.comm
        sizes = []
        for dim, g in enumerate(arr.gshape):
            counts, _ = comm.counts_displs_shape(arr.gshape, dim)
            sizes.append(np.asarray(counts, dtype=np.int64))
        self.__tile_dims = sizes
        self.__tile_ends = [np.cumsum(s) for s in sizes]

    @property
    def arr(self) -> DNDarray:
        return self.__arr

    @property
    def tile_dimensions(self):
        """Per-axis tile edge lengths (list of per-shard sizes)."""
        return self.__tile_dims

    @property
    def tile_locations(self) -> np.ndarray:
        """Which shard owns each tile along the split axis (None split → 0)."""
        comm = self.__arr.comm
        split = self.__arr.split
        shape = tuple(comm.size for _ in self.__arr.gshape)
        locs = np.zeros(shape, dtype=np.int64)
        if split is not None:
            view = np.arange(comm.size)
            expand = [1] * len(shape)
            expand[split] = comm.size
            locs[...] = view.reshape(expand)
        return locs

    def _slices(self, key) -> Tuple[slice, ...]:
        key_t = key if isinstance(key, tuple) else (key,)
        slices = []
        for dim in range(self.__arr.ndim):
            ends = self.__tile_ends[dim]
            starts = np.concatenate([[0], ends[:-1]])
            if dim < len(key_t) and key_t[dim] is not None and not (
                isinstance(key_t[dim], slice) and key_t[dim] == slice(None)
            ):
                k = key_t[dim]
                if isinstance(k, slice):
                    lo = starts[k.start or 0]
                    hi = ends[(k.stop or len(ends)) - 1]
                    slices.append(slice(int(lo), int(hi)))
                else:
                    slices.append(slice(int(starts[int(k)]), int(ends[int(k)])))
            else:
                slices.append(slice(0, int(ends[-1])))
        return tuple(slices)

    def __getitem__(self, key):
        return self.__arr._jarray[self._slices(key)]

    def __setitem__(self, key, value) -> None:
        jarr = self.__arr._jarray.at[self._slices(key)].set(
            value._jarray if isinstance(value, DNDarray) else value
        )
        self.__arr._jarray = self.__arr.comm.shard(jarr, self.__arr.split)


class SquareDiagTiles:
    """Square tiles along the diagonal (reference: blocked QR infrastructure).

    ``tiles_per_proc`` square blocks per shard along the split axis; exposes
    row/col decomposition indices and tile get/set by (row, col).
    """

    def __init__(self, arr: DNDarray, tiles_per_proc: int = 2):
        if arr.ndim != 2:
            raise ValueError("SquareDiagTiles requires a 2-D array")
        if tiles_per_proc < 1:
            raise ValueError("tiles_per_proc must be >= 1")
        self.__arr = arr
        m, n = arr.gshape
        nprocs = arr.comm.size
        ntiles = max(1, min(nprocs * tiles_per_proc, min(m, n)))
        base = min(m, n) // ntiles
        row_per = np.full(ntiles, base, dtype=np.int64)
        row_per[: min(m, n) - base * ntiles] += 1
        # rows may extend past the square part
        rows = list(row_per)
        if m > n:
            rows.append(m - int(np.sum(row_per)))
            rows = [r for r in rows if r > 0]
        cols = list(row_per)
        if n > m:
            cols.append(n - int(np.sum(row_per)))
            cols = [c for c in cols if c > 0]
        self.__row_per_proc_list = rows
        self.__col_per_proc_list = cols
        self.__row_ends = np.cumsum(rows)
        self.__col_ends = np.cumsum(cols)

    @property
    def arr(self) -> DNDarray:
        return self.__arr

    @property
    def tile_rows(self) -> int:
        return len(self.__row_per_proc_list)

    @property
    def tile_columns(self) -> int:
        return len(self.__col_per_proc_list)

    @property
    def row_indices(self):
        return [0] + list(self.__row_ends[:-1])

    @property
    def col_indices(self):
        return [0] + list(self.__col_ends[:-1])

    def _slice(self, row: int, col: int) -> Tuple[slice, slice]:
        rs = 0 if row == 0 else int(self.__row_ends[row - 1])
        re = int(self.__row_ends[row])
        cs = 0 if col == 0 else int(self.__col_ends[col - 1])
        ce = int(self.__col_ends[col])
        return slice(rs, re), slice(cs, ce)

    def __getitem__(self, key):
        row, col = key
        return self.__arr._jarray[self._slice(row, col)]

    def __setitem__(self, key, value) -> None:
        row, col = key
        jarr = self.__arr._jarray.at[self._slice(row, col)].set(
            value._jarray if isinstance(value, DNDarray) else value
        )
        self.__arr._jarray = self.__arr.comm.shard(jarr, self.__arr.split)
