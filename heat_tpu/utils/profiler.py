"""Profiling shim (SURVEY §5.1).

The reference has no built-in tracer (external perun only).  On TPU we get a
first-class story: this wraps ``jax.profiler`` so benchmarks are one-liner
instrumented, plus a wall-clock timer that forces completion (the tunneled
platform's ``block_until_ready`` can be a no-op, so timers fetch a scalar).
"""

from __future__ import annotations

import contextlib
import time
import weakref
from typing import Callable, Dict, Optional

import jax
import numpy as np

from ..core._cache import cache_stats, reset_cache_stats

__all__ = [
    "trace",
    "timer",
    "sync",
    "annotate",
    "timeit_min",
    "cache_stats",
    "reset_cache_stats",
    "cache_hit_rate",
    "counter_inc",
    "counter_max",
    "counters",
    "reset_counters",
    "register_counter_provider",
]


# ---------------------------------------------------------------------- #
# generic event counters (retry attempts, skipped train steps, ...)
# ---------------------------------------------------------------------- #
# Two sources merge in counters(): plain incremented counters (retry.<site>
# from utils.faults) and registered *providers* — callbacks polled at read
# time so device-resident counters (DASO's skip counter is a jax array,
# updated asynchronously with NO host sync on the step path) only
# materialize when somebody actually asks.
_counters: Dict[str, int] = {}
_providers: Dict[str, Callable[[], Dict[str, int]]] = {}


def counter_inc(name: str, n: int = 1) -> None:
    """Increment a named event counter (host-side, cheap)."""
    _counters[name] = _counters.get(name, 0) + int(n)


def counter_max(name: str, value: int) -> None:
    """High-water-mark counter: keep the MAX of all observed values (e.g.
    ``comm.resplit.peak_tile_bytes`` — additive semantics would be a lie
    for a peak).  Reads/resets/exports exactly like any other counter."""
    v = int(value)
    if v > _counters.get(name, 0):
        _counters[name] = v


def register_counter_provider(name: str, fn: Callable[[], Dict[str, int]]) -> str:
    """Register a callback polled by :func:`counters`.  Bound methods are
    held weakly so registering does not pin the owning object alive (a dead
    provider is pruned at the next :func:`counters` read).  ``name`` is
    de-duplicated with a numeric suffix — a second registrant never silently
    replaces the first — and the effective name is returned."""
    if hasattr(fn, "__self__"):
        ref = weakref.WeakMethod(fn)

        def fn():  # noqa: F811 — the weak indirection replaces the strong ref
            m = ref()
            return m() if m is not None else None  # None: owner was collected

    base, k = name, 2
    while name in _providers:
        name = f"{base}{k}"
        k += 1
    _providers[name] = fn
    return name


def counters() -> Dict[str, int]:
    """Snapshot of all counters: incremented ones plus every provider's
    current values.  May sync device-resident counters — call it at
    reporting boundaries, not inside the hot loop.

    Provider values are namespaced unambiguously under ``<provider>.<key>``
    (a key already carrying that exact dotted prefix is kept as-is).  The
    earlier rule — any key merely *starting with* the provider name passed
    through un-prefixed — let a provider key like ``daso_total`` silently
    overwrite an identically-named plain counter."""
    out = dict(_counters)
    for name, fn in list(_providers.items()):
        vals = fn()
        if vals is None:  # provider's owner was garbage collected
            _providers.pop(name, None)
            continue
        prefix = name + "."
        for k, v in vals.items():
            out[k if k.startswith(prefix) else f"{name}.{k}"] = int(v)
    return out


def reset_counters() -> None:
    """Clear the incremented counters (providers re-report on next read)."""
    _counters.clear()


def cache_hit_rate() -> float:
    """Hit rate of the sharding-keyed program caches since the last
    ``reset_cache_stats()`` — 1.0 means every dispatched op reused a
    compiled executable (zero recompilation)."""
    s = cache_stats()
    total = s["hits"] + s["misses"]
    return s["hits"] / total if total else 1.0


def timeit_min(fn, reps: int = 3) -> float:
    """Best-of-``reps`` wall-clock seconds of ``fn()``, forcing completion of
    its result (the benchmark harness's shared timing methodology)."""
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn()
        sync(out)
        best = min(best, time.perf_counter() - t0)
    return best


def sync(x=None) -> None:
    """Force device completion (fetch-based; tunnel-safe)."""
    if x is None:
        return
    arr = getattr(x, "_jarray", x)
    try:
        np.asarray(jax.device_get(arr.ravel()[:1] if hasattr(arr, "ravel") else arr))
    except Exception:
        jax.block_until_ready(arr)


@contextlib.contextmanager
def timer(label: str = "", result_holder: Optional[dict] = None, sync_on=None):
    """Wall-clock a block; forces completion of ``sync_on`` before stopping.

    Exception-safe: a raising block still records its elapsed time into
    ``result_holder`` (and still syncs) — the exception propagates, but the
    measurement of the partial work is not lost."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        sync(sync_on)
        dt = time.perf_counter() - t0
        if result_holder is not None:
            result_holder[label or "elapsed"] = dt


@contextlib.contextmanager
def trace(logdir: str = "/tmp/heat_tpu_trace"):
    """XProf/TensorBoard trace of the block (``jax.profiler.trace``)."""
    with jax.profiler.trace(logdir):
        yield


annotate = jax.profiler.TraceAnnotation

# the program-cache stats surface in counters() too (counter naming scheme
# cache.* — see design.md "Telemetry & metrics"), so telemetry.report()
# carries hit/miss/slow next to comm.*/retry.*/io.* without a second API
register_counter_provider("cache", lambda: {k: int(v) for k, v in cache_stats().items()})
