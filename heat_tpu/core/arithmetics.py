"""Arithmetic operations (reference: ``heat/core/arithmetics.py``).

All ops route through the dispatch core; XLA fuses elementwise chains and
inserts collectives where splits demand (SURVEY §2.2).
"""

from __future__ import annotations


import jax.numpy as jnp
import numpy as np

from . import types
from ._operations import _binary_op, _cum_op, _local_op, _reduce_op
from .dndarray import DNDarray
from .stride_tricks import sanitize_axis

__all__ = [
    "add",
    "bitwise_and",
    "bitwise_not",
    "bitwise_or",
    "bitwise_xor",
    "copysign",
    "cumprod",
    "cumsum",
    "diff",
    "div",
    "divide",
    "divmod",
    "float_power",
    "floordiv",
    "floor_divide",
    "fmod",
    "heaviside",
    "gcd",
    "hypot",
    "invert",
    "lcm",
    "ldexp",
    "left_shift",
    "mod",
    "mul",
    "multiply",
    "nanprod",
    "nansum",
    "neg",
    "negative",
    "pos",
    "positive",
    "pow",
    "power",
    "prod",
    "remainder",
    "right_shift",
    "sub",
    "subtract",
    "sum",
    "trapezoid",
    "trapz",
    "true_divide",
]


def add(t1, t2, out=None, where=None) -> DNDarray:
    """Elementwise addition ``t1 + t2``."""
    return _binary_op(jnp.add, t1, t2, out=out, where=where)


def sub(t1, t2, out=None, where=None) -> DNDarray:
    """Elementwise subtraction ``t1 - t2``."""
    return _binary_op(jnp.subtract, t1, t2, out=out, where=where)


subtract = sub


def mul(t1, t2, out=None, where=None) -> DNDarray:
    """Elementwise multiplication ``t1 * t2``."""
    return _binary_op(jnp.multiply, t1, t2, out=out, where=where)


multiply = mul


def div(t1, t2, out=None, where=None) -> DNDarray:
    """Elementwise true division ``t1 / t2``."""
    return _binary_op(jnp.true_divide, t1, t2, out=out, where=where)


divide = div
true_divide = div


def floordiv(t1, t2) -> DNDarray:
    """Elementwise floor division ``t1 // t2``."""
    return _binary_op(jnp.floor_divide, t1, t2)


floor_divide = floordiv


def mod(t1, t2) -> DNDarray:
    """Elementwise modulo (sign follows divisor, Python semantics)."""
    return _binary_op(jnp.mod, t1, t2)


remainder = mod


def fmod(t1, t2) -> DNDarray:
    """Elementwise C-style fmod (sign follows dividend)."""
    return _binary_op(jnp.fmod, t1, t2)


def divmod(t1, t2):
    return (floordiv(t1, t2), mod(t1, t2))


def pow(t1, t2) -> DNDarray:
    """Elementwise power ``t1 ** t2``."""
    return _binary_op(jnp.power, t1, t2)


power = pow


def copysign(t1, t2) -> DNDarray:
    return _binary_op(jnp.copysign, t1, t2)


def hypot(t1, t2) -> DNDarray:
    return _binary_op(jnp.hypot, t1, t2)


def gcd(t1, t2) -> DNDarray:
    return _binary_op(jnp.gcd, t1, t2)


def lcm(t1, t2) -> DNDarray:
    return _binary_op(jnp.lcm, t1, t2)


def float_power(t1, t2) -> DNDarray:
    """``t1 ** t2`` computed in the widest available float type (numpy
    ``float_power`` semantics; f32 on TPU unless x64 is enabled)."""
    return _binary_op(jnp.float_power, t1, t2)


def ldexp(t1, t2) -> DNDarray:
    """Elementwise ``t1 * 2**t2`` (numpy ``ldexp``)."""
    return _binary_op(jnp.ldexp, t1, t2)


def heaviside(t1, t2) -> DNDarray:
    """Heaviside step function with ``t2`` as the value at 0."""
    return _binary_op(jnp.heaviside, t1, t2)


def trapz(y, x=None, dx: float = 1.0, axis: int = -1) -> DNDarray:
    """Trapezoidal-rule integration along ``axis``.

    Pure array-API composition (diff + sum) so the distributed reduction over
    a split axis rides the standard ``_reduce_op`` collective path.
    """
    from . import manipulations

    sl1 = [slice(None)] * y.ndim
    sl2 = [slice(None)] * y.ndim
    sl1[axis] = slice(1, None)
    sl2[axis] = slice(None, -1)
    if x is None:
        avg = (y[tuple(sl1)] + y[tuple(sl2)]) * (0.5 * dx)
    else:
        d = diff(x, axis=axis if x.ndim > 1 else 0)
        if x.ndim == 1 and y.ndim > 1:
            shape = [1] * y.ndim
            shape[axis] = d.shape[0]
            d = manipulations.reshape(d, tuple(shape))
        avg = (y[tuple(sl1)] + y[tuple(sl2)]) * d * 0.5
    return sum(avg, axis=axis)


trapezoid = trapz


def neg(x, out=None) -> DNDarray:
    """Elementwise negation."""
    return _local_op(jnp.negative, x, out=out)


negative = neg


def pos(x, out=None) -> DNDarray:
    return _local_op(jnp.positive, x, out=out)


positive = pos


def bitwise_and(t1, t2) -> DNDarray:
    return _binary_op(jnp.bitwise_and, t1, t2)


def bitwise_or(t1, t2) -> DNDarray:
    return _binary_op(jnp.bitwise_or, t1, t2)


def bitwise_xor(t1, t2) -> DNDarray:
    return _binary_op(jnp.bitwise_xor, t1, t2)


def invert(x, out=None) -> DNDarray:
    """Elementwise bitwise NOT."""
    if x.dtype is types.bool:
        return _local_op(jnp.logical_not, x, out=out)
    return _local_op(jnp.invert, x, out=out)


bitwise_not = invert


def left_shift(t1, t2) -> DNDarray:
    return _binary_op(jnp.left_shift, t1, t2)


def right_shift(t1, t2) -> DNDarray:
    return _binary_op(jnp.right_shift, t1, t2)


def cumsum(x, axis, dtype=None, out=None) -> DNDarray:
    """Cumulative sum along ``axis`` (reference: Exscan; here one XLA scan)."""
    return _cum_op(jnp.cumsum, x, axis, dtype=dtype, out=out)


def cumprod(x, axis, dtype=None, out=None) -> DNDarray:
    return _cum_op(jnp.cumprod, x, axis, dtype=dtype, out=out)


cumproduct = cumprod


def sum(x, axis=None, out=None, keepdims=False, dtype=None) -> DNDarray:
    """Sum over ``axis``; reducing the split axis is an implicit Allreduce."""
    return _reduce_op(jnp.sum, x, axis=axis, keepdims=keepdims, out=out, dtype=dtype)


def prod(x, axis=None, out=None, keepdims=False, dtype=None) -> DNDarray:
    return _reduce_op(jnp.prod, x, axis=axis, keepdims=keepdims, out=out, dtype=dtype)


def nansum(x, axis=None, out=None, keepdims=False) -> DNDarray:
    return _reduce_op(jnp.nansum, x, axis=axis, keepdims=keepdims, out=out)


def nanprod(x, axis=None, out=None, keepdims=False) -> DNDarray:
    return _reduce_op(jnp.nanprod, x, axis=axis, keepdims=keepdims, out=out)


def diff(x, n: int = 1, axis: int = -1, prepend=None, append=None) -> DNDarray:
    """n-th discrete difference along ``axis``."""
    axis = sanitize_axis(x.shape, axis)
    kw = {}
    if prepend is not None:
        kw["prepend"] = prepend._jarray if isinstance(prepend, DNDarray) else prepend
    if append is not None:
        kw["append"] = append._jarray if isinstance(append, DNDarray) else append
    result = jnp.diff(x._jarray, n=n, axis=axis, **kw)
    split = x.split
    result = x.comm.shard(result, split)
    return DNDarray(
        result, tuple(result.shape), types.canonical_heat_type(result.dtype), split, x.device, x.comm, True
    )


# ---------------------------------------------------------------------- #
# DNDarray operator wiring (the reference does this inline in dndarray.py)
# ---------------------------------------------------------------------- #
def _rbin(fn):
    return lambda self, other: fn(other, self)


DNDarray.__add__ = lambda self, other: add(self, other)
DNDarray.__radd__ = lambda self, other: add(self, other)
DNDarray.__sub__ = lambda self, other: sub(self, other)
DNDarray.__rsub__ = _rbin(sub)
DNDarray.__mul__ = lambda self, other: mul(self, other)
DNDarray.__rmul__ = lambda self, other: mul(self, other)
DNDarray.__truediv__ = lambda self, other: div(self, other)
DNDarray.__rtruediv__ = _rbin(div)
DNDarray.__floordiv__ = lambda self, other: floordiv(self, other)
DNDarray.__rfloordiv__ = _rbin(floordiv)
DNDarray.__mod__ = lambda self, other: mod(self, other)
DNDarray.__rmod__ = _rbin(mod)
DNDarray.__pow__ = lambda self, other: pow(self, other)
DNDarray.__rpow__ = _rbin(pow)
DNDarray.__divmod__ = lambda self, other: divmod(self, other)
DNDarray.__neg__ = lambda self: neg(self)
DNDarray.__pos__ = lambda self: pos(self)
DNDarray.__and__ = lambda self, other: bitwise_and(self, other)
DNDarray.__rand__ = _rbin(bitwise_and)
DNDarray.__or__ = lambda self, other: bitwise_or(self, other)
DNDarray.__ror__ = _rbin(bitwise_or)
DNDarray.__xor__ = lambda self, other: bitwise_xor(self, other)
DNDarray.__rxor__ = _rbin(bitwise_xor)
DNDarray.__invert__ = lambda self: invert(self)
DNDarray.__lshift__ = lambda self, other: left_shift(self, other)
DNDarray.__rshift__ = lambda self, other: right_shift(self, other)


def _iop(fn):
    def inner(self, other):
        # donate self's buffer to the compiled op: an in-place update never
        # holds two live copies (XLA aliases in/out storage when the result
        # signature matches).  _binary_op only honors the donation when the
        # result provably replaces self (same shape, not self-referencing),
        # so the shape guard below can still fire safely on the slow path.
        from ._operations import donate_first_operand

        with donate_first_operand():
            res = fn(self, other)
        if tuple(res.shape) != tuple(self.shape):
            raise ValueError(
                f"output shape {res.shape} of in-place operation does not match "
                f"the array shape {self.shape} (in-place broadcasting growth is not allowed)"
            )
        self._jarray = res._jarray.astype(self.dtype.jax_dtype())
        return self

    return inner


DNDarray.__iadd__ = _iop(add)
DNDarray.__isub__ = _iop(sub)
DNDarray.__imul__ = _iop(mul)
DNDarray.__itruediv__ = _iop(div)
DNDarray.__ifloordiv__ = _iop(floordiv)
DNDarray.__imod__ = _iop(mod)
DNDarray.__ipow__ = _iop(pow)

# method forms
DNDarray.add = add
DNDarray.sub = sub
DNDarray.mul = mul
DNDarray.div = div
DNDarray.pow = pow
DNDarray.sum = sum
DNDarray.prod = prod
DNDarray.cumsum = cumsum
DNDarray.cumprod = cumprod
DNDarray.nansum = nansum
DNDarray.fmod = fmod
DNDarray.mod = mod


def reciprocal(x, out=None) -> DNDarray:
    """Elementwise ``1/x``."""
    return _local_op(jnp.reciprocal, x, out=out)


def nextafter(t1, t2) -> DNDarray:
    """Next representable float after ``t1`` toward ``t2``."""
    return _binary_op(jnp.nextafter, t1, t2)


def spacing(x, out=None) -> DNDarray:
    """Distance to the next representable float (numpy ``spacing``)."""
    return _local_op(jnp.spacing, x, out=out)


def ediff1d(x, to_end=None, to_begin=None) -> DNDarray:
    """Differences of consecutive elements of the raveled array."""
    res = jnp.ediff1d(
        x._jarray,
        to_end=None if to_end is None else jnp.asarray(np.asarray(to_end)),
        to_begin=None if to_begin is None else jnp.asarray(np.asarray(to_begin)),
    )
    from .manipulations import _wrap

    return _wrap(res, 0 if x.split is not None else None, x)


def gradient(f: DNDarray, *varargs, axis=None, edge_order: int = 1):
    """Central-difference gradient (numpy semantics).

    Returns one DNDarray per requested axis (or a single one for 1 axis);
    each keeps the input's split — the stencil's neighbor exchange is derived
    by XLA from the sharded slices.
    """
    from .manipulations import _wrap

    if edge_order != 1:
        raise NotImplementedError("gradient supports edge_order=1 only (XLA backend)")
    jv = [v._jarray if isinstance(v, DNDarray) else v for v in varargs]
    res = jnp.gradient(f._jarray, *jv, axis=axis)
    if isinstance(res, (list, tuple)):
        return [_wrap(r, f.split, f) for r in res]
    return _wrap(res, f.split, f)


def interp(x, xp, fp, left=None, right=None, period=None) -> DNDarray:
    """1-D linear interpolation of ``x`` against sample points (xp, fp)."""
    from .manipulations import _wrap

    jx = x._jarray if isinstance(x, DNDarray) else jnp.asarray(np.asarray(x))
    jxp = xp._jarray if isinstance(xp, DNDarray) else jnp.asarray(np.asarray(xp))
    jfp = fp._jarray if isinstance(fp, DNDarray) else jnp.asarray(np.asarray(fp))
    res = jnp.interp(jx, jxp, jfp, left=left, right=right, period=period)
    proto = x if isinstance(x, DNDarray) else (xp if isinstance(xp, DNDarray) else fp)
    split = x.split if isinstance(x, DNDarray) else None
    return _wrap(res, split, proto)


def nancumsum(x, axis: int = None, dtype=None, out=None) -> DNDarray:
    """Cumulative sum treating NaN as zero."""
    return _cum_op(jnp.nancumsum, x, axis=axis, dtype=dtype, out=out)


def nancumprod(x, axis: int = None, dtype=None, out=None) -> DNDarray:
    """Cumulative product treating NaN as one."""
    return _cum_op(jnp.nancumprod, x, axis=axis, dtype=dtype, out=out)


def i0(x) -> DNDarray:
    """Modified Bessel function of the first kind, order 0."""
    return _local_op(jnp.i0, x)


__all__ += ["ediff1d", "gradient", "i0", "interp", "nancumprod", "nancumsum", "nextafter", "reciprocal", "spacing"]


# array-API bitwise aliases (numpy 2 names)
bitwise_invert = invert
bitwise_left_shift = left_shift
bitwise_right_shift = right_shift


def bitwise_count(x, out=None) -> DNDarray:
    """Number of set bits per element (numpy ``bitwise_count``)."""
    return _local_op(jnp.bitwise_count, x, out=out)


__all__ += ["bitwise_count", "bitwise_invert", "bitwise_left_shift", "bitwise_right_shift"]
