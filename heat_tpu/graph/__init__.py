"""Graph analytics (reference: ``heat/graph/``)."""

from .laplacian import Laplacian
