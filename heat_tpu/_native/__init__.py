"""Native (C++) runtime components, loaded via ctypes.

The reference inherits its native layer from torch/MPI (SURVEY §2.7 — zero
first-party native code); here the framework carries its own: a threaded
mmap CSV engine (byte-range splitting with line fixup, exactly the
reference's parallel-CSV strategy run across threads instead of ranks) and
the shard/chunk math.  Compiled on demand with g++ into ``libheatnative.so``
next to this file; every entry point has a pure-Python fallback so the
package works without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import warnings

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "csvparse.cc")
_SO = os.path.join(_HERE, "libheatnative.so")

_lock = threading.Lock()
_lib = None
_build_failed = False


def _build() -> bool:
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        "-o", _SO, _SRC,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        warnings.warn(f"heat_tpu native build failed ({e}); using Python fallbacks")
        return False


def _load():
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        stale = not os.path.exists(_SO) or (
            os.path.exists(_SRC) and os.path.getmtime(_SO) < os.path.getmtime(_SRC)
        )
        if stale and not _build():
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as e:
            warnings.warn(f"heat_tpu native load failed ({e}); using Python fallbacks")
            _build_failed = True
            return None
        lib.csv_index_open.restype = ctypes.c_void_p
        lib.csv_index_open.argtypes = [ctypes.c_char_p, ctypes.c_int64, ctypes.c_int]
        lib.csv_index_close.restype = None
        lib.csv_index_close.argtypes = [ctypes.c_void_p]
        lib.csv_index_rows.restype = ctypes.c_int64
        lib.csv_index_rows.argtypes = [ctypes.c_void_p]
        lib.csv_index_cols.restype = ctypes.c_int64
        lib.csv_index_cols.argtypes = [ctypes.c_void_p, ctypes.c_char]
        lib.csv_index_parse.restype = ctypes.c_int64
        lib.csv_index_parse.argtypes = [
            ctypes.c_void_p, ctypes.c_char, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.POINTER(ctypes.c_double), ctypes.c_int,
        ]
        lib.csv_write.restype = ctypes.c_int64
        lib.csv_write.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_double),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_char, ctypes.c_int,
            ctypes.c_int, ctypes.c_int,
        ]
        lib.chunk_counts_displs.restype = ctypes.c_int64
        lib.chunk_counts_displs.argtypes = [
            ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ]
        _lib = lib
        return _lib


def available() -> bool:
    """True when the native library is built and loadable."""
    return _load() is not None


class CsvIndex:
    """A reusable row index over a CSV file: one mmap + line scan serves
    dims and any number of window parses (the per-shard hyperslab reads)."""

    def __init__(self, path: str, skiprows: int = 0, nthreads: int = 0):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.csv_index_open(path.encode(), skiprows, nthreads)
        if not self._h:
            raise OSError(f"cannot open/index {path}")

    @property
    def nrows(self) -> int:
        return int(self._lib.csv_index_rows(self._h))

    def ncols(self, sep: str = ",") -> int:
        return int(self._lib.csv_index_cols(self._h, sep.encode()[:1]))

    def parse(self, sep: str = ",", row_begin: int = 0, row_end: int | None = None,
              ncols: int | None = None, nthreads: int = 0) -> np.ndarray:
        if row_end is None:
            row_end = self.nrows
        if ncols is None:
            ncols = self.ncols(sep)
        out = np.empty((max(row_end - row_begin, 0), ncols), dtype=np.float64)
        rc = self._lib.csv_index_parse(
            self._h, sep.encode()[:1], row_begin, row_end, ncols,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), nthreads,
        )
        if rc == -3:
            raise ValueError("ragged CSV: rows have inconsistent column counts")
        if rc != 0:
            raise ValueError(f"csv parse failed (rc={rc})")
        return out

    def close(self) -> None:
        if self._h:
            self._lib.csv_index_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def csv_dims(path: str, sep: str = ",", skiprows: int = 0, nthreads: int = 0):
    """(nrows, ncols) of the data region of a CSV file, or None on fallback."""
    if _load() is None or len(sep) != 1:
        return None
    try:
        with CsvIndex(path, skiprows, nthreads) as idx:
            return idx.nrows, idx.ncols(sep)
    except OSError:
        return None


def csv_parse(path: str, sep: str = ",", skiprows: int = 0,
              row_begin: int = 0, row_end: int | None = None,
              ncols: int | None = None, nthreads: int = 0) -> np.ndarray | None:
    """Parse rows [row_begin, row_end) into a float64 (rows, ncols) array.

    Returns None when the native library is unavailable or the file cannot
    be opened (caller falls back); raises ValueError on malformed data.
    """
    if _load() is None or len(sep) != 1:
        return None
    try:
        idx = CsvIndex(path, skiprows, nthreads)
    except OSError:
        return None
    with idx:
        if row_end is not None and row_end > idx.nrows:
            return None
        return idx.parse(sep, row_begin, row_end, ncols, nthreads)


def csv_write(path: str, data: np.ndarray, sep: str = ",", decimals: int = -1,
              float32_repr: bool = False, nthreads: int = 0) -> bool:
    """Write a 2-D float array as CSV; returns False on fallback."""
    lib = _load()
    if lib is None or len(sep) != 1:
        return False
    arr = np.ascontiguousarray(data, dtype=np.float64)
    if arr.ndim != 2:
        raise ValueError("csv_write expects a 2-D array")
    rc = lib.csv_write(
        path.encode(), arr.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        arr.shape[0], arr.shape[1], sep.encode()[:1], decimals,
        1 if float32_repr else 0, nthreads,
    )
    return rc == 0


def chunk_counts_displs(n: int, nproc: int):
    """Per-rank (counts, displs) of the ceil-div grid, or None on fallback."""
    lib = _load()
    if lib is None:
        return None
    counts = (ctypes.c_int64 * nproc)()
    displs = (ctypes.c_int64 * nproc)()
    rc = lib.chunk_counts_displs(n, nproc, counts, displs)
    if rc != 0:
        return None
    return np.ctypeslib.as_array(counts).copy(), np.ctypeslib.as_array(displs).copy()
