"""NN layer (reference: ``heat/nn/``): module constructors + DataParallel."""

from .modules import *
from . import modules
from .activations import *
from .losses import *
from .spatial import *
from .padshuffle import *
from .extended import *
from . import activations, extended, losses, padshuffle, spatial
from .attention import MultiheadAttention, apply_rope
from .moe import MoE
from .pipelined import Pipelined
from .recurrent import GRU, GRUCell, LSTM, LSTMCell, RNN, RNNCell
from .data_parallel import DataParallel, DataParallelMultiGPU
from . import functional
from . import models
