"""Data utilities (reference: ``heat/utils/data/``)."""

from . import matrixgallery
from . import spherical
from .spherical import create_spherical_dataset, create_clusters
from .datatools import Dataset, DataLoader, dataset_shuffle, dataset_ishuffle
from .mnist import MNISTDataset
from .partial_dataset import PartialH5Dataset
