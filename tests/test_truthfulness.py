"""Truthfulness pass (VERDICT r2 item 8): perf-trap warnings on gather-based
rooted collectives, the documented reshape output-split rule, and the
single-controller rank/lshape semantics."""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import heat_tpu as ht

# SPMD-safe: deterministic data, collective-friendly — runs in the
# multi-process lane too (VERDICT r4 weak #6; see conftest HEAT_MP_COORD)
pytestmark = pytest.mark.mp
from heat_tpu.core.communication import Communication
from test_suites.basic_test import TestCase


class TestGatherTrapWarnings(TestCase):
    def test_gather_warns_above_threshold(self):
        """Gather inherently materializes the full buffer on every shard
        (SPMD) — it stays warned.  Bcast/Exscan/Scan/prod were rewritten to
        O(1)/O(log p) collective forms and must NOT warn (see
        test_scalable_collectives_silent)."""
        comm = ht.communication.get_comm()
        if not comm.is_distributed():
            pytest.skip("p=1: Gather is local, nothing to warn about")
        old = Communication.GATHER_WARN_THRESHOLD
        # threshold relative to the actual mesh so this mesh counts as
        # "large" at any device count (the warning fires when size > thr)
        Communication.GATHER_WARN_THRESHOLD = max(comm.size - 1, 1)
        try:
            with warnings.catch_warnings(record=True) as rec:
                warnings.simplefilter("always")
                x = jnp.ones((2 * comm.size, 4))  # divisible at any mesh size
                comm.shard_map(
                    lambda b: comm.Gather(b), in_splits=((2, 0),), out_splits=(2, 0)
                )(x)
            msgs = [str(w.message) for w in rec if "gather-based" in str(w.message)]
            assert any("Gather" in m for m in msgs), "no perf-trap warning for Gather"
        finally:
            Communication.GATHER_WARN_THRESHOLD = old

    def test_scalable_collectives_silent(self):
        """Bcast (masked psum), Exscan/Scan (recursive doubling) and
        Allreduce('prod') (scan + masked psum) are scalable now: no perf-trap
        warning even above the threshold, and values stay correct."""
        comm = ht.communication.get_comm()
        old = Communication.GATHER_WARN_THRESHOLD
        Communication.GATHER_WARN_THRESHOLD = 2
        try:
            p = comm.size
            rows = 2 * p  # raw shard_map needs a divisible axis at any p
            with warnings.catch_warnings(record=True) as rec:
                warnings.simplefilter("always")
                x = jnp.ones((rows, 4))
                bc = comm.shard_map(
                    lambda b: comm.Bcast(b), in_splits=((2, 0),), out_splits=(2, 0)
                )(x)
                ex = comm.shard_map(
                    lambda b: comm.Exscan(b), in_splits=((2, 0),), out_splits=(2, 0)
                )(x)
                pr = comm.shard_map(
                    lambda b: comm.Allreduce(b, op="prod"),
                    in_splits=((2, 0),),
                    out_splits=(2, 0),
                )(x)
            assert not [w for w in rec if "gather-based" in str(w.message)]
            # host_fetch, not np.asarray: shard_map outputs span every
            # process in the -m mp lane (non-addressable shards)
            np.testing.assert_allclose(comm.host_fetch(bc), np.ones((rows, 4)))
            # each shard holds 2 rows of ones → exclusive scan gives every
            # element of shard i the value i (parametric in p)
            want = np.repeat(np.arange(p, dtype=np.float64), 2)[:, None] * np.ones(4)
            np.testing.assert_allclose(comm.host_fetch(ex), want)
            np.testing.assert_allclose(comm.host_fetch(pr), np.ones((rows, 4)))
        finally:
            Communication.GATHER_WARN_THRESHOLD = old


class TestReshapeSplitRule(TestCase):
    def test_same_axis_index_kept(self):
        d = np.arange(24, dtype=np.float32).reshape(4, 6)
        x = ht.array(d, split=1)
        y = ht.reshape(x, (6, 4))
        assert y.split == 1  # SAME axis index, per the documented rule
        self.assert_array_equal(y, d.reshape(6, 4))

    def test_vanished_axis_falls_to_zero(self):
        d = np.arange(24, dtype=np.float32).reshape(4, 6)
        x = ht.array(d, split=1)
        y = ht.reshape(x, (24,))
        assert y.split == 0
        self.assert_array_equal(y, d.reshape(24))

    def test_explicit_new_split_honored(self):
        d = np.arange(24, dtype=np.float32).reshape(4, 6)
        x = ht.array(d, split=0)
        y = ht.reshape(x, (2, 12), new_split=1)
        assert y.split == 1
        self.assert_array_equal(y, d.reshape(2, 12))


class TestSingleControllerSemantics(TestCase):
    def test_rank_is_process_index(self):
        comm = ht.communication.get_comm()
        assert comm.rank == jax.process_index()
        assert comm.n_processes == jax.process_count()
        assert comm.size == len(jax.devices())  # shards ≠ processes

    def test_lshape_is_shard0_chunk(self):
        p = ht.communication.get_comm().size
        c = -(-100 // p)  # ceil-div chunk
        x = ht.zeros((100, 16), split=0)
        assert x.lshape == (c, 16)  # ceil-div chunk of shard 0
        lmap = x.lshape_map()
        assert lmap[:, 0].sum() == 100  # per-shard truth sums to the extent
        want = [min(c, max(100 - i * c, 0)) for i in range(p)]
        assert list(lmap[:, 0]) == want
