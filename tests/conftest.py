"""Test bootstrap: run everything on a virtual 8-device CPU mesh.

The reference runs its suite under ``mpirun -n N`` for several N; the
TPU-native analogue (SURVEY §4) is a multi-device CPU mesh in ONE process via
``--xla_force_host_platform_device_count`` — same code paths as a real pod,
only the transport differs.

**Multi-process mode** (VERDICT r4 weak #6): when ``HEAT_MP_COORD`` is set
(``"n_proc:pid:port:devs"``, exported by
``scripts/multiprocess_dryrun.launch_pytest``), this conftest instead joins
an n-process ``jax.distributed`` world over gloo BEFORE any backend touch,
so the ``-m mp`` subset of the REAL suite runs SPMD across OS processes —
the reference's ``mpirun -n N pytest`` contract, not a bespoke dryrun.
``tmp_path`` is then redirected to a shared per-test directory so file
round-trips exercise the token-ring writers across the process seam.
"""

import os

_MP = os.environ.get("HEAT_MP_COORD")
if _MP:
    _n_proc, _pid, _port, _devs = (int(v) for v in _MP.split(":"))
    os.environ["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={_devs}"
else:
    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_platforms", "cpu")

if _MP:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{_port}",
        num_processes=_n_proc,
        process_id=_pid,
    )

# Persistent XLA compilation cache: the suite is compile-bound on the 1-core
# CI host (measured 54 s -> 31 s for test_linalg.py on a warm cache), and the
# CI matrix re-runs the same programs across device-count/python lanes.
# Cache entries key on topology + HLO, so lanes coexist in one directory.
jax.config.update(
    "jax_compilation_cache_dir",
    os.environ.get("HEAT_TPU_JAX_CACHE", "/tmp/heat_tpu_jax_cache"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

if _MP:
    # watchdog (robustness tier): a rank wedged in a collective must dump
    # per-thread stacks into its log and exit instead of hanging the lane —
    # the launcher (scripts/multiprocess_dryrun.launch_pytest) also sends
    # SIGUSR1 at ITS deadline to demand a dump from a live-but-stuck rank.
    import faulthandler as _faulthandler
    import signal as _signal

    _faulthandler.register(_signal.SIGUSR1)
    _wd = os.environ.get("HEAT_MP_WATCHDOG")
    if _wd:
        _faulthandler.dump_traceback_later(float(_wd), exit=True)

    import heat_tpu as _ht

    _ht.core.bootstrap.init_distributed(num_processes=_n_proc, process_id=_pid)

import numpy as np
import pytest


@pytest.fixture
def ht():
    import heat_tpu

    return heat_tpu


if _MP:
    @pytest.fixture
    def tmp_path(request):
        """Shared-across-ranks tmp dir: each test gets ONE directory common
        to every process (keyed on the test's nodeid), so a token-ring
        hyperslab write from rank 0 and rank 1 lands in the same file —
        pytest's per-process default would silently split the round-trip."""
        import hashlib
        import pathlib

        base = pathlib.Path(os.environ["HEAT_MP_TMP"])
        key = hashlib.sha1(request.node.nodeid.encode()).hexdigest()[:16]
        p = base / key
        p.mkdir(parents=True, exist_ok=True)
        return p


# split sweep used across op tests (the reference's distributed-coverage trick)
SPLITS_1D = [None, 0]
SPLITS_2D = [None, 0, 1]
