"""Round-3 numpy-parity batch 4: sorting/selection, set ops, gradients,
histograms, factories (windows, index helpers), inner/tensordot, correlate.

Every DNDarray-returning op goes through ``assert_array_equal`` (value vs
numpy oracle AND physical-sharding check) where the result is deterministic.
"""

import numpy as np
import pytest

import heat_tpu as ht

from test_suites.basic_test import TestCase

rng = np.random.default_rng(0)
X = rng.standard_normal((24, 6)).astype(np.float32)
V = rng.standard_normal(24).astype(np.float32)


class TestSortingSelection(TestCase):
    @pytest.mark.parametrize("split", [None, 0])
    def test_argsort_take_partition(self, split):
        a = ht.array(X, split=split)
        av = ht.array(V, split=split)
        self.assert_array_equal(ht.argsort(av), np.argsort(V, stable=True))
        self.assert_array_equal(ht.take(a, [3, 1, 2], axis=0), np.take(X, [3, 1, 2], axis=0))
        idx = np.argsort(X, axis=0)
        self.assert_array_equal(ht.take_along_axis(a, ht.array(idx, split=split), 0), np.take_along_axis(X, idx, 0))
        got = np.sort(ht.partition(av, 5).numpy()[:5])
        np.testing.assert_allclose(got, np.sort(np.partition(V, 5)[:5]))
        self.assert_array_equal(ht.searchsorted(ht.array(np.sort(V)), av), np.searchsorted(np.sort(V), V))

    def test_take_split_bookkeeping(self):
        a = ht.array(X, split=1)
        t = ht.take(a, [0, 2], axis=0)  # take before the split axis
        assert t.split == 1
        self.assert_array_equal(t, np.take(X, [0, 2], axis=0))
        t2 = ht.take(ht.array(X, split=0), 3, axis=0)  # scalar drops the axis
        assert t2.split is None

    def test_selection_ops(self):
        a = ht.array(X, split=0)
        av = ht.array(V, split=0)
        self.assert_array_equal(ht.compress(V > 0, av), np.compress(V > 0, V))
        self.assert_array_equal(ht.extract(a > 0, a), np.extract(X > 0, X))
        self.assert_array_equal(ht.select([a > 1, a < -1], [a, -a], default=0.0), np.select([X > 1, X < -1], [X, -X], 0.0))
        self.assert_array_equal(ht.lexsort([av, ht.array(V[::-1].copy(), split=0)]), np.lexsort([V, V[::-1]]))

    def test_reorder_and_trim(self):
        a = ht.array(X, split=0)
        self.assert_array_equal(ht.rollaxis(a, 1), np.rollaxis(X, 1))
        self.assert_array_equal(ht.resize(a, (5, 7)), np.resize(X, (5, 7)))
        z = np.array([0, 0, 1, 2, 0], np.float32)
        self.assert_array_equal(ht.trim_zeros(ht.array(z)), np.trim_zeros(z))
        self.assert_array_equal(ht.concat([a, a]), np.concatenate([X, X]))
        self.assert_array_equal(ht.permute_dims(a), X.T)
        self.assert_array_equal(ht.matrix_transpose(a), X.T)
        self.assert_array_equal(ht.argwhere(a > 0.5), np.argwhere(X > 0.5))

    def test_diag_and_fill(self):
        self.assert_array_equal(ht.diagflat(ht.array(V[:4], split=0)), np.diagflat(V[:4]))
        b = ht.array(X.copy(), split=0)
        ht.fill_diagonal(b, 9.0)
        xb = X.copy()
        np.fill_diagonal(xb, 9.0)
        self.assert_array_equal(b, xb)


class TestSetOps(TestCase):
    def test_all_set_ops(self):
        i1 = np.array([1, 2, 3, 4], np.int32)
        i2 = np.array([3, 4, 5], np.int32)
        a1, a2 = ht.array(i1, split=0), ht.array(i2)
        self.assert_array_equal(ht.union1d(a1, a2), np.union1d(i1, i2))
        self.assert_array_equal(ht.intersect1d(a1, a2), np.intersect1d(i1, i2))
        self.assert_array_equal(ht.setdiff1d(a1, a2), np.setdiff1d(i1, i2))
        self.assert_array_equal(ht.setxor1d(a1, a2), np.setxor1d(i1, i2))
        self.assert_array_equal(ht.isin(a1, i2), np.isin(i1, i2))
        self.assert_array_equal(ht.in1d(a1, i2), np.isin(i1, i2))


class TestNumericalOps(TestCase):
    @pytest.mark.parametrize("split", [None, 0])
    def test_elementwise(self, split):
        a = ht.array(X, split=split)
        self.assert_array_equal(ht.reciprocal(a), np.reciprocal(X))
        self.assert_array_equal(ht.nextafter(a, a + 1), np.nextafter(X, X + 1))
        self.assert_array_equal(ht.fix(a * 3), np.fix(X * 3))
        self.assert_array_equal(ht.around(a * 3), np.around(X * 3))
        self.assert_array_equal(ht.i0(ht.array(V, split=split)), np.i0(V), rtol=1e-3)

    def test_gradient_interp_ediff1d(self):
        a = ht.array(X, split=0)
        av = ht.array(V, split=0)
        self.assert_array_equal(ht.gradient(a, axis=0), np.gradient(X, axis=0))
        for g, w in zip(ht.gradient(a, axis=(0, 1)), np.gradient(X, axis=(0, 1))):
            self.assert_array_equal(g, w)
        with pytest.raises(NotImplementedError):
            ht.gradient(a, axis=0, edge_order=2)
        xp = np.sort(rng.standard_normal(10)).astype(np.float32)
        fp = rng.standard_normal(10).astype(np.float32)
        self.assert_array_equal(ht.interp(av, ht.array(xp), ht.array(fp)), np.interp(V, xp, fp).astype(np.float32))
        self.assert_array_equal(ht.ediff1d(a), np.ediff1d(X))

    def test_nan_cums_and_quantiles(self):
        xn = X.copy()
        xn[2, 1] = np.nan
        an = ht.array(xn, split=0)
        self.assert_array_equal(ht.nancumsum(an, axis=0), np.nancumsum(xn, axis=0))
        self.assert_array_equal(ht.nancumprod(an, axis=0), np.nancumprod(xn, axis=0), rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(float(ht.nanmedian(an).numpy()), np.nanmedian(xn), rtol=1e-4)
        np.testing.assert_allclose(float(ht.nanpercentile(an, 30).numpy()), np.nanpercentile(xn, 30), rtol=1e-3)
        np.testing.assert_allclose(float(ht.nanquantile(an, 0.7).numpy()), np.nanquantile(xn, 0.7), rtol=1e-3)
        self.assert_array_equal(ht.fmax(an, ht.array(X, split=0)), np.fmax(xn, X))
        self.assert_array_equal(ht.fmin(an, ht.array(X, split=0)), np.fmin(xn, X))

    def test_histograms(self):
        av = ht.array(V, split=0)
        self.assert_array_equal(ht.histogram_bin_edges(av, 8), np.histogram_bin_edges(V, 8).astype(np.float32), rtol=1e-4)
        h2, _, _ = ht.histogram2d(av, ht.array(V[::-1].copy(), split=0), bins=5)
        wh, _, _ = np.histogram2d(V, V[::-1], bins=5)
        self.assert_array_equal(h2, wh)
        hd, _ = ht.histogramdd(ht.array(X[:, :2], split=0), bins=4)
        whd, _ = np.histogramdd(X[:, :2], bins=4)
        self.assert_array_equal(hd, whd)

    def test_predicates(self):
        a = ht.array(X, split=0)
        assert ht.array_equal(a, ht.array(X)) and not ht.array_equal(a, a[1:])
        assert ht.array_equiv(ht.array(np.ones((1, 6), np.float32)), ht.array(np.ones((3, 6), np.float32)))
        assert not ht.iscomplexobj(a) and ht.isrealobj(a)
        assert not ht.isscalar(a) and ht.isscalar(3.0)
        assert ht.amax(a, axis=None).numpy() == np.amax(X)


class TestFactoriesBatch(TestCase):
    def test_structured(self):
        self.assert_array_equal(ht.identity(5), np.identity(5, np.float32))
        self.assert_array_equal(ht.geomspace(1, 256, 9), np.geomspace(1, 256, 9).astype(np.float32), rtol=1e-4)
        self.assert_array_equal(ht.tri(4, 6, 1), np.tri(4, 6, 1).astype(np.float32))
        self.assert_array_equal(ht.vander(ht.array(V[:5], split=0)), np.vander(V[:5]), rtol=1e-3)
        self.assert_array_equal(ht.indices((3, 4)), np.indices((3, 4)))

    def test_index_helpers(self):
        r, _ = ht.diag_indices(4)
        np.testing.assert_array_equal(r.numpy(), np.diag_indices(4)[0])
        a = ht.array(X[:6, :6], split=0)
        r2, c2 = ht.tril_indices_from(a)
        er2, ec2 = np.tril_indices_from(X[:6, :6])
        np.testing.assert_array_equal(r2.numpy(), er2)
        np.testing.assert_array_equal(c2.numpy(), ec2)
        u = ht.unravel_index(ht.array(np.array([7, 13], np.int32)), (4, 6))
        eu = np.unravel_index(np.array([7, 13]), (4, 6))
        np.testing.assert_array_equal(u[0].numpy(), eu[0])
        rm = ht.ravel_multi_index((ht.array(np.array([1, 2], np.int32)), ht.array(np.array([3, 4], np.int32))), (4, 6))
        np.testing.assert_array_equal(rm.numpy(), np.ravel_multi_index((np.array([1, 2]), np.array([3, 4])), (4, 6)))
        ix = ht.ix_(ht.array(np.array([0, 2], np.int32)), ht.array(np.array([1, 3], np.int32)))
        np.testing.assert_array_equal(ix[0].numpy(), np.ix_(np.array([0, 2]), np.array([1, 3]))[0])

    def test_windows(self):
        for name in ("bartlett", "blackman", "hamming", "hanning"):
            self.assert_array_equal(getattr(ht, name)(16), getattr(np, name)(16).astype(np.float32), rtol=1e-4)
        self.assert_array_equal(ht.kaiser(16, 8.6), np.kaiser(16, 8.6).astype(np.float32), rtol=1e-3)


class TestLinalgBatch(TestCase):
    @pytest.mark.parametrize("split", [None, 0])
    def test_inner_tensordot_vecdot(self, split):
        a = ht.array(X, split=split)
        self.assert_array_equal(ht.inner(a, a), np.inner(X, X), rtol=1e-3, atol=1e-3)
        td = ht.tensordot(a, ht.array(X.T), axes=1)
        self.assert_array_equal(td, np.tensordot(X, X.T, 1), rtol=1e-3, atol=1e-2)
        if split == 0:
            assert td.split == 0  # a's free split axis survives the contraction
        self.assert_array_equal(ht.vecdot(a, a), np.sum(X * X, -1), rtol=1e-3)

    def test_tensordot_contracted_split(self):
        a = ht.array(X, split=1)  # split axis IS contracted
        td = ht.tensordot(a, ht.array(X.T), axes=1)
        assert td.split is None
        self.assert_array_equal(td, np.tensordot(X, X.T, 1), rtol=1e-3, atol=1e-2)


class TestCorrelate(TestCase):
    @pytest.mark.parametrize("mode", ["full", "same", "valid"])
    def test_matches_numpy(self, mode):
        a = rng.standard_normal(40).astype(np.float32)
        v = rng.standard_normal(5).astype(np.float32)
        got = ht.correlate(ht.array(a, split=0), ht.array(v), mode=mode)
        self.assert_array_equal(got, np.correlate(a, v, mode=mode), rtol=1e-4, atol=1e-4)


class TestMopUp(TestCase):
    """Final parity batch: append/astype/copyto, in-place mutators, apply
    helpers, array-API unique quartet and bitwise aliases."""

    def test_append_astype_layout(self):
        a = ht.array(X, split=0)
        self.assert_array_equal(ht.append(a, ht.array(X[:2], split=0), axis=0), np.append(X, X[:2], axis=0))
        self.assert_array_equal(ht.append(a, [1.0, 2.0]), np.append(X, [1.0, 2.0]).astype(np.float32))
        assert ht.astype(a, ht.int32).dtype == ht.int32
        assert ht.ascontiguousarray(a) is a
        assert isinstance(ht.array2string(a), str)
        assert isinstance(ht.array_str(a), str) and isinstance(ht.array_repr(a), str)

    def test_mutators(self):
        b = ht.array(X.copy(), split=0)
        idx = np.argsort(X, axis=0)[:1]
        ht.put_along_axis(b, ht.array(idx.astype(np.int32)), 0.0, 0)
        xb = X.copy()
        np.put_along_axis(xb, idx, 0.0, 0)
        self.assert_array_equal(b, xb)
        c = ht.array(X.copy(), split=0)
        ht.put(c, [0, 5], [9.0, 8.0])
        xc = X.copy()
        np.put(xc, [0, 5], [9.0, 8.0])
        self.assert_array_equal(c, xc)
        d = ht.array(X.copy(), split=0)
        vals = np.array([7.0, 6.0], np.float32)
        ht.place(d, X > 0.5, vals)
        xd = X.copy()
        np.place(xd, X > 0.5, vals)
        self.assert_array_equal(d, xd)
        e = ht.array(X.copy(), split=0)
        ht.putmask(e, X > 0.5, ht.array(X * 10, split=0))
        xe = X.copy()
        np.putmask(xe, X > 0.5, X * 10)
        self.assert_array_equal(e, xe)
        f = ht.array(X.copy(), split=0)
        ht.copyto(f, 0.0, where=ht.array(X > 0, split=0))
        xf = X.copy()
        np.copyto(xf, 0.0, where=X > 0)
        self.assert_array_equal(f, xf)

    def test_apply_helpers(self):
        import jax.numpy as jnp

        a = ht.array(X, split=0)
        self.assert_array_equal(
            ht.apply_along_axis(lambda r: r - r.mean(), 0, a),
            np.apply_along_axis(lambda r: r - r.mean(), 0, X), rtol=1e-5, atol=1e-6,
        )
        self.assert_array_equal(ht.apply_over_axes(jnp.sum, a, [0]), np.apply_over_axes(np.sum, X, [0]), rtol=1e-5, atol=1e-4)
        self.assert_array_equal(
            ht.piecewise(a, [a < 0, a >= 0], [lambda v: -v, lambda v: v]),
            np.piecewise(X, [X < 0, X >= 0], [lambda v: -v, lambda v: v]),
        )

    def test_unique_quartet_and_bitwise(self):
        iv = ht.array(np.array([3, 1, 2, 1, 3], np.int32), split=0)
        nua = np.unique_all(np.array([3, 1, 2, 1, 3], np.int32))
        ua = ht.unique_all(iv)
        np.testing.assert_array_equal(ua.values.numpy(), nua.values)
        np.testing.assert_array_equal(ua.inverse_indices.numpy(), nua.inverse_indices)
        np.testing.assert_array_equal(ua.counts.numpy(), nua.counts)
        np.testing.assert_array_equal(ht.unique_counts(iv).counts.numpy(), nua.counts)
        np.testing.assert_array_equal(ht.unique_inverse(iv).inverse_indices.numpy(), nua.inverse_indices)
        np.testing.assert_array_equal(ht.unique_values(iv).numpy(), nua.values)
        bc = np.array([7, 8], np.int32)
        self.assert_array_equal(ht.bitwise_count(ht.array(bc)), np.bitwise_count(bc))
        assert ht.bitwise_invert is ht.invert
        r, _ = ht.mask_indices(4, np.triu, 1)
        np.testing.assert_array_equal(r.numpy(), np.mask_indices(4, np.triu, 1)[0])
        assert ht.isdtype(ht.float32, "real floating") and not ht.isdtype(ht.int32, "real floating")

    def test_full_coverage_scripted(self):
        """The scripts/ coverage table reports 100% of the in-scope surface."""
        import subprocess
        import sys
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        # pin the subprocess to CPU: inheriting the accelerator platform
        # hangs the import when the tunnel is wedged (it only lists names)
        env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
        env["JAX_PLATFORMS"] = "cpu"
        out = subprocess.run(
            [sys.executable, os.path.join(repo, "scripts", "numpy_coverage.py")],
            capture_output=True, text=True, timeout=240, env=env,
        )
        assert out.returncode == 0, out.stderr[-500:]
        assert "(100.0%)" in out.stdout, out.stdout[-300:]

    def test_raise_modes_and_cycling(self):
        """Regression: numpy 'raise' contracts + put value cycling."""
        with pytest.raises(ValueError):
            ht.ravel_multi_index((ht.array(np.array([5], np.int32)), ht.array(np.array([0], np.int32))), (3, 3))
        with pytest.raises(ValueError):
            ht.choose(ht.array(np.array([0, 3], np.int32)), [ht.zeros((2,)), ht.ones((2,))])
        x = np.arange(12, dtype=np.float32)
        p = ht.array(x.copy(), split=0)
        ht.put(p, [0, 1, 2], [10.0, 20.0])  # short list cycles
        xe = x.copy()
        np.put(xe, [0, 1, 2], [10.0, 20.0])
        self.assert_array_equal(p, xe)
        with pytest.raises(IndexError):
            ht.put(ht.array(x.copy()), [99], [1.0])
        p2 = ht.array(x.copy(), split=0)
        ht.put(p2, [13], [5.0], mode="wrap")
        x2 = x.copy()
        np.put(x2, [13], [5.0], mode="wrap")
        self.assert_array_equal(p2, x2)
        with pytest.raises(TypeError):
            ht.lexsort([np.array([1, 2]), np.array([3, 4])])

    def test_copyto_keeps_sharding(self):
        c = ht.arange(16, dtype=ht.float32, split=0)
        ht.copyto(c, np.ones(16, np.float32))
        self.assert_distributed(c)
        self.assert_array_equal(c, np.ones(16, np.float32))

    def test_complex_correlate_conjugates(self):
        a = np.array([1 + 2j, 2 - 1j, 0.5 + 0j], np.complex64)
        v = np.array([0 + 1j, 1 + 0j], np.complex64)
        got = ht.correlate(ht.array(a), ht.array(v), mode="full")
        np.testing.assert_allclose(got.numpy(), np.correlate(a, v, mode="full"), rtol=1e-5)
