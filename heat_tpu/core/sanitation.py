"""Input/output sanitation (reference: ``heat/core/sanitation.py``).

Host-sync contract (zero-copy dispatch audit): every check in this module
is METADATA-ONLY — shapes, dtypes, splits, types.  No function here may
read array *values* (no ``item()``/``np.asarray``/comparisons on device
data): sanitation runs on every op dispatch, and a value-dependent check
would be a blocking device→host sync in the middle of an async pipeline.
Value-dependent validation belongs behind explicit materialization points
(``numpy()``, ``item()``, printing) or inside the computation itself.
"""

from __future__ import annotations

import warnings
from typing import Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np

from . import types
from .communication import sanitize_comm
from .dndarray import DNDarray

__all__ = [
    "sanitize_in",
    "sanitize_infinity",
    "sanitize_in_tensor",
    "sanitize_lshape",
    "sanitize_out",
    "sanitize_distribution",
    "sanitize_sequence",
    "scalar_to_1d",
]


def sanitize_in(x) -> None:
    """Raise if ``x`` is not a DNDarray."""
    if not isinstance(x, DNDarray):
        raise TypeError(f"Input must be a DNDarray, got {type(x)}")


def sanitize_infinity(x) -> Union[int, float]:
    """Largest representable value of ``x``'s dtype (for ±inf substitution)."""
    dtype = x.dtype if isinstance(x, DNDarray) else types.canonical_heat_type(x.dtype)
    if types.heat_type_is_exact(dtype):
        return types.iinfo(dtype).max
    return types.finfo(dtype).max


def sanitize_in_tensor(x) -> jnp.ndarray:
    """Coerce to a raw jax array."""
    if isinstance(x, DNDarray):
        return x._jarray
    return jnp.asarray(x)


def sanitize_lshape(array: DNDarray, tensor) -> None:
    """Validate that a local tensor is a plausible shard of ``array``."""
    tshape = tuple(tensor.shape)
    if array.split is None:
        if tshape != array.gshape:
            raise ValueError(f"local tensor shape {tshape} inconsistent with {array.gshape}")
        return
    for i, (t, g) in enumerate(zip(tshape, array.gshape)):
        if i != array.split and t != g:
            raise ValueError(f"local tensor shape {tshape} inconsistent with {array.gshape}")


def sanitize_out(
    out: DNDarray,
    output_shape: Sequence[int],
    output_split: Optional[int],
    output_device,
    output_comm=None,
) -> None:
    """Validate an ``out=`` buffer against the expected result metadata."""
    sanitize_in(out)
    if tuple(out.shape) != tuple(output_shape):
        raise ValueError(f"Expecting output buffer of shape {tuple(output_shape)}, got {out.shape}")
    if out.split != output_split:
        # like the reference, repartition out to the required split (with warning)
        warnings.warn(
            f"Split axis of output buffer is inconsistent with split semantics (resplitting out from {out.split} to {output_split})."
        )
        out.resplit_(output_split)


def sanitize_distribution(*args, target: DNDarray, diff_map=None):
    """Force all DNDarray args onto the split/comm of ``target`` (reference parity).

    Under XLA this is a resharding ``device_put`` per mismatched operand.
    Returns single array or tuple.
    """
    out = []
    for a in args:
        sanitize_in(a)
        if a.split != target.split:
            a = a.resplit(target.split)
        out.append(a)
    return out[0] if len(out) == 1 else tuple(out)


def sanitize_sequence(seq) -> list:
    if isinstance(seq, list):
        return seq
    if isinstance(seq, tuple):
        return list(seq)
    if isinstance(seq, DNDarray):
        if seq.split is None:
            return [seq[i] for i in range(len(seq))]
        raise TypeError("seq must not be distributed")
    raise TypeError(f"seq must be a list, tuple or DNDarray, got {type(seq)}")


def scalar_to_1d(x: DNDarray) -> DNDarray:
    """Reshape a scalar DNDarray to shape (1,)."""
    if x.ndim == 0:
        return DNDarray(
            x._jarray.reshape(1), (1,), x.dtype, None, x.device, x.comm, True
        )
    return x
