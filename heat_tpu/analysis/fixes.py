"""heatfix — the proof-carrying autofix layer over heatlint's findings.

The analyses can *prove* things (call-graph effect summaries, rank-taint +
metadata abstract interpretation); this module closes the loop from proof
to patch.  Each :class:`Fixer` is registered against one rule code,
receives the finding plus the facts that produced it (the parsed
:class:`~.framework.LintContext` and the package-wide
:class:`~.summaries.Program`), and emits concrete token/AST-span splices on
the ORIGINAL source — **only when a safety proof holds**:

- HT101 host syncs (``.item()`` / ``float()``/``int()``/``bool()`` casts of
  device values) rewrite to the sanctioned deadline-guarded
  ``Communication.host_fetch`` route only when the expression is *provably
  0-d* (a full-array reduction with no ``axis=``, or abstract metadata with
  ``dims == []``) **and** the enclosing function is provably not inside a
  traced context (no jit/vmap/grad/shard_map decorator, not a nested def a
  parent might trace, never passed to a tracing transform, no module-level
  jit alias).
- HT105 raw-entropy sites reroute through ``core/random``'s sanctioned
  ``host_rng`` only when the seed is a literal constant — the one case
  where rank-uniformity is provable rather than hoped.
- HT107 naked blocking waits wrap in ``with comm.deadline(...)`` only when
  a Communication handle is lexically in scope **and** the call graph
  proves no enclosing scope already arms a deadline (wrapping under an
  armed caller would silently tighten the caller's budget).
- HT110 stale suppressions delete themselves — the staleness re-lint IS
  the proof.

Unprovable sites are left byte-identical with a per-site refusal
``reason`` (the honesty policy, fix edition) that ships in ``--json`` and
the CLI summary.  The engine's own contract, asserted on every run:

- **post-fix re-lint**: every fixed file re-lints clean for the fixed
  fingerprints (a fix that does not kill its finding is a bug → raised,
  never written silently);
- **idempotence**: planning fixes on the fixed tree yields zero edits
  (fix ∘ fix = fix), asserted before anything touches disk;
- SARIF ``fixes`` objects carry every planned patch so code scanning
  surfaces the concrete edit next to the finding.

Stdlib-only and standalone-loadable, like the rest of ``analysis/``.
"""

from __future__ import annotations

import ast
import difflib
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .callgraph import call_name, last_attr
from .framework import Finding, LintContext, all_rules, disabled_rules_for

__all__ = [
    "Edit",
    "Fixer",
    "FixOutcome",
    "FixError",
    "register_fixer",
    "fixable_rules",
    "plan_fixes",
    "apply_edits",
    "execute_fixes",
    "node_span",
    "ensure_import_edit",
    "sarif_fixes",
]


class FixError(RuntimeError):
    """A fixer violated its own contract (post-fix re-lint dirty, or the
    engine is not idempotent).  Raised BEFORE any file is written."""


# ------------------------------------------------------------------ #
# edits: character-offset splices on the original source
# ------------------------------------------------------------------ #


@dataclass(frozen=True)
class Edit:
    """One splice: replace ``source[start:end]`` with ``replacement``.
    Offsets are CHARACTER offsets into the file's source text (an insertion
    has ``start == end``)."""

    path: str
    start: int
    end: int
    replacement: str
    note: str = ""


def _line_starts(source: str) -> List[int]:
    starts = [0]
    for i, ch in enumerate(source):
        if ch == "\n":
            starts.append(i + 1)
    return starts


def _pos_to_offset(source: str, lines: Sequence[str], starts: Sequence[int],
                   line: int, byte_col: int) -> int:
    """(1-based line, utf-8 byte col — ast's coordinate system) → char offset."""
    text = lines[line - 1] if line - 1 < len(lines) else ""
    col = len(text.encode("utf-8")[:byte_col].decode("utf-8", errors="ignore"))
    return starts[line - 1] + col


def node_span(ctx: LintContext, node: ast.AST) -> Tuple[int, int]:
    """Character span of ``node`` in ``ctx.source`` (ast cols are utf-8
    byte offsets; files with non-ASCII lines still splice correctly)."""
    starts = _line_starts(ctx.source)
    s = _pos_to_offset(ctx.source, ctx.lines, starts, node.lineno, node.col_offset)
    e = _pos_to_offset(
        ctx.source, ctx.lines, starts, node.end_lineno or node.lineno,
        node.end_col_offset or node.col_offset,
    )
    return s, e


def offset_to_linecol(source: str, offset: int) -> Tuple[int, int]:
    """char offset → (1-based line, 1-based character column) for SARIF."""
    line = source.count("\n", 0, offset) + 1
    last_nl = source.rfind("\n", 0, offset)
    return line, offset - (last_nl + 1) + 1


def apply_edits(source: str, edits: Sequence[Edit]) -> str:
    """Apply non-overlapping edits (any order given; applied right-to-left
    so earlier offsets stay valid).  Overlap is the PLANNER's job to
    prevent; here it is a hard error."""
    ordered = sorted(edits, key=lambda e: (e.start, e.end), reverse=True)
    prev_start = None
    for e in ordered:
        if prev_start is not None and e.end > prev_start:
            raise ValueError(f"overlapping edits at offsets {e.start}..{e.end}")
        prev_start = e.start
    out = source
    for e in ordered:
        out = out[: e.start] + e.replacement + out[e.end :]
    return out


def _last_import_line(tree: ast.AST) -> int:
    """1-based line AFTER which a new import should land: below the last
    top-level import, else below the module docstring, else line 0."""
    last = 0
    body = getattr(tree, "body", [])
    for stmt in body:
        if isinstance(stmt, (ast.Import, ast.ImportFrom)):
            last = max(last, stmt.end_lineno or stmt.lineno)
    if last == 0 and body and isinstance(body[0], ast.Expr) and isinstance(
        body[0].value, ast.Constant
    ) and isinstance(body[0].value.value, str):
        last = body[0].end_lineno or body[0].lineno
    return last


def _relative_core_prefix(path: str) -> str:
    """Relative-import prefix reaching ``heat_tpu.core`` from ``path``
    (``heat_tpu/cluster/x.py`` → ``..core``); absolute for files outside
    the package (benchmarks, fixtures)."""
    parts = path.replace("\\", "/").split("/")
    if "heat_tpu" in parts[:-1]:
        depth = len(parts) - parts.index("heat_tpu") - 2  # dirs below heat_tpu/
        return "." * (depth + 1) + "core"
    return "heat_tpu.core"


def ensure_import_edit(ctx: LintContext, import_line: str, marker: str) -> Optional[Edit]:
    """Insertion Edit adding ``import_line`` after the module's imports,
    unless an existing import statement already binds ``marker``."""
    for node in ctx.walk(ast.Import, ast.ImportFrom):
        seg = ast.get_source_segment(ctx.source, node) or ""
        if marker in seg:
            return None
    after = _last_import_line(ctx.tree)
    starts = _line_starts(ctx.source)
    offset = starts[after] if after < len(starts) else len(ctx.source)
    return Edit(ctx.path, offset, offset, import_line + "\n", note=f"import {marker}")


# ------------------------------------------------------------------ #
# fixer protocol + registry
# ------------------------------------------------------------------ #


@dataclass
class FixAttempt:
    """Outcome of one fixer on one finding: either edits or a refusal."""

    finding: Finding
    fixer: str
    edits: List[Edit] = field(default_factory=list)
    refusal: Optional[str] = None  # the per-site `reason` (honesty policy)


class Fixer:
    """One rule's autofix.  Subclass, set ``code``/``name``, implement
    :meth:`try_fix` returning ``(edits, None)`` when the safety proof holds
    or ``([], reason)`` when it does not, and decorate with
    :func:`register_fixer`."""

    code: str = "HT000"
    name: str = "unnamed-fix"
    description: str = ""

    def try_fix(
        self, finding: Finding, ctx: LintContext, program
    ) -> Tuple[List[Edit], Optional[str]]:  # pragma: no cover - interface
        raise NotImplementedError


_FIXERS: Dict[str, Fixer] = {}


def register_fixer(cls):
    _FIXERS[cls.code] = cls()
    return cls


def fixable_rules() -> List[str]:
    return sorted(_FIXERS)


def _find_call(ctx: LintContext, line: int, col: int) -> Optional[ast.Call]:
    for node in ctx.walk(ast.Call):
        if node.lineno == line and node.col_offset == col:
            return node
    return None


# ------------------------------------------------------------------ #
# shared proofs
# ------------------------------------------------------------------ #

# transforms that trace their argument: a host sync inside a traced body is
# a different bug (it fails at trace time or constant-folds), so rewriting
# there is out of the proof's reach
TRACING_TRANSFORMS = frozenset(
    {
        "jit", "pjit", "vmap", "pmap", "grad", "value_and_grad", "shard_map",
        "checkpoint", "remat", "custom_jvp", "custom_vjp", "scan",
        "fori_loop", "while_loop", "cond", "switch",
    }
)

# full-array reductions: with no axis=/keepdims= the result is 0-d whatever
# the operand's rank — the syntactic arm of the 0-d proof
SCALAR_REDUCTIONS = frozenset(
    {
        "sum", "max", "min", "mean", "prod", "any", "all", "argmax",
        "argmin", "median", "std", "var", "ptp", "count_nonzero",
        "nanmax", "nanmin", "nansum", "nanmean", "vdot",
    }
)


def _decorator_names(fn: ast.AST) -> List[str]:
    out = []
    for dec in getattr(fn, "decorator_list", []):
        node = dec.func if isinstance(dec, ast.Call) else dec
        name = None
        if isinstance(node, ast.Attribute):
            name = node.attr
        elif isinstance(node, ast.Name):
            name = node.id
        if name:
            out.append(name)
    return out


def prove_untraced(ctx: LintContext, node: ast.AST, program) -> Optional[str]:
    """None when the enclosing function is provably NOT inside a traced
    context; otherwise the refusal reason.  Conservative on purpose: a
    nested def (closure) refuses because its parent may hand it to a
    tracing transform this pass cannot see."""
    fns = [
        a
        for a in [node] + ctx.ancestors(node)
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    if len(fns) >= 2:
        return (
            f"nested def `{fns[0].name}` may be traced by its enclosing "
            f"function `{fns[1].name}` (closures are routinely passed to "
            "jit/fori_loop) — cannot prove untraced"
        )
    if not fns:
        return None  # module level executes eagerly at import
    fn = fns[0]
    for dec in _decorator_names(fn):
        if dec in TRACING_TRANSFORMS:
            return f"enclosing def `{fn.name}` is decorated with `{dec}` (traced context)"
    # the function object handed to a tracing transform anywhere in the file
    for call in ctx.walk(ast.Call):
        la = last_attr(call)
        if la not in TRACING_TRANSFORMS:
            continue
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            if isinstance(arg, ast.Name) and arg.id == fn.name:
                return (
                    f"`{fn.name}` is passed to `{la}` at line {call.lineno} "
                    "(traced context)"
                )
    # module-level jit aliases recorded by the call graph
    if program is not None:
        facts = program.facts.get(ctx.path)
        if facts is not None:
            for alias, (target, _don) in facts.module_aliases.items():
                if target == fn.name:
                    return (
                        f"`{fn.name}` is jit-aliased at module level as "
                        f"`{alias}` (traced context)"
                    )
    return None


def _strip_item(expr: ast.expr) -> ast.expr:
    if (
        isinstance(expr, ast.Call)
        and isinstance(expr.func, ast.Attribute)
        and expr.func.attr == "item"
        and not expr.args
    ):
        return expr.func.value
    return expr


def _absint_call_meta(ctx: LintContext, program, qualname: str, expr: ast.expr):
    """Concrete abstract metadata for ``expr`` when it is a call the absint
    pass recorded in this function — the value-domain arm of the 0-d proof."""
    if program is None or not isinstance(expr, ast.Call):
        return None
    view = getattr(program, "absint", None)
    if view is None:
        return None
    key = (ctx.path, qualname)
    rec = view.functions.get(key)
    if rec is None:
        return None
    desc = None
    for cid, call in enumerate(rec["calls"]):
        d = call["desc"]
        if d.get("line") == expr.lineno and d.get("col") == expr.col_offset:
            desc = cid
            break
    if desc is None:
        return None
    return view.concrete_meta(key, {"call": desc})


def prove_zero_d(
    ctx: LintContext, expr: ast.expr, program, qualname: str
) -> Optional[str]:
    """None when ``expr`` is provably a 0-d value; else the refusal reason.

    Two proof arms: (1) a full-array reduction with no ``axis=`` /
    ``keepdims=`` is 0-d whatever the operand's rank; (2) abstract
    metadata resolved by the absint layer with ``dims == []``.  Everything
    else — including a provably non-0-d meta — refuses."""
    meta = _absint_call_meta(ctx, program, qualname, expr)
    if meta is not None and meta.get("dims") is not None:
        if meta["dims"] == []:
            return None
        return (
            f"abstract metadata proves the value is {len(meta['dims'])}-d "
            f"(dims {meta['dims']}), not 0-d — host-fetching it would move "
            "the whole array"
        )
    if isinstance(expr, ast.Call):
        la = last_attr(expr)
        if la in SCALAR_REDUCTIONS:
            # function form `jnp.sum(x[, axis])` vs method form `x.sum([axis])`:
            # the operand is args[0] in the first, the receiver in the second
            dn = call_name(expr) or ""
            function_form = dn.split(".")[0] in ("jnp", "np", "numpy", "jax", "lax")
            positional_axis = len(expr.args) >= (2 if function_form else 1)
            bad_kw = None
            for kw in expr.keywords:
                if kw.arg == "axis":
                    if not (isinstance(kw.value, ast.Constant) and kw.value.value is None):
                        bad_kw = "axis"
                elif kw.arg == "keepdims":
                    if not (
                        isinstance(kw.value, ast.Constant) and kw.value.value is False
                    ):
                        bad_kw = "keepdims"
                elif kw.arg == "out":
                    bad_kw = "out"
            if bad_kw is not None:
                return (
                    f"`{la}` reduction carries `{bad_kw}=` — the result is not "
                    "provably 0-d"
                )
            if positional_axis:
                return (
                    f"`{la}` reduction has a positional axis argument — the "
                    "result is not provably 0-d"
                )
            return None
    return (
        "cannot prove the expression is 0-d (not a full-array reduction and "
        "no abstract metadata resolves it)"
    )


# ------------------------------------------------------------------ #
# HT101 — host syncs → Communication.host_fetch
# ------------------------------------------------------------------ #


@register_fixer
class HostSyncFixer(Fixer):
    """``float()``/``int()``/``bool()`` casts of device values and
    ``.item()`` syncs rewrite to the sanctioned ``Communication.host_fetch``
    route (deadline-guarded, fault-retried, SPMD-collective-correct) when
    0-d-ness and untraced-ness are proved."""

    code = "HT101"
    name = "host-sync-to-host-fetch"
    description = "route the proved-0-d host sync through Communication.host_fetch"

    def try_fix(self, finding, ctx, program):
        node = _find_call(ctx, finding.line, finding.col)
        if node is None:
            return [], "could not locate the offending call node"
        reason = prove_untraced(ctx, node, program)
        if reason is not None:
            return [], reason

        if finding.detail == "item":
            inner = node.func.value
            reason = prove_zero_d(ctx, inner, program, finding.qualname)
            if reason is not None:
                return [], reason
            inner_src = ast.get_source_segment(ctx.source, inner)
            if inner_src is None:
                return [], "could not extract the receiver's source segment"
            parent = ctx.parent(node)
            cast_parent = (
                isinstance(parent, ast.Call)
                and isinstance(parent.func, ast.Name)
                and parent.func.id in ("float", "int", "bool")
                and len(parent.args) == 1
                and parent.args[0] is node
            )
            s, e = node_span(ctx, node)
            if cast_parent:
                # int(X.item()) -> int(Communication.host_fetch(X)): the
                # cast stays, the sync is replaced by the sanctioned fetch
                replacement = f"Communication.host_fetch({inner_src})"
            else:
                # bare X.item() -> host_fetch(X).item(): .item() on the
                # fetched host array preserves the exact scalar semantics
                replacement = f"Communication.host_fetch({inner_src}).item()"
            edits = [Edit(ctx.path, s, e, replacement, note="HT101 item")]
        elif finding.detail in ("float-cast", "int-cast", "bool-cast"):
            arg = node.args[0]
            reason = prove_zero_d(ctx, arg, program, finding.qualname)
            if reason is not None:
                return [], reason
            arg_src = ast.get_source_segment(ctx.source, arg)
            if arg_src is None:
                return [], "could not extract the argument's source segment"
            s, e = node_span(ctx, arg)
            edits = [
                Edit(
                    ctx.path, s, e, f"Communication.host_fetch({arg_src})",
                    note=f"HT101 {finding.detail}",
                )
            ]
        elif finding.detail == "device_get":
            return [], (
                "`jax.device_get` accepts pytrees; `host_fetch` takes one "
                "array — the mechanical rewrite is not semantics-preserving, "
                "route by hand"
            )
        else:
            return [], (
                f"no mechanical route for `{finding.detail}` — materialize "
                "via numpy()/host_fetch by hand"
            )
        prefix = _relative_core_prefix(ctx.path)
        imp = ensure_import_edit(
            ctx,
            f"from {prefix}.communication import Communication",
            "Communication",
        )
        if imp is not None:
            edits.append(imp)
        return edits, None


# ------------------------------------------------------------------ #
# HT105 — raw entropy → core/random's sanctioned host_rng
# ------------------------------------------------------------------ #


@register_fixer
class EntropyRouteFixer(Fixer):
    """``np.random.default_rng(<literal seed>)`` reroutes through
    ``core/random.host_rng`` — same Generator, same stream, but the draw is
    owned by the module whose job is broadcast-uniform randomness.  Only a
    literal seed is provably rank-uniform; everything else refuses."""

    code = "HT105"
    name = "entropy-to-ht-random"
    description = "reroute literal-seeded np.random entropy through core/random.host_rng"

    def try_fix(self, finding, ctx, program):
        # a chained `np.random.default_rng(SEED).permutation(n)` puts the
        # OUTER call at the same (line, col) as the flagged inner one —
        # match by the finding's dotted name, not position alone
        node = None
        for cand in ctx.walk(ast.Call):
            if (
                cand.lineno == finding.line
                and cand.col_offset == finding.col
                and call_name(cand) == finding.detail
            ):
                node = cand
                break
        if node is None:
            return [], "could not locate the offending call node"
        if finding.detail not in ("np.random.default_rng", "numpy.random.default_rng"):
            return [], (
                f"no mechanical route for `{finding.detail}` — draw from the "
                "broadcast ht.random state (or derive the seed via "
                "core.random.derive_seed()) by hand"
            )
        if not node.args:
            return [], (
                "seedless `default_rng()` is true process entropy — no "
                "deterministic rank-uniform rewrite exists; seed it from the "
                "broadcast state (core.random.derive_seed()) by hand"
            )
        seed = node.args[0]
        if not (isinstance(seed, ast.Constant) and isinstance(seed.value, int)):
            return [], (
                "cannot prove the seed expression is rank-uniform (only a "
                "literal constant is provable) — route through "
                "core.random.host_rng by hand if the seed is broadcast"
            )
        s, e = node_span(ctx, node.func)
        prefix = _relative_core_prefix(ctx.path)
        edits = [Edit(ctx.path, s, e, "ht_random.host_rng", note="HT105 default_rng")]
        imp = ensure_import_edit(
            ctx, f"from {prefix} import random as ht_random", "random as ht_random"
        )
        if imp is not None:
            edits.append(imp)
        return edits, None


# ------------------------------------------------------------------ #
# HT107 — naked blocking waits → with comm.deadline(...)
# ------------------------------------------------------------------ #

_DEFAULT_DEADLINE_S = "60.0"


def _caller_arms_deadline(program, key) -> Optional[str]:
    """Qualname of a (transitive) caller that arms a deadline around a call
    path reaching ``key``; None when no enclosing scope provably arms one.

    A function is "deadlined" when any resolved call to it sits under a
    lexical ``with ...deadline(...)`` in its caller (the effect pass records
    ``under_dl`` per call site), or when one of its callers is itself
    deadlined — the contextvar flows down the whole chain."""
    if program is None:
        return None
    callers: Dict[tuple, List[Tuple[tuple, bool]]] = {}
    for ck, eff in program.effects.items():
        for cid, entry in enumerate(eff["calls"]):
            under_dl = bool(entry[2]) if len(entry) > 2 else False
            r = program.resolved[ck][cid]
            if r.kind == "resolved":
                callers.setdefault(r.target, []).append((ck, under_dl))
    seen = set()
    frontier = [key]
    while frontier:
        cur = frontier.pop()
        if cur in seen:
            continue
        seen.add(cur)
        for ck, under_dl in callers.get(cur, ()):
            if under_dl:
                return ck[1]
            frontier.append(ck)
    return None


@register_fixer
class DeadlineWrapFixer(Fixer):
    """Wrap the statement holding a naked blocking wait in
    ``with comm.deadline(...)`` — only when a Communication handle is
    lexically in scope and the call graph proves no enclosing scope already
    arms a deadline (an armed caller means wrapping would NEST and silently
    tighten the caller's budget)."""

    code = "HT107"
    name = "wrap-wait-in-deadline"
    description = "arm a comm.deadline scope around the proved-undeadlined blocking wait"

    def _comm_handle(
        self, ctx: LintContext, fn: ast.AST, before: ast.AST
    ) -> Optional[str]:
        args = fn.args
        names = {p.arg for p in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)}
        if "comm" in names:
            return "comm"
        # a local `comm = ...` counts only when it is bound BEFORE the wait
        # — wrapping a wait that precedes the assignment would emit an
        # UnboundLocalError the post-fix re-lint cannot see
        wait_pos = (before.lineno, before.col_offset)
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if (
                        isinstance(tgt, ast.Name)
                        and tgt.id == "comm"
                        and (node.lineno, node.col_offset) < wait_pos
                    ):
                        return "comm"
        # `self.comm` counts only when THIS function's own class touches it
        # — a different class in the same file having a comm attribute
        # proves nothing about this one
        cls = next(
            (a for a in ctx.ancestors(fn) if isinstance(a, ast.ClassDef)), None
        )
        if cls is not None:
            for node in ast.walk(cls):
                if (
                    isinstance(node, ast.Attribute)
                    and node.attr == "comm"
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                ):
                    return "self.comm"
        return None

    def try_fix(self, finding, ctx, program):
        node = _find_call(ctx, finding.line, finding.col)
        if node is None:
            return [], "could not locate the offending call node"
        fn = ctx.enclosing_function(node)
        if fn is None:
            return [], "module-level wait: no function scope to arm a deadline in"
        handle = self._comm_handle(ctx, fn, node)
        if handle is None:
            return [], (
                "no Communication handle (`comm`/`self.comm`) in scope — "
                "cannot arm a deadline here"
            )
        if program is None:
            return [], (
                "program facts unavailable (narrow --select run) — cannot "
                "prove no caller already arms a deadline"
            )
        armed_by = _caller_arms_deadline(program, (ctx.path, finding.qualname))
        if armed_by is not None:
            return [], (
                f"caller `{armed_by}` already arms a deadline around a call "
                "path to this function — wrapping would nest and silently "
                "tighten that budget"
            )
        # wrap the whole enclosing statement
        stmt: ast.AST = node
        for anc in [node] + ctx.ancestors(node):
            if isinstance(anc, ast.stmt):
                stmt = anc
                break
        first = ctx.lines[stmt.lineno - 1]
        indent = first[: len(first) - len(first.lstrip())]
        starts = _line_starts(ctx.source)
        s = starts[stmt.lineno - 1]
        end_line = stmt.end_lineno or stmt.lineno
        e = (
            starts[end_line] - 1  # up to but excluding the trailing newline
            if end_line < len(starts)
            else len(ctx.source)
        )
        body = "\n".join(
            "    " + ln if ln.strip() else ln
            for ln in ctx.source[s:e].split("\n")
        )
        replacement = f"{indent}with {handle}.deadline({_DEFAULT_DEADLINE_S}):\n{body}"
        return [Edit(ctx.path, s, e, replacement, note="HT107 deadline wrap")], None


# ------------------------------------------------------------------ #
# HT110 — stale suppressions delete themselves
# ------------------------------------------------------------------ #


@register_fixer
class StaleSuppressionFixer(Fixer):
    """Delete the stale code from a ``# heatlint: disable=...`` comment —
    the whole comment when nothing live remains.  The rule's staleness
    re-lint IS the safety proof, so this fixer never refuses a located
    finding."""

    code = "HT110"
    name = "delete-stale-suppression"
    description = "remove the suppression code (or whole comment) that suppresses nothing"

    _COMMENT = re.compile(r"#\s*heatlint:\s*disable=((?:[A-Za-z0-9_]+\s*,\s*)*[A-Za-z0-9_]+)")

    def try_fix(self, finding, ctx, program):
        line_text = ctx.lines[finding.line - 1]
        m = self._COMMENT.search(line_text)
        if m is None:
            return [], "could not locate the suppression comment"
        codes = [c.strip() for c in m.group(1).split(",") if c.strip()]
        # drop EVERY stale code of this line in one edit, not just this
        # finding's: two stale codes on one comment would otherwise plan
        # two overlapping single-code edits, and the overlap resolution
        # would refuse one forever.  Identical edits from the sibling
        # findings dedupe cleanly in the planner.
        from .rules import StaleSuppressionRule

        stale = {
            f.detail.upper()
            for f in StaleSuppressionRule().check(ctx)
            if f is not None and f.line == finding.line
        } or {finding.detail.upper()}
        live = [c for c in codes if c.upper() not in stale]
        starts = _line_starts(ctx.source)
        line_off = starts[finding.line - 1]
        if live:
            s = line_off + m.start(1)
            e = line_off + m.end(1)
            return [
                Edit(ctx.path, s, e, ",".join(live), note="HT110 drop stale code")
            ], None
        # nothing live: delete the whole comment (and the padding before it)
        s = line_off + m.start()
        e = line_off + len(line_text)  # comments run to end of line
        while s > line_off and line_text[s - line_off - 1] in " \t":
            s -= 1
        return [Edit(ctx.path, s, e, "", note="HT110 delete comment")], None


# ------------------------------------------------------------------ #
# planning + execution
# ------------------------------------------------------------------ #


@dataclass
class FixOutcome:
    applied: List[dict] = field(default_factory=list)  # fingerprint, rule, ...
    refused: List[dict] = field(default_factory=list)
    diffs: Dict[str, str] = field(default_factory=dict)
    new_sources: Dict[str, str] = field(default_factory=dict)
    attempts: List[FixAttempt] = field(default_factory=list)

    def fixed_fingerprints(self) -> List[str]:
        return [a["fingerprint"] for a in self.applied]


def plan_fixes(
    findings: Sequence[Finding],
    contexts: Dict[str, LintContext],
    program,
) -> List[FixAttempt]:
    """One :class:`FixAttempt` per error finding whose rule has a fixer.
    Overlapping edits are resolved deterministically: document order wins,
    the loser is downgraded to a refusal (re-running --fix picks it up once
    the first fix landed)."""
    attempts: List[FixAttempt] = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule, f.detail)):
        if f.severity != "error":
            continue
        fixer = _FIXERS.get(f.rule)
        if fixer is None:
            continue
        ctx = contexts.get(f.path)
        if ctx is None:
            attempts.append(
                FixAttempt(f, fixer.name, refusal="no parsed context for this path")
            )
            continue
        edits, reason = fixer.try_fix(f, ctx, program)
        attempts.append(FixAttempt(f, fixer.name, edits=edits, refusal=reason))
    # overlap resolution per path (imports dedupe by identity first)
    taken: Dict[str, List[Tuple[int, int]]] = {}
    seen_edits: set = set()
    for att in attempts:
        if att.refusal is not None or not att.edits:
            continue
        kept: List[Edit] = []
        clash = False
        for e in att.edits:
            ident = (e.path, e.start, e.end, e.replacement)
            if ident in seen_edits:
                continue  # identical edit (shared import insertion)
            spans = taken.setdefault(e.path, [])
            if any(
                not (e.end <= s or e.start >= t) and not (e.start == e.end == s == t)
                for s, t in spans
            ):
                clash = True
                break
            kept.append(e)
        if clash:
            att.edits = []
            att.refusal = (
                "overlaps an earlier fix on the same span — re-run --fix "
                "after it lands"
            )
            continue
        for e in kept:
            seen_edits.add((e.path, e.start, e.end, e.replacement))
            taken[e.path].append((e.start, e.end))
        att.edits = kept
    return attempts


def _relint_file_rules(path: str, source: str) -> List[Finding]:
    ctx = LintContext(path, source)
    disabled = disabled_rules_for(ctx.path)
    out: List[Finding] = []
    for rule in all_rules():
        if rule.program_level or rule.code in disabled:
            continue
        out.extend(f for f in rule.check(ctx) if f is not None)
    return out


def execute_fixes(
    attempts: Sequence[FixAttempt],
    contexts: Dict[str, LintContext],
    write: bool = True,
) -> FixOutcome:
    """Apply planned fixes with the engine's two-part contract asserted
    BEFORE anything touches disk:

    1. post-fix re-lint — every fixed fingerprint is gone from its file;
    2. idempotence — re-planning on the fixed sources yields zero edits.

    Raises :class:`FixError` on either violation."""
    outcome = FixOutcome(attempts=list(attempts))
    by_path: Dict[str, List[Edit]] = {}
    for att in attempts:
        f = att.finding
        rec = {
            "fingerprint": f.fingerprint,
            "rule": f.rule,
            "path": f.path,
            "line": f.line,
            "qualname": f.qualname,
            "fixer": att.fixer,
        }
        if att.refusal is not None:
            outcome.refused.append(dict(rec, reason=att.refusal))
            continue
        if not att.edits:
            continue
        outcome.applied.append(rec)
        for e in att.edits:
            by_path.setdefault(e.path, []).append(e)

    relint_contexts: Dict[str, LintContext] = {}
    for path, edits in sorted(by_path.items()):
        src = contexts[path].source
        new_src = apply_edits(src, edits)
        outcome.new_sources[path] = new_src
        outcome.diffs[path] = "".join(
            difflib.unified_diff(
                src.splitlines(keepends=True),
                new_src.splitlines(keepends=True),
                fromfile=f"a/{path}",
                tofile=f"b/{path}",
            )
        )
        # contract 1: each fixed fingerprint's finding COUNT drops by the
        # number of fixes applied to it.  Fingerprints are a multiset (two
        # same-detail findings in one def are real), so a refused sibling
        # legitimately still reporting the shared fingerprint must not
        # convict the applied fix — and an applied fix that did not reduce
        # the count is a genuine contract violation.
        try:
            remaining = _relint_file_rules(path, new_src)
        except SyntaxError as exc:  # pragma: no cover - engine bug guard
            raise FixError(f"fix broke the syntax of {path}: {exc}") from exc
        pre_counts: Dict[str, int] = {}
        for f in _relint_file_rules(path, src):
            pre_counts[f.fingerprint] = pre_counts.get(f.fingerprint, 0) + 1
        post_counts: Dict[str, int] = {}
        for f in remaining:
            post_counts[f.fingerprint] = post_counts.get(f.fingerprint, 0) + 1
        applied_counts: Dict[str, int] = {}
        for rec in outcome.applied:
            if rec["path"] == path:
                applied_counts[rec["fingerprint"]] = (
                    applied_counts.get(rec["fingerprint"], 0) + 1
                )
        still = sorted(
            fp
            for fp, n in applied_counts.items()
            if post_counts.get(fp, 0) > pre_counts.get(fp, 0) - n
        )
        if still:
            raise FixError(
                f"post-fix re-lint of {path} still reports fixed fingerprint(s): "
                f"{still} — fixer contract violated, nothing written"
            )
        relint_contexts[path] = LintContext(path, new_src)

    # contract 2: fix ∘ fix = fix — plan again on the fixed sources.  The
    # second-pass Program is built over the FULL context set with the
    # fixed sources substituted in: a program narrowed to just the fixed
    # files would lose cross-file facts (e.g. the caller that arms a
    # deadline), turn pass-1 refusals into pass-2 plans, and fail the
    # idempotence assertion spuriously.
    if relint_contexts:
        second_findings: List[Finding] = []
        for path, ctx2 in relint_contexts.items():
            second_findings.extend(_relint_file_rules(path, ctx2.source))
        second_contexts = dict(contexts)
        second_contexts.update(relint_contexts)
        program2 = None
        try:
            from . import summaries as _summaries

            program2 = _summaries.build_program(second_contexts, cache_path=None)
        except Exception:
            program2 = None  # idempotence still checked with file facts only
        second = plan_fixes(second_findings, second_contexts, program2)
        regressions = [a for a in second if a.edits]
        if regressions:
            names = [
                f"{a.finding.path}:{a.finding.line} {a.finding.rule}" for a in regressions
            ]
            raise FixError(
                "fix engine is not idempotent: a second --fix pass would still "
                f"edit {names} — nothing written"
            )

    if write:
        for path, new_src in outcome.new_sources.items():
            with open(path, "w", encoding="utf-8") as fh:
                fh.write(new_src)
    return outcome


# ------------------------------------------------------------------ #
# SARIF `fixes` objects
# ------------------------------------------------------------------ #


def sarif_fixes(
    attempts: Iterable[FixAttempt],
    contexts: Dict[str, LintContext],
    norm=None,
) -> Dict[str, dict]:
    """fingerprint → SARIF ``fix`` object for every planned (non-refused)
    fix, so code-scanning surfaces the concrete patch next to the finding.
    ``norm`` optionally normalizes artifact URIs (the CLI's baseline-
    relative path scheme)."""
    norm = norm or (lambda p: p)
    out: Dict[str, dict] = {}
    for att in attempts:
        if att.refusal is not None or not att.edits:
            continue
        changes: Dict[str, List[dict]] = {}
        for e in att.edits:
            ctx = contexts.get(e.path)
            if ctx is None:
                continue
            sl, sc = offset_to_linecol(ctx.source, e.start)
            el, ec = offset_to_linecol(ctx.source, e.end)
            changes.setdefault(e.path, []).append(
                {
                    "deletedRegion": {
                        "startLine": sl,
                        "startColumn": sc,
                        "endLine": el,
                        "endColumn": ec,
                    },
                    "insertedContent": {"text": e.replacement},
                }
            )
        out[att.finding.fingerprint] = {
            "description": {"text": f"{att.fixer}: {att.finding.rule} autofix"},
            "artifactChanges": [
                {
                    "artifactLocation": {"uri": norm(p), "uriBaseId": "%SRCROOT%"},
                    "replacements": reps,
                }
                for p, reps in sorted(changes.items())
            ],
        }
    return out
