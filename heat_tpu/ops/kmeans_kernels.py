"""Pallas TPU kernels for the KMeans E-step.

``fused_assign`` (labels + min-distance): each grid step loads a (TILE, d)
row block plus the full (k, d) centers into VMEM, runs the distance GEMM
on the MXU, and reduces in VMEM — the (n, k) matrix never exists in HBM.

``fused_em_stats`` (round-4): the whole Lloyd iteration body — assignment
AND the (k, d)/(k,) statistics accumulation in ONE grid sweep with
constant-index accumulator blocks; labels never reach HBM.  Inputs stay in
their storage dtype (bf16 at the 1e8×32 BASELINE scale) and are cast
per-tile in VMEM.

Both are WIRED into ``cluster.KMeans`` via ``assign_kernel='pallas'``
(fit: fused E+M on both the sharded and global paths; predict: fused
assign), with the jnp path as ``'jnp'`` and the measured-faster default as
``'auto'``.

**Measured verdict (v5e, round 4)**: XLA's fusion of the jnp form wins
this workload at every tested geometry — 18.6 vs 16.8 it/s at 2^23×32
k=64 f32 (the kernel's best, TILE=4096), 0.25×/0.48× at d=128/256 —
so ``'auto'`` stays ``'jnp'`` and the kernel remains an opt-in, A-B'd by
``bench.py`` every round.  Two hardware reasons, kept here for the next
tuner: (1) a ``d < 128`` input forces Pallas to relayout X into the
128-lane tiled layout — a ``128/d``× padded HBM copy per call (at
1e8×32 bf16 that copy alone is 25.6 GiB — OOM; the `_relayout_copy_bytes`
gate below falls back to jnp before that happens), while XLA's fused path
keeps X in its native packed layout; (2) at the E-step's shapes the MXU
contraction is shallow (k=64 output, d-deep) and XLA's pipelining of the
two fused GEMM passes beats the kernel's sequential grid.  Contrast
``flash_attention``, where the same Pallas treatment WINS ~4.5× — the
difference is attention's (S, S) intermediate actually disappears,
whereas KMeans' (n, k) intermediate was already fused away by XLA.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except ImportError:  # pragma: no cover
    _HAS_PALLAS = False

__all__ = ["fused_assign", "fused_em_stats"]

_TILE = 4096  # 4096 measured 4x faster than 1024 on v5e (grid-step amortization)


def _relayout_copy_bytes(n_rows: int, d: int, itemsize: int) -> int:
    """HBM bytes of the relayout copy Pallas forces for a non-lane-aligned
    trailing dim: d % 128 != 0 pads every row to the 128-lane tile, so a
    FULL padded copy of X materializes (the silent 4x blowup that OOMs
    1e8x32 bf16).  Lane-aligned d needs no copy — returns 0 so an explicit
    ``assign_kernel='pallas'`` opt-in is honored at any size there."""
    if d % 128 == 0:
        return 0
    lanes = -(-d // 128) * 128
    return n_rows * lanes * itemsize


def _assign_kernel(x_ref, c_ref, cc_ref, lab_ref, d2_ref):
    # cast per-TILE in VMEM: casting X up front would materialize a full
    # f32 copy in HBM (2x the bf16 working set — OOM at 1e8x32)
    x = x_ref[:].astype(jnp.float32)  # (TILE, d)
    c = c_ref[:]  # (k, d)
    cc = cc_ref[:]  # (1, k) — precomputed ||c||²
    xx = jnp.sum(x * x, axis=1, keepdims=True)  # (TILE, 1)
    dots = jax.lax.dot_general(
        x, c, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # (TILE, k) on the MXU
    d2 = xx + cc - 2.0 * dots
    d2 = jnp.maximum(d2, 0.0)
    lab_ref[:] = jnp.argmin(d2, axis=1, keepdims=True).astype(jnp.int32)
    d2_ref[:] = jnp.min(d2, axis=1, keepdims=True)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _fused_assign_impl(x, centers, interpret: bool):
    n, d = x.shape
    k = centers.shape[0]
    tile = min(_TILE, n)
    grid = (pl.cdiv(n, tile),)
    cc = jnp.sum(centers * centers, axis=1)[None, :]  # (1, k)
    labels, d2 = pl.pallas_call(
        _assign_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile, d), lambda i: (i, 0)),
            pl.BlockSpec((k, d), lambda i: (0, 0)),
            pl.BlockSpec((1, k), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 1), jnp.int32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x, centers.astype(jnp.float32), cc.astype(jnp.float32))
    return labels[:, 0], d2[:, 0]


def _em_stats_kernel(n_ref, x_ref, c_ref, cc_ref, sums_ref, counts_ref):
    """Fused E+M grid step: assign one (TILE, d) row block and fold it
    straight into the (k, d)/(1, k) statistics accumulators.

    The accumulators' BlockSpecs are CONSTANT across the grid, so the TPU's
    sequential grid revisits the same VMEM block — step 0 initializes,
    later steps add (the `pl.when` idiom).  Labels never reach HBM and the
    (n, k) distance matrix never exists anywhere: one X read per iteration
    is the entire HBM traffic.
    """
    i = pl.program_id(0)
    x = x_ref[:].astype(jnp.float32)  # (TILE, d) — cast per-tile (see above)
    c = c_ref[:]  # (k, d)
    cc = cc_ref[:]  # (1, k)
    tile = x.shape[0]
    k = c.shape[0]
    xx = jnp.sum(x * x, axis=1, keepdims=True)
    dots = jax.lax.dot_general(
        x, c, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    d2 = jnp.maximum(xx + cc - 2.0 * dots, 0.0)  # (TILE, k)
    lab = jnp.argmin(d2, axis=1)  # (TILE,)
    # rows at global index ≥ n are pad: contribute nothing.  The iota MUST
    # be ≥2-D: Mosaic rejects 1-D iota (the compile error only surfaces on
    # real TPU hardware — interpret mode accepts it silently)
    gidx = i * tile + jax.lax.broadcasted_iota(jnp.int32, (tile, 1), 0)
    valid = gidx < n_ref[0]  # (TILE, 1)
    # zero the pad/out-of-bounds rows of x too: a ragged final block reads
    # undefined tile memory, and 0·garbage in the GEMM is only safe when
    # the garbage cannot be inf/NaN — masking x makes it actually zero
    x = jnp.where(valid, x, 0.0)
    onehot = ((lab[:, None] == jax.lax.broadcasted_iota(jnp.int32, (tile, k), 1))
              & valid).astype(jnp.float32)
    bs = jax.lax.dot_general(  # (k, TILE) @ (TILE, d) on the MXU
        onehot, x, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    bc = jnp.sum(onehot, axis=0, keepdims=True)  # (1, k)

    @pl.when(i == 0)
    def _():
        sums_ref[:] = bs
        counts_ref[:] = bc

    @pl.when(i > 0)
    def _():
        sums_ref[:] = sums_ref[:] + bs
        counts_ref[:] = counts_ref[:] + bc


@functools.partial(jax.jit, static_argnames=("interpret",))
def _fused_em_stats_impl(x, centers, n, interpret: bool):
    npad, d = x.shape
    k = centers.shape[0]
    tile = min(_TILE, npad)
    grid = (pl.cdiv(npad, tile),)
    cc = jnp.sum(centers * centers, axis=1)[None, :]
    sums, counts = pl.pallas_call(
        _em_stats_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM if _HAS_PALLAS and not interpret else None),
            pl.BlockSpec((tile, d), lambda i: (i, 0)),
            pl.BlockSpec((k, d), lambda i: (0, 0)),
            pl.BlockSpec((1, k), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((k, d), lambda i: (0, 0)),
            pl.BlockSpec((1, k), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((k, d), jnp.float32),
            jax.ShapeDtypeStruct((1, k), jnp.float32),
        ],
        interpret=interpret,
    )(
        jnp.asarray([n], jnp.int32),
        x,
        centers.astype(jnp.float32),
        cc.astype(jnp.float32),
    )
    return sums, counts[0]


def fused_em_stats(x, centers, n=None):
    """(sums (k, d), counts (k,)) of one fused assign-and-accumulate pass.

    The Lloyd-iteration E+M kernel (round-4): assignment and per-cluster
    statistics in ONE grid sweep — labels never reach HBM.  Rows at index
    ≥ ``n`` (pad) contribute nothing.  Pallas on TPU, interpreter on small
    CPU shards, jnp fallback otherwise.
    """
    rows = x.shape[0]
    n = rows if n is None else n
    if not _HAS_PALLAS:
        return _jnp_em_stats(x, centers, n)
    platform = jax.devices()[0].platform
    if platform not in ("tpu", "cpu") or (platform == "cpu" and rows > 16384):
        return _jnp_em_stats(x, centers, n)
    # conservative VMEM budget at trace time: the accumulator + centers +
    # one tile must fit comfortably; oversize problems take the jnp path
    # HERE because a Mosaic failure under an OUTER jit surfaces at that
    # jit's compile, where the try below cannot catch it
    k, d = centers.shape
    tile = min(_TILE, rows)
    vmem = 4 * (2 * k * d + tile * d + 2 * tile * k)
    if vmem > 8 * 2**20:
        return _jnp_em_stats(x, centers, n)
    # the narrow-d relayout copy (see module docstring) must also fit HBM
    if _relayout_copy_bytes(rows, d, x.dtype.itemsize) > 6 * 2**30:
        return _jnp_em_stats(x, centers, n)
    try:
        return _fused_em_stats_impl(x, centers, n, interpret=(platform == "cpu"))
    except Exception:
        return _jnp_em_stats(x, centers, n)


def _jnp_em_stats(x, centers, n):
    lab, _ = _jnp_assign(x, centers)
    k = centers.shape[0]
    valid = jnp.arange(x.shape[0]) < n
    onehot = ((lab[:, None] == jnp.arange(k)[None, :]) & valid[:, None]).astype(jnp.float32)
    return onehot.T @ x.astype(jnp.float32), jnp.sum(onehot, axis=0)


def _jnp_assign(x, centers):
    xx = jnp.sum(x * x, axis=1, keepdims=True)
    cc = jnp.sum(centers * centers, axis=1)[None, :]
    d2 = xx + cc - 2.0 * (x @ centers.T)
    d2 = jnp.maximum(d2, 0.0)
    return jnp.argmin(d2, axis=1), jnp.min(d2, axis=1)


def fused_assign(x, centers):
    """(labels, min_d2) of each row of ``x`` against ``centers``.

    Pallas-fused on TPU; interpreter mode on CPU shards; jnp fallback when
    Pallas is unavailable or the VMEM estimate says the blocks won't fit.
    Ragged row counts ride the clipped final grid block — no padded copy
    of X is ever made (a concatenate would double peak HBM at the 1e8×32
    scale this kernel exists for); garbage values in the clipped tail are
    discarded with the sliced outputs.
    """
    if not _HAS_PALLAS:
        return _jnp_assign(x, centers)
    n = x.shape[0]
    platform = jax.devices()[0].platform
    if platform not in ("tpu", "cpu"):
        return _jnp_assign(x, centers)
    if platform == "cpu" and n > 16384:
        # interpreter mode is slow; only use it at test scale
        return _jnp_assign(x, centers)
    k, d = centers.shape
    tile = min(_TILE, n)
    if 4 * (k * d + tile * d + 2 * tile * k) > 8 * 2**20:
        return _jnp_assign(x, centers)  # VMEM-gated (see fused_em_stats)
    if _relayout_copy_bytes(n, d, x.dtype.itemsize) > 6 * 2**30:
        return _jnp_assign(x, centers)  # narrow-d relayout copy must fit HBM
    try:
        labels, d2 = _fused_assign_impl(x, centers, interpret=(platform == "cpu"))
    except Exception:
        return _jnp_assign(x, centers)
    return labels[:n], d2[:n]
