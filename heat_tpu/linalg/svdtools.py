"""Distributed SVD (reference: ``heat/core/linalg/svdtools.py``).

- ``svd``: exact SVD; tall-skinny row-split inputs go through TSQR (QR then
  SVD of the small R — the communication-avoiding TS-SVD of the reference).
- ``hsvd_rank`` / ``hsvd_rtol``: **hierarchical approximate SVD** — local
  truncated SVDs of column blocks merged pairwise up a binary tree, exactly
  the reference's algorithm; each merge is a small on-device QR/SVD, the
  block extraction is sharded slicing (implicit collectives).
- ``rsvd``: randomized SVD (Halko-Martinsson-Tropp sketch).
"""

from __future__ import annotations

import collections
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..core import types
from ..core.communication import Communication
from ..core.dndarray import DNDarray
from ..core.sanitation import sanitize_in
from .qr import tsqr

__all__ = ["hsvd", "hsvd_rank", "hsvd_rtol", "rsvd", "svd"]

SVDTuple = collections.namedtuple("SVD", "U, S, V")


def _wrap(jarr, split, proto):
    if split is not None and split >= jarr.ndim:
        split = None
    jarr = proto.comm.shard(jarr, split)
    return DNDarray(
        jarr, tuple(jarr.shape), types.canonical_heat_type(jarr.dtype), split, proto.device, proto.comm, True
    )


def svd(a: DNDarray, full_matrices: bool = False, compute_uv: bool = True, qr_procs_to_merge: int = 2):
    """Exact SVD. Row-split tall matrices: TSQR → SVD(R) (TS-SVD)."""
    sanitize_in(a)
    if a.ndim != 2:
        raise ValueError("svd requires a 2-D array")
    if full_matrices:
        raise NotImplementedError("full_matrices=True is not supported (reference parity)")
    m, n = a.shape
    if a.split == 0 and m >= n:
        q, r = tsqr(a)
        ur, s, vt = jnp.linalg.svd(r._jarray, full_matrices=False)
        if not compute_uv:
            return _wrap(s, None, a)
        u = q._jarray @ ur  # (m,n) split-0 GEMM against replicated (n,n)
        return SVDTuple(_wrap(u, 0, a), _wrap(s, None, a), _wrap(vt.T, None, a))
    if a.split == 1 and n > m:
        # wide: transpose reduces to the tall case
        ut, s, vt = svd(a.T.resplit(0), compute_uv=True)
        if not compute_uv:
            return _wrap(s._jarray, None, a)
        return SVDTuple(vt, s, ut)
    u, s, vt = jnp.linalg.svd(a._jarray, full_matrices=False)
    if not compute_uv:
        return _wrap(s, None, a)
    return SVDTuple(_wrap(u, a.split, a), _wrap(s, None, a), _wrap(vt.T, None, a))


def _truncate(u, s, rank: Optional[int] = None, rtol: Optional[float] = None, safetyshift: int = 0):
    if rank is not None:
        k = min(rank + safetyshift, s.shape[0])
        return u[:, :k], s[:k]
    # rtol truncation: discard tail energy below rtol * ||s||
    err2 = jnp.cumsum((s**2)[::-1])[::-1]
    thresh = (rtol**2) * jnp.sum(s**2)
    # the truncation rank becomes a SHAPE, so a concrete integer is
    # unavoidable — but the raw `.item()` that used to sit here was a naked
    # blocking device→host read in the middle of the merge tree (heatlint
    # HT101's first real catch).  Route the one scalar through the sanctioned
    # materialization point instead: host_fetch is collective-correct under
    # multi-process meshes (every rank attends, so all ranks agree on the
    # rank/shape), fault-retried, and fetches the already-reduced 0-d count —
    # an 8-byte transfer instead of an unaccounted ad-hoc sync
    keep = int(Communication.host_fetch(jnp.sum(err2 > thresh)))
    keep = max(keep, 1)
    keep = min(keep + safetyshift, s.shape[0])
    return u[:, :keep], s[:keep]


def hsvd(
    a: DNDarray,
    maxrank: Optional[int] = None,
    maxmergedim: Optional[int] = None,
    rtol: Optional[float] = None,
    safetyshift: int = 0,
    no_of_merges: Optional[int] = None,
    compute_sv: bool = False,
    silent: bool = True,
):
    """Hierarchical SVD core: local SVDs of column blocks, pairwise tree merge.

    Mirrors the reference's binary process tree; each level halves the number
    of factors.  Runs on the sharded global array — block slicing and the
    final small GEMMs produce the collectives.
    """
    sanitize_in(a)
    if a.ndim != 2:
        raise ValueError("hsvd requires a 2-D array")
    m, n = a.shape
    comm = a.comm
    nblocks = min(comm.size, n) if comm.size > 1 else min(4, n)
    ja = a._jarray

    # leaf factors: truncated local SVD of each column block
    factors = []
    bounds = np.linspace(0, n, nblocks + 1, dtype=np.int64)
    for i in range(nblocks):
        lo, hi = int(bounds[i]), int(bounds[i + 1])
        if hi <= lo:
            continue
        blk = ja[:, lo:hi]
        u, s, _ = jnp.linalg.svd(blk, full_matrices=False)
        u, s = _truncate(u, s, rank=maxrank, rtol=rtol, safetyshift=safetyshift)
        factors.append(u * s)

    # binary tree merge
    while len(factors) > 1:
        merged = []
        for i in range(0, len(factors) - 1, 2):
            cat = jnp.concatenate([factors[i], factors[i + 1]], axis=1)
            u, s, _ = jnp.linalg.svd(cat, full_matrices=False)
            u, s = _truncate(u, s, rank=maxrank, rtol=rtol, safetyshift=safetyshift)
            merged.append(u * s)
        if len(factors) % 2 == 1:
            merged.append(factors[-1])
        factors = merged

    us = factors[0]
    u, s, _ = jnp.linalg.svd(us, full_matrices=False)
    u, s = _truncate(u, s, rank=maxrank, rtol=rtol, safetyshift=0)
    U = _wrap(u, 0 if a.split == 0 else None, a)
    if not compute_sv:
        return U, _wrap(s, None, a)
    # V = A^T U diag(1/s)
    vt = (u.T @ ja) / s[:, None]
    V = _wrap(vt.T, 0 if a.split == 1 else None, a)
    # relative error estimate
    err = jnp.linalg.norm(ja - (u * s) @ vt) / jnp.maximum(jnp.linalg.norm(ja), 1e-30)
    return U, _wrap(s, None, a), V, float(err)


def hsvd_rank(
    a: DNDarray,
    maxrank: int,
    compute_sv: bool = False,
    maxmergedim: Optional[int] = None,
    safetyshift: int = 5,
    silent: bool = True,
):
    """Hierarchical SVD truncated to ``maxrank`` (reference API)."""
    res = hsvd(
        a, maxrank=maxrank, maxmergedim=maxmergedim, safetyshift=safetyshift,
        compute_sv=compute_sv, silent=silent,
    )
    if compute_sv:
        U, s, V, err = res
        k = min(maxrank, s.shape[0])
        return U[:, :k], s[:k], V[:, :k], err
    U, s = res
    k = min(maxrank, s.shape[0])
    return U[:, :k]


def hsvd_rtol(
    a: DNDarray,
    rtol: float,
    compute_sv: bool = False,
    maxrank: Optional[int] = None,
    maxmergedim: Optional[int] = None,
    safetyshift: int = 5,
    no_of_merges: Optional[int] = None,
    silent: bool = True,
):
    """Hierarchical SVD truncated to relative tolerance ``rtol`` (reference API)."""
    res = hsvd(
        a, maxrank=maxrank, rtol=rtol, maxmergedim=maxmergedim, safetyshift=safetyshift,
        compute_sv=compute_sv, silent=silent,
    )
    if compute_sv:
        return res
    U, s = res
    return U


def rsvd(
    a: DNDarray,
    rank: int,
    n_oversamples: int = 10,
    power_iter: int = 0,
    qr_procs_to_merge: int = 2,
):
    """Randomized SVD (sketch + TSQR + small SVD) — reference ``rsvd``."""
    sanitize_in(a)
    from ..core import random as ht_random

    m, n = a.shape
    k = min(rank + n_oversamples, min(m, n))
    omega = ht_random.randn(n, k, dtype=a.dtype if types.heat_type_is_inexact(a.dtype) else types.float32)
    y = a._jarray @ omega._jarray
    for _ in range(power_iter):
        y = a._jarray @ (a._jarray.T @ y)
    q, _ = jnp.linalg.qr(y, mode="reduced")
    b = q.T @ a._jarray  # (k, n)
    ub, s, vt = jnp.linalg.svd(b, full_matrices=False)
    u = q @ ub
    r = min(rank, s.shape[0])
    return (
        _wrap(u[:, :r], 0 if a.split == 0 else None, a),
        _wrap(s[:r], None, a),
        _wrap(vt[:r].T, None, a),
    )
