"""Memory-bounded streaming resplit (ISSUE 6): planner, executor, wiring.

Three layers under test:

- the PURE planner (``plan_resplit``): tile-axis choice, budget→tile-extent
  math, exact partitioning (tail tile clipped, never overlapping), and every
  monolithic-fallback reason;
- the streaming executor through the public surfaces
  (``Communication.resplit(memory_budget=)`` / ``resplit_tiled`` /
  ``manipulations.resplit`` / ``DNDarray.resplit_``): bit-exact equality
  with the unchunked path over all transitions × budgets, canonical output
  sharding, program-cache steady state (second identical resplit compiles
  NOTHING), and telemetry — ``comm.resplit.bytes`` totals IDENTICAL between
  chunked and monolithic (telescoped per-tile accounting), ``.calls`` = K,
  ``.tiles`` = K, ``.peak_tile_bytes`` = the largest tile;
- the robustness hooks: per-tile ``comm.collective`` fault site under an
  armed ``comm.deadline`` (a hung tile trips ``CollectiveTimeoutError``),
  the donate-kwarg ``TypeError`` fallback counted under
  ``comm.resplit.donate_fallbacks`` with a one-time warning, and the budget
  default plumbing (``set_redistribution_budget`` / env parsing).
"""

import warnings

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.core import redistribution as rd
from heat_tpu.core.communication import Communication
from heat_tpu.utils import profiler


@pytest.fixture(autouse=True)
def _no_process_budget():
    """Tests control the budget explicitly; never inherit another test's."""
    prev = rd.set_redistribution_budget(None)
    yield
    rd.set_redistribution_budget(prev)


def _counters():
    return {
        k: v for k, v in profiler.counters().items() if k.startswith("comm.resplit")
    }


# ---------------------------------------------------------------------- #
# planner (pure)
# ---------------------------------------------------------------------- #
class TestPlanner:
    def test_basic_tiling(self):
        p = rd.plan_resplit((8, 5, 8), 4, 0, 2, 8, 512)
        assert p.tile_axis == 1  # the only non-split axis
        assert p.n_tiles > 1 and p.reason == "tiled"
        assert p.max_tile_bytes <= 512

    def test_tiles_partition_exactly(self):
        p = rd.plan_resplit((8, 7, 8), 4, 0, 2, 8, 500)
        spans = [p.tile_bounds(i) for i in range(p.n_tiles)]
        # contiguous, non-overlapping, covering [0, n)
        assert spans[0][0] == 0
        for (s0, l0), (s1, _) in zip(spans, spans[1:]):
            assert s0 + l0 == s1
        s, length = spans[-1]
        assert s + length == 7
        assert sum(length for _, length in spans) == 7
        # byte totals partition too (tail tile clipped, not padded-counted)
        assert sum(p.tile_nbytes(length) for _, length in spans) == p.total_bytes

    def test_largest_free_axis_wins(self):
        p = rd.plan_resplit((8, 3, 9, 8), 4, 0, 3, 8, 1024)
        assert p.tile_axis == 2

    def test_budget_below_one_slice_floors(self):
        # tiling axis 1 (extent 4), one slice = 1024 B >> the 1 B budget:
        # best effort floors at one slice per tile
        p = rd.plan_resplit((8, 4, 8), 4, 0, 2, 8, 1)
        assert p.tile_axis == 1 and p.tile_extent == 1
        assert p.n_tiles == 4
        assert p.reason == "tiled-floor-one-slice"

    @pytest.mark.parametrize(
        "gshape,src,dst,budget,reason",
        [
            ((8, 5, 8), 0, 2, None, "no-budget"),
            ((16,), 0, None, 16, "too-few-dims"),
            ((), None, None, 16, "too-few-dims"),
            ((8, 5, 8), 0, 2, 10**9, "fits-in-budget"),
            ((9, 5, 8), 0, 2, 64, "ragged-src"),
            ((8, 5, 9), 0, 2, 64, "ragged-dst"),
            ((8, 8), 0, 1, 64, "no-free-axis"),
            ((8, 1, 8), 0, 2, 64, "no-free-axis"),  # free axis too short
        ],
    )
    def test_monolithic_reasons(self, gshape, src, dst, budget, reason):
        p = rd.plan_resplit(gshape, 4, src, dst, 8, budget)
        assert p.n_tiles == 1 and p.tile_axis is None
        assert p.reason == reason

    def test_negative_split_normalized(self):
        p = rd.plan_resplit((8, 5, 8), 4, 0, -1, 8, 512)
        assert p.dst_split == 2 and p.tile_axis == 1

    def test_parse_budget(self):
        assert rd.parse_budget(None) is None
        assert rd.parse_budget(0) is None
        assert rd.parse_budget(-3) is None
        assert rd.parse_budget("") is None
        assert rd.parse_budget(4096) == 4096
        assert rd.parse_budget("512") == 512
        assert rd.parse_budget("4K") == 4096
        assert rd.parse_budget("64M") == 64 * 2**20
        assert rd.parse_budget("2GB") == 2 * 2**30
        # fractional budgets scale BEFORE truncation ("0.5G" must not
        # int()-truncate to 0 and silently mean unbounded)
        assert rd.parse_budget("0.5G") == 512 * 2**20
        assert rd.parse_budget("1.5M") == 1536 * 2**10

    def test_default_budget_roundtrip(self):
        prev = rd.set_redistribution_budget("1M")
        try:
            assert rd.get_redistribution_budget() == 2**20
            assert ht.get_redistribution_budget() == 2**20  # flat re-export
        finally:
            rd.set_redistribution_budget(prev)


# ---------------------------------------------------------------------- #
# round-trip correctness over transitions × budgets
# ---------------------------------------------------------------------- #
def _fresh(shape, split):
    n = int(np.prod(shape))
    return ht.reshape(ht.arange(n, dtype=ht.float32, split=0), shape).resplit(split)


class TestRoundTrip:
    SHAPE = (16, 6, 8)

    @pytest.mark.mp
    @pytest.mark.parametrize("src,dst", [(0, 2), (2, 0), (0, None), (None, 0), (1, 2)])
    @pytest.mark.parametrize("budget", [256, 4096, "64M"])
    def test_bit_exact_vs_monolithic(self, src, dst, budget):
        x = _fresh(self.SHAPE, src)
        ref = x.resplit(dst)  # unchunked oracle
        got = x.resplit(dst, memory_budget=budget)
        assert got.split == dst
        np.testing.assert_array_equal(got.numpy(), ref.numpy())
        comm = x.comm
        assert got._jarray.sharding == comm.sharding(len(self.SHAPE), dst)

    def test_one_slice_budget(self):
        # the finest possible streaming: one tiling-axis slice per tile
        x = _fresh((8, 6, 8), 0)
        got = x.resplit(2, memory_budget=1)
        np.testing.assert_array_equal(got.numpy(), x.resplit(2).numpy())

    def test_ragged_tiling_axis_tail_tile(self):
        x = _fresh((8, 7, 8), 0)  # 7 on the tiling axis: K=4 with tail 1
        comm = x.comm
        plan = rd.plan_resplit((8, 7, 8), 4, 0, 2, comm.size, 600)
        assert plan.n_tiles == 4
        assert plan.tile_bounds(plan.n_tiles - 1)[1] < plan.tile_extent
        got = x.resplit(2, memory_budget=600)
        np.testing.assert_array_equal(got.numpy(), x.resplit(2).numpy())

    def test_inplace_budgeted(self):
        x = _fresh(self.SHAPE, 0)
        want = x.numpy()
        x.resplit_(2, memory_budget=512)
        assert x.split == 2
        np.testing.assert_array_equal(x.numpy(), want)

    def test_inplace_budgeted_to_none(self):
        x = _fresh(self.SHAPE, 0)
        want = x.numpy()
        x.resplit_(None, memory_budget=512)
        assert x.split is None
        np.testing.assert_array_equal(x.numpy(), want)

    def test_process_default_budget_applies(self):
        x = _fresh(self.SHAPE, 0)
        ref = x.resplit(2)
        rd.set_redistribution_budget(512)
        profiler.reset_counters()
        got = x.resplit(2)
        assert _counters()["comm.resplit.tiles"] > 1  # the default kicked in
        np.testing.assert_array_equal(got.numpy(), ref.numpy())

    def test_explicit_zero_budget_forces_monolithic(self):
        rd.set_redistribution_budget(512)
        x = _fresh(self.SHAPE, 0)
        profiler.reset_counters()
        got = x.resplit(2, memory_budget=0)  # overrides the process default
        assert _counters()["comm.resplit.tiles"] == 1
        np.testing.assert_array_equal(got.numpy(), x.resplit(2).numpy())

    def test_edge_cases_fall_back(self):
        # 2-d k->j (no free axis), 1-d, ragged: all monolithic, all exact
        m = _fresh((8, 8), 0)
        np.testing.assert_array_equal(
            m.resplit(1, memory_budget=64).numpy(), m.resplit(1).numpy()
        )
        v = ht.arange(16, dtype=ht.float32, split=0)
        np.testing.assert_array_equal(
            v.resplit(None, memory_budget=8).numpy(), np.arange(16, dtype=np.float32)
        )
        r = ht.reshape(ht.arange(9 * 5 * 8, dtype=ht.float32), (9, 5, 8))
        got = r.resplit(0, memory_budget=64)  # ragged dst -> monolithic
        np.testing.assert_array_equal(got.numpy(), r.numpy())

    def test_resplit_tiled_explicit_entry(self):
        comm = ht.communication.get_comm()
        x = _fresh(self.SHAPE, 0)
        out = comm.resplit_tiled(x._jarray, 2, memory_budget=512)
        assert out.sharding == comm.sharding(3, 2)
        np.testing.assert_array_equal(
            np.asarray(Communication.host_fetch(out)), x.resplit(2).numpy()
        )
        # untileable input degenerates to the monolithic path, same result
        m = _fresh((8, 8), 0)
        out2 = comm.resplit_tiled(m._jarray, 1, memory_budget=64)
        np.testing.assert_array_equal(
            np.asarray(Communication.host_fetch(out2)), m.resplit(1).numpy()
        )


# ---------------------------------------------------------------------- #
# telemetry: exact byte totals, tiles, peak tile, calls
# ---------------------------------------------------------------------- #
class TestAccounting:
    SHAPE = (16, 6, 8)

    def test_bytes_identical_chunked_vs_monolithic(self):
        # including an odd budget whose tiles do NOT divide the total evenly:
        # the telescoped per-tile accounting must still sum to the byte
        for budget in (256, 500, 1000, 4096):
            x = _fresh(self.SHAPE, 0)
            profiler.reset_counters()
            _ = x.resplit(2, memory_budget=0)
            mono = _counters()
            profiler.reset_counters()
            _ = x.resplit(2, memory_budget=budget)
            tiled = _counters()
            assert tiled["comm.resplit.bytes"] == mono["comm.resplit.bytes"], budget
            assert mono["comm.resplit.calls"] == 1
            assert mono["comm.resplit.tiles"] == 1

    def test_tiles_calls_and_peak(self):
        comm = ht.communication.get_comm()
        x = _fresh(self.SHAPE, 0)
        plan = rd.make_plan(comm, x._jarray, 2, 512)
        assert plan is not None and plan.n_tiles > 1
        profiler.reset_counters()
        _ = x.resplit(2, memory_budget=512)
        c = _counters()
        assert c["comm.resplit.calls"] == plan.n_tiles  # one staged transfer per tile
        assert c["comm.resplit.tiles"] == plan.n_tiles
        assert c["comm.resplit.peak_tile_bytes"] == plan.max_tile_bytes
        assert c["comm.resplit.peak_tile_bytes"] <= 512

    def test_noop_resplit_still_uncounted(self):
        x = _fresh(self.SHAPE, 0)
        profiler.reset_counters()
        _ = x.resplit(0, memory_budget=512)  # already there: no bytes, no tiles
        assert _counters().get("comm.resplit.calls", 0) == 0
        assert _counters().get("comm.resplit.tiles", 0) == 0

    def test_counter_max_semantics(self):
        profiler.reset_counters()
        profiler.counter_max("t.peak", 5)
        profiler.counter_max("t.peak", 3)
        profiler.counter_max("t.peak", 9)
        assert profiler.counters()["t.peak"] == 9


# ---------------------------------------------------------------------- #
# program cache: steady-state chunked resplit recompiles nothing
# ---------------------------------------------------------------------- #
class TestProgramCache:
    def test_zero_recompiles_second_run(self):
        shape = (16, 6, 8)
        x = _fresh(shape, 0)
        _ = x.resplit(2, memory_budget=512)  # warm: builds the per-tile programs
        y = _fresh(shape, 0)  # fresh array, same signature
        profiler.reset_cache_stats()
        got = y.resplit(2, memory_budget=512)
        stats = profiler.cache_stats()
        assert stats["misses"] == 0, stats
        assert stats["hits"] > 0
        np.testing.assert_array_equal(got.numpy(), y.resplit(2).numpy())

    def test_flip_flop_steady_state(self):
        x = _fresh((16, 6, 8), 0)
        x.resplit_(2, memory_budget=512)
        x.resplit_(0, memory_budget=512)  # warm both directions
        profiler.reset_cache_stats()
        x.resplit_(2, memory_budget=512)
        x.resplit_(0, memory_budget=512)
        assert profiler.cache_stats()["misses"] == 0


# ---------------------------------------------------------------------- #
# robustness hooks
# ---------------------------------------------------------------------- #
class TestRobustness:
    def test_hung_tile_trips_deadline(self):
        from heat_tpu.utils import faults, health

        comm = ht.communication.get_comm()
        x = _fresh((16, 6, 8), 0)
        profiler.reset_counters()
        with faults.inject("comm.collective", hang=1):
            with pytest.raises(health.CollectiveTimeoutError):
                with comm.deadline(0.3):
                    x.resplit(2, memory_budget=512)
        # a mid-plan abort leaves the plan-shape counters CONSISTENT with
        # the per-tile traffic counters (tiles advance per tile, not at the
        # end of the loop): the hung tile staged nothing, so both are equal
        c = _counters()
        assert c.get("comm.resplit.tiles", 0) == c.get("comm.resplit.calls", 0)

    def test_blown_deadline_refuses_next_tile(self):
        import time

        from heat_tpu.utils import health

        comm = ht.communication.get_comm()
        x = _fresh((16, 6, 8), 0)
        with pytest.raises(health.CollectiveTimeoutError):
            with comm.deadline(0.05):
                time.sleep(0.1)  # blow the budget before the first tile
                x.resplit(2, memory_budget=512)

    def test_donate_fallback_counted_and_warned_once(self, monkeypatch):
        import jax

        from heat_tpu.core import communication as comm_mod

        real = jax.device_put

        def no_donate(x, sharding=None, **kw):
            if kw.pop("donate", False):
                raise TypeError("device_put() got an unexpected keyword 'donate'")
            return real(x, sharding, **kw)

        monkeypatch.setattr(comm_mod.jax, "device_put", no_donate)
        monkeypatch.setattr(Communication, "_DONATE_FALLBACK_WARNED", False)
        profiler.reset_counters()
        x = _fresh((8, 8), 0)
        want = x.resplit(1).numpy()
        with pytest.warns(UserWarning, match="donate"):
            x.resplit_(1)  # monolithic donate path hits the TypeError
        np.testing.assert_array_equal(x.numpy(), want)
        assert profiler.counters()["comm.resplit.donate_fallbacks"] == 1
        # second occurrence: counted again, warned never again
        y = _fresh((8, 8), 0)
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            y.resplit_(1)
        assert profiler.counters()["comm.resplit.donate_fallbacks"] == 2

    def test_no_warnings_on_tiled_path(self):
        # the expected "donated buffers were not usable" compile noise of the
        # per-tile programs must be filtered at the source
        x = _fresh((16, 6, 8), 0)
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            got = x.resplit(2, memory_budget=512)
        np.testing.assert_array_equal(got.numpy(), x.resplit(2).numpy())

    def test_sanitizer_checks_tiled_output(self):
        from heat_tpu.core import sanitation

        was = sanitation.checks_enabled()
        sanitation.enable_checks()
        try:
            x = _fresh((16, 6, 8), 0)
            got = x.resplit(2, memory_budget=512)  # _RESPLIT_CHECK runs on out
            assert got.split == 2
            got2 = sanitation.check(got, "test")
            assert got2 is got
        finally:
            if not was:
                sanitation.disable_checks()

    def test_tracer_falls_back(self):
        import jax

        comm = ht.communication.get_comm()
        rd.set_redistribution_budget(64)

        @jax.jit
        def f(j):
            return comm.resplit(j, 1)  # tracer: planner must decline

        x = _fresh((8, 8), 0)
        out = f(x._jarray)
        np.testing.assert_array_equal(np.asarray(out), x.numpy())
