"""Streaming datasets for larger-than-memory files (reference:
``heat/utils/data/partial_dataset.py``).

``PartialH5Dataset`` streams HDF5 in chunks with a background prefetch
thread — per-shard byte-range reads replace the reference's per-rank
parallel-HDF5 loads.
"""

from __future__ import annotations

import queue
import threading
from typing import Optional

import numpy as np

from ...core import factories
from ...core import axisspec

__all__ = ["PartialH5Dataset", "PartialH5DataLoaderIter"]


class PartialH5Dataset:
    """Iterate an HDF5 dataset in batches without loading it whole.

    Parameters mirror the reference: ``file``, ``dataset_names``,
    ``batch_size``, ``initial_load`` (rows resident at once), ``use_gpu``
    kept for parity (placement is the mesh's concern here).
    """

    def __init__(self, file: str, comm=None, dataset_names="data", initial_load: int = 7000,
                 load_length: Optional[int] = None, use_gpu: bool = True, np_buffer: bool = True,
                 np_buffer_dataset_names="data", transforms=None):
        try:
            import h5py
        except ImportError as e:
            raise RuntimeError("PartialH5Dataset requires h5py") from e
        self.file = file
        self.names = [dataset_names] if isinstance(dataset_names, str) else list(dataset_names)
        self.load_size = load_length or initial_load
        self.transforms = transforms
        with h5py.File(file, "r") as f:
            self.length = f[self.names[0]].shape[0]
            self.shapes = {n: f[n].shape for n in self.names}

    def __len__(self) -> int:
        return self.length

    def _reader(self, q: "queue.Queue", chunk: int, stop: "threading.Event"):
        import h5py

        try:
            with h5py.File(self.file, "r") as f:
                for lo in range(0, self.length, chunk):
                    if stop.is_set():
                        return
                    hi = min(lo + chunk, self.length)
                    block = {n: np.asarray(f[n][lo:hi]) for n in self.names}
                    while not stop.is_set():
                        try:
                            q.put(block, timeout=0.1)
                            break
                        except queue.Full:
                            continue
        finally:
            while True:
                try:
                    q.put(None, timeout=0.1)
                    return
                except queue.Full:
                    if stop.is_set():
                        return

    def __iter__(self):
        """Yield dicts of DNDarrays (one chunk at a time, prefetched).

        Early iterator abandonment signals the reader thread to stop, so the
        HDF5 handle is released (no leaked threads across partial epochs).
        """
        q: "queue.Queue" = queue.Queue(maxsize=2)
        stop = threading.Event()
        t = threading.Thread(target=self._reader, args=(q, self.load_size, stop), daemon=True)
        t.start()
        try:
            while True:
                block = q.get()
                if block is None:
                    break
                out = {}
                for n, arr in block.items():
                    if self.transforms is not None:
                        arr = self.transforms(arr)
                    out[n] = factories.array(arr, split=axisspec.named(0))
                yield out if len(out) > 1 else next(iter(out.values()))
        finally:
            stop.set()
            while True:  # drain so a blocked put wakes up
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            t.join(timeout=2.0)


PartialH5DataLoaderIter = PartialH5Dataset  # reference-name alias
