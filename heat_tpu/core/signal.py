"""Signal processing (reference: ``heat/core/signal.py``).

1-D ``convolve`` with full/same/valid modes.  Distributed signals take the
reference's halo path (``DNDarray.get_halo`` + local ``torch.conv1d``,
SURVEY §5.7): each shard exchanges ``m-1`` boundary elements with its ring
neighbors (``parallel.halo.halo_exchange`` → ``lax.ppermute``) and runs a
LOCAL valid-mode XLA conv on ``[halo_prev | block | halo_next]`` — no
global gather.  A distributed kernel is gathered first (kernels are small;
same as the reference's ``v`` broadcast).  Replicated signals use one
global XLA convolution.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import types
from ._cache import comm_cached
from .dndarray import DNDarray

__all__ = ["convolve", "convolve2d"]

# diagnostics: tests assert the halo path actually executes
_HALO_CONV_RUNS = 0


def _conv1d_full(a: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    """Full correlation-free convolution via XLA conv (MXU-eligible)."""
    n, m = a.shape[0], v.shape[0]
    # conv_general_dilated computes correlation; flip the kernel for convolution
    lhs = a.reshape(1, 1, n)
    rhs = v[::-1].reshape(1, 1, m)
    out = jax.lax.conv_general_dilated(
        lhs, rhs, window_strides=(1,), padding=[(m - 1, m - 1)]
    )
    return out.reshape(-1)


def _conv1d_valid(x: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    lhs = x.reshape(1, 1, -1)
    rhs = v[::-1].reshape(1, 1, -1)
    return jax.lax.conv_general_dilated(
        lhs, rhs, window_strides=(1,), padding=[(0, 0)]
    ).reshape(-1)


def _halo_body(a: DNDarray, jv: jnp.ndarray, offset: int) -> jnp.ndarray:
    """Per-shard rows ``G[lo+offset : lo+offset+c]`` of the signal's FULL
    convolution, via halo exchange — the reference's convolve mechanism.

    Each shard extends its block with ``m-1`` neighbor elements on both sides
    (zeros at the global edges = conv zero-padding; the PHYSICAL padded array
    is used, whose trailing pad zeros are exactly conv semantics) and runs a
    local valid conv: ``valid(ext)[i] == G[lo + i]``.  Returns the padded
    physical result aligned with the signal's shards.
    """
    global _HALO_CONV_RUNS
    comm = a.comm
    # pads are DEAD data, not guaranteed zero (elementwise fast paths leave
    # f(0) garbage there) — mask to the conv zero-padding this path relies on
    phys = a._masked(0).astype(jv.dtype)
    body = _halo_conv_program(comm, int(jv.shape[0]), offset)(phys, jv)
    _HALO_CONV_RUNS += 1
    return body


@comm_cached
def _halo_conv_program(comm, m: int, offset: int):
    """Jitted + comm-cached halo-convolve pipeline (the TSQR recompile
    lesson applied to the op surface: convolve is called eagerly, so a
    fresh shard_map per call would recompile every time).  The kernel rides
    as a replicated argument, not a closure constant, so one program serves
    every kernel of length ``m``."""
    from ..parallel.halo import halo_exchange

    h = m - 1

    def shard_fn(blk, jv):
        prev, nxt = halo_exchange(blk, h, comm.axis, comm.size, 0)
        ext = jnp.concatenate([prev, blk, nxt], axis=0)
        val = _conv1d_valid(ext, jv)  # c + m - 1 rows: G[lo : lo + c + m - 1]
        return jax.lax.dynamic_slice_in_dim(val, offset, blk.shape[0])

    return jax.jit(comm.shard_map(
        shard_fn, in_splits=((1, 0), (1, None)), out_splits=(1, 0)
    ))


def convolve(a: DNDarray, v: DNDarray, mode: str = "full", stride: int = 1) -> DNDarray:
    """Discrete 1-D convolution of ``a`` with kernel ``v`` (numpy modes)."""
    from . import factories

    if not isinstance(a, DNDarray):
        a = factories.array(a)
    if not isinstance(v, DNDarray):
        v = factories.array(v)
    if a.ndim != 1 or v.ndim != 1:
        raise ValueError("convolve requires 1-D inputs")
    if mode not in ("full", "same", "valid"):
        raise ValueError(f"Unsupported mode {mode!r}")
    if stride != 1:
        raise NotImplementedError("stride != 1 not supported (reference parity)")
    n, m = a.shape[0], v.shape[0]
    signal = a  # output metadata follows the SIGNAL even if operands swap
    if n < m:
        a, v = v, a
        n, m = m, n
    dt = types.promote_types(a.dtype, v.dtype)
    if types.heat_type_is_exact(dt):
        work_dt = types.float32
    else:
        work_dt = dt
    # a distributed kernel is gathered — kernels are small and every shard
    # needs all of it (reference: Bcast of v)
    jv = (v.resplit(None) if v.split is not None else v)._jarray.astype(work_dt.jax_dtype())

    from . import _complexsafe

    comm = a.comm
    c_blk = comm.padded_extent(n) // comm.size if comm.size else n
    is_hosted_complex = jnp.issubdtype(
        work_dt.jax_dtype(), jnp.complexfloating
    ) and not _complexsafe.native_complex_supported()
    use_halo = (
        a.split == 0
        and comm.is_distributed()
        and m - 1 <= c_blk  # halo must fit in one neighbor block
        and not is_hosted_complex  # host-resident complex cannot ride shard_map
    )

    if use_halo:
        split = signal.split
        if mode == "same":
            body = _halo_body(a, jv, (m - 1) // 2)  # G[lo+(m-1)//2 : …+c] per shard
            res_d = DNDarray(
                body, (n,), types.canonical_heat_type(body.dtype), 0,
                signal.device, comm, True,
            )
        else:
            body = _halo_body(a, jv, 0)  # G[lo : lo+c] per shard → G[0:n]
            body_d = DNDarray(
                body, (n,), types.canonical_heat_type(body.dtype), 0,
                signal.device, comm, True,
            )
            if mode == "valid":
                res_d = body_d[m - 1 : n]
            else:  # full: append the global tail G[n : n+m-1] (last m-1 rows)
                if m > 1:
                    t = a[n - (m - 1) :]._jarray.astype(jv.dtype)
                    tail = _conv1d_full(t, jv)[m - 1 : 2 * (m - 1)]
                    res = jnp.concatenate([body_d._jarray, tail])
                else:
                    res = body_d._jarray
                res_d = DNDarray(
                    res, tuple(res.shape), types.canonical_heat_type(res.dtype), 0,
                    signal.device, comm, True,
                )
        if types.heat_type_is_exact(dt):
            res_d = DNDarray(
                jnp.round(res_d._parray).astype(dt.jax_dtype()), res_d.shape,
                dt, res_d.split, res_d.device, res_d.comm, True,
            )
        if res_d.split != split:
            res_d.resplit_(split)  # result split follows the SIGNAL operand
        return res_d

    ja = a._jarray.astype(work_dt.jax_dtype())
    full = _conv1d_full(ja, jv)
    if mode == "full":
        res = full
    elif mode == "same":
        lo = (m - 1) // 2
        res = full[lo : lo + n]
    else:  # valid
        res = full[m - 1 : m - 1 + n - m + 1]
    if types.heat_type_is_exact(dt):
        res = jnp.round(res).astype(dt.jax_dtype())
    split = signal.split
    res = signal.comm.shard(res, split)
    return DNDarray(
        res, tuple(res.shape), types.canonical_heat_type(res.dtype), split,
        signal.device, signal.comm, True,
    )


def convolve2d(a: DNDarray, v: DNDarray, mode: str = "full") -> DNDarray:
    """2-D convolution (extension beyond the reference's 1-D surface)."""
    from . import factories

    if not isinstance(a, DNDarray):
        a = factories.array(a)
    if not isinstance(v, DNDarray):
        v = factories.array(v)
    if a.ndim != 2 or v.ndim != 2:
        raise ValueError("convolve2d requires 2-D inputs")
    n0, n1 = a.shape
    m0, m1 = v.shape
    lhs = a._jarray.astype(jnp.float32).reshape(1, 1, n0, n1)
    rhs = v._jarray.astype(jnp.float32)[::-1, ::-1].reshape(1, 1, m0, m1)
    if mode == "full":
        pad = [(m0 - 1, m0 - 1), (m1 - 1, m1 - 1)]
    elif mode == "same":
        pad = [((m0 - 1) // 2, m0 // 2), ((m1 - 1) // 2, m1 // 2)]
    elif mode == "valid":
        pad = [(0, 0), (0, 0)]
    else:
        raise ValueError(f"Unsupported mode {mode!r}")
    out = jax.lax.conv_general_dilated(lhs, rhs, window_strides=(1, 1), padding=pad)
    res = out.reshape(out.shape[2], out.shape[3])
    res = a.comm.shard(res, a.split)
    return DNDarray(
        res, tuple(res.shape), types.canonical_heat_type(res.dtype), a.split, a.device, a.comm, True
    )


def correlate(a: DNDarray, v: DNDarray, mode: str = "valid") -> DNDarray:
    """Cross-correlation of 1-D sequences (numpy ``correlate`` semantics:
    ``a ⋆ v = a * conj(reverse(v))``) — rides the distributed ``convolve``
    halo path for split signals."""
    from . import factories, manipulations

    if not isinstance(v, DNDarray):
        v = factories.array(v)
    flipped = manipulations.flip(v, 0)
    if jnp.issubdtype(flipped.dtype.jax_dtype(), jnp.complexfloating):
        from .complex_math import conjugate

        flipped = conjugate(flipped)
    return convolve(a, flipped, mode=mode)


__all__ += ["correlate"]
