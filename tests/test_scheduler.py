"""Elastic multi-tenant job scheduler (ISSUE 10 tentpole).

Four enforcement layers under test:

- **admission control**: the bounded queue sheds with a structured
  :class:`JobRejected` (``queue_full`` / ``tenant_cap`` /
  ``deadline_infeasible``) *synchronously* — never a hang — and the
  ``sched.*`` counters reconcile (every offered job is accepted or shed;
  every accepted job ends done, failed or pending);
- **per-job deadlines + retries**: an injected ``sched.dispatch`` hang
  trips the armed deadline as THAT job's failure while the queue keeps
  serving; transient faults retry with ``sched.<kind>.retries`` /
  ``.exhausted`` counters;
- **crash-durable journal**: submit→dispatch→done/failed record streams
  replay exactly-once (torn final record tolerated, DONE jobs never
  re-executed, newer-schema journals fail loud);
- **graceful degradation**: ``drain()`` fails the remainder in priority
  order with ``world_unavailable`` and the report names every outcome.

Plus the jax-side serving executors (``parallel.serving``): all four job
kinds, shape-keyed micro-batching through the PR 1 program cache, and the
standalone-load contract (``scheduler.py`` must load with jax import
BLOCKED, like ``supervisor.py`` — the supervising launcher replays
journals without a backend).
"""

import importlib.util
import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from heat_tpu.parallel import scheduler as S  # noqa: E402
from heat_tpu.utils import faults, health, profiler  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_counters():
    S.reset_counters()
    yield
    S.reset_counters()


def _stub_executor(log=None, results=None, fail=None):
    """Executor double: records batches, optionally raises."""
    calls = log if log is not None else []

    def execute(jobs):
        calls.append([j.job_id for j in jobs])
        if fail is not None:
            raise fail
        if results is not None:
            return [results(j) for j in jobs]
        return [{"digest": float(len(j.job_id))} for j in jobs]

    execute.calls = calls
    return execute


# ---------------------------------------------------------------------- #
# admission control
# ---------------------------------------------------------------------- #
class TestAdmission:
    def test_queue_full_sheds_immediately_not_blocks(self):
        """Acceptance: a full queue answers with JobRejected{queue_full}
        NOW — submit never blocks waiting for capacity."""
        s = S.Scheduler(_stub_executor(), max_queue=3)
        for i in range(3):
            s.submit(S.Job(f"j{i}", "matmul"))
        t0 = time.monotonic()
        with pytest.raises(S.JobRejected) as ei:
            s.submit(S.Job("overflow", "matmul"))
        assert time.monotonic() - t0 < 1.0, "shedding must be synchronous"
        assert ei.value.reason == S.QUEUE_FULL
        assert ei.value.job_id == "overflow"
        assert "queue_full" in str(ei.value)
        assert S.counters()["sched.shed.queue_full"] == 1
        # the shed job is still named in the report (every outcome named)
        assert s.report()["jobs"]["overflow"]["state"] == S.SHED

    def test_tenant_cap_protects_other_tenants(self):
        s = S.Scheduler(_stub_executor(), max_queue=10, tenant_cap=2)
        s.submit(S.Job("a1", "matmul", tenant="acme"))
        s.submit(S.Job("a2", "matmul", tenant="acme"))
        with pytest.raises(S.JobRejected) as ei:
            s.submit(S.Job("a3", "matmul", tenant="acme"))
        assert ei.value.reason == S.TENANT_CAP
        # a DIFFERENT tenant still gets in: no cross-tenant starvation
        s.submit(S.Job("b1", "matmul", tenant="globex"))
        assert s.pending() == 3
        # capacity frees as the capped tenant's jobs finish
        s.run()
        s.submit(S.Job("a4", "matmul", tenant="acme"))
        assert s.pending() == 1

    def test_deadline_infeasible_rejected_at_admission(self):
        s = S.Scheduler(
            _stub_executor(), min_exec_estimate={"kmeans": 1.0}
        )
        with pytest.raises(S.JobRejected) as ei:
            s.submit(S.Job("k", "kmeans", deadline_s=0.5))
        assert ei.value.reason == S.DEADLINE_INFEASIBLE
        # at/below zero is infeasible for ANY kind, estimate or not
        with pytest.raises(S.JobRejected) as ei2:
            s.submit(S.Job("m", "matmul", deadline_s=0.0))
        assert ei2.value.reason == S.DEADLINE_INFEASIBLE
        # a feasible deadline and an unbounded job are both admitted
        s.submit(S.Job("k2", "kmeans", deadline_s=5.0))
        s.submit(S.Job("k3", "kmeans"))
        assert s.pending() == 2

    def test_duplicate_live_id_raises(self):
        s = S.Scheduler(_stub_executor())
        s.submit(S.Job("dup", "matmul"))
        with pytest.raises(ValueError):
            s.submit(S.Job("dup", "matmul"))


# ---------------------------------------------------------------------- #
# dispatch: priority, micro-batching, results
# ---------------------------------------------------------------------- #
class TestDispatch:
    def test_priority_order_with_fifo_tiebreak(self):
        log = []
        s = S.Scheduler(_stub_executor(log), max_batch=1)
        s.submit(S.Job("low1", "matmul", priority=0))
        s.submit(S.Job("hi1", "matmul", priority=5))
        s.submit(S.Job("low2", "matmul", priority=0))
        s.submit(S.Job("hi2", "matmul", priority=5))
        s.run()
        assert log == [["hi1"], ["hi2"], ["low1"], ["low2"]]

    def test_micro_batching_shares_one_dispatch(self):
        log = []
        s = S.Scheduler(_stub_executor(log), max_batch=4)
        for i in range(4):
            s.submit(S.Job(f"j{i}", "matmul", payload={"n": 16}))
        s.run()
        assert log == [["j0", "j1", "j2", "j3"]]
        c = S.counters()
        assert c["sched.dispatches"] == 1
        assert c["sched.batched"] == 3  # 3 jobs rode a shared dispatch

    def test_incompatible_payloads_do_not_batch(self):
        log = []
        s = S.Scheduler(_stub_executor(log), max_batch=4)
        s.submit(S.Job("a", "matmul", payload={"n": 16}))
        s.submit(S.Job("b", "matmul", payload={"n": 32}))
        s.submit(S.Job("c", "solve", payload={"n": 16}))
        s.run()
        assert len(log) == 3

    def test_non_jsonable_payload_fallback_keys_on_values_too(self):
        """Review finding: the non-JSON fallback signature must include
        payload VALUES — a keys-only signature would batch jobs whose
        payloads differ, handing an executor incompatible work."""
        blob = object()  # forces the non-JSON fallback
        a = S.Job("a", "nn_forward", payload={"features": 8, "x": blob})
        b = S.Job("b", "nn_forward", payload={"features": 16, "x": blob})
        c = S.Job("c", "nn_forward", payload={"features": 8, "x": blob})
        assert a.effective_batch_key() != b.effective_batch_key()
        assert a.effective_batch_key() == c.effective_batch_key()

    def test_custom_batch_key_overrides_grouping(self):
        log = []
        key = lambda j: j.kind  # noqa: E731 — data-blind compatibility
        s = S.Scheduler(_stub_executor(log), max_batch=8, batch_key=key)
        s.submit(S.Job("a", "matmul", payload={"seed": 1}))
        s.submit(S.Job("b", "matmul", payload={"seed": 2}))
        s.run()
        assert log == [["a", "b"]]

    def test_results_and_outcomes_delivered(self):
        s = S.Scheduler(_stub_executor(results=lambda j: {"id": j.job_id}))
        s.submit(S.Job("r1", "matmul", tenant="acme"))
        s.run()
        assert s.result("r1") == {"id": "r1"}
        out = s.outcome("r1")
        assert out["state"] == S.DONE and out["tenant"] == "acme"
        assert out["queue_wait_s"] is not None and out["exec_s"] is not None

    def test_non_transient_executor_error_fails_batch_named(self):
        s = S.Scheduler(_stub_executor(fail=ValueError("boom")))
        s.submit(S.Job("e1", "matmul"))
        s.submit(S.Job("e2", "matmul"))
        s.run()
        for jid in ("e1", "e2"):
            o = s.outcome(jid)
            assert o["state"] == S.FAILED
            assert o["reason"] == "error:ValueError"
        # a programming error is NOT retried (only transient faults are)
        assert "sched.matmul.retries" not in S.counters()


# ---------------------------------------------------------------------- #
# per-job deadlines + retries (fault sites sched.dispatch / journal.write)
# ---------------------------------------------------------------------- #
class TestDeadlineAndRetry:
    def test_transient_faults_retried_with_counters(self):
        s = S.Scheduler(_stub_executor(), retry_base_delay=0.001)
        s.submit(S.Job("t1", "matmul", retry_budget=3))
        base = profiler.counters().get("retry.sched.matmul", 0)
        with faults.inject("sched.dispatch", fail=2):
            s.run()
        assert s.outcome("t1")["state"] == S.DONE
        assert S.counters()["sched.matmul.retries"] == 2
        assert "sched.matmul.exhausted" not in S.counters()
        # faults.call_with_retries' own counters rode along
        assert profiler.counters()["retry.sched.matmul"] == base + 2

    def test_retry_budget_exhaustion_named_and_counted(self):
        s = S.Scheduler(_stub_executor(), retry_base_delay=0.001)
        s.submit(S.Job("x1", "solve", retry_budget=2))
        with faults.inject("sched.dispatch", fail=-1):
            s.run()
        o = s.outcome("x1")
        assert o["state"] == S.FAILED and o["reason"] == S.RETRIES_EXHAUSTED
        c = S.counters()
        assert c["sched.solve.exhausted"] == 1
        assert c["sched.solve.retries"] == 2  # the budget was really spent

    def test_hang_trips_as_jobs_failure_not_wedged_queue(self):
        """Acceptance (satellite 1): an injected dispatch HANG under the
        job's deadline surfaces as THAT job's deadline_expired failure —
        the queue behind it keeps serving."""
        log = []
        s = S.Scheduler(_stub_executor(log), retry_base_delay=0.001)
        s.submit(S.Job("wedged", "matmul", priority=9, deadline_s=0.5,
                       retry_budget=1))
        s.submit(S.Job("healthy", "solve", priority=0))
        base = profiler.counters().get("health.deadline.trips", 0)
        t0 = time.monotonic()
        with faults.inject("sched.dispatch", hang=1):
            s.run()
        took = time.monotonic() - t0
        assert took < 10.0, f"queue wedged for {took:.1f}s"
        o = s.outcome("wedged")
        assert o["state"] == S.FAILED and o["reason"] == S.DEADLINE_EXPIRED
        # the victim's deadline trip is the health counter's business too
        assert profiler.counters()["health.deadline.trips"] >= base + 1
        # and the job BEHIND the wedge completed normally
        assert s.outcome("healthy")["state"] == S.DONE
        assert ["healthy"] in log

    def test_expired_in_queue_fails_without_dispatch(self):
        clock = {"t": 100.0}
        log = []
        s = S.Scheduler(_stub_executor(log), clock=lambda: clock["t"])
        s.submit(S.Job("late", "matmul", deadline_s=5.0))
        clock["t"] += 10.0  # the deadline passed while queued
        s.run()
        o = s.outcome("late")
        assert o["state"] == S.FAILED and o["reason"] == S.DEADLINE_EXPIRED
        assert log == []  # never dispatched with a blown budget

    def test_expired_job_does_not_drag_live_batchmates(self):
        clock = {"t": 0.0}
        log = []
        s = S.Scheduler(_stub_executor(log), clock=lambda: clock["t"],
                        max_batch=4)
        s.submit(S.Job("dead", "matmul", deadline_s=1.0))
        clock["t"] += 2.0
        s.submit(S.Job("live", "matmul"))  # same batch key
        s.run()
        assert s.outcome("dead")["reason"] == S.DEADLINE_EXPIRED
        assert s.outcome("live")["state"] == S.DONE
        assert log == [["live"]]

    def test_world_broken_requeues_batch_instead_of_failing(self, tmp_path):
        """Review follow-up: a transport death under a dispatch (executor
        raises WorldBroken — serving converts XLA runtime errors) is NOT a
        job outcome.  The batch goes back on the queue, the journal keeps
        it DISPATCHED (so a restarted world's replay requeues it), and the
        error propagates to the process owner."""
        path = str(tmp_path / "j.jsonl")
        s = S.Scheduler(
            _stub_executor(fail=S.WorldBroken("peer died")), journal=path
        )
        s.submit(S.Job("w1", "matmul"))
        s.submit(S.Job("w2", "matmul"))
        with pytest.raises(S.WorldBroken):
            s.run()
        # nothing terminally failed; both jobs are pending again
        assert s.pending() == 2
        assert S.counters().get("sched.failed", 0) == 0
        assert S.counters()["sched.world_broken"] == 1
        rep = S.replay_journal(path)
        assert rep["jobs"]["w1"]["state"] == S.DISPATCHED  # replay requeues
        # a fresh scheduler (the restarted world) recovers and serves them
        s2 = S.Scheduler(_stub_executor(), journal=None)
        assert s2.recover(path) == 2
        s2.run()
        assert s2.outcome("w1")["state"] == S.DONE
        assert s2.outcome("w2")["state"] == S.DONE

    def test_mid_retry_expiry_sheds_alone_batchmates_survive(self):
        """Review finding: a job whose budget expires BETWEEN retry
        attempts fails alone — the surviving batch-mate's retry window is
        its OWN budget, not the expired job's."""
        log = []
        s = S.Scheduler(_stub_executor(log), max_batch=4,
                        retry_base_delay=0.2)
        s.submit(S.Job("short", "matmul", deadline_s=0.05, retry_budget=2))
        s.submit(S.Job("long", "matmul", deadline_s=100.0, retry_budget=2))
        with faults.inject("sched.dispatch", fail=1):
            s.run()  # first attempt fails; the ~0.2s backoff outlives "short"
        assert s.outcome("short")["reason"] == S.DEADLINE_EXPIRED
        assert s.outcome("long")["state"] == S.DONE
        assert log[-1] == ["long"]  # the retry ran WITHOUT the expired job

    def test_recover_attempts_counted_from_pre_restart_epochs_only(
        self, tmp_path
    ):
        """Review finding: like the deadline anchor, restored attempt
        counts must ignore the restarted generation's own racing dispatch
        appends — every rank derives the identical count."""
        path = str(tmp_path / "j.jsonl")
        recs = [
            {"type": "meta", "schema": S.SCHEMA_VERSION, "epoch": 0, "t": 1.0},
            dict(S.Job("a", "matmul").to_submit_record(), t=1.0, epoch=0),
            {"type": S.DISPATCHED, "id": "a", "seq": 1, "attempt": 1,
             "t": 2.0, "epoch": 0},
            # rank 0's fresh epoch-1 records, racing this rank's replay:
            {"type": "meta", "schema": S.SCHEMA_VERSION, "epoch": 1, "t": 9.0},
            {"type": "requeue", "id": "a", "t": 9.1, "epoch": 1},
            {"type": S.DISPATCHED, "id": "a", "seq": 2, "attempt": 2,
             "t": 9.2, "epoch": 1},
        ]
        with open(path, "w") as fh:
            for r in recs:
                fh.write(json.dumps(r) + "\n")
        s = S.Scheduler(_stub_executor())
        assert s.recover(path, epoch=1) == 1
        assert s._jobs["a"].attempts == 1  # the epoch-1 record didn't count

    def test_done_id_resubmit_after_recover_rejected_not_phantom(
        self, tmp_path
    ):
        """Review finding: after recover(), reusing a DONE job's id must
        raise ValueError (the in-process duplicate rule) — never slip
        through and be attested DONE-with-None without executing."""
        path = str(tmp_path / "j.jsonl")
        s0 = S.Scheduler(_stub_executor(), journal=path)
        s0.submit(S.Job("done-id", "matmul", tenant="acme"))
        s0.run()
        s1 = S.Scheduler(_stub_executor())
        s1.recover(path)
        with pytest.raises(ValueError):
            s1.submit(S.Job("done-id", "matmul", payload={"new": "work"}))
        assert s1.outcome("done-id")["state"] == S.DONE  # prior result visible

    def test_replay_shed_record_never_erases_done(self, tmp_path):
        """Review finding: a SHED record for an id already DONE (torn or
        foreign sequence) must not flip completed work to shed in the
        attestation."""
        path = str(tmp_path / "j.jsonl")
        j = S.JobJournal(path)
        j.append(S.Job("a", "matmul").to_submit_record())
        j.append({"type": S.DISPATCHED, "id": "a", "seq": 1, "attempt": 1})
        j.append({"type": S.DONE, "id": "a"})
        j.append({"type": S.SHED, "id": "a", "kind": "matmul",
                  "tenant": "acme", "reason": S.QUEUE_FULL})
        rep = S.replay_journal(path)
        assert rep["jobs"]["a"]["state"] == S.DONE
        summ = S.jobs_summary(rep)
        assert summ["done"] == 1 and summ["shed"] == 0

    def test_poison_job_retires_named_instead_of_crash_looping(self, tmp_path):
        """Review finding: a job whose payload deterministically kills the
        runtime (classified WorldBroken) must not crash-loop the restart
        budget away.  Attempts accumulate across generations via replay;
        past retry_budget + 1 dispatches the next WorldBroken fails the
        job NAMED (world_broken) before the crash, so the following
        generation retires it and serves the rest."""
        path = str(tmp_path / "j.jsonl")
        poison_raises = {"n": 0}

        def executor(jobs):
            if any(j.job_id == "poison" for j in jobs):
                poison_raises["n"] += 1
                raise S.WorldBroken("deterministic runtime death")
            return [{"ok": True} for _ in jobs]

        # generation 0: poison (retry_budget=0) + an innocent behind it
        s0 = S.Scheduler(executor, journal=path, max_batch=1)
        s0.submit(S.Job("poison", "matmul", priority=5, retry_budget=0))
        s0.submit(S.Job("bystander", "solve"))
        with pytest.raises(S.WorldBroken):
            s0.run()  # attempts=1 <= budget+1: requeued, world dies
        # generation 1: replay carries attempts=1; dispatch -> attempts=2
        # > retry_budget+1 -> FAILED world_broken journaled pre-crash
        s1 = S.Scheduler(executor, journal=S.JobJournal(path, epoch=1),
                         max_batch=1)
        assert s1.recover(path, epoch=1) == 2
        assert s1._jobs["poison"].attempts == 1  # restored from the journal
        with pytest.raises(S.WorldBroken):
            s1.run()
        assert s1.outcome("poison")["reason"] == S.WORLD_BROKEN
        # generation 2: poison is terminal in the journal — NOT requeued;
        # the bystander completes and nothing is lost
        s2 = S.Scheduler(executor, journal=S.JobJournal(path, epoch=2),
                         max_batch=1)
        assert s2.recover(path, epoch=2) == 1
        s2.run()
        assert s2.outcome("bystander")["state"] == S.DONE
        summ = S.jobs_summary(S.replay_journal(path))
        assert summ["lost"] == 0 and summ["failed"] == 1
        assert poison_raises["n"] == 2  # bounded: it never ran a third time

    def test_wrong_length_result_list_fails_batch_named(self):
        """Review finding: an executor returning the wrong number of
        results is a BUG — fail the batch loudly, never attest jobs DONE
        with someone else's result."""
        s = S.Scheduler(lambda jobs: [{"only": "one"}], max_batch=4)
        s.submit(S.Job("a", "matmul"))
        s.submit(S.Job("b", "matmul"))
        s.run()
        for jid in ("a", "b"):
            o = s.outcome(jid)
            assert o["state"] == S.FAILED
            assert o["reason"] == "error:ResultLengthMismatch"
        # the scalar convenience still works for a single-job batch
        s2 = S.Scheduler(lambda jobs: {"scalar": True})
        s2.submit(S.Job("solo", "matmul"))
        s2.run()
        assert s2.result("solo") == {"scalar": True}

    def test_journal_write_fault_propagates_loud_no_phantom_job(self, tmp_path):
        """A scheduler that cannot journal must not silently accept work:
        the sched.journal.write fault surfaces out of submit() AND the job
        is truly not accepted — not queued, not counted, never executed
        (review finding: journaling after the queue mutation left a
        runnable job the journal knew nothing about)."""
        path = str(tmp_path / "j.jsonl")
        log = []
        s = S.Scheduler(_stub_executor(log), journal=path)
        with faults.inject("sched.journal.write", fail=1):
            with pytest.raises(faults.TransientFault):
                s.submit(S.Job("phantom", "matmul"))
        assert s.pending() == 0
        assert "phantom" not in s._jobs
        assert S.counters().get("sched.accepted", 0) == 0
        s.run()
        assert log == []  # nothing to execute: the raise meant NOT accepted
        assert "phantom" not in S.replay_journal(path)["jobs"]
        # the scheduler heals: the next submit journals and runs normally
        s.submit(S.Job("real", "matmul"))
        s.run()
        assert S.replay_journal(path)["jobs"]["real"]["state"] == S.DONE

    def test_journal_write_fault_during_shed_mutates_nothing(self, tmp_path):
        s = S.Scheduler(_stub_executor(), journal=str(tmp_path / "j.jsonl"),
                        max_queue=0)
        with faults.inject("sched.journal.write", fail=1):
            with pytest.raises(faults.TransientFault):
                s.submit(S.Job("over", "matmul"))
        assert "over" not in s._jobs
        assert S.counters().get("sched.shed", 0) == 0


# ---------------------------------------------------------------------- #
# journal: durability + replay edge cases (satellite 3)
# ---------------------------------------------------------------------- #
class TestJournal:
    def _mk(self, tmp_path, name="sched_journal.jsonl"):
        return str(tmp_path / name)

    def test_header_and_roundtrip(self, tmp_path):
        path = self._mk(tmp_path)
        s = S.Scheduler(_stub_executor(), journal=path)
        s.submit(S.Job("a", "matmul", tenant="acme", priority=2,
                       payload={"n": 8}))
        s.run()
        first = json.loads(open(path).readline())
        assert first["type"] == "meta" and first["schema"] == S.SCHEMA_VERSION
        rep = S.replay_journal(path)
        v = rep["jobs"]["a"]
        assert v["state"] == S.DONE and v["tenant"] == "acme"
        assert v["attempts"] == 1 and v["payload"] == {"n": 8}
        assert rep["torn"] == 0

    def test_torn_final_record_tolerated(self, tmp_path):
        path = self._mk(tmp_path)
        s = S.Scheduler(_stub_executor(), journal=path)
        s.submit(S.Job("a", "matmul"))
        s.submit(S.Job("b", "matmul"))
        s.run()
        with open(path, "a") as fh:  # SIGKILL mid-append: half a record
            fh.write('{"type": "done", "id": "b", "t"')
        rep = S.replay_journal(path)
        assert rep["torn"] == 1
        assert rep["jobs"]["a"]["state"] == S.DONE  # salvage, don't sink
        assert rep["jobs"]["b"]["state"] == S.DONE

    def test_newer_schema_fails_loud_never_misparses(self, tmp_path):
        path = self._mk(tmp_path)
        with open(path, "w") as fh:
            fh.write(json.dumps({"type": "meta", "schema": S.SCHEMA_VERSION + 1}) + "\n")
            fh.write(json.dumps({"type": "submitted", "id": "a"}) + "\n")
        with pytest.raises(S.JournalSchemaError) as ei:
            S.replay_journal(path)
        assert str(S.SCHEMA_VERSION + 1) in str(ei.value)
        assert str(S.SCHEMA_VERSION) in str(ei.value)

    def test_headerless_journal_fails_loud(self, tmp_path):
        path = self._mk(tmp_path)
        with open(path, "w") as fh:
            fh.write(json.dumps({"type": "submitted", "id": "a"}) + "\n")
        with pytest.raises(S.JournalSchemaError):
            S.replay_journal(path)

    def test_crash_replay_requeues_in_flight_exactly_once(self, tmp_path):
        """DISPATCHED-but-not-DONE requeues ONCE however many dispatch
        records piled up; queued-never-dispatched requeues too; DONE does
        not."""
        path = self._mk(tmp_path)
        j = S.JobJournal(path)
        for jid in ("a", "b", "c"):
            j.append(S.Job(jid, "matmul").to_submit_record())
        j.append({"type": S.DISPATCHED, "id": "a", "seq": 1, "attempt": 1})
        j.append({"type": S.DISPATCHED, "id": "a", "seq": 1, "attempt": 2})
        j.append({"type": S.DONE, "id": "a"})
        j.append({"type": S.DISPATCHED, "id": "b", "seq": 2, "attempt": 1})
        j.append({"type": S.DISPATCHED, "id": "b", "seq": 2, "attempt": 2})
        # crash: b in flight (2 attempts), c still queued, a done
        log = []
        s = S.Scheduler(_stub_executor(log))
        n = s.recover(path)
        assert n == 2
        queued = sorted(x.job_id for x in s._queue)
        assert queued == ["b", "c"]  # each exactly once, a absent
        s.run()
        assert sorted(sum(log, [])) == ["b", "c"]  # a never re-executed

    def test_double_crash_no_duplicate_execution_of_done(self, tmp_path):
        """Crash → recover (j2 done in gen 1) → crash again → recover: the
        second replay must not re-run j2."""
        path = self._mk(tmp_path)
        j0 = S.JobJournal(path, epoch=0)
        j0.append(S.Job("j1", "matmul").to_submit_record())
        j0.append(S.Job("j2", "solve").to_submit_record())
        j0.append({"type": S.DISPATCHED, "id": "j2", "seq": 1, "attempt": 1})
        # generation 1: recovers, finishes j2, dispatches j1, crashes
        log1 = []
        s1 = S.Scheduler(_stub_executor(log1), journal=S.JobJournal(path, epoch=1))
        assert s1.recover(path) == 2
        s1.run()
        assert sorted(sum(log1, [])) == ["j1", "j2"]
        # fake gen 1 dying before it could close j1 out: strip j1's
        # terminal record from the journal (j2's DONE stays)
        lines = [
            l for l in open(path).read().splitlines()
            if not (
                json.loads(l).get("id") == "j1"
                and json.loads(l)["type"] in (S.DONE, S.FAILED)
            )
        ]
        with open(path, "w") as fh:
            fh.write("\n".join(lines) + "\n")
        # generation 2: j1 requeues (in flight at the crash), j2 must NOT
        log2 = []
        s2 = S.Scheduler(_stub_executor(log2), journal=S.JobJournal(path, epoch=2))
        assert s2.recover(path) == 1
        s2.run()
        assert sum(log2, []) == ["j1"]
        final = S.replay_journal(path)
        assert final["jobs"]["j1"]["state"] == S.DONE
        assert final["jobs"]["j2"]["state"] == S.DONE
        summ = S.jobs_summary(final)
        assert summ["lost"] == 0 and summ["requeued"] == 3  # 2 in gen1 + 1 in gen2

    def test_recovery_charges_journal_visible_deadline_time(self, tmp_path):
        """Review finding: recovery must not grant a crashed job a fresh
        wall budget per generation.  The charge is journal-derived (latest
        PRE-restart record t − submit t), so every rank computes the same
        remainder — and the restarted generation's own records (the fresh
        epoch header, racing requeue appends) never move the anchor; an
        already-expired job requeues and fails deadline_expired at
        dispatch — named, not lost, and never executed."""
        path = self._mk(tmp_path)
        recs = [
            {"type": "meta", "schema": S.SCHEMA_VERSION, "epoch": 0,
             "t": 1000.0},
            dict(S.Job("tight", "matmul", deadline_s=5.0).to_submit_record(),
                 t=1000.0, epoch=0),
            dict(S.Job("roomy", "matmul", deadline_s=500.0).to_submit_record(),
                 t=1000.0, epoch=0),
            {"type": S.DISPATCHED, "id": "tight", "seq": 1, "attempt": 1,
             "t": 1008.0, "epoch": 0},  # 8 s of journal-visible life
            # the restarted generation's header + a racing requeue append,
            # stamped much later: must NOT feed the anchor
            {"type": "meta", "schema": S.SCHEMA_VERSION, "epoch": 1,
             "t": 5000.0},
            {"type": "requeue", "id": "tight", "t": 5001.0, "epoch": 1},
        ]
        with open(path, "w") as fh:
            for r in recs:
                fh.write(json.dumps(r) + "\n")
        log = []
        s = S.Scheduler(_stub_executor(log))
        assert s.recover(path, epoch=1) == 2
        by_id = {x.job_id: x for x in s._queue}
        assert by_id["tight"].deadline_s == pytest.approx(-3.0)  # 5 − 8
        assert by_id["roomy"].deadline_s == pytest.approx(492.0)  # 500 − 8
        s.run()
        o = s.outcome("tight")
        assert o["state"] == S.FAILED and o["reason"] == S.DEADLINE_EXPIRED
        assert s.outcome("roomy")["state"] == S.DONE
        assert sum(log, []) == ["roomy"]  # the expired job never executed

    def test_no_restart_context_charges_nothing(self, tmp_path):
        """recover() at epoch 0 (no supervised restart) leaves deadlines
        untouched — there is no pre-restart generation to charge for."""
        path = self._mk(tmp_path)
        j = S.JobJournal(path, epoch=0)
        j.append(S.Job("a", "matmul", deadline_s=5.0).to_submit_record())
        s = S.Scheduler(_stub_executor())
        assert s.recover(path, epoch=0) == 1
        assert s._queue[0].deadline_s == 5.0

    def test_resubmit_after_shed_survives_crash_replay(self, tmp_path):
        """Review finding: a shed id that was later RE-submitted (which
        submit() explicitly permits) must replay as the accepted job, not
        the stale shed — or recovery silently drops an accepted job while
        the attestation still says lost=0."""
        path = self._mk(tmp_path)
        j = S.JobJournal(path)
        j.append({"type": S.SHED, "id": "x", "kind": "matmul",
                  "tenant": "acme", "reason": S.QUEUE_FULL})
        j.append(S.Job("x", "matmul", tenant="acme").to_submit_record())
        j.append({"type": S.DISPATCHED, "id": "x", "seq": 1, "attempt": 1})
        # crash here: x was accepted and in flight
        rep = S.replay_journal(path)
        assert rep["jobs"]["x"]["state"] == S.DISPATCHED
        log = []
        s = S.Scheduler(_stub_executor(log))
        assert s.recover(path) == 1
        s.run()
        assert s.outcome("x")["state"] == S.DONE
        assert S.jobs_summary(S.replay_journal(path))["lost"] == 1  # pre-recovery file
        # runtime end-to-end: shed, resubmit, complete — counted once each
        S.reset_counters()
        s2 = S.Scheduler(_stub_executor(), max_queue=0)
        with pytest.raises(S.JobRejected):
            s2.submit(S.Job("y", "matmul"))
        s2.max_queue = 4
        s2.submit(S.Job("y", "matmul"))
        s2.run()
        assert s2.outcome("y")["state"] == S.DONE

    def test_recovered_jobs_keep_priority_order(self, tmp_path):
        path = self._mk(tmp_path)
        j = S.JobJournal(path)
        j.append(S.Job("lo", "matmul", priority=0).to_submit_record())
        j.append(S.Job("hi", "matmul", priority=9).to_submit_record())
        log = []
        s = S.Scheduler(_stub_executor(log), max_batch=1)
        s.recover(path)
        s.run()
        assert log == [["hi"], ["lo"]]

    def test_shed_is_journaled_and_summarized(self, tmp_path):
        path = self._mk(tmp_path)
        s = S.Scheduler(_stub_executor(), max_queue=1, journal=path)
        s.submit(S.Job("in", "matmul", tenant="acme"))
        with pytest.raises(S.JobRejected):
            s.submit(S.Job("out", "matmul", tenant="globex"))
        s.run()
        summ = S.jobs_summary(S.replay_journal(path))
        assert summ == {
            "jobs": 2, "accepted": 1, "done": 1, "failed": 0, "shed": 1,
            "retried": 0, "requeued": 0, "lost": 0, "torn": 0,
            "generations": {"0": {
                "accepted": 1, "dispatched": 1, "completed": 1,
                "failed": 0, "shed": 1, "requeued": 0,
            }},
        }
        line = S.attestation_line(summ)
        assert line == "SCHED jobs=2 done=1 requeued=0 shed=1 failed=0 lost=0"

    def test_generations_attributed_by_epoch(self, tmp_path):
        path = self._mk(tmp_path)
        j0 = S.JobJournal(path, epoch=0)
        j0.append(S.Job("a", "matmul").to_submit_record())
        j1 = S.JobJournal(path, epoch=1)  # the restarted world re-opens
        j1.append({"type": S.DISPATCHED, "id": "a", "seq": 1, "attempt": 1})
        j1.append({"type": S.DONE, "id": "a"})
        summ = S.jobs_summary(S.replay_journal(path))
        assert summ["generations"]["0"]["accepted"] == 1
        assert summ["generations"]["1"]["completed"] == 1


# ---------------------------------------------------------------------- #
# graceful degradation + accounting
# ---------------------------------------------------------------------- #
class TestDrainAndReport:
    def test_drain_fails_remainder_world_unavailable_priority_order(
        self, tmp_path
    ):
        path = str(tmp_path / "j.jsonl")
        s = S.Scheduler(_stub_executor(), journal=path)
        s.submit(S.Job("lo", "matmul", priority=0, tenant="acme"))
        s.submit(S.Job("hi", "matmul", priority=9, tenant="globex"))
        s.submit(S.Job("mid", "matmul", priority=5, tenant="acme"))
        n = s.drain()
        assert n == 3 and s.pending() == 0
        rep = s.report()
        for jid in ("lo", "hi", "mid"):
            assert rep["jobs"][jid]["state"] == S.FAILED
            assert rep["jobs"][jid]["reason"] == S.WORLD_UNAVAILABLE
        # priority order is visible in the journal's failure sequence
        order = [json.loads(l)["id"] for l in open(path)
                 if json.loads(l).get("type") == S.FAILED]
        assert order == ["hi", "mid", "lo"]

    def test_drain_journal_fault_leaves_no_phantom(self, tmp_path):
        """Regression (ISSUE 17 satellite): a journal-append failure
        mid-drain must propagate with the failing job (and everything
        behind it) still queued and still SUBMITTED — the pre-fix drain
        iterated a snapshot and cleared the queue afterward, so a fault
        mid-loop left jobs FAILED in memory that the journal (and every
        recovery replaying it) never saw: phantom terminal states, and a
        retried drain double-finished the already-failed prefix."""
        path = str(tmp_path / "j.jsonl")
        s = S.Scheduler(_stub_executor(), journal=path)
        s.submit(S.Job("hi", "matmul", priority=9, tenant="acme"))
        s.submit(S.Job("mid", "matmul", priority=5, tenant="acme"))
        s.submit(S.Job("lo", "matmul", priority=0, tenant="globex"))
        # first append (the "hi" failure record) faults
        with faults.inject("sched.journal.write", fail=1):
            with pytest.raises(OSError):
                s.drain()
        # NOTHING mutated: all three still queued, SUBMITTED, accounted
        assert s.pending() == 3
        for jid in ("hi", "mid", "lo"):
            assert s.outcome(jid)["state"] == S.SUBMITTED
        assert s._tenant_inflight == {"acme": 2, "globex": 1}
        c = s.report()["counters"]
        assert c.get("sched.failed", 0) == 0
        # journal agrees: no FAILED record ever landed
        recs = [json.loads(l) for l in open(path)]
        assert not any(r.get("type") == S.FAILED for r in recs)
        # the retry drains cleanly — each job fails exactly once
        assert s.drain() == 3 and s.pending() == 0
        recs = [json.loads(l) for l in open(path)]
        failed = [r["id"] for r in recs if r.get("type") == S.FAILED]
        assert failed == ["hi", "mid", "lo"]  # priority order, no duplicates
        summ = S.jobs_summary(S.replay_journal(path))
        assert summ["failed"] == 3 and summ["lost"] == 0

    def test_drain_journal_fault_midway_keeps_remainder_queued(self, tmp_path):
        """The partial-progress shape: with the fault armed for the SECOND
        append, the first victim is terminally failed (journal + memory
        agree) and the rest stay queued for the retry."""
        path = str(tmp_path / "j.jsonl")
        s = S.Scheduler(_stub_executor(), journal=path)
        s.submit(S.Job("hi", "matmul", priority=9))
        s.submit(S.Job("lo", "matmul", priority=0))
        # fail the SECOND append of the drain (the "lo" failure record)
        orig, calls = s.journal.append, iter([False, True])
        s.journal.append = lambda rec: (
            (_ for _ in ()).throw(OSError("disk full")) if next(calls)
            else orig(rec)
        )
        try:
            with pytest.raises(OSError):
                s.drain()
        finally:
            s.journal.append = orig
        assert s.outcome("hi")["state"] == S.FAILED
        assert s.outcome("lo")["state"] == S.SUBMITTED
        assert s.pending() == 1
        assert s.drain() == 1
        failed = [json.loads(l)["id"] for l in open(path)
                  if json.loads(l).get("type") == S.FAILED]
        assert failed == ["hi", "lo"]

    def test_counters_reconcile_accepted_done_failed_shed(self):
        """Acceptance: sched.* counters reconcile — offered = accepted +
        shed, accepted = done + failed once the queue is empty."""
        s = S.Scheduler(_stub_executor(), max_queue=3, retry_base_delay=0.001)
        s.submit(S.Job("d1", "matmul"))
        s.submit(S.Job("d2", "matmul"))
        s.submit(S.Job("f1", "solve", retry_budget=0))
        with pytest.raises(S.JobRejected):
            s.submit(S.Job("s1", "matmul"))
        with faults.inject("sched.dispatch", fail=1):
            s.run()  # solve dispatches first? order: FIFO same priority
        rep = s.report()
        c = rep["counters"]
        assert c["sched.accepted"] == 3 and c["sched.shed"] == 1
        assert c.get("sched.done", 0) + c.get("sched.failed", 0) == 3
        assert rep["pending"] == 0
        assert rep["reconciled"] is True
        assert json.loads(json.dumps(rep)) == rep  # report is JSON-able

    def test_report_names_every_job(self):
        s = S.Scheduler(_stub_executor(), max_queue=2)
        s.submit(S.Job("a", "matmul"))
        with pytest.raises(S.JobRejected):
            s.submit(S.Job("b", "matmul", tenant="t",
                           deadline_s=-1.0))
        s.run()
        rep = s.report()
        assert set(rep["jobs"]) == {"a", "b"}
        assert rep["by_state"] == {S.DONE: 1, S.SHED: 1}


# ---------------------------------------------------------------------- #
# telemetry spans (the SLO table's source)
# ---------------------------------------------------------------------- #
class TestTelemetrySpans:
    def test_sched_job_events_carry_tenant_and_wait(self, tmp_path):
        from heat_tpu.utils import telemetry

        telemetry.enable()
        try:
            telemetry.reset()

            def execute(jobs):  # solve requests fail, the rest complete
                if jobs[0].kind == "solve":
                    raise ValueError("no solver today")
                return [{"ok": True} for _ in jobs]

            s = S.Scheduler(execute, max_queue=4)
            s.submit(S.Job("ok", "matmul", tenant="acme"))
            s.submit(S.Job("bad", "solve", tenant="globex", retry_budget=0))
            s.run()
            path = telemetry.flush(str(tmp_path))
            recs = [json.loads(l) for l in open(path)]
            spans = [r for r in recs
                     if r.get("type") == "span" and r["name"] == "sched.job"]
            assert len(spans) == 2
            by_id = {sp["attrs"]["id"]: sp for sp in spans}
            assert by_id["ok"]["attrs"]["tenant"] == "acme"
            assert by_id["ok"]["attrs"]["outcome"] == S.DONE
            assert by_id["ok"]["attrs"]["queue_wait_s"] >= 0.0
            assert by_id["ok"]["attrs"]["attempts"] == 1
            # a FAILED job's event names its reason as the outcome — the
            # SLO table's failed column comes from here on spans-only dirs
            assert by_id["bad"]["attrs"]["outcome"] == "error:ValueError"
        finally:
            telemetry.disable()
            telemetry.reset()


# ---------------------------------------------------------------------- #
# jax-side serving executors (micro-batching through the program cache)
# ---------------------------------------------------------------------- #
class TestServingExecutors:
    @pytest.fixture()
    def served(self, ht):
        from heat_tpu.parallel import serving

        return S.Scheduler(
            serving.make_executor(), batch_key=serving.batch_key,
            max_queue=32,
        )

    def test_all_four_kinds_complete(self, served):
        jobs = [
            S.Job("m", "matmul", payload={"n": 16, "seed": 1}),
            S.Job("s", "solve", payload={"n": 8}),
            S.Job("k", "kmeans", payload={"n": 32, "k": 2}),
            S.Job("f", "nn_forward", payload={"batch": 4, "features": 8}),
        ]
        for j in jobs:
            served.submit(j)
        rep = served.run()
        assert rep["by_state"] == {S.DONE: 4}
        for j in jobs:
            assert isinstance(served.result(j.job_id)["digest"], float)

    def test_same_shape_jobs_share_programs(self, served, ht):
        """The PR 1 cache contract: the SECOND identical-shape matmul
        request compiles nothing."""
        served.submit(S.Job("warm", "matmul", payload={"n": 16, "seed": 3}))
        served.run()
        before = profiler.cache_stats()["misses"]
        served.submit(S.Job("hit", "matmul", payload={"n": 16, "seed": 4}))
        served.run()
        assert profiler.cache_stats()["misses"] == before
        assert served.result("hit")["digest"] != served.result("warm")["digest"]

    def test_nn_forward_batches_stack_into_one_dispatch(self, served):
        for i in range(3):
            served.submit(S.Job(
                f"f{i}", "nn_forward",
                payload={"batch": 4, "features": 8, "seed": i},
            ))
        served.run()
        c = S.counters()
        assert c["sched.dispatches"] == 1 and c["sched.batched"] == 2
        digests = {served.result(f"f{i}")["digest"] for i in range(3)}
        assert len(digests) == 3  # per-job results, one shared forward

    def test_batch_key_ignores_data_fields(self, ht):
        from heat_tpu.parallel import serving

        a = S.Job("a", "matmul", payload={"n": 16, "seed": 1})
        b = S.Job("b", "matmul", payload={"n": 16, "seed": 2})
        c = S.Job("c", "matmul", payload={"n": 32, "seed": 1})
        assert serving.batch_key(a) == serving.batch_key(b)
        assert serving.batch_key(a) != serving.batch_key(c)

    def test_unknown_kind_fails_named(self, served):
        served.submit(S.Job("u", "fft_of_doom"))
        served.run()
        o = served.outcome("u")
        assert o["state"] == S.FAILED and o["reason"] == "error:ValueError"

    def test_dispatch_runs_under_comm_deadline(self, served, ht):
        """The armed scope IS the comm.deadline contextvar: a job with a
        deadline sees an active health deadline during execution."""
        seen = {}
        orig = served.executor

        def spying(jobs):
            seen["deadline"] = health.active_deadline()
            return orig(jobs)

        served.executor = spying
        served.submit(S.Job("d", "matmul", payload={"n": 16},
                            deadline_s=120.0))
        served.run()
        assert seen["deadline"] is not None
        assert served.outcome("d")["state"] == S.DONE


# ---------------------------------------------------------------------- #
# standalone-load contract (quick-lane import test, satellite 5)
# ---------------------------------------------------------------------- #
class TestStandaloneLoad:
    def test_scheduler_loads_and_serves_with_jax_blocked(self, tmp_path):
        """scheduler.py must load via spec_from_file_location and run a
        full submit→dispatch→journal→replay cycle with jax AND numpy
        imports blocked — the supervising launcher's requirement (it
        replays journals in a process that never pays the backend
        import), and the same contract supervisor.py keeps."""
        code = (
            "import importlib.util, json, sys;"
            "sys.modules['jax'] = None; sys.modules['numpy'] = None;"
            "spec = importlib.util.spec_from_file_location('s', sys.argv[1]);"
            "m = importlib.util.module_from_spec(spec);"
            "sys.modules['s'] = m; spec.loader.exec_module(m);"
            "sch = m.Scheduler(lambda jobs: [{'ok': j.job_id} for j in jobs],"
            " journal=sys.argv[2], max_queue=4);"
            "sch.submit(m.Job('a', 'matmul', tenant='t'));"
            "rep = sch.run();"
            "assert rep['by_state'] == {'done': 1}, rep;"
            "summ = m.jobs_summary(m.replay_journal(sys.argv[2]));"
            "assert summ['done'] == 1 and summ['lost'] == 0, summ;"
            "assert sys.modules.get('jax') is None and "
            "sys.modules.get('numpy') is None;"
            "print(m.attestation_line(summ))"
        )
        p = subprocess.run(
            [sys.executable, "-c", code,
             os.path.join(REPO, "heat_tpu", "parallel", "scheduler.py"),
             str(tmp_path / "j.jsonl")],
            capture_output=True, text=True, timeout=60,
        )
        assert p.returncode == 0, p.stderr[-2000:]
        assert p.stdout.strip() == (
            "SCHED jobs=1 done=1 requeued=0 shed=0 failed=0 lost=0"
        )

    def test_package_exports(self, ht):
        import heat_tpu

        assert heat_tpu.parallel.Scheduler is S.Scheduler
        assert heat_tpu.parallel.Job is S.Job
        assert heat_tpu.parallel.JobRejected is S.JobRejected
        assert callable(heat_tpu.parallel.make_executor)


# ---------------------------------------------------------------------- #
# supervisor integration: the jobs report section
# ---------------------------------------------------------------------- #
class TestSupervisorJobsSection:
    def _sup(self):
        spec = importlib.util.spec_from_file_location(
            "sup_for_sched", os.path.join(REPO, "heat_tpu", "parallel",
                                          "supervisor.py")
        )
        mod = importlib.util.module_from_spec(spec)
        sys.modules[spec.name] = mod
        spec.loader.exec_module(mod)
        return mod

    def test_report_gains_jobs_section_from_journal(self, tmp_path):
        path = str(tmp_path / "sched_journal.jsonl")
        sch = S.Scheduler(_stub_executor(), journal=path, max_queue=1)
        sch.submit(S.Job("a", "matmul", tenant="acme"))
        with pytest.raises(S.JobRejected):
            sch.submit(S.Job("b", "matmul"))
        sch.run()
        sup = self._sup()

        def spawn(rank, epoch, port):
            return subprocess.Popen([sys.executable, "-c", "pass"])

        res = sup.Supervisor(spawn, 1, poll_interval=0.05,
                             job_journal=path).run()
        assert res.ok and res.jobs is not None
        assert res.jobs["done"] == 1 and res.jobs["shed"] == 1
        assert res.jobs["lost"] == 0
        rep = res.report()
        assert rep["jobs"]["generations"]["0"]["completed"] == 1
        assert json.loads(json.dumps(rep)) == rep

    def test_no_journal_no_section(self):
        sup = self._sup()

        def spawn(rank, epoch, port):
            return subprocess.Popen([sys.executable, "-c", "pass"])

        res = sup.Supervisor(spawn, 1, poll_interval=0.05).run()
        assert res.ok and res.jobs is None
        assert "jobs" not in res.report()

    def test_corrupt_journal_degrades_not_crashes(self, tmp_path):
        path = str(tmp_path / "sched_journal.jsonl")
        with open(path, "w") as fh:
            fh.write(json.dumps({"type": "meta", "schema": 99}) + "\n")
        sup = self._sup()

        def spawn(rank, epoch, port):
            return subprocess.Popen([sys.executable, "-c", "pass"])

        res = sup.Supervisor(spawn, 1, poll_interval=0.05,
                             job_journal=path).run()
        assert res.ok
        assert "error" in res.jobs and "replay failed" in res.jobs["error"]


# ---------------------------------------------------------------------- #
# trace propagation (ISSUE 11): trace identity minted at submission,
# journaled with every record, preserved by replay across restarts
# ---------------------------------------------------------------------- #
class TestTracePropagation:
    def test_trace_id_minted_deterministically_at_submit(self):
        """Every rank of an SPMD world must derive the IDENTICAL id for the
        same job — so the mint is a pure function of the job identity, not
        process entropy (two independent schedulers agree)."""
        s1, s2 = S.Scheduler(_stub_executor()), S.Scheduler(_stub_executor())
        s1.submit(S.Job("j1", "matmul", tenant="acme"))
        s2.submit(S.Job("j1", "matmul", tenant="acme"))
        tid = s1._jobs["j1"].trace_id
        assert tid and tid == s2._jobs["j1"].trace_id
        assert tid == S.job_trace_id("j1", "matmul", "acme")

    def test_client_supplied_trace_id_adopted(self):
        s = S.Scheduler(_stub_executor())
        s.submit(S.Job("j1", "matmul", trace_id="feedface00000001"))
        assert s._jobs["j1"].trace_id == "feedface00000001"

    def test_every_journal_record_carries_the_tid(self, tmp_path):
        path = str(tmp_path / "sched_journal.jsonl")
        s = S.Scheduler(_stub_executor(), journal=path, max_queue=1)
        s.submit(S.Job("j1", "solve"))
        with pytest.raises(S.JobRejected):
            s.submit(S.Job("over", "solve"))  # shed: its record has a tid too
        s.run()
        replay = S.replay_journal(path)
        tid = S.job_trace_id("j1", "solve", "default")
        by_type = {}
        for rec in replay["records"]:
            if rec.get("id") == "j1":
                by_type[rec["type"]] = rec.get("tid")
        assert by_type == {
            "submitted": tid, "dispatched": tid, "done": tid,
        }
        shed = [r for r in replay["records"] if r.get("id") == "over"]
        assert shed and shed[0]["tid"] == S.job_trace_id(
            "over", "solve", "default"
        )

    def test_recover_preserves_trace_id_across_generations(self, tmp_path):
        """Satellite acceptance: a requeued job carries the SAME trace_id
        pre- and post-restart — journal replay preserves it, and the
        requeue record itself is journaled with it."""
        path = str(tmp_path / "sched_journal.jsonl")
        s = S.Scheduler(None, journal=path)
        s.submit(S.Job("j1", "kmeans", tenant="globex"))
        tid = s._jobs["j1"].trace_id
        # "restart": a fresh scheduler (fresh process in real life) replays
        s2 = S.Scheduler(_stub_executor(), journal=S.JobJournal(path))
        assert s2.recover(path) == 1
        assert s2._jobs["j1"].trace_id == tid
        s2.run()
        cont = S.trace_continuity(S.replay_journal(path))
        assert cont["ok"] and cont["jobs"] >= 1, cont

    def test_trace_continuity_flags_a_severed_chain(self, tmp_path):
        path = str(tmp_path / "sched_journal.jsonl")
        j = S.JobJournal(path)
        j.append({"type": S.SUBMITTED, "id": "j1", "kind": "matmul",
                  "tid": "aaaa000000000000"})
        j.append({"type": S.DISPATCHED, "id": "j1", "seq": 1, "attempt": 1,
                  "tid": "bbbb000000000000"})  # re-minted: the violation
        cont = S.trace_continuity(S.replay_journal(path))
        assert not cont["ok"] and cont["violations"] == ["j1"]

    def test_trace_continuity_flags_a_dropped_tid(self, tmp_path):
        """A record that LOSES the tid on a traced job is a severed chain
        too (the likeliest regression: a write path forgetting the field);
        a wholly tid-less journal — the pre-trace schema — is simply
        untraced, not a violation."""
        path = str(tmp_path / "sched_journal.jsonl")
        j = S.JobJournal(path)
        j.append({"type": S.SUBMITTED, "id": "j1", "kind": "matmul",
                  "tid": "aaaa000000000000"})
        j.append({"type": S.DISPATCHED, "id": "j1", "seq": 1, "attempt": 1})
        j.append({"type": S.SUBMITTED, "id": "old1", "kind": "matmul"})
        j.append({"type": S.DONE, "id": "old1"})  # pre-trace records: fine
        cont = S.trace_continuity(S.replay_journal(path))
        assert not cont["ok"] and cont["violations"] == ["j1"]

    def test_offered_untouched_when_the_journal_append_fails(self, tmp_path):
        """offered counts at the same point as accepted/shed — a
        sched.journal.write failure leaves the whole ledger untouched, so
        offered = accepted + shed survives journal faults (the /metrics
        reconciliation)."""
        s = S.Scheduler(_stub_executor(), journal=str(tmp_path / "j.jsonl"),
                        max_queue=1)
        with faults.inject("sched.journal.write", fail=1):
            with pytest.raises(faults.TransientFault):
                s.submit(S.Job("a", "matmul"))
        c = S.counters()
        assert c.get("sched.offered", 0) == 0
        s.submit(S.Job("a", "matmul"))  # the retry succeeds and counts once
        with faults.inject("sched.journal.write", fail=1):
            with pytest.raises(faults.TransientFault):
                s.submit(S.Job("b", "matmul"))  # _shed's append fails
        c = S.counters()
        assert c["sched.offered"] == 1
        assert c["sched.offered"] == c["sched.accepted"] + c.get("sched.shed", 0)

    def test_dispatch_arms_the_tracing_context(self):
        """The executor runs under telemetry.tracing(head.trace_id): spans
        recorded inside the dispatch carry the job's id, and the sched.job
        completion event carries each job's own id."""
        from heat_tpu.utils import telemetry

        telemetry.reset()
        telemetry.enable()
        try:
            seen = {}

            def execute(jobs):
                seen["ambient"] = telemetry.current_trace_id()
                with telemetry.span("exec.work"):
                    pass
                return [None] * len(jobs)

            s = S.Scheduler(execute)
            s.submit(S.Job("j1", "matmul"))
            s.run()
            tid = S.job_trace_id("j1", "matmul", "default")
            assert seen["ambient"] == tid
            recs = {r[0]: r[5] for r in telemetry._ring}
            assert recs["exec.work"]["trace_id"] == tid
            assert recs["sched.job"]["trace_id"] == tid
        finally:
            telemetry.disable()
            telemetry.reset()

    def test_offered_reconciles_with_accepted_plus_shed(self):
        s = S.Scheduler(_stub_executor(), max_queue=2)
        s.submit(S.Job("a", "matmul"))
        s.submit(S.Job("b", "matmul"))
        with pytest.raises(S.JobRejected):
            s.submit(S.Job("c", "matmul"))
        c = S.counters()
        assert c["sched.offered"] == 3
        assert c["sched.offered"] == c["sched.accepted"] + c["sched.shed"]
        # duplicates raise BEFORE being offered: neither side of the ledger
        with pytest.raises(ValueError):
            s.submit(S.Job("a", "matmul"))
        assert S.counters()["sched.offered"] == 3

    def test_monitor_gauge_source_reports_queue_state(self):
        """The scheduler registers a weakly-held gauge source with
        utils.monitor: queue depth + per-tenant in-flight, pruned once the
        scheduler is collected."""
        import gc

        from heat_tpu.utils import monitor

        s = S.Scheduler(_stub_executor(), max_queue=8)
        s.submit(S.Job("a", "matmul", tenant="acme"))
        s.submit(S.Job("b", "matmul", tenant="globex"))
        text = monitor.metrics_text()
        assert "sched_queue_depth 2" in text, text
        assert "sched_inflight_acme 1" in text
        assert "sched_inflight_globex 1" in text
        del s
        gc.collect()
        text = monitor.metrics_text()  # dead source pruned, no crash
        assert "sched_queue_depth" not in text
