"""Chaos lane: crash-recovery under real process death (ISSUE 2 robustness).

A victim subprocess is SIGKILLed in the middle of ``save_array_checkpoint``
— the fault site ``io.write`` is armed (via ``HEAT_TPU_FAULTS``) with a
per-chunk delay so the kill deterministically lands inside the chunk-write
loop — and the parent then asserts the previous checkpoint version still
loads bit-exact.  This is the torn-write scenario the fsync +
version-then-flip discipline exists for; no amount of in-process mocking
proves it the way a real SIGKILL does.

Marked ``chaos`` (+ ``slow``/``heavy``): runs in the dedicated chaos CI job,
not in the quick verify lane.
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

pytestmark = [pytest.mark.chaos, pytest.mark.slow, pytest.mark.heavy]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the victim: phase "seed" completes a checkpoint; phase "victim" starts a
# second save (announcing SAVING first so the parent can time its kill)
VICTIM = """
import os, sys
import numpy as np
ckpt, phase = sys.argv[1], sys.argv[2]
import heat_tpu as ht

n = 64
if phase == "seed":
    ht.save_array_checkpoint(ht.array(np.arange(n, dtype=np.float32) * 1.5, split=0), ckpt)
    print("SEEDED", flush=True)
else:
    x = ht.array(np.arange(n, dtype=np.float32) * -2.0, split=0)
    print("SAVING", flush=True)
    ht.save_array_checkpoint(x, ckpt)
    print("COMPLETED", flush=True)  # must never be reached (killed mid-save)
"""


def _env(faults_spec: str = "") -> dict:
    env = {k: v for k, v in os.environ.items() if k not in ("XLA_FLAGS",)}
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONUNBUFFERED"] = "1"
    if faults_spec:
        env["HEAT_TPU_FAULTS"] = faults_spec
    else:
        env.pop("HEAT_TPU_FAULTS", None)
    return env


def _run_victim(script_path, ckpt, phase, faults_spec=""):
    return subprocess.Popen(
        [sys.executable, script_path, ckpt, phase],
        env=_env(faults_spec), cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )


class TestKillMidSave:
    def test_sigkill_mid_save_previous_version_survives(self, tmp_path):
        """Acceptance: after SIGKILL during ``save_array_checkpoint``,
        ``load_array_checkpoint`` returns the previous version bit-exact."""
        script = str(tmp_path / "victim.py")
        with open(script, "w") as fh:
            fh.write(VICTIM)
        ckpt = str(tmp_path / "ckpt")

        seed = _run_victim(script, ckpt, "seed")
        out, _ = seed.communicate(timeout=240)
        assert seed.returncode == 0 and "SEEDED" in out, out[-2000:]
        assert open(os.path.join(ckpt, "LATEST")).read().strip() == "v0"

        # 8 chunks x 0.5 s injected delay per write: the save needs >= 4 s
        # after SAVING — a kill 1 s in lands inside the chunk-write loop
        victim = _run_victim(script, ckpt, "victim",
                             faults_spec="io.write:delay=0.5")
        deadline = time.monotonic() + 240
        line = ""
        while time.monotonic() < deadline:
            line = victim.stdout.readline()
            if "SAVING" in line or line == "":
                break
        assert "SAVING" in line, "victim never reached the save"
        time.sleep(1.0)
        victim.send_signal(signal.SIGKILL)
        rest = victim.communicate(timeout=60)[0]
        assert victim.returncode == -signal.SIGKILL
        assert "COMPLETED" not in rest, "kill missed the save window"

        # torn v1 may exist on disk; LATEST must still name the durable v0
        assert open(os.path.join(ckpt, "LATEST")).read().strip() == "v0"

        import heat_tpu as ht

        back = ht.load_array_checkpoint(ckpt)
        np.testing.assert_array_equal(
            back.numpy(), np.arange(64, dtype=np.float32) * 1.5
        )

    def test_sigkill_then_resave_then_load(self, tmp_path):
        """After a torn save, the NEXT save must succeed and supersede the
        wreckage (the torn v-dir is skipped for version numbering and pruned
        once a complete newer version lands)."""
        script = str(tmp_path / "victim.py")
        with open(script, "w") as fh:
            fh.write(VICTIM)
        ckpt = str(tmp_path / "ckpt")

        seed = _run_victim(script, ckpt, "seed")
        out, _ = seed.communicate(timeout=240)
        assert seed.returncode == 0 and "SEEDED" in out, out[-2000:]
        victim = _run_victim(script, ckpt, "victim", faults_spec="io.write:delay=0.5")
        deadline = time.monotonic() + 240
        line = ""
        while time.monotonic() < deadline:
            line = victim.stdout.readline()
            if "SAVING" in line or line == "":
                break
        assert "SAVING" in line, "victim never reached the save"
        time.sleep(1.0)
        victim.send_signal(signal.SIGKILL)
        rest = victim.communicate(timeout=60)[0]
        assert "COMPLETED" not in rest, "kill missed the save window"

        import heat_tpu as ht

        d3 = np.arange(64, dtype=np.float32) + 7
        ht.save_array_checkpoint(ht.array(d3, split=0), ckpt)
        back = ht.load_array_checkpoint(ckpt)
        np.testing.assert_array_equal(back.numpy(), d3)
