"""Mathematical constants, mirroring the reference's ``heat/core/constants.py``.

Reference parity: ``heat.pi``, ``heat.e``, ``heat.inf``, ``heat.nan``.
"""

import math

__all__ = ["e", "euler_gamma", "inf", "nan", "pi", "E", "Inf", "Infty", "Infinity", "NaN"]

e = math.e
euler_gamma = 0.57721566490153286060651209008240243
inf = math.inf
nan = math.nan
pi = math.pi

# numpy-style aliases
E = e
Inf = inf
Infty = inf
Infinity = inf
NaN = nan
