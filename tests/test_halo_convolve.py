"""Halo-path convolve (VERDICT r2 item 7; reference
``heat/core/signal.py::convolve`` + ``DNDarray.get_halo``, SURVEY §5.7).

Distributed signals must take the halo-exchange path (per-shard local conv
on [halo_prev | block | halo_next], no global gather) — asserted via the
``signal._HALO_CONV_RUNS`` counter — and match numpy for full/same/valid,
including ragged lengths, distributed kernels (gathered), and the
operand-swap case where the KERNEL is the distributed long operand.
"""

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.core import signal as sg
from test_suites.basic_test import TestCase


class TestHaloConvolve(TestCase):
    @pytest.mark.parametrize("n,m", [(40, 5), (37, 4), (16, 3), (20, 1), (64, 9), (13, 2)])
    @pytest.mark.parametrize("mode", ["full", "same", "valid"])
    def test_matrix(self, n, m, mode):
        rng = np.random.default_rng(n * 31 + m)
        an = rng.uniform(-2, 2, n).astype(np.float32)
        vn = rng.uniform(-1, 1, m).astype(np.float32)
        want = np.convolve(an, vn, mode=mode)
        p = ht.communication.get_comm().size
        c_blk = -(-n // p)
        for asplit in (0, None):
            for vsplit in (None, 0):
                before = sg._HALO_CONV_RUNS
                r = ht.convolve(
                    ht.array(an, split=asplit), ht.array(vn, split=vsplit), mode=mode
                )
                self.assert_array_equal(r, want, rtol=1e-4, atol=1e-4)
                # the halo path only exists on a distributed mesh (p=1 has
                # no neighbors to exchange with — global conv is correct)
                if asplit == 0 and m - 1 <= c_blk and p > 1:
                    assert sg._HALO_CONV_RUNS > before, (
                        f"halo path skipped for n={n} m={m} mode={mode} "
                        f"(vsplit={vsplit}) — fell back to global gather"
                    )

    def test_halo_too_wide_falls_back(self):
        # kernel wider than a block: halo cannot fit, global path must serve.
        # sized from the ACTUAL device count so the halo (m-1) always
        # exceeds the ceil-div block at any mesh width
        p = ht.communication.get_comm().size
        n = 13
        m = -(-n // p) + 2
        an = np.arange(n, dtype=np.float32)
        vn = np.ones(m, dtype=np.float32)
        before = sg._HALO_CONV_RUNS
        r = ht.convolve(ht.array(an, split=0), ht.array(vn), mode="full")
        assert sg._HALO_CONV_RUNS == before
        self.assert_array_equal(r, np.convolve(an, vn))

    def test_swapped_distributed_kernel(self):
        # signal shorter than kernel: operands swap, the distributed long
        # operand drives the halo path, result split follows the SIGNAL (None)
        an = np.arange(4, dtype=np.float32)
        vn = np.linspace(0, 1, 40, dtype=np.float32)
        before = sg._HALO_CONV_RUNS
        r = ht.convolve(ht.array(an), ht.array(vn, split=0), mode="full")
        if ht.communication.get_comm().is_distributed():
            assert sg._HALO_CONV_RUNS > before
        assert r.split is None
        self.assert_array_equal(r, np.convolve(an, vn), rtol=1e-4, atol=1e-4)

    def test_int_dtype_rounding(self):
        ai = np.arange(20)
        vi = np.array([1, 2, 3])
        r = ht.convolve(ht.array(ai, split=0), ht.array(vi), mode="full")
        assert np.array_equal(r.numpy(), np.convolve(ai, vi))

    def test_result_distributed(self):
        an = np.arange(64, dtype=np.float32)
        vn = np.ones(5, np.float32)
        for mode in ("full", "same", "valid"):
            r = ht.convolve(ht.array(an, split=0), ht.array(vn), mode=mode)
            assert r.split == 0
            self.assert_distributed(r)
