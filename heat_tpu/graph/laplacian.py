"""Graph Laplacian (reference: ``heat/graph/laplacian.py``)."""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp

from ..core import types
from ..core.dndarray import DNDarray

__all__ = ["Laplacian"]


class Laplacian:
    """Similarity-graph Laplacian L = D − A (or normalized variants).

    Parameters mirror the reference: a similarity callable (e.g.
    ``spatial.rbf``), ``definition`` ('simple' | 'norm_sym'),
    ``mode`` ('fully_connected' | 'eNeighbour'), thresholds for
    epsilon-ball sparsification.
    """

    def __init__(
        self,
        similarity: Callable,
        definition: str = "norm_sym",
        mode: str = "fully_connected",
        threshold_key: str = "upper",
        threshold_value: float = 1.0,
        neighbours: Optional[int] = None,
    ):
        self.similarity = similarity
        if definition not in ("simple", "norm_sym"):
            raise NotImplementedError(f"definition {definition!r} not supported")
        if mode not in ("fully_connected", "eNeighbour"):
            raise NotImplementedError(f"mode {mode!r} not supported")
        self.definition = definition
        self.mode = mode
        self.epsilon = (threshold_key, threshold_value)
        self.neighbours = neighbours

    def _normalized_symmetric_L(self, A):
        d = jnp.sum(A, axis=1)
        d_inv_sqrt = jnp.where(d > 0, 1.0 / jnp.sqrt(jnp.maximum(d, 1e-30)), 0.0)
        L = jnp.eye(A.shape[0], dtype=A.dtype) - d_inv_sqrt[:, None] * A * d_inv_sqrt[None, :]
        return L

    def _simple_L(self, A):
        return jnp.diag(jnp.sum(A, axis=1)) - A

    def construct(self, x: DNDarray) -> DNDarray:
        """Build the Laplacian of the similarity graph of row-samples of x."""
        S = self.similarity(x)
        A = S._jarray if isinstance(S, DNDarray) else jnp.asarray(S)
        # zero the self-similarity diagonal (reference convention)
        A = A * (1.0 - jnp.eye(A.shape[0], dtype=A.dtype))
        if self.mode == "eNeighbour":
            # epsilon-neighborhood graph: BINARY adjacency (a raw distance
            # kept as weight would invert affinities — far in-epsilon points
            # would dominate)
            key, val = self.epsilon
            mask = (A < val) if key == "upper" else (A > val)
            A = mask.astype(A.dtype) * (1.0 - jnp.eye(A.shape[0], dtype=A.dtype))
        L = self._normalized_symmetric_L(A) if self.definition == "norm_sym" else self._simple_L(A)
        proto = S if isinstance(S, DNDarray) else x
        L = proto.comm.shard(L, proto.split)
        return DNDarray(
            L, tuple(L.shape), types.canonical_heat_type(L.dtype), proto.split, proto.device, proto.comm, True
        )
