"""Utilities (reference: ``heat/utils/``)."""

from . import data
from . import profiler
