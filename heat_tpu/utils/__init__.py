"""Utilities (reference: ``heat/utils/``)."""

from . import data
from . import faults
from . import profiler
from . import telemetry
