"""Minimal pure-JAX module system backing ``ht.nn``.

The reference's ``ht.nn`` is a passthrough to ``torch.nn`` (SURVEY §2.5);
the TPU-native equivalent exposes the same constructor names
(``ht.nn.Linear``, ``ht.nn.ReLU``, ``ht.nn.Sequential``, …) as lightweight
pure-functional modules: ``init(key) -> params`` (a pytree) and
``apply(params, x) -> y``.  Arbitrary flax modules duck-type the same
contract and work everywhere these are accepted.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "Module",
    "Linear",
    "Identity",
    "ReLU",
    "Tanh",
    "Sigmoid",
    "GELU",
    "Softmax",
    "LogSoftmax",
    "Dropout",
    "Dropout1d",
    "Dropout2d",
    "Dropout3d",
    "Flatten",
    "Unflatten",
    "Sequential",
    "Conv2d",
    "MaxPool2d",
    "AvgPool2d",
    "AdaptiveAvgPool2d",
    "BatchNorm1d",
    "BatchNorm2d",
    "BatchNorm3d",
    "LayerNorm",
    "RMSNorm",
    "GroupNorm",
    "Embedding",
    "Residual",
]


def _pair(v) -> Tuple[int, int]:
    """torch-style int-or-tuple normalization for 2-D spatial args."""
    return v if isinstance(v, tuple) else (v, v)


def _concrete_int(x):
    """``int(x)`` when ``x`` is concrete, else ``None`` — the probe jit-safe
    eager validations share (traced values raise the public Tracer*Error
    family; ``jax.core.Tracer`` isinstance checks are a deprecated path).
    Used by the decode-step capacity guard and EmbeddingBag's offsets
    check."""
    import jax

    try:
        return int(x)
    except (jax.errors.TracerIntegerConversionError,
            jax.errors.ConcretizationTypeError, TypeError):
        return None


def _module_accepts_train(module) -> bool:
    """Whether ``module.apply`` should be called with ``train=``/``key=``.

    heat modules always do.  Duck-typed modules qualify only via an EXPLICIT
    ``train`` parameter in their apply signature — a bare ``**kwargs`` does
    not (flax's apply has ``**kwargs`` it would forward to ``__call__``,
    crashing models whose ``__call__`` lacks ``train``)."""
    import inspect

    if isinstance(module, Module):
        return True
    try:
        sig = inspect.signature(module.apply)
        return "train" in sig.parameters
    except (TypeError, ValueError, AttributeError):
        return False


class Module:
    """Base: stateless apply + parameter init."""

    def init(self, key) -> Any:
        return ()

    def apply(self, params, x, *, train: bool = False, key=None):
        raise NotImplementedError

    def __call__(self, params, x, **kw):
        return self.apply(params, x, **kw)


class Linear(Module):
    """Dense layer y = x Wᵀ + b (torch parameter convention: W is (out, in))."""

    def __init__(self, in_features: int, out_features: int, bias: bool = True):
        self.in_features = in_features
        self.out_features = out_features
        self.bias = bias

    def init(self, key):
        wk, bk = jax.random.split(key)
        bound = 1.0 / jnp.sqrt(self.in_features)
        w = jax.random.uniform(wk, (self.out_features, self.in_features), minval=-bound, maxval=bound)
        if self.bias:
            b = jax.random.uniform(bk, (self.out_features,), minval=-bound, maxval=bound)
            return {"weight": w, "bias": b}
        return {"weight": w}

    def apply(self, params, x, **kw):
        y = x @ params["weight"].T
        if self.bias:
            y = y + params["bias"]
        return y


class _Activation(Module):
    fn: Callable = None

    def apply(self, params, x, **kw):
        return type(self).fn(x)


class ReLU(_Activation):
    fn = staticmethod(jax.nn.relu)


class Tanh(_Activation):
    fn = staticmethod(jnp.tanh)


class Sigmoid(_Activation):
    fn = staticmethod(jax.nn.sigmoid)


class GELU(Module):
    """torch parity: default is the EXACT erf form (``approximate='none'``);
    ``jax.nn.gelu``'s default is the tanh approximation, so the flag maps
    explicitly."""

    def __init__(self, approximate: str = "none"):
        if approximate not in ("none", "tanh"):
            raise ValueError(f"approximate must be 'none' or 'tanh', got {approximate!r}")
        self.approximate = approximate

    def apply(self, params, x, **kw):
        return jax.nn.gelu(x, approximate=self.approximate == "tanh")


class Softmax(Module):
    def __init__(self, dim: int = -1):
        self.dim = dim

    def apply(self, params, x, **kw):
        return jax.nn.softmax(x, axis=self.dim)


class LogSoftmax(Module):
    def __init__(self, dim: int = -1):
        self.dim = dim

    def apply(self, params, x, **kw):
        return jax.nn.log_softmax(x, axis=self.dim)


class Dropout(Module):
    def __init__(self, p: float = 0.5):
        self.p = p

    def apply(self, params, x, *, train: bool = False, key=None):
        if not train or self.p == 0.0:
            return x
        if key is None:
            raise ValueError("Dropout in train mode requires a PRNG key")
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(key, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0)


class _ChannelDropout(Module):
    """Zero whole channels (torch ``Dropout1d/2d/3d``): the mask covers
    (N, C) and broadcasts over the trailing ``spatial`` dims."""

    spatial: int = 0

    def __init__(self, p: float = 0.5):
        self.p = p

    def apply(self, params, x, *, train: bool = False, key=None):
        if not train or self.p == 0.0:
            return x
        if key is None:
            raise ValueError("channel dropout in train mode requires a PRNG key")
        if x.ndim != self.spatial + 2:
            raise ValueError(
                f"expected a {self.spatial + 2}-D (N, C, ...) input, got {x.ndim}-D"
            )
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(key, keep, x.shape[:2] + (1,) * self.spatial)
        return jnp.where(mask, x / keep, 0.0)


class Dropout1d(_ChannelDropout):
    spatial = 1


class Dropout2d(_ChannelDropout):
    spatial = 2


class Dropout3d(_ChannelDropout):
    spatial = 3


class Flatten(Module):
    def apply(self, params, x, **kw):
        return x.reshape(x.shape[0], -1)


class Unflatten(Module):
    """Inverse of Flatten: expand ``dim`` into ``unflattened_size`` (torch
    argument convention)."""

    def __init__(self, dim: int, unflattened_size):
        self.dim = dim
        self.unflattened_size = tuple(unflattened_size)

    def apply(self, params, x, **kw):
        d = self.dim % x.ndim
        return x.reshape(x.shape[:d] + self.unflattened_size + x.shape[d + 1:])


class Conv2d(Module):
    """2-D convolution, NCHW layout (torch convention)."""

    def __init__(self, in_channels: int, out_channels: int, kernel_size: int,
                 stride: int = 1, padding: int = 0, bias: bool = True):
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = _pair(kernel_size)
        self.stride = _pair(stride)
        self.padding = _pair(padding)
        self.bias = bias

    def init(self, key):
        wk, bk = jax.random.split(key)
        fan_in = self.in_channels * self.kernel_size[0] * self.kernel_size[1]
        bound = 1.0 / jnp.sqrt(fan_in)
        w = jax.random.uniform(
            wk, (self.out_channels, self.in_channels) + self.kernel_size, minval=-bound, maxval=bound
        )
        if self.bias:
            return {"weight": w, "bias": jax.random.uniform(bk, (self.out_channels,), minval=-bound, maxval=bound)}
        return {"weight": w}

    def apply(self, params, x, **kw):
        y = jax.lax.conv_general_dilated(
            x, params["weight"], window_strides=self.stride,
            padding=[(p, p) for p in self.padding],
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )
        if self.bias:
            y = y + params["bias"][None, :, None, None]
        return y


class _Pool2d(Module):
    def __init__(self, kernel_size: int, stride: Optional[int] = None):
        self.kernel_size = _pair(kernel_size)
        self.stride = _pair(stride if stride is not None else kernel_size)


def _max_pool_indices(x, kernel, stride, rank):
    """Max pooling that ALSO returns torch-convention indices: each output
    position's flat index into its channel's spatial plane (what
    ``MaxUnpoolNd`` consumes).  The flat index is derived ARITHMETICALLY
    from the within-window argmax (window start = out_pos·stride, plus the
    row-major in-window offset), all in integer math — exact at any plane
    size, and no second patches pass over an index plane."""
    from math import prod

    dn = {1: ("NCH", "OIH", "NCH"), 2: ("NCHW", "OIHW", "NCHW"),
          3: ("NCDHW", "OIDHW", "NCDHW")}[rank]
    spatial = x.shape[2:]
    p = jax.lax.conv_general_dilated_patches(
        x, kernel, stride, [(0, 0)] * rank, dimension_numbers=dn
    )
    px = p.reshape(p.shape[0], x.shape[1], prod(kernel), *p.shape[2:])
    am = jnp.argmax(px, axis=2)  # (N, C, *out_spatial), row-major in-window
    vals = jnp.take_along_axis(px, am[:, :, None], axis=2)[:, :, 0]

    # decompose am row-major over the kernel dims (the patches layout)
    offs, rem = [], am
    for kd in reversed(kernel):
        offs.append(rem % kd)
        rem = rem // kd
    offs = offs[::-1]
    idx = jnp.zeros_like(am)
    plane = 1
    out_spatial = am.shape[2:]
    for d in reversed(range(rank)):
        pos = jnp.arange(out_spatial[d]).reshape(
            (1, 1) + (1,) * d + (-1,) + (1,) * (rank - 1 - d)
        )
        idx = idx + (pos * stride[d] + offs[d]) * plane
        plane *= spatial[d]
    return vals, idx.astype(jnp.int32)


class MaxPool2d(_Pool2d):
    def __init__(self, kernel_size: int, stride: Optional[int] = None,
                 return_indices: bool = False):
        super().__init__(kernel_size, stride)
        self.return_indices = return_indices

    def apply(self, params, x, **kw):
        if self.return_indices:
            return _max_pool_indices(x, self.kernel_size, self.stride, 2)
        return jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max,
            window_dimensions=(1, 1) + self.kernel_size,
            window_strides=(1, 1) + self.stride,
            padding="VALID",
        )


class AvgPool2d(_Pool2d):
    def apply(self, params, x, **kw):
        summed = jax.lax.reduce_window(
            x, 0.0, jax.lax.add,
            window_dimensions=(1, 1) + self.kernel_size,
            window_strides=(1, 1) + self.stride,
            padding="VALID",
        )
        return summed / (self.kernel_size[0] * self.kernel_size[1])


class _AdaptivePool(Module):
    """Adaptive pooling over the trailing ``spatial`` dims, divisible case
    (torch semantics where input size is a multiple of output size — the
    pooled windows are then uniform).  ``output_size`` accepts an int, a
    tuple/list, and torch's ``None`` entries (keep that dim)."""

    spatial: int = 2
    op = staticmethod(jnp.mean)

    def __init__(self, output_size=1):
        n = self.spatial
        if isinstance(output_size, (tuple, list)):
            self.output_size = tuple(output_size)
        else:
            self.output_size = (output_size,) * n
        if len(self.output_size) != n:
            raise ValueError(f"output_size must have {n} entries")

    def apply(self, params, x, **kw):
        n = self.spatial
        spatial = x.shape[-n:]
        outs = tuple(
            s if o is None else int(o)  # torch: None keeps the input extent
            for s, o in zip(spatial, self.output_size)
        )
        shape = list(x.shape[:-n])
        axes = []
        for s, o in zip(spatial, outs):
            if s % o:
                raise ValueError(
                    f"{type(self).__name__}: input {s} not divisible by output {o}"
                )
            shape += [o, s // o]
            axes.append(len(shape) - 1)
        return type(self).op(x.reshape(shape), axis=tuple(axes))


class AdaptiveAvgPool2d(_AdaptivePool):
    spatial = 2


class Identity(Module):
    def apply(self, params, x, **kw):
        return x


class _BatchNorm(Module):
    """Batch normalization with torch parameter names.

    Functional-JAX contract: training normalizes with batch statistics;
    evaluation uses the stored running stats.  Because ``apply`` is pure, the
    running-stat EMA is exposed as :meth:`update_stats` (returns new params)
    for callers that track it; train steps that never call it still match the
    reference's training-mode math exactly.

    ``running_mean``/``running_var`` are buffers, not parameters: the
    framework's optimizers mask every ``running_*`` leaf from updates and
    weight decay (see ``optim.dp_optimizer._nontrainable_mask``).
    """

    def __init__(self, num_features: int, eps: float = 1e-5, momentum: float = 0.1,
                 affine: bool = True):
        self.num_features = num_features
        self.eps = eps
        self.momentum = momentum
        self.affine = affine

    def _axes(self, ndim: int) -> Tuple[int, ...]:
        # all dims except channel (dim 1): (N,C)->(0,), (N,C,L)->(0,2), (N,C,H,W)->(0,2,3)
        return (0,) + tuple(range(2, ndim))

    def init(self, key):
        c = self.num_features
        p = {"running_mean": jnp.zeros(c), "running_var": jnp.ones(c)}
        if self.affine:
            p["weight"] = jnp.ones(c)
            p["bias"] = jnp.zeros(c)
        return p

    def _bcast(self, v, ndim):
        shape = [1] * ndim
        shape[1] = self.num_features
        return v.reshape(shape)

    def apply(self, params, x, *, train: bool = False, **kw):
        if train:
            mean = jnp.mean(x, axis=self._axes(x.ndim))
            var = jnp.var(x, axis=self._axes(x.ndim))
        else:
            mean, var = params["running_mean"], params["running_var"]
        y = (x - self._bcast(mean, x.ndim)) / jnp.sqrt(self._bcast(var, x.ndim) + self.eps)
        if self.affine:
            y = y * self._bcast(params["weight"], x.ndim) + self._bcast(params["bias"], x.ndim)
        return y

    def update_stats(self, params, x):
        """EMA update of running stats from a batch (returns new params).

        Uses the unbiased (ddof=1) variance, matching torch's running-stat
        convention (train-mode normalization stays biased, also like torch).
        """
        m = self.momentum
        axes = self._axes(x.ndim)
        mean = jnp.mean(x, axis=axes)
        var = jnp.var(x, axis=axes, ddof=1)
        new = dict(params)
        new["running_mean"] = (1 - m) * params["running_mean"] + m * mean
        new["running_var"] = (1 - m) * params["running_var"] + m * var
        return new


class BatchNorm1d(_BatchNorm):
    """BatchNorm over (N, C) or (N, C, L) input."""

    def _axes(self, ndim: int) -> Tuple[int, ...]:
        if ndim not in (2, 3):
            raise ValueError(f"BatchNorm1d expects 2-D or 3-D input, got {ndim}-D")
        return super()._axes(ndim)


class BatchNorm2d(_BatchNorm):
    """BatchNorm over (N, C, H, W) input."""

    def _axes(self, ndim: int) -> Tuple[int, ...]:
        if ndim != 4:
            raise ValueError(f"BatchNorm2d expects 4-D input, got {ndim}-D")
        return super()._axes(ndim)


class BatchNorm3d(_BatchNorm):
    """BatchNorm over (N, C, D, H, W) input."""

    def _axes(self, ndim: int) -> Tuple[int, ...]:
        if ndim != 5:
            raise ValueError(f"BatchNorm3d expects 5-D input, got {ndim}-D")
        return super()._axes(ndim)


class LayerNorm(Module):
    """Layer normalization over the trailing ``normalized_shape`` dims."""

    def __init__(self, normalized_shape, eps: float = 1e-5, elementwise_affine: bool = True):
        self.normalized_shape = (
            (normalized_shape,) if isinstance(normalized_shape, int) else tuple(normalized_shape)
        )
        self.eps = eps
        self.affine = elementwise_affine

    def init(self, key):
        if self.affine:
            return {"weight": jnp.ones(self.normalized_shape), "bias": jnp.zeros(self.normalized_shape)}
        return {}

    def apply(self, params, x, **kw):
        axes = tuple(range(x.ndim - len(self.normalized_shape), x.ndim))
        mean = jnp.mean(x, axis=axes, keepdims=True)
        var = jnp.var(x, axis=axes, keepdims=True)
        y = (x - mean) / jnp.sqrt(var + self.eps)
        if self.affine:
            y = y * params["weight"] + params["bias"]
        return y


class RMSNorm(Module):
    """Root-mean-square normalization over the trailing ``normalized_shape``
    dims (torch ``nn.RMSNorm``; the LLM-standard LayerNorm variant — no
    mean subtraction, no bias).  ``eps=None`` follows torch: the input
    dtype's machine epsilon."""

    def __init__(self, normalized_shape, eps: float | None = None,
                 elementwise_affine: bool = True):
        self.normalized_shape = (
            (normalized_shape,) if isinstance(normalized_shape, int) else tuple(normalized_shape)
        )
        self.eps = eps
        self.affine = elementwise_affine

    def init(self, key):
        if self.affine:
            return {"weight": jnp.ones(self.normalized_shape)}
        return {}

    def apply(self, params, x, **kw):
        axes = tuple(range(x.ndim - len(self.normalized_shape), x.ndim))
        eps = jnp.finfo(x.dtype).eps if self.eps is None else self.eps
        y = x * jax.lax.rsqrt(jnp.mean(x * x, axis=axes, keepdims=True) + eps)
        if self.affine:
            y = y * params["weight"]
        return y


class GroupNorm(Module):
    def __init__(self, num_groups: int, num_channels: int, eps: float = 1e-5, affine: bool = True):
        if num_channels % num_groups:
            raise ValueError("num_channels must be divisible by num_groups")
        self.num_groups = num_groups
        self.num_channels = num_channels
        self.eps = eps
        self.affine = affine

    def init(self, key):
        if self.affine:
            return {"weight": jnp.ones(self.num_channels), "bias": jnp.zeros(self.num_channels)}
        return {}

    def apply(self, params, x, **kw):
        n, c = x.shape[:2]
        g = self.num_groups
        xg = x.reshape((n, g, c // g) + x.shape[2:])
        axes = tuple(range(2, xg.ndim))
        mean = jnp.mean(xg, axis=axes, keepdims=True)
        var = jnp.var(xg, axis=axes, keepdims=True)
        y = ((xg - mean) / jnp.sqrt(var + self.eps)).reshape(x.shape)
        if self.affine:
            shape = [1] * x.ndim
            shape[1] = c
            y = y * params["weight"].reshape(shape) + params["bias"].reshape(shape)
        return y


class Embedding(Module):
    def __init__(self, num_embeddings: int, embedding_dim: int):
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim

    def init(self, key):
        return {"weight": jax.random.normal(key, (self.num_embeddings, self.embedding_dim))}

    def apply(self, params, x, **kw):
        return params["weight"][x]


class Residual(Module):
    """y = body(x) + shortcut(x) — the ResNet block skeleton."""

    def __init__(self, body: Module, shortcut: Optional[Module] = None):
        self.body = body
        self.shortcut = shortcut if shortcut is not None else Identity()

    def init(self, key):
        bk, sk = jax.random.split(key)
        return {"body": self.body.init(bk), "shortcut": self.shortcut.init(sk)}

    def apply(self, params, x, *, train: bool = False, key=None):
        bk = sk = None
        if key is not None:
            bk, sk = jax.random.split(key)
        return self.body.apply(params["body"], x, train=train, key=bk) + self.shortcut.apply(
            params["shortcut"], x, train=train, key=sk
        )


class Sequential(Module):
    """Chain of modules; params is a list of per-layer pytrees."""

    def __init__(self, *layers: Module):
        self.layers = list(layers)

    def init(self, key):
        keys = jax.random.split(key, max(len(self.layers), 1))
        return [l.init(k) for l, k in zip(self.layers, keys)]

    def apply(self, params, x, *, train: bool = False, key=None):
        for i, (l, p) in enumerate(zip(self.layers, params)):
            if isinstance(l, Dropout) and train and l.p > 0.0 and key is None:
                raise ValueError(
                    "Sequential contains Dropout: apply(train=True) requires a "
                    "PRNG key (use make_train_step(..., with_rng=True))"
                )
            if key is not None:
                key, sub = jax.random.split(key)
                x = l.apply(p, x, train=train, key=sub)
            else:
                x = l.apply(p, x, train=train)
        return x
