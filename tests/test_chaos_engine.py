"""Chaos campaign engine (ISSUE 20): deterministic fault-space sweeps,
invariant oracles, auto-shrunk reproducers.

Fast tier (unmarked): the schedule generator's determinism and
survivable envelope, env/token/CHAOS-REPRO round-trips, the shrinker's
algebra against synthetic run functions, the campaign journal's
crash-durability contract, verdict-table rendering, the read-side hooks
the oracles consume (``supervisor.parse_failure``,
``scheduler.execution_witness``, ``postmortem.verdict_rank``), the
declarative scenario specs, and ONE real single-schedule engine run.

Chaos tier (``slow``/``chaos``-marked): real multi-schedule campaigns —
the same-seed identical-verdict-table acceptance — and the known-bad
schedule's end-to-end shrink to a minimal reproducer whose
``CHAOS-REPRO`` line replays to the same failure.
"""

import copy
import importlib.util
import json
import os
import re
import types

import pytest

from heat_tpu.chaos import engine, scenarios, shrink
from heat_tpu.chaos import schedule as sched_mod
from heat_tpu.parallel import scheduler as S
from heat_tpu.parallel import supervisor as sup_mod
from heat_tpu.utils import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ORACLE_NAMES = (
    "workload_completed", "no_lost_jobs", "replay_determinism",
    "exactly_once", "counters_reconcile", "trace_continuity",
    "mem_drained", "blame",
)


def _known_bad():
    """A schedule OUTSIDE the survivable envelope: ``fail=-1`` never
    heals, so the serve workload's journal writes fail forever and the
    run must break an oracle — the shrinker's canonical prey."""
    return {
        "seed": 0, "index": 0, "workload": "serve", "ranks": 2, "jobs": 9,
        "faults": [
            {"site": "io.write", "mode": "fail", "value": -1,
             "rank": 0, "generation": 0},
            {"site": "mem.alloc", "mode": "delay", "value": 0.05,
             "rank": 1, "generation": 0},
        ],
    }


class TestScheduleGenerator:
    def test_pure_function_of_seed_and_index(self):
        a = sched_mod.generate_schedule(42, 7)
        b = sched_mod.generate_schedule(42, 7)
        assert a == b
        assert sched_mod.schedule_digest(a) == sched_mod.schedule_digest(b)
        assert a != sched_mod.generate_schedule(42, 8)
        assert a != sched_mod.generate_schedule(43, 7)

    def test_independent_of_campaign_length(self):
        # schedule i is the same whatever campaign it was drawn inside —
        # a resumed campaign re-derives the identical tail
        short = sched_mod.generate_campaign(7, 5)
        long = sched_mod.generate_campaign(7, 9)
        assert short == long[:5]

    def test_survivable_envelope(self):
        for i in range(40):
            s = sched_mod.generate_schedule(99, i)
            sched_mod.validate_schedule(s)
            assert s["workload"] in ("train", "serve", "fed")
            assert s["ranks"] == 1 if s["workload"] == "fed" else s["ranks"] in (1, 2)
            assert 6 <= s["jobs"] <= 10
            assert 1 <= len(s["faults"]) <= 3
            lethal = [f for f in s["faults"]
                      if f["mode"] in sched_mod.LETHAL_MODES]
            assert len(lethal) <= 1
            for f in s["faults"]:
                assert 0 <= f["rank"] < s["ranks"]
                if f["mode"] == "fail":
                    assert 1 <= f["value"] <= 3  # inside the retry budget
                if f["mode"] == "exit":
                    assert f["value"] >= 2  # never kills the first firing
                # benign faults ride the restarted generation iff a lethal
                # fault guarantees that restart exists
                if lethal and f["mode"] not in sched_mod.LETHAL_MODES:
                    assert f["generation"] == 1
                elif not lethal:
                    assert f["generation"] == 0

    def test_ci_seed_covers_all_fast_sites(self):
        # the CI chaos-campaign lane's pinned seed must span the whole
        # catalog (acceptance: >= 8 distinct sites; this seed hits all 10)
        hit = set()
        for i in range(50):
            for f in sched_mod.generate_schedule(20260807, i)["faults"]:
                hit.add(f["site"])
        assert hit == set(sched_mod.FAST_SITES)

    def test_validate_rejects_bad_schedules(self):
        s = _known_bad()
        bad_site = copy.deepcopy(s)
        bad_site["faults"][0]["site"] = "io.wrte"
        with pytest.raises(ValueError, match="not in faults.catalog"):
            sched_mod.validate_schedule(bad_site)
        bad_mode = copy.deepcopy(s)
        bad_mode["faults"][1]["mode"] = "exit"  # mem.alloc: fail/delay only
        with pytest.raises(ValueError, match="not legal at site"):
            sched_mod.validate_schedule(bad_mode)
        bad_workload = copy.deepcopy(s)
        bad_workload["workload"] = "mine-bitcoin"
        with pytest.raises(ValueError, match="unknown workload"):
            sched_mod.validate_schedule(bad_workload)

    def test_lethal_count(self):
        s = {
            "seed": 0, "index": 0, "workload": "train", "ranks": 2, "jobs": 6,
            "faults": [
                {"site": "proc.exit", "mode": "exit", "value": 2,
                 "rank": 0, "generation": 0},
                {"site": "comm.collective", "mode": "hang", "value": 2,
                 "rank": 1, "generation": 0},
            ],
        }
        assert sched_mod.lethal_count(s) == 3  # one exit + two wedged gens
        assert sched_mod.lethal_count(_known_bad()) == 0

    def test_env_for_round_trips_through_the_fault_grammar(self):
        s = _known_bad()
        armed = faults.parse_spec(sched_mod.env_for(s, 0, 0))
        assert set(armed) == {"io.write"} and armed["io.write"].fail == -1
        armed = faults.parse_spec(sched_mod.env_for(s, 1, 0))
        assert set(armed) == {"mem.alloc"} and armed["mem.alloc"].delay == 0.05
        assert sched_mod.env_for(s, 0, 1) == ""  # nothing armed off-schedule

    def test_token_round_trip(self):
        s = sched_mod.generate_schedule(5, 3)
        tok = sched_mod.schedule_token(s)
        assert re.fullmatch(r"[A-Za-z0-9_=-]+", tok)  # grep/paste-safe
        assert sched_mod.schedule_from_token(tok) == s

    def test_repro_line_parses_back(self):
        s = _known_bad()
        line = sched_mod.repro_line(s, "mem_drained")
        assert line.startswith("CHAOS-REPRO ")
        assert "fail=mem_drained" in line
        assert "rank0/gen0:HEAT_TPU_FAULTS=io.write:fail=-1" in line
        assert "replay='python scripts/chaoscamp.py --replay " in line
        assert sched_mod.parse_repro(line) == s
        with pytest.raises(ValueError, match="no schedule="):
            sched_mod.parse_repro("CHAOS-REPRO seed=0 fail=x")


class TestShrinkAlgebra:
    def test_candidates_fixed_order(self):
        descs = [d for d, _ in shrink.candidates(_known_bad())]
        assert descs == [
            "drop io.write:fail",
            "drop mem.alloc:delay",
            "floor mem.alloc:delay=0.02",  # fail=-1 has no floor step
            "ranks->1",
            "jobs->6",
        ]
        # a positionally-minimal schedule yields no candidates at all
        minimal = {
            "seed": 0, "index": 0, "workload": "serve", "ranks": 1, "jobs": 6,
            "faults": [{"site": "io.write", "mode": "fail", "value": 1,
                        "rank": 0, "generation": 0}],
        }
        assert shrink.candidates(minimal) == []

    def test_ranks_collapse_repins_victims(self):
        cands = dict(shrink.candidates(_known_bad()))
        assert all(f["rank"] == 0 for f in cands["ranks->1"]["faults"])

    def test_shrink_minimizes_to_the_guilty_fault(self):
        probes = []

        def run_fn(s):
            probes.append(s)
            guilty = any(f["site"] == "io.write" for f in s["faults"])
            return ["mem_drained"] if guilty else []

        minimal, fail = shrink.shrink(_known_bad(), run_fn)
        assert fail == "mem_drained"
        assert [f["site"] for f in minimal["faults"]] == ["io.write"]
        assert minimal["ranks"] == 1 and minimal["jobs"] == 6
        assert len(probes) <= 40

    def test_shrink_never_chases_a_different_oracle(self):
        # dropping either fault changes (or heals) the failure — only the
        # trigger floor / topology candidates keep failing the SAME oracle,
        # so both faults must survive shrinking
        def run_fn(s):
            sites = {f["site"] for f in s["faults"]}
            if sites == {"io.write", "mem.alloc"}:
                return ["no_lost_jobs"]
            if sites == {"io.write"}:
                return ["blame"]  # a different bug: must not be chased
            return []

        minimal, fail = shrink.shrink(_known_bad(), run_fn)
        assert fail == "no_lost_jobs"
        assert len(minimal["faults"]) == 2
        assert minimal["ranks"] == 1 and minimal["jobs"] == 6

    def test_shrink_refuses_non_failing_original(self):
        with pytest.raises(ValueError, match="does not fail"):
            shrink.shrink(_known_bad(), lambda s: [])

    def test_shrink_refuses_flaky_minimum(self):
        # positionally minimal already (no candidates): the probe fails
        # once, then passes on re-confirmation — a lying reproducer
        minimal = {
            "seed": 0, "index": 0, "workload": "serve", "ranks": 1, "jobs": 6,
            "faults": [{"site": "io.write", "mode": "fail", "value": 1,
                        "rank": 0, "generation": 0}],
        }
        calls = [0]

        def flaky(s):
            calls[0] += 1
            return ["no_lost_jobs"] if calls[0] == 1 else []

        with pytest.raises(ValueError, match="flaky"):
            shrink.shrink(minimal, flaky)


class TestCampaignJournal:
    def test_header_append_replay(self, tmp_path):
        p = str(tmp_path / "campaign.jsonl")
        j = engine.CampaignJournal(p, seed=11, count=2, tier="fast")
        j.append({"type": "verdict", "index": 0, "ok": True})
        j.append({"type": "repro", "index": 1, "fail": "blame", "line": "x"})
        j.close()
        with open(p) as fh:
            head = json.loads(fh.readline())
        assert head == {"type": "meta", "schema": 1, "seed": 11,
                        "count": 2, "tier": "fast"}
        state = engine.CampaignJournal.replay(p)
        assert state["meta"]["seed"] == 11
        assert set(state["verdicts"]) == {0}
        assert [r["fail"] for r in state["repros"]] == ["blame"]

    def test_torn_tail_tolerated(self, tmp_path):
        p = str(tmp_path / "campaign.jsonl")
        j = engine.CampaignJournal(p, seed=11, count=2, tier="fast")
        j.append({"type": "verdict", "index": 0, "ok": True})
        j.close()
        with open(p, "a") as fh:
            fh.write('{"type": "verdict", "index": 1')  # crash mid-append
        state = engine.CampaignJournal.replay(p)
        assert set(state["verdicts"]) == {0}

    def test_resume_refuses_campaign_mismatch(self, tmp_path):
        p = str(tmp_path / "campaign.jsonl")
        j = engine.CampaignJournal(p, seed=11, count=2, tier="fast")
        j.append({"type": "verdict", "index": 0, "ok": True})
        j.close()
        same = engine.CampaignJournal(p, seed=11, count=5, tier="fast")
        assert set(same.resume()) == {0}  # count may grow; identity may not
        same.close()
        other = engine.CampaignJournal(p, seed=12, count=2, tier="fast")
        with pytest.raises(ValueError, match="refusing to mix campaigns"):
            other.resume()
        other.close()


class TestVerdictTable:
    def test_deterministic_rendering_and_summary(self):
        rows = [
            {"index": 1, "workload": "serve", "ranks": 2, "jobs": 9,
             "faults": ["io.write:fail=-1@r0g0"], "ok": False,
             "fails": ["no_lost_jobs"]},
            {"index": 0, "workload": "train", "ranks": 1, "jobs": 6,
             "faults": ["proc.exit:exit=2@r0g0"], "ok": True, "fails": []},
        ]
        t1 = engine.verdict_table(rows)
        t2 = engine.verdict_table(list(reversed(rows)))  # order-insensitive
        assert t1 == t2
        lines = t1.splitlines()
        assert lines[0].split() == ["idx", "workload", "r", "jobs",
                                    "faults", "verdict"]
        assert lines[2].startswith("0")  # sorted by index
        assert "FAIL:no_lost_jobs" in t1
        assert lines[-1] == "CHAOS-CAMPAIGN schedules=2 ok=1 fail=1"


class TestReadSideHooks:
    def test_parse_failure_died(self):
        got = sup_mod.parse_failure(
            "epoch 1: rank 0 died with exit code -9 (signal 9)"
        )
        assert got == {"epoch": 1, "rank": 0, "kind": "died", "code": -9}

    def test_parse_failure_stale(self):
        got = sup_mod.parse_failure(
            "epoch 0: rank 1 heartbeat stale (2.6s > 2.5s) — hung or wedged"
        )
        assert got == {"epoch": 0, "rank": 1, "kind": "stale", "age": 2.6}

    def test_parse_failure_rankless_shapes_are_none(self):
        assert sup_mod.parse_failure("epoch 2: generation deadline") is None
        assert sup_mod.parse_failure("") is None

    def test_execution_witness(self, tmp_path):
        p = str(tmp_path / "journal.jsonl")
        j0 = S.JobJournal(p, epoch=0)
        j0.append({"type": S.SUBMITTED, "id": "a", "kind": "matmul"})
        j0.append({"type": S.DISPATCHED, "id": "a"})
        j1 = S.JobJournal(p, epoch=1)  # the restarted generation
        j1.append({"type": S.DISPATCHED, "id": "a"})
        j1.append({"type": S.DONE, "id": "a", "result": 1})
        j1.append({"type": S.SUBMITTED, "id": "b", "kind": "matmul"})
        w = S.execution_witness(S.replay_journal(p))
        assert w["a"] == {"dispatch_epochs": [0, 1], "first_done_epoch": 1}
        assert w["b"] == {"dispatch_epochs": [], "first_done_epoch": None}

    def test_postmortem_verdict_rank(self):
        spec = importlib.util.spec_from_file_location(
            "pm_chaos_hooks", os.path.join(REPO, "scripts", "postmortem.py")
        )
        pm = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(pm)
        assert pm.verdict_rank(
            {"verdict": "straggler", "straggler": {"rank": 1}}) == 1
        assert pm.verdict_rank({"verdict": "oom", "oom": {"rank": 2}}) == 2
        assert pm.verdict_rank(
            {"verdict": "desync", "deviating_ranks": [0]}) == 0
        # a desync blaming several ranks names no single victim
        assert pm.verdict_rank(
            {"verdict": "desync", "deviating_ranks": [0, 2]}) is None
        assert pm.verdict_rank({"verdict": "inconclusive"}) is None


class TestScenarioSpecs:
    def test_all_five_legacy_scenarios_declared(self):
        assert set(scenarios.SCENARIOS) == {
            "kill-resume-train",
            "serve-sigkill-mid-queue",
            "hang-straggler-verdict",
            "desync-minority-verdict",
            "fed-world-kill",
        }

    def test_specs_well_formed(self):
        for name, spec in scenarios.SCENARIOS.items():
            assert spec["mode"] in ("train", "serve", "fed", "postmortem")
            assert spec["expect_rc"] in ("zero", "nonzero")
            assert spec["n_proc"] >= 1 and spec["devs_per_proc"] >= 1
            for pat in spec.get("expect_re", ()):
                re.compile(pat)
            for capture, template in spec.get("derived", ()):
                assert re.compile(capture).groups >= 1
                assert "{0}" in template

    def test_unknown_scenario_named_loudly(self):
        with pytest.raises(KeyError, match="kill-resume-train"):
            scenarios.scenario("no-such-scenario")

    def test_check_scenario_clause_engine(self):
        # hang-straggler: expect_rc=nonzero, so the clause engine judges a
        # synthetic transcript without touching the dryrun launcher
        ok_out = "\n".join([
            "epoch 0: rank 1 heartbeat stale (26.0s > 25.0s) — hung or "
            "wedged (stuck at seq 7 resplit)",
            "[1] PM-HANG expect_seq=7",
            "SUPERVISOR GAVE UP",
            "POSTMORTEM epoch=0 verdict=straggler rank=1 seq=7 op=resplit",
            "CRITICAL-PATH kind=collective rank=1 op=resplit seq=7",
            "TRACE-EXPORT events=36 ranks=2 out=/tmp/x/trace.json",
        ])
        proc = types.SimpleNamespace(returncode=1, stdout=ok_out)
        assert scenarios.check_scenario("hang-straggler-verdict", proc) == []
        # the post-mortem names the WRONG seq: the derived clause breaks
        wrong = proc.stdout.replace("verdict=straggler rank=1 seq=7",
                                    "verdict=straggler rank=1 seq=9")
        bad = scenarios.check_scenario(
            "hang-straggler-verdict",
            types.SimpleNamespace(returncode=1, stdout=wrong),
        )
        assert any("derived assertion missing" in b for b in bad)
        # a zero rc on a must-fail scenario is itself a violation
        bad = scenarios.check_scenario(
            "hang-straggler-verdict",
            types.SimpleNamespace(returncode=0, stdout=ok_out),
        )
        assert any("expected nonzero rc" in b for b in bad)


class TestEngineSingleRun:
    def test_benign_schedule_passes_every_oracle(self, tmp_path):
        """One REAL supervised run in the quick lane: a transient
        ``io.write`` fault inside the retry budget must pass all eight
        oracles (the campaign-scale sweeps live in the chaos lane)."""
        s = {
            "seed": 1, "index": 0, "workload": "train", "ranks": 1, "jobs": 6,
            "faults": [{"site": "io.write", "mode": "fail", "value": 1,
                        "rank": 0, "generation": 0}],
        }
        v = engine.run_schedule(s, str(tmp_path / "run"), keep=True)
        assert v["fails"] == [], v["oracles"]
        assert v["ok"] is True
        assert set(v["oracles"]) == set(ORACLE_NAMES)
        assert v["sup"]["ok"] is True and v["sup"]["restarts"] == 0
        assert v["digest"] == sched_mod.schedule_digest(s)
        assert os.path.isdir(v["run_dir"])  # keep=True preserves evidence


@pytest.mark.chaos
@pytest.mark.slow
class TestCampaignE2E:
    def test_same_seed_campaigns_render_identical_tables(self, tmp_path):
        logs = []
        r1 = engine.run_campaign(42, 3, str(tmp_path / "c1"),
                                 log=logs.append)
        r2 = engine.run_campaign(42, 3, str(tmp_path / "c2"),
                                 log=logs.append)
        assert [r["ok"] for r in r1["rows"]] == [True, True, True]
        assert r1["table"] == r2["table"]  # THE determinism acceptance
        assert r1["table"].endswith("CHAOS-CAMPAIGN schedules=3 ok=3 fail=0")
        assert sum(1 for ln in logs if ln.startswith("CHAOS-RUN ")) == 6
        # the journal is the campaign's durable truth
        state = engine.CampaignJournal.replay(
            str(tmp_path / "c1" / "campaign.jsonl"))
        assert set(state["verdicts"]) == {0, 1, 2}
        # resuming replays the journal instead of re-running anything
        r3 = engine.run_campaign(42, 3, str(tmp_path / "c1"), resume=True,
                                 log=logs.append)
        assert r3["table"] == r1["table"]

    def test_known_bad_shrinks_and_replays_to_same_failure(self, tmp_path):
        s = _known_bad()
        first = engine.run_schedule(s, str(tmp_path / "orig"), keep=False)
        assert not first["ok"], "known-bad schedule unexpectedly passed"
        target = first["fails"][0]

        n = [0]

        def probe(cand):
            n[0] += 1
            d = str(tmp_path / f"probe{n[0]:03d}")
            return list(engine.run_schedule(cand, d, keep=False)["fails"])

        minimal, fail = shrink.shrink(s, probe)
        assert fail == target
        assert len(minimal["faults"]) <= 2  # the acceptance bar
        assert minimal["ranks"] == 1 and minimal["jobs"] == 6
        line = sched_mod.repro_line(minimal, fail)
        # the greppable line alone reproduces the failure
        replayed = sched_mod.parse_repro(line)
        v = engine.run_schedule(replayed, str(tmp_path / "replay"),
                                keep=False)
        assert v["fails"] and v["fails"][0] == target
