"""Federated multi-world serving (ISSUE 17 tentpole).

Covers the four federation capabilities plus their satellites:

- **Memory-aware admission**: :class:`AdmissionPredictor` unit tests
  against recorded per-kind peak history (the acceptance criterion), the
  full admission matrix (no predictor / unobserved kind / no healthy
  world / uncapped world / infeasible shed), and the shed surfacing as a
  synchronous structured ``JobRejected``.
- **Journal-before-mutation**: a failed federation-journal append
  propagates with NOTHING mutated (the HT112 contract, fault-injected).
- **Health state machine + work stealing**: verdict-driven transitions
  (forward-only), world loss requeueing every non-terminal job.
- **Deterministic recovery** (satellite): two replicas replaying the
  same federation journal derive identical requeue sets under the
  epoch-scoped anchor discipline.
- **Elastic resize**: the pure :func:`resize_target` formula and the
  Supervisor's relaunch-boundary resize hook.
- **HTTP ingress** (tentpole edge): POST /submit + GET /status|/result
  over a real localhost socket — 200/400/404/413/429/503 paths, the
  /healthz federation gate and the ``fed_worlds_*`` gauges.
- **Standalone-load contract**: federation.py serves a full federate →
  steal → recover cycle with jax AND numpy imports blocked.
"""

import json
import os
import subprocess
import sys
import urllib.error
import urllib.request

import pytest

from heat_tpu.parallel import federation as F
from heat_tpu.parallel import scheduler as S
from heat_tpu.utils import faults, monitor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_global_state():
    monitor.clear_ingress()
    monitor.clear_federation_source()
    F.reset_counters()
    yield
    monitor.clear_ingress()
    monitor.clear_federation_source()
    monitor.disable()
    F.reset_counters()


def _job(jid="a", kind="matmul", **kw):
    return F.Job(jid, kind, **kw)


def _fed(tmp_path, name="fed.jsonl", **kw):
    return F.Federation(str(tmp_path / name), **kw)


def _req(url, payload=None, timeout=10):
    """HTTP helper that treats error statuses as answers: returns
    (status, parsed-JSON body or raw text)."""
    data = None
    if payload is not None:
        data = payload if isinstance(payload, bytes) else json.dumps(payload).encode()
    req = urllib.request.Request(url, data=data)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            status, raw = resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        status, raw = e.code, e.read().decode()
    try:
        return status, json.loads(raw)
    except ValueError:
        return status, raw


# ---------------------------------------------------------------------- #
# AdmissionPredictor: per-kind peak history → footprint prediction
# ---------------------------------------------------------------------- #
class TestAdmissionPredictor:
    def test_predict_from_recorded_peak_history(self, tmp_path):
        p = F.AdmissionPredictor(str(tmp_path / "peaks.json"), safety=1.5)
        p.observe("matmul", 1000)
        p.observe("matmul", 400)  # smaller: the per-kind MAX is kept
        p.observe("solve", 200)
        assert p.predict("matmul") == 1500  # ceil(1000 * 1.5)
        assert p.predict("solve") == 300

    def test_unobserved_kind_predicts_none(self):
        assert F.AdmissionPredictor(safety=2.0).predict("kmeans") is None

    def test_history_persists_and_reloads(self, tmp_path):
        path = str(tmp_path / "peaks.json")
        F.AdmissionPredictor(path).observe("nn_forward", 4096)
        reloaded = F.AdmissionPredictor(path, safety=1.0)
        assert reloaded.predict("nn_forward") == 4096

    def test_torn_history_is_empty_history(self, tmp_path):
        path = tmp_path / "peaks.json"
        path.write_text('{"matmul": 10')  # torn mid-write
        assert F.AdmissionPredictor(str(path)).predict("matmul") is None

    def test_non_numeric_entries_dropped_on_load(self, tmp_path):
        path = tmp_path / "peaks.json"
        path.write_text('{"matmul": "big", "solve": 64, "bad": -3}')
        p = F.AdmissionPredictor(str(path), safety=1.0)
        assert p.predict("matmul") is None
        assert p.predict("solve") == 64
        assert p.predict("bad") is None

    def test_negative_observation_ignored(self, tmp_path):
        p = F.AdmissionPredictor(str(tmp_path / "peaks.json"))
        p.observe("matmul", -5)
        assert p.predict("matmul") is None


# ---------------------------------------------------------------------- #
# memory-aware admission: the shed matrix
# ---------------------------------------------------------------------- #
class TestMemAdmission:
    def _predictor(self, peak=1 << 30, safety=1.0):
        p = F.AdmissionPredictor(safety=safety)
        p.observe("matmul", peak)
        return p

    def test_no_predictor_admits(self, tmp_path):
        fed = _fed(tmp_path)
        fed.add_world("w0", capacity_bytes=1)
        assert fed.submit(_job()) == "a"

    def test_unobserved_kind_admits_optimistically(self, tmp_path):
        fed = _fed(tmp_path, predictor=self._predictor())
        fed.add_world("w0", capacity_bytes=1)
        fed.submit(_job(kind="kmeans"))  # no history for kmeans

    def test_no_healthy_world_admits_and_queues(self, tmp_path):
        # admission must not shed against an EMPTY roster: the queue
        # holds until worlds join (deadline sheds later, never silently)
        fed = _fed(tmp_path, predictor=self._predictor())
        assert fed.submit(_job()) == "a"

    def test_uncapped_world_fits_anything(self, tmp_path):
        fed = _fed(tmp_path, predictor=self._predictor())
        fed.add_world("w0")  # no capacity configured → unbounded
        fed.add_world("w1", capacity_bytes=1)
        fed.submit(_job())

    def test_infeasible_job_shed_at_the_edge(self, tmp_path):
        fed = _fed(tmp_path, predictor=self._predictor(peak=1 << 30))
        fed.add_world("w0", capacity_bytes=1 << 20)
        with pytest.raises(F.JobRejected) as ei:
            fed.submit(_job())
        assert ei.value.reason == F.MEM_INFEASIBLE
        assert ei.value.job_id == "a" and "headroom" in ei.value.detail
        # the shed is terminal state, journaled, and ingress-visible
        assert fed._jobs["a"].state == F.SHED
        assert fed.ingress_status("a")["reason"] == F.MEM_INFEASIBLE
        summary = F.fed_summary(F.replay_federation(fed.journal.path))
        assert summary["shed"] == 1 and summary["lost"] == 0

    def test_quarantined_world_headroom_does_not_admit(self, tmp_path):
        fed = _fed(tmp_path, predictor=self._predictor(peak=1 << 30))
        fed.add_world("big", capacity_bytes=1 << 40)
        fed.add_world("small", capacity_bytes=1 << 20)
        fed.world_lost("big", "killed")
        with pytest.raises(F.JobRejected):
            fed.submit(_job())

    def test_beacon_live_bytes_shrink_headroom(self, tmp_path):
        hb = tmp_path / "hb"
        hb.mkdir()
        (hb / "rank0.json").write_text(json.dumps({"seq": 3, "mem_live": 900}))
        (hb / "rank1.json").write_text(json.dumps({"seq": 3, "mem_live": 50}))
        w = F.WorldHandle("w0", capacity_bytes=1000, heartbeat_dir=str(hb))
        assert w.live_bytes() == 950
        assert w.headroom_bytes() == 50
        fed = _fed(tmp_path, predictor=self._predictor(peak=100))
        fed.worlds["w0"] = w
        with pytest.raises(F.JobRejected) as ei:
            fed.submit(_job())
        assert ei.value.reason == F.MEM_INFEASIBLE

    def test_queue_full_sheds_before_mem_check(self, tmp_path):
        fed = _fed(tmp_path, max_queue=1)
        fed.submit(_job("a"))
        with pytest.raises(F.JobRejected) as ei:
            fed.submit(_job("b"))
        assert ei.value.reason == F.QUEUE_FULL


# ---------------------------------------------------------------------- #
# journal-before-mutation (the HT112 contract, fault-injected)
# ---------------------------------------------------------------------- #
class TestJournalFirst:
    def test_failed_append_leaves_submit_unmutated(self, tmp_path):
        fed = _fed(tmp_path)
        with faults.inject("sched.journal.write", fail=1):
            with pytest.raises(OSError):
                fed.submit(_job())
        # NOTHING mutated: no phantom job the journal never saw
        assert fed._jobs == {} and fed._queue == []
        # the retry admits cleanly — no duplicate-id complaint
        assert fed.submit(_job()) == "a"
        summary = F.fed_summary(F.replay_federation(fed.journal.path))
        assert summary["jobs"] == 1

    def test_failed_append_leaves_shed_unmutated(self, tmp_path):
        p = F.AdmissionPredictor()
        p.observe("matmul", 1 << 30)
        fed = _fed(tmp_path, predictor=p)
        fed.add_world("w0", capacity_bytes=1)
        with faults.inject("sched.journal.write", fail=1):
            with pytest.raises(OSError):
                fed.submit(_job())
        assert fed._jobs == {}  # the shed itself was never recorded → not taken

    def test_failed_append_aborts_world_transition(self, tmp_path):
        fed = _fed(tmp_path)
        fed.add_world("w0")
        with faults.inject("sched.journal.write", fail=1):
            with pytest.raises(OSError):
                fed.world_lost("w0", "killed")
        assert fed.worlds["w0"].state == F.HEALTHY


# ---------------------------------------------------------------------- #
# world health state machine
# ---------------------------------------------------------------------- #
class TestWorldStateMachine:
    def test_one_straggler_verdict_keeps_world_healthy(self, tmp_path):
        fed = _fed(tmp_path, straggler_drain_after=2)
        fed.add_world("w0")
        assert fed.note_verdict("w0", "straggler") == F.HEALTHY

    def test_repeated_straggler_drains(self, tmp_path):
        fed = _fed(tmp_path, straggler_drain_after=2)
        fed.add_world("w0")
        fed.note_verdict("w0", "straggler")
        assert fed.note_verdict("w0", {"verdict": "straggler"}) == F.DRAINING
        assert "straggler" in fed.worlds["w0"].state_reason

    def test_interleaved_verdicts_reset_the_streak(self, tmp_path):
        fed = _fed(tmp_path, straggler_drain_after=2)
        fed.add_world("w0")
        fed.note_verdict("w0", "straggler")
        fed.note_verdict("w0", "inconclusive")
        assert fed.note_verdict("w0", "straggler") == F.HEALTHY

    def test_oom_quarantines_and_steals(self, tmp_path):
        fed = _fed(tmp_path)
        fed.add_world("w0")
        fed.submit(_job())
        fed.assign()
        assert fed.note_verdict("w0", {"verdict": "oom"}) == F.QUARANTINED
        assert fed._jobs["a"].state == F.SUBMITTED  # stolen back
        assert fed.worlds["w0"].assigned == set()

    def test_transitions_only_move_forward(self, tmp_path):
        fed = _fed(tmp_path, straggler_drain_after=1)
        fed.add_world("w0")
        fed.note_verdict("w0", "oom")
        # a later straggler streak cannot demote quarantined → draining
        fed.note_verdict("w0", "straggler")
        assert fed.worlds["w0"].state == F.QUARANTINED

    def test_retire_steals_leftovers(self, tmp_path):
        fed = _fed(tmp_path)
        fed.add_world("w0")
        fed.submit(_job())
        fed.assign()
        fed.retire("w0")
        assert fed.worlds["w0"].state == F.RETIRED
        assert fed._jobs["a"].state == F.SUBMITTED

    def test_duplicate_world_rejected(self, tmp_path):
        fed = _fed(tmp_path)
        fed.add_world("w0")
        with pytest.raises(ValueError, match="duplicate world"):
            fed.add_world("w0")


# ---------------------------------------------------------------------- #
# work-stealing dispatch + zero-loss world loss
# ---------------------------------------------------------------------- #
class TestDispatchAndStealing:
    def test_least_loaded_world_steals_next_job(self, tmp_path):
        fed = _fed(tmp_path)
        fed.add_world("w0", n_ranks=1)
        fed.add_world("w1", n_ranks=1)
        for i in range(4):
            fed.submit(_job(f"j{i}"))
        out = fed.assign()
        assert sorted(len(v) for v in out.values()) == [2, 2]

    def test_rank_weighted_load(self, tmp_path):
        # a 3-rank world absorbs 3× the jobs of a 1-rank world
        fed = _fed(tmp_path)
        fed.add_world("big", n_ranks=3)
        fed.add_world("small", n_ranks=1)
        for i in range(8):
            fed.submit(_job(f"j{i}"))
        out = fed.assign()
        assert len(out["big"]) == 6 and len(out["small"]) == 2

    def test_priority_orders_assignment(self, tmp_path):
        fed = _fed(tmp_path)
        fed.add_world("w0")
        fed.submit(_job("low", priority=0))
        fed.submit(_job("high", priority=5))
        out = fed.assign()
        assert [j.job_id for j in out["w0"]] == ["high", "low"]

    def test_draining_world_gets_nothing_new(self, tmp_path):
        fed = _fed(tmp_path, straggler_drain_after=1)
        fed.add_world("w0")
        fed.add_world("w1")
        fed.note_verdict("w1", "straggler")
        fed.submit(_job())
        out = fed.assign()
        assert list(out) == ["w0"]

    def test_no_healthy_world_holds_the_queue(self, tmp_path):
        fed = _fed(tmp_path)
        fed.add_world("w0")
        fed.world_lost("w0")
        fed.submit(_job())
        assert fed.assign() == {}
        assert len(fed._queue) == 1  # held, not dropped

    def test_world_lost_requeues_then_reassigns(self, tmp_path):
        fed = _fed(tmp_path)
        fed.add_world("w0")
        fed.add_world("w1")
        for i in range(4):
            fed.submit(_job(f"j{i}"))
        fed.assign()
        stolen = fed.world_lost("w1", "SIGKILL")
        assert stolen == 2
        out = fed.assign()
        assert list(out) == ["w0"] and len(out["w0"]) == 2
        summary = F.fed_summary(F.replay_federation(fed.journal.path))
        assert summary["stolen"] == 2 and summary["lost"] == 4  # none terminal yet

    def test_in_process_submit_hook_receives_jobs(self, tmp_path):
        got = []
        fed = _fed(tmp_path)
        fed.add_world("w0", submit=got.append)
        fed.submit(_job())
        fed.assign()
        assert [j.job_id for j in got] == ["a"]

    def test_in_process_world_does_not_alias_federation_state(self, tmp_path):
        # an in-process Scheduler mutates the Job it was handed; if that
        # were the federation's own object, state would flip to DONE with
        # no federation journal record and replay would count it lost
        fed = _fed(tmp_path)
        wj = str(tmp_path / "w0.jsonl")
        sch = S.Scheduler(
            lambda jobs: [{"digest": 7.0} for _ in jobs], journal=wj, max_queue=4
        )
        fed.add_world("w0", journal_path=wj, submit=sch.submit)
        fed.submit(_job())
        fed.assign()
        sch.run()
        assert fed._jobs["a"].state == F.ASSIGNED  # not mutated by aliasing
        assert fed.reconcile_world_journal("w0") == {"done": 1, "failed": 0}
        assert fed.ingress_result("a")["result"] == {"digest": 7.0}
        summary = F.fed_summary(F.replay_federation(fed.journal.path))
        assert summary["done"] == 1 and summary["lost"] == 0

    def test_reconcile_folds_world_journal_up(self, tmp_path):
        # a world scheduler runs the assigned job; reconciliation folds
        # its DONE record (with result) into the federation journal
        fed = _fed(tmp_path)
        wj = str(tmp_path / "w0.jsonl")
        fed.add_world("w0", journal_path=wj)
        fed.submit(_job())
        fed.assign()
        sch = S.Scheduler(
            lambda jobs: [{"digest": 7.0} for _ in jobs], journal=wj, max_queue=4
        )
        sch.submit(_job())
        sch.run()
        got = fed.reconcile_world_journal("w0")
        assert got == {"done": 1, "failed": 0}
        assert fed._jobs["a"].state == F.DONE
        assert fed.ingress_result("a")["result"] == {"digest": 7.0}
        summary = F.fed_summary(F.replay_federation(fed.journal.path))
        assert summary["done"] == 1 and summary["lost"] == 0


# ---------------------------------------------------------------------- #
# deterministic recovery (satellite: two replicas, identical requeues)
# ---------------------------------------------------------------------- #
class TestDeterministicRecovery:
    def _crashed_fed_journal(self, tmp_path):
        fed = _fed(tmp_path)
        fed.add_world("w0")
        fed.add_world("w1")
        fed.submit(_job("slow", priority=0, deadline_s=100.0))
        fed.submit(_job("urgent", priority=9, deadline_s=50.0))
        fed.submit(_job("mid", priority=3))
        fed.assign()
        # one job finished before the crash; the rest are in flight
        fed.journal.append({"type": F.DONE, "id": "mid", "world": "w0",
                            "exec_s": 0.1, "result": {"digest": 1.0}})
        return fed.journal.path

    def test_two_replicas_derive_identical_requeue_sets(self, tmp_path):
        path = self._crashed_fed_journal(tmp_path)
        replays = [F.replay_federation(path) for _ in range(2)]
        sets = [F.requeue_set(r, epoch=1) for r in replays]
        assert sets[0] == sets[1]
        assert [v["id"] for v in sets[0]] == ["urgent", "slow"]  # priority desc
        assert all("deadline_remaining" in v for v in sets[0])

    def test_two_federations_recover_identically(self, tmp_path):
        path = self._crashed_fed_journal(tmp_path)
        feds = [
            F.Federation(str(tmp_path / f"replica{i}.jsonl")) for i in range(2)
        ]
        ns = [f.recover(path, epoch=1) for f in feds]
        assert ns == [2, 2]
        q0, q1 = ([j.job_id for j in f._queue] for f in feds)
        assert q0 == q1 == ["urgent", "slow"]
        d0, d1 = ([j.deadline_s for j in f._queue] for f in feds)
        assert d0 == d1
        # the DONE job is visible (result served), never requeued
        for f in feds:
            assert f.ingress_result("mid")["result"] == {"digest": 1.0}

    def test_epoch_anchor_scopes_deadline_charging(self, tmp_path):
        path = self._crashed_fed_journal(tmp_path)
        replay = F.replay_federation(path)
        # epoch 0: no records are strictly-before → no anchor → uncharged
        uncharged = F.requeue_set(replay, epoch=0)
        assert [v["deadline_remaining"] for v in uncharged] == [50.0, 100.0]
        charged = F.requeue_set(replay, epoch=1)
        for v in charged:
            assert v["deadline_remaining"] <= {"urgent": 50.0, "slow": 100.0}[v["id"]]

    def test_recover_restores_ingress_seq(self, tmp_path):
        fed = _fed(tmp_path)
        jid = fed.ingress_submit({"kind": "matmul"})["id"]
        assert jid == "req000001"
        fed2 = F.Federation(str(tmp_path / "r2.jsonl"))
        fed2.recover(fed.journal.path, epoch=1)
        assert fed2.ingress_submit({"kind": "matmul"})["id"] == "req000002"


# ---------------------------------------------------------------------- #
# replay / summary / attestation
# ---------------------------------------------------------------------- #
class TestReplayAndAttestation:
    def test_headerless_journal_refused(self, tmp_path):
        path = tmp_path / "j.jsonl"
        path.write_text('{"type": "submitted", "id": "a"}\n')
        with pytest.raises(S.JournalSchemaError, match="before any"):
            F.replay_federation(str(path))

    def test_newer_schema_refused(self, tmp_path):
        path = tmp_path / "j.jsonl"
        path.write_text(json.dumps({"type": "meta", "schema": 99}) + "\n")
        with pytest.raises(S.JournalSchemaError, match="schema 99"):
            F.replay_federation(str(path))

    def test_torn_tail_tolerated(self, tmp_path):
        fed = _fed(tmp_path)
        fed.submit(_job())
        with open(fed.journal.path, "a") as fh:
            fh.write('{"type": "done", "id": "a", "wor')  # torn mid-crash
        replay = F.replay_federation(fed.journal.path)
        assert replay["torn"] == 1
        assert replay["jobs"]["a"]["state"] == F.SUBMITTED  # torn DONE never lands

    def test_torn_final_record_requeued_on_recovery(self, tmp_path):
        """A crash mid-append of a job's TERMINAL record is the canonical
        torn-tail: the half-written DONE must not land, and recovery must
        requeue the job — a torn terminal treated as landed would be a
        silently lost answer (the exact failure class the chaos
        ``no_lost_jobs`` oracle exists to catch)."""
        fed = _fed(tmp_path)
        fed.add_world("w0")
        fed.submit(_job("keep"))
        fed.submit(_job("torn"))
        fed.assign()
        # 'keep' finishes cleanly; 'torn' crashes mid-terminal-append
        fed.journal.append({"type": F.DONE, "id": "keep", "world": "w0",
                            "exec_s": 0.1, "result": {"digest": 1.0}})
        with open(fed.journal.path, "a") as fh:
            fh.write('{"type": "done", "id": "torn", "wor')
        replay = F.replay_federation(fed.journal.path)
        assert replay["torn"] == 1
        assert replay["jobs"]["torn"]["state"] == F.ASSIGNED
        fed2 = F.Federation(str(tmp_path / "r2.jsonl"))
        n = fed2.recover(fed.journal.path, epoch=1)
        assert n == 1
        assert [j.job_id for j in fed2._queue] == ["torn"]
        # the cleanly journaled DONE is served, never re-executed
        assert fed2.ingress_result("keep")["result"] == {"digest": 1.0}

    def test_torn_header_refused_loudly(self, tmp_path):
        """A journal whose meta header line itself is torn must REFUSE to
        replay (JournalSchemaError), not silently recover zero jobs: the
        header is written via tmp+rename, so a torn header means file
        corruption outside the append protocol — guessing would risk
        resurrecting a journal whose schema can no longer be verified."""
        path = tmp_path / "j.jsonl"
        path.write_text(
            '{"type": "meta", "sch\n'
            + json.dumps({"type": F.SUBMITTED, "id": "a", "kind": "matmul"})
            + "\n"
        )
        with pytest.raises(S.JournalSchemaError, match="before any"):
            F.replay_federation(str(path))
        fed = _fed(tmp_path)
        with pytest.raises(S.JournalSchemaError):
            fed.recover(str(path), epoch=1)

    def test_torn_world_journal_terminal_not_folded(self, tmp_path):
        """``reconcile_world_journal`` over a world journal whose final
        DONE record is torn must fold NOTHING for that job (the terminal
        never durably landed) — and folding again after the world's
        journal heals must remain exactly-once."""
        fed = _fed(tmp_path)
        fed.add_world("w0")
        fed.submit(_job("j1"))
        fed.assign()
        wj = tmp_path / "w0.jsonl"
        sched_j = S.JobJournal(str(wj))
        sched_j.append({"type": S.SUBMITTED, "id": "j1", "kind": "matmul"})
        with open(wj, "a") as fh:
            fh.write('{"type": "done", "id": "j1"')  # torn terminal
        assert fed.reconcile_world_journal("w0", path=str(wj)) == {
            "done": 0, "failed": 0,
        }
        assert F.replay_federation(fed.journal.path)["jobs"]["j1"]["state"] == (
            F.ASSIGNED
        )
        # the world heals: a restarted generation re-opens the journal
        # (its fresh header line absorbs the torn tail) and lands a
        # complete terminal, which folds exactly once
        sched_j2 = S.JobJournal(str(wj), epoch=1)
        sched_j2.append({"type": S.DONE, "id": "j1", "result": {"d": 2.0}})
        assert fed.reconcile_world_journal("w0", path=str(wj)) == {
            "done": 1, "failed": 0,
        }
        assert fed.reconcile_world_journal("w0", path=str(wj)) == {
            "done": 0, "failed": 0,
        }

    def test_attestation_line_shape(self, tmp_path):
        fed = _fed(tmp_path)
        fed.add_world("w0")
        fed.submit(_job())
        line = fed.attestation()
        assert line == ("FED worlds=1 lost=1 jobs=1 done=0 failed=0 "
                        "shed=0 stolen=0 quarantined=0")

    def test_world_roster_derivable_from_journal_alone(self, tmp_path):
        fed = _fed(tmp_path)
        fed.add_world("w0", n_ranks=2)
        fed.add_world("w1")
        fed.world_lost("w1", "killed")
        replay = F.replay_federation(fed.journal.path)
        assert set(replay["worlds"]) == {"w0", "w1"}
        assert replay["worlds"]["w0"]["ranks"] == 2
        assert replay["worlds"]["w1"]["state"] == F.QUARANTINED
        summary = F.fed_summary(replay)
        assert summary["worlds"] == 2 and summary["quarantined"] == 1


# ---------------------------------------------------------------------- #
# elastic capacity: the resize formula + the Supervisor hook
# ---------------------------------------------------------------------- #
class TestElasticResize:
    def test_resize_target_formula(self):
        assert F.resize_target(0, 4) == 1  # empty queue shrinks to the floor
        assert F.resize_target(4, 1, jobs_per_rank=4) == 1
        assert F.resize_target(5, 1, jobs_per_rank=4) == 2
        assert F.resize_target(10, 1, jobs_per_rank=4, max_ranks=2) == 2
        assert F.resize_target(3, 1, jobs_per_rank=1, min_ranks=2) == 3
        assert F.resize_target(-7, 1) == 1  # garbage depth clamps

    def test_resize_plan_splits_queue_across_healthy_worlds(self, tmp_path):
        fed = _fed(tmp_path)
        fed.add_world("w0")
        fed.add_world("dead")
        fed.world_lost("dead")
        for i in range(8):
            fed.submit(_job(f"j{i}"))
        plan = fed.resize_plan(jobs_per_rank=2, max_ranks=8)
        assert plan == {"w0": 4}  # 8 queued / 1 healthy world / 2 per rank

    def test_supervisor_applies_resize_between_generations(self, tmp_path):
        sup_mod = __import__("heat_tpu.parallel.supervisor",
                             fromlist=["Supervisor"])

        def spawn(rank, epoch, port):
            code = "import sys; sys.exit(1)" if epoch == 0 else "pass"
            return subprocess.Popen([sys.executable, "-c", code])

        sup = sup_mod.Supervisor(
            spawn, 1, heartbeat_dir=str(tmp_path / "hb"),
            restart_budget=1, poll_interval=0.05, grace=1.0,
            resize=lambda cur: cur + 1,
        )
        res = sup.run()
        assert res.ok
        assert sup.n_ranks == 2
        assert sup.counters["health.resizes"] == 1

    def test_broken_resize_hook_does_not_kill_supervision(self, tmp_path):
        sup_mod = __import__("heat_tpu.parallel.supervisor",
                             fromlist=["Supervisor"])

        def spawn(rank, epoch, port):
            code = "import sys; sys.exit(1)" if epoch == 0 else "pass"
            return subprocess.Popen([sys.executable, "-c", code])

        def resize(cur):
            raise RuntimeError("resize oracle crashed")

        sup = sup_mod.Supervisor(
            spawn, 1, heartbeat_dir=str(tmp_path / "hb"),
            restart_budget=1, poll_interval=0.05, grace=1.0, resize=resize,
        )
        res = sup.run()
        assert res.ok and sup.n_ranks == 1


# ---------------------------------------------------------------------- #
# HTTP ingress: the monitor edge over a real localhost socket
# ---------------------------------------------------------------------- #
class TestIngressHTTP:
    def _armed(self, tmp_path, **fed_kw):
        fed = _fed(tmp_path, **fed_kw)
        mon = monitor.Monitor(port=0, heartbeat_dir=str(tmp_path / "hb"))
        monitor.set_ingress(fed)
        host, port = mon.addr
        return fed, mon, f"http://{host}:{port}"

    def test_submit_status_result_roundtrip(self, tmp_path):
        fed, mon, base = self._armed(tmp_path)
        try:
            fed.add_world("w0")
            status, out = _req(f"{base}/submit",
                               {"kind": "matmul", "tenant": "acme",
                                "payload": {"n": 8}})
            assert status == 200
            jid, tid = out["id"], out["trace_id"]
            assert out["state"] == F.SUBMITTED and len(tid) == 16
            status, view = _req(f"{base}/status/{jid}")
            assert status == 200
            assert view["state"] == F.SUBMITTED and view["trace_id"] == tid
            status, res = _req(f"{base}/result/{jid}")
            assert status == 200 and "detail" in res  # pending, not terminal
        finally:
            mon.close()

    def test_mem_infeasible_shed_is_synchronous_429(self, tmp_path):
        p = F.AdmissionPredictor()
        p.observe("giant", 1 << 40)
        fed, mon, base = self._armed(tmp_path, predictor=p)
        try:
            fed.add_world("w0", capacity_bytes=1 << 20)
            status, body = _req(f"{base}/submit",
                                {"id": "g1", "kind": "giant", "tenant": "acme"})
            assert status == 429
            assert body["error"] == F.MEM_INFEASIBLE
            assert body["id"] == "g1" and body["tenant"] == "acme"
            assert "headroom" in body["detail"]
            # the shed is journaled: the attestation counts it, loses nothing
            assert "shed=1" in fed.attestation()
        finally:
            mon.close()

    def test_queue_full_is_429(self, tmp_path):
        fed, mon, base = self._armed(tmp_path, max_queue=1)
        try:
            assert _req(f"{base}/submit", {"kind": "matmul"})[0] == 200
            status, body = _req(f"{base}/submit", {"kind": "matmul"})
            assert status == 429 and body["error"] == F.QUEUE_FULL
        finally:
            mon.close()

    def test_malformed_bodies_are_400(self, tmp_path):
        fed, mon, base = self._armed(tmp_path)
        try:
            assert _req(f"{base}/submit", b"not json{")[0] == 400
            status, body = _req(f"{base}/submit", {"tenant": "acme"})
            assert status == 400 and "kind" in body["detail"]
            assert _req(f"{base}/submit", {"kind": "matmul",
                                           "payload": [1, 2]})[0] == 400
        finally:
            mon.close()

    def test_oversized_body_413_before_read(self, tmp_path):
        fed, mon, base = self._armed(tmp_path)
        try:
            req = urllib.request.Request(
                f"{base}/submit", data=b"{}",
                headers={"Content-Length": str(monitor.MAX_BODY_BYTES + 1)},
            )
            try:
                with urllib.request.urlopen(req, timeout=10) as resp:
                    status = resp.status
            except urllib.error.HTTPError as e:
                status, body = e.code, json.loads(e.read().decode())
                assert body["error"] == "payload_too_large"
            assert status == 413
        finally:
            mon.close()

    def test_unknown_job_404_and_unarmed_503(self, tmp_path):
        fed, mon, base = self._armed(tmp_path)
        try:
            status, body = _req(f"{base}/result/nope")
            assert status == 404 and body["error"] == "unknown_job"
            monitor.clear_ingress()
            assert _req(f"{base}/status/x")[0] == 503
            assert _req(f"{base}/submit", {"kind": "matmul"})[0] == 503
        finally:
            mon.close()

    def test_healthz_federation_gate_and_gauges(self, tmp_path):
        fed, mon, base = self._armed(tmp_path, straggler_drain_after=1)
        monitor.set_federation_source(fed.health_report)
        try:
            fed.add_world("w0")
            fed.add_world("w1")
            status, body = _req(f"{base}/healthz")
            assert status == 200 and body["federation"]["healthy"] == 2
            # a quarantined world is HANDLED degradation: still 200
            fed.world_lost("w1", "killed")
            status, body = _req(f"{base}/healthz")
            assert status == 200
            assert body["federation"]["quarantined"] == 1
            metrics = _req(f"{base}/metrics")[1]
            assert "fed_worlds_healthy 1" in metrics
            assert "fed_worlds_quarantined 1" in metrics
            assert "fed_queue_depth 0" in metrics
            # a DRAINING world is not ok: every non-quarantined world
            # must be healthy for the federation gate to pass
            fed.note_verdict("w0", "straggler")
            status, body = _req(f"{base}/healthz")
            assert status == 503 and body["ok"] is False
        finally:
            mon.close()

    def test_federation_registers_itself_when_monitor_loaded(self, tmp_path):
        # Federation.__init__ wires the weakref source without any caller
        # plumbing — and a discarded federation prunes at the next scrape
        fed, mon, base = self._armed(tmp_path)
        try:
            fed.add_world("w0")
            status, body = _req(f"{base}/healthz")
            assert body.get("federation", {}).get("healthy") == 1
            monitor.clear_ingress()
            del fed
            status, body = _req(f"{base}/healthz")
            assert "federation" not in body
        finally:
            mon.close()


# ---------------------------------------------------------------------- #
# standalone-load contract (stdlib-only, jax+numpy blocked)
# ---------------------------------------------------------------------- #
class TestStandaloneLoad:
    def test_federates_with_jax_and_numpy_blocked(self, tmp_path):
        """federation.py must spec-load and run a submit → assign →
        world-lost → steal → recover cycle in a process where importing
        jax or numpy raises — the federating launcher's requirement
        (same bar as supervisor.py / scheduler.py / monitor.py)."""
        code = f"""
import importlib.util, sys

class _Block:
    def find_module(self, name, path=None):
        if name in ("jax", "jaxlib", "numpy", "heat_tpu"):
            raise ImportError(f"import of {{name}} is blocked in this test")
sys.meta_path.insert(0, _Block())

spec = importlib.util.spec_from_file_location(
    "heat_federation",
    {os.path.join(REPO, "heat_tpu", "parallel", "federation.py")!r},
)
F = importlib.util.module_from_spec(spec)
sys.modules[spec.name] = F
spec.loader.exec_module(F)

fed = F.Federation({str(tmp_path / "fed.jsonl")!r})
fed.add_world("w0")
fed.add_world("w1")
for i in range(4):
    fed.submit(F.Job(f"j{{i}}", "matmul", tenant="t"))
fed.assign()
stolen = fed.world_lost("w1", "SIGKILL")
assert stolen == 2, stolen
fed.assign()

fed2 = F.Federation({str(tmp_path / "replica.jsonl")!r})
n = fed2.recover({str(tmp_path / "fed.jsonl")!r}, epoch=1)
assert n == 4, n

print(fed.attestation())
"""
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=60,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert proc.stdout.strip() == (
            "FED worlds=2 lost=4 jobs=4 done=0 failed=0 "
            "shed=0 stolen=2 quarantined=1"
        )

    def test_package_exports(self):
        import heat_tpu

        assert heat_tpu.parallel.Federation is F.Federation
        assert heat_tpu.parallel.WorldHandle is F.WorldHandle
        assert heat_tpu.parallel.AdmissionPredictor is F.AdmissionPredictor

    def test_counters_mirror_into_profiler(self, tmp_path):
        from heat_tpu.utils import profiler

        fed = _fed(tmp_path)
        fed.submit(_job())
        try:
            assert profiler.counters().get("fed.accepted") == 1
        finally:
            F.reset_counters()
