"""Distributed sparse matrices (reference: ``heat/sparse/``)."""

from .dcsr_matrix import DCSR_matrix
from .factories import sparse_csr_matrix, sparse_csc_matrix
from ._arithmetics import add, mul


def todense(sparse_matrix: DCSR_matrix):
    """Densify a distributed CSR matrix into a DNDarray (reference parity:
    ``heat.sparse.todense``)."""
    return sparse_matrix.todense()


def to_dense(sparse_matrix: DCSR_matrix):
    return sparse_matrix.todense()
