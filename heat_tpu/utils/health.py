"""Health protocol: heartbeats, deadlines, and the collective watchdog.

HeAT's MPI heritage assumes a fixed, immortal world: one hung or killed
rank deadlocks every collective forever.  This module is the *detection*
half of the elastic runtime (the *recovery* half is
``heat_tpu.parallel.supervisor``): it lets every process prove liveness
cheaply, and lets blocking collective waits fail fast instead of hanging.

Three pieces:

- **Heartbeat** — a per-process beacon file, atomically rewritten
  (tmp + rename) with a monotonic step counter, an epoch timestamp, the
  pid and the current restart epoch.  A supervisor reads *only* the file
  mtime/payload — no signal, no socket — so heartbeats survive every
  transport failure short of a dead filesystem.  Writes count under
  ``health.heartbeat.writes``.

- **Deadline** — a monotonic-clock budget with ``remaining()`` /
  ``expired()`` / ``check()``.  :func:`deadline` (also exposed as
  ``Communication.deadline``) arms one for a block via a contextvar;
  collective staging points check it and blocking waits are guarded by
  it.

- **guard_blocking** — the watchdog around a blocking call (``Wait``,
  ``Barrier``, ``host_fetch``): with a deadline armed, the call runs on a
  daemon worker thread joined with the remaining budget; on expiry every
  thread's stack is dumped via :mod:`faulthandler` (the same dump the
  multiprocess watchdog wires) and :class:`CollectiveTimeoutError` is
  raised — the abandoned worker thread is the supervisor's problem, which
  is exactly the point: *this* process stops pretending the collective
  will complete.  Trips count under ``health.deadline.trips``.

Counters live in a module-local store mirrored into ``utils.profiler``
(when loaded) via a counter provider, so ``telemetry.report()`` carries
``health.*`` next to ``comm.*``/``retry.*`` — but nothing here imports
jax: the supervisor process reads heartbeats without paying a backend
import.

Stdlib-only on purpose.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, Iterator, Optional

__all__ = [
    "CollectiveTimeoutError",
    "Deadline",
    "Heartbeat",
    "deadline",
    "active_deadline",
    "guard_blocking",
    "write_heartbeat",
    "read_heartbeat",
    "heartbeat_age",
    "restart_epoch",
    "counters",
    "counter_inc",
    "reset_counters",
]


class CollectiveTimeoutError(TimeoutError):
    """A collective (or other guarded blocking call) exceeded its armed
    deadline.  Raised *instead of hanging forever* — the surviving process
    can tear down cleanly and let the supervisor restart the world."""


def restart_epoch() -> int:
    """The current restart generation: 0 on a fresh launch, incremented by
    the supervisor on every world restart (``HEAT_TPU_RESTART_EPOCH``).
    Workers branch on this to resume from the newest verified checkpoint."""
    try:
        return int(os.environ.get("HEAT_TPU_RESTART_EPOCH", "0") or 0)
    except ValueError:
        return 0


# ---------------------------------------------------------------------- #
# counters — module-local so the supervisor never imports jax; mirrored
# into utils.profiler (as a provider) when that module is loaded
# ---------------------------------------------------------------------- #
_counters: Dict[str, int] = {}
_provider_registered = False


def counter_inc(name: str, n: int = 1) -> None:
    _counters[name] = _counters.get(name, 0) + int(n)
    _ensure_provider()


def counters() -> Dict[str, int]:
    return dict(_counters)


def reset_counters() -> None:
    _counters.clear()


def _ensure_provider() -> None:
    """Register the ``health`` provider with ``utils.profiler`` — but only
    if profiler is ALREADY loaded (importing it pulls jax, which the
    supervisor process must never pay)."""
    global _provider_registered
    if _provider_registered:
        return
    prof = sys.modules.get("heat_tpu.utils.profiler")
    if prof is None:
        return
    # keys are emitted pre-prefixed ("health.*"), so the provider namespace
    # rule passes them through verbatim
    prof.register_counter_provider("health", lambda: dict(_counters))
    _provider_registered = True


# ---------------------------------------------------------------------- #
# heartbeat beacon
# ---------------------------------------------------------------------- #
def write_heartbeat(
    path: str, step: int, status: str = "ok", extra: Optional[dict] = None
) -> None:
    """Atomically (re)write the heartbeat file at ``path``.

    The payload is one JSON object: ``pid``, monotonic ``step``, epoch
    ``time``, the process's ``restart_epoch`` and a free-form ``status``.
    tmp-then-rename so a reader never sees a torn write; the parent
    directory must exist.  The tmp name is unique per pid AND thread —
    the ``start_beacon`` daemon thread writes concurrently with the train
    loop's ``beat()`` by design, and a shared tmp would let one writer's
    ``os.replace`` consume the file out from under the other's."""
    rec = {
        "pid": os.getpid(),
        "step": int(step),
        "time": time.time(),
        "restart_epoch": restart_epoch(),
        "status": status,
    }
    # fold the flight recorder's latest collective into the beacon: the
    # supervisor then sees live SEMANTIC progress ("rank 2 stuck at seq 417
    # Alltoall while peers are at 423"), not just mtime staleness.  Via
    # sys.modules — this module must stay importable without the package.
    fr = sys.modules.get("heat_tpu.utils.flightrec")
    if fr is not None:
        try:
            last = fr.last_collective()
        except Exception:
            last = None
        if last is not None:
            rec["seq"], rec["collective"] = int(last[0]), str(last[1])
    # fold the memory ledger's live bytes in the same way: the supervisor's
    # staleness lines then report memory alongside seq progress, and the
    # /metrics heartbeat gauges get a per-rank memory view for free
    ml = sys.modules.get("heat_tpu.utils.memledger")
    if ml is not None:
        try:
            if ml.enabled():
                rec["mem_live"] = int(ml.live_bytes())
        except Exception:
            pass
    if extra:
        rec.update(extra)
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w") as fh:
        json.dump(rec, fh)
    os.replace(tmp, path)
    counter_inc("health.heartbeat.writes")


def read_heartbeat(path: str) -> Optional[dict]:
    """The last complete heartbeat record, or None (missing/torn file —
    a torn read can only happen for a non-atomic foreign writer, but the
    supervisor must never crash on one)."""
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def heartbeat_age(path: str, now: Optional[float] = None) -> Optional[float]:
    """Seconds since the beacon at ``path`` was last rewritten (file mtime —
    cheaper than parsing, and immune to clock skew between writer fields),
    or None when the file does not exist yet."""
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        return None
    return (now if now is not None else time.time()) - mtime


class Heartbeat:
    """Convenience beacon bound to one path: ``beat()`` bumps the monotonic
    step and rewrites the file; ``start_beacon(interval)`` additionally
    spawns a daemon thread re-beating the *current* step every interval —
    liveness proof for long single-step sections (a big compile, a long
    collective that IS making progress)."""

    def __init__(self, path: str):
        self.path = path
        self.step = 0
        self._stop: Optional[threading.Event] = None
        self._thread: Optional[threading.Thread] = None
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)

    def beat(self, step: Optional[int] = None, status: str = "ok", **extra) -> None:
        self.step = self.step + 1 if step is None else int(step)
        write_heartbeat(self.path, self.step, status=status, extra=extra or None)

    def start_beacon(self, interval: float = 5.0) -> None:
        if self._thread is not None:
            return
        self._stop = threading.Event()

        def run() -> None:
            while not self._stop.wait(interval):
                try:
                    write_heartbeat(self.path, self.step, status="beacon")
                except OSError:
                    # a transiently full/contended filesystem must not kill
                    # the beacon silently — missing ONE beat is recoverable,
                    # a dead beacon thread reads as a wedged rank
                    pass

        self._thread = threading.Thread(target=run, name="heat-heartbeat", daemon=True)
        self._thread.start()

    def stop_beacon(self) -> None:
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)
        self._thread = None
        self._stop = None

    def __enter__(self) -> "Heartbeat":
        return self

    def __exit__(self, *exc) -> bool:
        self.stop_beacon()
        return False


# ---------------------------------------------------------------------- #
# deadlines
# ---------------------------------------------------------------------- #
class Deadline:
    """A monotonic-clock time budget.  Cheap by design: creation is two
    float reads; ``check()`` is one clock read and a comparison."""

    __slots__ = ("seconds", "_t1")

    def __init__(self, seconds: float):
        self.seconds = float(seconds)
        self._t1 = time.monotonic() + self.seconds

    def remaining(self) -> float:
        """Seconds left (may be negative once expired)."""
        return self._t1 - time.monotonic()

    def expired(self) -> bool:
        return time.monotonic() >= self._t1

    def check(self, what: str = "operation") -> None:
        """Raise :class:`CollectiveTimeoutError` when the budget is gone.
        Collective staging points call this so an already-blown deadline
        stops staging MORE work on a world that is being torn down."""
        if self.expired():
            counter_inc("health.deadline.trips")
            raise CollectiveTimeoutError(
                f"{what} exceeded its {self.seconds:.3f}s deadline"
            )

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Deadline({self.seconds}, remaining={self.remaining():.3f})"


_active: contextvars.ContextVar[Optional[Deadline]] = contextvars.ContextVar(
    "heat_tpu_deadline", default=None
)


def active_deadline() -> Optional[Deadline]:
    return _active.get()


@contextlib.contextmanager
def deadline(seconds: float) -> Iterator[Deadline]:
    """Arm a deadline for the block: guarded blocking waits inside it raise
    :class:`CollectiveTimeoutError` instead of hanging, and collective
    staging checks it.  Nested deadlines: the innermost governs (its budget
    is what the block explicitly asked for)."""
    dl = Deadline(seconds)
    token = _active.set(dl)
    try:
        yield dl
    finally:
        _active.reset(token)


def _dump_stacks() -> None:
    """Every thread's stack to stderr — the same diagnostic the mp-lane
    watchdog produces, so a tripped deadline is debuggable post-hoc."""
    try:
        import faulthandler

        faulthandler.dump_traceback(file=sys.stderr)
    except Exception:  # pragma: no cover - faulthandler is stdlib
        pass


def _wait_observer():
    """The telemetry module iff it is loaded AND armed; None otherwise.
    Via ``sys.modules`` so this module never imports the package (a bare
    supervisor process must keep working without telemetry)."""
    tel = sys.modules.get("heat_tpu.utils.telemetry")
    if tel is None or not getattr(tel, "_ENABLED", False):
        return None
    return tel


def _observe_wait(what: str, seconds: float) -> None:
    """Record an observed blocking-wait duration into the per-collective
    histogram ``<what>.wait`` (e.g. ``comm.Wait.wait``,
    ``comm.host_fetch.wait``, ``comm.resplit.tile.wait``) — the straggler
    evidence ``scripts/postmortem.py`` reads from the telemetry export —
    AND as a ``<what>.wait`` leaf record in the span ring, which is what
    positions the wait INSIDE its enclosing step span: the step-time
    breakdown (``scripts/stepprof.py``) attributes per-step comm-wait from
    these leaf records, the cumulative histogram alone cannot say which
    step paid.  Gated on telemetry being ARMED: disarmed, the observation
    could never reach an export anyway, and doing per-call histogram work
    between back-to-back collectives is exactly the hot-path cost the
    telemetry-off contract forbids (measured: it can perturb rapid
    small-collective streams on slow hosts)."""
    tel = _wait_observer()
    if tel is None:
        return
    try:
        tel.observe(f"{what}.wait", seconds)
        tel.record_event(f"{what}.wait", seconds)
    except Exception:
        pass


def guard_blocking(fn: Callable[[], Any], what: str) -> Any:
    """Run ``fn()`` under the active deadline (plain call when none armed).

    The blocking call runs on a daemon worker thread joined with the
    remaining budget.  On expiry: ``health.deadline.trips`` increments,
    stacks are dumped, and :class:`CollectiveTimeoutError` raises — the
    worker thread is abandoned (it is stuck in uninterruptible C code by
    hypothesis; only a process teardown can reclaim it, and that teardown
    is exactly what the caller's error handling / the supervisor performs).

    Either way the observed wait time lands in the ``<what>.wait``
    histogram (:func:`_observe_wait`) WHEN telemetry is armed: on a trip
    the recorded value is the full burned budget, so a straggler's guard
    sites accumulate visibly long waits — the attribution the post-mortem
    analyzer names.  Telemetry off: the no-deadline path is a bare
    ``fn()`` call — no clocks, no histogram — per the off-cost contract."""
    dl = _active.get()
    if dl is None:
        if _wait_observer() is None:
            return fn()
        t0 = time.monotonic()
        try:
            return fn()
        finally:
            _observe_wait(what, time.monotonic() - t0)
    remaining = dl.remaining()
    if remaining <= 0:
        dl.check(what)  # raises
    box: dict = {}
    # threads do NOT inherit contextvars — copy the caller's context so
    # fault injections (faults.inject is contextvar-scoped) and the armed
    # deadline are visible inside the worker thread
    ctx = contextvars.copy_context()

    def run() -> None:
        try:
            box["value"] = ctx.run(fn)
        except BaseException as e:  # propagate the real failure to the caller
            box["error"] = e

    t = threading.Thread(target=run, name=f"heat-guard:{what}", daemon=True)
    t0 = time.monotonic()
    t.start()
    t.join(remaining)
    _observe_wait(what, time.monotonic() - t0)
    if t.is_alive():
        counter_inc("health.deadline.trips")
        _dump_stacks()
        raise CollectiveTimeoutError(
            f"{what} exceeded its {dl.seconds:.3f}s deadline "
            f"(blocked > {remaining:.3f}s remaining budget)"
        )
    if "error" in box:
        raise box["error"]
    return box.get("value")
