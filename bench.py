"""heat_tpu benchmark — prints ONE JSON line for the driver.

HONEST ACCOUNTING (VERDICT r2 item 3): the headline metric is the
**bf16 16384² distributed matmul** through the public ``ht.matmul`` —
bf16 is the TPU MXU's native GEMM precision, so TFLOPS/peak = true MFU.
The payload carries ``device_kind``, the chip's bf16 peak, and the
computed **MFU**.  Three GEMM precisions are reported separately and
labeled for what they are:

- ``*_bf16``: native MXU passes (the headline);
- ``*_f32_default_precision``: f32 inputs under JAX's DEFAULT TPU matmul
  precision — the MXU computes in bf16 passes (this was mislabeled "f32"
  in round 2; it is NOT true f32);
- ``*_f32_highest``: ``jax.default_matmul_precision('highest')`` — true
  f32-accuracy emulation (6-pass bf16), the only honest f32 number.

``vs_baseline`` is **null by design** (round-4): no reference (HeAT-CUDA)
numbers exist in this environment (BASELINE.json has no published numbers;
see BASELINE.md provenance), and any ratio in that slot reads as a
framework comparison.  The only measurable host reference — a torch-CPU
f32 4096 GEMM — rides in ``extra.host_ratio_vs_torch_cpu`` with an
explicit definition string.

Also measured: a GEMM size sweep (4096/8192/16384; the sub-16384 sizes are
slope-timed so the tunneled dispatch constant cancels — round-3's "6 TFLOPS
at 4096" was that constant, not the chip), matmul_summa vs GSPMD (strategy
comparison on an 8-device CPU mesh; degenerate on 1 chip), and KMeans at
two sizes up to the largest row count that fits HBM (bytes reported) plus
BASELINE config[2]'s 1e8×32 in bf16.

Timing notes: on the tunneled axon platform ``block_until_ready`` does not
actually block, so completion is forced by fetching a scalar.  The chained
GEMMs run as ONE fused jitted ``lax.scan`` through the public ``ht.matmul``,
so per-GEMM time measures on-device compute and excludes per-dispatch/tunnel
latency; chained values are rescaled each step to stay finite.
"""

from __future__ import annotations

import functools
import json
import time

import numpy as np

# bf16 peak TFLOPS per chip by device_kind substring (public spec sheets)
_BF16_PEAKS = (
    ("v5 lite", 197.0),
    ("v5litepod", 197.0),
    ("v5e", 197.0),
    ("v6 lite", 918.0),
    ("v6e", 918.0),
    ("v5p", 459.0),
    ("v5", 459.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 46.0),
)


def _bf16_peak(device_kind: str):
    dk = device_kind.lower()
    for key, peak in _BF16_PEAKS:
        if key in dk:
            return peak
    return None


def _gemm_seconds(ht, jax, n: int, dtype, iters: int, reps: int = 1, reps_gate=None) -> float:
    """Per-GEMM seconds for an n x n chain through the public ht.matmul.

    ``reps`` > 1 takes the best-of-``reps`` chain (the chip's capability,
    not the jitter) via the shared ``timeit_min`` methodology.  ``reps_gate``
    (a nullary bool callable) is re-checked AFTER the compile+warm — the
    dominant cost on a degraded tunnel — and drops to one rep when it fails.
    """
    a = ht.random.randn(n, n, dtype=dtype, split=0)
    b = ht.random.randn(n, n, dtype=dtype, split=1)
    scale = float(1.0 / np.sqrt(n))  # keeps chained values finite

    @functools.partial(jax.jit, static_argnames="iters")
    def chain(a, b, iters):
        def body(c, _):
            return (ht.matmul(c, b) * scale), None

        c, _ = jax.lax.scan(body, a, None, length=iters)
        return c

    from heat_tpu.utils.profiler import timeit_min

    float(chain(a, b, iters)._jarray[0, 0])  # compile + warm
    if reps > 1 and reps_gate is not None and not reps_gate():
        reps = 1
    return timeit_min(lambda: chain(a, b, iters)._jarray, reps=reps) / iters


def _gemm_seconds_slope(ht, jax, n: int, dtype, iters_lo: int, iters_hi: int,
                        reps: int = 2) -> dict:
    """Per-GEMM seconds with the constant dispatch/readback cost REMOVED.

    Round-3's 4096 number (6 TFLOPS, 3% of peak) was a measurement artifact:
    at 0.9 ms/GEMM the tunneled dispatch + scalar readback (~1 s/chain)
    dominated the naive chain/iters quotient.  Timing the SAME chain at two
    iteration counts and taking the slope (t_hi - t_lo)/(iters_hi - iters_lo)
    cancels every per-call constant, leaving pure on-device per-GEMM time.
    Returns both the slope and the naive quotients so the artifact stays
    documented."""
    a = ht.random.randn(n, n, dtype=dtype, split=0)
    b = ht.random.randn(n, n, dtype=dtype, split=1)
    scale = float(1.0 / np.sqrt(n))

    @functools.partial(jax.jit, static_argnames="iters")
    def chain(a, b, iters):
        def body(c, _):
            return (ht.matmul(c, b) * scale), None

        c, _ = jax.lax.scan(body, a, None, length=iters)
        return c

    from heat_tpu.utils.profiler import timeit_min

    for it in (iters_lo, iters_hi):
        float(chain(a, b, it)._jarray[0, 0])  # compile + warm both lengths
    t_lo = timeit_min(lambda: chain(a, b, iters_lo)._jarray, reps=reps)
    t_hi = timeit_min(lambda: chain(a, b, iters_hi)._jarray, reps=reps)
    slope = (t_hi - t_lo) / (iters_hi - iters_lo)
    if slope <= 0:
        # jitter swamped the added iterations: refuse to report a number
        # (a clamped slope would fabricate absurd TFLOPS) — callers record
        # the failure reason instead
        raise RuntimeError(
            f"slope timing noise-dominated at n={n}: t_lo={t_lo:.4f}s "
            f"t_hi={t_hi:.4f}s over {iters_hi - iters_lo} extra iters"
        )
    return {
        "per_gemm_s": slope,
        "naive_per_gemm_s": t_hi / iters_hi,
        "const_overhead_s": max(t_lo - slope * iters_lo, 0.0),
    }


def _summa_vs_gspmd_cpu8(repo_root: str) -> dict:
    """Strategy comparison on a virtual 8-device CPU mesh: explicit shard_map
    SUMMA ring vs GSPMD-partitioned matmul (SURVEY §7 hard part #4).  Run in
    a subprocess with the scrubbed CPU env (platform pinned BEFORE jax import,
    axon site injection stripped) so a wedged accelerator tunnel can never
    hang the child at import time — the round-1 failure mode.

    Round-5 methodology fix (VERDICT r4 weak #4): the two arms are timed
    INTERLEAVED (min over alternating reps) instead of back-to-back
    ``timeit_min`` blocks — r4d's one-shot 0.708 "SUMMA ahead at 2048" was
    an ordering artifact of the sequential blocks.  Both shapes of the
    measured crossover are recorded: 2048 (GSPMD side) and 4096 (SUMMA
    side), matching the ``_SUMMA_DISPATCH`` table in linalg/basics.py."""
    import subprocess
    import sys

    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    from __graft_entry__ import _scrubbed_cpu_env

    script = (
        "import sys, os, json, time\n"
        "import jax\n"
        f"sys.path.insert(0, {repo_root!r})\n"
        "import heat_tpu as ht\n"
        "from heat_tpu.linalg.basics import matmul_summa\n"
        "out = {}\n"
        "for n, reps in ((2048, 4), (4096, 3)):\n"
        "    a = ht.random.randn(n, n, split=0); b = ht.random.randn(n, n, split=0)\n"
        "    ht.matmul(a, b, method='gspmd')._jarray.block_until_ready()\n"
        "    matmul_summa(a, b)._jarray.block_until_ready()\n"
        "    tg, ts = [], []\n"
        "    for _ in range(reps):\n"
        "        t0 = time.perf_counter(); ht.matmul(a, b, method='gspmd')._jarray.block_until_ready(); tg.append(time.perf_counter() - t0)\n"
        "        t0 = time.perf_counter(); matmul_summa(a, b)._jarray.block_until_ready(); ts.append(time.perf_counter() - t0)\n"
        "    out[f'summa_{n}_s0xs0_s'] = round(min(ts), 5)\n"
        "    out[f'gspmd_{n}_s0xs0_s'] = round(min(tg), 5)\n"
        "    out[f'summa_over_gspmd_{n}'] = round(min(ts) / min(tg), 3)\n"
        "print(json.dumps(out))\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        text=True,
        timeout=600,
        env=_scrubbed_cpu_env(8),
        cwd=repo_root,
    )
    line = next((l for l in out.stdout.splitlines() if l.startswith("{")), None)
    if line:
        return json.loads(line)
    return {"error": (out.stderr or "no output")[-200:]}


def main(state: dict = None) -> dict:
    import os

    import jax

    import heat_tpu as ht

    t_begin = time.perf_counter()
    try:
        budget = float(os.environ.get("HEAT_BENCH_TIMEOUT_S", "1500"))
    except ValueError:
        budget = 1500.0

    def time_left() -> float:
        return budget - (time.perf_counter() - t_begin)

    n_chips = max(len(jax.devices()), 1)
    dk = getattr(jax.devices()[0], "device_kind", "unknown")
    peak = _bf16_peak(str(dk))
    extra = {
        "platform": jax.devices()[0].platform,
        "n_chips": n_chips,
        "device_kind": str(dk),
        "bf16_peak_tflops_per_chip": peak,
        "skipped": [],
        # machine-readable capture manifest (VERDICT r4 item 1): a
        # watchdog-cut payload shows exactly which rows landed vs were due
        "rows_expected": [
            "headline", "f32_default", "f32_highest", "m4096", "m8192",
            "host_ratio", "summa_vs_gspmd", "kmeans", "qr_tsqr",
            "kmeans_kernel_ab", "flash_attention_ab", "gqa_attention_ab",
            "flash_attention_32k", "lm_generate", "moe_block",
            "kmeans_1e8_bf16",
        ],
        "rows_captured": [],
    }

    def captured(name: str):
        extra["rows_captured"].append(name)

    N = 16384
    flops = 2.0 * N * N * N

    # --- headline: 16384^2 bf16 (native MXU precision) -------------------- #
    # best-of-3 only when >55% of the budget remains AFTER the compile+warm
    # (the gate re-checks then): cheap on a healthy chip, never worth risking
    # the whole payload on a degraded tunnel
    t_bf16 = _gemm_seconds(
        ht, jax, N, ht.bfloat16, iters=10, reps=3,
        reps_gate=lambda: time_left() > 0.55 * budget,
    )
    tflops_bf16 = flops / t_bf16 / 1e12 / n_chips
    extra["matmul_16384_bf16_wallclock_s"] = round(t_bf16, 6)
    if peak:
        extra["mfu_bf16"] = round(tflops_bf16 / peak, 4)
    payload = {
        "metric": "dist_matmul_16384_bf16_tflops_per_chip",
        "value": round(tflops_bf16, 3),
        "unit": "TFLOPS/chip",
        # null by design: no reference (HeAT-CUDA) numbers exist in this
        # environment — the labeled host ratio lives in extra
        "vs_baseline": None,
        "extra": extra,
    }

    def snapshot():
        # the watchdog may serialize state['partial'] while this thread keeps
        # mutating `payload` — store an immutable deep copy, refreshed at
        # section boundaries, so the timeout emission can never race
        if state is not None:
            import copy

            state["partial"] = copy.deepcopy(payload)

    captured("headline")
    # headline is in: from here on a watchdog timeout emits the snapshot
    # (partial, flagged) instead of discarding the TPU datapoint
    snapshot()

    # remaining sections are optional and budget-guarded: on a degraded
    # tunnel, preserving the headline beats completing the tail
    def skip(name: str, frac: float) -> bool:
        if time_left() < budget * frac:
            extra["skipped"].append(name)
            return True
        return False

    # --- f32 inputs, DEFAULT TPU matmul precision (bf16 MXU passes) ------- #
    # SLOPE-TIMED from round 5 (VERDICT r4 weak #2): the r4b-vs-r4d 35.6 →
    # 4.617 swing on this row was the naive chain/iters quotient absorbing a
    # multi-second tunnel stall into 6 iterations; the slope cancels every
    # per-call constant, so a degrading relay shows up as the explicit
    # noise-dominated error instead of a silently wrong TFLOPS number.
    if not skip("f32_default", 0.45):
        try:
            r = _gemm_seconds_slope(ht, jax, N, ht.float32, 2, 8)
            extra["matmul_16384_f32_default_precision_tflops_per_chip"] = round(
                flops / r["per_gemm_s"] / 1e12 / n_chips, 3
            )
            extra["f32_default_dispatch_overhead_s"] = round(r["const_overhead_s"], 4)
            captured("f32_default")
        except Exception as e:
            extra["f32_default_error"] = str(e)[:80]
        snapshot()

    # --- TRUE f32: precision=HIGHEST (6-pass bf16 emulation) -------------- #
    # The v5e has no native f32 MXU mode; HIGHEST is the honest f32 number
    # and its arithmetic ceiling is bf16_peak/6 (the 6-pass decomposition).
    # mfu_f32 is reported against that ceiling (doc/design.md "f32 on TPU").
    if not skip("f32_highest", 0.4):
        try:
            with jax.default_matmul_precision("highest"):
                r = _gemm_seconds_slope(ht, jax, N, ht.float32, 2, 6)
            v = flops / r["per_gemm_s"] / 1e12 / n_chips
            extra["matmul_16384_f32_highest_tflops_per_chip"] = round(v, 3)
            if peak:
                extra["f32_ceiling_tflops_per_chip"] = round(peak / 6.0, 1)
                extra["mfu_f32"] = round(v / (peak / 6.0), 4)
            captured("f32_highest")
        except Exception as e:
            extra["f32_highest_error"] = str(e)[:80]
        snapshot()

    # --- GEMM size sweep (slope-timed: dispatch/readback constant removed,
    # the round-3 "6 TFLOPS at 4096" artifact — see _gemm_seconds_slope) --- #
    for nn, lo, hi in ((4096, 10, 110), (8192, 5, 35)):
        if skip(f"m{nn}", 0.35):
            break
        try:
            r = _gemm_seconds_slope(ht, jax, nn, ht.bfloat16, lo, hi)
            f = 2.0 * nn**3
            extra[f"matmul_{nn}_bf16_tflops_per_chip"] = round(
                f / r["per_gemm_s"] / 1e12 / n_chips, 3
            )
            extra[f"matmul_{nn}_bf16_naive_tflops_per_chip"] = round(
                f / r["naive_per_gemm_s"] / 1e12 / n_chips, 3
            )
            extra[f"matmul_{nn}_dispatch_overhead_s"] = round(r["const_overhead_s"], 4)
            captured(f"m{nn}")
        except Exception as e:
            extra[f"m{nn}_error"] = str(e)[:80]
        snapshot()

    # --- torch-CPU host reference (context only) -------------------------- #
    # vs_baseline stays null at top level (VERDICT r3 weak #3): no reference
    # (HeAT-CUDA) numbers exist in this environment, and a TPU-vs-one-CPU
    # ratio in the headline slot reads as a framework comparison it is not.
    # The host ratio survives — clearly labeled — in extra.
    try:
        import torch

        ta = torch.randn(4096, 4096, dtype=torch.float32)
        tb = torch.randn(4096, 4096, dtype=torch.float32)
        ta @ tb  # warmup
        t0 = time.perf_counter()
        ta @ tb
        t_torch = time.perf_counter() - t0
        torch_tflops = 2.0 * 4096**3 / t_torch / 1e12
        extra["torch_cpu_4096_f32_tflops"] = round(torch_tflops, 3)
        extra["host_ratio_vs_torch_cpu"] = round(tflops_bf16 * n_chips / torch_tflops, 3)
        extra["host_ratio_definition"] = (
            "headline bf16 TFLOPS (all chips) / torch-CPU f32 4096 GEMM TFLOPS "
            "on this host; context only — NOT a HeAT-CUDA comparison (no "
            "reference numbers exist in this environment, see BASELINE.md)"
        )
        captured("host_ratio")
    except Exception as e:
        extra["host_ratio_error"] = f"torch-CPU reference unavailable: {e}"[:120]

    payload["vs_baseline"] = None
    snapshot()

    # --- SUMMA vs GSPMD strategy comparison (CPU subprocess) -------------- #
    if not skip("summa_vs_gspmd", 0.25):
        try:
            repo_root = os.path.dirname(os.path.abspath(__file__))
            extra["summa_vs_gspmd_cpu8dev"] = _summa_vs_gspmd_cpu8(repo_root)
            if "error" not in extra["summa_vs_gspmd_cpu8dev"]:
                captured("summa_vs_gspmd")
        except Exception as e:
            extra["summa_vs_gspmd_cpu8dev"] = {"error": str(e)[:120]}
        snapshot()

    # --- KMeans iter/sec at the largest n fitting HBM (config[2] path) ---- #
    def _kmeans_attempt(n_rows: int, dtype=None, timed_iters: int = 8,
                        assign_kernel: str = "auto") -> float:
        # scoped so a failed attempt's arrays are freed before the next rung
        X = ht.random.randn(n_rows, 32, dtype=dtype or ht.float32, split=0)
        km = ht.cluster.KMeans(
            n_clusters=64, max_iter=2, tol=0.0, random_state=0, init="random",
            assign_kernel=assign_kernel,
        )
        km.fit(X)  # compile
        t0 = time.perf_counter()
        km2 = ht.cluster.KMeans(
            n_clusters=64, max_iter=timed_iters, tol=0.0, random_state=0, init="random",
            assign_kernel=assign_kernel,
        )
        km2.fit(X)
        # force completion (f32 readback: bf16 scalars lack a Python float path)
        float(km2.cluster_centers_._jarray.astype("float32")[0, 0])
        return (time.perf_counter() - t0) / km2.n_iter_

    largest = None
    for log2n in (26, 25, 23, 17):
        if skip(f"kmeans_2e{log2n}", 0.15):
            break
        n_rows = 2**log2n
        try:
            t_km = _kmeans_attempt(n_rows)
            extra["kmeans_rows"] = n_rows
            extra["kmeans_data_gib"] = round(n_rows * 32 * 4 / 2**30, 2)
            extra[f"kmeans_{n_rows}_x32_k64_iter_per_s"] = round(1.0 / t_km, 3)
            largest = log2n
            captured("kmeans")
            break
        except Exception as e:
            extra[f"kmeans_2e{log2n}_error"] = str(e)[:80]
            continue
    # a second, smaller sweep point so the snapshot shows scaling, not one dot
    if largest is not None and largest > 23 and not skip("kmeans_2e23_sweep", 0.15):
        try:
            t_km = _kmeans_attempt(2**23)
            extra[f"kmeans_{2**23}_x32_k64_iter_per_s"] = round(1.0 / t_km, 3)
        except Exception as e:
            extra["kmeans_2e23_sweep_error"] = str(e)[:80]
    snapshot()

    # --- BASELINE config[1]: tall-skinny QR (TSQR), 1e6 x 256 f32 --------- #
    # A-B: 'cholqr2' (the MXU-shaped CholeskyQR2 local factorization, the
    # 'auto' default for tall blocks) vs 'householder' (XLA's QR — measured
    # 7 GFLOPS in round 3).  The TSQR program is comm-cached, so warm reps
    # time factorization, not the per-call retrace+recompile that round-3's
    # 18.6 s figure mostly was.
    if not skip("qr_tsqr", 0.13):
        try:
            from heat_tpu.utils.profiler import timeit_min

            A = ht.random.randn(1_000_000, 256, dtype=ht.float32, split=0)
            for meth in ("cholqr2", "householder"):
                if meth == "householder" and skip("qr_householder", 0.1):
                    break
                # mode='r' label: the 2*m*n^2 flop model covers the
                # factorization only (Q formation would misstate ~2x)
                rf = ht.linalg.qr(A, mode="r", method=meth).R  # compile+warm
                float(rf._jarray.astype("float32")[0, 0])
                # timeit_min's sync() already blocks on the executable
                dt = timeit_min(
                    lambda: ht.linalg.qr(A, mode="r", method=meth).R, reps=2
                )
                extra[f"qr_tsqr_1e6x256_f32_{meth}_s"] = round(dt, 4)
                extra[f"qr_tsqr_1e6x256_{meth}_gflops"] = round(
                    2.0 * 1_000_000 * 256**2 / dt / 1e9, 1
                )
            del A, rf
            captured("qr_tsqr")
        except Exception as e:
            extra["qr_tsqr_error"] = str(e)[:100]
        snapshot()

    # --- kernel-on vs kernel-off (VERDICT r4 #2: the Pallas E-step must
    # earn its keep in the benched workload or stay opt-out).  A-B at 2^23:
    # beyond that the narrow-d relayout gate (_relayout_copy_bytes)
    # silently falls the 'pallas' arm back to jnp and the A-B is vacuous --- #
    if largest is not None and not skip("kmeans_kernel_ab", 0.12):
        n_ab = 2 ** min(largest, 23)
        try:
            t_on = _kmeans_attempt(n_ab, timed_iters=6, assign_kernel="pallas")
            t_off = _kmeans_attempt(n_ab, timed_iters=6, assign_kernel="jnp")
            extra[f"kmeans_{n_ab}_x32_k64_kernel_pallas_iter_per_s"] = round(1.0 / t_on, 3)
            extra[f"kmeans_{n_ab}_x32_k64_kernel_jnp_iter_per_s"] = round(1.0 / t_off, 3)
            extra["kmeans_kernel_speedup"] = round(t_off / t_on, 3)
            captured("kmeans_kernel_ab")
        except Exception as e:
            extra["kmeans_kernel_ab_error"] = str(e)[:120]
        snapshot()

    # --- flash attention: Pallas kernel vs dense XLA local attention ------ #
    # causal bf16, slope-timed (chained lax.scan at two lengths so the
    # tunnel dispatch constant cancels).  ONE timing harness serves both
    # points, and each point records its own error key so a failed/noisy
    # measurement is visible in the payload, never silently absent.
    def _attn_slope(f, qkv, lo, hi):
        """Per-call seconds for f(q,k,v), slope-timed over chained scans."""
        import jax.numpy as jnp

        from heat_tpu.utils.profiler import timeit_min

        def chain(iters):
            @jax.jit
            def run(q, k, v):
                def body(c, _):
                    return f(c, k, v), None

                c, _ = jax.lax.scan(body, q, None, length=iters)
                return c

            return run

        rl, rh = chain(lo), chain(hi)
        for r in (rl, rh):  # compile + warm
            float(jnp.abs(r(*qkv)).sum())
        t_lo = timeit_min(lambda: float(jnp.abs(rl(*qkv)).sum()), reps=2)
        t_hi = timeit_min(lambda: float(jnp.abs(rh(*qkv)).sum()), reps=2)
        s = (t_hi - t_lo) / (hi - lo)
        if s <= 0:
            raise RuntimeError(
                f"slope noise-dominated: t_lo={t_lo:.4f}s t_hi={t_hi:.4f}s"
            )
        return s

    H, d = 8, 64
    if not skip("flash_attention_ab", 0.1):
        try:
            import jax.numpy as jnp

            from heat_tpu.ops.flash_attention import _dense_attention, flash_attention

            B, S = 4, 4096
            key = jax.random.key(0)
            qkv = [
                jax.random.normal(jax.random.fold_in(key, i), (B, H, S, d), jnp.bfloat16)
                for i in range(3)
            ]
            t_flash = _attn_slope(
                lambda q, k, v: flash_attention(q, k, v, causal=True), qkv, 2, 12
            )
            t_dense = _attn_slope(
                lambda q, k, v: _dense_attention(q, k, v, True, d**-0.5, S), qkv, 2, 12
            )
            extra["attn_4x8x4096x64_causal_flash_ms"] = round(t_flash * 1e3, 3)
            extra["attn_4x8x4096x64_causal_dense_ms"] = round(t_dense * 1e3, 3)
            extra["flash_attention_speedup"] = round(t_dense / t_flash, 3)
            captured("flash_attention_ab")
        except Exception as e:
            extra["flash_attention_ab_error"] = str(e)[:120]
        snapshot()

    # --- GQA: head-mapping kernel vs dense over a repeated K/V ------------ #
    # 8 query heads sharing 2 K/V heads (g=4): the kernel reads each group's
    # K/V head from its index map; the control arm materializes the 4x
    # repeat in HBM and runs the dense path (what sdpa did before round 4c)
    if not skip("gqa_attention_ab", 0.1):
        try:
            import jax.numpy as jnp

            from heat_tpu.ops.flash_attention import (
                _dense_attention, flash_attention_gqa,
            )

            Bg, Hkv, Sg = 4, 2, 4096
            key = jax.random.key(1)
            qg = jax.random.normal(key, (Bg, H, Sg, d), jnp.bfloat16)
            kg, vg = (
                jax.random.normal(jax.random.fold_in(key, i), (Bg, Hkv, Sg, d),
                                  jnp.bfloat16)
                for i in (1, 2)
            )
            t_gqa = _attn_slope(
                lambda q, k, v: flash_attention_gqa(q, k, v, causal=True),
                [qg, kg, vg], 2, 12,
            )
            t_rep = _attn_slope(
                lambda q, k, v: _dense_attention(
                    q, jnp.repeat(k, H // Hkv, axis=-3),
                    jnp.repeat(v, H // Hkv, axis=-3), True, d**-0.5, Sg),
                [qg, kg, vg], 2, 12,
            )
            extra["gqa_4x8over2x4096x64_kernel_ms"] = round(t_gqa * 1e3, 3)
            extra["gqa_4x8over2x4096x64_dense_repeat_ms"] = round(t_rep * 1e3, 3)
            extra["gqa_kernel_speedup"] = round(t_rep / t_gqa, 3)
            captured("gqa_attention_ab")
        except Exception as e:
            extra["gqa_attention_ab_error"] = str(e)[:120]
        snapshot()

    # long-context point, flash only (its own try: independent of the A-B
    # above): at (2, 8, 32768, 64) the dense path's f32 scores alone are
    # 64 GiB — off the table on a 16 GiB chip; flash streams them via VMEM
    if not skip("flash_attention_32k", 0.1):
        try:
            import jax.numpy as jnp

            from heat_tpu.ops.flash_attention import flash_attention

            B2, S2 = 2, 32768
            key = jax.random.key(0)
            qkv2 = [
                jax.random.normal(jax.random.fold_in(key, 9 + i),
                                  (B2, H, S2, d), jnp.bfloat16)
                for i in range(3)
            ]
            per = _attn_slope(
                lambda q, k, v: flash_attention(q, k, v, causal=True), qkv2, 1, 3
            )
            fl = 2 * 2 * B2 * H * S2 * S2 * d / 2  # causal
            extra["attn_2x8x32768x64_causal_flash_ms"] = round(per * 1e3, 2)
            extra["attn_32k_flash_tflops"] = round(fl / per / 1e12, 2)
            captured("flash_attention_32k")
        except Exception as e:
            extra["flash_attention_32k_error"] = str(e)[:120]
        snapshot()

    # --- autoregressive decode throughput (round-4d TransformerLM) -------- #
    # one jitted scan over static KV caches; tokens/s counts GENERATED
    # tokens (prompt consumption rides the same step).  The whole loop is a
    # single dispatch, so the tunnel constant amortizes over the sequence.
    if not skip("lm_generate", 0.1):
        try:
            import jax.numpy as jnp

            from heat_tpu.nn.models import TransformerLM

            lm = TransformerLM(vocab_size=32768, embed_dim=512, num_heads=8,
                               depth=8, max_len=1024)
            lp = lm.init(jax.random.key(0))
            lp = jax.tree.map(lambda a: a.astype(jnp.bfloat16), lp)
            prompt = jax.random.randint(jax.random.key(1), (8, 64), 0, 32768)
            n_new = 448
            out = lm.generate(lp, prompt, n_new)
            jax.block_until_ready(out)
            int(np.asarray(out[0, -1]))  # force completion through the tunnel
            from heat_tpu.utils.profiler import timeit_min

            t = timeit_min(
                lambda: int(np.asarray(lm.generate(lp, prompt, n_new)[0, -1])),
                reps=2,
            )
            extra["lm_decode_b8_d8_e512_tok_per_s"] = round(8 * n_new / t, 1)
            captured("lm_generate")
        except Exception as e:
            extra["lm_generate_error"] = str(e)[:120]
        snapshot()

    # --- Switch-block throughput (round-4d MoE) --------------------------- #
    # one Switch-transformer block forward (MoE FFN, top-2 of 32 experts)
    # at (8, 2048, 1024) bf16 — tokens/s through routing + dispatch +
    # expert GEMMs + combine, slope-timed like the attention rows
    if not skip("moe_block", 0.1):
        try:
            import jax.numpy as jnp

            from heat_tpu.nn.models import _TransformerBlock
            from heat_tpu.nn.moe import MoE

            blk = _TransformerBlock(1024, 8, mlp_ratio=4, causal=True,
                                    ffn=MoE(1024, 32, hidden_dim=4096, top_k=2))
            bp = blk.init(jax.random.key(3))
            bp = jax.tree.map(lambda a: a.astype(jnp.bfloat16), bp)
            xb = jax.random.normal(jax.random.key(4), (8, 2048, 1024), jnp.bfloat16)
            per = _attn_slope(lambda q, k, v: blk.apply(bp, q), [xb, xb, xb], 1, 3)
            extra["moe_switch_block_8x2048x1024_ms"] = round(per * 1e3, 2)
            extra["moe_switch_block_tokens_per_s"] = round(8 * 2048 / per, 1)
            captured("moe_block")
        except Exception as e:
            extra["moe_block_error"] = str(e)[:120]
        snapshot()

    # --- BASELINE config[2] scale: 1e8×32 with bf16 storage --------------- #
    # The f32 working set (12.8 GiB + temporaries) exceeds one v5e's HBM; the
    # bf16 layout (6.4 GiB) fits, keeps the E-step GEMM on the MXU's native
    # input type, and is labeled as bf16 so the dtype is never misrepresented.
    if not skip("kmeans_1e8_bf16", 0.15):
        try:
            n_rows = 100_000_000
            t_km = _kmeans_attempt(n_rows, dtype=ht.bfloat16, timed_iters=6)
            extra["kmeans_bf16_rows"] = n_rows
            extra["kmeans_bf16_data_gib"] = round(n_rows * 32 * 2 / 2**30, 2)
            extra["kmeans_1e8_x32_k64_bf16_iter_per_s"] = round(1.0 / t_km, 3)
            captured("kmeans_1e8_bf16")
        except Exception as e:
            extra["kmeans_1e8_bf16_error"] = str(e)[:80]

    if not extra["skipped"]:
        del extra["skipped"]
    return payload


def _cpu_fallback_payload(worker_error: str = "") -> dict:
    """Small CPU-mesh measurement used when the accelerator bench could not
    produce a result (transport wedged OR the worker raised).  Reported with
    value 0.0 under the standard metric name so degraded runs never
    masquerade as real 16384 datapoints; the host number and the worker's
    failure reason ride in extra."""
    import os
    import subprocess
    import sys

    payload = {
        "metric": "dist_matmul_16384_bf16_tflops_per_chip",
        "value": 0.0,
        "unit": "TFLOPS/chip",
        "vs_baseline": None,
        "extra": {"platform": "cpu-fallback",
                  "note": ("accelerator worker raised" if worker_error
                           else "accelerator transport unreachable (timeout)")
                  + "; 2048 GEMM on host mesh"},
    }
    if worker_error:
        payload["extra"]["worker_error"] = worker_error[:300]
    # point degraded runs at the round's real-chip captures (the relay comes
    # and goes; manual runs were taken while it was up)
    import glob

    repo_root = os.path.dirname(os.path.abspath(__file__))
    manual = sorted(
        os.path.basename(f)
        for f in glob.glob(os.path.join(repo_root, "BENCH_r*_manual.json"))
    )
    if manual:
        payload["extra"]["real_chip_captures"] = manual
    script = (
        "import sys, jax, json, time\n"
        f"sys.path.insert(0, {repo_root!r})\n"
        "jax.config.update('jax_platforms','cpu')\n"
        "import heat_tpu as ht\n"
        "n=2048\n"
        "a=ht.random.randn(n,n,split=0); b=ht.random.randn(n,n,split=1)\n"
        "dt=ht.utils.profiler.timeit_min(lambda: a@b, reps=2)\n"
        "print(json.dumps({'cpu_2048_tflops': round(2.0*n**3/dt/1e12, 3)}))\n"
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", script], capture_output=True, text=True, timeout=600
        )
        line = next((l for l in out.stdout.splitlines() if l.startswith("{")), None)
        if line:
            payload["extra"].update(json.loads(line))
        else:
            payload["extra"]["error"] = (out.stderr or "no output")[-300:]
    except Exception as e:  # TimeoutExpired and anything else: still one line
        payload["extra"]["error"] = f"cpu fallback failed: {e}"[:300]
    return payload


if __name__ == "__main__":
    import os
    import sys
    import threading
    import traceback

    # the tunneled platform can wedge hard (device init or the first compile
    # never returns); a watchdog guarantees the driver always gets exactly
    # ONE JSON line on stdout.  The worker never prints — the main thread
    # does, so a late-finishing worker cannot race a second line out.
    state = {}
    done = threading.Event()

    def _run():
        try:
            state["payload"] = main(state)
        except Exception as e:
            state["error"] = f"{type(e).__name__}: {e}"
            traceback.print_exc(file=sys.stderr)
        finally:
            done.set()

    t = threading.Thread(target=_run, daemon=True)
    t.start()
    try:
        budget = float(os.environ.get("HEAT_BENCH_TIMEOUT_S", "1500"))
    except ValueError:
        budget = 1500.0
    done.wait(budget)
    payload = state.get("payload")
    if payload is None:
        # worker still running or dead: a measured headline (state['partial'])
        # beats the cpu fallback — emit it flagged as partial
        payload = state.get("partial")  # an immutable snapshot (deepcopied)
        if payload is not None and payload.get("value", 0) > 0:
            payload["extra"]["watchdog_timeout"] = True
        else:
            payload = _cpu_fallback_payload(state.get("error", ""))
    try:
        line = json.dumps(payload)
    except Exception:  # belt-and-braces: the driver must ALWAYS get one line
        line = json.dumps(_cpu_fallback_payload("payload serialization failed"))
    print(line)
    sys.stdout.flush()
    os._exit(0)
