"""Sparse manipulations (reference: ``heat/sparse/manipulations.py``).

Conversions between dense DNDarrays and distributed CSR, and pattern-level
transforms.  Sparsification runs on-device (``BCOO.fromdense`` lowers to XLA
scatter/gather); the split metadata follows the dense operand.
"""

from __future__ import annotations


from jax.experimental import sparse as jsparse

from ..core.dndarray import DNDarray
from .dcsr_matrix import DCSR_matrix

__all__ = ["todense", "to_dense", "to_sparse", "transpose"]


def todense(sparse_matrix: DCSR_matrix) -> DNDarray:
    """Densify a distributed CSR matrix into a DNDarray."""
    return sparse_matrix.todense()


def to_dense(sparse_matrix: DCSR_matrix) -> DNDarray:
    return sparse_matrix.todense()


def to_sparse(x: DNDarray) -> DCSR_matrix:
    """Sparsify a dense 2-D DNDarray into a DCSR_matrix (reference
    ``heat.sparse.to_sparse``); the row split carries over."""
    if not isinstance(x, DNDarray):
        raise TypeError(f"to_sparse expects a DNDarray, got {type(x)}")
    if x.ndim != 2:
        raise ValueError("to_sparse requires a 2-D DNDarray")
    if x.split not in (None, 0):
        raise ValueError(
            "DCSR is row-split only (split ∈ {None, 0}, reference CSR "
            f"constraint); resplit the dense array first (got split={x.split})"
        )
    arr = jsparse.BCOO.fromdense(x._jarray)
    return DCSR_matrix(
        arr, int(arr.nse), x.shape, x.dtype, x.split, x.device, x.comm, True
    )


def transpose(sparse_matrix: DCSR_matrix) -> DCSR_matrix:
    """Transpose a DCSR matrix (COO index swap; a row split becomes
    unrepresentable after transposition — result is split=None, matching the
    reference's CSR-rows-only constraint)."""
    bcoo = sparse_matrix.larray
    swapped = jsparse.BCOO(
        (bcoo.data, bcoo.indices[:, ::-1]),
        shape=(bcoo.shape[1], bcoo.shape[0]),
    ).sum_duplicates()
    return DCSR_matrix(
        swapped,
        sparse_matrix.gnnz,
        (sparse_matrix.shape[1], sparse_matrix.shape[0]),
        sparse_matrix.dtype,
        None,
        sparse_matrix.device,
        sparse_matrix.comm,
        True,
    )
