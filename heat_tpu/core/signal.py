"""Signal processing (reference: ``heat/core/signal.py``).

1-D ``convolve`` with full/same/valid modes.  The reference exchanges halos
(Isend/Irecv with neighbors) and runs local ``torch.conv1d``; here the
default path is one global XLA convolution (the partitioner materializes the
boundary exchange), and an explicit shard_map halo path
(``parallel.halo``) demonstrates the manual-control skeleton.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import types
from .dndarray import DNDarray
from .sanitation import sanitize_in

__all__ = ["convolve", "convolve2d"]


def _conv1d_full(a: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    """Full correlation-free convolution via XLA conv (MXU-eligible)."""
    n, m = a.shape[0], v.shape[0]
    # conv_general_dilated computes correlation; flip the kernel for convolution
    lhs = a.reshape(1, 1, n)
    rhs = v[::-1].reshape(1, 1, m)
    out = jax.lax.conv_general_dilated(
        lhs, rhs, window_strides=(1,), padding=[(m - 1, m - 1)]
    )
    return out.reshape(-1)


def convolve(a: DNDarray, v: DNDarray, mode: str = "full", stride: int = 1) -> DNDarray:
    """Discrete 1-D convolution of ``a`` with kernel ``v`` (numpy modes)."""
    from . import factories

    if not isinstance(a, DNDarray):
        a = factories.array(a)
    if not isinstance(v, DNDarray):
        v = factories.array(v)
    if a.ndim != 1 or v.ndim != 1:
        raise ValueError("convolve requires 1-D inputs")
    if mode not in ("full", "same", "valid"):
        raise ValueError(f"Unsupported mode {mode!r}")
    if stride != 1:
        raise NotImplementedError("stride != 1 not supported (reference parity)")
    n, m = a.shape[0], v.shape[0]
    signal = a  # output metadata follows the SIGNAL even if operands swap
    if n < m:
        a, v = v, a
        n, m = m, n
    dt = types.promote_types(a.dtype, v.dtype)
    if types.heat_type_is_exact(dt):
        work_dt = types.float32
    else:
        work_dt = dt
    ja = a._jarray.astype(work_dt.jax_dtype())
    jv = v._jarray.astype(work_dt.jax_dtype())

    full = _conv1d_full(ja, jv)
    if mode == "full":
        res = full
    elif mode == "same":
        lo = (m - 1) // 2
        res = full[lo : lo + n]
    else:  # valid
        res = full[m - 1 : m - 1 + n - m + 1]
    if types.heat_type_is_exact(dt):
        res = jnp.round(res).astype(dt.jax_dtype())
    split = signal.split
    res = signal.comm.shard(res, split)
    return DNDarray(
        res, tuple(res.shape), types.canonical_heat_type(res.dtype), split,
        signal.device, signal.comm, True,
    )


def convolve2d(a: DNDarray, v: DNDarray, mode: str = "full") -> DNDarray:
    """2-D convolution (extension beyond the reference's 1-D surface)."""
    from . import factories

    if not isinstance(a, DNDarray):
        a = factories.array(a)
    if not isinstance(v, DNDarray):
        v = factories.array(v)
    if a.ndim != 2 or v.ndim != 2:
        raise ValueError("convolve2d requires 2-D inputs")
    n0, n1 = a.shape
    m0, m1 = v.shape
    lhs = a._jarray.astype(jnp.float32).reshape(1, 1, n0, n1)
    rhs = v._jarray.astype(jnp.float32)[::-1, ::-1].reshape(1, 1, m0, m1)
    if mode == "full":
        pad = [(m0 - 1, m0 - 1), (m1 - 1, m1 - 1)]
    elif mode == "same":
        pad = [((m0 - 1) // 2, m0 // 2), ((m1 - 1) // 2, m1 // 2)]
    elif mode == "valid":
        pad = [(0, 0), (0, 0)]
    else:
        raise ValueError(f"Unsupported mode {mode!r}")
    out = jax.lax.conv_general_dilated(lhs, rhs, window_strides=(1, 1), padding=pad)
    res = out.reshape(out.shape[2], out.shape[3])
    res = a.comm.shard(res, a.split)
    return DNDarray(
        res, tuple(res.shape), types.canonical_heat_type(res.dtype), a.split, a.device, a.comm, True
    )
