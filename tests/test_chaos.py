"""Chaos lane: crash-recovery under real process death (ISSUE 2 robustness;
elastic restart-with-resume in ISSUE 5).

Three scenario families:

- **kill mid-save** (ISSUE 2): a victim subprocess is SIGKILLed in the
  middle of ``save_array_checkpoint`` — the fault site ``io.write`` is
  armed (via ``HEAT_TPU_FAULTS``) with a per-chunk delay so the kill
  deterministically lands inside the chunk-write loop — and the parent
  then asserts the previous checkpoint version still loads bit-exact.
- **collective hang** (ISSUE 5): an injected ``comm.collective`` hang
  under an armed ``comm.deadline`` raises ``CollectiveTimeoutError``
  within the budget (``health.deadline.trips`` asserted) instead of
  blocking the suite.
- **kill-and-resume** (ISSUE 5 acceptance): one rank of a 2-process DASO
  training world is SIGKILLed mid-training via the ``proc.exit`` fault
  site; the supervising launcher restarts the world and training resumes
  from the newest verified checkpoint (``RESUMED epoch=1`` marker),
  reaching the target step having lost at most ``checkpoint_every``
  steps.

(A fourth family — the ISSUE 10 serving scenario, where a rank is
SIGKILLed mid-job-queue and the scheduler's journal replay must lose zero
accepted jobs — lives in tests/test_multiprocess.py
``test_serve_sigkill_mid_queue_loses_zero_jobs``, chaos-marked so this
lane runs it too.)

No amount of in-process mocking proves these the way a real SIGKILL does.

Marked ``chaos`` (+ ``slow``/``heavy``): runs in the dedicated chaos CI job,
not in the quick verify lane.
"""

import importlib.util
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

pytestmark = [pytest.mark.chaos, pytest.mark.slow, pytest.mark.heavy]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "multiprocess_dryrun_chaos",
    os.path.join(REPO, "scripts", "multiprocess_dryrun.py"),
)
mpd = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(mpd)

# the victim: phase "seed" completes a checkpoint; phase "victim" starts a
# second save (announcing SAVING first so the parent can time its kill)
VICTIM = """
import os, sys
import numpy as np
ckpt, phase = sys.argv[1], sys.argv[2]
import heat_tpu as ht

n = 64
if phase == "seed":
    ht.save_array_checkpoint(ht.array(np.arange(n, dtype=np.float32) * 1.5, split=0), ckpt)
    print("SEEDED", flush=True)
else:
    x = ht.array(np.arange(n, dtype=np.float32) * -2.0, split=0)
    print("SAVING", flush=True)
    ht.save_array_checkpoint(x, ckpt)
    print("COMPLETED", flush=True)  # must never be reached (killed mid-save)
"""


def _env(faults_spec: str = "") -> dict:
    env = {k: v for k, v in os.environ.items() if k not in ("XLA_FLAGS",)}
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONUNBUFFERED"] = "1"
    if faults_spec:
        env["HEAT_TPU_FAULTS"] = faults_spec
    else:
        env.pop("HEAT_TPU_FAULTS", None)
    return env


def _run_victim(script_path, ckpt, phase, faults_spec=""):
    return subprocess.Popen(
        [sys.executable, script_path, ckpt, phase],
        env=_env(faults_spec), cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )


class TestKillMidSave:
    def test_sigkill_mid_save_previous_version_survives(self, tmp_path):
        """Acceptance: after SIGKILL during ``save_array_checkpoint``,
        ``load_array_checkpoint`` returns the previous version bit-exact."""
        script = str(tmp_path / "victim.py")
        with open(script, "w") as fh:
            fh.write(VICTIM)
        ckpt = str(tmp_path / "ckpt")

        seed = _run_victim(script, ckpt, "seed")
        out, _ = seed.communicate(timeout=240)
        assert seed.returncode == 0 and "SEEDED" in out, out[-2000:]
        assert open(os.path.join(ckpt, "LATEST")).read().strip() == "v0"

        # 8 chunks x 0.5 s injected delay per write: the save needs >= 4 s
        # after SAVING — a kill 1 s in lands inside the chunk-write loop
        victim = _run_victim(script, ckpt, "victim",
                             faults_spec="io.write:delay=0.5")
        deadline = time.monotonic() + 240
        line = ""
        while time.monotonic() < deadline:
            line = victim.stdout.readline()
            if "SAVING" in line or line == "":
                break
        assert "SAVING" in line, "victim never reached the save"
        time.sleep(1.0)
        victim.send_signal(signal.SIGKILL)
        rest = victim.communicate(timeout=60)[0]
        assert victim.returncode == -signal.SIGKILL
        assert "COMPLETED" not in rest, "kill missed the save window"

        # torn v1 may exist on disk; LATEST must still name the durable v0
        assert open(os.path.join(ckpt, "LATEST")).read().strip() == "v0"

        import heat_tpu as ht

        back = ht.load_array_checkpoint(ckpt)
        np.testing.assert_array_equal(
            back.numpy(), np.arange(64, dtype=np.float32) * 1.5
        )

    def test_sigkill_then_resave_then_load(self, tmp_path):
        """After a torn save, the NEXT save must succeed and supersede the
        wreckage (the torn v-dir is skipped for version numbering and pruned
        once a complete newer version lands)."""
        script = str(tmp_path / "victim.py")
        with open(script, "w") as fh:
            fh.write(VICTIM)
        ckpt = str(tmp_path / "ckpt")

        seed = _run_victim(script, ckpt, "seed")
        out, _ = seed.communicate(timeout=240)
        assert seed.returncode == 0 and "SEEDED" in out, out[-2000:]
        victim = _run_victim(script, ckpt, "victim", faults_spec="io.write:delay=0.5")
        deadline = time.monotonic() + 240
        line = ""
        while time.monotonic() < deadline:
            line = victim.stdout.readline()
            if "SAVING" in line or line == "":
                break
        assert "SAVING" in line, "victim never reached the save"
        time.sleep(1.0)
        victim.send_signal(signal.SIGKILL)
        rest = victim.communicate(timeout=60)[0]
        assert "COMPLETED" not in rest, "kill missed the save window"

        import heat_tpu as ht

        d3 = np.arange(64, dtype=np.float32) + 7
        ht.save_array_checkpoint(ht.array(d3, split=0), ckpt)
        back = ht.load_array_checkpoint(ckpt)
        np.testing.assert_array_equal(back.numpy(), d3)


# the OOM victim: parks a dominant live buffer, then starts a budgeted
# resplit whose first tile's env-armed mem.alloc fault fires mid-plan —
# the memory ledger dumps the account into the crash-durable flight ring
# before the error re-raises, and the victim then SIGKILLs ITSELF, so the
# only surviving evidence is the harvested ring (announcing DUMPED first
# lets the parent assert ordering)
OOM_VICTIM = """
import os, signal, sys
import heat_tpu as ht
from heat_tpu.utils import memledger

park = ht.zeros((128, 128), dtype=ht.float32, split=0)  # the dominant buffer
p = ht.communication.get_comm().size
src = ht.zeros((p, 16, p), dtype=ht.float32, split=0)
print("ARMED", flush=True)
try:
    src.resplit_(2, memory_budget=2 * p * p * 4)
    print("NO-OOM", flush=True)  # must never be reached
except Exception as e:
    assert memledger.is_oom(e), e
    print("DUMPED", flush=True)
    os.kill(os.getpid(), signal.SIGKILL)  # die like a real OOM-killed rank
"""


class TestInjectedOOM:
    def test_injected_oom_mid_resplit_yields_oom_verdict_after_sigkill(
        self, tmp_path
    ):
        """Acceptance (ISSUE 14): the ``mem.alloc`` fault armed mid-resplit
        kills a rank AFTER the memory ledger dumped its account into the
        crash-durable ring; harvesting the ring post-SIGKILL must yield
        ``POSTMORTEM verdict=oom`` naming the rank, the failed request
        bytes and the top live buffer with its minting provenance intact."""
        import importlib.util

        script = tmp_path / "oom_victim.py"
        script.write_text(OOM_VICTIM)
        ring_dir = tmp_path / "flightrec"
        env = _env("mem.alloc:fail=1")
        env["HEAT_TPU_FLIGHTREC_DIR"] = str(ring_dir)
        env["HEAT_TPU_MEMLEDGER"] = "1"
        victim = subprocess.run(
            [sys.executable, str(script)],
            env=env, cwd=REPO, capture_output=True, text=True, timeout=240,
        )
        assert "DUMPED" in victim.stdout, victim.stdout + victim.stderr
        assert "NO-OOM" not in victim.stdout
        assert victim.returncode == -signal.SIGKILL, victim.returncode

        spec = importlib.util.spec_from_file_location(
            "pm_chaos_oom", os.path.join(REPO, "scripts", "postmortem.py")
        )
        pm = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(pm)
        rings = pm.load_rings(str(ring_dir))
        assert rings, "SIGKILL destroyed the ring — durability contract broken"
        verdict = pm.analyze(rings, expected_ranks=[0])
        assert verdict["verdict"] == "oom", verdict
        oom = verdict["oom"]
        assert oom["rank"] == 0
        assert oom["req_bytes"] > 0  # the failed tile allocation
        assert oom["where"] == "comm.resplit_tiled"
        # the dominant live buffer: the parked 64 KiB factory output, with
        # minting provenance (op + category) intact across the SIGKILL
        top = oom["top_buffers"][0]
        assert top["op"] == "zeros"
        assert top["nb"] == 128 * 128 * 4
        assert top["cat"] == "activation"
        line = pm.summary_line(verdict)
        assert "POSTMORTEM verdict=oom rank=0" in line
        assert "top=zeros" in line


class TestCollectiveDeadline:
    def test_injected_hang_trips_deadline_within_budget(self, ht):
        """Acceptance (ISSUE 5): an injected collective hang raises
        ``CollectiveTimeoutError`` within the armed deadline instead of
        blocking the suite, and ``health.deadline.trips`` records it."""
        from heat_tpu.utils import faults, health, profiler

        comm = ht.communication.get_comm()
        x = ht.arange(8, dtype=ht.float32, split=0)
        base = profiler.counters().get("health.deadline.trips", 0)
        t0 = time.monotonic()
        with faults.inject("comm.collective", hang=1):
            with comm.deadline(1.0):
                with pytest.raises(health.CollectiveTimeoutError):
                    comm.Wait(x._jarray)
        took = time.monotonic() - t0
        assert took < 10.0, f"deadline trip took {took:.1f}s — watchdog not arming"
        assert profiler.counters()["health.deadline.trips"] == base + 1


class TestOverlappedSyncHang:
    def test_hang_on_one_bucket_names_straggler_at_that_bucket(self, ht, tmp_path):
        """Acceptance (ISSUE 16): a ``comm.collective`` hang on ONE bucket in
        the middle of an overlapped bucketed param sync raises
        ``CollectiveTimeoutError`` at the offending bucket — not at the end of
        the step — and the flight-recorder post-mortem names this rank a
        straggler stuck at exactly that bucket's seq with op ``allreduce``.

        The seq stamp lands BEFORE the fault site fires (the
        ``_account_bytes`` contract), so the hung bucket is the rank's last
        ring record and the analyzer can convict it precisely."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from heat_tpu.core import collectives as coll
        from heat_tpu.core.communication import Communication
        from heat_tpu.utils import faults, flightrec, health, telemetry

        devs = jax.devices()
        if len(devs) != 8:
            pytest.skip("needs the 8-device test mesh")
        mesh = Mesh(np.asarray(devs).reshape(4, 2), ("dcn", "ici"))
        comm = Communication(mesh, "dcn")
        sh = NamedSharding(mesh, P("dcn"))
        params = {
            f"w{j}": jax.device_put(jnp.ones((4, 64, 3 + j), jnp.float32), sh)
            for j in range(4)
        }
        leaves = jax.tree_util.tree_leaves(params)
        plan = coll.plan_grad_buckets([a.nbytes for a in leaves], 6144)
        assert plan.n_buckets == 4  # 6144-byte budget: one bucket per leaf

        d = str(tmp_path)
        try:
            flightrec.enable(d, rank=0)
            # round 1: a clean overlapped sync — compiles the bucket
            # programs and stamps every staged collective into the ring
            params = coll.bucketed_param_sync(comm, params, 0.5, plan=plan)
            # round 2: one-shot hang — lands on the FIRST staged stage of
            # the next sync's first bucket; the armed deadline converts the
            # hang into a timeout at that bucket instead of blocking
            t0 = time.monotonic()
            with faults.inject("comm.collective", hang=1):
                with comm.deadline(1.0):
                    with pytest.raises(health.CollectiveTimeoutError):
                        coll.bucketed_param_sync(comm, params, 0.5, plan=plan)
            took = time.monotonic() - t0
            assert took < 10.0, f"hang took {took:.1f}s — deadline not arming"
        finally:
            flightrec.disable()
            telemetry._uninstall_signal_flush()

        ring = flightrec.read_ring(os.path.join(d, "flight_rank0.ring"))
        colls0 = [r for r in ring["records"] if r["k"] == "coll"]
        stuck = colls0[-1]
        assert stuck["op"] == "allreduce"
        # clean sync: the DASO bucket-average program accounts two stages
        # (cross-domain exchange + allgather) per bucket; the hang hit the
        # first stage of round 2's first bucket
        assert stuck["seq"] == 2 * plan.n_buckets + 1

        # synthetic rank-1 peer: identical op stream on the common window
        # (fingerprints must agree, else the verdict would be desync), but
        # it progressed `lag` collectives further — rank 0 is the straggler
        lag = 3
        fp_fields = ("op", "gshape", "dtype", "src", "dst", "wire")
        r1 = flightrec.FlightRecorder(
            os.path.join(d, "flight_rank1.ring"), rank=1
        )
        seq = 0
        for rec in colls0:
            seq = rec["seq"]
            r1.record(
                "coll", seq=seq, **{f: rec[f] for f in fp_fields if f in rec}
            )
        tail = {f: stuck[f] for f in fp_fields if f in stuck}
        for _ in range(lag):
            seq += 1
            r1.record("coll", seq=seq, **tail)
        r1.close()

        spec = importlib.util.spec_from_file_location(
            "pm_overlap_chaos", os.path.join(REPO, "scripts", "postmortem.py")
        )
        pm = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(pm)
        v = pm.analyze_dir(d)
        assert v["verdict"] == "straggler"
        s = v["straggler"]
        assert s["rank"] == 0 and s["op"] == "allreduce"
        assert s["seq"] == stuck["seq"]
        assert s["lag"] == lag and s["peers_at"] == stuck["seq"] + lag
        assert f"rank 0 stuck at seq {stuck['seq']}" in v["detail"]


class TestKillAndResume:
    def test_sigkill_rank_mid_daso_training_supervisor_resumes(self):
        """Acceptance (ISSUE 5): ``kill -9`` of one rank mid-DASO-training →
        the supervising launcher restarts the world → training resumes from
        the newest verified checkpoint and reaches the target step, losing
        at most ``checkpoint_every`` steps.

        ISSUE 20: the scenario is now DATA — the launch shape and the
        whole attestation contract (SIGKILL witnessed, exactly one
        restart, both ranks resumed at step 3, watchdog accounting,
        STEP-OVERLAP baseline) live in the declarative
        ``chaos.scenarios`` spec this test replays through the engine;
        the spec keeps the known-flake retry for the documented gloo
        ``op.preamble.length`` SIGABRT."""
        from heat_tpu.chaos import scenarios

        proc = scenarios.run_scenario("kill-resume-train")
        assert scenarios.check_scenario("kill-resume-train", proc) == [], (
            (proc.stderr or proc.stdout)[-3000:]
        )

    def test_world_kill_loses_zero_jobs(self):
        """Acceptance (ISSUE 17): SIGKILL an ENTIRE world (world 1 of 2)
        mid-queue → the federation steals its non-terminal jobs, the
        survivor resizes and serves them, and the journal-derived
        attestation proves ``FED worlds=2 lost=0`` with the shed giant
        accounted (12 jobs + 1).  The full contract — HTTP-edge shed with
        the structured 429, quarantine with stolen>=1, degraded-but-200
        healthz, elastic resize, a stolen job served end-to-end — is the
        declarative ``fed-world-kill`` spec (ISSUE 20)."""
        from heat_tpu.chaos import scenarios

        proc = scenarios.run_scenario("fed-world-kill")
        assert scenarios.check_scenario("fed-world-kill", proc) == [], (
            (proc.stderr or proc.stdout)[-3000:]
        )

    def test_supervised_dryrun_restart_budget_give_up(self):
        """A rank that dies on EVERY generation exhausts the restart budget
        and the launcher prints the merged diagnostic report instead of
        retrying forever."""
        proc = mpd.launch(
            timeout=700,
            n_proc=2,
            devs_per_proc=4,
            mode="train",
            extra_env={
                "MPDRYRUN_TARGET_STEPS": 8,
                "MPDRYRUN_CKPT_EVERY": 3,
                # a persistently bad node: the fault re-arms on EVERY
                # generation, so every restart dies again and the budget
                # must run out
                "MPDRYRUN_FAULT_RANK": 1,
                "MPDRYRUN_FAULT_SPEC": "proc.exit:exit=2",
                "MPDRYRUN_FAULT_EVERY_EPOCH": 1,
                "MPDRYRUN_STEP_DELAY": 0.1,
                "MPDRYRUN_RESTARTS": 1,
            },
        )
        out = proc.stdout
        assert proc.returncode != 0
        assert "SUPERVISOR GAVE UP" in out, out[-3000:]
        assert "MULTIPROCESS DRYRUN: FAIL" in out
        assert '"restarts": 1' in out  # budget honored, not a retry loop
