"""heat_tpu benchmark — prints ONE JSON line for the driver.

Primary metric (BASELINE.md): distributed-matmul TFLOPS/chip on the
`ht.matmul` path (config[0]: 4096x4096 float32).  vs_baseline is measured
against torch-CPU running the identical GEMM on this host (the only
reference implementation available in this environment — BASELINE.json has
no published numbers and the reference mount is empty).
Secondary numbers (KMeans iter/s, TSQR) ride along in "extra".

Timing notes: on the tunneled axon platform ``block_until_ready`` does not
actually block, so completion is forced by fetching a scalar.  METHODOLOGY
(changed from the first revision, numbers are not comparable to it): the
CHAIN GEMMs run as ONE fused jitted ``lax.scan`` program through the public
``ht.matmul``, so per-GEMM time measures on-device compute and excludes
per-dispatch/tunnel latency entirely; the chained values are rescaled each
step to stay finite in float32.
"""

from __future__ import annotations

import json
import time

import numpy as np

CHAIN = 100


def main() -> None:
    import jax
    import jax.numpy as jnp

    import heat_tpu as ht

    n = 4096
    flops = 2.0 * n * n * n

    # --- heat_tpu distributed matmul (split=0 @ split=1), f32 ------------ #
    a = ht.random.randn(n, n, dtype=ht.float32, split=0)
    b = ht.random.randn(n, n, dtype=ht.float32, split=1)

    # the chain runs through the framework's public matmul (DNDarray is a
    # pytree, so the whole chain is ONE jitted XLA program — per-GEMM cost
    # is measured without per-dispatch tunnel latency)
    import functools

    import jax as _jax

    scale = float(1.0 / np.sqrt(n))  # keeps the chained values finite in f32

    @functools.partial(_jax.jit, static_argnames="iters")
    def chain(a, b, iters):
        import heat_tpu as _ht

        def body(c, _):
            return (_ht.matmul(c, b) * scale), None

        c, _ = _jax.lax.scan(body, a, None, length=iters)
        return c

    float(chain(a, b, CHAIN)._jarray[0, 0])  # compile + warm
    t0 = time.perf_counter()
    c = chain(a, b, CHAIN)
    _ = float(c._jarray[0, 0])  # forces completion through the tunnel
    t_ht = (time.perf_counter() - t0) / CHAIN
    tflops = flops / t_ht / 1e12
    n_chips = max(len(jax.devices()), 1)
    tflops_per_chip = tflops / n_chips

    extra = {"platform": jax.devices()[0].platform, "n_chips": n_chips,
             "matmul_wallclock_s": round(t_ht, 6), "chain_iters": CHAIN}

    # --- torch-CPU reference for the same GEMM --------------------------- #
    try:
        import torch

        ta = torch.randn(n, n, dtype=torch.float32)
        tb = torch.randn(n, n, dtype=torch.float32)
        ta @ tb  # warmup
        t0 = time.perf_counter()
        tc = ta @ tb
        t_torch = time.perf_counter() - t0
        extra["torch_cpu_wallclock_s"] = round(t_torch, 5)
        vs_baseline = t_torch / t_ht  # speedup over torch-CPU wall-clock
    except Exception:
        vs_baseline = 1.0

    # --- KMeans iter/sec (scaled-down config[2]) ------------------------- #
    try:
        X = ht.random.randn(2**17, 32, dtype=ht.float32, split=0)
        km = ht.cluster.KMeans(n_clusters=64, max_iter=2, tol=0.0, random_state=0, init="random")
        km.fit(X)  # compile
        t0 = time.perf_counter()
        km2 = ht.cluster.KMeans(n_clusters=64, max_iter=10, tol=0.0, random_state=0, init="random")
        km2.fit(X)
        t_km = (time.perf_counter() - t0) / km2.n_iter_
        extra["kmeans_131k_x32_k64_iter_per_s"] = round(1.0 / t_km, 3)
    except Exception as e:
        extra["kmeans_error"] = str(e)[:80]

    print(json.dumps({
        "metric": "dist_matmul_4096_f32_tflops_per_chip",
        "value": round(tflops_per_chip, 3),
        "unit": "TFLOPS/chip",
        "vs_baseline": round(vs_baseline, 3),
        "extra": extra,
    }))


if __name__ == "__main__":
    main()
