"""Pallas kernel tests (interpret mode on the CPU mesh)."""

import numpy as np
import pytest

import heat_tpu as ht

# long-tail contract tests: nightly-style lane (CI 'test' matrix), excluded
# from the PR smoke lane (VERDICT r4 weak #7)
pytestmark = pytest.mark.heavy


class TestFusedAssign:
    def test_matches_oracle(self):
        import jax.numpy as jnp

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(1000, 32)).astype(np.float32))
        c = jnp.asarray(rng.normal(size=(16, 32)).astype(np.float32))
        lab, d2 = ht.ops.fused_assign(x, c)
        D = ((np.asarray(x)[:, None, :] - np.asarray(c)[None, :, :]) ** 2).sum(-1)
        np.testing.assert_array_equal(np.asarray(lab), D.argmin(1))
        np.testing.assert_allclose(np.asarray(d2), D.min(1), atol=1e-2)

    def test_ragged_rows(self):
        import jax.numpy as jnp

        rng = np.random.default_rng(1)
        # row count not divisible by the kernel tile → padding path
        x = jnp.asarray(rng.normal(size=(1537, 8)).astype(np.float32))
        c = jnp.asarray(rng.normal(size=(5, 8)).astype(np.float32))
        lab, d2 = ht.ops.fused_assign(x, c)
        assert lab.shape == (1537,)
        D = ((np.asarray(x)[:, None, :] - np.asarray(c)[None, :, :]) ** 2).sum(-1)
        np.testing.assert_array_equal(np.asarray(lab), D.argmin(1))


class TestFusedEMStats:
    """Fused assign+accumulate kernel (round-4: wired into KMeans via
    assign_kernel='pallas'; interpret mode on CPU)."""

    def test_matches_oracle_with_pad(self):
        import jax.numpy as jnp

        from heat_tpu.ops.kmeans_kernels import fused_em_stats

        rng = np.random.default_rng(0)
        x = rng.standard_normal((2000, 16)).astype(np.float32)
        c = rng.standard_normal((8, 16)).astype(np.float32)
        n = 1987  # tail rows are pad: must contribute nothing
        s, cnt = fused_em_stats(jnp.asarray(x), jnp.asarray(c), n)
        d2 = ((x[:n, None, :] - c[None, :, :]) ** 2).sum(-1)
        lab = d2.argmin(1)
        want_s = np.zeros((8, 16), np.float32)
        want_c = np.zeros(8, np.float32)
        for i, l in enumerate(lab):
            want_s[l] += x[i]
            want_c[l] += 1
        np.testing.assert_allclose(np.asarray(cnt), want_c)
        np.testing.assert_allclose(np.asarray(s), want_s, rtol=1e-4, atol=1e-3)

    def test_kmeans_kernel_matches_jnp(self):
        """assign_kernel='pallas' is the same estimator: identical centers,
        labels, inertia on both fit paths (sharded + global)."""
        from sklearn.datasets import make_blobs

        X, _ = make_blobs(n_samples=1500, centers=5, n_features=8, random_state=0)
        X = X.astype(np.float32)
        for split in (0, None):
            hx = ht.array(X, split=split)
            kj = ht.cluster.KMeans(n_clusters=5, random_state=0, init="random",
                                   assign_kernel="jnp").fit(hx)
            kp = ht.cluster.KMeans(n_clusters=5, random_state=0, init="random",
                                   assign_kernel="pallas").fit(hx)
            np.testing.assert_allclose(
                kj.cluster_centers_.numpy(), kp.cluster_centers_.numpy(), rtol=1e-4, atol=1e-4
            )
            np.testing.assert_array_equal(kj.labels_.numpy(), kp.labels_.numpy())
            np.testing.assert_array_equal(kp.predict(hx).numpy(), kj.predict(hx).numpy())

    def test_assign_kernel_validation(self):
        import pytest

        with pytest.raises(ValueError):
            ht.cluster.KMeans(assign_kernel="bogus")


class TestFlashAttention:
    """Flash-fused local attention (round-4b): the (S, S) score matrix never
    materializes.  Interpret mode on the CPU mesh; the same pallas_call runs
    compiled on TPU."""

    def _dense(self, q, k, v, causal):
        import jax.numpy as jnp

        from heat_tpu.ops.flash_attention import _dense_attention

        return _dense_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal,
            1.0 / np.sqrt(q.shape[-1]), q.shape[-2],
        )

    def test_matches_dense(self):
        import jax.numpy as jnp

        from heat_tpu.ops.flash_attention import flash_attention, path_counts

        rng = np.random.default_rng(0)
        before = path_counts["pallas"]
        for shape in ((2, 3, 64, 16), (1, 97, 8), (2, 300, 32)):
            q, k, v = (jnp.asarray(rng.normal(size=shape), jnp.float32)
                       for _ in range(3))
            for causal in (False, True):
                out = flash_attention(q, k, v, causal=causal)
                ref = self._dense(q, k, v, causal)
                np.testing.assert_allclose(
                    np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
                )
        # every call above actually took the Pallas path (S <= 512 on CPU)
        assert path_counts["pallas"] >= before + 6

    def test_bf16_accumulates_f32(self):
        import jax.numpy as jnp

        from heat_tpu.ops.flash_attention import flash_attention

        rng = np.random.default_rng(1)
        q, k, v = (jnp.asarray(rng.normal(size=(2, 2, 96, 16)), jnp.bfloat16)
                   for _ in range(3))
        out = flash_attention(q, k, v, causal=True)
        assert out.dtype == jnp.bfloat16
        ref = self._dense(np.float32(q), np.float32(k), np.float32(v), True)
        np.testing.assert_allclose(
            np.float32(out), np.asarray(ref), rtol=5e-2, atol=5e-2
        )

    def test_large_s_falls_back_dense_on_cpu(self):
        import jax.numpy as jnp

        from heat_tpu.ops.flash_attention import flash_attention, path_counts

        rng = np.random.default_rng(2)
        q, k, v = (jnp.asarray(rng.normal(size=(1, 600, 8)), jnp.float32)
                   for _ in range(3))
        before = path_counts["dense"]
        out = flash_attention(q, k, v)
        assert path_counts["dense"] == before + 1
        ref = self._dense(q, k, v, False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_grad_matches_dense_and_stays_pallas(self):
        """custom_vjp: jax.grad runs the flash backward kernels (dq + dk/dv
        sweeps) — training never silently falls back to the (S, S)-
        materializing dense path (round-4b review finding)."""
        import jax
        import jax.numpy as jnp

        from heat_tpu.ops.flash_attention import (
            _dense_attention, flash_attention, path_counts,
        )

        rng = np.random.default_rng(7)
        shape = (2, 2, 96, 16)
        q, k, v = (jnp.asarray(rng.normal(size=shape), jnp.float32)
                   for _ in range(3))
        w = jnp.asarray(rng.normal(size=shape), jnp.float32)
        before = path_counts["pallas"]
        gf = jax.grad(
            lambda q, k, v: jnp.sum(flash_attention(q, k, v, causal=True) * w),
            argnums=(0, 1, 2),
        )(q, k, v)
        assert path_counts["pallas"] == before + 1  # grad did NOT fall back
        gd = jax.grad(
            lambda q, k, v: jnp.sum(
                _dense_attention(q, k, v, True, 0.25, 96) * w
            ),
            argnums=(0, 1, 2),
        )(q, k, v)
        for a, b in zip(gf, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-5)

    def test_shape_mismatch_raises(self):
        import jax.numpy as jnp
        import pytest

        from heat_tpu.ops.flash_attention import flash_attention

        q = jnp.zeros((1, 8, 4))
        k = jnp.zeros((1, 9, 4))
        with pytest.raises(ValueError):
            flash_attention(q, k, q)

    def test_ring_size1_routes_through_flash(self):
        import jax.numpy as jnp

        from heat_tpu.ops.flash_attention import path_counts as flash_counts
        from heat_tpu.parallel.ring_attention import ring_attention

        import jax
        from jax.sharding import Mesh

        comm = ht.communication.Communication(
            Mesh(np.asarray(jax.devices()[:1]), ("x",))
        )
        rng = np.random.default_rng(3)
        q, k, v = (jnp.asarray(rng.normal(size=(2, 40, 8)), jnp.float32)
                   for _ in range(3))
        before = flash_counts["pallas"]
        out = ring_attention(q, k, v, comm, causal=True)
        assert flash_counts["pallas"] == before + 1
        ref = self._dense(q, k, v, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


class TestFlashGQA:
    """Grouped-query attention kernel: each query head reads its group's
    K/V head straight from the grid index map — no repeated K/V in HBM,
    forward or backward (the dk/dv sweep accumulates a whole group through
    one scratch).  Oracle: dense attention over an explicit repeat."""

    def _ref(self, q, k, v, causal):
        import jax.numpy as jnp

        from heat_tpu.ops.flash_attention import _dense_attention

        g = q.shape[-3] // k.shape[-3]
        return _dense_attention(
            q, jnp.repeat(k, g, axis=-3), jnp.repeat(v, g, axis=-3),
            causal, q.shape[-1] ** -0.5, q.shape[-2],
        )

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("heads", [(4, 2), (4, 1)])  # GQA and MQA
    def test_matches_repeat_oracle(self, heads, causal):
        import jax.numpy as jnp

        from heat_tpu.ops.flash_attention import (
            flash_attention_gqa, path_counts,
        )

        hq, hk = heads
        rng = np.random.default_rng(hq * 10 + hk)
        B, S, d = 2, 40, 8
        q = jnp.asarray(rng.normal(size=(B, hq, S, d)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, hk, S, d)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, hk, S, d)), jnp.float32)
        before = path_counts["pallas"]
        out = flash_attention_gqa(q, k, v, causal=causal)
        assert path_counts["pallas"] == before + 1  # kernel, not fallback
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(self._ref(q, k, v, causal)),
            rtol=1e-5, atol=1e-5,
        )

    def test_grads_match_repeat_oracle(self):
        """dk/dv arrive in K/V-head shape (the group-summed gradient) and
        match differentiating the dense repeat."""
        import jax
        import jax.numpy as jnp

        from heat_tpu.ops.flash_attention import flash_attention_gqa

        rng = np.random.default_rng(3)
        B, hq, hk, S, d = 2, 4, 2, 37, 8  # ragged S exercises pad keys
        q = jnp.asarray(rng.normal(size=(B, hq, S, d)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, hk, S, d)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, hk, S, d)), jnp.float32)
        w = jnp.asarray(rng.normal(size=(B, hq, S, d)), jnp.float32)
        g = jax.grad(
            lambda q, k, v: jnp.sum(flash_attention_gqa(q, k, v, causal=True) * w),
            (0, 1, 2))(q, k, v)
        gr = jax.grad(
            lambda q, k, v: jnp.sum(self._ref(q, k, v, True) * w),
            (0, 1, 2))(q, k, v)
        assert g[1].shape == k.shape and g[2].shape == v.shape
        for a, b in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_sdpa_routes_gqa_to_kernel(self):
        import jax.numpy as jnp

        import heat_tpu as ht
        from heat_tpu.ops.flash_attention import path_counts

        rng = np.random.default_rng(4)
        q = jnp.asarray(rng.normal(size=(2, 4, 24, 8)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(2, 1, 24, 8)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(2, 1, 24, 8)), jnp.float32)
        before = path_counts["pallas"]
        y = ht.nn.functional.scaled_dot_product_attention(
            q, k, v, is_causal=True, enable_gqa=True)
        assert path_counts["pallas"] == before + 1
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(self._ref(q, k, v, True)),
            rtol=1e-5, atol=1e-5,
        )

    def test_shape_validation(self):
        import jax.numpy as jnp

        from heat_tpu.ops.flash_attention import flash_attention_gqa

        q = jnp.zeros((2, 3, 8, 4))
        kv = jnp.zeros((2, 2, 8, 4))
        with pytest.raises(ValueError, match="multiple"):
            flash_attention_gqa(q, kv, kv)

    def test_sdpa_gqa_broadcastable_batch_still_works(self):
        """Unequal-but-broadcastable leading axes must keep the repeat +
        dense einsum path (regression: the kernel route briefly rejected
        them)."""
        import jax.numpy as jnp

        import heat_tpu as ht

        rng = np.random.default_rng(6)
        q = jnp.asarray(rng.normal(size=(2, 4, 24, 8)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(1, 1, 24, 8)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(1, 1, 24, 8)), jnp.float32)
        y = ht.nn.functional.scaled_dot_product_attention(
            q, k, v, is_causal=True, enable_gqa=True)
        kb = jnp.broadcast_to(k, (2, 1, 24, 8))
        vb = jnp.broadcast_to(v, (2, 1, 24, 8))
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(self._ref(q, kb, vb, True)),
            rtol=1e-5, atol=1e-5,
        )
