"""Linalg tests (reference: heat/core/linalg/tests/)."""

import numpy as np
import pytest

import heat_tpu as ht

from test_suites.basic_test import TestCase

SPLITS_2D = [None, 0, 1]


# first mp batch for the linalg lane (VERDICT r5 weak #6): matmul + QR run
# SPMD across OS processes in the -m mp tier — data is seeded numpy / seeded
# ht.random, so every rank collects and computes identically
class TestMatmul(TestCase):
    pytestmark = pytest.mark.mp
    def test_matmul_split_cases(self):
        rng = np.random.default_rng(1)
        a = rng.normal(size=(16, 8)).astype(np.float32)
        b = rng.normal(size=(8, 24)).astype(np.float32)
        expected = a @ b
        for sa in SPLITS_2D:
            for sb in SPLITS_2D:
                ha = ht.array(a, split=sa)
                hb = ht.array(b, split=sb)
                hc = ha @ hb
                self.assert_array_equal(hc, expected, rtol=1e-4, atol=1e-4)

    def test_matmul_result_split(self):
        a = ht.ones((16, 8), split=0)
        b = ht.ones((8, 24))
        assert (a @ b).split == 0
        c = ht.ones((16, 8))
        d = ht.ones((8, 24), split=1)
        assert (c @ d).split == 1

    def test_matmul_vector(self):
        rng = np.random.default_rng(2)
        a = rng.normal(size=(8, 6)).astype(np.float32)
        v = rng.normal(size=6).astype(np.float32)
        self.assert_array_equal(ht.matmul(ht.array(a, split=0), ht.array(v)), a @ v, rtol=1e-4)

    def test_summa(self):
        from heat_tpu.linalg.basics import matmul_summa

        rng = np.random.default_rng(3)
        a = rng.normal(size=(32, 32)).astype(np.float32)
        b = rng.normal(size=(32, 32)).astype(np.float32)
        res = matmul_summa(ht.array(a, split=0), ht.array(b, split=0))
        self.assert_array_equal(res, a @ b, rtol=1e-3, atol=1e-3)
        assert res.split == 0

    def test_matmul_summa_auto_dispatch(self, monkeypatch):
        """matmul(method='auto') consults the measured (platform, p) table
        (VERDICT r4 weak #4 reopened): SUMMA only for 2-D split0×split0
        products at/above the measured crossover, GSPMD everywhere else;
        explicit method= forces either path."""
        from heat_tpu.linalg import basics

        rng = np.random.default_rng(5)
        a = rng.normal(size=(96, 96)).astype(np.float32)
        b = rng.normal(size=(96, 96)).astype(np.float32)
        ha, hb = ht.array(a, split=0), ht.array(b, split=0)
        comm = ha.comm
        platform = comm.mesh.devices.flat[0].platform

        calls = []
        real_summa = basics.matmul_summa
        monkeypatch.setattr(basics, "matmul_summa",
                            lambda *x: (calls.append(1), real_summa(*x))[1])

        # below the crossover: GSPMD
        monkeypatch.setattr(basics, "_SUMMA_DISPATCH", {(platform, comm.size): 128})
        self.assert_array_equal(basics.matmul(ha, hb), a @ b, rtol=1e-3, atol=1e-3)
        assert not calls
        # at/above the crossover: the ring path, same numbers and split —
        # except at p=1, where auto NEVER dispatches (nothing to ring over)
        monkeypatch.setattr(basics, "_SUMMA_DISPATCH", {(platform, comm.size): 64})
        res = basics.matmul(ha, hb)
        if comm.size > 1:
            assert calls and res.split == 0  # ring path, split preserved
        else:
            assert not calls and res.split in (0, None)
        self.assert_array_equal(res, a @ b, rtol=1e-3, atol=1e-3)
        # other split cases never dispatch, whatever the table says
        calls.clear()
        basics.matmul(ht.array(a, split=1), hb)
        basics.matmul(ht.array(a), hb)
        assert not calls
        # forced paths + validation
        basics.matmul(ha, hb, method="gspmd")
        assert not calls
        basics.matmul(ha, hb, method="summa")
        assert calls
        with pytest.raises(ValueError, match="method"):
            basics.matmul(ha, hb, method="ring")
        # the real committed table keeps 2048² on GSPMD on the cpu p=8 mesh
        # (r5 interleaved measurement: GSPMD 1.04-1.14x there, SUMMA wins
        # only from 4096 up)
        monkeypatch.undo()
        assert basics._SUMMA_DISPATCH.get(("cpu", 8)) == 4096

    def test_dot_outer_trace(self):
        x = np.arange(5.0, dtype=np.float32)
        y = np.arange(5.0, dtype=np.float32) + 1
        assert ht.dot(ht.array(x, split=0), ht.array(y, split=0)).item() == pytest.approx(x @ y)
        self.assert_array_equal(ht.linalg.outer(ht.array(x), ht.array(y)), np.outer(x, y))
        m = np.arange(9.0, dtype=np.float32).reshape(3, 3)
        assert ht.linalg.trace(ht.array(m, split=0)).item() == pytest.approx(np.trace(m))

    def test_transpose_norm(self):
        m = np.arange(24.0, dtype=np.float32).reshape(4, 6)
        for split in SPLITS_2D:
            a = ht.array(m, split=split)
            self.assert_array_equal(ht.transpose(a), m.T)
            assert ht.norm(a).item() == pytest.approx(np.linalg.norm(m), rel=1e-4)
        a = ht.array(m, split=0)
        assert a.T.split == 1
        self.assert_array_equal(ht.linalg.vector_norm(a, axis=1), np.linalg.norm(m, axis=1), rtol=1e-4)

    def test_tril_triu(self):
        m = np.arange(16.0, dtype=np.float32).reshape(4, 4)
        for split in SPLITS_2D:
            a = ht.array(m, split=split)
            self.assert_array_equal(ht.linalg.tril(a), np.tril(m))
            self.assert_array_equal(ht.linalg.triu(a, 1), np.triu(m, 1))


class TestQR(TestCase):
    pytestmark = pytest.mark.mp

    def test_tsqr_tall_skinny(self):
        rng = np.random.default_rng(4)
        a = rng.normal(size=(64, 8)).astype(np.float32)
        for split in [None, 0, 1]:
            ha = ht.array(a, split=split)
            q, r = ht.linalg.qr(ha)
            np.testing.assert_allclose(q.numpy() @ r.numpy(), a, atol=1e-4)
            np.testing.assert_allclose(q.numpy().T @ q.numpy(), np.eye(8), atol=1e-4)
            # R upper triangular
            np.testing.assert_allclose(np.tril(r.numpy(), -1), 0, atol=1e-4)
        q, r = ht.linalg.qr(ht.array(a, split=0))
        assert q.split == 0

    def test_qr_mode_r(self):
        a = np.random.default_rng(5).normal(size=(32, 4)).astype(np.float32)
        res = ht.linalg.qr(ht.array(a, split=0), mode="r")
        assert res.Q is None
        assert res.R.shape == (4, 4)

    def test_qr_ragged(self):
        # 30 rows on 8 devices: ragged fallback path
        a = np.random.default_rng(6).normal(size=(30, 4)).astype(np.float32)
        q, r = ht.linalg.qr(ht.array(a, split=0))
        np.testing.assert_allclose(q.numpy() @ r.numpy(), a, atol=1e-4)

    def test_qr_methods_agree(self):
        """cholqr2 (the MXU-shaped round-4 local factorization) and
        householder produce the same factorization up to column signs, and
        both are orthogonal to f32 working precision."""
        rng = np.random.default_rng(11)
        a = rng.normal(size=(512, 16)).astype(np.float32)
        ha = ht.array(a, split=0)
        for method in ("cholqr2", "householder"):
            q, r = ht.linalg.qr(ha, method=method)
            np.testing.assert_allclose(q.numpy() @ r.numpy(), a, atol=1e-4)
            np.testing.assert_allclose(
                q.numpy().T @ q.numpy(), np.eye(16), atol=1e-4
            )
            np.testing.assert_allclose(np.tril(r.numpy(), -1), 0, atol=1e-5)

    def test_qr_cholqr2_illconditioned_fallback(self):
        """kappa ~ 1e7 breaks the Gram Cholesky (kappa^2 >> 1/eps_f32); the
        in-jit lax.cond must fall back to Householder per shard and still
        return an orthogonal Q."""
        rng = np.random.default_rng(12)
        u, _ = np.linalg.qr(rng.normal(size=(1024, 16)))
        v, _ = np.linalg.qr(rng.normal(size=(16, 16)))
        bad = ((u * np.logspace(0, -7, 16)) @ v).astype(np.float32)
        q, r = ht.linalg.qr(ht.array(bad, split=0), method="cholqr2")
        np.testing.assert_allclose(q.numpy() @ r.numpy(), bad, atol=1e-5)
        np.testing.assert_allclose(q.numpy().T @ q.numpy(), np.eye(16), atol=1e-3)

    def test_qr_method_validation(self):
        import pytest

        a = ht.array(np.eye(8, 4, dtype=np.float32), split=0)
        with pytest.raises(ValueError):
            ht.linalg.qr(a, method="bogus")


class TestSVD(TestCase):
    def test_tssvd(self):
        rng = np.random.default_rng(7)
        a = rng.normal(size=(64, 8)).astype(np.float32)
        u, s, v = ht.linalg.svd(ht.array(a, split=0))
        np.testing.assert_allclose(
            u.numpy() @ np.diag(s.numpy()) @ v.numpy().T, a, atol=1e-3
        )
        np.testing.assert_allclose(s.numpy(), np.linalg.svd(a, compute_uv=False), rtol=1e-3)

    def test_hsvd_rank(self):
        from heat_tpu.utils.data.matrixgallery import random_known_rank

        A, (u, sv, v) = random_known_rank(64, 32, 5, split=0)
        U, s, V, err = ht.linalg.svdtools.hsvd_rank(A, 5, compute_sv=True)
        assert U.shape == (64, 5)
        assert err < 1e-3
        np.testing.assert_allclose(np.sort(s.numpy())[::-1][:5], np.sort(sv.numpy())[::-1], rtol=1e-2)

    def test_hsvd_rtol(self):
        from heat_tpu.utils.data.matrixgallery import random_known_rank

        A, _ = random_known_rank(64, 32, 5, split=0)
        U, s, V, err = ht.linalg.svdtools.hsvd_rtol(A, 1e-4, compute_sv=True)
        assert err < 1e-3

    def test_rsvd(self):
        from heat_tpu.utils.data.matrixgallery import random_known_rank

        A, (u, sv, v) = random_known_rank(64, 32, 5, split=0)
        U, s, V = ht.linalg.svdtools.rsvd(A, 5)
        np.testing.assert_allclose(np.sort(s.numpy())[::-1], np.sort(sv.numpy())[::-1], rtol=1e-2)


class TestSolvers(TestCase):
    def test_cg(self):
        rng = np.random.default_rng(8)
        a = rng.normal(size=(16, 16)).astype(np.float32)
        spd = a @ a.T + 16 * np.eye(16, dtype=np.float32)
        b = rng.normal(size=16).astype(np.float32)
        x = ht.linalg.solver.cg(ht.array(spd, split=0), ht.array(b))
        np.testing.assert_allclose(spd @ x.numpy(), b, atol=1e-3)

    def test_lanczos(self):
        rng = np.random.default_rng(9)
        a = rng.normal(size=(16, 16)).astype(np.float32)
        spd = a @ a.T + 16 * np.eye(16, dtype=np.float32)
        V, T = ht.linalg.solver.lanczos(ht.array(spd, split=0), 16)
        # Lanczos with full reorthogonalization reproduces the spectrum
        evals = np.sort(np.linalg.eigvalsh(T.numpy()))
        expected = np.sort(np.linalg.eigvalsh(spd))
        np.testing.assert_allclose(evals[-4:], expected[-4:], rtol=1e-2)

    def test_solve_triangular(self):
        rng = np.random.default_rng(10)
        L = np.tril(rng.normal(size=(8, 8)).astype(np.float32)) + 8 * np.eye(8, dtype=np.float32)
        b = rng.normal(size=(8, 2)).astype(np.float32)
        x = ht.linalg.solver.solve_triangular(ht.array(L, split=0), ht.array(b, split=0), lower=True)
        np.testing.assert_allclose(L @ x.numpy(), b, atol=1e-4)


class TestBlockedTriangularSolve(TestCase):
    """The blocked-substitution path over tiling.SquareDiagTiles — the
    reference's tile-Bcast algorithm (SURVEY §2.3 solve_triangular)."""

    @pytest.mark.parametrize("n", [32, 37])  # 37: ragged on the 8-device mesh
    @pytest.mark.parametrize("lower", [True, False])
    @pytest.mark.parametrize("split", [None, 0, 1])
    def test_matches_scipy(self, n, lower, split):
        import scipy.linalg as sla

        rng = np.random.default_rng(0)
        M = rng.standard_normal((n, n)).astype(np.float32) + n * np.eye(n, dtype=np.float32)
        T = np.tril(M) if lower else np.triu(M)
        B = rng.standard_normal((n, 5)).astype(np.float32)
        A = ht.array(T, split=split)
        b = ht.array(B, split=0 if split is not None else None)
        got = ht.linalg.solve_triangular(A, b, lower=lower)
        want = sla.solve_triangular(T, B, lower=lower)
        np.testing.assert_allclose(got.numpy(), want, rtol=2e-2, atol=2e-3)
        self.assert_distributed(got)

    def test_blocked_path_engages_for_split_A(self, monkeypatch):
        """Auto mode must actually route distributed A through SquareDiagTiles."""
        import heat_tpu.core.tiling as tiling

        if not ht.communication.get_comm().is_distributed():
            pytest.skip("p=1: auto mode correctly keeps the fused local solve")

        calls = []
        orig = tiling.SquareDiagTiles.__init__

        def spy(self, arr, tiles_per_proc=2):
            calls.append(arr.shape)
            orig(self, arr, tiles_per_proc)

        monkeypatch.setattr(tiling.SquareDiagTiles, "__init__", spy)
        rng = np.random.default_rng(1)
        L = np.tril(rng.standard_normal((32, 32)).astype(np.float32)) + 32 * np.eye(32, dtype=np.float32)
        ht.linalg.solve_triangular(ht.array(L, split=0), ht.array(rng.standard_normal((32, 2)).astype(np.float32)), lower=True)
        assert calls == [(32, 32)]
        calls.clear()
        # replicated A takes the native fused solve, no tiles
        ht.linalg.solve_triangular(ht.array(L), ht.array(rng.standard_normal((32, 2)).astype(np.float32)), lower=True)
        assert calls == []

    def test_1d_rhs(self):
        rng = np.random.default_rng(2)
        U = np.triu(rng.standard_normal((24, 24)).astype(np.float32)) + 24 * np.eye(24, dtype=np.float32)
        b = rng.standard_normal(24).astype(np.float32)
        x = ht.linalg.solve_triangular(ht.array(U, split=1), ht.array(b, split=0), lower=False)
        np.testing.assert_allclose(U @ x.numpy(), b, atol=2e-3)
