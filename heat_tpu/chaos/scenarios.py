"""The legacy chaos scenarios as declarative specs.

Each of the five full-tier chaos scenarios that used to live as
imperative test bodies (tests/test_chaos.py, tests/test_multiprocess.py)
is expressed here as DATA: the dryrun launch shape (mode, world size,
env) plus the attestation contract the run must print.  The engine
replays a spec through the real ``scripts/multiprocess_dryrun.py``
launcher — the same subprocess worlds, the same markers — and the
original tests now drive these specs through :func:`run_scenario` /
:func:`check_scenario` instead of duplicating the env dicts inline.

Why data, not code: a declarative spec is diffable (the whole
fault-injection surface of a scenario is visible in one dict), greppable
(CI logs name the spec), and replayable from the command line
(``scripts/chaoscamp.py --scenario kill-resume-train``).

The contract language:

- ``expect_rc``      — ``"zero"`` or ``"nonzero"``
- ``expect``         — literal substrings that must appear in stdout
- ``expect_re``      — regexes that must match stdout
- ``derived``        — two-stage assertions ``[capture_re, template]``:
  the capture's group(1) is substituted into the template (as ``{0}``)
  and the result must appear literally.  This is how the hang/desync
  scenarios assert the post-mortem names the EXACT seq the victim
  announced (``PM-HANG expect_seq=N`` → ``verdict=straggler … seq=N``).
- ``forbid``         — substrings that must NOT appear

Stdlib-only and standalone-loadable; the launcher module is spec-loaded
so this file never imports jax either.
"""

from __future__ import annotations

import importlib.util
import os
import re
import sys
from typing import Dict, List, Optional

__all__ = ["SCENARIOS", "scenario", "run_scenario", "check_scenario"]

_REPO = os.path.normpath(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
)


def _mpd():
    for name in ("multiprocess_dryrun_chaos", "heat_chaos_mpd"):
        if name in sys.modules:
            return sys.modules[name]
    spec = importlib.util.spec_from_file_location(
        "heat_chaos_mpd", os.path.join(_REPO, "scripts", "multiprocess_dryrun.py")
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------- #
# the five scenarios
# ---------------------------------------------------------------------- #
SCENARIOS: Dict[str, dict] = {
    # ISSUE 5 acceptance: SIGKILL one rank mid-DASO-training; the
    # supervisor restarts the world and training resumes from the newest
    # verified checkpoint (killed at step 5, checkpoint every 3 -> both
    # ranks resume at step 3 and reach the target).
    "kill-resume-train": {
        "mode": "train",
        "n_proc": 2,
        "devs_per_proc": 4,
        "timeout": 700,
        "flake_retry": True,  # documented gloo op.preamble.length victim
        "extra_env": {
            "MPDRYRUN_TARGET_STEPS": 12,
            "MPDRYRUN_CKPT_EVERY": 3,
            "MPDRYRUN_FAULT_RANK": 1,
            "MPDRYRUN_FAULT_SPEC": "proc.exit:exit=5",
            "MPDRYRUN_STEP_DELAY": 0.1,
            "MPDRYRUN_RESTARTS": 2,
        },
        "expect_rc": "zero",
        "expect": [
            "rank 1 died with exit code -9",
            "SUPERVISOR restarts=1 generations=2",
            "[0] RESUMED epoch=1 step=3 ok=True",
            "[1] RESUMED epoch=1 step=3 ok=True",
            "[0] TRAIN-OK steps=12",
            "[1] TRAIN-OK steps=12",
            "watchdog.kills",
            "TELEMETRY-MERGED ranks=2",
        ],
        "expect_re": [
            r"STEP-OVERLAP kind=daso\.step steps=\d+ overlap=\d\.\d+",
        ],
    },
    # ISSUE 10 acceptance: SIGKILL one serving rank mid-queue; journal
    # replay requeues the in-flight jobs exactly once and the attestation
    # proves zero lost and unbroken trace chains across the restart.
    "serve-sigkill-mid-queue": {
        "mode": "serve",
        "n_proc": 2,
        "devs_per_proc": 4,
        "timeout": 700,
        "extra_env": {
            "MPDRYRUN_FAULT_RANK": 1,
            "MPDRYRUN_FAULT_SPEC": "sched.dispatch:exit=4",
            "MPDRYRUN_RESTARTS": 2,
        },
        "expect_rc": "zero",
        "expect": [
            "rank 1 died with exit code -9",
            "SUPERVISOR restarts=1 generations=2",
            "[0] SERVE-OK",
            "[1] SERVE-OK",
            "TELEMETRY-MERGED ranks=2",
            "SCHED-TRACE-CONTINUITY jobs=20 ok=True",
            "causal timeline for trace",
        ],
        "expect_re": [
            r"SCHED jobs=20 done=18 requeued=[1-9]\d* shed=2 failed=0 lost=0",
        ],
        # SPMD lockstep recovery: every rank requeued the SAME set the
        # journal attestation counted (a divergent requeue would desync)
        "derived": [
            [
                r"SCHED jobs=20 done=18 requeued=(\d+)",
                "[0] SCHED-RECOVERED epoch=1 requeued={0}",
            ],
            [
                r"SCHED jobs=20 done=18 requeued=(\d+)",
                "[1] SCHED-RECOVERED epoch=1 requeued={0}",
            ],
        ],
    },
    # ISSUE 7 acceptance: one rank wedges on an injected collective hang;
    # the supervisor's heartbeat staleness converts the wedge into
    # teardown and the ring post-mortem names the straggler at the exact
    # seq the victim announced before hanging.
    "hang-straggler-verdict": {
        "mode": "postmortem",
        "n_proc": 2,
        "devs_per_proc": 4,
        "timeout": 700,
        "extra_env": {
            "MPDRYRUN_HANG_RANK": 1,
            "MPDRYRUN_CHAOS_AT": 3,
            "MPDRYRUN_HB_TIMEOUT": 25,
        },
        "expect_rc": "nonzero",  # a wedged world is a FAILED run
        "expect": ["SUPERVISOR GAVE UP"],
        "expect_re": [
            r"heartbeat stale .*stuck at seq \d+ resplit",
            r"TRACE-EXPORT events=\d+ ranks=\d+ out=",
        ],
        "derived": [
            [
                r"\[1\] PM-HANG expect_seq=(\d+)",
                "POSTMORTEM epoch=0 verdict=straggler rank=1 seq={0} op=resplit",
            ],
            [
                r"\[1\] PM-HANG expect_seq=(\d+)",
                "CRITICAL-PATH kind=collective rank=1 op=resplit seq={0}",
            ],
        ],
    },
    # ISSUE 7 acceptance: one of three ranks stages a rank-conditional
    # EXTRA collective; the analyzer names the first divergent seq and
    # convicts the minority fingerprint by majority vote.
    "desync-minority-verdict": {
        "mode": "postmortem",
        "n_proc": 3,
        "devs_per_proc": 2,
        "timeout": 700,
        "extra_env": {
            "MPDRYRUN_DESYNC_RANK": 1,
            "MPDRYRUN_CHAOS_AT": 3,
            "MPDRYRUN_HB_TIMEOUT": 25,
        },
        "expect_rc": "nonzero",
        "expect": ["SUPERVISOR GAVE UP"],
        "derived": [
            [
                r"\[1\] PM-DESYNC expect_seq=(\d+)",
                "POSTMORTEM epoch=0 verdict=desync seq={0} ranks=1",
            ],
        ],
    },
    # ISSUE 17 acceptance: SIGKILL an entire world of a two-world
    # federation mid-queue; the survivor absorbs the stolen jobs and the
    # journal-derived attestation proves zero loss (12 jobs + the shed
    # giant accounted).
    "fed-world-kill": {
        "mode": "fed",
        "n_proc": 2,
        "devs_per_proc": 2,
        "timeout": 700,
        "extra_env": {"MPDRYRUN_JOBS": 12},
        "expect_rc": "zero",
        "expect": [
            "submitted=12",
            "FED-SHED id=giant reason=mem_infeasible http=429",
            "FED worlds=2 lost=0 jobs=13",
        ],
        "expect_re": [
            r"FED-QUARANTINED world=w1 stolen=[1-9]\d*",
            r"FED-HEALTHZ-DEGRADED http=200 healthy=1 quarantined=1",
            r"FED-RESIZE world=w0 ranks=1->\d+ queue=\d+",
            r"FED-RESULT id=\S+ http=200 digest=",
        ],
    },
}


def scenario(name: str) -> dict:
    try:
        return SCENARIOS[name]
    except KeyError:
        raise KeyError(
            f"unknown scenario {name!r} (have: {', '.join(sorted(SCENARIOS))})"
        )


def run_scenario(name: str, *, timeout: Optional[float] = None):
    """Launch one spec through the real dryrun harness.  Returns the
    ``CompletedProcess``; judge it with :func:`check_scenario`."""
    spec = scenario(name)
    mpd = _mpd()
    launch = (
        mpd.launch_retrying_known_flake if spec.get("flake_retry")
        else mpd.launch
    )
    return launch(
        timeout=timeout if timeout is not None else spec["timeout"],
        n_proc=spec["n_proc"],
        devs_per_proc=spec["devs_per_proc"],
        mode=spec["mode"],
        extra_env=dict(spec["extra_env"]),
    )


def check_scenario(name: str, proc) -> List[str]:
    """Evaluate a finished run against its spec's attestation contract.
    Returns the list of violated clauses — empty means the scenario
    reproduced; tests assert ``check_scenario(...) == []`` so a failure
    names every broken clause at once."""
    spec = scenario(name)
    out = proc.stdout
    bad: List[str] = []
    rc = proc.returncode
    if spec["expect_rc"] == "zero":
        if rc != 0:
            bad.append(f"expected rc==0, got {rc}")
        mpd = _mpd()
        if mpd.PASS_MARKER not in out:
            bad.append(f"missing pass marker {mpd.PASS_MARKER!r}")
    elif rc == 0:
        bad.append("expected nonzero rc, got 0")
    for lit in spec.get("expect", ()):
        if lit not in out:
            bad.append(f"missing literal {lit!r}")
    for pat in spec.get("expect_re", ()):
        if not re.search(pat, out):
            bad.append(f"no match for /{pat}/")
    for capture, template in spec.get("derived", ()):
        m = re.search(capture, out)
        if not m:
            bad.append(f"derived capture /{capture}/ never matched")
            continue
        want = template.format(m.group(1))
        if want not in out:
            bad.append(f"derived assertion missing: {want!r}")
    for lit in spec.get("forbid", ()):
        if lit in out:
            bad.append(f"forbidden output present: {lit!r}")
    return bad
