"""Runtime telemetry: spans, collective byte accounting, structured export.

The reference framework ships no built-in tracer (SURVEY §5.1 — external
perun only); this module is the TPU port's first-class story.  Three layers:

- **Spans** — :func:`span` is a nestable context manager that records wall
  time, carries attributes (op name, shapes, split, bytes), tracks
  *self-time* (own duration minus children), and forwards its name to
  ``jax.profiler.TraceAnnotation`` so XProf traces inherit the runtime's
  vocabulary.  Records land in a bounded ring buffer — telemetry memory is
  O(ring), never O(run length).

- **Counters & histograms** — byte accounting of every ``Communication``
  collective (``comm.<name>.calls`` / ``comm.<name>.bytes``, payload nbytes
  × the collective's algorithmic traffic factor) rides the generic
  ``utils.profiler`` counter store; latencies go into fixed log-spaced-bin
  histograms (:class:`Histogram`) with O(1) observation and bounded memory.

- **Export** — :func:`flush` drains the span ring as JSON-lines to a
  per-rank file (``{dir}/rank{k}.jsonl``) together with counter and
  histogram snapshots; ``scripts/telemetry_report.py`` merges multi-rank
  files into one timeline/summary.  :func:`report` returns the in-process
  merged view (counters ∪ histograms ∪ top spans by self-time).

**Overhead contract.**  Disabled (the default), every instrumentation site
reduces to one module-global load — the dispatch tails in
``core._operations`` check a flag that :func:`enable`/:func:`disable` poke
*into that module*, so the hot path never even calls into here.  Enabled,
a span costs two clock reads, a ring append and (optionally) a
TraceAnnotation; the CI telemetry lane gates the enabled cost at <5% of
dispatch overhead (``benchmarks/dispatch.py --telemetry-gate``).

Arming: ``telemetry.enable()`` in-process, or ``HEAT_TPU_TELEMETRY=1`` in
the environment (checked once at import).  ``HEAT_TPU_TELEMETRY_DIR``
additionally registers an atexit flush of the rank file — the multiprocess
lane's per-rank exports are produced this way.

**Trace-time caveat.**  XLA collectives are *staged*: the Python wrappers
in ``core.communication`` run at trace time, and a cached executable's
replays never re-enter them.  ``comm.*.calls`` therefore counts distinct
*staged* collectives (per compilation), not runtime executions; a
collective inside ``lax.scan`` counts once however many times the loop
runs.  Eager sites (``resplit``, checkpoint IO, optimizer steps) count
per call.  See design.md "Telemetry & metrics".

Stdlib-only at module level on purpose: imported (lazily) from the
innermost dispatch/comm/IO paths, where a heavy import would be a cycle.
"""

from __future__ import annotations

import atexit
import contextlib
import contextvars
import functools
import hashlib
import json
import math
import os
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = [
    "enable",
    "disable",
    "enabled",
    "span",
    "traced",
    "tracing",
    "mint_trace_id",
    "current_trace_id",
    "current_span_id",
    "record_event",
    "observe",
    "histogram",
    "Histogram",
    "account_collective",
    "counter_inc",
    "counter_max",
    "report",
    "span_summary",
    "flush",
    "write_counters_line",
    "install_signal_flush",
    "reset",
    "ring_dropped",
]

RING_SIZE = 4096

_ENABLED = False
_ring: deque = deque(maxlen=RING_SIZE)
# evicted-by-overwrite span records since the last reset(): the bounded
# ring silently drops the OLDEST record on overflow, and a truncated trace
# must never be mistaken for a complete one — surfaced as the counter
# ``telemetry.ring.dropped`` in report()/flush() and the merged CLI report
_ring_dropped = 0
_histograms: Dict[str, "Histogram"] = {}
_hist_lock = threading.Lock()
_tls = threading.local()
_flush_dir: Optional[str] = None
_atexit_registered = False
_trace_annotation = None  # jax.profiler.TraceAnnotation, resolved at enable()
_profiler = None  # utils.profiler, resolved on first counter touch

# flight-recorder hook (``utils.flightrec.enable()`` pokes the module in):
# armed, context-manager span open/close boundaries are mirrored into the
# crash-durable ring — the named phases around the seq-stamped collectives.
# The leaf-record fast paths (record_dispatch/record_event) are NOT hooked
# here; the dispatch tails have their own hook in ``core._operations``.
_FLIGHTREC = None

# wall-clock anchor: span timestamps are perf_counter-based for precision
# but exported in epoch seconds so multi-rank timelines merge on one axis
_T0_PERF = time.perf_counter()
_T0_WALL = time.time()


def _prof():
    global _profiler
    if _profiler is None:
        from . import profiler

        _profiler = profiler
    return _profiler


def _stack() -> list:
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


def _ring_push(rec: tuple) -> None:
    """Append into the bounded span ring, counting an eviction under
    ``telemetry.ring.dropped`` first — ring truncation is always visible
    in the export.  (``record_dispatch`` inlines this with identical
    semantics: the hottest recorder cannot afford the call frame.)"""
    global _ring_dropped
    if len(_ring) == _ring.maxlen:
        _ring_dropped += 1
    _ring.append(rec)


def ring_dropped() -> int:
    """Span records evicted from the bounded ring since the last reset."""
    return _ring_dropped


# ---------------------------------------------------------------------- #
# trace identity — the causal join key across ranks, spans and restarts
# ---------------------------------------------------------------------- #
# The contextvar carries ``(trace_id, parent_span_id)``.  It is set by
# :func:`tracing` (the ONE sanctioned way to adopt or mint trace identity —
# heatlint HT109 flags manual trace_id fiddling in library code) and read
# by every recording site below: spans, leaf events and dispatch records
# stamp the ambient trace into their attrs, and the flight recorder reads
# :func:`current_trace_id` at the ``_account_bytes`` choke point so staged
# collectives carry the same id into the crash-durable ring.  Contextvars
# flow into ``health.guard_blocking`` worker threads and ``faults``
# retries automatically, so one job's whole causal path — dispatch spans,
# collective stamps, retry attempts — shares one id without any plumbing.
_TRACE: contextvars.ContextVar = contextvars.ContextVar(
    "heat_tpu_trace", default=None
)
_trace_seq = 0
_span_seq = 0


def mint_trace_id(name: str = "trace") -> str:
    """A new 16-hex-digit trace id, minted DETERMINISTICALLY from a
    per-process counter + ``name`` + the restart epoch — NOT from process
    entropy: under multi-process SPMD every rank executes the identical
    trace-opening sites in lockstep, so every rank derives the IDENTICAL
    id for the same logical trace (the whole point of a cross-rank join
    key; per-rank entropy would shatter it — the HT105 divergence class).
    Callers whose traces are NOT lockstep-opened (a per-tenant job) should
    pass a name that is itself rank-invariant (the scheduler derives ids
    from the job id)."""
    global _trace_seq
    _trace_seq += 1
    epoch = os.environ.get("HEAT_TPU_RESTART_EPOCH", "0")
    return hashlib.sha1(
        f"{name}|{_trace_seq}|{epoch}".encode()
    ).hexdigest()[:16]


def _mint_span_id() -> str:
    global _span_seq
    _span_seq += 1
    return f"s{_span_seq:x}"


def current_trace_id() -> Optional[str]:
    """The ambient trace id, or None outside any :func:`tracing` block.
    Read by the flight recorder at collective staging — safe to call with
    telemetry disabled (one contextvar load)."""
    t = _TRACE.get()
    return t[0] if t is not None else None


def current_span_id() -> Optional[str]:
    """The innermost open span's id (None outside a traced span)."""
    stack = _stack()
    for s in reversed(stack):
        sid = getattr(s, "span_id", None)
        if sid is not None:
            return sid
    t = _TRACE.get()
    return t[1] if t is not None else None


@contextlib.contextmanager
def tracing(trace_id: Optional[str] = None, name: str = "trace",
            parent_id: Optional[str] = None):
    """Arm a trace context for the block: every span/event/dispatch record
    (and every flight-recorder collective stamp) inside it carries
    ``trace_id``.  Minted via :func:`mint_trace_id` when not given;
    ``parent_id`` links into an enclosing trace from another process (a
    job's submit-side span).  Works with telemetry DISABLED too — the
    flight recorder stamps trace ids independently of the span ring, so a
    crash-durable causal path exists even when nothing else is armed.
    Yields the trace id."""
    tid = trace_id or mint_trace_id(name)
    token = _TRACE.set((tid, parent_id))
    try:
        yield tid
    finally:
        _TRACE.reset(token)


def _trace_attrs(attrs: Optional[dict], span_id: Optional[str] = None,
                 parent_id: Optional[str] = None) -> Optional[dict]:
    """Fold the ambient trace identity into a record's attrs (shared by
    spans, leaf events and dispatch records).  No active trace: attrs pass
    through untouched — zero cost added to untraced recording."""
    t = _TRACE.get()
    if t is None:
        return attrs
    out = dict(attrs) if attrs else {}
    out["trace_id"] = t[0]
    if span_id is not None:
        out["span_id"] = span_id
    if parent_id is None:
        parent_id = t[1]
    if parent_id is not None:
        out["parent_id"] = parent_id
    return out


# ---------------------------------------------------------------------- #
# enable / disable
# ---------------------------------------------------------------------- #
def enabled() -> bool:
    return _ENABLED


def _poke_dispatch_hook(on: bool) -> None:
    """Arm/disarm the dispatch hot-path hook: ``core._operations`` reads its
    own module global (one load, no call) to decide whether to record —
    set from here so the disabled cost stays at that single load."""
    mod = sys.modules.get("heat_tpu.core._operations")
    if mod is not None:
        mod._TELEMETRY = sys.modules[__name__] if on else None


def enable(directory: Optional[str] = None, ring_size: Optional[int] = None) -> None:
    """Arm telemetry.  ``directory`` (or ``HEAT_TPU_TELEMETRY_DIR``) also
    registers an atexit :func:`flush` of this process's rank file."""
    global _ENABLED, _ring, _flush_dir, _atexit_registered, _trace_annotation
    if ring_size is not None and ring_size != _ring.maxlen:
        _ring = deque(_ring, maxlen=int(ring_size))
    if _trace_annotation is None:
        try:
            import jax

            _trace_annotation = jax.profiler.TraceAnnotation
        except Exception:  # pragma: no cover - jax always present in-tree
            _trace_annotation = None
    if directory:
        _flush_dir = directory
    elif _flush_dir is None:
        _flush_dir = os.environ.get("HEAT_TPU_TELEMETRY_DIR") or None
    if _flush_dir and not _atexit_registered:
        atexit.register(_atexit_flush)
        _atexit_registered = True
    if _flush_dir:
        # graceful kills (SIGTERM/SIGINT) must export too — atexit never
        # runs when a supervisor tears the world down with signals
        install_signal_flush()
    _ENABLED = True
    _poke_dispatch_hook(True)


def disable() -> None:
    global _ENABLED
    _ENABLED = False
    _poke_dispatch_hook(False)


def reset() -> None:
    """Drop recorded spans and histograms (counters have their own reset in
    ``utils.profiler``), and zero the ring-eviction counter."""
    global _ring_dropped
    _ring.clear()
    _ring_dropped = 0
    with _hist_lock:
        _histograms.clear()


def _atexit_flush() -> None:  # pragma: no cover - exercised by the mp lane
    try:
        if _ENABLED and _flush_dir:
            flush(_flush_dir)
    except Exception:
        pass


# ---------------------------------------------------------------------- #
# graceful-kill flush: SIGTERM/SIGINT export what atexit cannot
# ---------------------------------------------------------------------- #
_signal_prev: Dict[int, Any] = {}
_signal_installed = False


def _signal_flush_handler(signum, frame):  # pragma: no cover - exercised
    # via os.kill in tests; keep it exception-proof: a failed flush must
    # never mask the signal's real semantics
    try:
        from . import health as _hlth

        _hlth.counter_inc("health.signal_flush")
    except Exception:
        pass
    try:
        if _ENABLED:
            flush()
    except Exception:
        pass
    try:
        fr = sys.modules.get("heat_tpu.utils.flightrec")
        if fr is not None:
            fr.sync()
    except Exception:
        pass
    prev = _signal_prev.get(signum)
    if callable(prev):
        prev(signum, frame)  # chain (incl. Python's default SIGINT handler)
    else:
        # SIG_DFL (or unset): restore the default disposition and re-raise
        # so the process still dies of the signal with the right exit code
        import signal as _signal

        _signal.signal(signum, _signal.SIG_DFL if prev is None else prev)
        os.kill(os.getpid(), signum)


def install_signal_flush() -> bool:
    """Arm a SIGTERM/SIGINT handler that flushes the telemetry ring and
    msyncs the flight recorder before chaining to whatever handler was
    installed before (or re-raising the default disposition) — so a
    *graceful* kill exports even without the ``HEAT_TPU_TELEMETRY_DIR``
    atexit hook (SIGKILL needs no help: the flight recorder's mmap
    survives it by construction).  Invocations count under
    ``health.signal_flush``.  Idempotent; returns False off the main
    thread (signal handlers can only be installed there) and on platforms
    without the signals."""
    global _signal_installed
    if _signal_installed:
        return True
    import signal as _signal

    if threading.current_thread() is not threading.main_thread():
        return False
    ok = False
    for sig in (_signal.SIGTERM, _signal.SIGINT):
        try:
            prev = _signal.getsignal(sig)
            _signal.signal(sig, _signal_flush_handler)
        except (ValueError, OSError):  # non-main thread race / exotic platform
            continue
        _signal_prev[sig] = None if prev is _signal.SIG_DFL else prev
        ok = True
    _signal_installed = ok
    return ok


def _uninstall_signal_flush() -> None:
    """Test hook: restore the pre-install handlers."""
    global _signal_installed
    if not _signal_installed:
        return
    import signal as _signal

    for sig, prev in list(_signal_prev.items()):
        try:
            _signal.signal(sig, _signal.SIG_DFL if prev is None else prev)
        except (ValueError, OSError):
            pass
    _signal_prev.clear()
    _signal_installed = False


# ---------------------------------------------------------------------- #
# spans
# ---------------------------------------------------------------------- #
class _NullSpan:
    """Singleton returned by :func:`span` when telemetry is disabled."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("name", "attrs", "t0", "child", "_ta", "_depth", "span_id",
                 "_parent_id")

    def __init__(self, name: str, attrs: dict, xprof: bool):
        self.name = name
        self.attrs = attrs
        self.child = 0.0
        self.span_id = None
        self._parent_id = None
        self._ta = (
            _trace_annotation(name)
            if (xprof and _trace_annotation is not None)
            else None
        )

    def __enter__(self):
        stack = _stack()
        self._depth = len(stack)
        if _TRACE.get() is not None:
            # inside a trace: this span gets its own id, parented on the
            # innermost traced span (or the context's cross-process parent)
            self._parent_id = current_span_id()
            self.span_id = _mint_span_id()
        stack.append(self)
        if self._ta is not None:
            self._ta.__enter__()
        if _FLIGHTREC is not None:
            _FLIGHTREC.record_event("span", name=self.name)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, et, ev, tb):
        t1 = time.perf_counter()
        if self._ta is not None:
            self._ta.__exit__(et, ev, tb)
        if _FLIGHTREC is not None:
            _FLIGHTREC.record_event(
                "span_end", name=self.name, dur=round(t1 - self.t0, 6),
                **({"error": et.__name__} if et is not None else {}),
            )
        stack = _stack()
        if stack and stack[-1] is self:
            stack.pop()
        dur = t1 - self.t0
        if stack:
            stack[-1].child += dur
        if et is not None:
            self.attrs = dict(self.attrs, error=et.__name__)
        _ring_push(
            (
                self.name,
                _T0_WALL + (self.t0 - _T0_PERF),
                dur,
                max(dur - self.child, 0.0),
                self._depth,
                _trace_attrs(self.attrs, self.span_id, self._parent_id)
                or None,
            )
        )
        return False

    def set(self, **attrs):
        """Attach/override attributes mid-span (e.g. bytes known at the end)."""
        self.attrs = dict(self.attrs, **attrs)
        return self


def span(name: str, xprof: bool = True, **attrs):
    """Record a named, attributed, nested wall-time span of the block.

    No-op (a shared null object) when telemetry is disabled.  ``xprof=False``
    skips the ``jax.profiler.TraceAnnotation`` forwarding — for sites hot
    enough that creating the annotation object is measurable."""
    if not _ENABLED:
        return _NULL_SPAN
    return _Span(name, attrs, xprof)


def traced(name: str):
    """Decorator form of :func:`span` for whole functions (checkpoint
    save/load entry points).  Disabled cost: one flag check."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not _ENABLED:
                return fn(*args, **kwargs)
            with _Span(name, {}, True):
                return fn(*args, **kwargs)

        return wrapper

    return deco


def record_event(name: str, dur_s: float, attrs: Optional[dict] = None) -> None:
    """Leaf span record for a duration the caller already measured — no
    enter/exit machinery, no TraceAnnotation."""
    if not _ENABLED:
        return
    if _TRACE.get() is not None:  # one contextvar load when untraced
        attrs = _trace_attrs(attrs, None, current_span_id())
    stack = _stack()
    if stack:
        stack[-1].child += dur_s
    _ring_push(
        (
            name,
            _T0_WALL + (time.perf_counter() - dur_s - _T0_PERF),
            dur_s,
            dur_s,
            len(stack),
            attrs or None,
        )
    )


def record_dispatch(name: str, t0: float, t1: float, op_name: str, cache_hit: bool) -> None:
    """The dispatch tails' recorder — the leanest path here: the caller
    supplies both perf_counter readings and the pre-resolved span name, so
    one call records a leaf span with the op/cache attributes and nothing
    else happens on the hot path."""
    if not _ENABLED:
        return
    global _ring_dropped
    dur = t1 - t0
    attrs = {"op": op_name, "cache": "hit" if cache_hit else "miss"}
    if _TRACE.get() is not None:  # the leanest-path tax when untraced is
        attrs = _trace_attrs(attrs, None, current_span_id())  # this ONE load
    stack = _stack()
    if stack:
        stack[-1].child += dur
    # _ring_push inlined (same eviction-count semantics): this is the
    # hottest recorder and an extra call frame is measurable against the
    # telemetry-gate budget
    if len(_ring) == _ring.maxlen:
        _ring_dropped += 1
    _ring.append(
        (
            name,
            _T0_WALL + (t0 - _T0_PERF),
            dur,
            dur,
            len(stack),
            attrs,
        )
    )


def span_summary(top: Optional[int] = None) -> List[dict]:
    """Spans currently in the ring aggregated by name, sorted by total
    self-time (descending)."""
    agg: Dict[str, list] = {}
    for name, _ts, dur, self_s, _depth, _attrs in list(_ring):
        row = agg.get(name)
        if row is None:
            row = agg[name] = [0, 0.0, 0.0, 0.0]
        row[0] += 1
        row[1] += dur
        row[2] += self_s
        row[3] = max(row[3], dur)
    rows = [
        {
            "name": name,
            "count": c,
            "total_s": round(total, 6),
            "self_s": round(self_s, 6),
            "mean_us": round(total / c * 1e6, 2),
            "max_us": round(mx * 1e6, 2),
        }
        for name, (c, total, self_s, mx) in agg.items()
    ]
    rows.sort(key=lambda r: -r["self_s"])
    return rows[:top] if top is not None else rows


# ---------------------------------------------------------------------- #
# counters (delegated to utils.profiler — one store for retry.*, comm.*,
# io.*, daso.*; telemetry.report() reads them all back)
# ---------------------------------------------------------------------- #
def counter_inc(name: str, n: int = 1) -> None:
    """Increment a named counter in the shared ``utils.profiler`` store."""
    _prof().counter_inc(name, n)


def counter_max(name: str, value: int) -> None:
    """High-water-mark update of a counter in the shared store."""
    _prof().counter_max(name, value)


def account_collective(name: str, nbytes: float) -> None:
    """``comm.<name>.calls`` += 1 and ``comm.<name>.bytes`` += nbytes.

    Always on (two dict increments at collective *staging* time — nowhere
    near a hot path); ``nbytes`` is payload × algorithmic traffic factor,
    already computed by the caller."""
    p = _prof()
    p.counter_inc(f"comm.{name}.calls")
    if nbytes:
        p.counter_inc(f"comm.{name}.bytes", int(round(nbytes)))


# ---------------------------------------------------------------------- #
# histograms — fixed log-spaced bins, bounded memory, O(1) observe
# ---------------------------------------------------------------------- #
_H_LO = 1e-6  # 1 µs
_H_PER_DECADE = 5
_H_DECADES = 9  # 1 µs .. 1000 s
_H_NBINS = _H_DECADES * _H_PER_DECADE


class Histogram:
    """Latency histogram over fixed log-spaced bins (1 µs – 1000 s at 5
    bins/decade, plus under/overflow): memory is a constant 47 ints however
    many observations arrive — no unbounded sample lists.

    **Percentile resolution caveat.**  Quantiles are upper-edge estimates
    from the bin counts: at 5 bins/decade each bin spans ~58% of its lower
    edge, so a reported percentile can overstate the true value by up to
    one bin width.  This matters most for the deep tail — **p99.9** (the
    serving-SLO tail beyond the p99 the tables historically stopped at) is
    exact about WHICH bin the 99.9th observation landed in, but within
    that bin only the upper edge (clamped to the observed max) is known.
    At pod scale that is the right trade: the alternative, an exact
    reservoir, is unbounded memory on the hot path."""

    __slots__ = ("name", "counts", "count", "total", "vmin", "vmax")

    def __init__(self, name: str):
        self.name = name
        self.counts = [0] * (_H_NBINS + 2)  # [underflow, bins..., overflow]
        self.count = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = 0.0

    def observe(self, value_s: float) -> None:
        v = float(value_s)
        if not (v > 0.0):  # <=0 and NaN both land in the underflow bin
            idx = 0
            v = 0.0
        else:
            i = int(math.floor(math.log10(v / _H_LO) * _H_PER_DECADE))
            idx = min(max(i, -1), _H_NBINS) + 1
        self.counts[idx] += 1
        self.count += 1
        self.total += v
        if v < self.vmin:
            self.vmin = v
        if v > self.vmax:
            self.vmax = v

    def quantile(self, q: float) -> float:
        """Upper-edge estimate of the ``q``-quantile from the bin counts."""
        if self.count == 0:
            return 0.0
        target = q * self.count
        seen = 0
        for idx, n in enumerate(self.counts):
            seen += n
            if n and seen >= target:
                if idx == 0:
                    return self.vmin if self.vmin is not math.inf else 0.0
                # upper edge of bin idx-1; overflow and the top bin clamp
                # to the observed max
                return min(_H_LO * 10 ** (idx / _H_PER_DECADE), self.vmax)
        return self.vmax

    def summary(self) -> dict:
        if self.count == 0:
            return {"count": 0}
        return {
            "count": self.count,
            "total_s": round(self.total, 6),
            "mean_s": round(self.total / self.count, 9),
            "min_s": round(0.0 if self.vmin is math.inf else self.vmin, 9),
            "max_s": round(self.vmax, 9),
            "p50_s": round(self.quantile(0.50), 9),
            "p90_s": round(self.quantile(0.90), 9),
            "p99_s": round(self.quantile(0.99), 9),
            "p999_s": round(self.quantile(0.999), 9),
        }


def histogram(name: str) -> Histogram:
    """Get-or-create the named histogram."""
    h = _histograms.get(name)
    if h is None:
        with _hist_lock:
            h = _histograms.setdefault(name, Histogram(name))
    return h


def observe(name: str, value_s: float) -> None:
    """Record ``value_s`` (seconds) into the named histogram."""
    histogram(name).observe(value_s)


# ---------------------------------------------------------------------- #
# report & export
# ---------------------------------------------------------------------- #
def report(top: int = 15) -> dict:
    """In-process merged view: counters ∪ histograms ∪ top spans by
    self-time.  May sync device-resident counters — reporting boundary
    only, never the hot loop."""
    counters = _prof().counters()
    if _ring_dropped:
        # eviction is telemetry-internal state, not a profiler counter —
        # injected at the reporting boundary so a truncated span ring is
        # never mistaken for a complete trace
        counters["telemetry.ring.dropped"] = _ring_dropped
    return {
        "enabled": _ENABLED,
        "rank": _rank(),
        "counters": counters,
        "histograms": {n: h.summary() for n, h in sorted(_histograms.items())},
        "top_spans": span_summary(top),
    }


def _rank() -> int:
    jax_mod = sys.modules.get("jax")
    if jax_mod is not None:
        try:
            return int(jax_mod.process_index())
        except Exception:
            pass
    return int(os.environ.get("HEAT_TPU_TELEMETRY_RANK", "0") or 0)


def _jsonable(v: Any):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (tuple, list)):
        return [_jsonable(x) for x in v]
    return str(v)


def flush(directory: Optional[str] = None) -> Optional[str]:
    """Drain the span ring to ``{dir}/rank{k}.jsonl`` (appending), together
    with a meta line and current counter/histogram snapshots.  Returns the
    path written, or None when no directory is configured (arg,
    ``enable(directory=...)`` or ``HEAT_TPU_TELEMETRY_DIR``)."""
    directory = directory or _flush_dir or os.environ.get("HEAT_TPU_TELEMETRY_DIR")
    if not directory:
        return None
    os.makedirs(directory, exist_ok=True)
    rank = _rank()
    path = os.path.join(directory, f"rank{rank}.jsonl")
    spans = []
    while True:
        try:
            spans.append(_ring.popleft())
        except IndexError:
            break
    with open(path, "a") as fh:
        fh.write(
            json.dumps(
                {
                    "type": "meta",
                    "rank": rank,
                    "pid": os.getpid(),
                    "wall_time": time.time(),
                    "ring_size": _ring.maxlen,
                }
            )
            + "\n"
        )
        for name, ts, dur, self_s, depth, attrs in spans:
            rec = {
                "type": "span",
                "rank": rank,
                "name": name,
                "ts": round(ts, 6),
                "dur_s": round(dur, 9),
                "self_s": round(self_s, 9),
                "depth": depth,
            }
            if attrs:
                rec["attrs"] = {k: _jsonable(v) for k, v in attrs.items()}
            fh.write(json.dumps(rec) + "\n")
        values = _prof().counters()
        if _ring_dropped:
            values["telemetry.ring.dropped"] = _ring_dropped
        fh.write(
            json.dumps({"type": "counters", "rank": rank, "values": values})
            + "\n"
        )
        for name, h in sorted(_histograms.items()):
            fh.write(
                json.dumps(
                    {
                        "type": "hist",
                        "rank": rank,
                        "name": name,
                        "count": h.count,
                        "total_s": h.total,
                        "min_s": 0.0 if h.vmin is math.inf else h.vmin,
                        "max_s": h.vmax,
                        "lo": _H_LO,
                        "per_decade": _H_PER_DECADE,
                        "bins": {str(i): c for i, c in enumerate(h.counts) if c},
                    }
                )
                + "\n"
            )
    return path


def write_counters_line(directory: str, rank: int, values: Dict[str, int]) -> str:
    """Append ONE counters record for ``rank`` to ``{dir}/rank{rank}.jsonl``.

    This is how a process that is NOT a jax rank — the supervising
    launcher, chiefly — folds its own counters (``watchdog.dumps``,
    ``watchdog.kills``, ``health.restarts``) into the same multi-rank merge
    ``scripts/telemetry_report.py`` performs: give it a rank id outside the
    worker range (launchers use ``n_workers``) so its last-wins counters
    record never shadows a real rank's.  Stdlib-only, and safe to call from
    a module loaded standalone (no profiler/jax touch)."""
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"rank{int(rank)}.jsonl")
    with open(path, "a") as fh:
        fh.write(
            json.dumps(
                {"type": "counters", "rank": int(rank), "values": dict(values)}
            )
            + "\n"
        )
    return path


# env arming: one check at import, the documented subprocess story.  Gated
# on __package__: a STANDALONE load of this file (the supervising launcher
# pulls write_counters_line via spec_from_file_location — a process that
# must never import jax) is tooling, not the runtime, and must not run
# enable() (which resolves jax.profiler.TraceAnnotation) nor register an
# atexit flush into a shared telemetry dir it has no rank in.
if __package__ and os.environ.get(
    "HEAT_TPU_TELEMETRY", ""
).strip().lower() in ("1", "true", "on", "yes"):
    enable()

# the flight recorder may have been env-armed while this module was still
# importing (flightrec's poke would hit the half-initialized module and the
# `_FLIGHTREC = None` line above clobbered it) — re-read the flag now, same
# defensive pattern as core._operations / core.communication
if __package__:
    _fr_mod = sys.modules.get("heat_tpu.utils.flightrec")
    if _fr_mod is not None and _fr_mod.enabled():
        _FLIGHTREC = _fr_mod
    del _fr_mod
