"""DASO two-tier mechanism proof (VERDICT r2 item 4; reference
``heat/optim/dp_optimizer.py::DASO``, SURVEY §2.8).

The reference's hierarchy is NCCL-allreduce-every-step (intra-node) + async
MPI parameter averaging every k steps (inter-node).  The TPU mapping is a
('dcn', 'ici') mesh: these tests compile the actual train step on a 4×2
8-device mesh and assert, on the HLO itself, that

- the per-step program contains an all-reduce whose replica_groups are the
  ici SUBGROUPS (pairs within each dcn group) — the fast tier is a real
  collective, not metadata;
- the global-average program contains a cross-group collective over the dcn
  axis — the slow tier moves parameters between groups.
"""

# assert_distributed exception (r4 #8): these tests prove distribution from
# the compiled HLO itself (replica_groups of the per-step all-reduce) — a
# stronger check than device placement; no DNDarrays are produced.

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.optim.dp_optimizer import DASO, DataParallelOptimizer


def _mesh_4x2():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 devices")
    from jax.sharding import Mesh

    return Mesh(np.asarray(devs[:8]).reshape(4, 2), ("dcn", "ici"))


def _groups_of(hlo: str):
    """All replica_groups={{...}} occurrences as lists of lists of ints."""
    out = []
    for m in re.finditer(r"replica_groups=\{(\{[^=]*?\})\}", hlo):
        groups = [
            [int(v) for v in g.split(",") if v.strip()]
            for g in re.findall(r"\{([\d,]*)\}", m.group(1))
        ]
        out.append(groups)
    # iota-form v2 syntax: replica_groups=[4,2]<=[8] etc.
    for m in re.finditer(r"replica_groups=\[(\d+),(\d+)\]<=\[(\d+)\]", hlo):
        rows, cols, tot = int(m.group(1)), int(m.group(2)), int(m.group(3))
        flat = list(range(tot))
        out.append([flat[i * cols : (i + 1) * cols] for i in range(rows)])
    for m in re.finditer(
        r"replica_groups=\[(\d+),(\d+)\]<=\[(\d+),(\d+)\]T\(1,0\)", hlo
    ):
        rows, cols = int(m.group(1)), int(m.group(2))
        a, b = int(m.group(3)), int(m.group(4))
        grid = np.arange(a * b).reshape(a, b).T.reshape(rows, cols)
        out.append(grid.tolist())
    return out


class TestDASOHLO:
    def _build(self):
        mesh = _mesh_4x2()
        opt = DataParallelOptimizer("sgd", lr=0.1)
        daso = DASO(opt, mesh=mesh, global_skip=2, warmup_steps=0)
        model = ht.nn.Sequential(ht.nn.Linear(8, 16), ht.nn.ReLU(), ht.nn.Linear(16, 4))
        daso.init(model, key=jax.random.key(0))

        def loss_fn(pred, y):
            return jnp.mean((pred - y) ** 2)

        daso._build_steps(loss_fn)
        g, ici = daso.n_groups, daso.ici_size
        xs = jnp.zeros((g, 4 * ici, 8), jnp.float32)
        ys = jnp.zeros((g, 4 * ici, 4), jnp.float32)
        return daso, xs, ys

    def test_per_step_ici_allreduce_in_hlo(self):
        daso, xs, ys = self._build()
        hlo = (
            daso._train_step.lower(daso._params, daso._opt_state, xs, ys)
            .compile()
            .as_text()
        )
        assert "all-reduce" in hlo, "train step contains no collective at all"
        ici_pairs = [[0, 1], [2, 3], [4, 5], [6, 7]]
        found = any(g == ici_pairs for g in _groups_of(hlo))
        assert found, (
            "no all-reduce over the ici subgroups {{0,1},{2,3},{4,5},{6,7}} "
            f"in the compiled train step; groups seen: {_groups_of(hlo)}"
        )

    def test_dcn_collective_in_global_average(self):
        daso, xs, ys = self._build()
        hlo = daso._global_average.lower(daso._params).compile().as_text()
        has_collective = any(
            k in hlo for k in ("all-reduce", "all-gather", "reduce-scatter", "collective-permute")
        )
        assert has_collective, "global average compiles to no cross-group collective"
        # the collective must span devices from DIFFERENT dcn groups (on the
        # 4x2 mesh, dcn peers are stride-2 apart: {0,2,4,6}/{1,3,5,7})
        cross = any(
            any(len({d // 2 for d in grp}) > 1 for grp in groups)
            for groups in _groups_of(hlo)
        )
        assert cross, f"collective does not cross dcn groups: {_groups_of(hlo)}"

    def test_training_still_converges(self):
        daso, _, _ = self._build()
        rng = np.random.default_rng(0)
        W = rng.normal(size=(8, 4)).astype(np.float32)
        losses = []

        def loss_fn(pred, y):
            return jnp.mean((pred - y) ** 2)

        for i in range(30):
            xb = rng.normal(size=(16, 8)).astype(np.float32)
            yb = xb @ W
            losses.append(daso.step(loss_fn, jnp.asarray(xb), jnp.asarray(yb)))
        assert losses[-1] < losses[0] * 0.5, f"no convergence: {losses[0]} -> {losses[-1]}"

    def test_group_replicas_synced_by_dcn_tier(self):
        # after warmup full-sync, all dcn group replicas must be identical
        mesh = _mesh_4x2()
        daso = DASO(DataParallelOptimizer("sgd", lr=0.05), mesh=mesh, warmup_steps=3)
        model = ht.nn.Sequential(ht.nn.Linear(8, 4))
        daso.init(model, key=jax.random.key(1))

        def loss_fn(pred, y):
            return jnp.mean((pred - y) ** 2)

        rng = np.random.default_rng(1)
        for _ in range(3):  # within warmup: full sync every step
            xb = rng.normal(size=(16, 8)).astype(np.float32)
            daso.step(loss_fn, jnp.asarray(xb), jnp.asarray(xb @ np.ones((8, 4), np.float32)))
        leaves = jax.tree.leaves(daso.parameters)
        for leaf in leaves:
            arr = np.asarray(leaf)
            for gidx in range(1, arr.shape[0]):
                np.testing.assert_allclose(arr[gidx], arr[0], rtol=1e-5, atol=1e-6)
