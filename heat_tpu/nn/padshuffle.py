"""Padding, shuffle, and adaptive-max modules mirroring torch.nn.

Round-5 mirror completion (SURVEY §2.5): every padding module is one
``jnp.pad`` mode applied to the trailing spatial dims; the shuffles are
single reshape/transpose expressions; adaptive max pools follow the
divisible-case reshape pattern of ``AdaptiveAvgPool2d``.  All verified
against the ``torch.nn`` oracle in ``tests/test_nn_padshuffle.py``.
"""

from __future__ import annotations

import jax.numpy as jnp

from .modules import Module, _AdaptivePool

__all__ = [
    "AdaptiveAvgPool3d", "AdaptiveMaxPool1d", "AdaptiveMaxPool2d",
    "AdaptiveMaxPool3d", "ChannelShuffle", "CircularPad1d", "CircularPad2d",
    "CircularPad3d", "ConstantPad1d", "ConstantPad2d", "ConstantPad3d",
    "PixelShuffle", "PixelUnshuffle", "ReflectionPad1d", "ReflectionPad2d",
    "ReflectionPad3d", "ReplicationPad1d", "ReplicationPad2d",
    "ReplicationPad3d", "ZeroPad1d", "ZeroPad2d", "ZeroPad3d",
]


# ---------------------------------------------------------------------- #
# padding: torch gives per-side widths as a flat tuple ordered LAST dim
# first — (left, right[, top, bottom[, front, back]]); an int pads every
# side of every spatial dim
# ---------------------------------------------------------------------- #
class _Pad(Module):
    """Base: ``spatial`` trailing dims padded with one jnp.pad mode."""

    spatial: int = 1
    mode: str = "constant"

    def __init__(self, padding, value: float = 0.0):
        n = self.spatial
        if isinstance(padding, int):
            padding = (padding,) * (2 * n)
        padding = tuple(int(p) for p in padding)
        if len(padding) != 2 * n:
            raise ValueError(
                f"{type(self).__name__} expects an int or {2 * n} per-side "
                f"widths (torch order: last dim first), got {len(padding)}"
            )
        self.padding = padding
        self.value = value

    def apply(self, params, x, **kw):
        n = self.spatial
        if x.ndim < n + 1:
            raise ValueError(
                f"{type(self).__name__} expects at least {n + 1}-D input, got {x.ndim}-D"
            )
        # torch's flat tuple is last-dim-first: pairs reversed vs axis order
        widths = [(0, 0)] * (x.ndim - n) + [
            (self.padding[2 * (n - 1 - i)], self.padding[2 * (n - 1 - i) + 1])
            for i in range(n)
        ]
        kwargs = {"constant_values": self.value} if self.mode == "constant" else {}
        # torch semantics: NEGATIVE widths crop; jnp.pad rejects them, so
        # pad the non-negative part then slice the cropped edges off
        pads = [(max(lo, 0), max(hi, 0)) for lo, hi in widths]
        y = jnp.pad(x, pads, mode=self.mode, **kwargs)
        idx = tuple(
            slice(-min(lo, 0) or None, min(hi, 0) or None)
            for lo, hi in widths
        )
        return y[idx]


def _pad_family(spatial: int):
    """The four torch pad flavours for one spatial rank."""

    class Zero(_Pad):
        pass

    class Constant(_Pad):
        pass

    class Reflection(_Pad):
        mode = "reflect"

        def __init__(self, padding):
            super().__init__(padding)

    class Replication(_Pad):
        mode = "edge"

        def __init__(self, padding):
            super().__init__(padding)

    class Circular(_Pad):
        mode = "wrap"

        def __init__(self, padding):
            super().__init__(padding)

    for cls in (Zero, Constant, Reflection, Replication, Circular):
        cls.spatial = spatial
    return Zero, Constant, Reflection, Replication, Circular


ZeroPad1d, ConstantPad1d, ReflectionPad1d, ReplicationPad1d, CircularPad1d = _pad_family(1)
ZeroPad2d, ConstantPad2d, ReflectionPad2d, ReplicationPad2d, CircularPad2d = _pad_family(2)
ZeroPad3d, ConstantPad3d, ReflectionPad3d, ReplicationPad3d, CircularPad3d = _pad_family(3)
for _c, _n in ((ZeroPad1d, "ZeroPad1d"), (ConstantPad1d, "ConstantPad1d"),
               (ReflectionPad1d, "ReflectionPad1d"), (ReplicationPad1d, "ReplicationPad1d"),
               (CircularPad1d, "CircularPad1d"),
               (ZeroPad2d, "ZeroPad2d"), (ConstantPad2d, "ConstantPad2d"),
               (ReflectionPad2d, "ReflectionPad2d"), (ReplicationPad2d, "ReplicationPad2d"),
               (CircularPad2d, "CircularPad2d"),
               (ZeroPad3d, "ZeroPad3d"), (ConstantPad3d, "ConstantPad3d"),
               (ReflectionPad3d, "ReflectionPad3d"), (ReplicationPad3d, "ReplicationPad3d"),
               (CircularPad3d, "CircularPad3d")):
    _c.__name__ = _c.__qualname__ = _n


# ---------------------------------------------------------------------- #
# shuffles
# ---------------------------------------------------------------------- #
class PixelShuffle(Module):
    """(N, C·r², H, W) -> (N, C, H·r, W·r) (torch sub-pixel layout)."""

    def __init__(self, upscale_factor: int):
        self.r = int(upscale_factor)

    def apply(self, params, x, **kw):
        *lead, crr, h, w = x.shape
        r = self.r
        if crr % (r * r):
            raise ValueError(f"channels {crr} not divisible by r^2 = {r * r}")
        c = crr // (r * r)
        y = x.reshape(*lead, c, r, r, h, w)
        k = len(lead)
        # (..., c, r1, r2, h, w) -> (..., c, h, r1, w, r2)
        y = y.transpose(*range(k), k, k + 3, k + 1, k + 4, k + 2)
        return y.reshape(*lead, c, h * r, w * r)


class PixelUnshuffle(Module):
    """Inverse of :class:`PixelShuffle`."""

    def __init__(self, downscale_factor: int):
        self.r = int(downscale_factor)

    def apply(self, params, x, **kw):
        *lead, c, hr, wr = x.shape
        r = self.r
        if hr % r or wr % r:
            raise ValueError(f"spatial dims ({hr}, {wr}) not divisible by r = {r}")
        h, w = hr // r, wr // r
        y = x.reshape(*lead, c, h, r, w, r)
        k = len(lead)
        # (..., c, h, r1, w, r2) -> (..., c, r1, r2, h, w)
        y = y.transpose(*range(k), k, k + 2, k + 4, k + 1, k + 3)
        return y.reshape(*lead, c * r * r, h, w)


class ChannelShuffle(Module):
    """(N, g·c, ...) -> interleave the g channel groups (ShuffleNet)."""

    def __init__(self, groups: int):
        self.groups = int(groups)

    def apply(self, params, x, **kw):
        ch = x.shape[1]
        g = self.groups
        if ch % g:
            raise ValueError(f"channels {ch} not divisible by groups {g}")
        shape = x.shape
        return (x.reshape(shape[0], g, ch // g, *shape[2:])
                 .swapaxes(1, 2)
                 .reshape(shape))


# ---------------------------------------------------------------------- #
# adaptive pools — the shared divisible-case base lives in modules.py
# (AdaptiveAvgPool2d is the same class at spatial=2)
# ---------------------------------------------------------------------- #
class AdaptiveMaxPool1d(_AdaptivePool):
    spatial = 1
    op = staticmethod(jnp.max)


class AdaptiveMaxPool2d(_AdaptivePool):
    spatial = 2
    op = staticmethod(jnp.max)


class AdaptiveMaxPool3d(_AdaptivePool):
    spatial = 3
    op = staticmethod(jnp.max)


class AdaptiveAvgPool3d(_AdaptivePool):
    spatial = 3
