"""Random number generation (reference: ``heat/core/random.py``).

The reference implements two modes:

- ``Threefry``: counter-based — each element's value is a function of
  (seed, global index), so results are **split- and nprocs-invariant**.
- ``Batchparallel``: per-rank generator (faster, split-dependent).

``jax.random`` is Threefry counter-based *natively*, so the reference's
split-invariance guarantee holds by construction: we generate from a key
derived from (global seed, call counter) and shard the result.  Where
available, sharded generation (``out_sharding``) materializes each shard on
its own device.  A ``batchparallel`` mode is kept for API parity and simply
folds the process index into the key.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np

from . import devices, types
from .communication import sanitize_comm
from .dndarray import DNDarray
from .stride_tricks import sanitize_shape

# device-memory-ledger hook (``utils.memledger.enable()`` pokes the module
# in): ``_generate`` mints every random factory's buffer, so it is a
# registration choke point like ``factories._finalize``.  Disabled cost:
# one module-global load (module bottom re-arms).
_MEMLEDGER = None

__all__ = [
    "derive_seed",
    "get_state",
    "host_rng",
    "normal",
    "permutation",
    "rand",
    "randint",
    "randn",
    "random",
    "random_integer",
    "random_sample",
    "randperm",
    "ranf",
    "sample",
    "seed",
    "set_state",
    "standard_normal",
    "uniform",
]

# global RNG state: (mode, seed, counter)
__seed: int = 0
__counter: int = 0
__mode: str = "threefry"


def seed(seed: Optional[int] = None) -> None:
    """(Re-)seed the global generator."""
    global __seed, __counter
    if seed is None:
        seed = int(np.random.SeedSequence().entropy % (2**63))
    __seed = int(seed)
    __counter = 0


def get_state() -> Tuple[str, int, int, int, float]:
    """Reference-compatible state tuple (name, seed, counter, _, _)."""
    return ("Threefry" if __mode == "threefry" else "Batchparallel", __seed, __counter, 0, 0.0)


def set_state(state: Tuple) -> None:
    global __seed, __counter, __mode
    if state[0] not in ("Threefry", "Batchparallel"):
        raise ValueError(f"unknown RNG type {state[0]}")
    __mode = state[0].lower()
    __seed = int(state[1])
    __counter = int(state[2]) if len(state) > 2 else 0


def host_rng(seed: int) -> np.random.Generator:
    """Host-side numpy ``Generator`` for an explicitly-seeded draw.

    The sanctioned route for host-side (numpy) randomness in library code:
    the caller supplies a seed that is identical on every rank — a
    literal, a broadcast value, or :func:`derive_seed` — so nominally
    identical SPMD code draws identical values on every process.  A raw
    ``np.random.default_rng(...)`` anywhere else is the per-process-entropy
    hazard heatlint HT105 flags (and its autofixer rewrites to this)."""
    return np.random.default_rng(seed)


def derive_seed() -> int:
    """Rank-uniform 63-bit seed derived from the broadcast RNG state.

    Advances the global ``(seed, counter)`` state exactly like device-side
    generation, so lockstep SPMD callers derive the IDENTICAL value on
    every rank with no communication — the replacement for seeding host
    RNGs from ``np.random.randint(...)`` (per-process entropy: every rank
    would shuffle differently and desynchronize)."""
    global __counter
    ss = np.random.SeedSequence(entropy=__seed, spawn_key=(__counter,))
    __counter += 1
    return int(ss.generate_state(1, dtype=np.uint64)[0] >> 1)


def _next_key() -> jax.Array:
    global __counter
    key = jax.random.fold_in(jax.random.key(__seed), __counter)
    __counter += 1
    if __mode == "batchparallel":
        key = jax.random.fold_in(key, jax.process_index())
    return key


def _generate(sampler, shape, dtype, split, device, comm, **kw) -> DNDarray:
    shape = sanitize_shape(shape)
    dtype = types.canonical_heat_type(dtype)
    comm = sanitize_comm(comm)
    device = devices.sanitize_device(device)
    key = _next_key()
    sharding = comm.sharding(len(shape), split)
    try:
        # sharded generation (requires Explicit-mode mesh axes)
        jarr = sampler(key, shape, dtype=dtype.jax_dtype(), out_sharding=sharding, **kw)
    except (TypeError, ValueError):
        jarr = sampler(key, shape, dtype=dtype.jax_dtype(), **kw)
        jarr = comm.shard(jarr, split)
    ret = DNDarray(jarr, shape, dtype, split, device, comm, True)
    if _MEMLEDGER is not None:
        # ledger choke point: op=None -> the ledger's frame walk names the
        # public factory up-stack (rand/randn/randint/normal/...)
        _MEMLEDGER.register(ret._parray, op=None, site="factory")
    return ret


def rand(*d, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Uniform [0, 1) samples of the given shape."""
    shape = d if len(d) > 0 else (1,)
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    return _generate(jax.random.uniform, shape, dtype, split, device, comm)


def random_sample(shape=(1,), dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    return _generate(jax.random.uniform, shape, dtype, split, device, comm)


random = random_sample
ranf = random_sample
sample = random_sample


def randn(*d, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Standard-normal samples of the given shape."""
    shape = d if len(d) > 0 else (1,)
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    return _generate(jax.random.normal, shape, dtype, split, device, comm)


def standard_normal(shape=(1,), dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    return _generate(jax.random.normal, shape, dtype, split, device, comm)


def normal(mean=0.0, std=1.0, shape=(1,), dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Normal(mean, std) samples."""
    base = _generate(jax.random.normal, shape, dtype, split, device, comm)
    if np.isscalar(mean) and np.isscalar(std):
        if float(std) < 0:
            raise ValueError("std must be non-negative")
        base._jarray = base._jarray * float(std) + float(mean)
        return base
    from . import arithmetics

    return arithmetics.add(arithmetics.mul(base, std), mean)


def uniform(low=0.0, high=1.0, size=(1,), dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    return _generate(
        jax.random.uniform, size, dtype, split, device, comm, minval=float(low), maxval=float(high)
    )


def randint(low, high=None, size=None, dtype=types.int32, split=None, device=None, comm=None) -> DNDarray:
    """Random integers in [low, high)."""
    if high is None:
        low, high = 0, low
    if size is None:
        size = (1,)
    if high <= low:
        raise ValueError("low >= high")
    return _generate(
        jax.random.randint, size, dtype, split, device, comm, minval=int(low), maxval=int(high)
    )


random_integer = randint


def permutation(x, split=None, device=None, comm=None) -> DNDarray:
    """Random permutation of arange(x) or a shuffle of the array x along axis 0."""
    key = _next_key()
    if isinstance(x, DNDarray):
        res = jax.random.permutation(key, x._jarray, axis=0)
        res = x.comm.shard(res, x.split)
        return DNDarray(res, x.gshape, x.dtype, x.split, x.device, x.comm, True)
    if isinstance(x, (int, np.integer)):
        res = jax.random.permutation(key, int(x))
        comm = sanitize_comm(comm)
        res = comm.shard(res, split)
        return DNDarray(
            res, tuple(res.shape), types.canonical_heat_type(res.dtype), split,
            devices.sanitize_device(device), comm, True,
        )
    raise TypeError(f"x must be int or DNDarray, got {type(x)}")


def randperm(n: int, dtype=types.int32, split=None, device=None, comm=None) -> DNDarray:
    """Random permutation of range(n)."""
    return permutation(int(n), split=split, device=device, comm=comm).astype(dtype, copy=False)


seed()


# the memory ledger may have been env-armed (HEAT_TPU_MEMLEDGER=1) while
# this module was still importing — re-read the flag now (defensive
# module-bottom re-arm, the established hot-path-hook pattern)
import sys as _sys  # noqa: E402

_ml = _sys.modules.get("heat_tpu.utils.memledger")
if _ml is not None and getattr(_ml, "enabled", lambda: False)():
    _MEMLEDGER = _ml
del _sys, _ml
