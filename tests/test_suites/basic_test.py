"""Shared test base (reference: ``heat/core/tests/test_suites/basic_test.py``).

``assert_array_equal`` checks the GLOBAL result against a numpy oracle;
``assert_func_equal`` sweeps a numpy op vs a heat op over shapes × splits —
the reference's distributed-coverage strategy, with the world-size sweep
replaced by the 8-device CPU mesh.
"""

from __future__ import annotations

import numpy as np

import heat_tpu as ht


class TestCase:
    comm = None  # set lazily; mesh exists after jax init

    @classmethod
    def get_comm(cls):
        if cls.comm is None:
            cls.comm = ht.communication.get_comm()
        return cls.comm

    @staticmethod
    def assert_distributed(x):
        """Assert that ``split`` metadata reflects PHYSICAL sharding: the array
        actually lives on every device of its communicator and the sharding
        spec names the split axis.  This is what lets the suite distinguish a
        distributed framework from a single-device one (SURVEY §4: the split
        sweep must check the shard)."""
        import jax.numpy as jnp
        from jax.sharding import NamedSharding

        if not isinstance(x, ht.DNDarray) or x.split is None or x.ndim == 0:
            return
        comm = x.comm
        if not comm.is_distributed() or x.shape[x.split] == 0:
            return
        arr = x._parray
        if jnp.issubdtype(arr.dtype, jnp.complexfloating):
            from heat_tpu.core import _complexsafe

            if not _complexsafe.native_complex_supported():
                return  # hosted complex arrays cannot be mesh-placed
        ndev = len(getattr(arr, "sharding", None).device_set) if hasattr(arr, "sharding") else 0
        assert ndev >= comm.size, (
            f"split={x.split} claims distribution over {comm.size} shards but the "
            f"array physically lives on {ndev} device(s) — metadata lies"
        )
        if isinstance(arr.sharding, NamedSharding):
            spec = arr.sharding.spec
            entry = spec[x.split] if x.split < len(spec) else None
            names = entry if isinstance(entry, tuple) else (entry,)
            assert comm.axis in [n for n in names if n], (
                f"split={x.split} but sharding spec {spec} does not shard that axis "
                f"over {comm.axis!r}"
            )

    def assert_array_equal(self, heat_array, expected_array, rtol=1e-5, atol=1e-6):
        if isinstance(expected_array, ht.DNDarray):
            expected_array = expected_array.numpy()
        expected_array = np.asarray(expected_array)
        assert isinstance(heat_array, ht.DNDarray), f"expected DNDarray, got {type(heat_array)}"
        assert tuple(heat_array.shape) == tuple(expected_array.shape), (
            f"global shape mismatch: {heat_array.shape} != {expected_array.shape}"
        )
        got = heat_array.numpy()
        if got.dtype.kind in "fc":
            np.testing.assert_allclose(got.astype(np.float64), expected_array.astype(np.float64), rtol=rtol, atol=atol)
        else:
            np.testing.assert_array_equal(got, expected_array)
        # sharding metadata must be self-consistent AND physically true
        if heat_array.split is not None:
            assert 0 <= heat_array.split < max(heat_array.ndim, 1)
        self.assert_distributed(heat_array)

    def assert_func_equal(
        self,
        shape,
        heat_func,
        numpy_func,
        distributed_result=True,
        heat_args=None,
        numpy_args=None,
        data_types=(np.int32, np.float32),
        low=-10000,
        high=10000,
        splits=None,
    ):
        heat_args = heat_args or {}
        numpy_args = numpy_args or {}
        if splits is None:
            splits = [None] + list(range(len(shape)))
        rng = np.random.default_rng(42)
        for dtype in data_types:
            if np.issubdtype(dtype, np.integer):
                data = rng.integers(low, high, size=shape).astype(dtype)
            else:
                data = rng.uniform(low, high, size=shape).astype(dtype)
            expected = numpy_func(data, **numpy_args)
            for split in splits:
                a = ht.array(data, split=split)
                got = heat_func(a, **heat_args)
                self.assert_array_equal(got, expected, rtol=1e-4, atol=1e-4 * max(1.0, abs(high)))
