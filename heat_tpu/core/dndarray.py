"""DNDarray — the distributed N-D array, TPU-native.

Re-design of the reference's ``heat/core/dndarray.py`` (SURVEY §2.1).  The
reference's DNDarray is *locally a torch.Tensor, globally a chunked array*;
each MPI rank stores its chunk and all global bookkeeping (gshape, lshape_map,
index translation) is hand-maintained Python.  Here a DNDarray wraps ONE
globally-shaped :class:`jax.Array` whose ``NamedSharding`` over the
communicator's mesh realizes the ``split`` axis:

- ``split=None``  ⇔  fully replicated (``PartitionSpec()``)
- ``split=k``     ⇔  axis ``k`` sharded over the mesh axis
  (``PartitionSpec(..., 'x', ...)``)

All inter-chip data movement is emitted by XLA when ops require it; the
explicit ``resplit_`` maps to a resharding ``device_put`` (→ all-to-all).

DNDarray is registered as a JAX pytree (the array is the leaf; split/device/
comm are static aux data), so user functions over DNDarrays can be ``jax.jit``
-ed, differentiated, and vmapped — something the reference fundamentally
cannot offer.
"""

from __future__ import annotations

import warnings
from typing import List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import types
from .communication import Communication
from .devices import Device
from .stride_tricks import sanitize_axis

__all__ = ["DNDarray"]

Scalar = Union[int, float, bool, complex]


class LocalIndex:
    """Marker for local-index assignment, parity with reference ``x.lloc``."""

    def __init__(self, arr: "DNDarray"):
        self.arr = arr

    def __getitem__(self, key):
        return self.arr.larray[key]

    def __setitem__(self, key, value):
        # local == global view on a single controller; route through global set
        self.arr[key] = value


class DNDarray:
    """A globally-shaped, mesh-sharded N-D array with a NumPy-style API."""

    def __init__(
        self,
        array: jax.Array,
        gshape: Tuple[int, ...],
        dtype,
        split: Optional[int],
        device: Device,
        comm: Communication,
        balanced: Optional[bool] = True,
    ):
        self.__array = array
        self.__gshape = tuple(int(s) for s in gshape)
        self.__dtype = types.canonical_heat_type(dtype)
        self.__split = split
        self.__device = device
        self.__comm = comm
        self.__balanced = balanced

    # ------------------------------------------------------------------ #
    # internal access
    # ------------------------------------------------------------------ #
    @property
    def _jarray(self) -> jax.Array:
        """The underlying global jax.Array (framework-internal)."""
        return self.__array

    @_jarray.setter
    def _jarray(self, arr) -> None:
        self.__array = arr

    # ------------------------------------------------------------------ #
    # reference-parity attributes
    # ------------------------------------------------------------------ #
    @property
    def larray(self) -> jax.Array:
        """The process-local data.

        Single-controller JAX addresses all chips, so the 'local' view is the
        global array itself.  (Reference users index shards via
        ``lshape_map``/``chunk``.)
        """
        return self.__array

    @larray.setter
    def larray(self, array: jax.Array) -> None:
        self.__array = array

    @property
    def shape(self) -> Tuple[int, ...]:
        return self.__gshape

    @property
    def gshape(self) -> Tuple[int, ...]:
        return self.__gshape

    @property
    def lshape(self) -> Tuple[int, ...]:
        """Shape of this process's first shard (reference: this rank's chunk)."""
        _, lshape, _ = self.__comm.chunk(self.__gshape, self.__split, rank=0)
        return lshape

    def lshape_map(self, force_check: bool = False) -> np.ndarray:
        """(size, ndim) matrix of all shard shapes — pure math, no comm needed."""
        return self.__comm.lshape_map(self.__gshape, self.__split)

    @property
    def dtype(self):
        return self.__dtype

    @property
    def split(self) -> Optional[int]:
        return self.__split

    @property
    def device(self) -> Device:
        return self.__device

    @property
    def comm(self) -> Communication:
        return self.__comm

    @property
    def balanced(self) -> bool:
        return bool(self.__balanced)

    @property
    def ndim(self) -> int:
        return len(self.__gshape)

    @property
    def size(self) -> int:
        return int(np.prod(self.__gshape, dtype=np.int64)) if self.__gshape else 1

    @property
    def gnumel(self) -> int:
        return self.size

    @property
    def lnumel(self) -> int:
        return int(np.prod(self.lshape, dtype=np.int64)) if self.lshape else 1

    @property
    def nbytes(self) -> int:
        return self.size * self.__dtype.np_dtype().itemsize

    @property
    def gnbytes(self) -> int:
        return self.nbytes

    @property
    def lnbytes(self) -> int:
        return self.lnumel * self.__dtype.np_dtype().itemsize

    @property
    def imag(self) -> "DNDarray":
        from . import complex_math

        return complex_math.imag(self)

    @property
    def real(self) -> "DNDarray":
        from . import complex_math

        return complex_math.real(self)

    @property
    def T(self) -> "DNDarray":
        from ..linalg import basics

        return basics.transpose(self)

    @property
    def lloc(self) -> LocalIndex:
        return LocalIndex(self)

    @property
    def stride(self) -> Tuple[int, ...]:
        """Row-major strides in elements (XLA owns the physical layout)."""
        strides = np.cumprod((1,) + self.__gshape[:0:-1])[::-1]
        return tuple(int(s) for s in strides)

    @property
    def strides(self) -> Tuple[int, ...]:
        return tuple(s * self.__dtype.np_dtype().itemsize for s in self.stride)

    @property
    def __partitioned__(self) -> dict:
        """Cross-framework partitioned-array protocol (reference parity)."""
        comm = self.__comm
        parts = {}
        for r in range(comm.size if self.__split is not None else 1):
            off, lsh, _ = comm.chunk(self.__gshape, self.__split, r)
            pos = (r,)
            start = tuple(
                off if i == self.__split else 0 for i in range(self.ndim)
            ) if self.__split is not None else (0,) * self.ndim
            parts[pos] = {
                "start": start,
                "shape": lsh,
                "data": None,
                "location": [r],
                "dtype": self.__dtype.np_dtype(),
            }
        return {
            "shape": self.__gshape,
            "partition_tiling": (comm.size,) if self.__split is not None else (1,),
            "partitions": parts,
            "locals": [(comm.rank,)],
            "get": lambda x: x,
        }

    # ------------------------------------------------------------------ #
    # basic conversions
    # ------------------------------------------------------------------ #
    def astype(self, dtype, copy: bool = True) -> "DNDarray":
        from . import _complexsafe

        dtype = types.canonical_heat_type(dtype)
        jdt = dtype.jax_dtype()
        src = self.__array
        if jnp.issubdtype(jdt, jnp.complexfloating) and not _complexsafe.native_complex_supported():
            src = _complexsafe.to_host_backend(src)
        casted = src.astype(jdt)
        # honor JAX canonicalization (64→32-bit when x64 is off) in metadata
        dtype = types.canonical_heat_type(casted.dtype)
        if copy:
            return DNDarray(
                casted, self.__gshape, dtype, self.__split, self.__device, self.__comm, self.__balanced
            )
        self.__array = casted
        self.__dtype = dtype
        return self

    def numpy(self) -> np.ndarray:
        """Gather the global array to host memory as a numpy array."""
        try:
            return np.asarray(jax.device_get(self.__array))
        except jax.errors.JaxRuntimeError:
            if jnp.issubdtype(self.__array.dtype, jnp.complexfloating):
                # some TPU transports cannot ship complex buffers to host;
                # move the real/imag planes separately and recombine
                re = np.asarray(jax.device_get(jnp.real(self.__array)))
                im = np.asarray(jax.device_get(jnp.imag(self.__array)))
                return (re + 1j * im).astype(self.__dtype.np_dtype())
            raise

    def __array__(self, dtype=None) -> np.ndarray:
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def tolist(self, keepsplit: bool = False) -> List:
        return self.numpy().tolist()

    def item(self):
        if self.size != 1:
            raise ValueError("only one-element DNDarrays can be converted to scalars")
        return self.__array.reshape(()).item()

    def __bool__(self) -> bool:
        return bool(self.item())

    def __int__(self) -> int:
        return int(self.item())

    def __float__(self) -> float:
        return float(self.item())

    def __complex__(self) -> complex:
        return complex(self.item())

    def __index__(self) -> int:
        if not types.heat_type_is_exact(self.__dtype):
            raise TypeError("only integer scalar arrays can be used as an index")
        return int(self.item())

    def __len__(self) -> int:
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.__gshape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # ------------------------------------------------------------------ #
    # device / distribution management
    # ------------------------------------------------------------------ #
    def is_distributed(self) -> bool:
        return self.__split is not None and self.__comm.is_distributed()

    def is_balanced(self, force_check: bool = False) -> bool:
        return True  # ceil-div sharding is the only layout; always balanced

    def balance_(self) -> None:
        self.__balanced = True

    def resplit_(self, axis: Optional[int] = None) -> "DNDarray":
        """In-place redistribution to a new split axis (reference SURVEY §3.3).

        Lowered by XLA to an all-to-all (split↔split) or allgather (→None).
        """
        axis = sanitize_axis(self.__gshape, axis)
        if axis == self.__split:
            return self
        self.__array = self.__comm.resplit(self.__array, axis)
        self.__split = axis
        self.__balanced = True
        return self

    def redistribute_(self, lshape_map=None, target_map=None) -> None:
        """Reference parity: arbitrary re-chunking.

        The ceil-div grid is the only physical layout under NamedSharding, so
        redistribution to arbitrary chunk maps is a no-op on the contents; the
        request is honored by rebalancing.
        """
        self.balance_()

    def resplit(self, axis: Optional[int] = None) -> "DNDarray":
        from . import manipulations

        return manipulations.resplit(self, axis)

    def cpu(self) -> "DNDarray":
        from . import devices as _dev

        return self.to_device(_dev.cpu)

    def to_device(self, device) -> "DNDarray":
        from . import devices as _dev
        from .communication import Communication

        device = _dev.sanitize_device(device)
        if device == self.__device:
            return self
        comm = Communication(device.mesh)
        arr = jax.device_put(self.numpy(), comm.sharding(self.ndim, self.__split))
        return DNDarray(arr, self.__gshape, self.__dtype, self.__split, device, comm, True)

    # ------------------------------------------------------------------ #
    # halo support (reference: get_halo / array_with_halos, used by convolve)
    # ------------------------------------------------------------------ #
    def get_halo(self, halo_size: int, prev: bool = True, next: bool = True) -> None:
        """Record the requested halo width; materialization happens inside the
        shard_map of the consuming op (see ``parallel.halo.halo_exchange``)."""
        if not isinstance(halo_size, int) or halo_size < 0:
            raise (TypeError if not isinstance(halo_size, int) else ValueError)(
                f"halo_size needs to be a non-negative int, got {halo_size}"
            )
        self.__halo_size = halo_size

    @property
    def array_with_halos(self) -> jax.Array:
        from ..parallel.halo import with_halos

        hs = getattr(self, "_DNDarray__halo_size", 0)
        if self.__split is None or hs == 0:
            return self.__array
        return with_halos(self.__array, hs, self.__split, self.__comm)

    # ------------------------------------------------------------------ #
    # indexing
    # ------------------------------------------------------------------ #
    def _normalized_key(self, key):
        def conv(k):
            if isinstance(k, DNDarray):
                return k._jarray
            if isinstance(k, (list, np.ndarray)):
                # numpy-style list/ndarray fancy index → jnp array
                return jnp.asarray(k)
            return k

        if isinstance(key, tuple):
            return tuple(conv(k) for k in key)
        return conv(key)

    def _result_split_of_key(self, key) -> Optional[int]:
        """Compute the split axis of an indexing result (None ⇒ replicated)."""
        if self.__split is None:
            return None
        key_t = key if isinstance(key, tuple) else (key,)
        # expand Ellipsis
        if any(k is Ellipsis for k in key_t):
            n_specified = sum(1 for k in key_t if k is not None and k is not Ellipsis)
            fill = self.ndim - n_specified
            out = []
            for k in key_t:
                if k is Ellipsis:
                    out.extend([slice(None)] * fill)
                else:
                    out.append(k)
            key_t = tuple(out)
        # walk input axes vs output axes
        in_ax = 0
        out_ax = 0
        has_advanced = any(
            isinstance(k, (list, np.ndarray, jax.Array)) and not isinstance(k, (bool, np.bool_))
            for k in key_t
        )
        for k in key_t:
            if k is None:
                out_ax += 1
                continue
            if in_ax == self.__split:
                if isinstance(k, slice):
                    return out_ax
                if isinstance(k, (int, np.integer)):
                    return None
                # advanced index on the split axis
                if has_advanced and not isinstance(k, (bool, np.bool_)):
                    # 1-D fancy index keeps a distributed result axis
                    return 0 if not isinstance(k, slice) else out_ax
                return None
            if isinstance(k, (int, np.integer)):
                in_ax += 1  # consumes an axis, produces none
            elif isinstance(k, slice):
                in_ax += 1
                out_ax += 1
            else:
                # advanced index consumes (possibly several for bool) axes
                if isinstance(k, (np.ndarray, jax.Array)) and k.dtype == np.bool_:
                    in_ax += k.ndim
                else:
                    in_ax += 1
                out_ax += 1
        # remaining untouched axes
        if in_ax <= self.__split:
            return out_ax + (self.__split - in_ax)
        return None

    def __getitem__(self, key) -> "DNDarray":
        nkey = self._normalized_key(key)
        result = self.__array[nkey]
        new_split = self._result_split_of_key(nkey)
        if new_split is not None and new_split >= result.ndim:
            new_split = None
        result = self.__comm.shard(result, new_split)
        return DNDarray(
            result,
            tuple(result.shape),
            types.canonical_heat_type(result.dtype),
            new_split,
            self.__device,
            self.__comm,
            True,
        )

    def __setitem__(self, key, value) -> None:
        nkey = self._normalized_key(key)
        if isinstance(value, DNDarray):
            value = value._jarray
        updated = self.__array.at[nkey].set(value)
        self.__array = self.__comm.shard(updated, self.__split)

    def fill_diagonal(self, value) -> "DNDarray":
        n = min(self.__gshape[-2], self.__gshape[-1]) if self.ndim >= 2 else 0
        idx = jnp.arange(n)
        updated = self.__array.at[..., idx, idx].set(value)
        self.__array = self.__comm.shard(updated, self.__split)
        return self

    # ------------------------------------------------------------------ #
    # printing
    # ------------------------------------------------------------------ #
    def __repr__(self) -> str:
        from . import printing

        return printing.__repr__(self)

    def __str__(self) -> str:
        from . import printing

        return printing.__str__(self)

    # ------------------------------------------------------------------ #
    # interop stubs
    # ------------------------------------------------------------------ #
    def __torch_proxy__(self):
        import torch

        return torch.from_numpy(np.asarray(self.numpy()))

    def counts_displs(self):
        if self.__split is None:
            raise ValueError("Non-distributed DNDarray has no counts and displacements")
        return self.__comm.counts_displs_shape(self.__gshape, self.__split)


# ---------------------------------------------------------------------- #
# pytree registration: DNDarray-valued functions are jit/grad/vmap-able
# ---------------------------------------------------------------------- #
def _dnd_flatten(x: DNDarray):
    return (x._jarray,), (x.split, x.device, x.comm)


def _dnd_unflatten(aux, children):
    (arr,) = children
    split, device, comm = aux
    shape = tuple(arr.shape) if hasattr(arr, "shape") else ()
    try:
        dtype = types.canonical_heat_type(arr.dtype)
    except (TypeError, AttributeError):
        dtype = types.float32
    return DNDarray(arr, shape, dtype, split, device, comm, True)


jax.tree_util.register_pytree_node(DNDarray, _dnd_flatten, _dnd_unflatten)
