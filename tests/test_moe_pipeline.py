"""MoE (expert parallelism) and Pipelined (pipeline parallelism) tests.

assert_distributed exception (r4 #8): both layers operate on raw jax arrays
inside shard_map (not DNDarrays); distribution is the construction itself —
expert weights are mesh-sharded by in_specs and the EP path is asserted to
execute two all-to-alls / the pipeline to execute collective-permutes in the
compiled HLO below.
"""

import numpy as np
import pytest

import heat_tpu as ht

# long-tail contract tests: nightly-style lane (CI 'test' matrix), excluded
# from the PR smoke lane (VERDICT r4 weak #7)
pytestmark = pytest.mark.heavy


def _moe_oracle(x2d, params, top_k, capacity):
    """Per-token loop oracle with slot-major capacity claims."""
    n, _ = x2d.shape
    E = params["router"].shape[1]
    logits = x2d @ params["router"]
    g = np.exp(logits - logits.max(1, keepdims=True))
    g /= g.sum(1, keepdims=True)
    order = np.argsort(-g, axis=1, kind="stable")[:, :top_k]
    vals = np.take_along_axis(g, order, axis=1)
    vals = vals / (vals.sum(1, keepdims=True) + 1e-9)
    counts = np.zeros(E, int)
    y = np.zeros_like(x2d)
    # slot-major: every token's first choice claims before any second choice
    for j in range(top_k):
        for i in range(n):
            e = order[i, j]
            if counts[e] < capacity and vals[i, j] > 0:
                counts[e] += 1
                hid = x2d[i] @ params["w1"][e] + params["b1"][e]
                act = 0.5 * hid * (1 + np.tanh(np.sqrt(2 / np.pi) * (hid + 0.044715 * hid**3)))
                y[i] += vals[i, j] * (act @ params["w2"][e] + params["b2"][e])
    return y


class TestMoE:
    @pytest.mark.parametrize("top_k", [1, 2])
    def test_dense_matches_oracle(self, top_k):
        import jax

        D, E = 8, 4
        moe = ht.nn.MoE(D, E, hidden_dim=16, top_k=top_k, capacity_factor=64.0)
        params = moe.init(jax.random.key(0))
        rng = np.random.default_rng(1)
        x = rng.normal(size=(3, 5, D)).astype(np.float32)
        y = np.asarray(moe.apply(params, x))
        pnp = {k: np.asarray(v) for k, v in params.items()}
        ref = _moe_oracle(x.reshape(-1, D), pnp, top_k, moe._capacity(15)).reshape(x.shape)
        np.testing.assert_allclose(y, ref, rtol=2e-4, atol=2e-5)

    def test_capacity_drops_tokens(self):
        import jax
        import jax.numpy as jnp

        D, E = 8, 2
        # capacity 1 with many tokens: most tokens dropped, outputs finite
        moe = ht.nn.MoE(D, E, hidden_dim=8, top_k=1, capacity_factor=1e-6)
        params = moe.init(jax.random.key(0))
        x = jnp.asarray(np.random.default_rng(0).normal(size=(16, D)), jnp.float32)
        assert moe._capacity(16) == 1
        y = moe.apply(params, x)
        assert y.shape == x.shape and bool(jnp.isfinite(y).all())
        # at most top_k * E * capacity tokens can have nonzero output
        nonzero = int((jnp.abs(y).sum(1) > 0).sum())
        assert nonzero <= 2

    def test_expert_parallel_matches_dense(self):
        import jax
        import jax.numpy as jnp

        comm = ht.communication.get_comm()
        E = 2 * comm.size
        D = 8
        dense = ht.nn.MoE(D, E, hidden_dim=16, top_k=2, capacity_factor=64.0)
        ep = ht.nn.MoE(D, E, hidden_dim=16, top_k=2, capacity_factor=64.0, comm=comm)
        params = dense.init(jax.random.key(0))
        # ragged token count: exercises the pad-and-mask path
        x = jax.random.normal(jax.random.key(1), (3, 7, D))
        yd = dense.apply(params, x)
        yp = ep.apply(params, x)
        np.testing.assert_allclose(np.asarray(yd), np.asarray(yp), rtol=2e-4, atol=2e-5)
        # gradients flow identically through the EP collectives
        gd = jax.grad(lambda p: jnp.sum(dense.apply(p, x) ** 2))(params)
        gp = jax.grad(lambda p: jnp.sum(ep.apply(p, x) ** 2))(params)
        for k in gd:
            np.testing.assert_allclose(
                np.asarray(gd[k]), np.asarray(gp[k]), rtol=1e-3, atol=1e-4
            )

    def test_ep_hlo_has_all_to_all(self):
        import jax

        comm = ht.communication.get_comm()
        if comm.size == 1:
            pytest.skip("needs a multi-device mesh")
        E, D = comm.size, 8
        ep = ht.nn.MoE(D, E, hidden_dim=8, top_k=1, comm=comm)
        params = ep.init(jax.random.key(0))
        x = jax.random.normal(jax.random.key(1), (comm.size, 4, D))
        txt = jax.jit(lambda p, xx: ep.apply(p, xx)).lower(params, x).compile().as_text()
        assert "all-to-all" in txt

    def test_indivisible_experts_warns_and_falls_back(self):
        import jax

        comm = ht.communication.get_comm()
        if comm.size == 1:
            pytest.skip("any count divides 1")
        ep = ht.nn.MoE(8, comm.size + 1, hidden_dim=8, top_k=1, comm=comm)
        params = ep.init(jax.random.key(0))
        x = jax.random.normal(jax.random.key(1), (4, 8))
        with pytest.warns(UserWarning, match="not divisible"):
            y = ep.apply(params, x)
        assert y.shape == x.shape

    def test_decode_apply_matches_dense(self):
        """The drop-free decode path == the capacity path when capacity is
        not binding (the serving contract — see MoE.decode_apply)."""
        import jax
        import jax.numpy as jnp

        moe = ht.nn.MoE(8, 4, hidden_dim=16, top_k=2, capacity_factor=64.0)
        params = moe.init(jax.random.key(0))
        x = jax.random.normal(jax.random.key(1), (6, 8))
        np.testing.assert_allclose(
            np.asarray(moe.decode_apply(params, x)),
            np.asarray(moe.apply(params, x)),
            rtol=2e-4, atol=2e-5,
        )
        # and it NEVER drops: under capacity pressure the capacity path
        # zeroes overflow tokens while decode_apply still serves them
        tight = ht.nn.MoE(8, 2, hidden_dim=16, top_k=1, capacity_factor=1e-6)
        tp = tight.init(jax.random.key(2))
        xt = jax.random.normal(jax.random.key(3), (16, 8))
        served = np.asarray(jnp.abs(tight.decode_apply(tp, xt)).sum(1) > 0)
        assert served.all()

    def test_pad_tokens_do_not_consume_capacity(self):
        """Zero-gate (masked pad) tokens must not occupy queue positions:
        a pad's phantom slot-0 claim would evict a real token's claim under
        capacity pressure (caught in round-4d review)."""
        import jax.numpy as jnp

        from heat_tpu.nn.moe import _routing

        # pad first so any phantom claim outranks the real tokens
        gates = jnp.asarray([[0.0, 0.0], [1.0, 0.0], [1.0, 0.0]])
        dispatch, combine = _routing(gates, top_k=1, capacity=2)
        served = np.asarray(dispatch.sum(axis=(1, 2)))
        np.testing.assert_array_equal(served, [0.0, 1.0, 1.0])

    def test_dp_ep_composition(self):
        """Experts sharded over 'ep' with tokens sharded over 'dp' of one
        2-D mesh — each dp slice routes its tokens through the ep-sharded
        experts; output stays dp-sharded."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        n = len(jax.devices())
        if n < 4 or n % 2:
            pytest.skip("needs an even mesh of >= 4 devices")
        mesh = Mesh(np.asarray(jax.devices()).reshape(2, n // 2), ("dp", "ep"))
        comm_ep = ht.communication.Communication(mesh, axis="ep")
        D, E = 8, n  # divisible by the ep axis
        dense = ht.nn.MoE(D, E, hidden_dim=16, top_k=2, capacity_factor=64.0)
        moe = ht.nn.MoE(D, E, hidden_dim=16, top_k=2, capacity_factor=64.0,
                        comm=comm_ep, batch_axis="dp")
        params = dense.init(jax.random.key(0))
        x = jax.random.normal(jax.random.key(1), (6, 7, D))  # ragged tokens
        np.testing.assert_allclose(
            np.asarray(dense.apply(params, x)), np.asarray(moe.apply(params, x)),
            rtol=2e-4, atol=2e-5,
        )
        g = jax.grad(lambda p: jnp.sum(moe.apply(p, x) ** 2))(params)
        gd = jax.grad(lambda p: jnp.sum(dense.apply(p, x) ** 2))(params)
        for k in g:
            np.testing.assert_allclose(np.asarray(g[k]), np.asarray(gd[k]),
                                       rtol=1e-3, atol=1e-4)
        # the compiled EP program itself shards tokens over BOTH axes
        # jointly — no replicated expert compute over the ep axis (apply's
        # eager unpad/reshape afterwards may legitimately re-lay-out)
        from heat_tpu.nn.moe import _ep_program

        x2d = jax.random.normal(jax.random.key(2), (2 * n, 8))
        mask = jnp.ones((2 * n,), x2d.dtype)
        yprog = _ep_program(comm_ep, moe)(params, x2d, mask)
        assert set(yprog.sharding.spec[0]) == {"dp", "ep"}
        assert len(yprog.sharding.device_set) == n
        with pytest.raises(ValueError, match="batch_axis"):
            ht.nn.MoE(D, E, comm=None, batch_axis="dp")
        with pytest.raises(ValueError, match="batch_axis"):
            ht.nn.MoE(D, E, comm=comm_ep, batch_axis="ep")

    def test_dp_with_single_expert_shard(self):
        """(dp, ep=1) mesh: batch_axis must keep the dp token sharding
        alive instead of silently taking the replicated dense path."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh

        n = len(jax.devices())
        if n < 2:
            pytest.skip("needs a multi-device mesh")
        mesh = Mesh(np.asarray(jax.devices()).reshape(n, 1), ("dp", "ep"))
        comm_ep = ht.communication.Communication(mesh, axis="ep")
        dense = ht.nn.MoE(8, 4, hidden_dim=16, top_k=2, capacity_factor=64.0)
        moe = ht.nn.MoE(8, 4, hidden_dim=16, top_k=2, capacity_factor=64.0,
                        comm=comm_ep, batch_axis="dp")
        params = dense.init(jax.random.key(0))
        from heat_tpu.nn.moe import _ep_program

        x2d = jax.random.normal(jax.random.key(1), (2 * n, 8))
        mask = jnp.ones((2 * n,), x2d.dtype)
        y = _ep_program(comm_ep, moe)(params, x2d, mask)
        assert len(y.sharding.device_set) == n  # dp sharding survived
        np.testing.assert_allclose(
            np.asarray(moe.apply(params, x2d)), np.asarray(dense.apply(params, x2d)),
            rtol=2e-4, atol=2e-5,
        )

    def test_load_balance_loss(self):
        import jax

        moe = ht.nn.MoE(8, 4, hidden_dim=8)
        params = moe.init(jax.random.key(0))
        x = jax.random.normal(jax.random.key(1), (64, 8))
        aux = float(moe.load_balance_loss(params, x))
        assert aux >= 1.0 - 1e-5  # lower bound attained by a uniform router


class _ResBlock(ht.nn.modules.Module):
    def __init__(self, d):
        self.lin = ht.nn.Linear(d, d)

    def init(self, key):
        return self.lin.init(key)

    def apply(self, params, x, **kw):
        import jax.numpy as jnp

        return x + jnp.tanh(self.lin.apply(params, x))


class TestPipelined:
    @pytest.mark.parametrize("n_microbatches", [None, 4, 8])
    def test_matches_sequential(self, n_microbatches):
        import jax
        import jax.numpy as jnp

        comm = ht.communication.get_comm()
        D = 8
        depth = 2 * comm.size
        blk = _ResBlock(D)
        pp = ht.nn.Pipelined(blk, depth, comm, n_microbatches=n_microbatches)
        seq = ht.nn.Pipelined(blk, depth, comm=None)
        params = pp.init(jax.random.key(0))
        # batch divisible by every swept n_microbatches AND by comm.size
        x = jax.random.normal(jax.random.key(1), (8 * comm.size, D))
        y_pp = pp.apply(params, x)
        y_seq = seq.apply(params, x)
        np.testing.assert_allclose(np.asarray(y_pp), np.asarray(y_seq), rtol=1e-5, atol=1e-5)

    def test_backward_pipeline(self):
        import jax
        import jax.numpy as jnp

        comm = ht.communication.get_comm()
        D = 8
        blk = _ResBlock(D)
        pp = ht.nn.Pipelined(blk, 2 * comm.size, comm, remat=False)
        ppr = ht.nn.Pipelined(blk, 2 * comm.size, comm, remat=True)
        seq = ht.nn.Pipelined(blk, 2 * comm.size, comm=None)
        params = pp.init(jax.random.key(0))
        x = jax.random.normal(jax.random.key(1), (2 * comm.size, D))

        g_sq = jax.grad(lambda p: jnp.sum(seq.apply(p, x) ** 2))(params)
        for mod in (pp, ppr):
            g = jax.grad(lambda p: jnp.sum(mod.apply(p, x) ** 2))(params)
            for k in g_sq:
                np.testing.assert_allclose(
                    np.asarray(g[k]), np.asarray(g_sq[k]), rtol=1e-3, atol=1e-4
                )

    def test_hlo_has_collective_permute(self):
        import jax

        comm = ht.communication.get_comm()
        if comm.size == 1:
            pytest.skip("needs a multi-device mesh")
        blk = _ResBlock(8)
        pp = ht.nn.Pipelined(blk, comm.size, comm)
        params = pp.init(jax.random.key(0))
        x = jax.random.normal(jax.random.key(1), (comm.size, 8))
        txt = jax.jit(lambda p, xx: pp.apply(p, xx)).lower(params, x).compile().as_text()
        assert "collective-permute" in txt

    def test_stage_params_are_sharded(self):
        """Each device holds only its stage's slice of the weights."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        comm = ht.communication.get_comm()
        if comm.size == 1:
            pytest.skip("needs a multi-device mesh")
        p = comm.size
        blk = _ResBlock(8)
        pp = ht.nn.Pipelined(blk, p, comm)
        params = pp.init(jax.random.key(0))
        # place the stacked params the way a training loop would
        sharded = jax.device_put(
            params, NamedSharding(comm.mesh, P(comm.axis))
        )
        w = sharded["weight"]
        assert len(w.sharding.device_set) == p
        assert w.addressable_shards[0].data.shape[0] == 1
        x = jax.random.normal(jax.random.key(1), (p, 8))
        y = pp.apply(sharded, x)
        assert y.shape == x.shape

    def test_dp_pp_composition(self):
        """Pipeline over 'pp' with the batch sharded over 'dp' of one 2-D
        mesh: one compiled program is dp x pp parallel; output stays
        dp-sharded."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        n = len(jax.devices())
        if n < 4 or n % 2:
            pytest.skip("needs an even mesh of >= 4 devices")
        mesh = Mesh(np.asarray(jax.devices()).reshape(2, n // 2), ("dp", "pp"))
        comm_pp = ht.communication.Communication(mesh, axis="pp")
        blk = _ResBlock(8)
        pp = ht.nn.Pipelined(blk, depth=n // 2, comm=comm_pp,
                             n_microbatches=2, batch_axis="dp")
        seq = ht.nn.Pipelined(blk, depth=n // 2, comm=None)
        params = pp.init(jax.random.key(0))
        x = jax.random.normal(jax.random.key(1), (8, 8))
        np.testing.assert_allclose(
            np.asarray(pp.apply(params, x)), np.asarray(seq.apply(params, x)),
            rtol=1e-5, atol=1e-5,
        )
        g = jax.grad(lambda p: jnp.sum(pp.apply(p, x) ** 2))(params)
        gs = jax.grad(lambda p: jnp.sum(seq.apply(p, x) ** 2))(params)
        for k in g:
            np.testing.assert_allclose(np.asarray(g[k]), np.asarray(gs[k]),
                                       rtol=1e-3, atol=1e-4)
        y = pp.apply(params, jax.device_put(x, NamedSharding(mesh, P("dp"))))
        assert y.sharding.spec == P("dp")

    def test_dp_with_single_stage(self):
        """(dp, pp=1) mesh: batch_axis must still shard the batch and
        validate, not silently fall back to the unsharded path."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        n = len(jax.devices())
        if n < 2:
            pytest.skip("needs a multi-device mesh")
        mesh = Mesh(np.asarray(jax.devices()).reshape(n, 1), ("dp", "pp"))
        comm_pp = ht.communication.Communication(mesh, axis="pp")
        blk = _ResBlock(8)
        pp = ht.nn.Pipelined(blk, depth=1, comm=comm_pp, n_microbatches=1,
                             batch_axis="dp")
        seq = ht.nn.Pipelined(blk, depth=1, comm=None)
        params = pp.init(jax.random.key(0))
        x = jax.random.normal(jax.random.key(1), (2 * n, 8))
        y = pp.apply(params, jax.device_put(x, NamedSharding(mesh, P("dp"))))
        assert y.sharding.spec == P("dp")
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(seq.apply(params, x)), rtol=1e-5, atol=1e-5
        )
        bad = ht.nn.Pipelined(blk, depth=1, comm=comm_pp, n_microbatches=1,
                              batch_axis="nope")
        with pytest.raises(ValueError, match="batch_axis"):
            bad.apply(params, x)

    def test_bad_batch_axis_raises(self):
        import jax

        comm = ht.communication.get_comm()
        if comm.size == 1:
            pytest.skip("needs a multi-device mesh")
        blk = _ResBlock(8)
        pp = ht.nn.Pipelined(blk, comm.size, comm, batch_axis=comm.axis)
        params = pp.init(jax.random.key(0))
        x = jax.random.normal(jax.random.key(1), (comm.size, 8))
        with pytest.raises(ValueError, match="batch_axis"):
            pp.apply(params, x)

    def test_indivisible_depth_raises(self):
        comm = ht.communication.get_comm()
        if comm.size == 1:
            pytest.skip("any depth divides 1")
        with pytest.raises(ValueError, match="not divisible"):
            ht.nn.Pipelined(_ResBlock(8), comm.size + 1, comm)

    def test_microbatch_divisibility_raises(self):
        import jax

        comm = ht.communication.get_comm()
        if comm.size == 1:
            pytest.skip("p=1 path never microbatches")
        blk = _ResBlock(8)
        pp = ht.nn.Pipelined(blk, comm.size, comm, n_microbatches=3)
        params = pp.init(jax.random.key(0))
        x = jax.random.normal(jax.random.key(1), (4, 8))
        if x.shape[0] % 3 == 0:
            pytest.skip("pick a non-divisible batch")
        with pytest.raises(ValueError, match="not divisible"):
            pp.apply(params, x)


class TestPipelinedTransformer:
    def test_transformer_block_stack(self):
        """The real target: a transformer block tower, pipelined."""
        import jax
        import jax.numpy as jnp

        comm = ht.communication.get_comm()
        from heat_tpu.nn.models import _TransformerBlock

        blk = _TransformerBlock(16, 2, mlp_ratio=2, causal=True)
        depth = comm.size
        pp = ht.nn.Pipelined(blk, depth, comm, n_microbatches=2)
        seq = ht.nn.Pipelined(blk, depth, comm=None)
        params = pp.init(jax.random.key(0))
        x = jax.random.normal(jax.random.key(1), (4, 10, 16))
        y_pp = pp.apply(params, x)
        y_seq = seq.apply(params, x)
        np.testing.assert_allclose(
            np.asarray(y_pp), np.asarray(y_seq), rtol=2e-4, atol=2e-5
        )
