"""Extended activation zoo mirroring ``torch.nn``'s activation modules.

The reference's ``ht.nn`` resolves ALL of ``torch.nn`` dynamically
(SURVEY §2.5 "nn module mirror"); the TPU-native equivalent enumerates the
same constructor names as pure-functional modules over ``jax.nn`` /
``jnp`` primitives — every one is elementwise (fused into neighbouring ops
by XLA), matches torch's formulas and argument defaults, and is verified
against the ``torch.nn`` oracle in ``tests/test_nn_activations.py``.
``scripts/torch_coverage.py`` accounts for the full ``torch.nn`` surface.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .modules import Module, _Activation

__all__ = [
    "CELU", "ELU", "GLU", "Hardshrink", "Hardsigmoid", "Hardswish",
    "Hardtanh", "LeakyReLU", "LogSigmoid", "Mish", "PReLU", "RReLU",
    "ReLU6", "SELU", "SiLU", "Softmin", "Softplus", "Softshrink",
    "Softsign", "Tanhshrink", "Threshold",
]


class ELU(Module):
    def __init__(self, alpha: float = 1.0):
        self.alpha = alpha

    def apply(self, params, x, **kw):
        return jax.nn.elu(x, alpha=self.alpha)


class CELU(Module):
    def __init__(self, alpha: float = 1.0):
        self.alpha = alpha

    def apply(self, params, x, **kw):
        return jax.nn.celu(x, alpha=self.alpha)


class SELU(_Activation):
    fn = staticmethod(jax.nn.selu)


class SiLU(_Activation):
    fn = staticmethod(jax.nn.silu)


class Mish(_Activation):
    # x * tanh(softplus(x)) — jax.nn.mish is absent in some versions
    fn = staticmethod(lambda x: x * jnp.tanh(jax.nn.softplus(x)))


class ReLU6(_Activation):
    fn = staticmethod(lambda x: jnp.clip(x, 0.0, 6.0))


class LeakyReLU(Module):
    def __init__(self, negative_slope: float = 0.01):
        self.negative_slope = negative_slope

    def apply(self, params, x, **kw):
        return jax.nn.leaky_relu(x, negative_slope=self.negative_slope)


class LogSigmoid(_Activation):
    fn = staticmethod(jax.nn.log_sigmoid)


class Softplus(Module):
    """torch formula incl. the linear-above-threshold shortcut (numerical
    parity: torch returns x where beta*x > threshold)."""

    def __init__(self, beta: float = 1.0, threshold: float = 20.0):
        self.beta = beta
        self.threshold = threshold

    def apply(self, params, x, **kw):
        return jnp.where(
            self.beta * x > self.threshold, x,
            jax.nn.softplus(self.beta * x) / self.beta,
        )


class Softsign(_Activation):
    fn = staticmethod(jax.nn.soft_sign)


class Tanhshrink(_Activation):
    fn = staticmethod(lambda x: x - jnp.tanh(x))


class Hardtanh(Module):
    def __init__(self, min_val: float = -1.0, max_val: float = 1.0):
        self.min_val = min_val
        self.max_val = max_val

    def apply(self, params, x, **kw):
        return jnp.clip(x, self.min_val, self.max_val)


class Hardswish(_Activation):
    fn = staticmethod(lambda x: x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0)


class Hardsigmoid(_Activation):
    fn = staticmethod(lambda x: jnp.clip(x + 3.0, 0.0, 6.0) / 6.0)


class Hardshrink(Module):
    def __init__(self, lambd: float = 0.5):
        self.lambd = lambd

    def apply(self, params, x, **kw):
        return jnp.where(jnp.abs(x) > self.lambd, x, 0.0)


class Softshrink(Module):
    def __init__(self, lambd: float = 0.5):
        self.lambd = lambd

    def apply(self, params, x, **kw):
        return jnp.sign(x) * jnp.maximum(jnp.abs(x) - self.lambd, 0.0)


class Threshold(Module):
    def __init__(self, threshold: float, value: float):
        self.threshold = threshold
        self.value = value

    def apply(self, params, x, **kw):
        return jnp.where(x > self.threshold, x, self.value)


class GLU(Module):
    def __init__(self, dim: int = -1):
        self.dim = dim

    def apply(self, params, x, **kw):
        a, b = jnp.split(x, 2, axis=self.dim)
        return a * jax.nn.sigmoid(b)


class Softmin(Module):
    def __init__(self, dim: int = -1):
        self.dim = dim

    def apply(self, params, x, **kw):
        return jax.nn.softmax(-x, axis=self.dim)


class PReLU(Module):
    """Learned leaky slope (the one parametric activation in the zoo —
    ``num_parameters`` is 1 or the channel count, broadcast on axis 1 for
    >=2-D inputs exactly as torch does)."""

    def __init__(self, num_parameters: int = 1, init: float = 0.25):
        self.num_parameters = num_parameters
        self._init_val = init  # 'init' the attr would shadow init() the method

    def init(self, key):
        return {"weight": jnp.full((self.num_parameters,), self._init_val)}

    def apply(self, params, x, **kw):
        a = params["weight"]
        if x.ndim >= 2 and a.shape[0] > 1:
            a = a.reshape((1, -1) + (1,) * (x.ndim - 2))
        return jnp.where(x >= 0, x, a * x)


class RReLU(Module):
    """Randomized leaky ReLU: slope ~ U[lower, upper] per element in train
    mode (requires ``key=``, like Dropout), fixed mean slope in eval."""

    def __init__(self, lower: float = 1.0 / 8, upper: float = 1.0 / 3):
        self.lower = lower
        self.upper = upper

    def apply(self, params, x, *, train: bool = False, key=None):
        if not train:
            return jnp.where(x >= 0, x, 0.5 * (self.lower + self.upper) * x)
        if key is None:
            raise ValueError("RReLU in train mode requires a PRNG key")
        slope = jax.random.uniform(key, x.shape, minval=self.lower, maxval=self.upper)
        return jnp.where(x >= 0, x, slope * x)
