"""Generalized op dispatch (reference: ``heat/core/_operations.py``, SURVEY §2.1).

The reference's four dispatch helpers do sanitize → local torch call →
explicit collective → wrap.  Here the collective step vanishes: ops run on
globally-shaped sharded ``jax.Array``s and XLA's SPMD partitioner emits any
required communication.  What remains is *metadata propagation* — computing
the result ``split`` under broadcasting and reductions, and reconciling
mismatched splits (an explicit reshard, with the reference's perf warning).
"""

from __future__ import annotations

import warnings
from typing import Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import _complexsafe, sanitation, types
from .communication import sanitize_comm
from .dndarray import DNDarray
from .stride_tricks import broadcast_shape, sanitize_axis

__all__ = ["_local_op", "_binary_op", "_reduce_op", "_cum_op"]


def _reduce_kinds():
    # nan* ops: NaN is the exact masking identity on floats (ignored by the
    # op, and an all-NaN slice still yields NaN as numpy does); on integer
    # dtypes nan-ops degenerate to the plain op, so the base kind applies
    kinds = {}
    for name, kind in (
        ("sum", "zero"), ("nansum", ("nan", "zero")), ("count_nonzero", "zero"),
        ("any", "zero"), ("prod", "one"), ("nanprod", ("nan", "one")), ("all", "one"),
        ("max", "neg"), ("amax", "neg"), ("nanmax", ("nan", "neg")), ("argmax", "neg"),
        ("min", "pos"), ("amin", "pos"), ("nanmin", ("nan", "pos")), ("argmin", "pos"),
    ):
        fn = getattr(jnp, name, None)
        if fn is not None:
            kinds[fn] = kind
    return kinds


_REDUCE_KIND = _reduce_kinds()


def _reduce_identity(op, dtype):
    """Identity fill value for masking the pad region of a ragged array under
    reduction ``op`` (pad-and-mask boundary masking); None = op not maskable."""
    kind = _REDUCE_KIND.get(op)
    if kind is None:
        return None
    dt = jnp.dtype(dtype)
    is_float = jnp.issubdtype(dt, jnp.floating) or jnp.issubdtype(dt, jnp.complexfloating)
    if isinstance(kind, tuple):
        if is_float:
            return jnp.nan
        kind = kind[1]
    if kind == "zero":
        return False if dt == jnp.bool_ else 0
    if kind == "one":
        return True if dt == jnp.bool_ else 1
    if dt == jnp.bool_:
        return False if kind == "neg" else True
    if is_float:
        return -jnp.inf if kind == "neg" else jnp.inf
    info = jnp.iinfo(dt)
    return info.min if kind == "neg" else info.max


def _local_op(op: Callable, x: DNDarray, out: Optional[DNDarray] = None, **kwargs) -> DNDarray:
    """Elementwise op with no communication; split is preserved."""
    sanitation.sanitize_in(x)
    if x._pad and out is None:
        # ragged fast path: compute on the padded physical array — the pad
        # region produces dead values (masked at reduction boundaries), and
        # the result stays fully sharded with no unpad gather
        phys = op(x._parray, **kwargs)
        if phys.shape == x._parray.shape:
            return DNDarray(
                phys,
                x.shape,
                types.canonical_heat_type(phys.dtype),
                x.split,
                x.device,
                x.comm,
                x.balanced,
            )
    result = op(x._jarray, **kwargs)
    result = x.comm.shard(result, x.split if x.split is not None and x.split < result.ndim else None)
    if out is not None:
        sanitation.sanitize_out(out, result.shape, x.split, x.device)
        out._jarray = result.astype(out.dtype.jax_dtype())
        return out
    return DNDarray(
        result,
        tuple(result.shape),
        types.canonical_heat_type(result.dtype),
        x.split if x.split is not None and x.split < result.ndim else None,
        x.device,
        x.comm,
        x.balanced,
    )


def _result_split(
    shapes_splits: Tuple[Tuple[Tuple[int, ...], Optional[int]], ...], out_ndim: int
) -> Optional[int]:
    """Result split of a broadcasted op: operand splits aligned to output dims."""
    aligned = []
    for shape, split in shapes_splits:
        if split is None:
            continue
        aligned.append(split + (out_ndim - len(shape)))
    if not aligned:
        return None
    return aligned[0]


def _binary_op(
    op: Callable,
    t1,
    t2,
    out: Optional[DNDarray] = None,
    where=None,
    fn_kwargs: Optional[dict] = None,
) -> DNDarray:
    """Broadcasting binary op with split reconciliation (reference __binary_op)."""
    from . import factories

    fn_kwargs = fn_kwargs or {}
    if not isinstance(t1, DNDarray) and not isinstance(t2, DNDarray):
        raise TypeError(f"At least one operand must be a DNDarray, got {type(t1)}, {type(t2)}")

    proto = t1 if isinstance(t1, DNDarray) else t2
    device, comm = proto.device, proto.comm

    def as_operand(t):
        if isinstance(t, DNDarray):
            return t
        if np.isscalar(t) or isinstance(t, (np.ndarray, jax.Array, list, tuple)):
            return factories.array(t, device=device, comm=comm)
        raise TypeError(f"Unsupported operand type {type(t)}")

    # keep Python scalars as weak-typed scalars (jnp promotion handles them);
    # everything else becomes a DNDarray
    t1_scalar = np.isscalar(t1) and not isinstance(t1, (np.generic,))
    t2_scalar = np.isscalar(t2) and not isinstance(t2, (np.generic,))
    a1 = t1 if t1_scalar else as_operand(t1)
    a2 = t2 if t2_scalar else as_operand(t2)

    s1 = a1.split if isinstance(a1, DNDarray) else None
    s2 = a2.split if isinstance(a2, DNDarray) else None
    sh1 = a1.shape if isinstance(a1, DNDarray) else ()
    sh2 = a2.shape if isinstance(a2, DNDarray) else ()
    out_shape = broadcast_shape(sh1, sh2)
    out_ndim = len(out_shape)

    # split reconciliation: both distributed along different output axes →
    # reshard the second operand (comm!), mirroring the reference's warning
    if s1 is not None and s2 is not None:
        al1 = s1 + (out_ndim - len(sh1))
        al2 = s2 + (out_ndim - len(sh2))
        if al1 != al2:
            warnings.warn(
                "Binary operation with mismatched splits triggers a redistribution "
                f"(split {s2} -> {al1 - (out_ndim - len(sh2))}); this is a communication-heavy operation."
            )
            a2 = a2.resplit(al1 - (out_ndim - len(sh2)))
            s2 = a2.split

    res_split = _result_split(
        ((sh1, s1), (sh2, s2)),
        out_ndim,
    )

    # ragged fast path: same shape + same split + same pad → operate on the
    # padded physical arrays directly (pad regions stay dead, no unpad gather)
    if out is None and where is None:
        d1, d2 = isinstance(a1, DNDarray), isinstance(a2, DNDarray)
        p1 = a1._pad if d1 else 0
        p2 = a2._pad if d2 else 0
        if (p1 or p2) and (
            (d1 and d2 and sh1 == sh2 and s1 == s2 and p1 == p2)
            or (d1 and p1 and not d2 and np.isscalar(a2))
            or (d2 and p2 and not d1 and np.isscalar(a1))
        ):
            pj1 = a1._parray if d1 else a1
            pj2 = a2._parray if d2 else a2
            pj1, pj2 = _complexsafe.colocate(pj1, pj2) if (d1 and d2) else (pj1, pj2)
            phys = op(pj1, pj2, **fn_kwargs)
            return DNDarray(
                phys,
                out_shape,
                types.canonical_heat_type(phys.dtype),
                res_split,
                device,
                comm,
                True,
            )

    j1 = a1._jarray if isinstance(a1, DNDarray) else a1
    j2 = a2._jarray if isinstance(a2, DNDarray) else a2
    j1, j2 = _complexsafe.colocate(j1, j2)
    result = op(j1, j2, **fn_kwargs)
    if res_split is not None and res_split >= result.ndim:
        res_split = None
    result = comm.shard(result, res_split)

    if out is not None:
        if where is not None:
            w = where._jarray if isinstance(where, DNDarray) else jnp.asarray(where)
            w, result = _complexsafe.colocate(w, result)
            ob, result = _complexsafe.colocate(out._jarray, result)
            result = jnp.where(w, result, ob)
            result = comm.shard(result, res_split)
        sanitation.sanitize_out(out, result.shape, res_split, device)
        out._jarray = result.astype(out.dtype.jax_dtype())
        return out
    if where is not None:
        w = where._jarray if isinstance(where, DNDarray) else jnp.asarray(where)
        w, result = _complexsafe.colocate(w, result)
        result = comm.shard(jnp.where(w, result, jnp.zeros_like(result)), res_split)
    return DNDarray(
        result,
        tuple(result.shape),
        types.canonical_heat_type(result.dtype),
        res_split,
        device,
        comm,
        True,
    )


def _reduce_op(
    op: Callable,
    x: DNDarray,
    axis: Union[int, Tuple[int, ...], None] = None,
    keepdims: bool = False,
    out: Optional[DNDarray] = None,
    dtype=None,
    **kwargs,
) -> DNDarray:
    """Reduction with split bookkeeping (reference __reduce_op).

    Reducing over the split axis (or all axes) yields a replicated result —
    the implicit ``Allreduce``; other axes keep the (shifted) split.
    """
    sanitation.sanitize_in(x)
    axis = sanitize_axis(x.shape, axis)

    split = x.split
    if split is None or axis is None:
        new_split = None
        reduces_split = axis is None and split is not None
    else:
        axes = (axis,) if isinstance(axis, int) else tuple(axis)
        reduces_split = split in axes
        if reduces_split:
            new_split = None
        elif keepdims:
            new_split = split
        else:
            new_split = split - sum(1 for a in axes if a < split)

    # ragged fast path: reduce the padded physical array with the pad region
    # replaced by the op's identity element (pad-and-mask boundary masking)
    fill = _reduce_identity(op, x._parray.dtype) if x._pad else None
    if fill is not None and axis is None and op in (jnp.argmax, jnp.argmin):
        # flat arg-reductions index PHYSICAL coordinates when an interior axis
        # is padded — the flat index would be wrong; take the logical path
        fill = None
    if x._pad and out is None and fill is not None:
        ok_split = reduces_split or (new_split is not None)
        phys = op(x._masked(fill), axis=axis, keepdims=keepdims, **kwargs) if ok_split else None
        if phys is not None and (new_split is None or new_split < phys.ndim):
            if dtype is not None:
                phys = phys.astype(types.canonical_heat_type(dtype).jax_dtype())
            if reduces_split:
                # pad axis reduced away under identity masking: result logical
                phys = x.comm.shard(phys, None)
                return DNDarray(
                    phys, tuple(phys.shape), types.canonical_heat_type(phys.dtype),
                    None, x.device, x.comm, True,
                )
            # split axis survives (still padded in phys): logical gshape shrinks
            gshape = list(phys.shape)
            gshape[new_split] -= x._pad
            return DNDarray(
                phys, tuple(gshape), types.canonical_heat_type(phys.dtype),
                new_split, x.device, x.comm, True,
            )

    result = op(x._jarray, axis=axis, keepdims=keepdims, **kwargs)
    if dtype is not None:
        result = result.astype(types.canonical_heat_type(dtype).jax_dtype())
    if new_split is not None and new_split >= result.ndim:
        new_split = None
    result = x.comm.shard(result, new_split)
    if out is not None:
        sanitation.sanitize_out(out, result.shape, new_split, x.device)
        out._jarray = result.astype(out.dtype.jax_dtype())
        return out
    return DNDarray(
        result,
        tuple(result.shape),
        types.canonical_heat_type(result.dtype),
        new_split,
        x.device,
        x.comm,
        True,
    )


def _cum_op(
    op: Callable,
    x: DNDarray,
    axis: int,
    dtype=None,
    out: Optional[DNDarray] = None,
) -> DNDarray:
    """Cumulative op along ``axis`` (reference __cum_op via Exscan; here XLA scan)."""
    sanitation.sanitize_in(x)
    axis = sanitize_axis(x.shape, axis)
    if axis is not None and x._pad and out is None:
        # ragged fast path: identity-masked physical cumulation — the valid
        # prefix is exact (pad contributes the identity); pad region is dead
        fill = {getattr(jnp, "cumsum", None): 0, getattr(jnp, "cumprod", None): 1}.get(op)
        if fill is not None:
            src = x._masked(fill) if axis == x.split else x._parray
            phys = op(src, axis=axis)
            if dtype is not None:
                phys = phys.astype(types.canonical_heat_type(dtype).jax_dtype())
            return DNDarray(
                phys, x.shape, types.canonical_heat_type(phys.dtype),
                x.split, x.device, x.comm, True,
            )
    if axis is None:
        # numpy semantics: flatten
        flat = x._jarray.reshape(-1)
        result = op(flat, axis=0)
        split = None
    else:
        result = op(x._jarray, axis=axis)
        split = x.split
    if dtype is not None:
        result = result.astype(types.canonical_heat_type(dtype).jax_dtype())
    result = x.comm.shard(result, split)
    if out is not None:
        sanitation.sanitize_out(out, result.shape, split, x.device)
        out._jarray = result.astype(out.dtype.jax_dtype())
        return out
    return DNDarray(
        result,
        tuple(result.shape),
        types.canonical_heat_type(result.dtype),
        split,
        x.device,
        x.comm,
        True,
    )
