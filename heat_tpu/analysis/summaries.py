"""Per-function effect summaries, fixpoint-propagated through the call graph.

This is the other half of the interprocedural engine (structure lives in
:mod:`.callgraph`): for every function in the linted tree it computes a
serializable **effect summary** —

- the ordered **collective footprint** (which collectives are staged, in
  what order, with branch structure preserved: a data-conditional ``if``
  whose arms stage different sequences becomes an ``either`` atom, a
  rank-conditional one is recorded for HT201);
- **host syncs** performed (the HT101 sink vocabulary), with their
  lexical-visibility class (``naked`` = HT101 flags the site itself,
  ``suppressed`` = an inline disable hides it);
- **blocking waits** outside any lexical ``comm.deadline`` scope (HT107's
  vocabulary);
- **donated parameters** (directly, or transitively by passing a param
  into a callee position that donates);
- whether the function **returns a device value** (so ``float(helper(x))``
  can be recognized as a host sync lexical HT101 provably misses);

and then propagates them through resolved call edges to a fixpoint.
Propagation is honest about its blind spots: *poisoning* unresolved calls
(see ``callgraph.POISONING_REASONS``) turn any conclusion that crosses them
into ``info`` severity, and public functions are **consumption barriers** —
an effect is reported once, at the first public boundary that reaches it,
never cascaded to that boundary's callers.

Summaries are cached per file in ``.heatlint-summaries.json`` keyed by a
content hash, so an unchanged file costs one hash, not one AST walk; the
cross-file linking and fixpoint always re-run (they are cheap and depend on
the whole file set).  The cache carries TWO version axes: ``version`` (the
JSON layout) and ``schema`` (:data:`ANALYSIS_SCHEMA_REV` — the semantic
revision of the cached facts).  A content hash alone cannot know that the
*analysis* changed underneath an unchanged file: when a new pass adds fact
atoms (the HT3xx absint records, for one), an old cache would silently
serve summaries that lack them.  Bump ``ANALYSIS_SCHEMA_REV`` whenever the
extracted fact vocabulary changes; any mismatch — like a corrupt file — is
a miss, never an error.

Stdlib-only and standalone-loadable, like the rest of ``analysis/``.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from .callgraph import (
    CallDesc,
    CallGraph,
    FileFacts,
    FuncKey,
    Resolution,
    call_desc,
    call_name,
    dotted_name,
    extract_structure,
    last_attr,
)

CACHE_VERSION = 2  # JSON layout of the cache file
# Semantic revision of the cached per-file facts.  Bump whenever extraction
# gains/changes fact atoms so pre-existing caches (keyed by file content
# hash, which cannot see analyzer changes) become misses instead of
# silently serving summaries that lack the new facts.
# rev 2: absint records (rank-taint + array-metadata + split inventory)
# rev 3: ISSUE 13 — item-on-materialized-data sink exemption, axisspec
# named()-aware _literal_split, materializer-collective HT301 exclusion
ANALYSIS_SCHEMA_REV = 3
_EXPAND_CAP = 160  # atoms per expanded footprint before truncation
_CHAIN_CAP = 12  # hops kept in a provenance chain

# ------------------------------------------------------------------ #
# shared effect vocabulary (rules.py re-exports for compatibility)
# ------------------------------------------------------------------ #

COLLECTIVES = frozenset(
    {
        # Communication public API (MPI names)
        "Allreduce", "Allgather", "Alltoall", "Bcast", "Send", "Reduce",
        "Scatter", "Gather", "ReduceScatter", "Scan", "Exscan",
        "Iallreduce", "Iallgather", "Ialltoall", "Ibcast", "Isend", "Irecv",
        "Barrier", "resplit", "resplit_", "redistribute_",
        # collective-by-contract host boundary (every process must call)
        "host_fetch", "numpy", "process_allgather", "sync_global_devices",
        # raw lax collectives
        "psum", "pmax", "pmin", "pmean", "all_gather", "all_to_all",
        "ppermute", "psum_scatter", "pbroadcast",
    }
)

RANK_ATTRS = ("rank",)  # comm.rank, self.rank, ...
RANK_CALLS = ("process_index", "axis_index")  # jax.process_index(), ...
RANK_NAMES = ("rank", "process_id", "pid")  # bare local variables

# calls that END a device-value expression: their result is host data
MATERIALIZERS = frozenset({"host_fetch", "numpy", "tolist", "item"})

# the materialization API: effects NEVER propagate out of these defs —
# calling them is an explicit, visible host boundary, not a hidden sync
HOST_SANCTIONED_DEFS = frozenset(
    {
        "numpy", "item", "tolist", "host_fetch", "host_fetch_all",
        "__array__", "__bool__", "__int__", "__float__", "__complex__",
        "__index__", "__torch_proxy__", "__repr__", "__str__",
    }
)
# modules whose JOB is materialization
HOST_SANCTIONED_MODULES = ("core/printing.py", "core/io.py")

BLOCKING_ATTRS = frozenset(
    {"Barrier", "Wait", "block_until_ready", "sync_global_devices"}
)
WAIT_SANCTIONED_MODULES = ("core/communication.py", "utils/health.py")


def module_matches(path: str, suffixes: Tuple[str, ...]) -> bool:
    return any(path.endswith(s) for s in suffixes)


def routed_through_materializer(node: ast.AST) -> bool:
    """True when the value ``node`` evaluates to is PRODUCED by a
    sanctioned materialization call (``host_fetch``/``numpy()``/``tolist``)
    — i.e. the outermost producer, looking through attribute/subscript
    views (``host_fetch(x).T``, ``host_fetch(x)[0]``), is a materializer:
    the value is host data, so a trailing ``.item()`` on it is plain
    numpy, not a device sync.  A materializer merely *somewhere inside*
    does NOT count: ``jnp.abs(host_fetch(x) - y).item()`` re-enters the
    device domain on top of the fetched data and is exactly the sync the
    rule exists to flag.  ``item`` itself never counts as a route."""
    cur = node
    while isinstance(cur, (ast.Attribute, ast.Subscript)):
        cur = cur.value
    return isinstance(cur, ast.Call) and last_attr(cur) in MATERIALIZERS - {"item"}


def subtree_mentions_device_value(node: ast.AST) -> bool:
    """Heuristic for 'this expression is a device value': it touches the raw
    jax array plumbing (``._jarray``/``._parray``/``.larray``) or directly
    calls into jnp/lax/jax.numpy — UNLESS the expression already routes
    through a sanctioned materialization call (``host_fetch``/``numpy()``),
    in which case the value is host-side by the time it is consumed."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and last_attr(sub) in MATERIALIZERS:
            return False
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in (
            "_jarray",
            "_parray",
            "larray",
        ):
            return True
        if isinstance(sub, ast.Call):
            dn = call_name(sub)
            if dn and (
                dn.startswith("jnp.") or dn.startswith("lax.") or dn.startswith("jax.numpy.")
            ):
                return True
    return False


def rank_marker(test: ast.AST) -> Optional[str]:
    """The rank-identity expression a branch test depends on, or None."""
    for sub in ast.walk(test):
        if isinstance(sub, ast.Attribute) and sub.attr in RANK_ATTRS:
            return dotted_name(sub) or sub.attr
        if isinstance(sub, ast.Call):
            la = last_attr(sub)
            if la in RANK_CALLS:
                return la
        if isinstance(sub, ast.Name) and sub.id in RANK_NAMES:
            return sub.id
    return None


# ------------------------------------------------------------------ #
# effect extraction (one pass per function, shares the parsed tree)
# ------------------------------------------------------------------ #
#
# Footprint atoms are plain JSON lists so summaries round-trip through the
# cache unchanged:
#   ["coll", name, line]                     staged collective (lexical)
#   ["call", call_id, line]                  edge into effects["calls"][id]
#   ["cast", detail, line, call_id]          float/int/bool/np.asarray of a
#                                            single call (device-ness known
#                                            only interprocedurally)
#   ["branch", line, [A...], [B...]]         data-conditional if
#   ["rankbranch", marker, line, [A], [B], kind]   rank-conditional if/while
#   ["loop", line, [body...]]                for / non-rank while
#   ["dlscope", line, [body...]]             with ...deadline(...):
#   ["sink", detail, line, vis]              naked host sync (vis: "naked" |
#                                            "suppressed")
#   ["wait", detail, line, vis]              naked blocking wait


_CAST_NAMES = {"float": "float-cast", "int": "int-cast", "bool": "bool-cast"}


class _EffectExtractor:
    def __init__(self, ctx, fn_node: ast.AST):
        self.ctx = ctx
        self.fn = fn_node
        self.qual = ctx.qualname(fn_node)
        self.calls: List[list] = []  # [desc_json, line, under_dl]
        self.rank_branches: List[list] = []
        self.returns_device = False
        self.returns_calls: List[int] = []  # call ids
        self.direct_donated: List[list] = []  # [param_index, line]
        self.params = self._params()
        self.host_sanctioned = module_matches(
            ctx.path, HOST_SANCTIONED_MODULES
        ) or any(part in HOST_SANCTIONED_DEFS for part in self.qual.split("."))
        self.wait_sanctioned = module_matches(ctx.path, WAIT_SANCTIONED_MODULES)

    def _params(self) -> List[str]:
        a = self.fn.args
        params = [p.arg for p in list(a.posonlyargs) + list(a.args)]
        parent = self.ctx.parent(self.fn)
        if isinstance(parent, ast.ClassDef) and params and params[0] in ("self", "cls"):
            params = params[1:]
        return params

    def run(self) -> dict:
        footprint = self._stmts(self.fn.body, under_dl=False)
        return {
            "footprint": footprint,
            "calls": self.calls,
            "rank_branches": self.rank_branches,
            "returns_device": self.returns_device,
            "returns_calls": self.returns_calls,
            "direct_donated_params": self.direct_donated,
        }

    # ---------------- statement walk ---------------- #

    def _stmts(self, stmts: Sequence[ast.stmt], under_dl: bool) -> List[list]:
        out: List[list] = []
        for stmt in stmts:
            out.extend(self._stmt(stmt, under_dl))
        return out

    def _stmt(self, stmt: ast.stmt, under_dl: bool) -> List[list]:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return []  # their own entities
        if isinstance(stmt, ast.If):
            test_atoms = self._expr(stmt.test, under_dl)
            body = self._stmts(stmt.body, under_dl)
            orelse = self._stmts(stmt.orelse, under_dl)
            marker = rank_marker(stmt.test)
            if marker is not None:
                atom = ["rankbranch", marker, stmt.lineno, body, orelse, "if"]
                self.rank_branches.append(atom)
                return test_atoms + [atom]
            return test_atoms + [["branch", stmt.lineno, body, orelse]]
        if isinstance(stmt, ast.While):
            test_atoms = self._expr(stmt.test, under_dl)
            body = self._stmts(stmt.body + stmt.orelse, under_dl)
            marker = rank_marker(stmt.test)
            if marker is not None:
                atom = ["rankbranch", marker, stmt.lineno, body, [], "while"]
                self.rank_branches.append(atom)
                return test_atoms + [atom]
            return test_atoms + ([["loop", stmt.lineno, body]] if body else [])
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            iter_atoms = self._expr(stmt.iter, under_dl)
            body = self._stmts(stmt.body + stmt.orelse, under_dl)
            return iter_atoms + ([["loop", stmt.lineno, body]] if body else [])
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            item_atoms: List[list] = []
            arms_deadline = False
            for item in stmt.items:
                expr = item.context_expr
                if isinstance(expr, ast.Call) and last_attr(expr) == "deadline":
                    arms_deadline = True
                item_atoms.extend(self._expr(expr, under_dl))
            body = self._stmts(stmt.body, under_dl or arms_deadline)
            if arms_deadline:
                return item_atoms + [["dlscope", stmt.lineno, body]]
            return item_atoms + body
        if isinstance(stmt, ast.Try):
            body = self._stmts(stmt.body + stmt.orelse, under_dl)
            final = self._stmts(stmt.finalbody, under_dl)
            handlers: List[List[list]] = [
                self._stmts(h.body, under_dl) for h in stmt.handlers
            ]
            out = list(body)
            for h in handlers:
                if h != []:
                    # a handler that stages differently from nothing: model
                    # as a branch between "no exception" and this handler
                    out = [["branch", stmt.lineno, out, out + h]]
            return out + final
        if isinstance(stmt, ast.Return):
            if stmt.value is None:
                return []
            atoms = self._expr(stmt.value, under_dl)
            if subtree_mentions_device_value(stmt.value):
                self.returns_device = True
            if isinstance(stmt.value, ast.Call):
                # the call atom for this node was just emitted; it is the
                # last "call" atom referencing this line/col
                for atom in reversed(atoms):
                    if atom[0] == "call" and atom[2] == stmt.value.lineno:
                        self.returns_calls.append(atom[1])
                        break
            return atoms
        # any other statement: collect its expressions in document order
        out = []
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, (ast.expr, ast.keyword)):
                out.extend(self._expr(child, under_dl))
            elif isinstance(child, ast.stmt):
                out.extend(self._stmt(child, under_dl))
        return out

    # ---------------- expression walk ---------------- #

    def _expr(self, node: ast.AST, under_dl: bool) -> List[list]:
        out: List[list] = []
        self._expr_into(node, under_dl, out)
        return out

    def _expr_into(self, node: ast.AST, under_dl: bool, out: List[list]) -> None:
        if isinstance(node, (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # deferred bodies are their own (or no) entity
        if isinstance(node, ast.Call):
            self._call(node, under_dl, out)
            return
        for child in ast.iter_child_nodes(node):
            self._expr_into(child, under_dl, out)

    def _add_call(self, node: ast.Call, under_dl: bool) -> int:
        cid = len(self.calls)
        self.calls.append([call_desc(node).to_json(), node.lineno, under_dl])
        return cid

    def _call(self, node: ast.Call, under_dl: bool, out: List[list]) -> None:
        # Python evaluation order: the callee expression (including a
        # chained receiver — ``comm.resplit(x).numpy()`` stages resplit
        # FIRST) evaluates before the arguments, which evaluate before the
        # call itself; emit atoms in that order.
        if isinstance(node.func, ast.Call):
            # getattr(o, n)(...) — the resolving expression is a call itself
            self._expr_into(node.func, under_dl, out)
        elif isinstance(node.func, ast.Attribute):
            self._expr_into(node.func.value, under_dl, out)
        for child in list(node.args) + [kw.value for kw in node.keywords]:
            self._expr_into(child, under_dl, out)

        la = last_attr(node)
        dn = call_name(node)
        line = node.lineno

        # Barrier()/sync_global_devices are BOTH collectives (footprint) and
        # blocking waits (HT204): emit both atoms, not whichever comes first
        foreign_barrier = la == "Barrier" and (node.args or node.keywords)
        emitted = False
        if (
            la in BLOCKING_ATTRS
            and not self.wait_sanctioned
            and not foreign_barrier
            and not under_dl
        ):
            vis = (
                "suppressed"
                if self.ctx.is_suppressed("HT107", line)
                else "naked"
            )
            out.append(["wait", la, line, vis])
            emitted = True
        if la in COLLECTIVES and not foreign_barrier:
            out.append(["coll", la, line])
            emitted = True
        if emitted:
            return
        # host-sync sinks (HT101 vocabulary)
        if not self.host_sanctioned:
            vis = (
                "suppressed"
                if self.ctx.is_suppressed("HT101", line)
                else "naked"
            )
            if la == "item" and isinstance(node.func, ast.Attribute) and not node.args:
                if not routed_through_materializer(node.func.value):
                    # mirrors HT101: .item() on already-fetched host data is
                    # not a sync, so it must not propagate as one either
                    out.append(["sink", "item", line, vis])
                    return
            if dn == "jax.device_get":
                out.append(["sink", "device_get", line, vis])
                return
            if dn in ("np.asarray", "numpy.asarray", "np.array", "numpy.array") and node.args:
                if subtree_mentions_device_value(node.args[0]):
                    out.append(["sink", "np.asarray", line, vis])
                    return
                if isinstance(node.args[0], ast.Call):
                    cid = self._add_call(node.args[0], under_dl)
                    out.append(["cast", "np.asarray", line, cid])
                    return
            if dn in _CAST_NAMES and len(node.args) == 1:
                if subtree_mentions_device_value(node.args[0]):
                    out.append(["sink", _CAST_NAMES[dn], line, vis])
                    return
                if isinstance(node.args[0], ast.Call):
                    cid = self._add_call(node.args[0], under_dl)
                    out.append(["cast", _CAST_NAMES[dn], line, cid])
                    return

        # direct param donation: f(param, ..., donate=True) / jit positions
        desc = call_desc(node)
        if desc.donate_kwarg and node.args and isinstance(node.args[0], ast.Name):
            name = node.args[0].id
            if name in self.params:
                self.direct_donated.append([self.params.index(name), line])

        cid = self._add_call(node, under_dl)
        out.append(["call", cid, line])


def extract_effects(ctx) -> Dict[str, dict]:
    """qualname -> effect summary for every def in the file."""
    out: Dict[str, dict] = {}
    for node in ctx.walk(ast.FunctionDef, ast.AsyncFunctionDef):
        out[ctx.qualname(node)] = _EffectExtractor(ctx, node).run()
    return out


# ------------------------------------------------------------------ #
# the summary cache
# ------------------------------------------------------------------ #


def file_hash(source: str) -> str:
    return hashlib.sha256(source.encode("utf-8")).hexdigest()


def _empty_cache() -> dict:
    return {"version": CACHE_VERSION, "schema": ANALYSIS_SCHEMA_REV, "files": {}}


def load_cache(path: Optional[str]) -> dict:
    if not path or not os.path.exists(path):
        return _empty_cache()
    try:
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
        if data.get("version") != CACHE_VERSION:
            return _empty_cache()
        if data.get("schema") != ANALYSIS_SCHEMA_REV:
            # the analyzer changed underneath the cached facts: every entry
            # is stale regardless of content hash — full miss
            return _empty_cache()
        if not isinstance(data.get("files"), dict):
            return _empty_cache()
        return data
    except (OSError, ValueError):
        # a corrupt cache is a cache miss, never an error
        return _empty_cache()


def save_cache(path: str, data: dict) -> None:
    tmp = path + ".tmp"
    try:
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(data, fh)
        os.replace(tmp, path)
    except OSError:
        pass  # read-only checkout: the cache is an optimization only


# ------------------------------------------------------------------ #
# the linked program: resolution + fixpoint propagation
# ------------------------------------------------------------------ #


@dataclass
class _Norm:
    """One normalized footprint atom with provenance."""

    kind: str  # "coll" | "dyn" | "cycle" | "trunc" | "either" | "loop"
    data: object = None
    chain: Tuple[Tuple[str, str, int], ...] = ()  # (path, qualname, line) hops

    def stripped(self):
        if self.kind in ("either", "loop") and self.data is not None:
            return (self.kind, self.data)
        return (self.kind, self.data)


def _strip(seq: Sequence[_Norm]) -> Tuple:
    return tuple(n.stripped() for n in seq)


def _has_ambiguity(seq: Sequence[_Norm]) -> bool:
    for n in seq:
        if n.kind in ("dyn", "cycle", "trunc"):
            return True
        if n.kind in ("either", "loop"):
            # data holds stripped tuples; scan them textually
            if _tuple_has_ambiguity(n.data):
                return True
    return False


def _tuple_has_ambiguity(data) -> bool:
    if isinstance(data, tuple):
        if data and data[0] in ("dyn", "cycle", "trunc"):
            return True
        return any(_tuple_has_ambiguity(d) for d in data)
    return False


@dataclass
class SyncReport:
    entry: FuncKey
    entry_line: int
    chain: Tuple[Tuple[str, str, int], ...]
    detail: str
    vis: str  # "naked" | "suppressed" | "cast"


@dataclass
class WaitReport:
    entry: FuncKey
    entry_line: int
    chain: Tuple[Tuple[str, str, int], ...]
    detail: str
    vis: str


@dataclass
class DonationInfo:
    """Why calling this function donates parameter ``param``."""

    param: int
    chain: Tuple[Tuple[str, str, int], ...]


class Program:
    """Everything the HT2xx rules consume: contexts, facts, effects, the
    resolved call graph, and the fixpoint-propagated summaries."""

    def __init__(
        self,
        contexts: dict,
        facts: dict,
        effects: dict,
        graph: CallGraph,
        absint_facts: Optional[dict] = None,
    ):
        self.contexts = contexts  # path -> LintContext
        self.facts = facts  # path -> FileFacts
        self.effects = effects  # FuncKey -> effect dict
        self.graph = graph
        self.absint_facts = absint_facts or {}  # path -> absint fact dict
        self._absint_view = None
        # per function: list aligned with effects["calls"] of Resolution
        self.resolved: Dict[FuncKey, List[Resolution]] = {}
        # fixpoint results
        self.returns_device: Dict[FuncKey, bool] = {}
        self.donates: Dict[FuncKey, Dict[int, DonationInfo]] = {}
        self.sync_exposed: Dict[FuncKey, Dict[Tuple, Tuple]] = {}
        self.wait_exposed: Dict[FuncKey, Dict[Tuple, Tuple]] = {}
        self.sync_reports: List[SyncReport] = []
        self.wait_reports: List[WaitReport] = []
        self._norm_memo: Dict[FuncKey, List[_Norm]] = {}
        self._link()
        self._propagate()

    # ---------------- linking ---------------- #

    def _link(self) -> None:
        for key, eff in self.effects.items():
            res = []
            for desc_json, _line, _dl in eff["calls"]:
                res.append(self.graph.resolve(key, CallDesc.from_json(desc_json)))
            self.resolved[key] = res

    def func(self, key: FuncKey):
        return self.graph.functions.get(key)

    @property
    def absint(self):
        """The linked abstract-interpretation view (HT3xx's input), built
        lazily on first access so HT2xx-only runs never pay for it."""
        if self._absint_view is None:
            from . import absint as _absint

            self._absint_view = _absint.link(self)
        return self._absint_view

    def is_public(self, key: FuncKey) -> bool:
        fn = self.func(key)
        return fn is not None and fn.is_public

    # ---------------- fixpoint: returns_device ---------------- #

    def _propagate(self) -> None:
        rd = {k: bool(e["returns_device"]) for k, e in self.effects.items()}
        changed = True
        while changed:
            changed = False
            for key, eff in self.effects.items():
                if rd[key]:
                    continue
                for cid in eff["returns_calls"]:
                    r = self.resolved[key][cid]
                    if r.kind == "resolved" and rd.get(r.target, False):
                        fn = self.func(r.target)
                        if fn is not None and fn.name in MATERIALIZERS:
                            continue  # materializers return host data
                        rd[key] = True
                        changed = True
                        break
        self.returns_device = rd
        self._propagate_donates()
        self._propagate_sinks()
        self._propagate_waits()

    # ---------------- fixpoint: donated params ---------------- #

    def _propagate_donates(self) -> None:
        don: Dict[FuncKey, Dict[int, DonationInfo]] = {}
        for key, eff in self.effects.items():
            own: Dict[int, DonationInfo] = {}
            for p, line in eff["direct_donated_params"]:
                own[p] = DonationInfo(p, ((key[0], key[1], line),))
            don[key] = own
        changed = True
        while changed:
            changed = False
            for key, eff in self.effects.items():
                fn = self.func(key)
                if fn is None:
                    continue
                params = list(fn.params)
                for cid, (desc_json, line, _dl) in enumerate(eff["calls"]):
                    r = self.resolved[key][cid]
                    if r.kind != "resolved":
                        continue
                    callee_don = don.get(r.target, {})
                    positions = set(callee_don) | set(r.donates_override or ())
                    if not positions:
                        continue
                    args = desc_json.get("args", [])
                    for p in positions:
                        if p >= len(args) or args[p] is None:
                            continue
                        if args[p] in params:
                            my_p = params.index(args[p])
                            if my_p not in don[key]:
                                inner = callee_don.get(p)
                                chain = ((key[0], key[1], line),) + (
                                    inner.chain if inner else ()
                                )
                                don[key][my_p] = DonationInfo(my_p, chain[:_CHAIN_CAP])
                                changed = True
        self.donates = don

    # ---------------- propagation: host syncs ---------------- #

    def _sync_barrier(self, key: FuncKey) -> bool:
        path, qual = key
        if module_matches(path, HOST_SANCTIONED_MODULES):
            return True
        if any(part in HOST_SANCTIONED_DEFS for part in qual.split(".")):
            return True
        return self.is_public(key)  # consumed (and reported) at the boundary

    def _propagate_sinks(self) -> None:
        # sink id -> (vis, chain); chains kept shortest
        exposed: Dict[FuncKey, Dict[Tuple, Tuple]] = {}
        for key, eff in self.effects.items():
            own: Dict[Tuple, Tuple] = {}
            for atom in _iter_atoms(eff["footprint"]):
                if atom[0] == "sink":
                    detail, line, vis = atom[1], atom[2], atom[3]
                    sid = (key[0], key[1], line, detail, vis)
                    own[sid] = ((key[0], key[1], line),)
            exposed[key] = own
        changed = True
        while changed:
            changed = False
            for key, eff in self.effects.items():
                for cid, (desc_json, line, _dl) in enumerate(eff["calls"]):
                    r = self.resolved[key][cid]
                    if r.kind != "resolved" or self._sync_barrier(r.target):
                        continue
                    for sid, chain in exposed.get(r.target, {}).items():
                        cand = ((key[0], key[1], line),) + chain
                        cand = cand[:_CHAIN_CAP]
                        cur = exposed[key].get(sid)
                        if cur is None or len(cand) < len(cur):
                            exposed[key][sid] = cand
                            changed = True
        self.sync_exposed = exposed

        # reports: cast sinks at their containing function; naked/suppressed
        # sinks at public entries >= 1 hop away.  One report per
        # (entry, sink) — a second call path to the same sink is noise.
        seen: set = set()
        for key, eff in self.effects.items():
            for atom in _iter_atoms(eff["footprint"]):
                if atom[0] != "cast":
                    continue
                detail, line, cid = atom[1], atom[2], atom[3]
                r = self.resolved[key][cid]
                if r.kind == "resolved" and self.returns_device.get(r.target, False):
                    tf = self.func(r.target)
                    tline = tf.line if tf is not None else 1
                    self.sync_reports.append(
                        SyncReport(
                            entry=key,
                            entry_line=line,
                            chain=(
                                (key[0], key[1], line),
                                (r.target[0], r.target[1], tline),
                            ),
                            detail=detail,
                            vis="cast",
                        )
                    )
            if not self.is_public(key):
                continue
            for cid, (desc_json, line, _dl) in enumerate(eff["calls"]):
                r = self.resolved[key][cid]
                if r.kind != "resolved" or self._sync_barrier(r.target):
                    continue
                for sid, chain in self.sync_exposed.get(r.target, {}).items():
                    if (key, sid) in seen:
                        continue
                    seen.add((key, sid))
                    _p, _q, _sline, detail, vis = sid
                    self.sync_reports.append(
                        SyncReport(
                            entry=key,
                            entry_line=line,
                            chain=((key[0], key[1], line),) + chain,
                            detail=detail,
                            vis=vis,
                        )
                    )

    # ---------------- propagation: blocking waits ---------------- #

    def _wait_barrier(self, key: FuncKey) -> bool:
        path, qual = key
        if module_matches(path, WAIT_SANCTIONED_MODULES):
            return True
        if any(part in HOST_SANCTIONED_DEFS for part in qual.split(".")):
            return True  # the materialization API blocks by design
        return self.is_public(key)

    def _propagate_waits(self) -> None:
        exposed: Dict[FuncKey, Dict[Tuple, Tuple]] = {}
        for key, eff in self.effects.items():
            own: Dict[Tuple, Tuple] = {}
            for atom in _iter_atoms_outside_dlscope(eff["footprint"]):
                if atom[0] == "wait":
                    detail, line, vis = atom[1], atom[2], atom[3]
                    sid = (key[0], key[1], line, detail, vis)
                    own[sid] = ((key[0], key[1], line),)
            exposed[key] = own
        changed = True
        while changed:
            changed = False
            for key, eff in self.effects.items():
                for cid, (desc_json, line, under_dl) in enumerate(eff["calls"]):
                    if under_dl:
                        continue  # the caller armed a deadline around this call
                    r = self.resolved[key][cid]
                    if r.kind != "resolved" or self._wait_barrier(r.target):
                        continue
                    for sid, chain in exposed.get(r.target, {}).items():
                        cand = ((key[0], key[1], line),) + chain
                        cand = cand[:_CHAIN_CAP]
                        cur = exposed[key].get(sid)
                        if cur is None or len(cand) < len(cur):
                            exposed[key][sid] = cand
                            changed = True
        self.wait_exposed = exposed
        seen: set = set()
        for key, eff in self.effects.items():
            if not self.is_public(key):
                continue
            for cid, (desc_json, line, under_dl) in enumerate(eff["calls"]):
                if under_dl:
                    continue
                r = self.resolved[key][cid]
                if r.kind != "resolved" or self._wait_barrier(r.target):
                    continue
                for sid, chain in self.wait_exposed.get(r.target, {}).items():
                    if (key, sid) in seen:
                        continue
                    seen.add((key, sid))
                    _p, _q, _sline, detail, vis = sid
                    self.wait_reports.append(
                        WaitReport(
                            entry=key,
                            entry_line=line,
                            chain=((key[0], key[1], line),) + chain,
                            detail=detail,
                            vis=vis,
                        )
                    )

    # ---------------- ordered footprint expansion (HT201) ---------------- #

    def norm_function(self, key: FuncKey) -> List[_Norm]:
        memo = self._norm_memo.get(key)
        if memo is not None:
            return memo
        out, complete = self._norm_atoms(key, self.effects[key]["footprint"], (key,))
        if complete:
            self._norm_memo[key] = out
        return out

    def norm_arm(self, key: FuncKey, atoms: Sequence[list]) -> List[_Norm]:
        out, _complete = self._norm_atoms(key, atoms, (key,))
        return out

    def _norm_atoms(
        self, key: FuncKey, atoms: Sequence[list], stack: Tuple[FuncKey, ...]
    ) -> Tuple[List[_Norm], bool]:
        out: List[_Norm] = []
        complete = True
        for atom in atoms:
            if len(out) > _EXPAND_CAP:
                out.append(_Norm("trunc"))
                return out, complete
            kind = atom[0]
            if kind == "coll":
                out.append(
                    _Norm("coll", atom[1], chain=((key[0], key[1], atom[2]),))
                )
            elif kind == "call":
                cid, line = atom[1], atom[2]
                r = self.resolved[key][cid]
                if r.kind == "external":
                    continue
                if r.kind == "unresolved":
                    if not r.benign:
                        out.append(
                            _Norm("dyn", None, chain=((key[0], key[1], line),))
                        )
                    continue
                target = r.target
                if target in stack:
                    out.append(
                        _Norm("cycle", None, chain=((key[0], key[1], line),))
                    )
                    complete = False
                    continue
                if len(stack) >= 12:
                    out.append(
                        _Norm("trunc", None, chain=((key[0], key[1], line),))
                    )
                    complete = False
                    continue
                memo = self._norm_memo.get(target)
                if memo is None:
                    inner, inner_complete = self._norm_atoms(
                        target,
                        self.effects.get(target, {"footprint": []})["footprint"],
                        stack + (target,),
                    )
                    if inner_complete:
                        self._norm_memo[target] = inner
                    else:
                        complete = False
                    memo = inner
                hop = (key[0], key[1], line)
                for n in memo:
                    out.append(
                        _Norm(n.kind, n.data, chain=((hop,) + n.chain)[:_CHAIN_CAP])
                    )
                    if len(out) > _EXPAND_CAP:
                        out.append(_Norm("trunc"))
                        return out, complete
            elif kind == "cast" or kind == "sink" or kind == "wait":
                continue  # not collective traffic
            elif kind == "branch":
                a, ca = self._norm_atoms(key, atom[2], stack)
                b, cb = self._norm_atoms(key, atom[3], stack)
                complete = complete and ca and cb
                if _strip(a) == _strip(b):
                    out.extend(a)
                else:
                    out.append(
                        _Norm(
                            "either",
                            (_strip(a), _strip(b)),
                            chain=((key[0], key[1], atom[1]),),
                        )
                    )
            elif kind == "rankbranch":
                # a nested rank-conditional gets its own HT201 finding at its
                # own site; for the surrounding comparison treat it like a
                # plain branch
                a, ca = self._norm_atoms(key, atom[3], stack)
                b, cb = self._norm_atoms(key, atom[4], stack)
                complete = complete and ca and cb
                if _strip(a) == _strip(b):
                    out.extend(a)
                else:
                    out.append(
                        _Norm(
                            "either",
                            (_strip(a), _strip(b)),
                            chain=((key[0], key[1], atom[2]),),
                        )
                    )
            elif kind == "loop":
                body, cb = self._norm_atoms(key, atom[2], stack)
                complete = complete and cb
                if body:
                    out.append(
                        _Norm(
                            "loop", _strip(body), chain=((key[0], key[1], atom[1]),)
                        )
                    )
            elif kind == "dlscope":
                body, cb = self._norm_atoms(key, atom[2], stack)
                complete = complete and cb
                out.extend(body)
        return out, complete

    # ---------------- finding helper (suppression-aware) ---------------- #

    def is_suppressed(self, code: str, path: str, line: int) -> bool:
        ctx = self.contexts.get(path)
        return ctx is not None and ctx.is_suppressed(code, line)


def _iter_atoms(atoms):
    """Every atom in a footprint, including branch/loop/dlscope bodies."""
    for atom in atoms:
        yield atom
        kind = atom[0]
        if kind == "branch":
            yield from _iter_atoms(atom[2])
            yield from _iter_atoms(atom[3])
        elif kind == "rankbranch":
            yield from _iter_atoms(atom[3])
            yield from _iter_atoms(atom[4])
        elif kind in ("loop", "dlscope"):
            yield from _iter_atoms(atom[2])


def _iter_atoms_outside_dlscope(atoms):
    for atom in atoms:
        yield atom
        kind = atom[0]
        if kind == "branch":
            yield from _iter_atoms_outside_dlscope(atom[2])
            yield from _iter_atoms_outside_dlscope(atom[3])
        elif kind == "rankbranch":
            yield from _iter_atoms_outside_dlscope(atom[3])
            yield from _iter_atoms_outside_dlscope(atom[4])
        elif kind == "loop":
            yield from _iter_atoms_outside_dlscope(atom[2])
        # dlscope bodies are deliberately NOT descended into


# ------------------------------------------------------------------ #
# program assembly (the entry point framework.lint_paths uses)
# ------------------------------------------------------------------ #


def build_program(contexts: dict, cache_path: Optional[str] = None) -> Program:
    """contexts: path -> LintContext (syntax-clean files only)."""
    from . import absint as _absint  # lazy: absint imports our vocabulary

    cache = load_cache(cache_path)
    files = cache["files"]
    facts: Dict[str, object] = {}
    effects: Dict[FuncKey, dict] = {}
    absint_facts: Dict[str, dict] = {}
    dirty = False
    for path, ctx in contexts.items():
        h = file_hash(ctx.source)
        ent = files.get(ctx.path)
        # an entry missing the absint record predates the schema field's
        # introduction (or was hand-edited): treat as a miss, like any
        # other stale-schema artifact
        if ent is not None and ent.get("hash") == h and "absint" in ent:
            ff = FileFacts.from_json(ent["facts"])
            eff = ent["effects"]
            ai = ent["absint"]
        else:
            ff = extract_structure(ctx)
            eff = extract_effects(ctx)
            ai = _absint.extract_absint(ctx)
            files[ctx.path] = {
                "hash": h,
                "facts": ff.to_json(),
                "effects": eff,
                "absint": ai,
            }
            dirty = True
        facts[ctx.path] = ff
        absint_facts[ctx.path] = ai
        for qual, e in eff.items():
            effects[(ctx.path, qual)] = e
    # evict only entries whose file is GONE from disk: a narrow run (one
    # file, one subdirectory) must not wipe the repo-wide cache for
    # everything outside its scope
    linted = {ctx.path for ctx in contexts.values()}
    stale = [p for p in files if p not in linted and not os.path.exists(p)]
    for p in stale:
        del files[p]
        dirty = True
    if cache_path and dirty:
        save_cache(cache_path, cache)
    graph = CallGraph(facts)
    return Program(contexts, facts, effects, graph, absint_facts=absint_facts)
