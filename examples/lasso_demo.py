"""Lasso sparse-recovery demo (reference: ``examples/lasso``)."""

import numpy as np

import heat_tpu as ht


def main() -> None:
    rng = np.random.default_rng(0)
    n, d = 2048, 16
    X = rng.normal(size=(n, d)).astype(np.float32)
    w_true = np.zeros(d, dtype=np.float32)
    w_true[[1, 4, 9]] = [2.0, -3.0, 1.5]
    y = X @ w_true + 0.01 * rng.normal(size=n).astype(np.float32)

    hX = ht.array(X, split=0)
    hy = ht.array(y.reshape(-1, 1), split=0)
    lasso = ht.regression.Lasso(lam=0.01, max_iter=200)
    lasso.fit(hX, hy)
    print("true nonzeros :", np.nonzero(w_true)[0].tolist())
    coef = lasso.coef_.numpy().ravel()
    print("found nonzeros:", np.nonzero(np.abs(coef) > 0.05)[0].tolist())
    print("coefficients  :", np.round(coef, 2).tolist())


if __name__ == "__main__":
    main()
