"""Parallel I/O (reference: ``heat/core/io.py``, SURVEY §5.4).

``save``/``load`` dispatch by extension.  The reference reads/writes each
rank's hyperslab through parallel HDF5/netCDF; here each process reads its
byte range via the same ``comm.chunk`` math (single-controller: one process
reads, the device_put shards).  Checkpoint/resume for arrays is exactly
``save``/``load`` (SURVEY §5.4: array-level checkpointing, no separate
subsystem).
"""

from __future__ import annotations

import io as _pyio
import json
import os
import warnings
import zlib
from typing import List, Optional

import numpy as np

from . import devices, factories, types
from .communication import sanitize_comm
from .dndarray import DNDarray

# stdlib-only modules; safe to import from the innermost write paths
from ..utils import faults as _faults
from ..utils import flightrec as _flightrec
from ..utils import memledger as _memledger
from ..utils import telemetry as _telemetry

__all__ = [
    "load",
    "load_csv",
    "load_hdf5",
    "load_netcdf",
    "load_npy_from_path",
    "save",
    "save_csv",
    "save_hdf5",
    "save_zarr",
    "load_zarr",
    "save_netcdf",
    "supports_hdf5",
    "supports_netcdf",
    "load_checkpoint",
    "save_checkpoint",
    "save_array_checkpoint",
    "load_array_checkpoint",
    "CheckpointCorruptionError",
]


class CheckpointCorruptionError(RuntimeError):
    """A checkpoint failed integrity verification: checksum mismatch,
    missing/truncated chunk files, or unreadable metadata."""


# retry policy for transient checkpoint-I/O faults (flaky disk, injected
# TransientFault); tests shrink the delays — the schedule itself is unit
# tested against a fake clock in tests/test_faults.py
IO_RETRY = {"retries": 4, "base_delay": 0.02, "max_delay": 0.5, "jitter": 0.5}


def _retry(fn, site: str, **over):
    return _faults.call_with_retries(fn, site, **{**IO_RETRY, **over})


def _fsync_dir(path: str) -> None:
    """fsync a DIRECTORY so its entries (new files, renames) are durable —
    file fsync alone does not persist the directory entry pointing at it."""
    fd = os.open(path, os.O_RDONLY)
    try:
        _faults.fire("io.fsync", path=path)
        os.fsync(fd)
        _telemetry.counter_inc("io.fsync.calls")
    finally:
        os.close(fd)


def _durable_write(path: str, payload: bytes) -> None:
    """Write ``payload`` to ``path`` and fsync the file handle, retrying the
    whole write on transient faults (a partially-written attempt is simply
    overwritten by the next one).  Fault sites: ``io.write`` (after the
    bytes hit the file, before fsync — the corrupt mode flips a byte of the
    on-disk file there) and ``io.fsync``.  Telemetry: successful writes
    count under ``io.bytes_written``/``io.fsync.calls`` (retry attempts
    already count as ``retry.io.write`` in the faults layer)."""

    def attempt():
        with open(path, "wb") as fh:
            fh.write(payload)
            fh.flush()
            _faults.fire("io.write", path=path)
            _faults.fire("io.fsync", path=path)
            os.fsync(fh.fileno())

    _retry(attempt, "io.write")
    _telemetry.counter_inc("io.bytes_written", len(payload))
    _telemetry.counter_inc("io.fsync.calls")


def _read_file(path: str, site: str = "io.read") -> bytes:
    """Read a whole file with transient-fault retry (missing files are NOT
    retried — absence is a layout error, not a transient condition)."""

    def attempt():
        _faults.fire(site, path=path)
        with open(path, "rb") as fh:
            return fh.read()

    return _retry(
        attempt, site, retry_if=lambda e: not isinstance(e, FileNotFoundError)
    )

# diagnostics: counts individual hyperslab writes so tests can prove writes
# are chunked (peak host memory = one shard) rather than a full gather
_CHUNK_WRITES = {"count": 0, "max_bytes": 0}


def _note_chunk(nbytes: int) -> None:
    _CHUNK_WRITES["count"] += 1
    _CHUNK_WRITES["max_bytes"] = max(_CHUNK_WRITES["max_bytes"], int(nbytes))


def _proc_info(data) -> tuple:
    """(n_processes, process_index) — (1, 0) for plain arrays/single-controller."""
    import jax

    if isinstance(data, DNDarray):
        return data.comm.n_processes, data.comm.rank
    return jax.process_count(), jax.process_index()


# a cross-process barrier in a save path should fail loudly, not hang the
# world when a peer is dead: every io-layer sync_global_devices runs under
# this deadline (the elastic-runtime contract, PR 5) unless the caller
# already armed a tighter one
_IO_SYNC_DEADLINE = 600.0


def _bounded_sync(tag: str) -> None:
    """``sync_global_devices`` under a collective deadline: raises
    ``CollectiveTimeoutError`` (after a stack dump) instead of blocking
    forever on a dead peer.  An already-armed caller deadline governs (its
    remaining budget is re-armed, never loosened); otherwise the generous
    io default applies."""
    from jax.experimental import multihost_utils

    from ..utils import health as _health

    active = _health.active_deadline()
    budget = active.remaining() if active is not None else _IO_SYNC_DEADLINE
    with _health.deadline(budget):
        _health.guard_blocking(
            lambda: multihost_utils.sync_global_devices(tag), f"io.sync:{tag}"
        )


def _token_ring_write(data, tag: str, body) -> None:
    """Rank-ordered single-writer-at-a-time file writes for multi-process
    runs — the reference's token-ring fallback when parallel HDF5 is absent
    (SURVEY §5.4), generalized to every serial-writer format.

    ``body(first, slabs)`` writes this process's part: ``first`` marks the
    writer that must create/truncate the file; ``slabs`` iterates
    ``(global_slices, ndarray)``.  Split data: each process writes only its
    addressable hyperslabs, in rank order (ranks own ascending row ranges,
    so appends land in order).  Replicated data: written once by rank 0,
    prefetched on EVERY rank first (the fetch may be a collective;
    rank-0-only collectives would deadlock the barrier).  A failing writer
    still attends every remaining barrier, then re-raises — otherwise the
    surviving ranks hang at their next sync instead of surfacing the error.
    """
    nproc, rank = _proc_info(data)
    only_rank0 = not (
        isinstance(data, DNDarray) and data.split is not None and data.comm.is_distributed()
    )
    if nproc == 1:
        body(True, _iter_hyperslabs(data))
        return
    slabs = None
    if only_rank0:
        arr = data.numpy() if isinstance(data, DNDarray) else np.asarray(data)
        _note_chunk(arr.nbytes)
        slabs = [(tuple(slice(0, s) for s in arr.shape), arr)]
    failure = None
    for r in range(nproc):
        if failure is None and r == rank and (r == 0 or not only_rank0):
            try:
                body(r == 0, slabs if only_rank0 else _iter_hyperslabs(data))
            except Exception as e:  # noqa: BLE001 — re-raised after the ring
                failure = e
        _bounded_sync(f"token_ring:{tag}:{r}")
    if failure is not None:
        raise failure


def _iter_hyperslabs(x: DNDarray):
    """Yield ``(global_slices, chunk_ndarray)`` one shard at a time.

    The scalable-write core (reference: per-rank hyperslab writes in
    ``heat/core/io.py::save_hdf5``; SURVEY §5.4): each shard is fetched to
    host individually via ``addressable_shards`` — the full array is NEVER
    gathered, so checkpointable size is bounded by disk, not host RAM.
    Ragged pad rows are clipped to the logical extent.
    """
    if not isinstance(x, DNDarray):
        arr = np.asarray(x)
        _note_chunk(arr.nbytes)
        yield tuple(slice(0, s) for s in arr.shape), arr
        return
    split = x.split
    if split is None or not x.comm.is_distributed():
        arr = x.numpy()
        _note_chunk(arr.nbytes)
        yield tuple(slice(0, s) for s in arr.shape), arr
        return
    n = x.shape[split]
    seen = set()
    shards = sorted(
        x._parray.addressable_shards, key=lambda s: s.index[split].start or 0
    )

    # overlap, ONE shard ahead: start shard k+1's device→host copy while
    # shard k is being written to disk, so np.asarray finds the data
    # resident without a blocking fetch per chunk.  Never prefetch more —
    # the whole point of hyperslab iteration is that peak host memory
    # stays at ~one chunk, not the full array.
    def _prefetch(i):
        if i < len(shards):
            try:
                shards[i].data.copy_to_host_async()
            except (AttributeError, RuntimeError):
                pass

    _prefetch(0)
    for si, sh in enumerate(shards):
        _prefetch(si + 1)
        idx = sh.index
        start = idx[split].start or 0
        stop = idx[split].stop
        stop = n if stop is None else min(stop, n)
        if start >= stop or start in seen:
            continue  # pad-only or replicated duplicate shard
        seen.add(start)
        data = np.asarray(sh.data)
        valid = stop - start
        if data.shape[split] != valid:
            clip = [slice(None)] * x.ndim
            clip[split] = slice(0, valid)
            data = data[tuple(clip)]
        out = tuple(
            slice(start, stop) if i == split else slice(0, s)
            for i, s in enumerate(x.shape)
        )
        _note_chunk(data.nbytes)
        yield out, data


def supports_hdf5() -> bool:
    try:
        import h5py  # noqa: F401

        return True
    except ImportError:
        return False


def supports_netcdf() -> bool:
    """netCDF-4 is supported through the netCDF4 library or, failing that,
    through h5py (netCDF-4 files are HDF5 containers; classic CDF-1/2 files
    still need the netCDF4 library)."""
    try:
        import netCDF4  # noqa: F401

        return True
    except ImportError:
        return supports_hdf5()


# ---------------------------------------------------------------------- #
# HDF5
# ---------------------------------------------------------------------- #
def _read_hyperslab(reader, gshape, dtype, split, device, comm) -> DNDarray:
    """Assemble a split DNDarray where each PROCESS reads only its own
    hyperslab via ``reader(slices) -> ndarray`` (the reference's parallel
    read; shared by the HDF5 and netCDF loaders)."""
    import jax

    if split is None or comm.n_processes == 1:
        data = np.asarray(reader(tuple(slice(0, s) for s in gshape)))
        return factories.array(data, dtype=dtype, split=split, device=device, comm=comm)
    rank = comm.rank
    n = gshape[split]
    # the process's slab must match its devices' slices of the CANONICAL
    # padded ceil-div grid (make_array_from_process_local_data maps local
    # data onto the process's addressable slice extents — a ceil-over-
    # n_processes slab desynchronizes from the per-DEVICE grid whenever the
    # extent is ragged)
    cd = comm.padded_extent(n) // comm.size  # rows per device (padded grid)
    mesh_devs = list(comm.mesh.devices.ravel())
    idxs = [i for i, d in enumerate(mesh_devs) if d.process_index == rank]
    assert idxs == list(range(min(idxs), max(idxs) + 1)), (
        "mesh places this process's devices non-contiguously along the axis"
    )
    lo_pad, hi_pad = min(idxs) * cd, (max(idxs) + 1) * cd
    lo, hi = min(lo_pad, n), min(hi_pad, n)
    slices = tuple(
        slice(lo, hi) if i == split else slice(0, s) for i, s in enumerate(gshape)
    )
    np_dt = types.canonical_heat_type(dtype).np_dtype()
    data = np.asarray(reader(slices)).astype(np_dt)
    local_rows = hi_pad - lo_pad
    if data.shape[split] != local_rows:  # trailing pad rows of the grid
        widths = [(0, 0)] * len(gshape)
        widths[split] = (0, local_rows - data.shape[split])
        data = np.pad(data, widths)
    pshape = tuple(
        comm.padded_extent(n) if i == split else s for i, s in enumerate(gshape)
    )
    sharding = comm.sharding(len(gshape), split)
    jarr = jax.make_array_from_process_local_data(sharding, data, pshape)
    dev = devices.sanitize_device(device)
    return DNDarray(jarr, gshape, types.canonical_heat_type(dtype), split, dev, comm, True)


def load_hdf5(path: str, dataset: str, dtype=types.float32, load_fraction: float = 1.0,
              split: Optional[int] = None, device=None, comm=None) -> DNDarray:
    """Load an HDF5 dataset; with ``split``, each process reads only its
    hyperslab (the reference's parallel read)."""
    import h5py

    comm = sanitize_comm(comm)
    with h5py.File(path, "r") as f:
        ds = f[dataset]
        gshape = tuple(ds.shape)
        if load_fraction < 1.0 and split == 0:
            n = int(gshape[0] * load_fraction)
            gshape = (n,) + gshape[1:]
        return _read_hyperslab(lambda s: ds[s], gshape, dtype, split, device, comm)


def save_hdf5(data: DNDarray, path: str, dataset: str, mode: str = "w", **kwargs) -> None:
    """Write a DNDarray to HDF5 shard-by-shard.

    The dataset is created at full shape, then each shard's hyperslab is
    fetched and written individually (``_iter_hyperslabs``) — peak host
    memory is ONE shard, so checkpointable size is disk-bound, matching the
    reference's per-rank parallel write (``heat/core/io.py::save_hdf5``).
    """
    import h5py

    if isinstance(data, DNDarray):
        shape = data.shape
        np_dtype = data.dtype.np_dtype()
    else:
        data = np.asarray(data)
        shape, np_dtype = data.shape, data.dtype
    kwargs.setdefault("dtype", np_dtype)  # callers may override (cast-on-write)

    def body(first, slabs):
        with h5py.File(path, mode if first else "a") as f:
            if first:
                if dataset in f:
                    del f[dataset]
                ds = f.create_dataset(dataset, shape=shape, **kwargs)
            else:
                ds = f[dataset]
            for slices, chunk in slabs:
                ds[slices] = chunk

    _token_ring_write(data, f"h5:{dataset}", body)


# ---------------------------------------------------------------------- #
# CSV
# ---------------------------------------------------------------------- #
def load_csv(path: str, header_lines: int = 0, sep: str = ",", dtype=types.float32,
             encoding: str = "utf-8", split: Optional[int] = None, device=None, comm=None) -> DNDarray:
    """Parallel CSV ingest (reference: byte-range split across ranks with line
    fixup).  The native C++ engine (``heat_tpu._native``) runs the same
    byte-range strategy across threads — mmap, parallel line indexing,
    ``from_chars`` parsing; numpy ``genfromtxt`` is the fallback."""
    from .. import _native

    parsed = None
    if encoding.replace("-", "").lower() in ("utf8", "ascii"):
        parsed = _native.csv_parse(path, sep=sep, skiprows=header_lines)
    if parsed is not None:
        # genfromtxt shape rules: multi-column → 2-D, single column → 1-D,
        # single value → 0-d scalar
        if parsed.shape == (1, 1):
            data = parsed.reshape(())
        elif parsed.shape[1] > 1:
            data = parsed
        else:
            data = parsed.reshape(-1)
    else:
        data = np.genfromtxt(path, delimiter=sep, skip_header=header_lines, encoding=encoding)
        if data.ndim == 1:
            # single data row parses 1-D; sniff the first DATA line to decide
            with open(path, encoding=encoding) as f:
                for _ in range(header_lines):
                    f.readline()
                first_data_line = f.readline()
            if sep in first_data_line:
                data = data.reshape(-1, len(first_data_line.rstrip("\n").split(sep)))
    return factories.array(data, dtype=dtype, split=split, device=device, comm=comm)


def save_csv(data: DNDarray, path: str, header_lines: Optional[List[str]] = None,
             sep: str = ",", decimals: int = -1, truncate: bool = True) -> None:
    from .. import _native

    # split=0 streaming path: one shard of rows at a time (reference: each
    # rank writes its own row range) — no full host gather; multi-process
    # writers take turns in rank order (ranks own ascending row ranges)
    if isinstance(data, DNDarray) and data.split == 0 and data.comm.is_distributed():
        fmt = f"%.{decimals}f" if decimals >= 0 else "%s"

        def body(first, slabs):
            with open(path, "w" if first else "a", encoding="utf-8") as fh:
                if first and header_lines:
                    fh.write("\n".join(header_lines) + "\n")
                for _, chunk in slabs:
                    block = chunk.reshape(-1, 1) if chunk.ndim == 1 else chunk
                    np.savetxt(fh, block, delimiter=sep, fmt=fmt)

        _token_ring_write(data, "csv", body)
        return

    arr = data.numpy() if isinstance(data, DNDarray) else np.asarray(data)
    if arr.ndim == 1:
        arr = arr.reshape(-1, 1)
    if (
        not header_lines
        and np.issubdtype(arr.dtype, np.floating)
        and _native.csv_write(
            path, arr, sep=sep, decimals=decimals,
            float32_repr=(arr.dtype == np.float32),
        )
    ):
        return
    fmt = f"%.{decimals}f" if decimals >= 0 else "%s"
    header = "\n".join(header_lines) if header_lines else ""
    np.savetxt(path, arr, delimiter=sep, fmt=fmt, header=header, comments="")


# ---------------------------------------------------------------------- #
# NPY
# ---------------------------------------------------------------------- #
def load_npy_from_path(path: str, dtype=types.float32, split: int = 0, device=None, comm=None) -> DNDarray:
    """Load and concatenate all .npy files in a directory (reference API)."""
    if os.path.isdir(path):
        files = sorted(f for f in os.listdir(path) if f.endswith(".npy"))
        if not files:
            raise ValueError(f"no .npy files under {path}")
        arrays = [np.load(os.path.join(path, f), mmap_mode="r") for f in files]
        data = np.concatenate(arrays, axis=0)
    else:
        data = np.load(path, mmap_mode="r")
    return factories.array(np.asarray(data), dtype=dtype, split=split, device=device, comm=comm)


# ---------------------------------------------------------------------- #
# netCDF (reference: heat/core/io.py::load_netcdf/save_netcdf)
# ---------------------------------------------------------------------- #
def load_netcdf(path: str, variable: str, dtype=types.float32, split: Optional[int] = None,
                device=None, comm=None) -> DNDarray:
    """Load a variable from a netCDF file, hyperslab-parallel like
    :func:`load_hdf5`.

    Uses the netCDF4 library when present; otherwise reads netCDF-4 files
    through h5py (netCDF-4 data files ARE HDF5 containers).  Classic-format
    (CDF-1/2, magic ``CDF\\x01``/``CDF\\x02``) files require the netCDF4
    library.
    """
    try:
        import netCDF4  # noqa: F401
    except ImportError:
        with open(path, "rb") as fh:
            magic = fh.read(4)
        if magic[:3] == b"CDF":
            raise RuntimeError(
                "classic-format netCDF (CDF-1/2) needs the netCDF4 library, "
                "which is not available; re-save as netCDF-4/HDF5"
            )
        return load_hdf5(path, variable, dtype=dtype, split=split, device=device, comm=comm)
    import netCDF4

    comm = sanitize_comm(comm)
    with netCDF4.Dataset(path, "r") as f:
        var = f.variables[variable]
        gshape = tuple(var.shape)
        return _read_hyperslab(lambda s: var[s], gshape, dtype, split, device, comm)


def save_netcdf(data: DNDarray, path: str, variable: str, mode: str = "w",
                dimension_names=None, **kwargs) -> None:
    """Write a DNDarray as a netCDF variable, shard-by-shard hyperslabs.

    With netCDF4 available this writes through it; otherwise an HDF5 file
    with attached dimension scales is produced via h5py — readable by the
    netCDF4 library (netCDF-4 files are HDF5 files with dimension scales).
    Writes stream one shard at a time (``_iter_hyperslabs``) — no full host
    gather.
    """
    if isinstance(data, DNDarray):
        shape = data.shape
        np_dtype = data.dtype.np_dtype()
        ndim = data.ndim
    else:
        data = np.asarray(data)
        shape, np_dtype, ndim = data.shape, data.dtype, data.ndim
    if dimension_names is None:
        dimension_names = [f"{variable}_dim{i}" for i in range(ndim)]
    elif len(dimension_names) != ndim:
        raise ValueError(
            f"need {ndim} dimension names, got {len(dimension_names)}"
        )
    if mode not in ("w", "a", "r+"):
        raise ValueError(f"invalid save mode {mode!r}; use 'w', 'a' or 'r+'")
    # 'a' on a nonexistent file creates it on both backends (h5py would,
    # netCDF4 would not — normalize so code works regardless of backend)
    if mode in ("a", "r+") and not os.path.exists(path):
        mode = "w"

    def _check_existing(eshape, dt):
        # netCDF cannot delete variables: same-shape/dtype re-saves overwrite
        # in place; any shape or dtype change raises (both backends)
        if tuple(eshape) != tuple(shape) or np.dtype(dt) != np_dtype:
            raise ValueError(
                f"variable {variable!r} exists with shape {tuple(eshape)} dtype {dt}, "
                f"cannot re-save with shape {tuple(shape)} dtype {np_dtype}"
            )

    try:
        import netCDF4

        has_netcdf4 = True
    except ImportError:
        has_netcdf4 = False

    def body(first, slabs):
        eff_mode = mode if first else "a"
        if not has_netcdf4:
            import h5py

            with h5py.File(path, eff_mode) as f:
                if variable in f:
                    _check_existing(f[variable].shape, f[variable].dtype)
                    ds = f[variable]
                else:
                    kwargs.setdefault("dtype", np_dtype)
                    ds = f.create_dataset(variable, shape=shape, **kwargs)
                    for i, dname in enumerate(dimension_names):
                        if dname not in f:
                            scale = f.create_dataset(dname, data=np.arange(shape[i], dtype=np.float64))
                            scale.make_scale(dname)
                        ds.dims[i].attach_scale(f[dname])
                for slices, chunk in slabs:
                    ds[slices] = chunk
            return
        with netCDF4.Dataset(path, eff_mode) as f:
            if variable in f.variables:
                var = f.variables[variable]
                _check_existing(var.shape, var.dtype)
            else:
                for i, dname in enumerate(dimension_names):
                    if dname not in f.dimensions:
                        f.createDimension(dname, shape[i])
                var = f.createVariable(variable, np_dtype, tuple(dimension_names), **kwargs)
            for slices, chunk in slabs:
                var[slices] = chunk

    _token_ring_write(data, f"nc:{variable}", body)


# ---------------------------------------------------------------------- #
# zarr v2 (directory format, dependency-free)
# ---------------------------------------------------------------------- #
# The reference gained zarr support in recent versions (SURVEY §2.2 io
# row); the on-disk v2 layout is simple enough to write without the zarr
# package: a ``.zarray`` JSON descriptor + one raw C-order file per chunk
# named by dot-separated chunk indices, edge chunks stored at FULL nominal
# size padded with ``fill_value``.  That convention matches pad-and-mask
# sharding exactly — with the chunk extent set to the per-device padded
# chunk, each device shard IS one zarr chunk, so every process writes only
# its own chunk files (naturally parallel across the process seam; no
# token ring needed beyond the descriptor barrier).

def _zarr_dtype(np_dtype) -> str:
    s = np.dtype(np_dtype).str
    if s[1] == "V":  # ml_dtypes extension types (bfloat16 etc.)
        raise ValueError(
            f"dtype {np.dtype(np_dtype)} has no zarr v2 representation; "
            "astype(float32) before ht.save(..., '*.zarr')"
        )
    return s


def save_zarr(data: DNDarray, path: str) -> None:
    """Write ``data`` as a zarr v2 array directory (``path`` ends .zarr).

    Split data: the chunk grid along the split axis is the per-device
    padded chunk, each rank writes only its addressable shards' chunk
    files.  Replicated data: one chunk, written by rank 0.
    """
    import json

    if not isinstance(data, DNDarray):
        from . import factories

        data = factories.array(data)
    if data.ndim == 0:
        raise ValueError("zarr save requires ndim >= 1")
    split = data.split if data.comm.is_distributed() else None
    if split is not None:
        chunk_extent = data.comm.padded_extent(data.shape[split]) // data.comm.size
        chunks = [
            chunk_extent if i == split else s for i, s in enumerate(data.shape)
        ]
    else:
        chunks = list(data.shape)
    meta = {
        "zarr_format": 2,
        "shape": list(data.shape),
        "chunks": chunks,
        "dtype": _zarr_dtype(data.dtype.np_dtype()),
        "compressor": None,
        "fill_value": 0,
        "order": "C",
        "filters": None,
    }
    nproc, rank = _proc_info(data)
    if rank == 0:
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, ".zarray"), "w") as f:
            json.dump(meta, f)
    if nproc > 1:
        _bounded_sync("zarr:descriptor")
    np_dtype = data.dtype.np_dtype()
    if split is None:
        if rank == 0 or nproc == 1:
            arr = np.ascontiguousarray(data.numpy(), dtype=np_dtype)
            _note_chunk(arr.nbytes)
            name = ".".join("0" * data.ndim) if data.ndim else "0"
            arr.tofile(os.path.join(path, name))
        else:
            data.numpy()  # the fetch is collective: every rank attends
    else:
        c = chunks[split]
        for slices, chunk in _iter_hyperslabs(data):
            start = slices[split].start
            if chunk.shape[split] != c:  # edge chunk: pad to nominal size
                pad = [(0, 0)] * data.ndim
                pad[split] = (0, c - chunk.shape[split])
                chunk = np.pad(chunk, pad)
            idx = ["0"] * data.ndim
            idx[split] = str(start // c)
            np.ascontiguousarray(chunk, dtype=np_dtype).tofile(
                os.path.join(path, ".".join(idx))
            )
    if nproc > 1:
        _bounded_sync("zarr:chunks-written")


def load_zarr(path: str, dtype=None, split: Optional[int] = None,
              device=None, comm=None) -> DNDarray:
    """Load a zarr v2 array directory (uncompressed, C-order — the layout
    :func:`save_zarr` writes and the zarr package's defaults-off case).
    Each process reads only the chunk files overlapping its hyperslab."""
    import json

    with open(os.path.join(path, ".zarray")) as f:
        meta = json.load(f)
    if meta.get("zarr_format") != 2:
        raise ValueError(f"unsupported zarr_format {meta.get('zarr_format')}")
    if meta.get("compressor") is not None or meta.get("filters"):
        raise ValueError("compressed/filtered zarr arrays are not supported "
                         "(save_zarr writes raw C-order chunks)")
    if meta.get("order", "C") != "C":
        raise ValueError("only C-order zarr arrays are supported")
    gshape = tuple(meta["shape"])
    chunks = tuple(meta["chunks"])
    np_dtype = np.dtype(meta["dtype"])
    # null is legal v2 metadata ("no fill"); read it as 0 so integer
    # stores don't crash np.full with a NoneType
    fill = meta.get("fill_value")
    if fill is None:
        fill = 0

    def reader(slices):
        out_shape = tuple(s.stop - s.start for s in slices)
        out = np.full(out_shape, fill, dtype=np_dtype)
        lo = [s.start // c for s, c in zip(slices, chunks)]
        hi = [(s.stop - 1) // c for s, c in zip(slices, chunks)]
        import itertools

        for idx in itertools.product(*(range(a, b + 1) for a, b in zip(lo, hi))):
            f = os.path.join(path, ".".join(str(i) for i in idx))
            if not os.path.exists(f):
                continue  # absent chunk = fill_value (zarr convention)
            chunk = np.fromfile(f, dtype=np_dtype).reshape(chunks)
            src, dst = [], []
            for d, (i, s, c) in enumerate(zip(idx, slices, chunks)):
                c0 = i * c
                a = max(s.start, c0)
                b = min(s.stop, c0 + c, gshape[d])
                src.append(slice(a - c0, b - c0))
                dst.append(slice(a - s.start, b - s.start))
            out[tuple(dst)] = chunk[tuple(src)]
        return out

    comm = sanitize_comm(comm)
    ht_dtype = dtype or types.canonical_heat_type(np_dtype)
    return _read_hyperslab(
        lambda slices: reader(slices).astype(ht_dtype.np_dtype()),
        gshape, ht_dtype, split, device, comm,
    )


# ---------------------------------------------------------------------- #
# dispatch
# ---------------------------------------------------------------------- #
def load(path: str, *args, **kwargs) -> DNDarray:
    """Extension-dispatching loader (reference ``ht.load``)."""
    ext = os.path.splitext(path)[-1].lower()
    if ext in (".h5", ".hdf5"):
        return load_hdf5(path, *args, **kwargs)
    if ext == ".csv":
        return load_csv(path, *args, **kwargs)
    if ext == ".npy":
        return load_npy_from_path(path, *args, **kwargs)
    if ext in (".nc", ".nc4", ".netcdf"):
        return load_netcdf(path, *args, **kwargs)
    if ext == ".zarr":
        return load_zarr(path, *args, **kwargs)
    raise ValueError(f"Unsupported file extension {ext}")


def save(data: DNDarray, path: str, *args, **kwargs) -> None:
    """Extension-dispatching saver (reference ``ht.save``)."""
    ext = os.path.splitext(path)[-1].lower()
    if ext in (".h5", ".hdf5"):
        return save_hdf5(data, path, *args, **kwargs)
    if ext == ".csv":
        return save_csv(data, path, *args, **kwargs)
    if ext == ".npy":
        if isinstance(data, DNDarray) and data.split is not None and data.comm.is_distributed():
            # stream shard hyperslabs into a memmapped .npy — no host
            # gather; multi-process writers append in rank order
            def body(first, slabs):
                mm = np.lib.format.open_memmap(
                    path,
                    mode="w+" if first else "r+",
                    dtype=data.dtype.np_dtype(),
                    shape=data.shape,
                )
                for slices, chunk in slabs:
                    mm[slices] = chunk
                mm.flush()
                del mm

            _token_ring_write(data, "npy", body)
            return

        def body(first, slabs):
            np.save(path, next(iter(slabs))[1])

        _token_ring_write(data, "npy0", body)
        return
    if ext in (".nc", ".nc4", ".netcdf"):
        return save_netcdf(data, path, *args, **kwargs)
    if ext == ".zarr":
        return save_zarr(data, path, *args, **kwargs)
    raise ValueError(f"Unsupported file extension {ext}")


# ---------------------------------------------------------------------- #
# chunked array checkpoint — the zarr/ocdbt-style scalable path (SURVEY
# §5.4: tensorstore/zarr with per-shard writes; here one .npy per shard
# chunk + a json manifest, dependency-free)
# ---------------------------------------------------------------------- #
@_telemetry.traced("io.save_array_checkpoint")
def save_array_checkpoint(
    x: DNDarray, directory: str, donate: bool = False, keep_versions: int = 1
) -> None:
    """Checkpoint a (possibly huge) DNDarray as per-shard chunk files.

    Each shard is fetched and written individually — host memory stays at
    one chunk, so checkpointable size is disk-bound.  Layout:
    ``meta.json`` (gshape, dtype, split, chunk starts, per-chunk CRC32
    checksums) + ``chunk_<start>.npy``.

    Durability contract (see design.md "Failure model & recovery"): every
    chunk file, ``meta.json`` and the version directory are fsynced BEFORE
    the atomic ``LATEST`` rename makes the version visible, and the parent
    directory is fsynced after the flip — a crash at any point leaves either
    the previous complete version or the new complete version, never a torn
    mix.  Transient write faults are retried with jittered exponential
    backoff (``utils.profiler`` counter ``retry.io.write``).

    ``donate=True`` releases the array's device buffers as soon as the write
    completes (the checkpoint-and-swap pattern: evacuate state, then reuse
    the memory for the next resident) — ``x`` must not be used afterwards.

    ``keep_versions`` retains that many complete versions after the flip
    (default 1: only the new one — the seed behavior).  Keeping >= 2 lets
    :func:`load_array_checkpoint` fall back to the previous version when the
    latest is later found corrupted (bit rot, partial loss).
    """
    if not isinstance(x, DNDarray):
        x = factories.array(x)
    _flightrec.record_event("ckpt", op="save_array", path=directory)
    keep_versions = max(int(keep_versions), 1)
    os.makedirs(directory, exist_ok=True)
    # crash-safe layout: each save goes into a fresh v<k>/ subdirectory and
    # LATEST is flipped atomically afterwards — an interrupted re-save can
    # never destroy the previous checkpoint (old version + old LATEST stay
    # intact until the new version is complete); older versions are pruned
    # only after the flip
    existing = [
        int(d[1:]) for d in os.listdir(directory)
        if d.startswith("v") and d[1:].isdigit()
        and os.path.isdir(os.path.join(directory, d))
    ]
    version = max(existing, default=-1) + 1
    vdir = os.path.join(directory, f"v{version}")
    os.makedirs(vdir, exist_ok=True)
    split = x.split
    starts, checksums, chunk_bytes = [], {}, {}
    for slices, chunk in _iter_hyperslabs(x):
        start = slices[split].start if split is not None else 0
        starts.append(int(start))
        # serialize to memory first: the checksum is computed from what the
        # writer MEANT to write, so later on-disk corruption is detectable
        buf = _pyio.BytesIO()
        np.save(buf, chunk)
        payload = buf.getvalue()
        checksums[str(start)] = zlib.crc32(payload)
        chunk_bytes[str(start)] = len(payload)
        _durable_write(os.path.join(vdir, f"chunk_{start}.npy"), payload)
    meta = {
        "gshape": list(x.shape),
        "dtype": str(x.dtype.np_dtype().name),
        "split": split,
        "starts": sorted(starts),
        "checksums": checksums,
        "chunk_bytes": chunk_bytes,
    }
    _durable_write(os.path.join(vdir, "meta.json"), json.dumps(meta).encode())
    _fsync_dir(vdir)        # chunk/meta directory entries durable
    tmp = os.path.join(directory, ".LATEST.tmp")
    _durable_write(tmp, f"v{version}".encode())
    _fsync_dir(directory)   # v<k>/ and the tmp file durable BEFORE the flip
    os.replace(tmp, os.path.join(directory, "LATEST"))  # atomic flip
    _fsync_dir(directory)   # the flip itself durable
    if donate:
        # the write is durable (post-flip): free the device storage now
        try:
            x._parray.delete()
        except (AttributeError, RuntimeError):
            pass
    import shutil

    for old in sorted(existing, reverse=True)[keep_versions - 1 :]:
        shutil.rmtree(os.path.join(directory, f"v{old}"), ignore_errors=True)
    # legacy flat-format files (pre-versioned layout) stay valid until the
    # flip, then must go: globbing consumers would read stale data
    for legacy in os.listdir(directory):
        if (legacy.startswith("chunk_") and legacy.endswith(".npy")) or legacy == "meta.json":
            try:
                os.remove(os.path.join(directory, legacy))
            except OSError:
                pass


def _verify_version(vdir: str) -> dict:
    """Integrity-check one checkpoint version directory; returns its meta.

    Raises :class:`CheckpointCorruptionError` on unreadable metadata, a
    chunk set that does not match ``meta['starts']`` (naming exactly which
    chunks are absent), a truncated chunk, or a CRC32 mismatch.  Checksums
    are verified one chunk at a time — peak memory stays at one chunk.
    Pre-checksum (legacy) versions verify layout only.
    """
    meta_path = os.path.join(vdir, "meta.json")
    if not os.path.exists(meta_path):
        raise CheckpointCorruptionError(f"no meta.json under {vdir!r}")
    try:
        meta = json.loads(_read_file(meta_path).decode())
    except (ValueError, UnicodeDecodeError) as e:
        raise CheckpointCorruptionError(f"unreadable meta.json under {vdir!r}: {e}") from e
    for key in ("gshape", "dtype", "starts"):
        if key not in meta:
            raise CheckpointCorruptionError(f"meta.json under {vdir!r} lacks {key!r}")
    expected = {f"chunk_{s}.npy" for s in meta["starts"]}
    present = {f for f in os.listdir(vdir) if f.startswith("chunk_") and f.endswith(".npy")}
    missing = sorted(expected - present)
    if missing:
        raise CheckpointCorruptionError(
            f"checkpoint {vdir!r} is missing chunk files {missing} "
            f"(meta lists starts {meta['starts']}, found {sorted(present)})"
        )
    checksums = meta.get("checksums")
    if checksums:
        sizes = meta.get("chunk_bytes", {})
        for s in meta["starts"]:
            path = os.path.join(vdir, f"chunk_{s}.npy")
            payload = _read_file(path)
            want_n = sizes.get(str(s))
            if want_n is not None and len(payload) != int(want_n):
                raise CheckpointCorruptionError(
                    f"chunk {path!r} is truncated: {len(payload)} bytes on disk, "
                    f"{want_n} recorded at save time"
                )
            crc = zlib.crc32(payload)
            if crc != int(checksums[str(s)]):
                raise CheckpointCorruptionError(
                    f"chunk {path!r} fails its checksum: crc32 {crc:#010x} != "
                    f"recorded {int(checksums[str(s)]):#010x}"
                )
    return meta


def _checkpoint_candidates(directory: str):
    """Version directories to try, most-preferred first: the one ``LATEST``
    points at, then remaining versions newest-first, then the legacy flat
    layout (pre-versioned checkpoints kept meta.json at the top level)."""
    latest_target = None
    latest = os.path.join(directory, "LATEST")
    if os.path.exists(latest):
        latest_target = _read_file(latest).decode().strip()
    versions = sorted(
        (
            int(d[1:]) for d in os.listdir(directory)
            if d.startswith("v") and d[1:].isdigit()
            and os.path.isdir(os.path.join(directory, d))
        ),
        reverse=True,
    )
    out = []
    if latest_target is not None and os.path.isdir(os.path.join(directory, latest_target)):
        out.append((os.path.join(directory, latest_target), latest_target))
    for v in versions:
        name = f"v{v}"
        if name != latest_target:
            out.append((os.path.join(directory, name), name))
    if os.path.exists(os.path.join(directory, "meta.json")):
        out.append((directory, "<legacy flat layout>"))
    return out


@_telemetry.traced("io.load_array_checkpoint")
def load_array_checkpoint(directory: str, device=None, comm=None) -> DNDarray:
    """Restore a DNDarray saved by :func:`save_array_checkpoint`.

    The load mirrors the per-shard write: each device's block is assembled
    from the (memory-mapped) chunk files covering its row range and placed
    directly on that device — the full array NEVER exists in host memory, so
    a checkpoint that was too big to gather on save is loadable too.  The
    loader's mesh size may differ from the saver's (chunk boundaries are
    re-cut to the loader's ceil-div grid).

    Every candidate version is integrity-checked before assembly (chunk set
    vs ``meta['starts']``, per-chunk CRC32): if the version ``LATEST`` points
    at fails verification, the loader falls back to the newest older version
    that verifies (with a warning naming why) — a corrupted latest version
    degrades to the previous checkpoint instead of a crash.  When nothing
    verifies, :class:`CheckpointCorruptionError` reports every candidate's
    failure.
    """
    import jax

    _flightrec.record_event("ckpt", op="load_array", path=directory)
    if not os.path.isdir(directory):
        raise FileNotFoundError(f"checkpoint directory {directory!r} does not exist")
    candidates = _checkpoint_candidates(directory)
    if not candidates:
        raise FileNotFoundError(
            f"no checkpoint versions under {directory!r} (no LATEST, no v<k>/ "
            "directories, no legacy meta.json)"
        )
    meta, chosen, failures = None, None, []
    for vdir, label in candidates:
        try:
            meta = _verify_version(vdir)
            chosen = (vdir, label)
            break
        except CheckpointCorruptionError as e:
            failures.append(f"{label}: {e}")
    if chosen is None:
        raise CheckpointCorruptionError(
            f"no loadable checkpoint under {directory!r}; every version failed "
            "verification: " + " | ".join(failures)
        )
    if failures:
        warnings.warn(
            f"checkpoint version {candidates[0][1]} under {directory!r} failed "
            f"verification ({failures[0]}); falling back to {chosen[1]}",
            stacklevel=2,
        )
    directory = chosen[0]
    gshape = tuple(meta["gshape"])
    split = meta["split"]
    np_dtype = np.dtype(meta["dtype"])
    comm = sanitize_comm(comm)
    dev = devices.sanitize_device(device)
    if split is None:
        data = np.load(os.path.join(directory, "chunk_0.npy"))
        # the scoped override reaches factories._finalize's registration:
        # a restored checkpoint is `param` on this path too, not an
        # anonymous activation minted by `array`
        with _memledger.category("param"):
            return factories.array(data.reshape(gshape), split=None, device=device, comm=comm)

    ndim = len(gshape)
    n = gshape[split]
    target = comm.padded_extent(n)
    pshape = gshape[:split] + (target,) + gshape[split + 1 :]
    starts = sorted(meta["starts"])
    mmaps = [
        np.load(os.path.join(directory, f"chunk_{s}.npy"), mmap_mode="r") for s in starts
    ]

    def read_range(lo, hi):
        """Rows [lo, hi) assembled from the chunk files (mmap: only the
        requested rows are materialized)."""
        parts = []
        for s, mm in zip(starts, mmaps):
            a, b = max(lo, s), min(hi, s + mm.shape[split])
            if a < b:
                sl = tuple(
                    slice(a - s, b - s) if i == split else slice(None) for i in range(ndim)
                )
                parts.append(np.asarray(mm[sl]))
        if not parts:
            return None
        return parts[0] if len(parts) == 1 else np.concatenate(parts, axis=split)

    sharding = comm.sharding(ndim, split)
    singles = []
    for d, idx in sharding.addressable_devices_indices_map(pshape).items():
        lo = idx[split].start or 0
        hi = idx[split].stop if idx[split].stop is not None else target
        bshape = gshape[:split] + (hi - lo,) + gshape[split + 1 :]
        block = np.zeros(bshape, dtype=np_dtype)
        data = read_range(lo, min(hi, n))
        if data is not None:
            sl = tuple(
                slice(0, data.shape[split]) if i == split else slice(None)
                for i in range(ndim)
            )
            block[sl] = data
        singles.append(jax.device_put(block, d))
    arr = jax.make_array_from_single_device_arrays(pshape, sharding, singles)
    # ledger choke point: a restored checkpoint minting is ``param`` by
    # definition (register() is a no-op when the ledger is disarmed)
    _memledger.register(arr, op="load_array_checkpoint", site="ckpt",
                        category="param")
    return DNDarray(arr, gshape, types.canonical_heat_type(np_dtype), split, dev, comm, True)


# ---------------------------------------------------------------------- #
# pytree checkpointing (estimator/NN state; SURVEY §5.4 orbax-style dump)
# ---------------------------------------------------------------------- #
@_telemetry.traced("io.save_checkpoint")
def save_checkpoint(tree, path: str) -> None:
    """Save a pytree of arrays (params/opt state) to an .npz + structure json.

    The write is ATOMIC: the archive is serialized to memory, written to a
    ``<path>.tmp.<pid>`` sibling (per-process unique, so concurrent SPMD
    ranks saving the same path don't rename each other's tmp away), fsynced,
    and renamed over the destination (then the directory is fsynced) — a
    crash mid-save can never destroy an existing checkpoint, which the
    previous in-place ``np.savez`` could.
    Transient write faults are retried with backoff (``retry.io.write``).
    """
    import jax

    final = path if path.endswith(".npz") else path + ".npz"
    _flightrec.record_event("ckpt", op="save_tree", path=final)
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    # ONE batched device→host transfer for the whole tree: per-leaf
    # np.asarray would issue a blocking round-trip per parameter, turning a
    # checkpoint into hundreds of serial host syncs.  Under multi-process
    # SPMD a leaf sharded across processes is NOT fully addressable and
    # device_get would raise — ALL such leaves go through ONE collective
    # batched fetch (host_fetch_all wraps a single pytree process_allgather;
    # every rank calls save together, which the SPMD contract already
    # requires), the rest stay on the batched device_get.
    raw = [leaf for _, leaf in flat]
    is_local = [getattr(x, "is_fully_addressable", True) for x in raw]
    local_it = iter(jax.device_get([x for x, loc in zip(raw, is_local) if loc]))
    from .communication import Communication

    remote_it = iter(
        Communication.host_fetch_all([x for x, loc in zip(raw, is_local) if not loc])
    )
    leaves = [next(local_it) if loc else next(remote_it) for loc in is_local]
    arrays = {}
    keys = []
    for i, ((p, _), host) in enumerate(zip(flat, leaves)):
        keys.append(jax.tree_util.keystr(p))
        arrays[f"leaf_{i}"] = np.asarray(host)
    # per-process tmp name: in the multi-process SPMD lane every rank runs
    # this save against the SAME shared path — a shared tmp would let rank
    # 0's os.replace rename the file out from under rank 1's (found by the
    # -m mp lane).  Each rank streams its own tmp and the atomic renames
    # land last-wins with identical SPMD content.
    tmp = f"{final}.tmp.{os.getpid()}"

    def attempt():
        # stream the archive straight into the tmp file: no second full
        # in-memory copy of the model on top of the device_get'd leaves
        with open(tmp, "wb") as fh:
            np.savez(fh, __keys__=np.asarray(json.dumps(keys)), **arrays)
            fh.flush()
            _faults.fire("io.write", path=tmp)
            _faults.fire("io.fsync", path=tmp)
            os.fsync(fh.fileno())

    _retry(attempt, "io.write")
    try:
        _telemetry.counter_inc("io.bytes_written", os.path.getsize(tmp))
    except OSError:
        pass
    _telemetry.counter_inc("io.fsync.calls")
    os.replace(tmp, final)  # atomic: readers see the old or the new file
    _fsync_dir(os.path.dirname(os.path.abspath(final)))
    # opportunistic cleanup of tmps orphaned by crashed saves (per-pid names
    # mean nobody else renames them away).  Age-gated so a CONCURRENT SPMD
    # rank's in-flight tmp — seconds old — is never unlinked out from under
    # its still-open fd, which would make its os.replace raise.
    import glob as _glob
    import time as _time

    # glob.escape: checkpoint paths may contain glob metachars ('ck[1]');
    # '.tmp*' (not '.tmp.*') also sweeps legacy fixed-name '<path>.tmp' files
    for stale in _glob.glob(_glob.escape(final) + ".tmp*"):
        try:
            if _time.time() - os.path.getmtime(stale) > 900:
                os.unlink(stale)
        except OSError:
            pass  # raced with another cleaner or an active writer: fine


@_telemetry.traced("io.load_checkpoint")
def load_checkpoint(tree_like, path: str):
    """Restore a pytree saved by :func:`save_checkpoint` into the structure
    of ``tree_like``.

    Three layers of validation, each with an error naming the file:

    - the archive must exist and be readable (a truncated/corrupt ``.npz``
      raises :class:`CheckpointCorruptionError`, not a bare zipfile error);
    - structure paths must match ``tree_like`` (a refactored/reordered tree
      raises instead of silently misassigning);
    - every leaf's shape and dtype must match its ``tree_like`` counterpart
      (a reshaped layer raises instead of silently loading wrong weights).
    """
    import zipfile

    import jax
    import jax.numpy as jnp

    p = path if path.endswith(".npz") else path + ".npz"
    _flightrec.record_event("ckpt", op="load_tree", path=p)
    if not os.path.exists(p):
        raise FileNotFoundError(
            f"checkpoint file {p!r} does not exist"
            + (f" (given path {path!r})" if p != path else "")
        )
    try:
        data = np.load(p, allow_pickle=False)
        saved_keys = json.loads(str(data["__keys__"]))
    except KeyError as e:
        raise CheckpointCorruptionError(
            f"checkpoint {p!r} has no '__keys__' entry — not a heat_tpu pytree "
            "checkpoint, or truncated mid-write"
        ) from e
    except (zipfile.BadZipFile, OSError, ValueError) as e:
        raise CheckpointCorruptionError(
            f"checkpoint {p!r} is unreadable (truncated or corrupt): {e}"
        ) from e
    flat_p, treedef = jax.tree_util.tree_flatten_with_path(tree_like)
    live_keys = [jax.tree_util.keystr(kp) for kp, _ in flat_p]
    if saved_keys != live_keys:
        raise ValueError(
            "checkpoint structure mismatch: saved paths "
            f"{saved_keys[:3]}... != target paths {live_keys[:3]}..."
        )
    leaves = []
    for i, (kp, like) in enumerate(flat_p):
        name = jax.tree_util.keystr(kp)
        try:
            arr = data[f"leaf_{i}"]
        except KeyError as e:
            raise CheckpointCorruptionError(
                f"checkpoint {p!r} lacks leaf_{i} ({name}) — truncated archive"
            ) from e
        except (zipfile.BadZipFile, zlib.error, OSError) as e:
            raise CheckpointCorruptionError(
                f"checkpoint {p!r}: leaf_{i} ({name}) is corrupt: {e}"
            ) from e
        want_shape = getattr(like, "shape", None)
        if want_shape is not None and tuple(arr.shape) != tuple(want_shape):
            raise ValueError(
                f"checkpoint {p!r}: leaf {name} has shape {tuple(arr.shape)} "
                f"but the target tree expects {tuple(want_shape)} — refusing "
                "to load a reshaped parameter"
            )
        want_dtype = getattr(like, "dtype", None)
        if want_dtype is not None and np.dtype(arr.dtype) != np.dtype(want_dtype):
            raise ValueError(
                f"checkpoint {p!r}: leaf {name} has dtype {np.dtype(arr.dtype)} "
                f"but the target tree expects {np.dtype(want_dtype)}"
            )
        leaf = jnp.asarray(arr)
        # ledger choke point: restored pytree leaves are params (the
        # category() context can override for opt-state restores)
        _memledger.register(leaf, op="load_checkpoint", site="ckpt")
        leaves.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, leaves)
