"""Deterministic fault injection + bounded retry (the failure-hardening core).

A production run survives torn writes, flaky storage and slow coordinators
only if every recovery path is *testable on CPU*; this module provides the
two halves of that story:

- **fault sites**: named points threaded through the runtime where a test
  (or a chaos job) can deterministically inject a failure.  The catalog
  lives in ``doc/source/design.md`` ("Failure model & recovery"):

  ========================  ====================================================
  site                      fired from
  ========================  ====================================================
  ``io.write``              every durable checkpoint file write (chunk files,
                            ``meta.json``, ``LATEST`` tmp, pytree ``.npz``)
  ``io.read``               checkpoint verification/assembly reads
  ``io.fsync``              every fsync of a checkpoint file or directory
  ``comm.host_fetch``       ``Communication.host_fetch`` (device→host fetches)
  ``comm.collective``       every ``Communication`` collective staging point
                            (``_account``) and the blocking waits
                            (``Wait``/``Barrier``) — ``delay``/``hang`` here
                            model a slow or dead peer, the case the
                            ``comm.deadline`` watchdog exists for
  ``proc.exit``             once per training step (``DASO.step``) and per
                            dryrun-worker section — ``exit=N`` SIGKILLs the
                            process on the Nth firing, the deterministic
                            "rank dies mid-training" the supervisor lane
                            recovers from
  ``dist.init``             each ``jax.distributed.initialize`` attempt in
                            ``bootstrap.init_distributed``
  ``sched.dispatch``        every scheduler dispatch attempt
                            (``parallel.scheduler.Scheduler``), fired inside
                            the armed per-job deadline — ``fail``/``delay``
                            exercise the retry path, ``hang`` proves a wedged
                            dispatch trips as THAT job's failure (not a
                            wedged queue), ``exit`` SIGKILLs a serving rank
                            mid-queue (the chaos lane's journal-replay
                            scenario)
  ``sched.journal.write``   every append to the scheduler's crash-durable
                            job journal — makes torn-record and
                            journal-loss recovery deterministically testable
  ========================  ====================================================

- **retry with backoff**: :func:`call_with_retries` — capped, jittered
  exponential backoff around transient faults, with attempt counters pushed
  into ``utils.profiler`` (``retry.<site>``) so recoveries are observable.

Faults are armed either in-process::

    with faults.inject("io.write", fail=2):
        ht.save_array_checkpoint(x, d)   # first two chunk writes fail, then heal

or across a process boundary via the environment (the chaos lane's SIGKILL
tests configure the victim subprocess this way)::

    HEAT_TPU_FAULTS="io.write:delay=0.3;io.fsync:fail=1"

Modes per site (combinable):

- ``fail=N``     raise :class:`TransientFault` on the first N firings
  (``N=-1``: every firing); ``exc=`` overrides the exception type.
- ``delay=S``    sleep S seconds on every firing — widens crash windows so a
  SIGKILL deterministically lands inside a write loop.
- ``corrupt=N``  flip one byte of the file passed as ``fire(..., path=)`` on
  the first N firings — models bit rot / torn sectors *after* the writer
  computed its checksum.
- ``hang=N``     block forever on the first N firings (``-1``: every) —
  models a dead peer's collective; only a deadline watchdog or a kill
  reclaims the caller.
- ``exit=N``     SIGKILL the *own* process on the Nth firing — models rank
  death at a deterministic point (the supervisor chaos lane arms this on
  one rank's ``proc.exit``).

Everything here is stdlib-only on purpose: the registry is imported from the
innermost I/O and bootstrap paths, where a heavy import would be a cycle.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import time
from typing import Callable, Dict, Iterator, Optional, Tuple

__all__ = [
    "InjectedFault",
    "TransientFault",
    "FaultSpec",
    "inject",
    "fire",
    "trip_count",
    "reset_trips",
    "parse_spec",
    "backoff_schedule",
    "call_with_retries",
]


class InjectedFault(Exception):
    """Base class of every injected failure."""


class TransientFault(InjectedFault, OSError):
    """An injected failure that models a *transient* condition (flaky disk,
    slow coordinator) — the retry layer treats it as retryable.  Subclasses
    ``OSError`` so code with real-world ``except OSError`` handling exercises
    the same path the genuine failure would take."""


class FaultSpec:
    """Armed behavior of one site.  ``fail``/``corrupt``/``hang`` are
    countdowns (mutated as the site fires; ``-1`` = unlimited); ``delay``
    applies to every firing; ``exit`` counts DOWN to the fatal firing."""

    __slots__ = ("site", "fail", "delay", "corrupt", "hang", "exit", "exc")

    def __init__(
        self,
        site: str,
        fail: int = 0,
        delay: float = 0.0,
        corrupt: int = 0,
        hang: int = 0,
        exit: int = 0,
        exc: type = TransientFault,
    ):
        self.site = site
        self.fail = int(fail)
        self.delay = float(delay)
        self.corrupt = int(corrupt)
        self.hang = int(hang)
        self.exit = int(exit)
        self.exc = exc

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"FaultSpec({self.site!r}, fail={self.fail}, delay={self.delay}, "
            f"corrupt={self.corrupt}, hang={self.hang}, exit={self.exit})"
        )


def parse_spec(text: str) -> Dict[str, FaultSpec]:
    """Parse the ``HEAT_TPU_FAULTS`` grammar:
    ``site:key=val,key=val;site2:key=val`` with keys
    fail/delay/corrupt/hang/exit."""
    specs: Dict[str, FaultSpec] = {}
    for entry in filter(None, (e.strip() for e in text.split(";"))):
        site, _, kvs = entry.partition(":")
        site = site.strip()
        if not site:
            raise ValueError(f"empty fault site in {text!r}")
        kw: Dict[str, float] = {}
        for kv in filter(None, (p.strip() for p in kvs.split(","))):
            k, _, v = kv.partition("=")
            k = k.strip()
            if k not in ("fail", "delay", "corrupt", "hang", "exit"):
                raise ValueError(f"unknown fault mode {k!r} for site {site!r}")
            kw[k] = float(v) if k == "delay" else int(v)
        specs[site] = FaultSpec(site, **kw)
    return specs


# env-armed specs (subprocess chaos tests) parsed once at import; in-process
# tests use the contextvar so parallel/nested scopes stay isolated
_ENV: Dict[str, FaultSpec] = parse_spec(os.environ.get("HEAT_TPU_FAULTS", ""))
_ctx: contextvars.ContextVar[Optional[Dict[str, FaultSpec]]] = contextvars.ContextVar(
    "heat_tpu_faults", default=None
)
_trips: Dict[str, int] = {}


@contextlib.contextmanager
def inject(
    site: str,
    *,
    fail: int = 0,
    delay: float = 0.0,
    corrupt: int = 0,
    hang: int = 0,
    exit: int = 0,
    exc: type = TransientFault,
) -> Iterator[FaultSpec]:
    """Arm ``site`` for the duration of the block (nests; yields the live
    spec so tests can inspect the remaining countdown)."""
    spec = FaultSpec(
        site, fail=fail, delay=delay, corrupt=corrupt, hang=hang, exit=exit, exc=exc
    )
    current = dict(_ctx.get() or {})
    current[site] = spec
    token = _ctx.set(current)
    try:
        yield spec
    finally:
        _ctx.reset(token)


def _flip_byte(path: str) -> None:
    size = os.path.getsize(path)
    if size == 0:
        return
    offset = size // 2
    with open(path, "r+b") as fh:
        fh.seek(offset)
        b = fh.read(1)
        fh.seek(offset)
        fh.write(bytes([b[0] ^ 0xFF]))


def fire(site: str, path: Optional[str] = None) -> None:
    """Trip ``site`` if armed: delay, then hang, then corrupt ``path``,
    then exit, then fail.  A disarmed site is a dict miss — cheap enough
    for hot paths."""
    ctx = _ctx.get()
    if ctx is None and not _ENV:
        return
    spec = (ctx or {}).get(site) or _ENV.get(site)
    if spec is None:
        return
    _trips[site] = _trips.get(site, 0) + 1
    if spec.delay:
        time.sleep(spec.delay)
    if spec.hang != 0:
        if spec.hang > 0:
            spec.hang -= 1
        while True:  # a dead peer never returns; only a watchdog/kill ends this
            time.sleep(3600.0)
    if spec.corrupt != 0 and path is not None:
        if spec.corrupt > 0:
            spec.corrupt -= 1
        _flip_byte(path)
    if spec.exit > 0:
        spec.exit -= 1
        if spec.exit == 0:
            import signal

            os.kill(os.getpid(), signal.SIGKILL)  # rank death, not an exception
    if spec.fail != 0:
        if spec.fail > 0:
            spec.fail -= 1
        raise spec.exc(f"injected fault at site {site!r}")


def trip_count(site: str) -> int:
    """How many times ``site`` fired while armed (since :func:`reset_trips`)."""
    return _trips.get(site, 0)


def reset_trips() -> None:
    _trips.clear()


# ---------------------------------------------------------------------- #
# bounded retry with jittered exponential backoff
# ---------------------------------------------------------------------- #
def backoff_schedule(
    retries: int,
    base_delay: float = 0.05,
    factor: float = 2.0,
    max_delay: float = 2.0,
    jitter: float = 0.5,
    rand: Optional[Callable[[], float]] = None,
) -> Iterator[float]:
    """The delays slept between attempts: ``min(max_delay, base*factor**i)``
    stretched by up to ``jitter``× a uniform draw (decorrelates the retry
    storms of many writers hitting one flaky store).  ``rand`` is injectable
    so tests pin the schedule without sleeping."""
    if rand is None:
        import random

        rand = random.random
    for i in range(retries):
        yield min(max_delay, base_delay * factor**i) * (1.0 + jitter * rand())


def call_with_retries(
    fn: Callable,
    site: str,
    retries: int = 4,
    base_delay: float = 0.05,
    factor: float = 2.0,
    max_delay: float = 2.0,
    jitter: float = 0.5,
    retry_on: Tuple[type, ...] = (TransientFault, OSError),
    retry_if: Optional[Callable[[BaseException], bool]] = None,
    sleep: Callable[[float], None] = time.sleep,
    rand: Optional[Callable[[], float]] = None,
    deadline: Optional[float] = None,
    clock: Callable[[], float] = time.monotonic,
):
    """Run ``fn()`` with up to ``retries`` backoff retries on transient
    failures.  Each retry increments the ``retry.<site>`` counter in
    ``utils.profiler`` so recovered faults stay visible.  ``retry_if``
    narrows ``retry_on`` (e.g. only coordinator-unreachable RuntimeErrors);
    ``sleep``/``rand``/``clock`` are injectable for fake-clock tests.

    ``deadline`` is a TOTAL-time budget in seconds: cumulative time spent
    (attempts + backoff sleeps, measured on ``clock``) never exceeds it —
    a backoff sleep that would overrun the budget is not taken and the
    last failure re-raises instead.  This caps tail latency where the
    attempt count alone cannot (attempt durations vary; a slow NFS mount
    can eat the whole budget in one try).

    Every give-up — attempts exhausted OR deadline overrun — increments
    ``retry.<site>.exhausted`` before re-raising, so abandoned recoveries
    are visible post-hoc, not just the successful ones."""
    delays = None
    attempt = 0
    t0 = clock()
    while True:
        try:
            return fn()
        except retry_on as e:
            if retry_if is not None and not retry_if(e):
                raise
            from . import profiler

            if attempt >= retries:
                profiler.counter_inc(f"retry.{site}.exhausted")
                raise
            if delays is None:
                delays = list(
                    backoff_schedule(retries, base_delay, factor, max_delay, jitter, rand)
                )
            if deadline is not None:
                elapsed = clock() - t0
                if elapsed + delays[attempt] >= deadline:
                    profiler.counter_inc(f"retry.{site}.exhausted")
                    raise
            profiler.counter_inc(f"retry.{site}")
            sleep(delays[attempt])
            attempt += 1
