"""Padding / shuffle / adaptive-max modules vs the torch.nn oracle
(round-5 mirror completion; see heat_tpu/nn/padshuffle.py)."""

import numpy as np
import pytest
import torch

import heat_tpu as ht

RNG = np.random.default_rng(11)


def _x(spatial):
    shape = {1: (2, 3, 9), 2: (2, 3, 6, 7), 3: (2, 3, 4, 5, 6)}[spatial]
    return RNG.normal(size=shape).astype(np.float32)


PADS = [
    ("ZeroPad1d", 1, 2), ("ZeroPad1d", 1, (1, 3)),
    ("ZeroPad2d", 2, 1), ("ZeroPad2d", 2, (1, 2, 0, 3)),
    ("ZeroPad3d", 3, (1, 0, 2, 1, 0, 2)),
    ("ReflectionPad1d", 1, 2), ("ReflectionPad2d", 2, (1, 2, 0, 3)),
    ("ReflectionPad3d", 3, 1),
    ("ReplicationPad1d", 1, 3), ("ReplicationPad2d", 2, (2, 0, 1, 1)),
    ("ReplicationPad3d", 3, 1),
    ("CircularPad1d", 1, 2), ("CircularPad2d", 2, (1, 2, 3, 0)),
    ("CircularPad3d", 3, 1),
]


@pytest.mark.parametrize("name,spatial,pad", PADS,
                         ids=[f"{n}-{p}" for n, _, p in PADS])
def test_pad_matches_torch(name, spatial, pad):
    x = _x(spatial)
    got = np.asarray(getattr(ht.nn, name)(pad).apply((), x))
    want = getattr(torch.nn, name)(pad)(torch.from_numpy(x)).numpy()
    np.testing.assert_array_equal(got, want)


def test_constant_pad_value():
    x = _x(2)
    got = np.asarray(ht.nn.ConstantPad2d((1, 2, 0, 1), 7.5).apply((), x))
    want = torch.nn.ConstantPad2d((1, 2, 0, 1), 7.5)(torch.from_numpy(x)).numpy()
    np.testing.assert_array_equal(got, want)
    with pytest.raises(ValueError, match="per-side"):
        ht.nn.ConstantPad2d((1, 2, 3))


def test_pixel_shuffle_roundtrip_matches_torch():
    x = RNG.normal(size=(2, 12, 3, 4)).astype(np.float32)
    got = np.asarray(ht.nn.PixelShuffle(2).apply((), x))
    want = torch.nn.PixelShuffle(2)(torch.from_numpy(x)).numpy()
    np.testing.assert_array_equal(got, want)
    back = np.asarray(ht.nn.PixelUnshuffle(2).apply((), got))
    np.testing.assert_array_equal(back, x)
    wantu = torch.nn.PixelUnshuffle(2)(torch.from_numpy(got)).numpy()
    np.testing.assert_array_equal(back, wantu)
    with pytest.raises(ValueError, match="divisible"):
        ht.nn.PixelShuffle(5).apply((), x)


def test_channel_shuffle_matches_torch():
    x = RNG.normal(size=(2, 8, 3, 3)).astype(np.float32)
    got = np.asarray(ht.nn.ChannelShuffle(4).apply((), x))
    want = torch.nn.ChannelShuffle(4)(torch.from_numpy(x)).numpy()
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("name,spatial,out", [
    ("AdaptiveMaxPool1d", 1, 3), ("AdaptiveMaxPool2d", 2, (3, 7)),
    ("AdaptiveMaxPool3d", 3, (2, 5, 3)), ("AdaptiveAvgPool3d", 3, (2, 1, 2)),
])
def test_adaptive_pools_match_torch(name, spatial, out):
    x = _x(spatial)
    got = np.asarray(getattr(ht.nn, name)(out).apply((), x))
    want = getattr(torch.nn, name)(out)(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(got, want, atol=1e-6)


class TestExtended:
    """LPPool / alpha dropouts / EmbeddingBag / Fold-Unfold /
    TripletMarginWithDistanceLoss vs torch (heat_tpu/nn/extended.py)."""

    @pytest.mark.parametrize("name,spatial,args", [
        ("LPPool1d", 1, (2.0, 3)), ("LPPool1d", 1, (1.5, 2, 1)),
        ("LPPool2d", 2, (2.0, 2)), ("LPPool2d", 2, (3.0, (2, 3))),
        ("LPPool3d", 3, (2.0, 2)),
    ])
    def test_lppool_matches_torch(self, name, spatial, args):
        x = np.abs(_x(spatial))  # positive inputs: fair p-th-power ground
        got = np.asarray(getattr(ht.nn, name)(*args).apply((), x))
        want = getattr(torch.nn, name)(*args)(torch.from_numpy(x)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("name,spatial", [
        ("LPPool1d", 1), ("LPPool2d", 2),
    ])
    def test_lppool_signed_norm2_matches_torch(self, name, spatial):
        # signed inputs at norm_type=2: x^2 kills the sign, both agree
        x = _x(spatial)
        got = np.asarray(getattr(ht.nn, name)(2.0, 2).apply((), x))
        want = getattr(torch.nn, name)(2.0, 2)(torch.from_numpy(x)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_lppool_norm1_signed_sum_matches_torch(self):
        # ADVICE r5 #1 pinned: norm_type=1 is the SIGNED window sum (no relu
        # clamp) — torch([-3., -1.]) stays negative and so do we
        x = -np.ones((1, 1, 4), np.float32)
        got = np.asarray(ht.nn.LPPool1d(1.0, 2).apply((), x))
        want = torch.nn.LPPool1d(1.0, 2)(torch.from_numpy(x)).numpy()
        np.testing.assert_allclose(got, want, atol=1e-6)
        assert (got < 0).all(), "norm_type=1 must return the signed sum"
        # odd fractional root of a negative window sum: NaN, like torch.pow
        got3 = np.asarray(ht.nn.LPPool1d(3.0, 2).apply((), x))
        want3 = torch.nn.LPPool1d(3.0, 2)(torch.from_numpy(x)).numpy()
        np.testing.assert_array_equal(np.isnan(got3), np.isnan(want3))
        assert np.isnan(got3).all()

    def test_alpha_dropout_statistics(self):
        import jax

        x = RNG.normal(size=(2000, 64)).astype(np.float32)
        m = ht.nn.AlphaDropout(p=0.3)
        assert (np.asarray(m.apply((), x)) == x).all()  # eval = identity
        y = np.asarray(m.apply((), x, train=True, key=jax.random.key(0)))
        # self-normalizing contract: mean ~ 0, var ~ 1 preserved
        assert abs(y.mean()) < 0.05 and abs(y.var() - 1.0) < 0.1
        # dropped positions carry the affine-shifted SELU saturation value
        vals, counts = np.unique(np.round(y, 5), return_counts=True)
        assert counts.max() > 0.2 * y.size  # one repeated saturation value
        with pytest.raises(ValueError, match="PRNG key"):
            m.apply((), x, train=True)

    def test_feature_alpha_dropout_channelwise(self):
        import jax

        x = RNG.normal(size=(4, 8, 5, 5)).astype(np.float32)
        y = np.asarray(ht.nn.FeatureAlphaDropout(0.5).apply(
            (), x, train=True, key=jax.random.key(1)))
        # each (n, c) slice is either fully transformed-identity or fully
        # saturated: per-channel std of the "dropped" channels is ~0
        per = y.reshape(4, 8, -1)
        stds = per.std(axis=2)
        assert (stds < 1e-4).any() and (stds > 0.1).any()

    @pytest.mark.parametrize("mode", ["sum", "mean", "max"])
    def test_embedding_bag_2d_matches_torch(self, mode):
        import jax

        m = ht.nn.EmbeddingBag(11, 6, mode=mode)
        p = m.init(jax.random.key(0))
        t = torch.nn.EmbeddingBag(11, 6, mode=mode)
        with torch.no_grad():
            t.weight.copy_(torch.from_numpy(np.asarray(p["weight"])))
        idx = RNG.integers(0, 11, size=(5, 4)).astype(np.int64)
        got = np.asarray(m.apply(p, idx))
        want = t(torch.from_numpy(idx)).detach().numpy()
        np.testing.assert_allclose(got, want, atol=1e-5)

    @pytest.mark.parametrize("mode", ["sum", "mean", "max"])
    def test_embedding_bag_offsets_matches_torch(self, mode):
        import jax

        m = ht.nn.EmbeddingBag(11, 6, mode=mode)
        p = m.init(jax.random.key(0))
        t = torch.nn.EmbeddingBag(11, 6, mode=mode)
        with torch.no_grad():
            t.weight.copy_(torch.from_numpy(np.asarray(p["weight"])))
        idx = RNG.integers(0, 11, size=10).astype(np.int64)
        offsets = np.array([0, 3, 3, 7], dtype=np.int64)  # incl. empty bag
        got = np.asarray(m.apply(p, idx, offsets=offsets))
        want = t(torch.from_numpy(idx), torch.from_numpy(offsets)).detach().numpy()
        # incl. the empty bag: torch returns 0 there for every mode and so
        # do we (segment_max's -inf identity is masked to 0)
        np.testing.assert_allclose(got, want, atol=1e-5)
        assert np.isfinite(got).all()
        with pytest.raises(ValueError, match="offsets"):
            m.apply(p, idx, offsets=np.array([1, 3], dtype=np.int64))

    def test_embedding_bag_offsets_jittable(self):
        """The offsets form composes under jit: the eager offsets[0]
        validation steps aside for traced values (like the decode-step
        capacity guard)."""
        import jax

        m = ht.nn.EmbeddingBag(9, 4, mode="mean")
        p = m.init(jax.random.key(0))
        idx = np.array([1, 2, 3, 4, 5], dtype=np.int64)
        offs = np.array([0, 2], dtype=np.int64)
        eager = np.asarray(m.apply(p, idx, offsets=offs))
        jitted = np.asarray(jax.jit(
            lambda pp, i, o: m.apply(pp, i, offsets=o))(p, idx, offs))
        np.testing.assert_allclose(jitted, eager, atol=1e-6)

    def test_embedding_bag_per_sample_weights(self):
        import jax

        m = ht.nn.EmbeddingBag(7, 4, mode="sum")
        p = m.init(jax.random.key(0))
        t = torch.nn.EmbeddingBag(7, 4, mode="sum")
        with torch.no_grad():
            t.weight.copy_(torch.from_numpy(np.asarray(p["weight"])))
        idx = RNG.integers(0, 7, size=(3, 5)).astype(np.int64)
        psw = RNG.uniform(size=(3, 5)).astype(np.float32)
        got = np.asarray(m.apply(p, idx, per_sample_weights=psw))
        want = t(torch.from_numpy(idx),
                 per_sample_weights=torch.from_numpy(psw)).detach().numpy()
        np.testing.assert_allclose(got, want, atol=1e-5)
        with pytest.raises(ValueError, match="mode='sum'"):
            ht.nn.EmbeddingBag(7, 4, mode="mean").apply(p, idx, per_sample_weights=psw)

    @pytest.mark.parametrize("kwargs", [
        dict(), dict(stride=2), dict(padding=1), dict(dilation=2),
        dict(stride=2, padding=1, dilation=2),
    ])
    def test_unfold_matches_torch(self, kwargs):
        x = RNG.normal(size=(2, 3, 8, 9)).astype(np.float32)
        got = np.asarray(ht.nn.Unfold(3, **kwargs).apply((), x))
        want = torch.nn.Unfold(3, **kwargs)(torch.from_numpy(x)).numpy()
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_fold_matches_torch(self):
        x = RNG.normal(size=(2, 3 * 9, 9)).astype(np.float32)  # L = 3x3
        got = np.asarray(ht.nn.Fold((6, 6), 3, padding=1, stride=2).apply((), x))
        want = torch.nn.Fold((6, 6), 3, padding=1, stride=2)(torch.from_numpy(x)).numpy()
        np.testing.assert_allclose(got, want, atol=1e-5)
        # fold(unfold(x)) sums overlaps — the torch-documented identity
        img = RNG.normal(size=(1, 2, 4, 4)).astype(np.float32)
        cols = ht.nn.Unfold(2).apply((), img)
        back = np.asarray(ht.nn.Fold((4, 4), 2).apply((), cols))
        wantb = torch.nn.Fold((4, 4), 2)(
            torch.nn.Unfold(2)(torch.from_numpy(img))).numpy()
        np.testing.assert_allclose(back, wantb, atol=1e-6)

    @pytest.mark.parametrize("rank,shape,k", [
        (1, (2, 3, 9), 3), (2, (2, 3, 6, 8), 2), (3, (1, 2, 4, 4, 6), 2),
    ])
    def test_maxpool_indices_and_unpool_match_torch(self, rank, shape, k):
        x = RNG.normal(size=shape).astype(np.float32)
        pool_name = f"MaxPool{rank}d"
        unpool_name = f"MaxUnpool{rank}d"
        y, idx = getattr(ht.nn, pool_name)(k, return_indices=True).apply((), x)
        ty, tidx = getattr(torch.nn, pool_name)(k, return_indices=True)(
            torch.from_numpy(x))
        np.testing.assert_allclose(np.asarray(y), ty.numpy(), atol=1e-6)
        np.testing.assert_array_equal(np.asarray(idx), tidx.numpy())
        # unpool scatters back to the recorded positions
        got = np.asarray(getattr(ht.nn, unpool_name)(k).apply(
            (), np.asarray(y), indices=np.asarray(idx),
            output_size=x.shape[2:]))
        want = getattr(torch.nn, unpool_name)(k)(
            ty, tidx, output_size=x.shape).numpy()
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_maxunpool_validation_and_default_size(self):
        x = RNG.normal(size=(2, 3, 6, 8)).astype(np.float32)
        y, idx = ht.nn.MaxPool2d(2, return_indices=True).apply((), x)
        out = np.asarray(ht.nn.MaxUnpool2d(2).apply((), np.asarray(y),
                                                    indices=np.asarray(idx)))
        assert out.shape == (2, 3, 6, 8)  # (i-1)*s + k
        # torch also accepts the FULL (N, C, *spatial) shape as output_size
        out2 = np.asarray(ht.nn.MaxUnpool2d(2).apply(
            (), np.asarray(y), indices=np.asarray(idx), output_size=x.shape))
        np.testing.assert_array_equal(out2, out)
        with pytest.raises(ValueError, match="indices"):
            ht.nn.MaxUnpool2d(2).apply((), np.asarray(y))
        with pytest.raises(ValueError, match="entries"):
            ht.nn.MaxUnpool2d(2).apply((), np.asarray(y),
                                       indices=np.asarray(idx),
                                       output_size=(6,))
        # out-of-band output_size raises (torch contract), never a silent
        # partial scatter
        with pytest.raises(ValueError, match="must be between"):
            ht.nn.MaxUnpool2d(2).apply((), np.asarray(y),
                                       indices=np.asarray(idx),
                                       output_size=(3, 3))

    def test_maxunpool_strict_stride_band_matches_torch(self):
        # ADVICE r5 #2: torch's _unpool_output_size accepts default ± stride
        # EXCLUSIVE — with kernel != stride, the old ±kernel band admitted
        # sizes torch rejects
        x = RNG.normal(size=(1, 1, 10)).astype(np.float32)
        y, idx = ht.nn.MaxPool1d(4, 2, return_indices=True).apply((), x)
        ty, tidx = torch.nn.MaxPool1d(4, 2, return_indices=True)(
            torch.from_numpy(x))
        default = (np.asarray(y).shape[2] - 1) * 2 + 4  # (i-1)*s + k
        bad = default - 3  # inside ±kernel(4), outside ±stride(2)
        with pytest.raises(ValueError, match="must be between"):
            torch.nn.MaxUnpool1d(4, 2)(ty, tidx, output_size=(bad,))
        with pytest.raises(ValueError, match="must be between"):
            ht.nn.MaxUnpool1d(4, 2).apply(
                (), np.asarray(y), indices=np.asarray(idx), output_size=(bad,))

    def test_maxunpool_out_of_range_index_raises(self):
        # a legal-band but smaller-than-default output_size can leave the
        # recorded argmax positions outside the plane; torch raises and the
        # old .at[].set default silently clipped them onto the last slot
        x = np.array([[[0., 1., 0., 1., 0., 1.]]], np.float32)
        y, idx = ht.nn.MaxPool1d(2, return_indices=True).apply((), x)
        assert int(np.asarray(idx).max()) == 5
        with pytest.raises(ValueError, match="invalid max index"):
            ht.nn.MaxUnpool1d(2).apply(
                (), np.asarray(y), indices=np.asarray(idx), output_size=(5,))

    def test_triplet_with_distance_matches_torch(self):
        a = RNG.normal(size=(6, 5)).astype(np.float32)
        p_ = RNG.normal(size=(6, 5)).astype(np.float32)
        n = RNG.normal(size=(6, 5)).astype(np.float32)
        m = ht.nn.TripletMarginWithDistanceLoss(margin=0.7, swap=True)
        t = torch.nn.TripletMarginWithDistanceLoss(margin=0.7, swap=True)
        np.testing.assert_allclose(
            np.asarray(m(a, p_, n)),
            t(torch.from_numpy(a), torch.from_numpy(p_), torch.from_numpy(n)).numpy(),
            rtol=1e-4, atol=1e-5)
        # custom callable distance
        cos_d = lambda u, v: 1.0 - ht.nn.CosineSimilarity(dim=-1)(u, v)
        tcos = torch.nn.TripletMarginWithDistanceLoss(
            distance_function=lambda u, v: 1.0 - torch.nn.functional.cosine_similarity(u, v))
        mcos = ht.nn.TripletMarginWithDistanceLoss(distance_function=cos_d)
        np.testing.assert_allclose(
            np.asarray(mcos(a, p_, n)),
            tcos(torch.from_numpy(a), torch.from_numpy(p_), torch.from_numpy(n)).numpy(),
            rtol=1e-4, atol=1e-5)


def test_adaptive_divisibility_raises():
    with pytest.raises(ValueError, match="divisible"):
        ht.nn.AdaptiveMaxPool1d(4).apply((), _x(1))  # 9 rows / 4


CONVT = [
    ("ConvTranspose1d", (2, 3, 9), dict(stride=1, padding=0)),
    ("ConvTranspose1d", (2, 3, 9), dict(stride=2, padding=1, output_padding=1)),
    ("ConvTranspose2d", (2, 3, 6, 7), dict(stride=1, padding=1)),
    ("ConvTranspose2d", (2, 3, 6, 7), dict(stride=2, padding=0)),
    ("ConvTranspose2d", (2, 3, 6, 7), dict(stride=3, padding=2, output_padding=1)),
    ("ConvTranspose3d", (1, 2, 4, 5, 6), dict(stride=2, padding=1)),
]


@pytest.mark.parametrize("name,shape,kwargs", CONVT,
                         ids=[f"{n}-{k}" for n, _, k in CONVT])
def test_conv_transpose_matches_torch(name, shape, kwargs):
    import jax

    x = RNG.normal(size=shape).astype(np.float32)
    m = getattr(ht.nn, name)(shape[1], 4, 3, **kwargs)
    p = m.init(jax.random.key(0))
    t = getattr(torch.nn, name)(shape[1], 4, 3, **kwargs)
    with torch.no_grad():
        t.weight.copy_(torch.from_numpy(np.asarray(p["weight"])))
        t.bias.copy_(torch.from_numpy(np.asarray(p["bias"])))
    got = np.asarray(m.apply(p, x))
    want = t(torch.from_numpy(x)).detach().numpy()
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_conv_transpose_validation():
    with pytest.raises(ValueError, match="output_padding"):
        ht.nn.ConvTranspose2d(3, 4, 3, stride=1, output_padding=1)
    m = ht.nn.ConvTranspose1d(3, 4, 3, bias=False)
    import jax

    assert "bias" not in m.init(jax.random.key(0))


def test_batchnorm3d_matches_torch():
    import jax

    x = RNG.normal(size=(2, 3, 4, 5, 6)).astype(np.float32)
    m = ht.nn.BatchNorm3d(3)
    p = m.init(jax.random.key(0))
    t = torch.nn.BatchNorm3d(3)
    # train-mode normalization (batch statistics)
    got = np.asarray(m.apply(p, x, train=True))
    want = t(torch.from_numpy(x)).detach().numpy()
    np.testing.assert_allclose(got, want, atol=1e-5)
    with pytest.raises(ValueError, match="5-D"):
        m.apply(p, x[0], train=True)


def test_negative_padding_crops_like_torch():
    x = _x(2)
    for pad in ((-1, 1, 0, 0), (-1, -2, 1, -1)):
        got = np.asarray(ht.nn.ZeroPad2d(pad).apply((), x))
        want = torch.nn.ZeroPad2d(pad)(torch.from_numpy(x)).numpy()
        np.testing.assert_array_equal(got, want)


def test_pixel_shuffle_unbatched_and_5d():
    x3 = RNG.normal(size=(12, 3, 4)).astype(np.float32)
    got = np.asarray(ht.nn.PixelShuffle(2).apply((), x3))
    want = torch.nn.PixelShuffle(2)(torch.from_numpy(x3)).numpy()
    np.testing.assert_array_equal(got, want)
    x5 = RNG.normal(size=(2, 2, 8, 3, 4)).astype(np.float32)
    got = np.asarray(ht.nn.PixelShuffle(2).apply((), x5))
    want = torch.nn.PixelShuffle(2)(torch.from_numpy(x5)).numpy()
    np.testing.assert_array_equal(got, want)
    back = np.asarray(ht.nn.PixelUnshuffle(2).apply((), got))
    np.testing.assert_array_equal(back, x5)


def test_adaptive_output_size_forms():
    x = _x(2)  # (2, 3, 6, 7)
    # list form and torch's None (= keep that dim)
    got = np.asarray(ht.nn.AdaptiveMaxPool2d([3, 7]).apply((), x))
    want = torch.nn.AdaptiveMaxPool2d([3, 7])(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(got, want, atol=1e-6)
    got = np.asarray(ht.nn.AdaptiveMaxPool2d((3, None)).apply((), x))
    want = torch.nn.AdaptiveMaxPool2d((3, None))(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(got, want, atol=1e-6)
    with pytest.raises(ValueError, match="entries"):
        ht.nn.AdaptiveMaxPool2d((3, 4, 5))


class TestRecurrentCells:
    """RNNCell/LSTMCell/GRUCell vs torch: one step, torch parameter
    layout (state dicts round-trip with the scan layers')."""

    @pytest.mark.parametrize("name", ["RNNCell", "GRUCell"])
    def test_simple_cells_match_torch(self, name):
        import jax

        m = getattr(ht.nn, name)(6, 5)
        p = m.init(jax.random.key(0))
        t = getattr(torch.nn, name)(6, 5)
        with torch.no_grad():
            t.weight_ih.copy_(torch.from_numpy(np.asarray(p["weight_ih"])))
            t.weight_hh.copy_(torch.from_numpy(np.asarray(p["weight_hh"])))
            t.bias_ih.copy_(torch.from_numpy(np.asarray(p["bias_ih"])))
            t.bias_hh.copy_(torch.from_numpy(np.asarray(p["bias_hh"])))
        x = RNG.normal(size=(3, 6)).astype(np.float32)
        h = RNG.normal(size=(3, 5)).astype(np.float32)
        got = np.asarray(m.apply(p, x, hx=h))
        want = t(torch.from_numpy(x), torch.from_numpy(h)).detach().numpy()
        np.testing.assert_allclose(got, want, atol=1e-5)
        # default zero state
        got0 = np.asarray(m.apply(p, x))
        want0 = t(torch.from_numpy(x)).detach().numpy()
        np.testing.assert_allclose(got0, want0, atol=1e-5)

    def test_lstm_cell_matches_torch(self):
        import jax

        m = ht.nn.LSTMCell(6, 5)
        p = m.init(jax.random.key(0))
        t = torch.nn.LSTMCell(6, 5)
        with torch.no_grad():
            t.weight_ih.copy_(torch.from_numpy(np.asarray(p["weight_ih"])))
            t.weight_hh.copy_(torch.from_numpy(np.asarray(p["weight_hh"])))
            t.bias_ih.copy_(torch.from_numpy(np.asarray(p["bias_ih"])))
            t.bias_hh.copy_(torch.from_numpy(np.asarray(p["bias_hh"])))
        x = RNG.normal(size=(3, 6)).astype(np.float32)
        h = RNG.normal(size=(3, 5)).astype(np.float32)
        c = RNG.normal(size=(3, 5)).astype(np.float32)
        gh, gc = m.apply(p, x, hx=(h, c))
        wh, wc = t(torch.from_numpy(x), (torch.from_numpy(h), torch.from_numpy(c)))
        np.testing.assert_allclose(np.asarray(gh), wh.detach().numpy(), atol=1e-5)
        np.testing.assert_allclose(np.asarray(gc), wc.detach().numpy(), atol=1e-5)

    def test_cell_rejects_h0_spelling(self):
        import jax

        cell = ht.nn.GRUCell(4, 5)
        p = cell.init(jax.random.key(0))
        x = RNG.normal(size=(2, 4)).astype(np.float32)
        with pytest.raises(TypeError, match="hx="):
            cell.apply(p, x, h0=np.zeros((2, 5), np.float32))

    def test_cell_agrees_with_scan_layer(self):
        """Stepping the cell S times == the scan layer on the sequence."""
        import jax

        layer = ht.nn.GRU(4, 5)
        cell = ht.nn.GRUCell(4, 5)
        lp = layer.init(jax.random.key(0))
        x = RNG.normal(size=(2, 7, 4)).astype(np.float32)
        out, _ = layer.apply(lp, x)
        h = None
        for t_ in range(7):
            h = cell.apply(lp[0], x[:, t_], hx=h)
        np.testing.assert_allclose(np.asarray(h), np.asarray(out[:, -1]),
                                   atol=1e-5)
