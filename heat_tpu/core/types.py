"""The heat_tpu type system.

Mirrors the reference's ``heat/core/types.py`` contract — a small class
hierarchy of canonical types (``ht.bool`` … ``ht.complex128``) with
NumPy-style promotion — but maps onto JAX/XLA dtypes instead of torch.

TPU-first deviations (documented, deliberate):

- ``bfloat16`` is a first-class type (the MXU's native matmul dtype); the
  reference has none.
- 64-bit types exist but are only materialized when ``jax_enable_x64`` is on;
  otherwise JAX canonicalizes them to 32-bit (standard JAX behavior).  The
  default float type is ``float32`` (matching both the reference's torch
  default and the TPU sweet spot).
"""

from __future__ import annotations

import builtins
from typing import Type

import jax.numpy as jnp
import numpy as np

__all__ = [
    "datatype",
    "generic",
    "number",
    "integer",
    "signedinteger",
    "unsignedinteger",
    "floating",
    "flexible",
    "complexfloating",
    "bool",
    "bool_",
    "uint8",
    "ubyte",
    "int8",
    "byte",
    "int16",
    "short",
    "int32",
    "int",
    "int_",
    "int64",
    "long",
    "bfloat16",
    "float16",
    "half",
    "float32",
    "float",
    "float_",
    "float64",
    "double",
    "complex64",
    "cfloat",
    "complex128",
    "cdouble",
    "canonical_heat_type",
    "heat_type_of",
    "heat_type_is_exact",
    "heat_type_is_inexact",
    "heat_type_is_complexfloating",
    "issubdtype",
    "promote_types",
    "result_type",
    "can_cast",
    "iscomplex",
    "isreal",
    "finfo",
    "iinfo",
]


class datatype:
    """Base class of the heat_tpu scalar type hierarchy (``ht.generic``)."""

    _np_char: str = None  # numpy typestring for the concrete leaf classes

    def __new__(cls, *value, device=None, comm=None):
        # instantiation casts: ht.float32(x) == ht.array(x, dtype=ht.float32)
        from . import factories

        if len(value) == 0:
            value = (0,)
        if len(value) == 1:
            return factories.array(value[0], dtype=cls, device=device, comm=comm)
        raise TypeError(f"takes at most 1 argument, got {len(value)}")

    @classmethod
    def np_dtype(cls) -> np.dtype:
        return np.dtype(cls._np_char)

    @classmethod
    def jax_dtype(cls):
        return jnp.dtype(cls._np_char) if cls._np_char != "bfloat16" else jnp.bfloat16

    @classmethod
    def char(cls) -> str:
        return cls._np_char


generic = datatype


class bool(datatype):
    _np_char = "bool"


class number(datatype):
    pass


class integer(number):
    pass


class signedinteger(integer):
    pass


class unsignedinteger(integer):
    pass


class floating(number):
    pass


class flexible(datatype):
    pass


class complexfloating(number):
    pass


class uint8(unsignedinteger):
    _np_char = "uint8"


class uint16(unsignedinteger):
    _np_char = "uint16"


class uint32(unsignedinteger):
    _np_char = "uint32"


class uint64(unsignedinteger):
    _np_char = "uint64"


class int8(signedinteger):
    _np_char = "int8"


class int16(signedinteger):
    _np_char = "int16"


class int32(signedinteger):
    _np_char = "int32"


class int64(signedinteger):
    _np_char = "int64"


class bfloat16(floating):
    _np_char = "bfloat16"


class float16(floating):
    _np_char = "float16"


class float32(floating):
    _np_char = "float32"


class float64(floating):
    _np_char = "float64"


class complex64(complexfloating):
    _np_char = "complex64"


class complex128(complexfloating):
    _np_char = "complex128"


# aliases (reference-compatible)
bool_ = bool
ubyte = uint8
byte = int8
short = int16
int = int32
int_ = int32
long = int64
half = float16
float = float32
float_ = float32
double = float64
cfloat = complex64
cdouble = complex128


_HEAT_TYPES = [
    bool,
    uint8,
    uint16,
    uint32,
    uint64,
    int8,
    int16,
    int32,
    int64,
    bfloat16,
    float16,
    float32,
    float64,
    complex64,
    complex128,
]
_BY_CHAR = {t._np_char: t for t in _HEAT_TYPES}

# python-builtin / numpy / jax dtype → heat type
_CANONICAL = {}
for _t in _HEAT_TYPES:
    _CANONICAL[_t] = _t
    if _t._np_char != "bfloat16":
        _CANONICAL[np.dtype(_t._np_char)] = _t
        _CANONICAL[np.dtype(_t._np_char).type] = _t
_CANONICAL[builtins.bool] = bool
_CANONICAL[builtins.int] = int32
_CANONICAL[builtins.float] = float32
_CANONICAL[builtins.complex] = complex64
_CANONICAL[jnp.bfloat16] = bfloat16
_CANONICAL[jnp.dtype(jnp.bfloat16)] = bfloat16
_CANONICAL["bool"] = bool
for _c in ("uint8", "uint16", "uint32", "uint64", "int8", "int16", "int32", "int64",
           "bfloat16", "float16", "float32", "float64", "complex64", "complex128"):
    _CANONICAL[_c] = _BY_CHAR[_c]


def canonical_heat_type(a_type) -> Type[datatype]:
    """Resolve any dtype-like object to the canonical heat_tpu type class."""
    try:
        return _CANONICAL[a_type]
    except (KeyError, TypeError):
        pass
    try:
        return _CANONICAL[np.dtype(a_type)]
    except (KeyError, TypeError):
        raise TypeError(f"Data type {a_type!r} is not understood") from None


def heat_type_of(obj) -> Type[datatype]:
    """The heat type of ``obj``'s elements (DNDarray / jax / numpy / scalars / sequences)."""
    dt = getattr(obj, "dtype", None)
    if dt is not None:
        return canonical_heat_type(dt)
    if isinstance(obj, (builtins.bool, builtins.int, builtins.float, builtins.complex)):
        return canonical_heat_type(type(obj))
    if isinstance(obj, (list, tuple)):
        return canonical_heat_type(np.asarray(obj).dtype)
    raise TypeError(f"Cannot determine heat type of {type(obj)}")


def issubdtype(arg1, arg2) -> builtins.bool:
    """NumPy-semantics ``issubdtype`` over the heat class hierarchy."""
    if not isinstance(arg1, type) or not issubclass(arg1, datatype):
        arg1 = canonical_heat_type(arg1)
    if isinstance(arg2, type) and issubclass(arg2, datatype):
        return issubclass(arg1, arg2)
    return issubclass(arg1, canonical_heat_type(arg2))


def heat_type_is_exact(ht_dtype) -> builtins.bool:
    """True for integer/bool types."""
    t = canonical_heat_type(ht_dtype)
    return issubclass(t, integer) or t is bool


def heat_type_is_inexact(ht_dtype) -> builtins.bool:
    return issubclass(canonical_heat_type(ht_dtype), (floating, complexfloating))


def heat_type_is_complexfloating(ht_dtype) -> builtins.bool:
    return issubclass(canonical_heat_type(ht_dtype), complexfloating)


def promote_types(type1, type2) -> Type[datatype]:
    """NumPy-style type promotion over heat types (bfloat16-aware via jnp)."""
    t1, t2 = canonical_heat_type(type1), canonical_heat_type(type2)
    res = jnp.promote_types(t1.jax_dtype(), t2.jax_dtype())
    return canonical_heat_type(res)


def result_type(*operands) -> Type[datatype]:
    """The heat type resulting from combining the given operands (arrays or scalars)."""

    def as_np(o):
        if isinstance(o, type) and issubclass(o, datatype):
            return o.jax_dtype()
        dt = getattr(o, "dtype", None)
        if dt is not None:
            d = canonical_heat_type(dt)
            return d.jax_dtype()
        return o

    return canonical_heat_type(jnp.result_type(*[as_np(o) for o in operands]))


def can_cast(from_, to, casting: str = "safe") -> builtins.bool:
    """NumPy-semantics ``can_cast`` over heat types (intuitive | safe | same_kind | unsafe)."""
    if casting == "unsafe":
        return True
    try:
        f = canonical_heat_type(from_) if not isinstance(from_, (builtins.int, builtins.float, builtins.complex, builtins.bool)) else heat_type_of(from_)
    except TypeError:
        f = heat_type_of(from_)
    t = canonical_heat_type(to)
    fd, td = np.dtype(f._np_char if f._np_char != "bfloat16" else "float32"), np.dtype(
        t._np_char if t._np_char != "bfloat16" else "float32"
    )
    if casting == "same_kind":
        return np.can_cast(fd, td, casting="same_kind")
    if casting in ("safe", "intuitive"):
        return np.can_cast(fd, td, casting="safe")
    raise ValueError(f"Unknown casting mode {casting}")


def iscomplex(x):
    """Elementwise: does the element have a non-zero imaginary part."""
    from . import _operations
    from .dndarray import DNDarray

    if not isinstance(x, DNDarray):
        from . import factories

        x = factories.array(x)
    if heat_type_is_complexfloating(x.dtype):
        return _operations.__dict__["_local_op"](jnp.imag, x) != 0
    from . import factories

    return factories.zeros(x.shape, dtype=bool, split=x.split, device=x.device, comm=x.comm)


def isreal(x):
    """Elementwise: is the element real-valued (imag == 0)."""
    from .logical import logical_not

    return logical_not(iscomplex(x))


class finfo:
    """Machine limits for floating point heat types (mirrors ``np.finfo``)."""

    def __new__(cls, dtype):
        t = canonical_heat_type(dtype)
        if not issubclass(t, floating) and not issubclass(t, complexfloating):
            raise TypeError(f"Data type {dtype} not inexact")
        info = jnp.finfo(t.jax_dtype())
        self = object.__new__(cls)
        self.bits = info.bits
        self.eps = builtins.float(info.eps)
        self.max = builtins.float(info.max)
        self.min = builtins.float(info.min)
        self.tiny = builtins.float(info.tiny)
        return self


class iinfo:
    """Machine limits for integer heat types (mirrors ``np.iinfo``)."""

    def __new__(cls, dtype):
        t = canonical_heat_type(dtype)
        if t is bool or not issubclass(t, integer):
            raise TypeError(f"Data type {dtype} not an integer type")
        info = jnp.iinfo(t.jax_dtype())
        self = object.__new__(cls)
        self.bits = info.bits
        self.max = builtins.int(info.max)
        self.min = builtins.int(info.min)
        return self


def isdtype(dtype, kind) -> bool:
    """Array-API dtype predicate (numpy 2 ``isdtype``)."""
    import numpy as _np

    try:
        dt = canonical_heat_type(dtype).np_dtype()
    except (TypeError, ValueError):
        dt = _np.dtype(dtype)
    return _np.isdtype(dt, kind)


__all__ += ["isdtype"]
