"""Memory-bounded streaming redistribution: tiled resplit under a byte budget.

Redistribution (``DNDarray.resplit_`` → ``Communication.resplit``) is the
reference framework's signature data movement (SURVEY §3.3).  The monolithic
realization — one ``device_put`` to the target sharding, lowered by XLA to a
single all-to-all — materializes source and destination WHOLE: peak memory is
~2× the array plus collective staging, and donation recovers almost nothing
because the transfer itself holds both copies (``BENCH_DISPATCH.json``:
in-place resplit peaked at 751 MB vs 774 MB for the copy path).  Following
"Memory-efficient array redistribution through portable collective
communication" (arXiv 2112.01075), any split→split transition decomposes into
a *sequence of tiled collectives* with bounded peak memory.  This module is
that decomposition:

- :func:`plan_resplit` — a PURE planner: given (gshape, itemsize, src split,
  dst split, world size, budget bytes) it picks a tiling axis that is neither
  the source nor the destination split, sizes uniform tiles so each moves at
  most ``budget`` bytes (a shorter tail tile absorbs ragged extents — the
  "padded final tile" clipped to its true length so no byte is moved or
  accounted twice), and returns a :class:`ResplitPlan` with K tiles.  K=1
  degenerates to the monolithic fast path, with the reason recorded.

- :func:`execute_plan` — the streaming executor: preallocate the destination
  (dst-sharded zeros), then per tile *slice → reshard (the tiled all-to-all)
  → write into the destination in place*.  Every per-tile program is jitted
  and kept in the PR 1 sharding-keyed program cache (``cached_program``), so
  a steady-state chunked resplit recompiles nothing; the move and update
  programs DONATE their inputs, so each staged tile is freed before the next
  stage begins, and the in-place update aliases the accumulator (same shape/
  dtype/sharding → ``input_output_alias``).  With ``donate=True`` the source
  buffer is additionally ``delete()``-ed the moment the last tile has been
  sliced out of it.

**Peak-memory model** (documented contract, gated by ``benchmarks/dispatch.py
--resplit-gate``): beyond source + destination, the transient working set is
at most ``budget + one tile`` (one tile staged out of the source plus its
resharded copy in flight).  The monolithic path's transient is O(array).

**Budget semantics**: ``memory_budget`` bounds the bytes MOVED PER STEP.  The
resolution order is: explicit ``memory_budget=`` kwarg → process-wide default
(:func:`set_redistribution_budget`) → ``HEAT_TPU_RESPLIT_BUDGET`` env (read
once at import; suffixes K/M/G accepted).  ``None``/``0`` means unbounded
(monolithic).  A budget below one tiling-axis slice floors at one slice per
tile — best effort, recorded as the plan's ``reason``.

Transitions that cannot tile fall back to K=1 monolithic, recorded in
``ResplitPlan.reason``: tracers (nothing concrete to stream), hosted-complex
arrays, ragged source/destination extents (their placement is XLA's, not the
canonical sharding tiles are built from), 0-d/1-d arrays and 2-d k→j (no
non-split axis to tile along — the general basis-change decompositions of
arXiv 2112.01075 §5 are future work), and arrays whose total size already
fits the budget.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

# device-memory-ledger hook (``utils.memledger.enable()`` pokes the module
# in): the streaming executor registers every staged tile (category
# ``transient``), fires the ``mem.alloc`` fault site ahead of each tile's
# allocation, consumes donated buffers at donation, and transfers the
# aliased accumulator entry — so ``mem.live_bytes`` observes the
# budget + one-tile transient contract FROM INSIDE.  Disabled cost: one
# module-global load per plan.  Module bottom re-arms.
_MEMLEDGER = None

__all__ = [
    "ResplitPlan",
    "plan_resplit",
    "make_plan",
    "execute_plan",
    "parse_budget",
    "set_redistribution_budget",
    "get_redistribution_budget",
]


# ---------------------------------------------------------------------- #
# process-wide default budget
# ---------------------------------------------------------------------- #
def parse_budget(budget) -> Optional[int]:
    """Normalize a budget spec to bytes: ints pass through, strings accept
    K/M/G(B) suffixes (``"64M"`` → 67108864).  ``None``, ``0``, negative and
    the empty string all mean "unbounded" and normalize to ``None``."""
    if budget is None:
        return None
    if isinstance(budget, str):
        text = budget.strip().upper().removesuffix("B")
        if not text:
            return None
        scale = 1
        if text[-1] in "KMG":
            scale = 1024 ** ("KMG".index(text[-1]) + 1)
            text = text[:-1]
        # scale BEFORE truncating: "0.5G" is 512M, not int(0.5)=0 -> unbounded
        budget = int(float(text) * scale)
    else:
        budget = int(budget)
    return budget if budget > 0 else None


_DEFAULT_BUDGET: Optional[int] = parse_budget(
    os.environ.get("HEAT_TPU_RESPLIT_BUDGET")
)


def set_redistribution_budget(budget) -> Optional[int]:
    """Set the process-wide default resplit memory budget (bytes; K/M/G
    string suffixes accepted; ``None``/``0`` restores unbounded).  Returns
    the previous value so callers can scope-and-restore."""
    global _DEFAULT_BUDGET
    prev = _DEFAULT_BUDGET
    _DEFAULT_BUDGET = parse_budget(budget)
    return prev


def get_redistribution_budget() -> Optional[int]:
    """The process-wide default resplit budget in bytes (None = unbounded)."""
    return _DEFAULT_BUDGET


# ---------------------------------------------------------------------- #
# planner (pure — no jax, no mesh; unit-testable standalone)
# ---------------------------------------------------------------------- #
@dataclass(frozen=True)
class ResplitPlan:
    """A split→split transition decomposed into K tiled all-to-all steps.

    ``tile_axis`` is None iff the plan is monolithic (``n_tiles == 1`` via
    any fallback ``reason``); otherwise tile ``i`` covers
    ``[i*tile_extent, min((i+1)*tile_extent, gshape[tile_axis]))`` along
    ``tile_axis`` — the final tile is clipped to the true extent, so the
    tiles partition the array exactly (no overlap, no double-accounting).
    """

    gshape: Tuple[int, ...]
    itemsize: int
    src_split: Optional[int]
    dst_split: Optional[int]
    size: int
    budget: Optional[int]
    tile_axis: Optional[int]
    tile_extent: int
    n_tiles: int
    total_bytes: int
    reason: str

    def tile_bounds(self, i: int) -> Tuple[int, int]:
        """(start, length) of tile ``i`` along ``tile_axis``."""
        if self.tile_axis is None:
            return 0, self.gshape[0] if self.gshape else 0
        n = self.gshape[self.tile_axis]
        start = i * self.tile_extent
        return start, min(self.tile_extent, n - start)

    def tile_nbytes(self, length: int) -> int:
        """Payload bytes of a tile spanning ``length`` along ``tile_axis``."""
        if self.tile_axis is None:
            return self.total_bytes
        n = self.gshape[self.tile_axis]
        return (self.total_bytes // n) * length if n else 0

    @property
    def max_tile_bytes(self) -> int:
        return self.tile_nbytes(self.tile_extent) if self.tile_axis is not None else self.total_bytes


def _mono(gshape, itemsize, src, dst, size, budget, total, reason) -> ResplitPlan:
    return ResplitPlan(
        gshape=tuple(gshape), itemsize=itemsize, src_split=src, dst_split=dst,
        size=size, budget=budget, tile_axis=None, tile_extent=0, n_tiles=1,
        total_bytes=total, reason=reason,
    )


def plan_resplit(
    gshape,
    itemsize: int,
    src_split: Optional[int],
    dst_split: Optional[int],
    size: int,
    memory_budget: Optional[int],
) -> ResplitPlan:
    """Decompose the (src_split → dst_split) transition of a ``gshape`` array
    of ``itemsize``-byte elements over ``size`` shards into tiles of at most
    ``memory_budget`` bytes each.  Pure shard math — returns a monolithic
    K=1 plan (with ``reason``) whenever tiling does not apply."""
    gshape = tuple(int(s) for s in gshape)
    ndim = len(gshape)
    if src_split is not None and ndim:
        src_split = src_split % ndim
    if dst_split is not None and ndim:
        dst_split = dst_split % ndim
    total = int(np.prod(gshape, dtype=np.int64)) * int(itemsize) if gshape else int(itemsize)
    budget = parse_budget(memory_budget)
    args = (gshape, int(itemsize), src_split, dst_split, int(size), budget, total)
    if budget is None:
        return _mono(*args, "no-budget")
    if ndim < 2:
        return _mono(*args, "too-few-dims")
    if total <= budget:
        return _mono(*args, "fits-in-budget")
    # canonical shardings on both ends are what the per-tile programs are
    # built from; a ragged extent's placement is XLA's, not canonical
    if src_split is not None and gshape[src_split] % size != 0:
        return _mono(*args, "ragged-src")
    if dst_split is not None and gshape[dst_split] % size != 0:
        return _mono(*args, "ragged-dst")
    candidates = [
        i for i in range(ndim)
        if i != src_split and i != dst_split and gshape[i] >= 2
    ]
    if not candidates:
        return _mono(*args, "no-free-axis")
    # largest extent → finest achievable granularity (ties: lowest axis)
    axis = max(candidates, key=lambda i: (gshape[i], -i))
    n = gshape[axis]
    per_index = total // n  # bytes of one tiling-axis slice
    extent = max(1, budget // per_index) if per_index else n
    if extent >= n:
        return _mono(*args, "fits-in-budget")
    n_tiles = -(-n // extent)
    reason = "tiled" if per_index <= budget else "tiled-floor-one-slice"
    return ResplitPlan(
        gshape=gshape, itemsize=int(itemsize), src_split=src_split,
        dst_split=dst_split, size=int(size), budget=budget, tile_axis=axis,
        tile_extent=extent, n_tiles=n_tiles, total_bytes=total, reason=reason,
    )


# ---------------------------------------------------------------------- #
# eligibility + execution (jax-touching half)
# ---------------------------------------------------------------------- #
def make_plan(comm, array, dst_split: Optional[int], memory_budget=None) -> Optional[ResplitPlan]:
    """Plan the redistribution of a CONCRETE array, or None when the tiled
    pipeline cannot apply (tracer, hosted complex, non-canonical current
    placement) — the caller then takes the monolithic path unconditionally.

    ``memory_budget=None`` resolves to the process default
    (:func:`set_redistribution_budget` / ``HEAT_TPU_RESPLIT_BUDGET``); pass
    ``0`` to force monolithic regardless of the default."""
    import jax

    if memory_budget is None:
        budget = get_redistribution_budget()
    else:
        budget = parse_budget(memory_budget)
    if budget is None:
        return None
    if isinstance(array, jax.core.Tracer) or not isinstance(array, jax.Array):
        return None
    from . import _complexsafe

    if _complexsafe.guard(array) is not None:
        return None  # hosted complex: stays off the mesh
    ndim = array.ndim
    src_split = comm.split_of(array)
    # the per-tile slice programs assume the source carries exactly the
    # canonical sharding of src_split; anything else (XLA's opportunistic
    # ragged placement, sub-meshes) takes the monolithic path
    cur = getattr(array, "sharding", None)
    want = comm.sharding(ndim, src_split)
    if cur != want:
        try:
            if cur is None or not cur.is_equivalent_to(want, ndim):
                return None
        except Exception:
            return None
    return plan_resplit(
        array.shape, np.dtype(array.dtype).itemsize, src_split, dst_split,
        comm.size, budget,
    )


def execute_plan(comm, array, plan: ResplitPlan, donate: bool = False):
    """Run a K>1 :class:`ResplitPlan`: stream the array to its new sharding
    tile by tile, peak transient memory ≤ budget + one tile beyond the
    source and destination buffers.

    Per tile: *slice* (jitted dynamic-slice along the tiling axis, source
    sharding preserved, no communication) → *move* (jitted identity with the
    destination ``out_shardings`` — THE tiled all-to-all; input donated, so
    the staged slice is freed as soon as the transfer consumed it) →
    *update* (jitted ``dynamic_update_slice`` into the preallocated
    destination; the accumulator is donated and aliases in place, the moved
    tile is donated and freed).  All programs live in the PR 1 program cache
    keyed on (shape, dtype, splits, tile geometry): a steady-state chunked
    resplit is 100% cache hits.

    Accounting: each tile is byte-accounted exactly once at its staging
    point under ``comm.resplit.calls/.bytes`` with the resplit traffic
    factor (p-1)/p, using telescoped cumulative rounding so the SUM over
    tiles equals the monolithic path's single accounting to the byte;
    ``comm.resplit.tiles`` and ``comm.resplit.peak_tile_bytes`` record the
    plan shape.  The per-tile ``_account_bytes`` choke point also fires the
    ``comm.collective`` fault site and refuses to stage past a blown
    ``comm.deadline`` — and under an armed deadline every tile's transfer is
    awaited through the ``guard_blocking`` watchdog, so ONE hung tile trips
    ``CollectiveTimeoutError`` instead of wedging the whole plan.

    ``donate=True`` additionally deletes the source buffer once the last
    tile has been sliced out of it (the caller must not use it afterwards).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ._cache import cached_program

    ndim = array.ndim
    axis = plan.tile_axis
    src_sh = comm.sharding(ndim, plan.src_split)
    dst_sh = comm.sharding(ndim, plan.dst_split)
    dtype = array.dtype
    shape = tuple(array.shape)
    sig = (shape, str(jnp.dtype(dtype)), plan.src_split, plan.dst_split, axis)
    factor = (comm.size - 1) / comm.size

    def _program(kind: str, length: int, builder):
        return cached_program(comm, ("resplit", kind, sig, length), builder)

    def _build_init():
        return jax.jit(lambda: jnp.zeros(shape, dtype), out_shardings=dst_sh)

    def _build_slice(length: int):
        def f(src, start):
            return lax.dynamic_slice_in_dim(src, start, length, axis=axis)

        return jax.jit(f, out_shardings=src_sh)

    def _build_move():
        # identity with changed out_shardings: XLA lowers the sharding
        # change to the tile-sized all-to-all; donation frees the staged
        # slice as soon as the transfer has consumed it
        return jax.jit(lambda t: t, out_shardings=dst_sh, donate_argnums=(0,))

    def _build_update():
        def f(acc, tile, start):
            return lax.dynamic_update_slice_in_dim(acc, tile, start, axis=axis)

        # acc donated: same shape/dtype/sharding as the output, so XLA
        # aliases the buffers (true in-place); tile donated: freed on use
        return jax.jit(f, out_shardings=dst_sh, donate_argnums=(0, 1))

    from ..utils import health as _hlth
    from ..utils import telemetry as _tel

    def _quiet(prog, *args):
        # donated tiles cannot ALIAS their (differently-shaped) outputs —
        # the donation is for the early free, which still happens; jax's
        # compile-time "donated buffers were not usable" warning is expected
        # noise here, filtered at the call (= first-compile) site only
        import warnings

        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message=".*[Dd]onated buffers were not usable.*"
            )
            return prog(*args)

    from ..utils import profiler as _prof

    ml = _MEMLEDGER
    out = _program("init", 0, _build_init)()
    if ml is not None:
        # the preallocated destination: a transient until the finished plan
        # reclassifies it (comm.resplit_tiled)
        ml.register(out, op="resplit.init", site="resplit.tile")
    accounted = 0  # telescoped: totals match the monolithic path to the byte
    moved = 0
    for i in range(plan.n_tiles):
        start, length = plan.tile_bounds(i)
        tile_bytes = plan.tile_nbytes(length)
        moved += tile_bytes
        wire = int(round(moved * factor)) - accounted
        accounted += wire
        comm._account_bytes(
            "resplit", wire, x=array,
            src_split=plan.src_split, dst_split=plan.dst_split,
        )
        if ml is not None:
            # the mem.alloc fault site, per tile: chaos CI injects the
            # deterministic mid-resplit allocation failure HERE — the
            # caller's catch dumps the ledger and re-raises
            ml.alloc_check(tile_bytes, "comm.resplit.tile")
        # plan-shape counters advance PER TILE so a mid-plan failure (hung
        # tile tripping the deadline) leaves calls/bytes/tiles consistent in
        # the post-mortem report instead of tiles=0 masquerading as monolithic
        _tel.counter_inc("comm.resplit.tiles", 1)
        _prof.counter_max("comm.resplit.peak_tile_bytes", tile_bytes)
        staged = _program("slice", length, lambda: _build_slice(length))(array, start)
        if ml is not None:
            ml.register(staged, op="resplit.tile", site="resplit.tile")
        if donate and i == plan.n_tiles - 1:
            # every byte has been sliced out — free the source NOW, before
            # the last transfer, so peak memory never holds src + dst + tile
            try:
                array.delete()
            except Exception:
                pass
            if ml is not None:
                ml.consume(array)
        tile = _quiet(_program("move", length, _build_move), staged)
        if ml is not None:
            # consumed only AFTER the donating program ran (the monolithic
            # path's rule): an OOM inside the move must still find the
            # in-flight staged tile in the dump.  The ledger briefly holds
            # both tile stages — still within budget + one tile whenever a
            # tile fits the budget (the floor-at-one-slice case overcounts
            # transiently; the RSS gate owns that bound physically).
            ml.consume(staged)
            ml.register(tile, op="resplit.tile", site="resplit.tile")
        prev = out
        out = _quiet(_program("update", length, _build_update), prev, tile, start)
        if ml is not None:
            ml.consume(tile)  # donated into (and consumed by) the update
            # the accumulator was donated and aliases in place: move the
            # entry to the new handle without double-counting the buffer
            ml.transfer(prev, out, op="resplit.init")
        if _hlth.active_deadline() is not None:
            # deadline armed: await this tile under the watchdog so a hung
            # transfer raises CollectiveTimeoutError at the offending tile
            # (guarded + only reachable under an active deadline, which is
            # what HT107 wants — the rule's lexical with-block heuristic
            # cannot see the dynamic check one line up)
            _hlth.guard_blocking(
                lambda: jax.block_until_ready(out),  # heatlint: disable=HT107 — runs only under an armed deadline, via guard_blocking
                "comm.resplit.tile",
            )
    return out


# the memory ledger may have been env-armed (HEAT_TPU_MEMLEDGER=1) while
# this module was still importing — re-read the flag now (defensive
# module-bottom re-arm, the established hot-path-hook pattern)
import sys as _sys  # noqa: E402

_ml = _sys.modules.get("heat_tpu.utils.memledger")
if _ml is not None and getattr(_ml, "enabled", lambda: False)():
    _MEMLEDGER = _ml
del _sys, _ml
