"""N-process SPMD dryrun + supervising launcher (elastic runtime tier).

The reference's defining property is N-process SPMD (``mpirun -n N``,
SURVEY §4); single-controller JAX hides that tier.  This script stands it
up for real: **n_proc processes × devs_per_proc CPU devices** under
``jax.distributed`` (gloo collectives) — default 2×4, round-5 adds 4×2 —
exercising the paths that implicitly assumed all shards addressable:

- factories + binary ops + reductions on a global mesh spanning processes
- ``resplit_`` across the process boundary
- per-process hyperslab ``save_hdf5``/``load_hdf5`` (token-ring writes)
- ``numpy()`` / ``__repr__`` of a sharded array from ALL processes
- one ``DataParallel`` train step with cross-process gradient psum
- ring attention / MoE all_to_all / pipeline ppermute over the seam
- ``Communication.rank`` / ``n_processes`` semantics

The launcher is a **supervisor** (``heat_tpu.parallel.supervisor``, loaded
standalone so this process never imports jax): every worker writes a
heartbeat beacon; on any rank's death or stall the remaining world is
stack-dumped and killed, the coordinator is rebuilt on a fresh port, and
all ranks are relaunched with ``HEAT_TPU_RESTART_EPOCH`` incremented — up
to ``MPDRYRUN_RESTARTS`` times, after which a merged diagnostic report is
printed and the run fails.

``MPDRYRUN_MODE=train`` swaps the dryrun worker for a DASO training loop
(the kill-and-resume chaos scenario): train to ``MPDRYRUN_TARGET_STEPS``
with ``checkpoint_every=MPDRYRUN_CKPT_EVERY``; on a restart epoch the
worker resumes from the newest verified checkpoint and prints a
``RESUMED epoch=K step=N`` marker.  Arm ``MPDRYRUN_FAULT_RANK`` +
``MPDRYRUN_FAULT_SPEC`` (e.g. ``proc.exit:exit=5``) to SIGKILL one rank
deterministically — epoch 0 only, so the restarted world survives.

``MPDRYRUN_MODE=serve`` runs the elastic serving scenario (ISSUE 10):
every rank runs the IDENTICAL multi-tenant scheduler
(``heat_tpu.parallel.scheduler``) over ``MPDRYRUN_JOBS`` mixed jobs
(matmul / solve / KMeans / NN-forward, three tenants, mixed priorities)
against a ``MPDRYRUN_QUEUE``-bounded queue — overflow is shed with
``JobRejected``, never buffered.  Rank 0 journals every job transition
into ``{telemetry}/sched_journal.jsonl``; on a restart epoch every rank
replays that journal and requeues the accepted-but-unfinished jobs
exactly once (``SCHED-RECOVERED requeued=R``), so a rank SIGKILLed
mid-queue (``sched.dispatch:exit=N``) loses ZERO accepted jobs.  The
launcher prints the journal-derived attestation
``SCHED jobs=N done=K requeued=R shed=S failed=F lost=L`` plus the
per-tenant SLO table, and the supervisor report carries the per-generation
``jobs`` section.

``MPDRYRUN_MODE=fed`` runs the federated multi-world scenario (ISSUE 17):
the launcher stands up an HTTP ingress (``utils/monitor.py`` + a
standalone-loaded ``parallel/federation.py`` — still no jax in this
process), POSTs ``MPDRYRUN_JOBS`` jobs to ``/submit`` (plus one job shed
``mem_infeasible`` at the edge, HTTP 429), dispatches them across TWO
supervised worlds, SIGKILLs every rank of world w1 mid-queue
(``sched.dispatch:exit=2``, restart budget 0), quarantines it, steals its
unfinished jobs onto a resized w0, and proves zero loss with the
journal-derived ``FED worlds=2 lost=0`` attestation.

Run:  python scripts/multiprocess_dryrun.py                    (launcher, 2×4)
      MPDRYRUN_NPROC=4 MPDRYRUN_DEVS=2 python scripts/multiprocess_dryrun.py
      python scripts/multiprocess_dryrun.py WORKER_ID          (internal)

The launcher exits 0 iff every worker completes every check (in its final
generation).

``launch_pytest`` is the second tier (VERDICT r4 weak #6): it runs the
REAL test suite's ``-m mp`` subset inside the same n-process context —
every process executes the identical pytest selection SPMD-style, with a
shared tmp dir so file round-trips cross the process seam.
"""

from __future__ import annotations

import importlib.util
import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
N_PROC = 2
DEVS_PER_PROC = 4
MARKER = "MPDRYRUN-OK"
TRAIN_MARKER = "TRAIN-OK"


PASS_MARKER = "MULTIPROCESS DRYRUN: PASS"


def _load_standalone(modname: str, relpath: str):
    """Load a stdlib-only heat_tpu module (supervisor, telemetry) WITHOUT
    importing the package — the launcher process must never pay (or
    require) the jax import that ``import heat_tpu`` triggers."""
    if modname in sys.modules:
        return sys.modules[modname]
    spec = importlib.util.spec_from_file_location(
        modname, os.path.join(REPO, relpath)
    )
    mod = importlib.util.module_from_spec(spec)
    # registered BEFORE exec: dataclasses (supervisor.SupervisorResult)
    # resolve their defining module through sys.modules
    sys.modules[modname] = mod
    spec.loader.exec_module(mod)
    return mod


def _supervisor_mod():
    return _load_standalone("heat_supervisor", "heat_tpu/parallel/supervisor.py")


# launcher-side watchdog accounting (satellite of the elastic-runtime PR:
# the old code DROPPED _dump_stacks_then_kill's return value, so silent
# kills were invisible) — folded into the merged telemetry report by main()
_WATCHDOG = {"dumps": 0, "kills": 0}


def launch(timeout: float = 540.0, n_proc: int = 2, devs_per_proc: int = 4,
           mode: str = "dryrun", extra_env: dict = None):
    """Run the launcher as a subprocess with the scrub every caller needs
    (XLA_FLAGS stripped so workers pick their own device count) — THE ONE
    place the launch contract lives; the dryrun tier, the chaos lane and
    the pytest lane all call this.  Success iff ``returncode == 0`` and
    ``PASS_MARKER`` in stdout."""
    import subprocess as sp

    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["MPDRYRUN_NPROC"] = str(n_proc)
    env["MPDRYRUN_DEVS"] = str(devs_per_proc)
    env["MPDRYRUN_MODE"] = mode
    if extra_env:
        env.update({k: str(v) for k, v in extra_env.items()})
    return sp.run(
        [sys.executable, os.path.abspath(__file__)],
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=REPO,
    )


def launch_pytest(timeout: float = 1500.0, n_proc: int = 2,
                  devs_per_proc: int = 4, marker: str = "mp and not mp_unsafe",
                  extra_args: tuple = ()):
    """Run the real suite's ``-m {marker}`` subset in an n-process SPMD
    context: every process runs the IDENTICAL pytest selection (pytest's
    collection order is deterministic), so the collectives inside the
    tests line up across processes; ``tmp_path`` is redirected to a shared
    per-test directory (see tests/conftest.py) so IO round-trips exercise
    the token-ring writers across the seam.  Returns the list of completed
    processes (one per rank); success = every returncode 0."""
    import tempfile
    import time

    port = _free_port()
    tmpdir = tempfile.mkdtemp(prefix="mppytest_")
    procs, logs = [], []
    for pid in range(n_proc):
        env = {k: v for k, v in os.environ.items()
               if k not in ("XLA_FLAGS", "PYTHONPATH")}
        env["HEAT_MP_COORD"] = f"{n_proc}:{pid}:{port}:{devs_per_proc}"
        env["HEAT_MP_TMP"] = tmpdir
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONUNBUFFERED"] = "1"
        # rank self-watchdog (see tests/conftest.py): dump stacks + exit
        # shortly BEFORE this launcher's own deadline, so a wedged
        # collective yields tracebacks in the rank log, not a silent kill
        env.setdefault("HEAT_MP_WATCHDOG", str(max(60, int(timeout) - 60)))
        # stream to files (not PIPE): a wedged rank's progress stays
        # inspectable mid-run, and full buffers can't deadlock the child
        log = open(os.path.join(tmpdir, f"rank{pid}.log"), "w+b")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "pytest", "-m", marker, "-q",
             "-p", "no:cacheprovider", *extra_args, "tests/"],
            env=env, cwd=REPO, stdout=log, stderr=subprocess.STDOUT,
        ))
    print(f"launch_pytest: logs under {tmpdir}", flush=True)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        codes = [p.poll() for p in procs]
        if all(c is not None for c in codes):
            break
        if any(c is not None and c != 0 for c in codes):
            break  # one rank failed: peers will wedge on its collectives
        time.sleep(0.5)
    if _dump_stacks_then_kill(procs):
        # visible in THIS launcher's output too (the merged-telemetry
        # accounting lives in main(); launch_pytest has no merge step)
        print(
            f"launch_pytest watchdog: dumps={_WATCHDOG['dumps']} "
            f"kills={_WATCHDOG['kills']}",
            flush=True,
        )
    results = []
    for p, log in zip(procs, logs):
        if p.poll() is None:
            p.wait()
        log.seek(0)
        results.append((p.returncode, log.read().decode(errors="replace")))
        log.close()
    return results


# ---------------------------------------------------------------------- #
# known-flake retry harness (gloo `op.preamble.length` SIGABRT)
# ---------------------------------------------------------------------- #
# Documented pre-existing flake class (PR 7 notes; stash-verified on the
# unmodified HEAD in PR 11): gloo's socket preamble read occasionally
# trips its `op.preamble.length <= ...` assertion and SIGABRTs BOTH ranks
# of a 2-proc world during rapid small-collective streams — an
# environmental transport wedge, not a product failure.  The harness
# below retries EXACTLY ONCE and ONLY when that signature is present:
# a failure without the signature (or a second signatured failure in a
# row) is real and propagates, so a red chaos lane means something again.

GLOO_PREAMBLE_MARKERS = ("op.preamble.length",)
FLAKE_RETRY_MARKER = "KNOWN-FLAKE-RETRY gloo-preamble"


def is_known_gloo_preamble_flake(output: str) -> bool:
    """True iff ``output`` carries the documented gloo preamble-assertion
    signature.  Deliberately narrow: only the assertion text itself —
    a generic SIGABRT or timeout does NOT qualify."""
    return any(m in (output or "") for m in GLOO_PREAMBLE_MARKERS)


def launch_retrying_known_flake(**kwargs):
    """:func:`launch`, retried once iff the run failed WITH the gloo
    preamble signature.  Returns the final CompletedProcess; the retry is
    announced on stdout so CI logs show it happened."""
    proc = launch(**kwargs)
    failed = proc.returncode != 0 or PASS_MARKER not in (proc.stdout or "")
    if failed and is_known_gloo_preamble_flake(
        (proc.stdout or "") + (proc.stderr or "")
    ):
        print(f"{FLAKE_RETRY_MARKER} attempt=2", flush=True)
        proc = launch(**kwargs)
    return proc


def launch_pytest_retrying_known_flake(**kwargs):
    """:func:`launch_pytest`, retried once iff some rank failed WITH the
    gloo preamble signature in its log (a rank failing without it is a
    real failure and propagates immediately)."""
    results = launch_pytest(**kwargs)
    failed = [(rc, out) for rc, out in results if rc != 0]
    # ANY failed rank with the signature qualifies: the preamble SIGABRT
    # wedges the peer, whose own log then shows only the watchdog kill
    if failed and any(is_known_gloo_preamble_flake(out) for _rc, out in failed):
        print(f"{FLAKE_RETRY_MARKER} attempt=2", flush=True)
        results = launch_pytest(**kwargs)
    return results


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _dump_stacks_then_kill(procs, grace: float = 3.0) -> bool:
    """Watchdog teardown for wedged workers — delegates to the reusable
    ``heat_tpu.parallel.supervisor.dump_stacks_then_kill`` and ACCOUNTS the
    result in the module-level ``_WATCHDOG`` counters (``watchdog.dumps`` /
    ``watchdog.kills``), which ``main()`` folds into the merged telemetry
    report: a silent kill is now a visible counter post-hoc, not a dropped
    return value.  Returns True iff any process had to be reaped."""
    d = _supervisor_mod().dump_stacks_then_kill(procs, grace=grace)
    _WATCHDOG["dumps"] += d["dumps"]
    _WATCHDOG["kills"] += d["kills"]
    return d["dumps"] > 0


# ---------------------------------------------------------------------- #
# worker
# ---------------------------------------------------------------------- #
class _NullHeartbeat:
    """Stands in when no heartbeat dir is configured (standalone worker
    runs outside the supervising launcher)."""

    def beat(self, step=None, **kw) -> None:
        pass


def _make_heartbeat(pid: int):
    hb_dir = os.environ.get("MPDRYRUN_HB")
    if not hb_dir:
        return _NullHeartbeat()
    from heat_tpu.utils import health

    return health.Heartbeat(os.path.join(hb_dir, f"rank{pid}.json"))


def worker(pid: int, port: int, tmpdir: str) -> None:
    # watchdog (robustness tier): a wedged collective must dump stacks and
    # die, not hang the suite.  SIGUSR1 lets the launcher demand a stack
    # dump from a live-but-stuck worker; dump_traceback_later(exit=True) is
    # the self-watchdog — when a collective never completes, every thread's
    # stack goes to stderr and the process exits, unwedging the peers' poll
    # loop instead of riding out the full outer timeout.
    import faulthandler
    import signal

    faulthandler.register(signal.SIGUSR1)
    faulthandler.dump_traceback_later(
        float(os.environ.get("MPDRYRUN_WATCHDOG", "450")), exit=True
    )
    n_proc = int(os.environ.get("MPDRYRUN_NPROC", N_PROC))
    devs = int(os.environ.get("MPDRYRUN_DEVS", DEVS_PER_PROC))
    os.environ["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={devs}"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    # jax.distributed must initialize before ANY backend touch — importing
    # heat_tpu resolves the default device, so initialize first
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}", num_processes=n_proc, process_id=pid
    )
    sys.path.insert(0, REPO)

    import numpy as np

    import heat_tpu as ht

    ht.core.bootstrap.init_distributed(num_processes=n_proc, process_id=pid)
    comm = ht.communication.get_comm()
    # heartbeat beacon (elastic runtime): one beat per completed section —
    # the supervising launcher watches staleness and restarts a wedged world
    hb = _make_heartbeat(pid)
    # ---- rank/n_processes semantics --------------------------------- #
    assert comm.n_processes == n_proc, comm.n_processes
    assert comm.rank == pid, (comm.rank, pid)
    assert comm.size == n_proc * devs, comm.size
    hb.beat()
    print(f"[{pid}] comm: size={comm.size} rank={comm.rank}/{comm.n_processes}", flush=True)

    # ---- factories + binary ops + reduce ---------------------------- #
    n = 101  # ragged on 8 shards
    x = ht.arange(n, dtype=ht.float32, split=0)
    y = ht.ones(n, dtype=ht.float32, split=0)
    z = x * 2.0 + y
    total = float(z.sum().numpy())
    want = float(np.sum(np.arange(n, dtype=np.float32) * 2.0 + 1.0))
    assert total == want, (total, want)
    assert not z._jarray.is_fully_addressable  # genuinely cross-process
    hb.beat()
    print(f"[{pid}] factories/binary/reduce: OK ({total})", flush=True)

    # ---- numpy() / __repr__ from both processes --------------------- #
    full = z.numpy()
    np.testing.assert_allclose(full, np.arange(n, dtype=np.float32) * 2.0 + 1.0)
    r = repr(ht.reshape(ht.arange(64, dtype=ht.float32, split=0), (8, 8)))
    assert "DNDarray" in r and "split=0" in r, r[:80]
    hb.beat()
    print(f"[{pid}] numpy()/repr: OK", flush=True)

    # ---- resplit_ across the process boundary ----------------------- #
    m = ht.reshape(ht.arange(64, dtype=ht.float32, split=0), (8, 8))
    m2 = ht.resplit(m, 1)
    assert m2.split == 1
    np.testing.assert_allclose(m2.numpy(), np.arange(64, dtype=np.float32).reshape(8, 8))
    hb.beat()
    print(f"[{pid}] resplit_: OK", flush=True)

    # ---- budgeted (tiled) resplit across the process boundary -------- #
    # ISSUE 6: the chunked pipeline's per-tile jit programs (slice → tiled
    # all-to-all → in-place update) are ordinary SPMD computations, so the
    # memory-bounded path must work VERBATIM over a real process seam —
    # every rank stages the identical K tiles in the identical order
    from heat_tpu.core import redistribution as _rd
    from heat_tpu.utils import profiler as _prof

    p = comm.size
    bshape = (p, 5, p)
    per_slice = p * p * 4  # f32 bytes of one tiling-axis slice
    plan = _rd.plan_resplit(bshape, 4, 0, 2, p, 2 * per_slice)
    assert plan.n_tiles == 3 and plan.tile_axis == 1, plan
    big = ht.reshape(ht.arange(p * 5 * p, dtype=ht.float32, split=0), bshape)
    ref = big.resplit(2)  # monolithic oracle
    _prof.reset_counters()
    got = big.resplit(2, memory_budget=2 * per_slice)
    ctrs = _prof.counters()
    assert ctrs.get("comm.resplit.tiles", 0) == plan.n_tiles, ctrs
    assert got.split == 2
    np.testing.assert_allclose(got.numpy(), ref.numpy())
    # in-place donating variant over the seam too
    big.resplit_(2, memory_budget=2 * per_slice)
    np.testing.assert_allclose(big.numpy(), ref.numpy())
    hb.beat()
    print(f"[{pid}] RESPLIT-BUDGETED tiles={plan.n_tiles}", flush=True)

    # ---- per-process hyperslab HDF5 write + read -------------------- #
    try:
        import h5py  # noqa: F401

        has_h5 = True
    except ImportError:
        has_h5 = False
    if has_h5:
        path = os.path.join(tmpdir, "mp.h5")
        data = ht.reshape(ht.arange(96, dtype=ht.float32, split=0), (24, 4))
        ht.save_hdf5(data, path, "d")
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("mpdryrun:h5-written")
        back = ht.load_hdf5(path, "d", dtype=ht.float32, split=0)
        assert not back._jarray.is_fully_addressable
        np.testing.assert_allclose(back.numpy(), data.numpy())
        # replicated (split=None) save: regression for the rank-0-only write
        # deadlocking on the collective host fetch
        rep = ht.resplit(data, None)
        ht.save_hdf5(rep, os.path.join(tmpdir, "mp_rep.h5"), "d")
        multihost_utils.sync_global_devices("mpdryrun:h5-rep-written")
        back2 = ht.load_hdf5(os.path.join(tmpdir, "mp_rep.h5"), "d", dtype=ht.float32)
        np.testing.assert_allclose(back2.numpy(), data.numpy())
        # RAGGED extent (101 rows on 8 devices): the per-process slab must
        # follow the per-DEVICE padded grid, not ceil-over-processes
        ragged = ht.arange(101, dtype=ht.float32, split=0)
        ht.save_hdf5(ht.reshape(ragged, (101, 1)), os.path.join(tmpdir, "mp_rag.h5"), "d")
        multihost_utils.sync_global_devices("mpdryrun:h5-rag-written")
        back3 = ht.load_hdf5(os.path.join(tmpdir, "mp_rag.h5"), "d", dtype=ht.float32, split=0)
        assert back3.shape == (101, 1) and back3._pad == 3
        np.testing.assert_allclose(back3.numpy().ravel(), np.arange(101, dtype=np.float32))
        hb.beat()
        print(f"[{pid}] hdf5 hyperslab save/load: OK", flush=True)
    else:  # pragma: no cover
        print(f"[{pid}] hdf5 hyperslab save/load: SKIP (no h5py)", flush=True)

    # ---- one DataParallel step -------------------------------------- #
    model = ht.nn.Sequential(ht.nn.Linear(16, 8), ht.nn.ReLU(), ht.nn.Linear(8, 2))
    opt = ht.optim.DataParallelOptimizer("sgd", lr=0.1)
    dp = ht.nn.DataParallel(model, optimizer=opt)
    params = dp.init(jax.random.key(0))
    state = opt.init_state(params)
    step = dp.make_train_step(ht.nn.functional.cross_entropy)
    rng = np.random.default_rng(0)  # same data on every process (SPMD)
    xb = ht.array(rng.standard_normal((32, 16)).astype(np.float32), split=0)
    yb = ht.array(rng.integers(0, 2, 32).astype(np.int32), split=0)
    params, state, loss = step(params, state, xb._jarray, yb._jarray)
    # post-step params identical on every process and every device
    w = params[0]["weight"]
    wl = comm.host_fetch(w)
    digest = float(np.sum(wl * wl))
    from jax.experimental import multihost_utils

    digests = np.asarray(multihost_utils.process_allgather(np.asarray([digest])))
    assert np.all(digests == digests[0]), digests
    hb.beat()
    print(f"[{pid}] DataParallel step: OK (loss={float(loss):.4f})", flush=True)

    # ---- ring attention across the process boundary ------------------ #
    # the ring's ppermute crosses the 2-process seam every rotation — this
    # is the long-context path running over real inter-process transport
    # (gloo standing in for DCN), not just intra-process device lanes
    import jax.numpy as jnp

    from heat_tpu.parallel.ring_attention import _global_attention, ring_attention

    rng2 = np.random.default_rng(7)  # same operands on every process (SPMD)
    S, d = 37, 8  # ragged on 8 shards
    q = jnp.asarray(rng2.standard_normal((2, S, d)), jnp.float32)
    k = jnp.asarray(rng2.standard_normal((2, S, d)), jnp.float32)
    v = jnp.asarray(rng2.standard_normal((2, S, d)), jnp.float32)
    out = ring_attention(
        comm.shard(q, 1), comm.shard(k, 1), comm.shard(v, 1), comm, causal=True
    )
    assert not out.is_fully_addressable  # spans both processes
    got = comm.host_fetch(out)
    ref = np.asarray(_global_attention(q, k, v, True, d**-0.5))
    np.testing.assert_allclose(got, ref, atol=2e-5)
    hb.beat()
    print(f"[{pid}] ring attention (cross-process ppermute): OK", flush=True)

    # ---- expert parallelism across the process boundary --------------- #
    # the MoE's two all_to_alls move tokens between experts owned by
    # DIFFERENT processes (round-4d) — EP data movement over the seam
    moe = ht.nn.MoE(8, 2 * comm.size, hidden_dim=16, top_k=2,
                    capacity_factor=8.0, comm=comm)
    dense = ht.nn.MoE(8, 2 * comm.size, hidden_dim=16, top_k=2,
                      capacity_factor=8.0)
    mp_ = moe.init(jax.random.key(11))
    xm = jnp.asarray(np.random.default_rng(8).standard_normal((comm.size, 3, 8)),
                     jnp.float32)
    ym = moe.apply(mp_, xm)
    assert not ym.is_fully_addressable  # EP really crossed the seam (no dense fallback)
    np.testing.assert_allclose(
        comm.host_fetch(ym), np.asarray(dense.apply(mp_, xm)), atol=2e-5
    )
    hb.beat()
    print(f"[{pid}] MoE expert parallelism (cross-process all_to_all): OK", flush=True)

    # ---- pipeline parallelism across the process boundary ------------- #
    # stage weights sharded over devices of BOTH processes; activations
    # cross the seam on ppermute every tick
    blk = ht.nn.Linear(8, 8)
    pipe = ht.nn.Pipelined(blk, depth=comm.size, comm=comm, n_microbatches=2)
    seq = ht.nn.Pipelined(blk, depth=comm.size, comm=None)
    pp_ = pipe.init(jax.random.key(12))
    xp = jnp.asarray(np.random.default_rng(9).standard_normal((4, 8)), jnp.float32)
    yp = pipe.apply(pp_, xp)
    np.testing.assert_allclose(
        comm.host_fetch(yp), np.asarray(seq.apply(pp_, xp)), atol=2e-5
    )
    hb.beat()
    print(f"[{pid}] pipeline stages (cross-process ppermute): OK", flush=True)

    # ---- runtime metadata sanitizer across the process seam ----------- #
    # HEAT_TPU_CHECKS tier: arm the metadata-only validator (dispatch tails
    # + factory/resplit boundaries) on a REAL multi-process mesh, then
    # assert cross-rank metadata agreement — a rank whose (gshape, split,
    # dtype, pad) diverged would stage different collectives and deadlock
    # its peers, so the digest comparison itself is the canary
    from heat_tpu.core import sanitation

    checks_were_on = sanitation.checks_enabled()  # e.g. env-armed HEAT_TPU_CHECKS=1
    sanitation.enable_checks()
    try:
        chk = ht.arange(48, dtype=ht.float32, split=0) * 2.0  # validated at the tail
        sanitation.assert_cross_rank_consistent(chk, tag="mpdryrun.dispatch")
        chk2 = ht.resplit(ht.reshape(chk, (8, 6)), 1)  # validated at the boundary
        sanitation.assert_cross_rank_consistent(chk2, tag="mpdryrun.resplit")
        rag = ht.arange(101, dtype=ht.float32, split=0) + 1.0  # pad metadata agrees too
        sanitation.assert_cross_rank_consistent(rag, tag="mpdryrun.ragged")
    finally:
        # restore rather than disarm: an env-armed worker keeps validating
        # the rest of its checks
        if not checks_were_on:
            sanitation.disable_checks()
    hb.beat()
    print(f"[{pid}] SANITIZER-OK (cross-rank metadata agreement)", flush=True)

    # ---- telemetry per-rank export ----------------------------------- #
    # every rank flushes its span/counter/histogram state to a shared dir;
    # the launcher merges rank0+rank1+... with scripts/telemetry_report.py
    # — the multi-rank observability story running over a REAL process seam
    from heat_tpu.utils import telemetry

    telemetry.enable()
    with telemetry.span("mpdryrun.telemetry_check", rank=pid):
        _ = (x * 3.0).sum().numpy()
    rep = telemetry.report()
    assert rep["counters"].get("comm.resplit.calls", 0) >= 1, rep["counters"]
    assert rep["rank"] == pid, (rep["rank"], pid)
    tpath = telemetry.flush(os.path.join(tmpdir, "telemetry"))
    assert tpath and tpath.endswith(f"rank{pid}.jsonl"), tpath
    hb.beat()
    print(f"[{pid}] telemetry: rank file exported", flush=True)

    # ---- live observability endpoint (ISSUE 11) ----------------------- #
    # rank 0 arms the /metrics + /healthz monitor and scrapes its OWN
    # endpoint over a real localhost socket MID-RUN (the world is still
    # live): the payload must be non-empty Prometheus text carrying the
    # comm.* byte accounting, and /healthz must read every beacon fresh
    if pid == 0:
        import json as _json
        import urllib.request

        from heat_tpu.utils import monitor

        mhost, mport = monitor.enable(
            heartbeat_dir=os.environ.get("MPDRYRUN_HB") or None
        )
        with urllib.request.urlopen(
            f"http://{mhost}:{mport}/metrics", timeout=15
        ) as resp:
            payload = resp.read().decode()
        assert "comm_resplit_calls" in payload, payload[:500]
        n_metrics = sum(
            1 for ln in payload.splitlines() if ln and not ln.startswith("#")
        )
        with urllib.request.urlopen(
            f"http://{mhost}:{mport}/healthz", timeout=15
        ) as resp:
            hz = _json.loads(resp.read().decode())
        assert hz.get("ok") is True, hz
        monitor.disable()
        print(f"[{pid}] MONITOR-SCRAPED metrics={n_metrics} healthz=ok", flush=True)
    hb.beat()

    # ---- flight recorder (ISSUE 7) ----------------------------------- #
    # env-armed (HEAT_TPU_FLIGHTREC_DIR, exported by the launcher) at
    # heat_tpu import: every staged collective above was seq-stamped into
    # this rank's crash-durable ring; print the seq so the launcher-side
    # post-mortem has a cross-check, and so tests can assert the recorder
    # really ran on every rank
    from heat_tpu.utils import flightrec

    if flightrec.enabled():
        last = flightrec.last_collective()
        assert last is not None, "flight recorder armed but no collective stamped"
        print(f"[{pid}] FLIGHTREC seq={last[0]} op={last[1]}", flush=True)

    # ---- device-memory ledger (env-armed HEAT_TPU_MEMLEDGER=1) -------- #
    # every buffer minted at the choke points above was registered with
    # provenance; dump the final watermark + top buffers into the flight
    # ring (the telemetry_report memory section reads them back) and print
    # the greppable per-rank peak asserted by tests/test_multiprocess.py
    from heat_tpu.utils import memledger

    if memledger.enabled():
        assert memledger.peak_bytes() > 0, "ledger armed but nothing registered"
        memledger.dump_to_ring()
        print(
            f"[{pid}] MEM-PEAK rank={pid} bytes={memledger.peak_bytes()}",
            flush=True,
        )
    hb.beat()

    print(f"[{pid}] {MARKER}", flush=True)
    faulthandler.cancel_dump_traceback_later()
    ht.core.bootstrap.finalize_distributed()


# ---------------------------------------------------------------------- #
# postmortem worker (MPDRYRUN_MODE=postmortem): the flight-recorder chaos
# scenarios — a deterministic collective loop with injectable hang/desync
# ---------------------------------------------------------------------- #
def postmortem_worker(pid: int, port: int, tmpdir: str) -> None:
    """Deterministic seq-stamped collective stream for the post-mortem
    chaos scenarios (ISSUE 7 acceptance).

    Every rank stages the IDENTICAL loop of ``MPDRYRUN_PM_ITERS`` resplit
    flips — exactly one accounted collective per iteration, with NO host
    sync, so staging stays async and the surviving ranks keep staging past
    a wedged peer.  Two injectable failures at iteration
    ``MPDRYRUN_CHAOS_AT``:

    - ``MPDRYRUN_HANG_RANK=k``: rank k arms a ``comm.collective`` hang and
      stages one more flip — the stamp lands in the ring FIRST, so the
      rank's last record is exactly the collective it hung on (printed as
      ``PM-HANG expect_seq=N`` for the test's cross-check); peers finish
      the loop, so the analyzer names rank k as the straggler at seq N.
    - ``MPDRYRUN_DESYNC_RANK=k``: rank k stages one EXTRA collective its
      peers never post (the classic rank-conditional SPMD divergence) —
      from ``PM-DESYNC expect_seq=N`` on, rank k's fingerprint stream is
      shifted, and the analyzer must name seq N as the first divergence.

    After a chaos injection every rank PARKS (no beats, no teardown): the
    supervisor's heartbeat-staleness monitor is what must notice, tear the
    world down, and run the analyzer on the harvested rings."""
    import faulthandler
    import signal
    import time

    # pre-beat the beacon by mtime BEFORE the heavy bring-up imports: the
    # chaos tests run with a short MPDRYRUN_HB_TIMEOUT so post-hang
    # detection is fast, and jax + gloo bring-up alone can exceed it
    hb_dir = os.environ.get("MPDRYRUN_HB")
    if hb_dir:
        os.makedirs(hb_dir, exist_ok=True)
        with open(os.path.join(hb_dir, f"rank{pid}.json"), "a"):
            pass
    faulthandler.register(signal.SIGUSR1)
    faulthandler.dump_traceback_later(
        float(os.environ.get("MPDRYRUN_WATCHDOG", "450")), exit=True
    )
    n_proc = int(os.environ.get("MPDRYRUN_NPROC", N_PROC))
    devs = int(os.environ.get("MPDRYRUN_DEVS", DEVS_PER_PROC))
    os.environ["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={devs}"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}", num_processes=n_proc, process_id=pid
    )
    sys.path.insert(0, REPO)

    import heat_tpu as ht

    ht.core.bootstrap.init_distributed(num_processes=n_proc, process_id=pid)
    comm = ht.communication.get_comm()
    hb = _make_heartbeat(pid)
    hb.beat(status="bring-up")
    from heat_tpu.utils import faults, flightrec

    assert flightrec.enabled(), "postmortem mode needs HEAT_TPU_FLIGHTREC_DIR"
    hang_rank = int(os.environ.get("MPDRYRUN_HANG_RANK", "-1"))
    desync_rank = int(os.environ.get("MPDRYRUN_DESYNC_RANK", "-1"))
    chaos_at = int(os.environ.get("MPDRYRUN_CHAOS_AT", "3"))
    n_iters = int(os.environ.get("MPDRYRUN_PM_ITERS", "6"))
    chaos = hang_rank >= 0 or desync_rank >= 0

    m = ht.reshape(
        ht.arange(comm.size * comm.size, dtype=ht.float32, split=0),
        (comm.size, comm.size),
    )
    last = flightrec.last_collective()
    seq0 = last[0] if last else 0
    print(f"[{pid}] PM-LOOP start seq0={seq0}", flush=True)
    for i in range(n_iters):
        if pid == hang_rank and i == chaos_at:
            # the stamp is written before the fault site fires, so the
            # ring's last record IS the collective this rank hung on
            print(f"[{pid}] PM-HANG expect_seq={seq0 + i + 1}", flush=True)
            with faults.inject("comm.collective", hang=1):
                m = m.resplit(1 if m.split == 0 else 0)
            raise AssertionError("unreachable: staging was armed to hang")
        if pid == desync_rank and i == chaos_at:
            print(f"[{pid}] PM-DESYNC expect_seq={seq0 + i + 1}", flush=True)
            # the rank-conditional EXTRA collective: a different shape, so
            # the divergent fingerprint differs in op payload, not just order
            ht.arange(comm.size, dtype=ht.float32, split=0).resplit(None)
        m = m.resplit(1 if m.split == 0 else 0)
        hb.beat(step=i)
    last = flightrec.last_collective()
    print(f"[{pid}] FLIGHTREC seq={last[0]} op={last[1]}", flush=True)
    if chaos:
        # park: a clean teardown would need the wedged/diverged peers'
        # collectives.  Beats stop here on purpose — heartbeat staleness
        # is the signal the supervisor must convert into teardown+verdict.
        print(f"[{pid}] PM-PARK", flush=True)
        while True:
            time.sleep(60.0)
    print(f"[{pid}] {MARKER}", flush=True)
    faulthandler.cancel_dump_traceback_later()
    ht.core.bootstrap.finalize_distributed()


# ---------------------------------------------------------------------- #
# serve worker (MPDRYRUN_MODE=serve): the elastic multi-tenant serving
# scenario — a scheduler queue survives a SIGKILLed rank via journal replay
# ---------------------------------------------------------------------- #
SERVE_MARKER = "SERVE-OK"


def _serve_jobs(sched_mod, n_jobs: int, deadline_s: float):
    """The deterministic mixed job list — IDENTICAL on every rank (and on
    every generation), so the SPMD world schedules in lockstep.  Kinds
    rotate through the four serving workloads; tenants and priorities
    rotate so the admission/priority machinery sees real variety."""
    kinds = ("matmul", "solve", "kmeans", "nn_forward")
    tenants = ("acme", "globex", "initech")
    payloads = {
        "matmul": lambda i: {"n": 16, "seed": i},
        "solve": lambda i: {"n": 8},
        "kmeans": lambda i: {"n": 32, "k": 2, "seed": i % 3},
        "nn_forward": lambda i: {"batch": 4, "features": 8, "seed": i},
    }
    jobs = []
    for i in range(n_jobs):
        kind = kinds[i % len(kinds)]
        jobs.append(
            sched_mod.Job(
                f"job{i:03d}",
                kind,
                tenant=tenants[i % len(tenants)],
                priority=i % 3,
                deadline_s=deadline_s,
                retry_budget=1,
                payload=payloads[kind](i),
            )
        )
    return jobs


def serve_worker(pid: int, port: int, tmpdir: str) -> None:
    """Multi-tenant serving under the supervising launcher.

    Every rank runs the identical scheduler over the identical submissions
    (SPMD lockstep: divergent scheduling would stage divergent
    collectives).  Rank 0 journals; on ``HEAT_TPU_RESTART_EPOCH > 0``
    every rank replays rank 0's journal and requeues the
    accepted-but-unfinished jobs exactly once instead of resubmitting —
    a DONE job is never executed twice, an in-flight one is never lost.
    Arm ``MPDRYRUN_FAULT_RANK`` + ``MPDRYRUN_FAULT_SPEC=sched.dispatch:exit=N``
    to SIGKILL one rank at its Nth dispatch (epoch 0 only)."""
    import faulthandler
    import signal

    faulthandler.register(signal.SIGUSR1)
    faulthandler.dump_traceback_later(
        float(os.environ.get("MPDRYRUN_WATCHDOG", "450")), exit=True
    )
    n_proc = int(os.environ.get("MPDRYRUN_NPROC", N_PROC))
    devs = int(os.environ.get("MPDRYRUN_DEVS", DEVS_PER_PROC))
    os.environ["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={devs}"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}", num_processes=n_proc, process_id=pid
    )
    sys.path.insert(0, REPO)

    import heat_tpu as ht

    ht.core.bootstrap.init_distributed(num_processes=n_proc, process_id=pid)
    from heat_tpu.utils import telemetry

    telemetry.enable()
    comm = ht.communication.get_comm()
    hb = _make_heartbeat(pid)
    hb.beat(step=0, status="bring-up")

    from heat_tpu.parallel import scheduler as sched_mod
    from heat_tpu.parallel import serving

    n_jobs = int(os.environ.get("MPDRYRUN_JOBS", "20"))
    max_queue = int(os.environ.get("MPDRYRUN_QUEUE", "18"))
    deadline_s = float(os.environ.get("MPDRYRUN_JOB_DEADLINE", "300"))
    journal_path = os.path.join(tmpdir, "telemetry", "sched_journal.jsonl")
    epoch = ht.core.bootstrap.restart_epoch()
    # live observability endpoint (ISSUE 11): armed on rank 0 BEFORE the
    # scheduler is built, so the scheduler's queue-depth/tenant-inflight
    # gauge source registers with it; scraped after the drain below
    if pid == 0:
        from heat_tpu.utils import monitor

        monitor.enable(heartbeat_dir=os.environ.get("MPDRYRUN_HB") or None)
    sch = sched_mod.Scheduler(
        serving.make_executor(comm),
        max_queue=max_queue,
        max_batch=4,
        # only rank 0 writes (one journal per scheduler WORLD — the ranks
        # schedule in lockstep, so one rank's record stream is the truth);
        # every rank READS it on recovery
        journal=sched_mod.JobJournal(journal_path) if pid == 0 else None,
        batch_key=serving.batch_key,
    )
    # seq-stamped lockstep attestation: the serving dispatches are GSPMD
    # programs whose collectives live INSIDE jit (never staged through
    # Communication), so the flight-recorder ring would otherwise hold no
    # collective records and a green run could not read `clean`.  One
    # accounted resplit before and after the drain puts an identical
    # bracket in every rank's stream — rings then prove the ranks entered
    # and left the serving loop in lockstep.
    def _lockstep_stamp():
        ht.reshape(
            ht.arange(comm.size * comm.size, dtype=ht.float32, split=0),
            (comm.size, comm.size),
        ).resplit(1)

    _lockstep_stamp()
    requeued = 0
    if epoch > 0:
        requeued = sch.recover(journal_path)
        print(f"[{pid}] SCHED-RECOVERED epoch={epoch} requeued={requeued}", flush=True)
    else:
        for job in _serve_jobs(sched_mod, n_jobs, deadline_s):
            try:
                sch.submit(job)
            except sched_mod.JobRejected as e:
                # load shedding is an IMMEDIATE structured answer — the
                # submit loop keeps going, nothing blocks
                print(f"[{pid}] SCHED-SHED id={e.job_id} reason={e.reason}", flush=True)
    hb.beat(status="serving")
    rep = sch.run(beat=hb.beat)
    # scrape the live endpoint while the world is still up: the Prometheus
    # payload must be non-empty and its sched_* counters must reconcile
    # (offered = accepted + shed) — the serving plane's accounting
    # invariant, read straight off the wire format a Prometheus scraper
    # would see
    if pid == 0:
        import urllib.request

        from heat_tpu.utils import monitor

        mhost, mport = monitor.address()
        with urllib.request.urlopen(
            f"http://{mhost}:{mport}/metrics", timeout=15
        ) as resp:
            payload = resp.read().decode()
        vals = {}
        for ln in payload.splitlines():
            if ln.startswith("#") or "{" in ln or " " not in ln:
                continue
            k, _, v = ln.partition(" ")
            try:
                vals[k] = float(v)
            except ValueError:
                pass
        offered = int(vals.get("sched_offered", 0))
        accepted = int(vals.get("sched_accepted", 0))
        shed = int(vals.get("sched_shed", 0))
        assert offered == accepted + shed, (offered, accepted, shed, payload[:500])
        assert "sched_queue_depth" in vals, payload[:500]
        n_metrics = sum(
            1 for ln in payload.splitlines() if ln and not ln.startswith("#")
        )
        monitor.disable()
        print(
            f"[{pid}] MONITOR-SCRAPED metrics={n_metrics} "
            f"offered={offered} accepted={accepted} shed={shed} "
            "reconciled=True",
            flush=True,
        )
    _lockstep_stamp()
    done = rep["by_state"].get(sched_mod.DONE, 0)
    failed = rep["by_state"].get(sched_mod.FAILED, 0)
    shed = rep["by_state"].get(sched_mod.SHED, 0)
    print(
        f"[{pid}] {SERVE_MARKER} jobs={len(rep['jobs'])} done={done} "
        f"failed={failed} shed={shed} requeued={requeued} "
        f"reconciled={rep['reconciled']}",
        flush=True,
    )
    telemetry.flush(os.path.join(tmpdir, "telemetry"))
    print(f"[{pid}] telemetry: rank file exported", flush=True)
    print(f"[{pid}] {MARKER}", flush=True)
    faulthandler.cancel_dump_traceback_later()
    ht.core.bootstrap.finalize_distributed()


# ---------------------------------------------------------------------- #
# fed worker (MPDRYRUN_MODE=fed): one rank of one federated WORLD — runs
# the scheduler over the job slice the federator assigned to this world
# ---------------------------------------------------------------------- #
FED_SERVE_MARKER = "FEDSERVE-OK"


def fed_serve_worker(pid: int, port: int, tmpdir: str) -> None:
    """One rank of one federated world (ISSUE 17).

    Like :func:`serve_worker`, but the job list comes from the federator's
    assignment file (``MPDRYRUN_FED_JOBS``: the submit records
    ``Federation.assign`` journaled — trace ids already minted at the HTTP
    edge) and the scheduler journals into this WORLD's own journal
    (``MPDRYRUN_FED_JOURNAL``), which the federator later reconciles back
    into the federation journal.  ``HEAT_TPU_FED_PEAKS`` (set by the
    launcher) makes ``serving.make_executor`` record each batch's
    memledger peak per kind — the admission predictor's history."""
    import faulthandler
    import json as _json
    import signal

    faulthandler.register(signal.SIGUSR1)
    faulthandler.dump_traceback_later(
        float(os.environ.get("MPDRYRUN_WATCHDOG", "450")), exit=True
    )
    n_proc = int(os.environ.get("MPDRYRUN_NPROC", "1"))
    devs = int(os.environ.get("MPDRYRUN_DEVS", "2"))
    os.environ["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={devs}"
    world = os.environ.get("MPDRYRUN_FED_WORLD", "w?")
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}", num_processes=n_proc, process_id=pid
    )
    sys.path.insert(0, REPO)

    import heat_tpu as ht

    ht.core.bootstrap.init_distributed(num_processes=n_proc, process_id=pid)
    from heat_tpu.utils import telemetry

    telemetry.enable()
    comm = ht.communication.get_comm()
    hb = _make_heartbeat(pid)
    hb.beat(step=0, status="bring-up")

    from heat_tpu.parallel import scheduler as sched_mod
    from heat_tpu.parallel import serving

    with open(os.environ["MPDRYRUN_FED_JOBS"]) as fh:
        records = _json.load(fh)
    journal_path = os.environ["MPDRYRUN_FED_JOURNAL"]
    sch = sched_mod.Scheduler(
        serving.make_executor(comm),
        max_queue=max(len(records) + 2, 8),
        max_batch=4,
        # one journal per WORLD, written by its rank 0 (SPMD lockstep: one
        # rank's record stream is the world's truth)
        journal=sched_mod.JobJournal(journal_path) if pid == 0 else None,
        batch_key=serving.batch_key,
    )
    for rec in records:
        # from_record keeps the edge-minted trace id: the fed journal, this
        # world's journal and the flight rings correlate on the SAME id
        sch.submit(sched_mod.Job.from_record(rec))
    hb.beat(status="serving")
    rep = sch.run(beat=hb.beat)
    done = rep["by_state"].get(sched_mod.DONE, 0)
    failed = rep["by_state"].get(sched_mod.FAILED, 0)
    print(
        f"[{pid}] {FED_SERVE_MARKER} world={world} jobs={len(rep['jobs'])} "
        f"done={done} failed={failed}",
        flush=True,
    )
    telemetry.flush(os.path.join(tmpdir, "telemetry"))
    print(f"[{pid}] {MARKER}", flush=True)
    faulthandler.cancel_dump_traceback_later()
    ht.core.bootstrap.finalize_distributed()


# ---------------------------------------------------------------------- #
# train worker (MPDRYRUN_MODE=train): the kill-and-resume chaos scenario
# ---------------------------------------------------------------------- #
def train_worker(pid: int, port: int, tmpdir: str) -> None:
    """DASO training loop under the supervising launcher.

    Trains a small model to ``MPDRYRUN_TARGET_STEPS`` with
    ``checkpoint_every=MPDRYRUN_CKPT_EVERY`` auto-checkpoints into a dir
    SHARED across ranks and generations.  On ``HEAT_TPU_RESTART_EPOCH > 0``
    the worker resumes from the newest verified checkpoint (prints
    ``RESUMED epoch=K step=N``) — the full restart-with-resume loop: a
    rank SIGKILLed mid-training (fault site ``proc.exit``) costs at most
    ``checkpoint_every`` steps, not the run."""
    import faulthandler
    import signal
    import time

    faulthandler.register(signal.SIGUSR1)
    faulthandler.dump_traceback_later(
        float(os.environ.get("MPDRYRUN_WATCHDOG", "450")), exit=True
    )
    n_proc = int(os.environ.get("MPDRYRUN_NPROC", N_PROC))
    devs = int(os.environ.get("MPDRYRUN_DEVS", DEVS_PER_PROC))
    os.environ["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={devs}"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}", num_processes=n_proc, process_id=pid
    )
    sys.path.insert(0, REPO)

    import numpy as np
    import jax.numpy as jnp

    import heat_tpu as ht

    ht.core.bootstrap.init_distributed(num_processes=n_proc, process_id=pid)
    from heat_tpu.utils import telemetry

    telemetry.enable()
    comm = ht.communication.get_comm()
    hb = _make_heartbeat(pid)
    hb.beat(step=0, status="bring-up")

    target = int(os.environ.get("MPDRYRUN_TARGET_STEPS", "12"))
    ck_every = int(os.environ.get("MPDRYRUN_CKPT_EVERY", "3"))
    step_delay = float(os.environ.get("MPDRYRUN_STEP_DELAY", "0.05"))
    ckpt_dir = os.path.join(tmpdir, "daso_ckpt")

    model = ht.nn.Sequential(ht.nn.Linear(8, 4))
    loss_fn = lambda pred, y: jnp.mean((pred - y) ** 2)  # noqa: E731
    # fast axis = this host's devices, so the dcn tier crosses the process
    # seam (n_groups == n_proc) — the topology a real pod restart rebuilds
    daso = ht.optim.DASO(
        ht.optim.DataParallelOptimizer("sgd", lr=0.05),
        total_local_comm_size=devs,
        warmup_steps=1,
        global_skip=2,
        stale_steps=1,
        checkpoint_every=ck_every,
        checkpoint_dir=ckpt_dir,
    )
    daso.init(model, key=jax.random.key(0))
    epoch = ht.core.bootstrap.restart_epoch()
    if epoch > 0:
        resumed = daso.resume()
        print(
            f"[{pid}] RESUMED epoch={epoch} step={daso._step_count} ok={resumed}",
            flush=True,
        )

    # SPMD-identical batch, replicated onto the DASO mesh explicitly (a
    # host-local array is ambiguous under multi-process jit)
    from jax.sharding import NamedSharding, PartitionSpec as P

    rng = np.random.default_rng(0)
    xh = rng.standard_normal((4 * daso.n_groups * daso.ici_size, 8)).astype(np.float32)
    yh = rng.standard_normal((4 * daso.n_groups * daso.ici_size, 4)).astype(np.float32)
    from heat_tpu.core.communication import _array_from_callback

    rep = NamedSharding(daso.mesh, P())
    xg = _array_from_callback(xh, rep)
    yg = _array_from_callback(yh, rep)

    while daso._step_count < target:
        loss = daso.step(loss_fn, xg, yg)
        comm.Wait(loss)  # lockstep: the beat below attests a COMPLETED step
        hb.beat(step=daso._step_count)
        if step_delay:
            time.sleep(step_delay)  # widens the kill window deterministically
    print(f"[{pid}] {TRAIN_MARKER} steps={daso._step_count}", flush=True)
    telemetry.flush(os.path.join(tmpdir, "telemetry"))
    print(f"[{pid}] telemetry: rank file exported", flush=True)
    print(f"[{pid}] {MARKER}", flush=True)
    faulthandler.cancel_dump_traceback_later()
    ht.core.bootstrap.finalize_distributed()


# ---------------------------------------------------------------------- #
# fed launcher (MPDRYRUN_MODE=fed): the federated multi-world scenario —
# HTTP ingress, two supervised worlds, a SIGKILLed world mid-queue, work
# stealing, elastic resize, and the journal-derived FED lost=0 attestation
# ---------------------------------------------------------------------- #
def _http(method: str, url: str, payload: dict = None, timeout: float = 15.0):
    """(status, parsed-JSON body) — errors like 429/503 are ANSWERS here
    (the structured-backpressure contract under test), not exceptions."""
    import json as _json
    import urllib.error
    import urllib.request

    data = _json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    if data is not None:
        req.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            raw = resp.read().decode()
            code = resp.status
    except urllib.error.HTTPError as e:
        raw = e.read().decode()
        code = e.code
    try:
        return code, _json.loads(raw or "{}")
    except ValueError:
        return code, raw  # /metrics is Prometheus text, not JSON


def fed_main() -> int:
    """Two supervised worlds behind one HTTP ingress (ISSUE 17).

    Phase 1: 12 jobs POSTed to ``/submit`` (plus one ``giant`` job shed
    ``mem_infeasible`` at the edge — HTTP 429, structured), assigned
    across both worlds; world w1 is armed with ``sched.dispatch:exit=2``
    and ``restart_budget=0``, so every one of its ranks dies by SIGKILL
    mid-queue and the supervisor gives up — a whole WORLD lost.
    Phase 2: the federator reconciles both world journals, quarantines
    w1, steals its unfinished jobs, resizes w0 up, and re-serves them.
    The run passes iff the journal-replayed attestation reads
    ``FED worlds=2 lost=0`` and ``/result/<id>`` serves a stolen job's
    digest over HTTP."""
    import json as _json
    import tempfile
    import threading

    ok = True
    tmpdir = tempfile.mkdtemp(prefix="mpdryrun_fed_")
    n_jobs = int(os.environ.get("MPDRYRUN_JOBS", "12"))
    gen_deadline = float(os.environ.get("MPDRYRUN_DEADLINE", "420"))
    fed_dir = os.path.join(tmpdir, "fed")
    os.makedirs(fed_dir, exist_ok=True)
    peaks_path = os.path.join(fed_dir, "peaks.json")
    # seed the per-kind peak history: `giant` is KNOWN (recorded by a
    # previous serving generation, here pre-seeded) to peak at ~1 TiB —
    # no 8 GiB world can fit it, so admission must shed it at the edge
    with open(peaks_path, "w") as fh:
        _json.dump({"giant": 1 << 40}, fh)

    fed_mod = _load_standalone("heat_federation", "heat_tpu/parallel/federation.py")
    mon_mod = _load_standalone("heat_monitor", "heat_tpu/utils/monitor.py")
    sup_mod = _supervisor_mod()

    worlds = {}
    for name in ("w0", "w1"):
        wdir = os.path.join(tmpdir, name)
        worlds[name] = {
            "dir": wdir,
            "hb": os.path.join(wdir, "heartbeats"),
            "fr": os.path.join(wdir, "flightrec"),
            "journal": os.path.join(wdir, "telemetry", "sched_journal.jsonl"),
        }
        os.makedirs(worlds[name]["hb"], exist_ok=True)

    fed = fed_mod.Federation(
        os.path.join(fed_dir, "fed_journal.jsonl"),
        max_queue=max(32, n_jobs + 4),
        predictor=fed_mod.AdmissionPredictor(peaks_path),
    )
    for name, w in worlds.items():
        fed.add_world(
            name,
            n_ranks=1,
            capacity_bytes=8 << 30,
            heartbeat_dir=w["hb"],
            journal_path=w["journal"],
        )

    # the ingress: the monitor's HTTP server with the federation armed
    # behind it — submits journal at the edge, sheds answer synchronously
    mon = mon_mod.Monitor(port=0)
    mon_mod.set_ingress(fed)
    mon_mod.set_federation_source(fed.health_report)
    url = mon.url
    kinds = ("matmul", "solve", "kmeans", "nn_forward")
    tenants = ("acme", "globex", "initech")
    payloads = {
        "matmul": lambda i: {"n": 16, "seed": i},
        "solve": lambda i: {"n": 8},
        "kmeans": lambda i: {"n": 32, "k": 2, "seed": i % 3},
        "nn_forward": lambda i: {"batch": 4, "features": 8, "seed": i},
    }
    submitted = 0
    for i in range(n_jobs):
        kind = kinds[i % len(kinds)]
        code, body = _http("POST", f"{url}/submit", {
            "id": f"job{i:03d}",
            "kind": kind,
            "tenant": tenants[i % len(tenants)],
            "priority": i % 3,
            "deadline_s": 600,
            "retry_budget": 1,
            "payload": payloads[kind](i),
        })
        if code != 200 or not body.get("trace_id"):
            print(f"fed: POST /submit job{i:03d} -> {code} {body}")
            ok = False
        else:
            submitted += 1
    print(f"FED-INGRESS url={url} submitted={submitted}", flush=True)
    # the memory-infeasible job: shed at the edge, 429, structured reason
    code, body = _http("POST", f"{url}/submit", {
        "id": "giant", "kind": "giant", "tenant": "acme", "payload": {},
    })
    if code == 429 and body.get("error") == "mem_infeasible":
        print(f"FED-SHED id=giant reason={body['error']} http={code}", flush=True)
    else:
        print(f"fed: giant job expected 429 mem_infeasible, got {code} {body}")
        ok = False
    code, body = _http("GET", f"{url}/status/job000")
    if code != 200 or body.get("state") != "submitted":
        print(f"fed: GET /status/job000 -> {code} {body}")
        ok = False
    code, body = _http("GET", f"{url}/healthz")
    print(f"FED-HEALTHZ http={code} detail={body.get('detail', '')!r}", flush=True)
    ok = ok and code == 200

    assignment = fed.assign()
    log_paths = []
    open_logs = []

    def make_spawn(name: str, jobs_file: str, armed: bool, tag: str = "p1"):
        w = worlds[name]

        def spawn(rank: int, epoch: int, port: int):
            env = dict(os.environ)
            env["MPDRYRUN_PORT"] = str(port)
            env["MPDRYRUN_TMP"] = w["dir"]
            env["MPDRYRUN_HB"] = w["hb"]
            env["MPDRYRUN_NPROC"] = str(fed.worlds[name].n_ranks)
            env["MPDRYRUN_FED_WORLD"] = name
            env["MPDRYRUN_FED_JOBS"] = jobs_file
            env["MPDRYRUN_FED_JOURNAL"] = w["journal"]
            env["HEAT_TPU_FLIGHTREC_DIR"] = w["fr"]
            env["HEAT_TPU_FLIGHTREC_RANK"] = str(rank)
            env["HEAT_TPU_MEMLEDGER"] = "1"
            env["HEAT_TPU_FED_PEAKS"] = peaks_path
            env["HEAT_TPU_RESTART_EPOCH"] = str(epoch)
            env["PYTHONUNBUFFERED"] = "1"
            env.pop("PYTHONPATH", None)
            env["JAX_PLATFORMS"] = "cpu"
            if armed and epoch == 0:
                env["HEAT_TPU_FAULTS"] = "sched.dispatch:exit=2"
            else:
                env.pop("HEAT_TPU_FAULTS", None)
            path = os.path.join(w["dir"], f"{tag}_epoch{epoch}_rank{rank}.log")
            log = open(path, "wb")
            log_paths.append((f"{name} {tag}", epoch, rank, path))
            open_logs.append(log)
            return subprocess.Popen(
                [sys.executable, os.path.abspath(__file__), str(rank)],
                env=env,
                stdout=log,
                stderr=subprocess.STDOUT,
            )

        return spawn

    def write_jobs(name: str, tag: str) -> str:
        path = os.path.join(fed_dir, f"{name}_jobs_{tag}.json")
        with open(path, "w") as fh:
            _json.dump([j.to_submit_record() for j in assignment.get(name, [])], fh)
        return path

    def run_world(name: str, armed: bool, results: dict):
        w = worlds[name]
        sup = sup_mod.Supervisor(
            make_spawn(name, write_jobs(name, "p1"), armed),
            fed.worlds[name].n_ranks,
            heartbeat_dir=w["hb"],
            heartbeat_timeout=float(os.environ.get("MPDRYRUN_HB_TIMEOUT", "120")),
            # w1 is the chaos victim: zero restart budget, so its SIGKILLed
            # generation is the world's LAST — the federation must absorb it
            restart_budget=0 if armed else 1,
            generation_deadline=gen_deadline,
            flightrec_dir=w["fr"],
            telemetry_dir=os.path.join(w["dir"], "telemetry"),
        )
        results[name] = sup.run()

    results: dict = {}
    threads = [
        threading.Thread(target=run_world, args=("w0", False, results)),
        threading.Thread(target=run_world, args=("w1", True, results)),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for log in open_logs:
        try:
            log.close()
        except OSError:
            pass

    res0, res1 = results.get("w0"), results.get("w1")
    if res0 is None or not res0.ok:
        print("fed: world w0 (the healthy world) failed its generation")
        ok = False
    if res1 is None or res1.ok:
        print("fed: world w1 was armed to die and didn't")
        ok = False

    # fold both world journals up into the federation journal, then feed
    # the victim's postmortem verdicts and its death into the health model
    for name in ("w0", "w1"):
        r = fed.reconcile_world_journal(name)
        print(f"FED-RECONCILED world={name} done={r['done']} failed={r['failed']}",
              flush=True)
    if res1 is not None:
        for pm in res1.postmortems:
            fed.note_verdict("w1", pm)
    stolen = fed.world_lost("w1", "supervisor gave up: every rank SIGKILLed")
    print(f"FED-QUARANTINED world=w1 stolen={stolen}", flush=True)
    if stolen < 1:
        print("fed: the killed world held no unfinished jobs to steal — "
              "the kill landed after its queue drained; nothing was proven")
        ok = False

    # the quarantined world must NOT gate /healthz (handled degradation),
    # and the fed gauges must reconcile with the federator's census
    code, body = _http("GET", f"{url}/healthz")
    fedrep = body.get("federation", {})
    print(
        f"FED-HEALTHZ-DEGRADED http={code} healthy={fedrep.get('healthy')} "
        f"quarantined={fedrep.get('quarantined')}",
        flush=True,
    )
    ok = ok and code == 200 and fedrep.get("quarantined") == 1
    code, metrics = _http("GET", f"{url}/metrics")
    metrics = metrics if isinstance(metrics, str) else ""
    if "fed_worlds_healthy 1" not in metrics or "fed_worlds_quarantined 1" not in metrics:
        print(f"fed: /metrics fed_worlds_* gauges missing: {metrics[:400]}")
        ok = False

    # elastic resize: capacity follows the journal-derived queue depth —
    # the stolen jobs land on a GROWN w0 (applied between generations,
    # where the checkpoint world-reshaping path owns state)
    plan = fed.resize_plan(jobs_per_rank=1, max_ranks=2)
    new_ranks = plan.get("w0", 1)
    print(f"FED-RESIZE world=w0 ranks={fed.worlds['w0'].n_ranks}->{new_ranks} "
          f"queue={len(fed._queue)}", flush=True)
    fed.worlds["w0"].n_ranks = new_ranks
    assignment = fed.assign()
    if assignment.get("w1"):
        print("fed: assign() handed jobs to the quarantined world")
        ok = False
    results2: dict = {}
    run2 = threading.Thread(
        target=lambda: results2.update(
            {"w0": sup_mod.Supervisor(
                make_spawn("w0", write_jobs("w0", "p2"), False, tag="p2"),
                new_ranks,
                heartbeat_dir=worlds["w0"]["hb"],
                restart_budget=1,
                generation_deadline=gen_deadline,
                flightrec_dir=worlds["w0"]["fr"],
                telemetry_dir=os.path.join(worlds["w0"]["dir"], "telemetry"),
            ).run()}
        )
    )
    run2.start()
    run2.join()
    if not results2.get("w0") or not results2["w0"].ok:
        print("fed: resized w0 failed to serve the stolen jobs")
        ok = False
    r = fed.reconcile_world_journal("w0")
    print(f"FED-RECONCILED world=w0 done={r['done']} failed={r['failed']}",
          flush=True)

    # a stolen job's answer must now be servable OVER HTTP from the
    # journaled DONE record — the crash-surviving result path
    stolen_ids = sorted(
        rec["id"] for rec in fed_mod.replay_federation(fed.journal.path)["records"]
        if rec.get("type") == "requeue"
    )
    if stolen_ids:
        code, body = _http("GET", f"{url}/result/{stolen_ids[0]}")
        digest = (body.get("result") or {}).get("digest")
        print(f"FED-RESULT id={stolen_ids[0]} http={code} digest={digest}",
              flush=True)
        if code != 200 or digest is None:
            ok = False
    code, body = _http("GET", f"{url}/result/never-submitted")
    if code != 404:
        print(f"fed: unknown id served {code} {body}")
        ok = False

    # replay every world's logs (post-hoc diagnosability, same as main())
    for name, epoch, rank, path in log_paths:
        try:
            with open(path, "rb") as fh:
                text = fh.read().decode(errors="replace")
        except OSError:
            text = ""
        sys.stdout.write(f"---- {name} epoch {epoch} rank {rank} ----\n{text}")

    # the zero-loss attestation, derived from the federation journal alone
    line = fed.attestation()
    print(line, flush=True)
    summary = fed_mod.fed_summary(fed_mod.replay_federation(fed.journal.path))
    if summary["lost"] != 0:
        print("fed: accepted job(s) lost across the federation — the "
              "zero-loss contract is broken")
        ok = False
    if summary["worlds"] != 2 or summary["jobs"] != n_jobs + 1 \
            or summary["shed"] != 1:
        print(f"fed: attestation accounting off: {summary}")
        ok = False
    mon_mod.clear_ingress()
    mon_mod.clear_federation_source()
    mon.close()
    print("MULTIPROCESS DRYRUN:", "PASS" if ok else "FAIL", flush=True)
    return 0 if ok else 1


# ---------------------------------------------------------------------- #
# launcher — a Supervisor owns the world: liveness + heartbeat staleness
# monitoring, stack-dump teardown, restart budget, resume epochs
# ---------------------------------------------------------------------- #
def main() -> int:
    import tempfile

    n_proc = int(os.environ.get("MPDRYRUN_NPROC", N_PROC))
    mode = os.environ.get("MPDRYRUN_MODE", "dryrun")
    if mode == "fed":
        return fed_main()  # the federated multi-world scenario (ISSUE 17)
    tmpdir = tempfile.mkdtemp(prefix="mpdryrun_")
    hb_dir = os.path.join(tmpdir, "heartbeats")
    fr_dir = os.path.join(tmpdir, "flightrec")
    tdir = os.path.join(tmpdir, "telemetry")
    restart_budget = int(
        os.environ.get("MPDRYRUN_RESTARTS", "2" if mode in ("train", "serve") else "0")
    )
    # serve mode: rank 0's scheduler journals into the telemetry dir (the
    # launcher's attestation, the supervisor's jobs section and the SLO
    # table all read THIS file)
    job_journal = os.path.join(tdir, "sched_journal.jsonl") if mode == "serve" else None
    # per-generation deadline below the callers' outer timeout, so a hang is
    # reaped by this launcher — which can kill its children — rather than by
    # the caller killing the launcher and orphaning the workers
    gen_deadline = float(os.environ.get("MPDRYRUN_DEADLINE", "480"))
    hb_timeout = float(os.environ.get("MPDRYRUN_HB_TIMEOUT", "120"))
    fault_rank = int(os.environ.get("MPDRYRUN_FAULT_RANK", "-1"))
    fault_spec = os.environ.get("MPDRYRUN_FAULT_SPEC", "")
    if fault_spec:
        # arming-time catalog check (the HT113 contract, enforced at the
        # runtime boundary too): a typo'd site would arm NOTHING and the
        # chaos scenario would silently test a healthy world — fail the
        # launch loudly instead.  faults.py is stdlib-only, so the
        # launcher stays jax-free.
        flt = _load_standalone("heat_faults", "heat_tpu/utils/faults.py")
        known = set(flt.catalog_sites())
        armed = flt.parse_spec(fault_spec)
        unknown = sorted(set(armed) - known)
        if unknown:
            raise SystemExit(
                f"MPDRYRUN_FAULT_SPEC names unknown fault site(s) "
                f"{unknown}; catalog: {sorted(known)}"
            )
    # default: the injected fault models ONE crash (disarmed on restart);
    # =1 keeps it armed every generation — a persistently bad node, the
    # scenario that must exhaust the restart budget and produce the
    # merged give-up report instead of a retry loop
    fault_every_epoch = os.environ.get("MPDRYRUN_FAULT_EVERY_EPOCH", "0") == "1"
    sup_mod = _supervisor_mod()
    log_paths = []  # (epoch, rank, path) in launch order
    open_logs = []

    def spawn(rank: int, epoch: int, port: int):
        env = dict(os.environ)
        env["MPDRYRUN_PORT"] = str(port)
        env["MPDRYRUN_TMP"] = tmpdir
        env["MPDRYRUN_HB"] = hb_dir
        # black box: every staged collective is seq-stamped into a
        # crash-durable ring under fr_dir (env-armed at heat_tpu import);
        # the explicit rank is the fallback when jax isn't live yet
        env["HEAT_TPU_FLIGHTREC_DIR"] = fr_dir
        env["HEAT_TPU_FLIGHTREC_RANK"] = str(rank)
        # device-memory ledger (env-armed at heat_tpu import): live/peak
        # bytes ride the heartbeat beacons and the flight-ring watermark
        # records, and each worker prints its greppable MEM-PEAK line
        env["HEAT_TPU_MEMLEDGER"] = "1"
        env["HEAT_TPU_RESTART_EPOCH"] = str(epoch)
        env["PYTHONUNBUFFERED"] = "1"
        # scrub accelerator plumbing HERE (popping inside the worker is too
        # late: PYTHONPATH site hooks run at interpreter startup) — the
        # workers must come up as plain-CPU jax processes
        env.pop("PYTHONPATH", None)
        env["JAX_PLATFORMS"] = "cpu"
        if rank == fault_rank and fault_spec and (epoch == 0 or fault_every_epoch):
            env["HEAT_TPU_FAULTS"] = fault_spec
        else:
            # a restarted rank must NOT re-arm the fault that killed it —
            # the injected failure models ONE crash, not a crash loop
            env.pop("HEAT_TPU_FAULTS", None)
        path = os.path.join(tmpdir, f"epoch{epoch}_rank{rank}.log")
        log = open(path, "wb")
        log_paths.append((epoch, rank, path))
        open_logs.append(log)
        return subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), str(rank)],
            env=env,
            stdout=log,
            stderr=subprocess.STDOUT,
        )

    sup = sup_mod.Supervisor(
        spawn,
        n_proc,
        heartbeat_dir=hb_dir,
        heartbeat_timeout=hb_timeout,
        restart_budget=restart_budget,
        generation_deadline=gen_deadline,
        flightrec_dir=fr_dir,
        telemetry_dir=tdir,
        job_journal=job_journal,
    )
    res = sup.run()
    for log in open_logs:
        try:
            log.close()
        except OSError:
            pass
    # replay every generation's logs in order (epoch 0's kill diagnostics
    # AND the final generation's markers both matter post-hoc)
    final_epoch = max(e for e, _, _ in log_paths) if log_paths else 0
    final_texts = {}
    for epoch, rank, path in log_paths:
        try:
            with open(path, "rb") as fh:
                text = fh.read().decode(errors="replace")
        except OSError:
            text = ""
        sys.stdout.write(f"---- epoch {epoch} rank {rank} ----\n{text}")
        if epoch == final_epoch:
            final_texts[rank] = text
    ok = res.ok
    want = TRAIN_MARKER if mode == "train" else MARKER
    for rank in range(n_proc):
        text = final_texts.get(rank, "")
        if f"[{rank}] {want}" not in text:
            print(f"launcher: rank {rank} never printed its {want} marker")
            ok = False
    # fold the launcher's own counters into the telemetry merge: rank id
    # n_proc (outside the worker range) so last-wins counter merging never
    # shadows a real rank's counters — watchdog.dumps/kills + restarts are
    # now part of the SAME post-hoc report as comm.*/health.* (satellite:
    # the dump_stacks_then_kill return value used to be dropped)
    launcher_counters = dict(res.counters)
    launcher_counters["watchdog.dumps"] += _WATCHDOG["dumps"]
    launcher_counters["watchdog.kills"] += _WATCHDOG["kills"]
    trep = _load_standalone("telemetry_report", "scripts/telemetry_report.py")
    tele = _load_standalone("heat_telemetry", "heat_tpu/utils/telemetry.py")
    tele.write_counters_line(tdir, n_proc, launcher_counters)
    # merge every rank's telemetry export into one report (multi-rank jsonl
    # -> one summary table; the launcher's counters line rides along)
    merged = trep.merge_files(trep.find_rank_files(tdir))
    print(trep.render(merged, top=10, timeline=0), flush=True)
    worker_ranks = [r for r in merged["ranks"] if r < n_proc]
    if ok and len(worker_ranks) != n_proc:
        print(f"telemetry merge: expected {n_proc} worker ranks, got {merged['ranks']}")
        ok = False
    elif ok:
        print(f"TELEMETRY-MERGED ranks={len(worker_ranks)}", flush=True)
    # step-time breakdown (ISSUE 11): compute / comm-wait / host-sync /
    # idle + the overlap fraction per step kind, from the merged spans —
    # prints STEP-OVERLAP marker lines whenever the run recorded step
    # spans (daso.step in train mode, sched.job in serve mode)
    overlap = trep.overlap_section(merged["timeline"])
    if overlap:
        print(overlap, flush=True)
    print(
        f"SUPERVISOR restarts={res.restarts} generations={res.generations} "
        f"watchdog.dumps={launcher_counters['watchdog.dumps']} "
        f"watchdog.kills={launcher_counters['watchdog.kills']}",
        flush=True,
    )
    # serving attestation (ISSUE 10): the whole run's job accounting,
    # merged from the scheduler journal by the supervisor — every ACCEPTED
    # job must have reached DONE or a named FAILED across however many
    # generations it took; `lost` counts the ones that did neither, and a
    # single lost job fails the run
    if job_journal is not None:
        sched_mod = _load_standalone("heat_scheduler", "heat_tpu/parallel/scheduler.py")
        if res.jobs is None:
            print("launcher: serve mode but no job journal was written")
            ok = False
        elif "error" in res.jobs:
            print(f"launcher: job journal unreadable: {res.jobs['error']}")
            ok = False
        else:
            print(sched_mod.attestation_line(res.jobs), flush=True)
            if ok and res.jobs["lost"] != 0:
                print(
                    "launcher: accepted job(s) neither DONE nor FAILED — "
                    "the zero-loss contract is broken"
                )
                ok = False
            # the journal must have seen EVERY client submission: a rank
            # killed mid-submit-loop would otherwise yield a green lost=0
            # attestation over silently vanished requests
            expected_jobs = int(os.environ.get("MPDRYRUN_JOBS", "20"))
            if ok and res.jobs["jobs"] != expected_jobs:
                print(
                    f"launcher: journal saw {res.jobs['jobs']} of "
                    f"{expected_jobs} submitted jobs — submissions vanished"
                )
                ok = False
        # per-tenant SLO table: queue-wait + execution latency percentiles
        # from the journal and the ranks' already-merged sched.job spans
        # (spans= skips re-parsing every rank file)
        slo = trep.slo_section([tdir], spans=merged["timeline"])
        if slo:
            print(slo, flush=True)
        # trace propagation attestations (ISSUE 11): every journaled record
        # of one job — across however many generations — must carry the
        # SAME trace id (journal replay preserves it), and one trace id
        # must assemble into a causal timeline across journal + telemetry
        # + flight-ring sources.  Preference: a REQUEUED job, because its
        # chain crosses the SIGKILL restart — the continuity that matters.
        if os.path.exists(job_journal):
            try:
                replay = sched_mod.replay_journal(job_journal)
            except Exception as e:
                print(f"launcher: trace-continuity replay failed: {e!r}")
                replay = None
                ok = False
            if replay is not None:
                cont = sched_mod.trace_continuity(replay)
                print(
                    f"SCHED-TRACE-CONTINUITY jobs={cont['jobs']} "
                    f"ok={cont['ok']}"
                    + (f" violations={cont['violations']}"
                       if cont["violations"] else ""),
                    flush=True,
                )
                if not cont["ok"]:
                    print(
                        "launcher: requeued job(s) changed trace id across "
                        "the restart — the causal chain is severed"
                    )
                    ok = False
                requeued_tids = [
                    rec.get("tid") for rec in replay["records"]
                    if rec.get("type") == "requeue" and rec.get("tid")
                ]
                any_tids = [
                    v.get("tid") for v in replay["jobs"].values() if v.get("tid")
                ]
                pick = (requeued_tids or any_tids or [None])[0]
                if pick:
                    print(
                        trep.trace_section([tdir, fr_dir], pick,
                                           spans=merged["timeline"]),
                        flush=True,
                    )
    # flight-recorder post-mortem (ISSUE 7): failed generations were
    # analyzed + harvested by the supervisor at teardown (one verdict per
    # generation in res.postmortems); on success the final generation's
    # rings are still live under fr_dir — analyze them now so even a green
    # run ends with an explicit `POSTMORTEM verdict=clean` attestation
    pm = _load_standalone("heat_postmortem", "scripts/postmortem.py")
    for v in res.postmortems:
        print(pm.summary_line(v, epoch=v.get("epoch")), flush=True)
    if res.ok:
        # the FINAL generation succeeded (possibly after restarts): its
        # rings are still live under fr_dir — analyze them so every green
        # run ends with an explicit clean attestation, restarts or not
        verdict = pm.analyze_dir(
            fr_dir,
            heartbeat_dir=hb_dir,
            telemetry_dir=tdir,
            expected_ranks=list(range(n_proc)),
        )
        print(pm.summary_line(verdict), flush=True)
        if verdict.get("verdict") != "clean" and ok:
            # a green run whose rings do NOT read clean is itself a finding
            # (a rank lost its ring, streams diverged without failing, ...)
            print("launcher: postmortem disagrees with the green markers:")
            print(pm.render(verdict))
            ok = False
    if not res.ok:
        # merged diagnostic report: the give-up contract of the supervisor
        import json as _json

        print("SUPERVISOR GAVE UP; diagnostic report:", flush=True)
        print(_json.dumps(res.report(), indent=2), flush=True)
    # cross-rank timeline + critical-path attribution (ISSUE 18): align
    # every rank's clock on the shared collective-stamp anchors, name the
    # gating rank/op/seq, and export the Chrome trace artifact — on
    # FAILED runs too: assemble() folds in the supervisor's harvested
    # epoch<N>/ ring dirs, so the chaos lane's verdict ("rank 1 hung at
    # seq N") is corroborated by a CRITICAL-PATH line naming the same
    # rank, seq and op from the timeline side
    try:
        import json as _json

        tl = _load_standalone("heat_timeline", "heat_tpu/analysis/timeline.py")
        bundle = tl.assemble([tdir, fr_dir])
        clock = tl.clock_report(bundle)
        if clock:
            print(clock, flush=True)
        cp_report = tl.critical_path_report(bundle)
        if cp_report:
            print(cp_report, flush=True)
        trace = tl.to_chrome_trace(bundle)
        problems = tl.validate_chrome_trace(trace)
        trace_out = os.environ.get("MPDRYRUN_TRACE_OUT") or os.path.join(
            tmpdir, "trace.json"
        )
        with open(trace_out, "w") as fh:
            _json.dump(trace, fh)
        print(
            f"TRACE-EXPORT events={len(trace['traceEvents'])} "
            f"ranks={len(bundle['ranks'])} out={trace_out}",
            flush=True,
        )
        if problems:
            for p in problems:
                print(f"launcher: trace INVALID: {p}")
            ok = False
    except Exception as e:
        print(f"launcher: timeline export failed: {e!r}")
        ok = False
    print("MULTIPROCESS DRYRUN:", "PASS" if ok else "FAIL", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    if len(sys.argv) > 1:
        _mode = os.environ.get("MPDRYRUN_MODE", "dryrun")
        _target = {
            "train": train_worker,
            "postmortem": postmortem_worker,
            "serve": serve_worker,
            "fed": fed_serve_worker,
        }.get(_mode, worker)
        _target(
            int(sys.argv[1]),
            int(os.environ["MPDRYRUN_PORT"]),
            os.environ["MPDRYRUN_TMP"],
        )
    else:
        sys.exit(main())
