"""Sequence-parallel long-context attention demo.

Runs batched multi-head causal ring attention with the sequence axis sharded
over the device mesh: each chip holds S/p of the sequence, K/V blocks rotate
over the ICI ring (``lax.ppermute``) and a flash-style online softmax
accumulates — the (S, S) score matrix never exists, so context length scales
with the number of chips.  On TPU each ring step additionally runs the
Pallas flash kernel over its visiting block (``kernel='auto'``), so even
the per-chip (S/p, S/p) score block never materializes — per-chip memory is
one kernel tile.

Run (virtual 8-device CPU mesh):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/ring_attention_demo.py
"""

import os
import sys
import time

if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

import heat_tpu as ht
from heat_tpu.parallel import ring_attention


def main() -> None:
    comm = ht.communication.get_comm()
    p = comm.size
    B, H, d = 2, 4, 64
    S = 1024 * p  # context scales linearly with the mesh
    print(f"mesh: {p} devices — sequence length {S} ({S // p} per chip)")

    rng = np.random.default_rng(0)
    q, k, v = (
        comm.shard(jnp.asarray(rng.standard_normal((B, H, S, d)), jnp.float32), 2)
        for _ in range(3)
    )

    step = jax.jit(lambda q, k, v: ring_attention(q, k, v, comm, causal=True))
    out = jax.block_until_ready(step(q, k, v))  # compile
    t0 = time.perf_counter()
    out = jax.block_until_ready(step(q, k, v))
    dt = time.perf_counter() - t0

    # causal attention FLOPs ≈ 2 * B*H*S²*d (QK^T) + 2 * B*H*S²*d (PV), halved
    flops = 2 * 2 * B * H * S * S * d / 2
    print(f"one causal pass: {dt * 1e3:.1f} ms  (~{flops / dt / 1e9:.1f} GFLOP/s)")
    print(f"output sharded over {len(out.sharding.device_set)} devices")

    # correctness spot-check against the dense reference on a small slice
    Ss = 64
    # slice on device, gather only the prefix
    qs, ks, vs = (np.asarray(t[:, :, :Ss]) for t in (q, k, v))
    s = np.einsum("bhqd,bhkd->bhqk", qs, ks) / np.sqrt(d)
    s = np.where(np.tril(np.ones((Ss, Ss), bool)), s, -np.inf)
    pr = np.exp(s - s.max(-1, keepdims=True))
    pr /= pr.sum(-1, keepdims=True)
    want = np.einsum("bhqk,bhkd->bhqd", pr, vs)
    # the spot check runs in HIGHEST precision so it is exact on TPU too
    # (the timed pass above uses the default bf16 MXU passes)
    with jax.default_matmul_precision("highest"):
        small = jax.jit(lambda q, k, v: ring_attention(q, k, v, comm, causal=True))(
            comm.shard(jnp.asarray(qs), 2), comm.shard(jnp.asarray(ks), 2), comm.shard(jnp.asarray(vs), 2)
        )
    np.testing.assert_allclose(np.asarray(small), want, rtol=2e-3, atol=2e-4)
    print("matches dense reference on the 64-token prefix ✓")

    # ragged context: a prime sequence length still rides the ring — the
    # sequence axis is padded to ceil(S/p)·p and pad keys are masked, so no
    # length ever falls back to the O(S²) global path
    import importlib

    ra = importlib.import_module("heat_tpu.parallel.ring_attention")
    Sr = 997  # prime
    qr = jnp.asarray(rng.standard_normal((B, H, Sr, d)), jnp.float32)
    before = dict(ra.path_counts)
    out_r = ring_attention(qr, qr, qr, comm, causal=True)
    assert out_r.shape == (B, H, Sr, d)
    if comm.is_distributed():
        assert ra.path_counts["ring"] == before["ring"] + 1
        print(f"prime-length context S={Sr} stayed on the ring ✓")
    else:
        print(f"prime-length context S={Sr} ok (single device: no ring)")

    # --- sequence-parallel TRAINING (round 4b) --------------------------- #
    # The ring is differentiable (autodiff through shard_map + ppermute +
    # scan), and transformer_encoder(remat=True) checkpoints each block, so
    # a long-context training step holds neither the (S, S) scores nor
    # depth x (B, S, E) activations in HBM.  On TPU the single-chip local
    # block additionally runs the Pallas flash kernels in BOTH directions.
    E, Hm = 64, 4
    model = ht.nn.models.transformer_encoder(
        E, Hm, depth=2, causal=True, comm=comm, remat=True
    )
    params = model.init(jax.random.key(1))
    xb = jnp.asarray(rng.standard_normal((2, 1023, E)), jnp.float32)  # ragged S

    def loss(p):
        return jnp.mean(model.apply(p, xb) ** 2)

    l0 = float(loss(params))
    g = jax.grad(loss)(params)
    params = jax.tree.map(lambda w, gg: w - 0.05 * gg, params, g)
    l1 = float(loss(params))
    print(f"seq-parallel remat training step: loss {l0:.4f} -> {l1:.4f} ✓")


if __name__ == "__main__":
    main()
