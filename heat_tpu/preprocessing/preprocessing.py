"""Data scalers (reference: ``heat/preprocessing/preprocessing.py``).

All statistics are distributed global reductions (implicit Allreduce over
the split axis); the transforms are elementwise and fuse into one kernel.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from ..core import types
from ..core.base import BaseEstimator, TransformMixin
from ..core.dndarray import DNDarray

__all__ = ["StandardScaler", "MinMaxScaler", "MaxAbsScaler", "RobustScaler", "Normalizer"]


def _wrap_like(jarr, split, proto: DNDarray) -> DNDarray:
    if split is not None and split >= jarr.ndim:
        split = None
    jarr = proto.comm.shard(jarr, split)
    return DNDarray(
        jarr, tuple(jarr.shape), types.canonical_heat_type(jarr.dtype), split, proto.device, proto.comm, True
    )


class StandardScaler(TransformMixin, BaseEstimator):
    """Zero-mean unit-variance scaling (per feature)."""

    def __init__(self, copy: bool = True, with_mean: bool = True, with_std: bool = True):
        self.copy = copy
        self.with_mean = with_mean
        self.with_std = with_std
        self.mean_ = None
        self.var_ = None
        self.scale_ = None

    def fit(self, x: DNDarray, sample_weight=None) -> "StandardScaler":
        j = x._jarray
        mean = jnp.mean(j, axis=0)
        var = jnp.var(j, axis=0)
        scale = jnp.where(var > 1e-30, jnp.sqrt(var), 1.0)
        self.mean_ = _wrap_like(mean, None, x)
        self.var_ = _wrap_like(var, None, x)
        self.scale_ = _wrap_like(scale, None, x)
        return self

    def transform(self, x: DNDarray) -> DNDarray:
        j = x._jarray
        if self.with_mean:
            j = j - self.mean_._jarray[None, :]
        if self.with_std:
            j = j / self.scale_._jarray[None, :]
        return _wrap_like(j, x.split, x)

    def inverse_transform(self, x: DNDarray) -> DNDarray:
        j = x._jarray
        if self.with_std:
            j = j * self.scale_._jarray[None, :]
        if self.with_mean:
            j = j + self.mean_._jarray[None, :]
        return _wrap_like(j, x.split, x)


class MinMaxScaler(TransformMixin, BaseEstimator):
    """Scale features to a given range (default [0, 1])."""

    def __init__(self, feature_range: Tuple[float, float] = (0.0, 1.0), copy: bool = True, clip: bool = False):
        if feature_range[0] >= feature_range[1]:
            raise ValueError("Minimum of feature_range must be smaller than maximum")
        self.feature_range = feature_range
        self.copy = copy
        self.clip = clip
        self.data_min_ = None
        self.data_max_ = None
        self.scale_ = None
        self.min_ = None

    def fit(self, x: DNDarray) -> "MinMaxScaler":
        j = x._jarray
        dmin = jnp.min(j, axis=0)
        dmax = jnp.max(j, axis=0)
        rng = jnp.where(dmax > dmin, dmax - dmin, 1.0)
        lo, hi = self.feature_range
        scale = (hi - lo) / rng
        self.data_min_ = _wrap_like(dmin, None, x)
        self.data_max_ = _wrap_like(dmax, None, x)
        self.data_range_ = _wrap_like(rng, None, x)
        self.scale_ = _wrap_like(scale, None, x)
        self.min_ = _wrap_like(lo - dmin * scale, None, x)
        return self

    def transform(self, x: DNDarray) -> DNDarray:
        j = x._jarray * self.scale_._jarray[None, :] + self.min_._jarray[None, :]
        if self.clip:
            j = jnp.clip(j, self.feature_range[0], self.feature_range[1])
        return _wrap_like(j, x.split, x)

    def inverse_transform(self, x: DNDarray) -> DNDarray:
        j = (x._jarray - self.min_._jarray[None, :]) / self.scale_._jarray[None, :]
        return _wrap_like(j, x.split, x)


class MaxAbsScaler(TransformMixin, BaseEstimator):
    """Scale each feature by its maximum absolute value (sparse-safe)."""

    def __init__(self, copy: bool = True):
        self.copy = copy
        self.max_abs_ = None
        self.scale_ = None

    def fit(self, x: DNDarray) -> "MaxAbsScaler":
        j = x._jarray
        ma = jnp.max(jnp.abs(j), axis=0)
        self.max_abs_ = _wrap_like(ma, None, x)
        self.scale_ = _wrap_like(jnp.where(ma > 0, ma, 1.0), None, x)
        return self

    def transform(self, x: DNDarray) -> DNDarray:
        return _wrap_like(x._jarray / self.scale_._jarray[None, :], x.split, x)

    def inverse_transform(self, x: DNDarray) -> DNDarray:
        return _wrap_like(x._jarray * self.scale_._jarray[None, :], x.split, x)


class RobustScaler(TransformMixin, BaseEstimator):
    """Median/IQR scaling (distributed percentiles, SURVEY §2.4)."""

    def __init__(self, with_centering: bool = True, with_scaling: bool = True,
                 quantile_range: Tuple[float, float] = (25.0, 75.0), copy: bool = True,
                 unit_variance: bool = False):
        lo, hi = quantile_range
        if not 0 <= lo <= hi <= 100:
            raise ValueError(f"Invalid quantile range {quantile_range}")
        if unit_variance:
            raise NotImplementedError("unit_variance=True not supported (reference parity)")
        self.with_centering = with_centering
        self.with_scaling = with_scaling
        self.quantile_range = quantile_range
        self.copy = copy
        self.unit_variance = unit_variance
        self.center_ = None
        self.scale_ = None

    def fit(self, x: DNDarray) -> "RobustScaler":
        j = x._jarray.astype(jnp.float32)
        lo, hi = self.quantile_range
        if self.with_centering:
            self.center_ = _wrap_like(jnp.median(j, axis=0), None, x)
        if self.with_scaling:
            q = jnp.percentile(j, jnp.asarray([lo, hi]), axis=0)
            iqr = q[1] - q[0]
            self.scale_ = _wrap_like(jnp.where(iqr > 0, iqr, 1.0), None, x)
        return self

    def transform(self, x: DNDarray) -> DNDarray:
        j = x._jarray
        if self.with_centering:
            j = j - self.center_._jarray[None, :]
        if self.with_scaling:
            j = j / self.scale_._jarray[None, :]
        return _wrap_like(j, x.split, x)

    def inverse_transform(self, x: DNDarray) -> DNDarray:
        j = x._jarray
        if self.with_scaling:
            j = j * self.scale_._jarray[None, :]
        if self.with_centering:
            j = j + self.center_._jarray[None, :]
        return _wrap_like(j, x.split, x)


class Normalizer(TransformMixin, BaseEstimator):
    """Row-wise normalization to unit norm ('l1' | 'l2' | 'max') — stateless."""

    def __init__(self, norm: str = "l2", copy: bool = True):
        if norm not in ("l1", "l2", "max"):
            raise NotImplementedError(f"Unsupported norm {norm!r}")
        self.norm = norm
        self.copy = copy

    def fit(self, x: DNDarray) -> "Normalizer":
        return self

    def transform(self, x: DNDarray) -> DNDarray:
        j = x._jarray
        if self.norm == "l1":
            n = jnp.sum(jnp.abs(j), axis=1, keepdims=True)
        elif self.norm == "l2":
            n = jnp.sqrt(jnp.sum(j * j, axis=1, keepdims=True))
        else:
            n = jnp.max(jnp.abs(j), axis=1, keepdims=True)
        return _wrap_like(j / jnp.where(n > 0, n, 1.0), x.split, x)
