"""Test bootstrap: run everything on a virtual 8-device CPU mesh.

The reference runs its suite under ``mpirun -n N`` for several N; the
TPU-native analogue (SURVEY §4) is a multi-device CPU mesh in ONE process via
``--xla_force_host_platform_device_count`` — same code paths as a real pod,
only the transport differs.
"""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def ht():
    import heat_tpu

    return heat_tpu


# split sweep used across op tests (the reference's distributed-coverage trick)
SPLITS_1D = [None, 0]
SPLITS_2D = [None, 0, 1]
