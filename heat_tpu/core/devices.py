"""Device and mesh handles — the TPU-native seam of the framework.

In the reference (``heat/core/devices.py``) a ``Device`` names a torch device
(``cpu``/``gpu``) and each MPI rank pins itself to one accelerator.  In the
TPU-native design a ``Device`` instead names a *platform* (``tpu``/``cpu``/
``gpu``) together with the :class:`jax.sharding.Mesh` built over all visible
devices of that platform.  Arrays live as globally-shaped, sharded
``jax.Array``s on that mesh; there is no per-rank device pinning because JAX's
single-controller SPMD model addresses every chip at once.

Public parity surface: ``ht.cpu``, ``ht.gpu`` (alias of the accelerator
platform), ``ht.use_device``, ``ht.get_device``, ``sanitize_device``; new
TPU-native handles: ``ht.tpu``, ``use_mesh``, ``get_default_mesh``.
"""

from __future__ import annotations

from typing import Optional, Union

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = [
    "Device",
    "cpu",
    "get_device",
    "sanitize_device",
    "use_device",
    "use_mesh",
    "get_default_mesh",
    "make_mesh",
]


class Device:
    """Handle for a compute platform and the device mesh spanned over it.

    Parameters
    ----------
    device_type : str
        Platform name: ``'cpu'``, ``'gpu'`` or ``'tpu'``.
    device_id : int
        Kept for API parity with the reference; always 0 (the mesh addresses
        all devices of the platform collectively).
    """

    def __init__(self, device_type: str, device_id: int = 0):
        self.__device_type = device_type
        self.__device_id = device_id
        self.__mesh: Optional[Mesh] = None

    @property
    def device_type(self) -> str:
        return self.__device_type

    @property
    def device_id(self) -> int:
        return self.__device_id

    @property
    def jax_devices(self):
        """All JAX devices of this platform (raises if platform unavailable)."""
        return jax.devices(self.__device_type)

    @property
    def mesh(self) -> Mesh:
        """The (lazily built, cached) 1-D mesh over all devices of the platform."""
        if self.__mesh is None:
            self.__mesh = make_mesh(platform=self.__device_type)
        return self.__mesh

    def set_mesh(self, mesh: Mesh) -> None:
        self.__mesh = mesh

    @property
    def available(self) -> bool:
        try:
            return len(jax.devices(self.__device_type)) > 0
        except RuntimeError:
            return False

    def __eq__(self, other) -> bool:
        if isinstance(other, Device):
            return self.__device_type == other.device_type
        if isinstance(other, str):
            return self.__device_type == _canonical_name(other)
        return NotImplemented

    def __hash__(self) -> int:
        return hash(self.__device_type)

    def __repr__(self) -> str:
        return f"device({self.__str__()!r})"

    def __str__(self) -> str:
        return f"{self.__device_type}:{self.__device_id}"


def make_mesh(
    platform: Optional[str] = None,
    shape: Optional[tuple] = None,
    axis_names: tuple = ("x",),
) -> Mesh:
    """Build a mesh over the devices of ``platform``.

    Default is a 1-D mesh named ``('x',)`` over all devices — the direct
    analogue of the reference's ``MPI_WORLD`` world communicator.  Hierarchical
    meshes (e.g. ``('dcn', 'ici')`` for DASO, SURVEY §5.8) are produced by
    passing an explicit ``shape``/``axis_names``.
    """
    devs = jax.devices(platform) if platform else jax.devices()
    if shape is None:
        shape = (len(devs),)
    arr = np.asarray(devs).reshape(shape)
    return Mesh(arr, axis_names[: arr.ndim])


def _canonical_name(name: str) -> str:
    name = name.lower()
    aliases = {"cuda": "gpu", "axon": "tpu"}
    return aliases.get(name, name)


# Platform singletons.  `gpu` / `tpu` are created on demand because the
# platforms may be absent; `cpu` always exists.
cpu = Device("cpu")
_devices = {"cpu": cpu}

# default device: prefer the accelerator jax itself defaults to
__default_device: Optional[Device] = None


def _platform_singleton(name: str) -> Device:
    name = _canonical_name(name)
    if name not in _devices:
        dev = Device(name)
        if not dev.available:
            raise ValueError(f"Platform '{name}' has no available devices")
        _devices[name] = dev
    return _devices[name]


def __getattr__(name):  # module-level: ht.core.devices.gpu / .tpu resolve lazily
    if name in ("gpu", "tpu"):
        return _platform_singleton(name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def get_device() -> Device:
    """The current default :class:`Device`."""
    global __default_device
    if __default_device is None:
        backend = jax.default_backend()
        __default_device = _platform_singleton(_canonical_name(backend))
    return __default_device


def use_device(device: Union[str, Device, None] = None) -> None:
    """Set the default device, cf. ``ht.use_device('gpu')`` in the reference."""
    global __default_device
    if device is None:
        return
    __default_device = sanitize_device(device)


def sanitize_device(device: Union[str, Device, None]) -> Device:
    """Resolve ``device`` to a :class:`Device` (default device for ``None``)."""
    if device is None:
        return get_device()
    if isinstance(device, Device):
        return device
    if isinstance(device, str):
        return _platform_singleton(device)
    raise ValueError(f"Unknown device, must be 'cpu', 'gpu' or 'tpu', got {device}")


def use_mesh(mesh: Mesh, device: Union[str, Device, None] = None) -> None:
    """Install ``mesh`` as the mesh of ``device`` (default device if None).

    This is the TPU-native analogue of selecting a communicator: subsequent
    factories build arrays sharded over ``mesh``'s first axis by default.
    """
    dev = sanitize_device(device)
    dev.set_mesh(mesh)
    # invalidate cached world communication handles built on the old mesh
    from . import communication

    communication._invalidate_default(dev)


def get_default_mesh() -> Mesh:
    return get_device().mesh
