"""Op-dispatch microbenchmark: program-cache latency, hit rate, donation.

The zero-copy dispatch claim, measured (ISSUE 1 acceptance):

- ``dispatch_cached_latency_us`` — wall time of a repeated same-signature
  binary op through the sharding-keyed program cache (one compiled
  executable per ``(op, avals, split)``, output sharding compiled in);
- ``dispatch_eager_reference_latency_us`` — the SEED dispatch tail for the
  same op (eager jnp call + post-hoc ``comm.shard`` placement + metadata
  recompute), timed side by side so the speedup is self-contained;
- ``dispatch_overhead_us`` / ``dispatch_eager_reference_overhead_us`` —
  the same two paths with the compiled-program floor (a pre-built jitted
  add on the raw arrays, timed in-run) subtracted: pure Python dispatch
  cost, independent of how fast this host executes the op itself.  The
  seed measured ~230-470 us/op here; the cached path ~50 us/op
  (interleaved A/B on the 8-device host mesh, 2026-08-03);
- ``recompilations_100_ops`` / ``cache_hit_rate`` — program-cache misses
  across 100 repeated same-signature ops after warmup (target: 0 / ≥0.99);
- ``resplit_inplace_latency_us`` vs ``resplit_copy_latency_us`` and the
  peak-RSS of a large in-place redistribution with the source buffer
  donated vs the copying form.

Run: python benchmarks/dispatch.py [--out PATH] [--size N] [--reps R]
Writes a ``scripts/bench_compare.py``-consumable payload (committed
capture: ``BENCH_DISPATCH.json``).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time


def _time_interleaved(fns, sync, reps, batch=20):
    """Per-round per-call wall times (µs) of each fn, measured in
    INTERLEAVED rounds so drifting host load hits every path equally (the
    round-5 lesson: ordered one-shot timings produced phantom winners).
    Each round dispatches ``batch`` calls and syncs once.  Returns a list
    of per-round sample lists, one per fn."""
    samples = [[] for _ in fns]
    for _ in range(reps):
        for i, fn in enumerate(fns):
            t0 = time.perf_counter()
            out = None
            for _ in range(batch):
                out = fn()
            sync(out)
            samples[i].append((time.perf_counter() - t0) / batch * 1e6)
    return samples


def _mins(samples):
    return [min(s) for s in samples]


def _paired_delta(a, b):
    """Median of the PER-ROUND differences a_i − b_i: the two paths ran
    back-to-back each round, so host-load swings cancel pairwise — the
    robust estimator of pure overhead above a measured floor."""
    d = sorted(x - y for x, y in zip(a, b))
    return max(d[len(d) // 2], 0.0)


def _time_op(fn, sync, reps):
    return _mins(_time_interleaved([fn], sync, reps))[0]


def _rotated_hook_gate(floor_fn, off_fn, off2_fn, on_fn, sync, reps):
    """The shared measurement of the hook gates (flightrec, memledger):
    rotated pairwise rounds hardened for cpu-quota-throttled hosts.
    (1) the three hook states ROTATE through the round positions (the
    later path in a round is systematically slower as quota decays, and a
    fixed order biases the delta positive); (2) an off-vs-off NULL in the
    same rounds sets the noise floor — a measurement cannot assert a
    regression below its own noise; (3) the on-vs-off paired deltas must
    shift WHOLESALE (q25 > 0) before a gate may fail: a real regression
    taxes every round, symmetric scheduler noise cannot.  Returns
    ``(off_above_floor_us, added_us, noise_floor_us, consistent,
    added_pct)``."""
    s_floor, s_off, s_off2, s_on = [], [], [], []
    rotation = [(off_fn, s_off), (off2_fn, s_off2), (on_fn, s_on)]
    for i in range(reps):
        order = rotation[i % 3:] + rotation[: i % 3]
        for fn, out_samples in [(floor_fn, s_floor)] + order:
            t0 = time.perf_counter()
            out = None
            for _ in range(20):
                out = fn()
            sync(out)
            out_samples.append((time.perf_counter() - t0) / 20 * 1e6)
    off_oh = max(_paired_delta(s_off, s_floor), 1.0)
    added_us = _paired_delta(s_on, s_off)
    d_null = sorted(a - b for a, b in zip(s_off2, s_off))
    noise_us = abs(d_null[len(d_null) // 2])
    d_on = sorted(a - b for a, b in zip(s_on, s_off))
    consistent = d_on[len(d_on) // 4] > 0.0
    return off_oh, added_us, noise_us, consistent, added_us / off_oh * 100.0


def _peak_rss_subprocess(mode: str, size: int) -> float:
    """Peak RSS (MB) of one resplit of a (size, size) f32 array, measured in
    a fresh process so allocator history doesn't pollute the peak."""
    code = f"""
import os, resource, sys
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
import jax
jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
import heat_tpu as ht
x = ht.zeros(({size}, {size}), split=0)
x += 1.0  # touch every page
# memory_budget=0 pins the MONOLITHIC path regardless of any
# HEAT_TPU_RESPLIT_BUDGET / process default in the inherited env —
# these rows are labeled monolithic and must measure it
if {mode!r} == "inplace":
    x.resplit_(1, memory_budget=0)       # donating path
    out = x
else:
    out = x.resplit(1, memory_budget=0)  # copying path (source stays live)
ht.utils.profiler.sync(out)
print(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0)
"""
    try:
        r = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True, timeout=300
        )
        return float(r.stdout.strip().splitlines()[-1])
    except Exception:
        return float("nan")


def _peak_rss_resplit(shape, budget_bytes, mode: str) -> dict:
    """Budgeted-resplit peak-RSS capture in a fresh process: build a 3-d
    f32 array split 0, touch every page, record the pre-transfer RSS
    high-water mark (``base``, source included), resplit to split 1 under
    ``budget_bytes`` (``mode='budgeted'``) or monolithically
    (``mode='copy'``/``'inplace'``), and report the post-transfer peak plus
    the plan shape read back from the telemetry counters."""
    code = f"""
import json, os, resource, sys
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
import jax
jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
import heat_tpu as ht
from heat_tpu.utils import profiler
shape, budget, mode = {tuple(shape)!r}, {int(budget_bytes)}, {mode!r}
x = ht.zeros(shape, split=0)
x += 1.0  # touch every page
# completion fence WITHOUT materialization: profiler.sync would device_get
# the sharded array — a host-side full copy (~1 GB on this mesh) that has
# nothing to do with the transfer being measured
jax.block_until_ready(x._parray)
base_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0
profiler.reset_counters()
if mode == "budgeted":
    x.resplit_(1, memory_budget=budget)
    out = x
elif mode == "inplace":
    # memory_budget=0 pins the monolithic path even when the inherited env
    # carries HEAT_TPU_RESPLIT_BUDGET — the comparison row must not stream
    x.resplit_(1, memory_budget=0)
    out = x
else:
    out = x.resplit(1, memory_budget=0)
jax.block_until_ready(out._parray)
c = profiler.counters()
print(json.dumps({{
    "base_mb": base_mb,
    "peak_mb": resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0,
    "tiles": c.get("comm.resplit.tiles", 0),
    "peak_tile_bytes": c.get("comm.resplit.peak_tile_bytes", 0),
    "resplit_bytes": c.get("comm.resplit.bytes", 0),
}}))
"""
    r = None
    try:
        r = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True, timeout=600
        )
        return json.loads(r.stdout.strip().splitlines()[-1])
    except Exception as exc:
        # surface the capture's own diagnostics: a NaN payload without them
        # reads as "planner fell back to monolithic?" when the subprocess
        # actually died of an import error / OOM kill / timeout
        print(f"resplit RSS capture ({mode}) failed: {exc!r}", file=sys.stderr)
        if r is not None:
            print(f"  returncode={r.returncode}", file=sys.stderr)
            if r.stderr:
                print(r.stderr[-2000:], file=sys.stderr)
        return {"base_mb": float("nan"), "peak_mb": float("nan"), "tiles": 0,
                "peak_tile_bytes": 0, "resplit_bytes": 0}


def _overlap_capture(steps: int, warmup: int, budget: str) -> dict:
    """Rotated-pairwise DASO sync comparison, measured in a fresh process:
    two overlapped-sync DASO arms share one process — ``monolithic`` pins
    the single-bucket plan (budget 0), ``bucketed`` splits the sync under
    ``budget`` — and their steps are interleaved in alternating AB/BA order
    so scheduler drift cancels.  Per step: wall time (with the mpdryrun
    lockstep ``comm.Wait(loss)`` fence) and the guarded blocking-wait
    seconds (``comm.allreduce.wait`` + ``comm.Wait.wait`` histograms, which
    is what ``scripts/stepprof.py`` attributes too); overlap fraction =
    1 − wait/step.  Also captured: the per-arm ``comm.allreduce.bytes``
    deltas (the byte-invariance contract) and the steady-state program-
    cache stats after warmup (the zero-recompile contract)."""
    code = f"""
import json, os, statistics, sys, time
os.environ.pop("HEAT_TPU_GRAD_BUCKET_BYTES", None)  # arms pin their own plans
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
import jax
jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
import numpy as np
import heat_tpu as ht
from heat_tpu.utils import profiler, telemetry

steps, warmup, budget = {int(steps)}, {int(warmup)}, {budget!r}
telemetry.enable()  # arms the wait observer guard_blocking feeds

def build(bucket_budget):
    model = ht.nn.Sequential(
        ht.nn.Flatten(), ht.nn.Linear(128, 512), ht.nn.ReLU(),
        ht.nn.Linear(512, 128),
    )
    daso = ht.optim.DASO(
        ht.optim.DataParallelOptimizer("sgd", lr=0.05),
        total_local_comm_size=2,
        warmup_steps=0, global_skip=1, stale_steps=0,
        overlap_sync=True, grad_bucket_bytes=bucket_budget,
    )
    daso.init(model, key=jax.random.key(3))
    return daso

def mse(pred, y):
    return jax.numpy.mean((pred - y) ** 2)

def wait_s():
    return (telemetry.histogram("comm.allreduce.wait").total
            + telemetry.histogram("comm.Wait.wait").total)

comm = ht.communication.get_comm()
rng = np.random.default_rng(11)

def step(daso):
    x = jax.numpy.asarray(rng.normal(size=(32, 128)).astype(np.float32))
    y = jax.numpy.asarray(rng.normal(size=(32, 128)).astype(np.float32))
    w0, t0 = wait_s(), time.perf_counter()
    loss = daso.step(mse, x, y)
    comm.Wait(loss)  # lockstep fence: the wait lands in comm.Wait.wait
    return time.perf_counter() - t0, wait_s() - w0

# budget 0 parses to None -> the forced single-bucket (monolithic) plan
arms = [("monolithic", build(0)), ("bucketed", build(budget))]
for _, d in arms:
    for _ in range(warmup):
        step(d)
profiler.reset_cache_stats()
rows = {{name: [] for name, _ in arms}}
bytes_delta = {{name: 0 for name, _ in arms}}
for i in range(steps):
    for name, d in (arms if i % 2 == 0 else arms[::-1]):
        c0 = profiler.counters().get("comm.allreduce.bytes", 0)
        rows[name].append(step(d))
        bytes_delta[name] += (
            profiler.counters().get("comm.allreduce.bytes", 0) - c0
        )
stats = profiler.cache_stats()

def med_overlap(rs):
    return statistics.median(1.0 - min(w, dt) / dt for dt, w in rs)

print(json.dumps({{
    "overlap": {{k: round(med_overlap(v), 4) for k, v in rows.items()}},
    "step_ms": {{k: round(statistics.median(dt for dt, _ in v) * 1e3, 3)
                for k, v in rows.items()}},
    "wait_ms": {{k: round(statistics.median(w for _, w in v) * 1e3, 3)
                for k, v in rows.items()}},
    "allreduce_bytes": bytes_delta,
    "n_buckets": {{name: d._overlap_state()[1].n_buckets for name, d in arms}},
    "steady_cache_misses": stats["misses"],
    "steady_cache_hits": stats["hits"],
}}))
"""
    r = None
    try:
        r = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=600,
        )
        return json.loads(r.stdout.strip().splitlines()[-1])
    except Exception as exc:
        print(f"overlap capture failed: {exc!r}", file=sys.stderr)
        if r is not None:
            print(f"  returncode={r.returncode}", file=sys.stderr)
            if r.stderr:
                print(r.stderr[-2000:], file=sys.stderr)
        return {}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None, help="write payload JSON here")
    ap.add_argument("--size", type=int, default=256, help="square op size")
    ap.add_argument("--reps", type=int, default=40)
    ap.add_argument("--skip-rss", action="store_true",
                    help="skip the subprocess peak-memory captures")
    ap.add_argument("--telemetry-gate", type=float, default=None, metavar="PCT",
                    help="exit 4 if telemetry-on adds more than PCT%% to the "
                         "dispatch cost above the compiled-program floor "
                         "(the CI telemetry lane's 5%% overhead contract)")
    ap.add_argument("--flightrec-gate", type=float, default=None, metavar="PCT",
                    help="exit 6 if the armed flight recorder adds more than "
                         "PCT%% to the dispatch cost above the compiled-program "
                         "floor (the ISSUE 7 crash-durable-ring overhead "
                         "contract; same pairwise methodology as the "
                         "telemetry gate)")
    ap.add_argument("--monitor-gate", type=float, default=None, metavar="PCT",
                    help="exit 7 if dispatch under an ARMED + actively "
                         "scraped /metrics monitor costs more than PCT%% "
                         "above the unscraped dispatch cost over the "
                         "compiled floor (the ISSUE 11 live-endpoint "
                         "contract; same pairwise methodology as the "
                         "telemetry gate — the monitor adds NO hot-path "
                         "hook, so this measures pure scrape-thread "
                         "interference)")
    ap.add_argument("--memledger-gate", type=float, default=None, metavar="PCT",
                    help="exit 8 if the armed device-memory ledger adds more "
                         "than PCT%% to the dispatch cost above the "
                         "compiled-program floor (the ISSUE 14 per-buffer "
                         "registration overhead contract; same rotated "
                         "pairwise methodology + off-vs-off noise floor + "
                         "q25 wholesale-shift guard as the flightrec gate; "
                         "the disarmed path stays ONE module-global load by "
                         "construction)")
    ap.add_argument("--resplit-gate", action="store_true",
                    help="run the budgeted-resplit peak-RSS gate: exit 5 when "
                         "the chunked pipeline's peak RSS exceeds "
                         "base + destination + budget + one tile (+ slack)")
    ap.add_argument("--resplit-out", default=None, metavar="PATH",
                    help="write the resplit-gate payload here "
                         "(committed capture: BENCH_RESPLIT.json)")
    ap.add_argument("--resplit-shape", type=int, nargs=3, default=(1024, 1024, 16),
                    metavar=("R", "C", "D"),
                    help="3-d f32 array for the resplit gate (split 0 -> 1, "
                         "tiled along axis 2); default 64 MB")
    ap.add_argument("--resplit-budget-mb", type=float, default=16.0,
                    help="per-step byte budget for the gate")
    ap.add_argument("--resplit-slack-mb", type=float, default=48.0,
                    help="allocator/runtime slack added to the gate bound "
                         "(XLA CPU working memory + per-plan compile spikes "
                         "are not byte-exact; 48 MB keeps the gate below the "
                         "64 MB whole-array-staging regression it exists to "
                         "catch)")
    ap.add_argument("--overlap-gate", action="store_true",
                    help="run the ISSUE 16 overlapped-sync gate: exit 9 "
                         "unless the bucketed lookahead-1 DASO sync beats "
                         "the single-bucket (monolithic) sync on median "
                         "compute/comm overlap fraction in a rotated "
                         "pairwise short training loop, with byte-identical "
                         "comm.allreduce.bytes and zero steady-state "
                         "recompiles")
    ap.add_argument("--overlap-out", default=None, metavar="PATH",
                    help="write the overlap-gate payload here "
                         "(committed capture: BENCH_OVERLAP.json)")
    ap.add_argument("--overlap-steps", type=int, default=24,
                    help="measured rotated step pairs for the overlap gate")
    ap.add_argument("--overlap-warmup", type=int, default=6,
                    help="per-arm warmup steps (compiles the bucket "
                         "programs) before the overlap gate measures")
    ap.add_argument("--overlap-budget", default="256K",
                    help="grad-bucket budget of the bucketed arm (K/M/G "
                         "suffixes; the monolithic arm always pins the "
                         "single-bucket plan)")
    args = ap.parse_args(argv)

    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    import jax

    jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import jax.numpy as jnp

    import heat_tpu as ht
    from heat_tpu.utils import profiler, telemetry

    # the contract rows below are measured with telemetry OFF regardless of
    # how the job armed the env (HEAT_TPU_TELEMETRY=1 in the CI telemetry
    # lane): the committed BENCH_DISPATCH payload is a telemetry-off
    # capture, and the on-vs-off question has its own section below
    telemetry_armed = telemetry.enabled()
    telemetry.disable()

    comm = ht.communication.get_comm()
    n_dev = comm.size
    platform = comm.mesh.devices.flat[0].platform
    sync = profiler.sync
    n = args.size

    x = ht.random.randn(n, n, split=ht.axisspec.named(0))
    y = ht.random.randn(n, n, split=ht.axisspec.named(0))

    # --- compiled-program floor ---------------------------------------- #
    # a pre-built jitted (add + placement) on the raw arrays: the fastest
    # any dispatch layer could possibly go on this host.  Subtracted from
    # the measured paths to isolate pure Python dispatch overhead.
    j1, j2 = x._jarray, y._jarray
    floor_prog = jax.jit(lambda a, b: comm.shard(jnp.add(a, b), 0))

    # the seed dispatch, measured in-process: _FORCE_SLOW routes _binary_op
    # through its general path, which is the pre-cache implementation
    # preserved verbatim (metadata recompute + eager jnp op + post-hoc
    # placement + full wrap)
    from heat_tpu.core import _operations

    def eager_reference():
        _operations._FORCE_SLOW = True
        try:
            return x + y
        finally:
            _operations._FORCE_SLOW = False

    floor_prog(j1, j2)
    _ = x + y  # build + compile the cached program once
    eager_reference()
    s_floor, s_cached, s_eager = _time_interleaved(
        [lambda: floor_prog(j1, j2), lambda: x + y, eager_reference],
        sync,
        args.reps,
    )
    floor_us, cached_us, eager_us = (min(s_floor), min(s_cached), min(s_eager))
    overhead_us = _paired_delta(s_cached, s_floor)
    eager_overhead_us = _paired_delta(s_eager, s_floor)

    # --- telemetry-on dispatch overhead (ISSUE 3 contract) ------------- #
    # same interleaved paired-delta methodology: cached dispatch with the
    # telemetry hook disarmed vs armed, each vs the compiled floor.  The
    # toggle is the raw _operations module global (exactly what enable()/
    # disable() poke) so both timed paths carry identical toggle cost.
    from heat_tpu.core import _operations as _ops

    telemetry.enable()   # arm the recording machinery (ring etc.)

    def cached_tel_off():
        _ops._TELEMETRY = None
        return x + y

    def cached_tel_on():
        _ops._TELEMETRY = telemetry
        return x + y

    cached_tel_on()
    cached_tel_off()
    s_floor2, s_tel_off, s_tel_on = _time_interleaved(
        [lambda: floor_prog(j1, j2), cached_tel_off, cached_tel_on],
        sync,
        args.reps,
    )
    _ops._TELEMETRY = None
    telemetry.disable()
    # the ADDED cost is the direct pairwise on-vs-off delta (same round,
    # back-to-back): host-load swings cancel without routing through the
    # floor twice; the floor delta only normalizes it into a percentage
    tel_off_oh = max(_paired_delta(s_tel_off, s_floor2), 1.0)
    tel_added_us = _paired_delta(s_tel_on, s_tel_off)
    tel_added_pct = tel_added_us / tel_off_oh * 100.0

    # --- flight-recorder-on dispatch overhead (ISSUE 7 contract) ------- #
    # identical methodology: cached dispatch with the flightrec hook
    # disarmed vs armed (a REAL mmap ring in a tmpdir — the armed path pays
    # the full record_dispatch cost: the coalescing per-op counter bump,
    # with ring writes deferred to full-record boundaries), paired against
    # the compiled floor in the same interleaved rounds.
    import shutil
    import tempfile

    from heat_tpu.utils import flightrec

    fr_ring_dir = tempfile.mkdtemp(prefix="bench_flightrec_")
    flightrec.enable(fr_ring_dir, rank=0)

    def cached_fr_off():
        _ops._FLIGHTREC = None
        return x + y

    def cached_fr_on():
        _ops._FLIGHTREC = flightrec
        return x + y

    def cached_fr_off2():  # second, identical off path: the NULL measurement
        _ops._FLIGHTREC = None
        return x + y

    cached_fr_on()
    cached_fr_off()
    # rotated pairwise + null + q25 wholesale-shift guard — the shared
    # throttled-host hardening, see _rotated_hook_gate
    fr_off_oh, fr_added_us, fr_noise_us, fr_consistent, fr_added_pct = (
        _rotated_hook_gate(
            lambda: floor_prog(j1, j2), cached_fr_off, cached_fr_off2,
            cached_fr_on, sync, args.reps,
        )
    )
    _ops._FLIGHTREC = None
    flightrec.disable()
    shutil.rmtree(fr_ring_dir, ignore_errors=True)

    # --- memory-ledger-armed dispatch overhead (ISSUE 14 contract) ----- #
    # identical rotated-pairwise methodology to the flightrec gate: cached
    # dispatch with the ledger hooks disarmed vs armed, an off-vs-off null
    # in the same rounds as the noise floor, and the q25 wholesale-shift
    # guard.  What the armed path pays HERE is exactly what production's
    # hot loop pays: these 256 KiB outputs sit under the 1 MiB dispatch
    # threshold, so each dispatch is register_dispatch's COALESCED tier —
    # one call + aval byte math + a counter bump (the flightrec cost
    # class).  The full register() path (weakref + entry + provenance,
    # ~5 µs) is deliberately NOT gated at 5%: it runs only for ≥1 MiB
    # buffers, where microseconds amortize against megabyte lifetimes —
    # its correctness (and cost class) is pinned by tests/test_memledger
    # instead.  BOTH hook modules toggle (dispatch tail + _from_parts).
    ml_added_pct = ml_added_us = ml_off_oh = ml_noise_us = float("nan")
    ml_consistent = False
    if args.memledger_gate is not None:
        from heat_tpu.core import dndarray as _dnd
        from heat_tpu.utils import memledger

        memledger.enable()

        def cached_ml_off():
            _ops._MEMLEDGER = None
            _dnd._MEMLEDGER = None
            return x + y

        def cached_ml_on():
            _ops._MEMLEDGER = memledger
            _dnd._MEMLEDGER = memledger
            return x + y

        def cached_ml_off2():  # second, identical off path: the NULL
            _ops._MEMLEDGER = None
            _dnd._MEMLEDGER = None
            return x + y

        cached_ml_on()
        cached_ml_off()
        ml_off_oh, ml_added_us, ml_noise_us, ml_consistent, ml_added_pct = (
            _rotated_hook_gate(
                lambda: floor_prog(j1, j2), cached_ml_off, cached_ml_off2,
                cached_ml_on, sync, args.reps,
            )
        )
        memledger.disable()

    # --- monitor-armed dispatch overhead (ISSUE 11 contract) ----------- #
    # the /metrics endpoint adds NO hot-path hook (there is nothing to
    # poke: scrapes snapshot the registries from a server thread), so the
    # only possible cost is scrape-thread GIL/cache interference with the
    # dispatching main thread.  Measured per round, quiet vs actively
    # scraped at 10 Hz — an order of magnitude HOTTER than any sane
    # production cadence (Prometheus defaults to 15 s), but not a busy
    # loop: a busy-loop scraper measures GIL starvation of the scraper's
    # own making, not the endpoint's dispatch-path cost (measured: 5 ms
    # cadence reads ~60% on a throttled host, 100 ms reads ~0).  Each
    # state pairs against the compiled floor IN THE SAME STATE, and —
    # like the flightrec gate — a failure requires the paired deltas to
    # shift WHOLESALE (q25 > 0): a real regression taxes every round,
    # while a scrape landing inside a few timed windows cannot.
    mon_added_pct = mon_added_us = mon_off_oh = float("nan")
    mon_consistent = False
    if args.monitor_gate is not None:
        import threading as _threading
        import urllib.request as _url

        from heat_tpu.utils import monitor as _monitor

        mhost, mport = _monitor.enable()
        murl = f"http://{mhost}:{mport}/metrics"
        scraping = _threading.Event()
        stop_scraper = _threading.Event()

        def _scrape_loop():
            while not stop_scraper.wait(0.1):
                if scraping.is_set():
                    try:
                        with _url.urlopen(murl, timeout=5) as resp:
                            resp.read()
                    except Exception:
                        pass

        scr_thread = _threading.Thread(target=_scrape_loop, daemon=True)
        scr_thread.start()
        s_floor_q, s_mon_q, s_floor_s, s_mon_s = [], [], [], []
        for _ in range(args.reps):
            for active, fl, ca in (
                (False, s_floor_q, s_mon_q),
                (True, s_floor_s, s_mon_s),
            ):
                scraping.set() if active else scraping.clear()
                for fn, out_samples in (
                    (lambda: floor_prog(j1, j2), fl),
                    (lambda: x + y, ca),
                ):
                    t0 = time.perf_counter()
                    out = None
                    for _ in range(20):
                        out = fn()
                    sync(out)
                    out_samples.append((time.perf_counter() - t0) / 20 * 1e6)
        scraping.clear()
        stop_scraper.set()
        scr_thread.join(timeout=2.0)
        _monitor.disable()
        mon_off_oh = max(_paired_delta(s_mon_q, s_floor_q), 1.0)
        oh_scraped = [c - f for c, f in zip(s_mon_s, s_floor_s)]
        oh_quiet = [c - f for c, f in zip(s_mon_q, s_floor_q)]
        d_mon = sorted(a - b for a, b in zip(oh_scraped, oh_quiet))
        mon_added_us = max(d_mon[len(d_mon) // 2], 0.0)
        mon_consistent = d_mon[len(d_mon) // 4] > 0.0
        mon_added_pct = mon_added_us / mon_off_oh * 100.0

    # --- zero-recompilation across >=100 repeated same-signature ops --- #
    for _ in range(2):  # warm every signature used below
        _ = x + y, x * y, ht.exp(x), ht.sum(x, axis=0), ht.cumsum(x, axis=1)
    profiler.reset_cache_stats()
    for _ in range(25):
        _ = x + y
        _ = x * y
        _ = ht.exp(x)
        _ = ht.sum(x, axis=0)
        _ = ht.cumsum(x, axis=1)
    stats = profiler.cache_stats()
    hit_rate = profiler.cache_hit_rate()

    # --- reduction + matmul cached latencies --------------------------- #
    reduce_us = _time_op(lambda: ht.sum(x, axis=0), sync, args.reps)
    mm_a = ht.random.randn(n, n, split=ht.axisspec.named(0))
    mm_b = ht.random.randn(n, n, split=ht.axisspec.named(1))
    _ = mm_a @ mm_b
    matmul_us = _time_op(lambda: mm_a @ mm_b, sync, args.reps)

    # --- in-place donation surfaces ------------------------------------ #
    z = ht.random.randn(n, n, split=ht.axisspec.named(0))
    z += 1.0  # warm the donating program
    iadd_us = _time_op((lambda: z.__iadd__(1.0)), sync, max(args.reps // 2, 5))
    prog_alias = "unknown"
    try:
        from heat_tpu.core import _cache as _c

        table = z.comm.__dict__["_compiled_programs"][_c._DISPATCH_SLOT]
        donating = [v for k, v in table.items() if k[0] == "binary" and k[4]]
        hlo = donating[-1][0].lower(z._jarray, 1.0).compile().as_text()
        prog_alias = "input_output_alias" in hlo
    except Exception:
        pass

    # both variants alternate 0→1 and 1→0 so each per-call figure is the
    # same direction mix
    # memory_budget=0 pins the monolithic path throughout: these rows are
    # labeled monolithic and must not silently stream under an inherited
    # HEAT_TPU_RESPLIT_BUDGET / process default
    r = ht.random.randn(n, n, split=ht.axisspec.named(0))
    r.resplit_(1, memory_budget=0)  # warm both directions
    r.resplit_(0, memory_budget=0)

    def flip():
        r.resplit_(1 if r.split == 0 else 0, memory_budget=0)
        return r

    rc0 = ht.random.randn(n, n, split=ht.axisspec.named(0))
    rc1 = rc0.resplit(1, memory_budget=0)
    copy_state = [0]

    def copy_flip():
        copy_state[0] ^= 1
        return (
            rc0.resplit(1, memory_budget=0)
            if copy_state[0]
            else rc1.resplit(0, memory_budget=0)
        )

    # batch=1 (sync every call): in-place resplits form a serial dependency
    # chain, so batching would let only the copy variant overlap transfers
    resplit_us, resplit_copy_us = _mins(
        _time_interleaved([flip, copy_flip], sync, args.reps, batch=1)
    )

    rss_inplace = rss_copy = float("nan")
    if not args.skip_rss:
        rss_size = 2048
        rss_inplace = _peak_rss_subprocess("inplace", rss_size)
        rss_copy = _peak_rss_subprocess("copy", rss_size)

    # --- budgeted-resplit peak-RSS gate (ISSUE 6) ---------------------- #
    # the memory contract of the chunked pipeline, measured: beyond the
    # source (inside base) and the preallocated destination, the transient
    # working set is at most budget + one tile.  The monolithic copy path
    # is captured side by side as the comparison row.
    resplit_gate_ok = True
    resplit_payload = None
    if args.resplit_gate or args.resplit_out:
        shape = tuple(args.resplit_shape)
        budget = int(args.resplit_budget_mb * 1024 * 1024)
        # ONE unit everywhere: MiB, matching ru_maxrss/1024 (base_mb/peak_mb)
        # and budget_mb — mixing in decimal MB here loosened the bound by
        # ~4 MB and understated the reported transient by ~3 MiB
        arr_mb = (shape[0] * shape[1] * shape[2] * 4) / 2**20
        bud = _peak_rss_resplit(shape, budget, "budgeted")
        mono = _peak_rss_resplit(shape, 0, "copy")
        tile_mb = bud["peak_tile_bytes"] / 2**20
        # base already contains the source; the destination is a hard
        # requirement of ANY resplit, so the gate bound is
        # base + |dst| + budget + one tile + allocator slack
        allowed_mb = (
            bud["base_mb"] + arr_mb + args.resplit_budget_mb + tile_mb
            + args.resplit_slack_mb
        )
        transient_mb = bud["peak_mb"] - bud["base_mb"] - arr_mb
        resplit_payload = {
            "metric": "resplit_budgeted_transient_mb",
            "value": round(transient_mb, 1),
            "unit": "MB above source+destination (bound: budget + one tile)",
            "vs_baseline": None,
            "extra": {
                "platform": platform,
                "n_devices": n_dev,
                "array_shape": list(shape),
                "array_mb": round(arr_mb, 1),
                "budget_mb": args.resplit_budget_mb,
                "tiles": bud["tiles"],
                "peak_tile_mb": round(tile_mb, 1),
                "gate_allowed_peak_rss_mb": round(allowed_mb, 1),
                "budgeted_base_rss_mb_snapshot": round(bud["base_mb"], 1),
                "budgeted_peak_rss_mb_snapshot": round(bud["peak_mb"], 1),
                "monolithic_copy_peak_rss_mb_snapshot": round(mono["peak_mb"], 1),
                "monolithic_copy_transient_mb_snapshot": round(
                    mono["peak_mb"] - mono["base_mb"] - arr_mb, 1
                ),
                "resplit_bytes_accounted": bud["resplit_bytes"],
                "slack_mb": args.resplit_slack_mb,
                "provenance": "benchmarks/dispatch.py --resplit-gate, fresh "
                              "subprocess per capture (allocator history "
                              "cannot pollute the peak)",
            },
        }
        print(json.dumps(resplit_payload, indent=1))
        if bud["tiles"] < 2:
            resplit_gate_ok = False
            print(
                f"RESPLIT GATE: expected a chunked plan, got tiles={bud['tiles']}"
                " (planner fell back to monolithic?)",
                file=sys.stderr,
            )
        if not (bud["peak_mb"] <= allowed_mb):  # NaN-safe: fails on nan
            resplit_gate_ok = False
            print(
                f"RESPLIT GATE: budgeted resplit peaked at {bud['peak_mb']:.0f} MB"
                f" > allowed {allowed_mb:.0f} MB (base {bud['base_mb']:.0f}"
                f" + dst {arr_mb:.0f} + budget {args.resplit_budget_mb:.0f}"
                f" + tile {tile_mb:.0f} + slack {args.resplit_slack_mb:.0f})",
                file=sys.stderr,
            )
        if args.resplit_out:
            with open(args.resplit_out, "w") as fh:
                json.dump(resplit_payload, fh, indent=1)
        if not args.resplit_gate:
            resplit_gate_ok = True  # capture-only run: report, don't gate

    # --- overlapped-sync gate (ISSUE 16) ------------------------------- #
    # the perf contract of the bucketed lookahead-1 sync, measured: same
    # bytes on the wire, zero steady-state recompiles, and MORE of the
    # step hidden behind compute than the single-bucket sync manages.
    overlap_gate_ok = True
    overlap_payload = None
    if args.overlap_gate or args.overlap_out:
        cap = _overlap_capture(
            args.overlap_steps, args.overlap_warmup, args.overlap_budget
        )
        if not cap:
            overlap_gate_ok = False
            print("OVERLAP GATE: capture subprocess failed", file=sys.stderr)
        else:
            ov = cap["overlap"]
            ab = cap["allreduce_bytes"]
            overlap_payload = {
                "metric": "daso_sync_overlap_gain",
                "value": round(ov["bucketed"] - ov["monolithic"], 4),
                "unit": "overlap fraction gained (bucketed - monolithic, "
                        "median over rotated pairs; 1 - wait/step)",
                "vs_baseline": None,
                "extra": {
                    "platform": platform,
                    "n_devices": n_dev,
                    "overlap_monolithic": ov["monolithic"],
                    "overlap_bucketed": ov["bucketed"],
                    "step_ms_snapshot": cap["step_ms"],
                    "wait_ms_snapshot": cap["wait_ms"],
                    "allreduce_bytes": ab,
                    "n_buckets": cap["n_buckets"],
                    "bucket_budget": args.overlap_budget,
                    "measured_steps_per_arm": args.overlap_steps,
                    "steady_cache_misses": cap["steady_cache_misses"],
                    "steady_cache_hits": cap["steady_cache_hits"],
                    "provenance": "benchmarks/dispatch.py --overlap-gate, "
                                  "fresh subprocess, rotated AB/BA step "
                                  "pairs on the host mesh",
                },
            }
            print(json.dumps(overlap_payload, indent=1))
            if cap["n_buckets"].get("bucketed", 0) < 2:
                overlap_gate_ok = False
                print(
                    f"OVERLAP GATE: expected a multi-bucket plan, got "
                    f"{cap['n_buckets']} (budget {args.overlap_budget})",
                    file=sys.stderr,
                )
            if ab.get("monolithic") != ab.get("bucketed") or not ab.get("bucketed"):
                overlap_gate_ok = False
                print(
                    f"OVERLAP GATE: comm.allreduce.bytes must be byte-"
                    f"identical across arms, got {ab} (the telescoped "
                    f"stage accounting broke)",
                    file=sys.stderr,
                )
            if cap["steady_cache_misses"] != 0:
                overlap_gate_ok = False
                print(
                    f"OVERLAP GATE: {cap['steady_cache_misses']} steady-state "
                    f"recompiles after warmup (contract: 0)",
                    file=sys.stderr,
                )
            if not (ov["bucketed"] > ov["monolithic"]):
                overlap_gate_ok = False
                print(
                    f"OVERLAP GATE: bucketed sync hides no more comm than "
                    f"monolithic (overlap {ov['bucketed']:.3f} vs "
                    f"{ov['monolithic']:.3f})",
                    file=sys.stderr,
                )
            if args.overlap_out:
                with open(args.overlap_out, "w") as fh:
                    json.dump(overlap_payload, fh, indent=1)
        if not args.overlap_gate:
            overlap_gate_ok = True  # capture-only run: report, don't gate

    # Row-name scheme (scripts/bench_compare.py infers direction by name):
    # the TRACKED contract rows are the host-portable ratios (*_speedup,
    # higher-better); absolute µs figures carry a *_snapshot suffix — no
    # latency/overhead fragment — so they are reported but never flagged:
    # they swing ±2x between hosts and runs, and a same-payload comparison
    # must not fail CI on scheduler noise.
    payload = {
        "metric": "dispatch_overhead_speedup",
        "value": round(max(eager_overhead_us, 1.0) / max(overhead_us, 1.0), 3),
        "unit": "x (seed dispatch overhead / cached dispatch overhead)",
        "vs_baseline": None,
        "extra": {
            "platform": platform,
            "n_devices": n_dev,
            "op_size": n,
            "dispatch_walltime_speedup": round(eager_us / cached_us, 3)
            if cached_us
            else None,
            "recompilations_100_ops": stats["misses"],
            "cache_hits_100_ops": stats["hits"],
            "cache_hit_rate": round(hit_rate, 4),
            "iadd_donation_aliased": prog_alias,
            "dispatch_floor_us_snapshot": round(floor_us, 2),
            "dispatch_cached_us_snapshot": round(cached_us, 2),
            "dispatch_seed_path_us_snapshot": round(eager_us, 2),
            "dispatch_cost_above_floor_us_snapshot": round(max(overhead_us, 1.0), 2),
            "seed_cost_above_floor_us_snapshot": round(
                max(eager_overhead_us, 1.0), 2
            ),
            "reduce_cached_us_snapshot": round(reduce_us, 2),
            "matmul_cached_us_snapshot": round(matmul_us, 2),
            "iadd_donating_us_snapshot": round(iadd_us, 2),
            "resplit_inplace_us_snapshot": round(resplit_us, 2),
            "resplit_copy_us_snapshot": round(resplit_copy_us, 2),
            "resplit_peak_rss_mb_inplace": round(rss_inplace, 1),
            "resplit_peak_rss_mb_copy": round(rss_copy, 1),
            # *_snapshot / no overhead-latency fragment: reported, never
            # flagged by bench_compare — the gate below owns the contract
            "telemetry_off_above_floor_us_snapshot": round(tel_off_oh, 2),
            "telemetry_on_added_us_snapshot": round(tel_added_us, 2),
            "telemetry_on_added_dispatch_pct": round(tel_added_pct, 1),
            "flightrec_off_above_floor_us_snapshot": round(fr_off_oh, 2),
            "flightrec_on_added_us_snapshot": round(fr_added_us, 2),
            "flightrec_on_added_dispatch_pct": round(fr_added_pct, 1),
            "flightrec_noise_floor_us_snapshot": round(fr_noise_us, 2),
            # NaN-guarded like the monitor rows below: a run without
            # --memledger-gate must not write the invalid `NaN` token
            "memledger_off_above_floor_us_snapshot": round(ml_off_oh, 2)
            if ml_off_oh == ml_off_oh else None,
            "memledger_on_added_us_snapshot": round(ml_added_us, 2)
            if ml_added_us == ml_added_us else None,
            "memledger_on_added_dispatch_pct": round(ml_added_pct, 1)
            if ml_added_pct == ml_added_pct else None,
            "memledger_noise_floor_us_snapshot": round(ml_noise_us, 2)
            if ml_noise_us == ml_noise_us else None,
            # NaN-guarded (x == x): a run without --monitor-gate must not
            # write the invalid-strict-JSON `NaN` token into the payload
            "monitor_quiet_above_floor_us_snapshot": round(mon_off_oh, 2)
            if mon_off_oh == mon_off_oh else None,
            "monitor_scraped_added_us_snapshot": round(mon_added_us, 2)
            if mon_added_us == mon_added_us else None,
            "monitor_scraped_added_dispatch_pct": round(mon_added_pct, 1)
            if mon_added_pct == mon_added_pct else None,
            "provenance": "benchmarks/dispatch.py on the host mesh "
                          "(seed row = the pre-cache dispatch path, forced "
                          "via _FORCE_SLOW and measured in-run, interleaved)",
        },
    }
    print(json.dumps(payload, indent=1))
    # hits >= 100 guards the guard: misses==0 alone would also hold if every
    # signature fell through to the eager path (counted as "slow", not hits)
    ok = stats["misses"] == 0 and hit_rate >= 0.99 and stats["hits"] >= 100
    if not ok:
        print(f"WARNING: cache contract violated: {stats}", file=sys.stderr)
    gate_ok = True
    if args.telemetry_gate is not None and tel_added_pct > args.telemetry_gate:
        gate_ok = False
        print(
            f"TELEMETRY GATE: enabled telemetry adds {tel_added_pct:.1f}% "
            f"({tel_added_us:.2f} us) to the dispatch cost above floor "
            f"({tel_off_oh:.1f} us; limit {args.telemetry_gate:.1f}%)",
            file=sys.stderr,
        )
    flightrec_gate_ok = True
    if (
        args.flightrec_gate is not None
        and fr_added_pct > args.flightrec_gate
        and fr_added_us > fr_noise_us
        and fr_consistent
    ):
        flightrec_gate_ok = False
        print(
            f"FLIGHTREC GATE: the armed flight recorder adds {fr_added_pct:.1f}% "
            f"({fr_added_us:.2f} us) to the dispatch cost above floor "
            f"({fr_off_oh:.1f} us; limit {args.flightrec_gate:.1f}%, in-run "
            f"off-vs-off noise floor {fr_noise_us:.2f} us)",
            file=sys.stderr,
        )
    memledger_gate_ok = True
    if (
        args.memledger_gate is not None
        and ml_added_pct > args.memledger_gate
        and ml_added_us > ml_noise_us
        and ml_consistent
    ):
        memledger_gate_ok = False
        print(
            f"MEMLEDGER GATE: the armed device-memory ledger adds "
            f"{ml_added_pct:.1f}% ({ml_added_us:.2f} us) to the dispatch "
            f"cost above floor ({ml_off_oh:.1f} us; limit "
            f"{args.memledger_gate:.1f}%, in-run off-vs-off noise floor "
            f"{ml_noise_us:.2f} us, wholesale shift confirmed)",
            file=sys.stderr,
        )
    monitor_gate_ok = True
    if (
        args.monitor_gate is not None
        and mon_added_pct > args.monitor_gate
        and mon_consistent
    ):
        monitor_gate_ok = False
        print(
            f"MONITOR GATE: an actively scraped /metrics endpoint adds "
            f"{mon_added_pct:.1f}% ({mon_added_us:.2f} us) to the dispatch "
            f"cost above floor ({mon_off_oh:.1f} us; limit "
            f"{args.monitor_gate:.1f}%, wholesale shift confirmed)",
            file=sys.stderr,
        )
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(payload, fh, indent=1)
    if telemetry_armed:
        # the CI telemetry lane uploads this run's own spans as an artifact
        telemetry.enable()
        flushed = telemetry.flush()
        if flushed:
            print(f"telemetry flushed to {flushed}", file=sys.stderr)
    if not ok:
        return 3
    if not gate_ok:
        return 4
    if not resplit_gate_ok:
        return 5
    if not flightrec_gate_ok:
        return 6
    if not monitor_gate_ok:
        return 7
    if not memledger_gate_ok:
        return 8
    if not overlap_gate_ok:
        return 9
    return 0


if __name__ == "__main__":
    sys.exit(main())
