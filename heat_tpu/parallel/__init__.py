"""First-class distributed communication skeletons (SURVEY §5.7).

The reference contains three reusable comm patterns buried inside ops:
the **ring pipeline** (``spatial.cdist``), the **halo exchange**
(``signal.convolve``) and the **all-to-all axis swap** (``resplit_``).
Here they are public, named utilities built on ``shard_map`` +
``lax.ppermute``/``lax.all_to_all`` — and ``ring_attention`` demonstrates
the sequence/context-parallel composition they enable (ring attention's KV
rotation IS the cdist ring).
"""

from .ring import ring_map
from .halo import halo_exchange, with_halos
from .ring_attention import ring_attention, ring_self_attention
from .sample_sort import order_statistics_1d, sample_sort_1d
from .pipeline import pipeline_apply
from . import supervisor
from .supervisor import Supervisor, SupervisorResult
from . import scheduler
from .scheduler import Job, JobJournal, JobRejected, JournalSchemaError, Scheduler
from . import serving
from .serving import make_executor
from . import federation
from .federation import AdmissionPredictor, Federation, WorldHandle

__all__ = [
    "Supervisor",
    "SupervisorResult",
    "supervisor",
    "Scheduler",
    "Job",
    "JobJournal",
    "JobRejected",
    "JournalSchemaError",
    "scheduler",
    "serving",
    "make_executor",
    "federation",
    "Federation",
    "WorldHandle",
    "AdmissionPredictor",
    "pipeline_apply",
    "ring_map",
    "halo_exchange",
    "with_halos",
    "ring_attention",
    "ring_self_attention",
    "order_statistics_1d",
    "sample_sort_1d",
]
