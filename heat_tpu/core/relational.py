"""Relational operations (reference: ``heat/core/relational.py``)."""

from __future__ import annotations

import jax.numpy as jnp

from ._operations import _binary_op
from .dndarray import DNDarray

__all__ = ["eq", "equal", "ge", "greater_equal", "gt", "greater", "le", "less_equal", "lt", "less", "ne", "not_equal"]


def eq(t1, t2) -> DNDarray:
    """Elementwise ``t1 == t2`` (bool result)."""
    return _binary_op(jnp.equal, t1, t2)


def equal(t1, t2) -> bool:
    """Scalar: True iff all elements equal (reference ``ht.equal``).

    Returns a Python bool — materialization is the contract, so the fetch
    routes through the sanctioned ``host_fetch`` instead of a naked
    ``.item()`` sync."""
    from .communication import Communication
    from .logical import all as ht_all

    try:
        res = eq(t1, t2)
    except ValueError:
        return False
    return bool(Communication.host_fetch(ht_all(res)._jarray))


def ge(t1, t2) -> DNDarray:
    return _binary_op(jnp.greater_equal, t1, t2)


greater_equal = ge


def gt(t1, t2) -> DNDarray:
    return _binary_op(jnp.greater, t1, t2)


greater = gt


def le(t1, t2) -> DNDarray:
    return _binary_op(jnp.less_equal, t1, t2)


less_equal = le


def lt(t1, t2) -> DNDarray:
    return _binary_op(jnp.less, t1, t2)


less = lt


def ne(t1, t2) -> DNDarray:
    return _binary_op(jnp.not_equal, t1, t2)


not_equal = ne

DNDarray.__eq__ = lambda self, other: eq(self, other)
DNDarray.__ne__ = lambda self, other: ne(self, other)
DNDarray.__lt__ = lambda self, other: lt(self, other)
DNDarray.__le__ = lambda self, other: le(self, other)
DNDarray.__gt__ = lambda self, other: gt(self, other)
DNDarray.__ge__ = lambda self, other: ge(self, other)
DNDarray.__hash__ = None
