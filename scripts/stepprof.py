"""Step-time breakdown: compute vs comm-wait vs host-sync vs idle, per step.

    python scripts/stepprof.py DIR_OR_FILE... [--steps NAME,NAME...]
                               [--json OUT] [--per-step N]

The topology-aware compute/comm-overlap work (ROADMAP; "The Big Send-off",
arXiv 2504.18658, frames collective cost as THE measurable dominant term
at pod scale) needs a measured baseline before it can claim a win: how
much of each training/serving step is computation, how much is the host
*blocked* on collectives, how much is device→host synchronization, and
how much is unattributed idle.  This tool decomposes exactly that from
the telemetry span export (``rank*.jsonl``, ``telemetry.flush``):

- a **step** is any span whose name is in ``--steps`` (default:
  ``daso.step``, ``optim.step``, ``nn.train_step``, ``sched.job``);
- a step's **window** runs from its start to the start of the same rank's
  next step of the same name (the full step CYCLE — the trailing
  ``comm.Wait`` and checkpoint IO between two steps belong to the step
  that incurred them; the last step's window ends at the last record it
  contains);
- every other record of that rank inside the window is classified —
  **host-sync** (``*host_fetch*``, ``io.*``), **comm-wait** (``comm.*``
  spans and the ``*.wait`` leaf records ``health.guard_blocking`` emits),
  **compute** (everything else: ``dispatch.*``, the step span itself) —
  and the window is swept once with class priority host > comm > compute,
  so overlapping records (a ``comm.resplit`` span containing its own tile
  waits) are never double-counted; uncovered window time is **idle**;
- the **overlap fraction** of a step is ``1 − comm_wait / window``: the
  share of the step cycle NOT exposed as blocking communication.  1.0
  means every byte moved behind compute; 0.0 means the step is pure
  comm-wait.

**What this measures (and what it cannot).**  XLA collectives run
asynchronously on device; Python only sees comm when it *blocks* (the
guarded waits, eager resplit transfers).  The fraction is therefore
computed from *exposed* comm-wait — comm fully hidden behind compute is
(correctly) invisible and counts as overlap, but device-side comm that
merely overlaps OTHER comm cannot be distinguished.  This is the honest
host-observable number, the before/after comparison the hierarchical-
collectives PR will be judged against: pipelining gradient allreduce
against the backward pass shrinks exposed comm-wait, which raises this
fraction — see design.md "Observability plane".

Deliberately stdlib-only and standalone-loadable:
``scripts/telemetry_report.py`` loads this file for its overlap section —
one implementation of the decomposition.

Exit code: 0 (a report, possibly empty); 1 when no rank files were found.
"""

from __future__ import annotations

import argparse
import glob
import json
import math
import os
import sys
from typing import Dict, List, Optional, Tuple

DEFAULT_STEPS = ("daso.step", "optim.step", "nn.train_step", "sched.job")

# class priorities for the sweep: lower wins where records overlap
_HOST, _COMM, _COMPUTE = 0, 1, 2
_CLASS_NAMES = {_HOST: "host_sync", _COMM: "comm_wait", _COMPUTE: "compute"}


def classify(name: str) -> int:
    """host-sync > comm-wait > compute (see module docstring)."""
    if "host_fetch" in name or name.startswith("io."):
        return _HOST
    if name.startswith("comm.") or name.endswith(".wait"):
        return _COMM
    return _COMPUTE


def find_rank_files(target: str) -> List[str]:
    if os.path.isdir(target):
        return sorted(glob.glob(os.path.join(target, "rank*.jsonl")))
    return [target] if os.path.exists(target) else []


def read_spans(paths: List[str]) -> List[dict]:
    spans = []
    for path in paths:
        try:
            with open(path) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if rec.get("type") == "span":
                        spans.append(rec)
        except OSError:
            continue
    return spans


def _sweep(window: Tuple[float, float],
           intervals: List[Tuple[float, float, int]]) -> Dict[str, float]:
    """One pass over the window: each elementary segment is charged to the
    highest-priority class active there; uncovered time is idle.  Robust
    to overlapping and nested records by construction."""
    w0, w1 = window
    total = max(w1 - w0, 0.0)
    out = {"compute": 0.0, "comm_wait": 0.0, "host_sync": 0.0, "idle": 0.0,
           "total": total}
    if total <= 0.0:
        return out
    clipped = []
    for a, b, cls in intervals:
        a, b = max(a, w0), min(b, w1)
        if b > a:
            clipped.append((a, b, cls))
    points = sorted({w0, w1} | {a for a, _, _ in clipped}
                    | {b for _, b, _ in clipped})
    for p0, p1 in zip(points, points[1:]):
        active = [cls for a, b, cls in clipped if a <= p0 and b >= p1]
        if active:
            out[_CLASS_NAMES[min(active)]] += p1 - p0
        else:
            out["idle"] += p1 - p0
    return out


def step_breakdown(
    spans: List[dict], step_names: Tuple[str, ...] = DEFAULT_STEPS
) -> List[dict]:
    """Per-step decomposition rows (see module docstring for the window
    and classification rules).  ``spans`` are telemetry span records; all
    ranks may be mixed — each rank's timeline is decomposed separately."""
    by_rank: Dict[int, List[dict]] = {}
    for s in spans:
        by_rank.setdefault(int(s.get("rank", 0)), []).append(s)
    rows: List[dict] = []
    for rank, recs in sorted(by_rank.items()):
        recs = sorted(recs, key=lambda r: float(r.get("ts", 0.0)))
        steps = [r for r in recs if r.get("name") in step_names]
        if not steps:
            continue
        last_end = max(
            float(r.get("ts", 0.0)) + float(r.get("dur_s", 0.0)) for r in recs
        )
        # windows per step NAME: consecutive daso.steps chain; an unrelated
        # sched.job stream on the same rank chains independently
        by_name: Dict[str, List[dict]] = {}
        for st in steps:
            by_name.setdefault(st["name"], []).append(st)
        for name, sts in by_name.items():
            for i, st in enumerate(sts):
                t0 = float(st.get("ts", 0.0))
                dur = float(st.get("dur_s", 0.0))
                if i + 1 < len(sts):
                    t1 = float(sts[i + 1].get("ts", 0.0))
                else:
                    t1 = max(t0 + dur, min(last_end, t0 + dur + 60.0))
                window = (t0, max(t1, t0 + dur))
                intervals = [(t0, t0 + dur, _COMPUTE)]  # the step span itself
                for r in recs:
                    if r is st or r.get("name") in step_names:
                        continue
                    a = float(r.get("ts", 0.0))
                    b = a + float(r.get("dur_s", 0.0))
                    if b <= window[0] or a >= window[1]:
                        continue
                    intervals.append((a, b, classify(str(r.get("name", "")))))
                parts = _sweep(window, intervals)
                total = parts["total"]
                rows.append({
                    "rank": rank,
                    "step": name,
                    # the span's sync= attribute ('monolithic'/'bucketed'),
                    # when the emitter labeled it — feeds STEP-OVERLAP-DELTA
                    "sync": (st.get("attrs") or {}).get("sync"),
                    "n": i,
                    "ts": round(t0, 6),
                    "total_s": round(total, 6),
                    "compute_s": round(parts["compute"], 6),
                    "comm_wait_s": round(parts["comm_wait"], 6),
                    "host_sync_s": round(parts["host_sync"], 6),
                    "idle_s": round(parts["idle"], 6),
                    "overlap_fraction": round(
                        1.0 - (parts["comm_wait"] / total if total else 0.0), 4
                    ),
                })
    return rows


def aggregate(rows: List[dict]) -> List[dict]:
    """Per step-name aggregate over all ranks: totals per class and the
    comm-weighted overlap fraction (Σ over steps, so a single long blocked
    step is not averaged away by many fast ones)."""
    agg: Dict[str, dict] = {}
    for r in rows:
        a = agg.setdefault(r["step"], {
            "step": r["step"], "steps": 0, "total_s": 0.0, "compute_s": 0.0,
            "comm_wait_s": 0.0, "host_sync_s": 0.0, "idle_s": 0.0,
            "ranks": set(),
        })
        a["steps"] += 1
        a["ranks"].add(r["rank"])
        for k in ("total_s", "compute_s", "comm_wait_s", "host_sync_s", "idle_s"):
            a[k] += r[k]
    out = []
    for name in sorted(agg):
        a = agg[name]
        total = a["total_s"]
        out.append({
            "step": name,
            "steps": a["steps"],
            "ranks": sorted(a["ranks"]),
            "total_s": round(total, 6),
            "compute_s": round(a["compute_s"], 6),
            "comm_wait_s": round(a["comm_wait_s"], 6),
            "host_sync_s": round(a["host_sync_s"], 6),
            "idle_s": round(a["idle_s"], 6),
            "overlap_fraction": round(
                1.0 - (a["comm_wait_s"] / total if total else 0.0), 4
            ),
        })
    return out


def overlap_delta(rows: List[dict]) -> Dict[str, Dict[str, float]]:
    """Per step-kind overlap fractions split by the steps' ``sync=`` span
    attribute — {kind: {'monolithic': f, 'bucketed': f}} for every kind
    whose merged input holds BOTH labels (a monolithic and a bucketed run
    flushed into the same directory).  The before/after comparison of the
    hierarchical-collectives work, computed from one merge dir so the two
    runs share clocks and methodology."""
    by: Dict[str, Dict[str, Dict[str, float]]] = {}
    for r in rows:
        lbl = r.get("sync")
        if lbl not in ("monolithic", "bucketed"):
            continue
        a = by.setdefault(r["step"], {}).setdefault(
            lbl, {"total": 0.0, "wait": 0.0}
        )
        a["total"] += r["total_s"]
        a["wait"] += r["comm_wait_s"]
    out: Dict[str, Dict[str, float]] = {}
    for kind in sorted(by):
        labels = by[kind]
        if {"monolithic", "bucketed"} <= set(labels):
            out[kind] = {
                lbl: round(
                    1.0 - (v["wait"] / v["total"] if v["total"] else 0.0), 4
                )
                for lbl, v in labels.items()
            }
    return out


def _pctl(values: List[float], q: float) -> float:
    """Exact upper percentile of a small sample (step counts are
    human-scale; same rule as telemetry_report's SLO table)."""
    if not values:
        return 0.0
    vs = sorted(values)
    idx = min(len(vs) - 1, max(0, int(math.ceil(q * len(vs))) - 1))
    return vs[idx]


def distribution(rows: List[dict]) -> Dict[str, Dict[str, float]]:
    """Per-step-kind p50/p99 of the window, exposed comm-wait and overlap
    fraction ACROSS step cycles — the aggregate STEP-OVERLAP line hides a
    straggling cycle inside the mean; the tail percentiles don't."""
    by: Dict[str, List[dict]] = {}
    for r in rows:
        by.setdefault(r["step"], []).append(r)
    out: Dict[str, Dict[str, float]] = {}
    for kind in sorted(by):
        rs = by[kind]
        totals = [r["total_s"] for r in rs]
        waits = [r["comm_wait_s"] for r in rs]
        overlaps = [r["overlap_fraction"] for r in rs]
        out[kind] = {
            "n": len(rs),
            "total_s_p50": _pctl(totals, 0.5),
            "total_s_p99": _pctl(totals, 0.99),
            "comm_wait_s_p50": _pctl(waits, 0.5),
            "comm_wait_s_p99": _pctl(waits, 0.99),
            "overlap_p50": _pctl(overlaps, 0.5),
            "overlap_p99": _pctl(overlaps, 0.99),
        }
    return out


def _fmt_table(rows: List[List[str]], header: List[str]) -> str:
    widths = [max(len(str(r[i])) for r in [header] + rows) for i in range(len(header))]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    lines = [fmt.format(*header), fmt.format(*("-" * w for w in widths))]
    lines += [fmt.format(*(str(c) for c in r)) for r in rows]
    return "\n".join(lines)


def render(rows: List[dict], per_step: int = 0) -> str:
    """The report text: per-step-kind aggregate table, one greppable
    ``STEP-OVERLAP`` marker line per kind (CI asserts on these), and
    optionally the first ``per_step`` individual step rows."""
    if not rows:
        return ""
    out = ["-- step-time breakdown (compute | comm-wait | host-sync | idle) --"]
    aggs = aggregate(rows)
    table = [
        [a["step"], a["steps"], ",".join(str(r) for r in a["ranks"]),
         f"{a['total_s'] * 1e3:.1f}", f"{a['compute_s'] * 1e3:.1f}",
         f"{a['comm_wait_s'] * 1e3:.1f}", f"{a['host_sync_s'] * 1e3:.1f}",
         f"{a['idle_s'] * 1e3:.1f}", f"{a['overlap_fraction']:.3f}"]
        for a in aggs
    ]
    out.append(_fmt_table(table, [
        "step", "n", "ranks", "total_ms", "compute_ms", "comm_wait_ms",
        "host_sync_ms", "idle_ms", "overlap",
    ]))
    for a in aggs:
        out.append(
            f"STEP-OVERLAP kind={a['step']} steps={a['steps']} "
            f"overlap={a['overlap_fraction']:.3f} "
            f"comm_wait_ms={a['comm_wait_s'] * 1e3:.1f} "
            f"total_ms={a['total_s'] * 1e3:.1f}"
        )
    # per-cycle tail distribution beside each aggregate line (the
    # STEP-OVERLAP format above is pinned by test and stays untouched)
    for kind, d in distribution(rows).items():
        out.append(
            f"STEP-DIST kind={kind} n={d['n']} "
            f"total_ms_p50={d['total_s_p50'] * 1e3:.1f} "
            f"total_ms_p99={d['total_s_p99'] * 1e3:.1f} "
            f"comm_wait_ms_p50={d['comm_wait_s_p50'] * 1e3:.1f} "
            f"comm_wait_ms_p99={d['comm_wait_s_p99'] * 1e3:.1f} "
            f"overlap_p50={d['overlap_p50']:.3f} "
            f"overlap_p99={d['overlap_p99']:.3f}"
        )
    # monolithic-vs-bucketed delta, when both labeled runs share this merge
    # dir (the CI-greppable improvement line; the STEP-OVERLAP format above
    # is asserted elsewhere and stays untouched)
    for kind, f in overlap_delta(rows).items():
        out.append(
            f"STEP-OVERLAP-DELTA kind={kind} "
            f"monolithic={f['monolithic']:.3f} bucketed={f['bucketed']:.3f} "
            f"delta={f['bucketed'] - f['monolithic']:+.3f}"
        )
    if per_step > 0:
        out.append("")
        sub = rows[:per_step]
        out.append(_fmt_table(
            [
                [r["rank"], r["step"], r["n"], f"{r['total_s'] * 1e3:.1f}",
                 f"{r['compute_s'] * 1e3:.1f}", f"{r['comm_wait_s'] * 1e3:.1f}",
                 f"{r['host_sync_s'] * 1e3:.1f}", f"{r['idle_s'] * 1e3:.1f}",
                 f"{r['overlap_fraction']:.3f}"]
                for r in sub
            ],
            ["rank", "step", "#", "total_ms", "compute_ms", "comm_wait_ms",
             "host_sync_ms", "idle_ms", "overlap"],
        ))
    return "\n".join(out)


def overlap_section(spans: List[dict],
                    step_names: Tuple[str, ...] = DEFAULT_STEPS,
                    per_step: int = 0) -> str:
    """The embeddable form ``scripts/telemetry_report.py`` calls with its
    already-merged spans; '' when no step spans exist (the common
    non-training invocation prints nothing extra)."""
    rows = step_breakdown(
        [s for s in spans if s.get("type") == "span"], step_names
    )
    if not rows:
        return ""
    return "\n" + render(rows, per_step=per_step)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("targets", nargs="+",
                    help="telemetry dirs and/or rank*.jsonl files")
    ap.add_argument("--steps", default=",".join(DEFAULT_STEPS),
                    help="comma-separated step span names")
    ap.add_argument("--per-step", type=int, default=0,
                    help="also print the first N individual step rows")
    ap.add_argument("--json", default=None, help="write the per-step rows here")
    args = ap.parse_args(argv)

    paths: List[str] = []
    for t in args.targets:
        paths.extend(find_rank_files(t))
    paths = sorted(dict.fromkeys(paths))
    if not paths:
        print(f"no rank*.jsonl files under {args.targets}", file=sys.stderr)
        return 1
    step_names = tuple(s.strip() for s in args.steps.split(",") if s.strip())
    rows = step_breakdown(read_spans(paths), step_names)
    if not rows:
        print(f"no step spans ({', '.join(step_names)}) in {len(paths)} rank file(s)")
        return 0
    print(render(rows, per_step=args.per_step))
    if args.json:
        with open(args.json, "w") as fh:
            json.dump({"steps": rows, "aggregate": aggregate(rows),
                       "distribution": distribution(rows)}, fh, indent=1)
        print(f"\nper-step JSON written to {args.json}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
