"""Array factories (reference: ``heat/core/factories.py``, SURVEY §3.1).

The reference's ``array()`` materializes the full input on every rank, then
keeps only the local chunk.  Here the factory builds ONE global ``jax.Array``
and places it with the ``NamedSharding`` implied by ``split`` — XLA moves the
bytes.  ``is_split`` ingest (each process contributes its local chunk) maps to
assembling along the split axis then sharding; on a single controller it
degenerates to ``split=``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import _complexsafe, devices, sanitation, types
from .communication import Communication, sanitize_comm
from .dndarray import DNDarray
from .stride_tricks import sanitize_axis, sanitize_shape

# device-memory-ledger hook (``utils.memledger.enable()`` pokes the module
# in): ``_finalize``/``_filled`` are where every factory's buffer becomes
# live, so they are registration choke points.  Disabled cost: one
# module-global load (telemetry-hook pattern; module bottom re-arms).
_MEMLEDGER = None

__all__ = [
    "arange",
    "array",
    "asarray",
    "empty",
    "empty_like",
    "eye",
    "from_partitioned",
    "full",
    "full_like",
    "linspace",
    "logspace",
    "meshgrid",
    "ones",
    "ones_like",
    "zeros",
    "zeros_like",
]


def _finalize(
    jarr: jax.Array,
    split: Optional[int],
    device,
    comm,
    dtype=None,
) -> DNDarray:
    """Shard a raw jax array and wrap it as a DNDarray."""
    device = devices.sanitize_device(device)
    comm = sanitize_comm(comm)
    split = sanitize_axis(jarr.shape, split)
    if dtype is not None:
        dtype = types.canonical_heat_type(dtype)
        if jarr.dtype != dtype.jax_dtype():
            jarr = jarr.astype(dtype.jax_dtype())
    # derive the metadata dtype from the array the cast actually produced,
    # honoring JAX canonicalization (64→32-bit when x64 is off) like
    # DNDarray.astype does — a requested float64 with x64 off used to leave
    # float64 METADATA on a float32 buffer (runtime sanitizer's first catch)
    dtype = types.canonical_heat_type(jarr.dtype)
    jarr = comm.shard(jarr, split)
    ret = DNDarray(jarr, tuple(jarr.shape), dtype, split, device, comm, True)
    if _MEMLEDGER is not None:
        # ledger choke point: op=None -> the ledger's frame walk names the
        # public factory up-stack (arange/linspace/eye/..., skipping
        # comprehension frames — meshgrid/ix_ call from list comps)
        _MEMLEDGER.register(ret._parray, op=None, site="factory")
    # factory boundary of the runtime sanitizer (HEAT_TPU_CHECKS=1):
    # no-op unless armed, metadata-only when armed
    return sanitation.check(ret, "factory")


def array(
    obj,
    dtype=None,
    copy: Optional[bool] = None,
    ndmin: int = 0,
    order: str = "C",
    split: Optional[int] = None,
    is_split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """Create a DNDarray from array-like data — the workhorse factory.

    ``split=k`` shards axis ``k`` over the mesh; ``is_split=k`` declares the
    input to be this process's local chunk along ``k`` (single-controller: the
    chunks of all processes are the whole array, so it behaves as ``split``).
    """
    if split is not None and is_split is not None:
        raise ValueError("split and is_split are mutually exclusive")
    if isinstance(obj, DNDarray):
        jarr = obj._jarray
        comm = comm if comm is not None else obj.comm
        device = device if device is not None else obj.device
        if split is None and is_split is None:
            split = obj.split
    elif isinstance(obj, jax.Array):
        jarr = obj
    else:
        npa = np.asarray(obj)
        if npa.dtype == object:
            raise TypeError("invalid data of type object")
        with _complexsafe.creation_ctx(npa.dtype):
            jarr = jnp.asarray(npa)
    if dtype is not None:
        jdt = types.canonical_heat_type(dtype).jax_dtype()
        if jnp.issubdtype(jdt, jnp.complexfloating) and not _complexsafe.native_complex_supported():
            jarr = _complexsafe.to_host_backend(jarr)
        jarr = jarr.astype(jdt)
    while jarr.ndim < ndmin:
        jarr = jarr[jnp.newaxis]
    eff_split = split if split is not None else is_split
    return _finalize(jarr, eff_split, device, comm, dtype)


def asarray(obj, dtype=None, copy=None, order="C", is_split=None, device=None) -> DNDarray:
    return array(obj, dtype=dtype, copy=copy, order=order, is_split=is_split, device=device)


def _filled(shape, value, dtype, split, device, comm, like=None) -> DNDarray:
    shape = sanitize_shape(shape)
    dtype = types.canonical_heat_type(dtype)
    comm_s = sanitize_comm(comm)
    split_s = sanitize_axis(shape, split)
    jdt = dtype.jax_dtype()
    if jnp.issubdtype(jdt, jnp.complexfloating) and not _complexsafe.native_complex_supported():
        with _complexsafe.creation_ctx(jdt):
            jarr = jnp.full(shape, value, dtype=jdt)
    else:
        sharding = comm_s.sharding(len(shape), split_s)
        # jnp.full with out_sharding materializes each shard on its own device —
        # no host round-trip, no full replica (TPU-friendly for huge arrays)
        try:
            jarr = jnp.full(shape, value, dtype=jdt, out_sharding=sharding)
        except (TypeError, ValueError):
            jarr = comm_s.shard(jnp.full(shape, value, dtype=jdt), split_s)
    ret = DNDarray(jarr, shape, dtype, split_s, devices.sanitize_device(device), comm_s, True)
    if _MEMLEDGER is not None:
        _MEMLEDGER.register(ret._parray, op=None, site="factory")
    return ret


def zeros(shape, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    return _filled(shape, 0, dtype, split, device, comm)


def ones(shape, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    return _filled(shape, 1, dtype, split, device, comm)


def empty(shape, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    # XLA has no uninitialized buffers; empty == zeros (documented deviation)
    return _filled(shape, 0, dtype, split, device, comm)


def full(shape, fill_value, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    if dtype is None:
        dtype = types.heat_type_of(fill_value)
        if dtype is types.float64:
            dtype = types.float32
    return _filled(shape, fill_value, dtype, split, device, comm)


def _like(proto, factory, dtype, split, device, comm, **kw):
    if not isinstance(proto, DNDarray):
        proto = array(proto)
    return factory(
        proto.shape,
        dtype=dtype if dtype is not None else proto.dtype,
        split=split if split is not None else proto.split,
        device=device if device is not None else proto.device,
        comm=comm if comm is not None else proto.comm,
        **kw,
    )


def zeros_like(a, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    return _like(a, zeros, dtype, split, device, comm)


def ones_like(a, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    return _like(a, ones, dtype, split, device, comm)


def empty_like(a, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    return _like(a, empty, dtype, split, device, comm)


def full_like(a, fill_value, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    if not isinstance(a, DNDarray):
        a = array(a)
    return full(
        a.shape,
        fill_value,
        dtype=dtype if dtype is not None else a.dtype,
        split=split if split is not None else a.split,
        device=device if device is not None else a.device,
        comm=comm if comm is not None else a.comm,
    )


def arange(*args, dtype=None, split=None, device=None, comm=None) -> DNDarray:
    """``arange(stop)`` / ``arange(start, stop[, step])`` — reference-parity."""
    num_args = len(args)
    if num_args == 1:
        start, stop, step = 0, args[0], 1
    elif num_args == 2:
        start, stop, step = args[0], args[1], 1
    elif num_args == 3:
        start, stop, step = args
    else:
        raise TypeError(f"arange takes 1 to 3 positional arguments, got {num_args}")
    if dtype is None:
        all_ints = all(isinstance(a, (int, np.integer)) for a in (start, stop, step))
        dtype = types.int32 if all_ints else types.float32
    dtype = types.canonical_heat_type(dtype)
    jarr = jnp.arange(start, stop, step, dtype=dtype.jax_dtype())
    return _finalize(jarr, split, device, comm, dtype)


def linspace(
    start,
    stop,
    num: int = 50,
    endpoint: bool = True,
    retstep: bool = False,
    dtype=None,
    split=None,
    device=None,
    comm=None,
):
    num = int(num)
    jarr = jnp.linspace(float(start), float(stop), num, endpoint=endpoint, dtype=jnp.float32)
    res = _finalize(jarr, split, device, comm, dtype)
    if retstep:
        step = (float(stop) - float(start)) / max(1, (num - 1 if endpoint else num))
        return res, step
    return res


def logspace(
    start, stop, num=50, endpoint=True, base=10.0, dtype=None, split=None, device=None, comm=None
) -> DNDarray:
    jarr = jnp.logspace(float(start), float(stop), int(num), endpoint=endpoint, base=base, dtype=jnp.float32)
    return _finalize(jarr, split, device, comm, dtype)


def eye(shape, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    if isinstance(shape, (int, np.integer)):
        n, m = int(shape), int(shape)
    else:
        shape = sanitize_shape(shape)
        n, m = (shape[0], shape[0]) if len(shape) == 1 else shape[:2]
    dtype = types.canonical_heat_type(dtype)
    jarr = jnp.eye(n, m, dtype=dtype.jax_dtype())
    return _finalize(jarr, split, device, comm, dtype)


def meshgrid(*arrays, indexing: str = "xy") -> list:
    """Coordinate matrices from vectors. If any input is split, the result
    follows the reference's convention (first output split=0/second split=1
    under 'xy' is simplified to: all outputs split along the axis the split
    input occupies)."""
    comm = None
    device = None
    for a in arrays:
        if isinstance(a, DNDarray):
            comm, device = a.comm, a.device
            break
    jarrs = [a._jarray if isinstance(a, DNDarray) else jnp.asarray(a) for a in arrays]
    outs = jnp.meshgrid(*jarrs, indexing=indexing)
    # position of the first split input among ALL inputs (not just DNDarrays)
    split_in = next(
        (i for i, a in enumerate(arrays) if isinstance(a, DNDarray) and a.split is not None),
        None,
    )
    out_split = None
    if split_in is not None and len(arrays) >= 1:
        # vector i varies along output axis: 'xy' swaps the first two
        ax = split_in
        if indexing == "xy" and split_in in (0, 1) and len(arrays) >= 2:
            ax = 1 - split_in
        out_split = ax
    return [_finalize(o, out_split, device, comm) for o in outs]


def from_partitioned(x, comm=None) -> DNDarray:
    """Ingest an object exposing ``__partitioned__`` (reference parity)."""
    parts = x.__partitioned__
    shape = tuple(parts["shape"])
    tiling = parts.get("partition_tiling", (1,))
    split = None
    for i, t in enumerate(tiling):
        if t > 1:
            split = i
            break
    get = parts.get("get", lambda v: v)
    chunks = []
    for pos in sorted(parts["partitions"]):
        data = get(parts["partitions"][pos]["data"])
        chunks.append(np.asarray(data))
    full_arr = np.concatenate(chunks, axis=split or 0) if len(chunks) > 1 else chunks[0]
    return array(full_arr.reshape(shape), split=split, comm=comm)


def identity(n: int, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """n×n identity matrix (numpy ``identity``)."""
    return eye(int(n), dtype=dtype, split=split, device=device, comm=comm)


def geomspace(start, stop, num: int = 50, endpoint: bool = True, dtype=None, split=None, device=None, comm=None) -> DNDarray:
    """Log-spaced samples between start and stop (inclusive ends)."""
    dt = types.canonical_heat_type(dtype) if dtype is not None else types.float32
    jarr = jnp.geomspace(start, stop, num=num, endpoint=endpoint, dtype=dt.jax_dtype())
    return _finalize(jarr, split, device, comm, dt)


def tri(N: int, M=None, k: int = 0, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Lower-triangular ones matrix."""
    dt = types.canonical_heat_type(dtype)
    jarr = jnp.tri(int(N), None if M is None else int(M), k, dtype=dt.jax_dtype())
    return _finalize(jarr, split, device, comm, dt)


def vander(x, N=None, increasing: bool = False) -> DNDarray:
    """Vandermonde matrix of a 1-D input; rows follow the input's split."""
    from .dndarray import DNDarray as _D

    jx = x._jarray if isinstance(x, _D) else jnp.asarray(np.asarray(x))
    jarr = jnp.vander(jx, N=N, increasing=increasing)
    if isinstance(x, _D):
        split = 0 if x.split is not None else None
        jarr = x.comm.shard(jarr, split)
        return _D(jarr, tuple(jarr.shape), types.canonical_heat_type(jarr.dtype), split, x.device, x.comm, True)
    return _finalize(jarr, None, None, None, types.canonical_heat_type(jarr.dtype))


def indices(dimensions, dtype=types.int32, sparse: bool = False):
    """Grid-index arrays (numpy ``indices``); replicated."""
    dt = types.canonical_heat_type(dtype)
    res = jnp.indices(tuple(int(d) for d in dimensions), dtype=dt.jax_dtype(), sparse=sparse)
    if sparse:
        return tuple(_finalize(r, None, None, None, dt) for r in res)
    return _finalize(res, None, None, None, dt)


def ix_(*args):
    """Open-mesh index arrays from 1-D sequences (numpy ``ix_``)."""
    from .dndarray import DNDarray as _D

    js = [a._jarray if isinstance(a, _D) else jnp.asarray(np.asarray(a)) for a in args]
    outs = jnp.ix_(*js)
    return tuple(_finalize(o, None, None, None, types.canonical_heat_type(o.dtype)) for o in outs)


def diag_indices(n: int, ndim: int = 2):
    """Index arrays addressing the main diagonal of an ndim-cube."""
    res = jnp.diag_indices(int(n), int(ndim))
    return tuple(_finalize(r, None, None, None, types.canonical_heat_type(r.dtype)) for r in res)


def diag_indices_from(arr) -> tuple:
    if arr.ndim < 2 or len(set(arr.shape)) != 1:
        raise ValueError("input must be square along every axis")
    return diag_indices(arr.shape[0], arr.ndim)


def tril_indices_from(arr, k: int = 0):
    from .indexing import tril_indices

    if arr.ndim != 2:
        raise ValueError("input must be 2-D")
    return tril_indices(arr.shape[0], k=k, m=arr.shape[1])


def triu_indices_from(arr, k: int = 0):
    from .indexing import triu_indices

    if arr.ndim != 2:
        raise ValueError("input must be 2-D")
    return triu_indices(arr.shape[0], k=k, m=arr.shape[1])


def unravel_index(idx, shape):
    from .dndarray import DNDarray as _D

    ji = idx._jarray if isinstance(idx, _D) else jnp.asarray(np.asarray(idx))
    res = jnp.unravel_index(ji, tuple(int(s) for s in shape))
    if isinstance(idx, _D):
        outs = []
        for r in res:
            r = idx.comm.shard(r, idx.split)
            outs.append(_D(r, tuple(r.shape), types.canonical_heat_type(r.dtype), idx.split, idx.device, idx.comm, True))
        return tuple(outs)
    return tuple(_finalize(r, None, None, None, types.canonical_heat_type(r.dtype)) for r in res)


def ravel_multi_index(multi_index, dims, mode: str = "raise", order: str = "C"):
    from .dndarray import DNDarray as _D

    js = [m._jarray if isinstance(m, _D) else jnp.asarray(np.asarray(m)) for m in multi_index]
    dims_t = tuple(int(d) for d in dims)
    if mode == "raise":
        # numpy contract: out-of-bounds multi-indices are an error; validate
        # eagerly, then index with clip semantics.  ONE sanctioned host_fetch
        # for every axis's (min, max) pair (retried + deadline-guarded, see
        # choose()) instead of 2*ndim naked int() syncs
        checks = [(j, d) for j, d in zip(js, dims_t) if j.size]
        if checks:
            bounds = Communication.host_fetch(
                jnp.stack([jnp.stack([jnp.min(j), jnp.max(j)]) for j, _ in checks])
            )
            for (_j, d), bound in zip(checks, bounds):
                lo, hi = int(bound[0]), int(bound[1])
                if lo < 0 or hi >= d:
                    raise ValueError(f"invalid entry in coordinates array (range [{lo}, {hi}] for dim {d})")
        mode = "clip"
    res = jnp.ravel_multi_index(tuple(js), dims_t, mode=mode, order=order)
    proto = next((m for m in multi_index if isinstance(m, _D)), None)
    if proto is not None:
        r = proto.comm.shard(res, proto.split)
        return _D(r, tuple(r.shape), types.canonical_heat_type(r.dtype), proto.split, proto.device, proto.comm, True)
    return _finalize(res, None, None, None, types.canonical_heat_type(res.dtype))


def _window(fn, M: int) -> DNDarray:
    jarr = fn(int(M))
    return _finalize(jarr, None, None, None, types.canonical_heat_type(jarr.dtype))


def bartlett(M: int) -> DNDarray:
    return _window(jnp.bartlett, M)


def blackman(M: int) -> DNDarray:
    return _window(jnp.blackman, M)


def hamming(M: int) -> DNDarray:
    return _window(jnp.hamming, M)


def hanning(M: int) -> DNDarray:
    return _window(jnp.hanning, M)


def kaiser(M: int, beta: float) -> DNDarray:
    jarr = jnp.kaiser(int(M), beta)
    return _finalize(jarr, None, None, None, types.canonical_heat_type(jarr.dtype))


# the memory ledger may have been env-armed (HEAT_TPU_MEMLEDGER=1) while
# this module was still importing — re-read the flag now (defensive
# module-bottom re-arm, same pattern as _operations/communication)
import sys as _sys  # noqa: E402

_ml = _sys.modules.get("heat_tpu.utils.memledger")
if _ml is not None and _ml.enabled():
    _MEMLEDGER = _ml
del _sys, _ml

__all__ += [
    "bartlett",
    "blackman",
    "diag_indices",
    "diag_indices_from",
    "geomspace",
    "hamming",
    "hanning",
    "identity",
    "indices",
    "ix_",
    "kaiser",
    "ravel_multi_index",
    "tri",
    "tril_indices_from",
    "triu_indices_from",
    "unravel_index",
    "vander",
]
