"""Multi-world federation: ingress-fed dispatch across N supervised worlds.

The PR 9 scheduler is production-grade but in-process and single-world:
one ``Scheduler`` serves one SPMD world, and when that world dies for good
the only degradation mode is :meth:`Scheduler.drain`.  This module is the
layer above — the federator the ROADMAP's "serving at internet scale"
item names — turning world loss into a *degradation* instead of an outage:

1. **Ingress-fed admission.**  Jobs arrive through
   ``utils/monitor.py``'s HTTP ingress (``POST /submit``) or in-process
   :meth:`Federation.submit`.  Trace ids are minted at the edge (the
   HT109 choke-point contract), every acceptance/shed lands in a
   **federation-level journal** (the same crash-durable
   ``scheduler.JobJournal`` format), and rejection is synchronous and
   structured (:class:`scheduler.JobRejected` — surfaced as HTTP 429/413
   by the monitor).

2. **Memory-aware admission.**  :class:`AdmissionPredictor` keeps a
   persisted per-kind device-memory peak history (fed by
   ``serving.make_executor`` measuring each batch inside a
   ``memledger.peak_window``).  At submit time the job's predicted
   footprint (max observed peak × a safety factor) is checked against
   every healthy world's memledger headroom (capacity − heartbeat-carried
   live bytes): a job no world can fit is shed ``mem_infeasible`` *at the
   edge* — PR 14's OOM post-mortem turned into a prevented admission.

3. **Health-driven world state machine.**  Each world is
   ``healthy → draining → quarantined → retired``, driven by postmortem
   verdicts (:meth:`Federation.note_verdict`: a world that repeatedly
   reads ``straggler`` drains — no new assignments; one that reads
   ``oom`` is quarantined — its jobs are stolen) and by world death
   (:meth:`Federation.world_lost`).  Transitions are journaled and only
   move forward.

4. **Work-stealing dispatch + zero-loss stealing.**  Queued jobs go to
   the least-loaded healthy world (:meth:`Federation.assign` — an idle
   world steals the next job by having the smallest per-rank load).
   When a world is lost, every job it held that never reached a terminal
   record is requeued (``requeue`` records, journal-first) and
   reassigned: the chaos lane's proof is ``FED worlds=N lost=0`` after
   SIGKILLing an entire world mid-queue.

5. **Elastic resize.**  :func:`resize_target` /
   :meth:`Federation.resize_plan` derive per-world rank targets from the
   journal-visible queue depth; the supervisor applies them *between
   generations* (``Supervisor(resize=...)``) where the checkpoint
   world-reshaping path already guarantees state survives a world-size
   change.

Like ``supervisor.py``/``scheduler.py`` this module is stdlib-only and
standalone-loadable (``importlib.util.spec_from_file_location`` — the
launcher federates worlds without importing jax).  The sibling
``scheduler.py`` provides ``Job``/``JobJournal``/``JobRejected`` and the
journal idiom; it is imported in-package and spec-loaded standalone.
Every federation mutation inherits the **journal-before-mutation
contract** (heatlint HT112): the journal append comes first, and a failed
append propagates with nothing mutated.
"""

from __future__ import annotations

import copy
import json
import math
import os
import sys
import time
import weakref
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "HEALTHY",
    "DRAINING",
    "QUARANTINED",
    "RETIRED",
    "MEM_INFEASIBLE",
    "AdmissionPredictor",
    "WorldHandle",
    "Federation",
    "replay_federation",
    "requeue_set",
    "fed_summary",
    "attestation_line",
    "resize_target",
    "counters",
    "reset_counters",
]


def _scheduler_mod():
    """The sibling ``scheduler.py`` — in-package when this module was
    imported as part of ``heat_tpu``, spec-loaded standalone otherwise
    (both paths are stdlib-only; the standalone load is what keeps the
    federating launcher jax-free)."""
    if __package__:
        from . import scheduler as s

        return s
    import importlib.util

    name = "heat_federation_scheduler"
    if name in sys.modules:
        return sys.modules[name]
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "scheduler.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


_sched = _scheduler_mod()
Job = _sched.Job
JobJournal = _sched.JobJournal
JobRejected = _sched.JobRejected
job_trace_id = _sched.job_trace_id

# scheduler record types reused verbatim (one journal idiom repo-wide)
SUBMITTED = _sched.SUBMITTED
DISPATCHED = _sched.DISPATCHED
DONE = _sched.DONE
FAILED = _sched.FAILED
SHED = _sched.SHED
QUEUE_FULL = _sched.QUEUE_FULL

# federation-only record types
ASSIGNED = "assigned"  # job → world assignment
WORLD = "world"  # world state transition

# world states (the health state machine — transitions only move forward)
HEALTHY = "healthy"
DRAINING = "draining"
QUARANTINED = "quarantined"
RETIRED = "retired"
_STATE_ORDER = {HEALTHY: 0, DRAINING: 1, QUARANTINED: 2, RETIRED: 3}

# admission rejection reason introduced at this layer
MEM_INFEASIBLE = "mem_infeasible"


# ---------------------------------------------------------------------- #
# counters — module-local (standalone loads), mirrored into utils.profiler
# as the pre-prefixed "fed" provider when that is loaded
# ---------------------------------------------------------------------- #
_counters: Dict[str, int] = {}
_provider_registered = False


def counter_inc(name: str, n: int = 1) -> None:
    _counters[name] = _counters.get(name, 0) + int(n)
    _ensure_provider()


def counters() -> Dict[str, int]:
    return dict(_counters)


def reset_counters() -> None:
    _counters.clear()


def _ensure_provider() -> None:
    global _provider_registered
    if _provider_registered:
        return
    prof = sys.modules.get("heat_tpu.utils.profiler")
    if prof is None:
        return
    prof.register_counter_provider("fed", lambda: dict(_counters))
    _provider_registered = True


# ---------------------------------------------------------------------- #
# memory-aware admission: per-kind peak history → footprint prediction
# ---------------------------------------------------------------------- #
class AdmissionPredictor:
    """Persisted per-kind device-memory peak history.

    ``observe(kind, peak_bytes)`` records the memledger-measured
    *incremental* peak of one executed batch of ``kind`` (see
    ``serving.make_executor``'s ``memledger.peak_window`` bracket) and
    keeps the per-kind maximum; ``predict(kind)`` returns that maximum ×
    ``safety``, or ``None`` for a kind never observed.

    **Honesty caveats** (also in design.md): the prediction is a *recorded
    worst case*, not a bound — a payload larger than anything in history
    under-predicts (first ``n=4096`` matmul after a history of ``n=16``),
    and an unobserved kind predicts nothing at all (admitted
    optimistically; its first execution seeds the history).  The safety
    factor absorbs allocator slack, not payload growth.  What the
    predictor guarantees is only this: a job whose kind is KNOWN to peak
    beyond every world's headroom is shed at the edge instead of OOMing a
    world.

    Persistence is a tmp+rename JSON file — crash-safe, last-writer-wins
    (the per-kind max makes concurrent writers converge)."""

    def __init__(self, path: Optional[str] = None, safety: float = 1.2):
        self.path = path
        self.safety = float(safety)
        self.peaks: Dict[str, int] = {}
        if path and os.path.exists(path):
            try:
                with open(path) as fh:
                    data = json.load(fh)
                if isinstance(data, dict):
                    self.peaks = {
                        str(k): int(v)
                        for k, v in data.items()
                        if isinstance(v, (int, float)) and v >= 0
                    }
            except (OSError, ValueError):
                self.peaks = {}  # a torn history is an empty history

    def observe(self, kind: str, peak_bytes: int) -> None:
        """Record one measured peak; keeps the per-kind maximum and
        persists (atomic tmp+rename) when a path is configured."""
        peak_bytes = int(peak_bytes)
        if peak_bytes < 0:
            return
        prev = self.peaks.get(str(kind), -1)
        if peak_bytes <= prev:
            return
        self.peaks[str(kind)] = peak_bytes
        if self.path:
            tmp = f"{self.path}.tmp.{os.getpid()}"
            try:
                with open(tmp, "w") as fh:
                    json.dump(self.peaks, fh, sort_keys=True)
                os.replace(tmp, self.path)
            except OSError:
                pass  # history is advisory; never fail the serving path

    def predict(self, kind: str) -> Optional[int]:
        """Predicted footprint in bytes, or None for an unobserved kind
        (admitted optimistically — see the honesty caveats above)."""
        peak = self.peaks.get(str(kind))
        if peak is None:
            return None
        return int(math.ceil(peak * self.safety))


# ---------------------------------------------------------------------- #
# world handle: the federation-side view of one supervised world
# ---------------------------------------------------------------------- #
class WorldHandle:
    """One supervised world as the federator sees it: a name, a rank
    count, an optional device-memory capacity, an optional heartbeat dir
    (liveness + ``mem_live`` gauges ride the beacons), an optional
    scheduler-journal path (reconciliation + stealing evidence), and an
    optional in-process ``submit(job)`` hook for worlds living in the
    same process (tests, single-host serving)."""

    def __init__(
        self,
        name: str,
        *,
        n_ranks: int = 1,
        capacity_bytes: Optional[int] = None,
        heartbeat_dir: Optional[str] = None,
        journal_path: Optional[str] = None,
        submit: Optional[Callable[[Job], Any]] = None,
    ):
        self.name = str(name)
        self.n_ranks = max(1, int(n_ranks))
        self.capacity_bytes = None if capacity_bytes is None else int(capacity_bytes)
        self.heartbeat_dir = heartbeat_dir
        self.journal_path = journal_path
        self.submit = submit
        self.state = HEALTHY
        self.state_reason: Optional[str] = None
        self.verdicts: List[str] = []  # newest last
        self.assigned: set = set()  # job ids assigned, not yet terminal
        self.generation = 0

    # -- memory view ------------------------------------------------- #
    def live_bytes(self) -> Optional[int]:
        """Sum of the ranks' beacon-carried ``mem_live`` gauges (the
        memledger's live bytes riding the heartbeats), or None when no
        beacon carries one — the federation's read-only view of a
        world's device memory."""
        if not self.heartbeat_dir or not os.path.isdir(self.heartbeat_dir):
            return None
        total, seen = 0, False
        for fname in os.listdir(self.heartbeat_dir):
            if not (fname.startswith("rank") and fname.endswith(".json")):
                continue
            try:
                with open(os.path.join(self.heartbeat_dir, fname)) as fh:
                    payload = json.load(fh)
            except (OSError, ValueError):
                continue
            v = payload.get("mem_live") if isinstance(payload, dict) else None
            if isinstance(v, int):
                total += v
                seen = True
        return total if seen else None

    def headroom_bytes(self) -> Optional[int]:
        """capacity − live (None = unbounded: no capacity configured).
        With a capacity but no beacon-visible live bytes, the full
        capacity is the headroom (optimistic, like an unobserved kind)."""
        if self.capacity_bytes is None:
            return None
        return max(0, self.capacity_bytes - (self.live_bytes() or 0))

    def heartbeat_row(self, stale_after: float = 120.0) -> dict:
        """Per-world liveness summary from the beacons (rank count, worst
        age, min seq) — {} when no dir is configured."""
        if not self.heartbeat_dir or not os.path.isdir(self.heartbeat_dir):
            return {}
        now = time.time()
        ages, seqs = [], []
        for fname in os.listdir(self.heartbeat_dir):
            if not (fname.startswith("rank") and fname.endswith(".json")):
                continue
            path = os.path.join(self.heartbeat_dir, fname)
            try:
                ages.append(now - os.path.getmtime(path))
            except OSError:
                continue
            try:
                with open(path) as fh:
                    payload = json.load(fh)
                if isinstance(payload, dict) and isinstance(payload.get("seq"), int):
                    seqs.append(payload["seq"])
            except (OSError, ValueError):
                pass
        if not ages:
            return {}
        row = {
            "ranks_beating": len(ages),
            "worst_age_s": round(max(ages), 3),
            "stale": max(ages) > stale_after,
        }
        if seqs:
            row["min_seq"] = min(seqs)
            row["seq_lag"] = max(seqs) - min(seqs)
        return row


# ---------------------------------------------------------------------- #
# the federator
# ---------------------------------------------------------------------- #
class Federation:
    """Dispatch across N supervised worlds (see module docstring).

    The federation owns its OWN journal (``scheduler.JobJournal`` format)
    recording every acceptance, shed, world assignment, steal and
    terminal outcome — the cross-world truth the zero-loss proof replays.
    Per-world scheduler journals stay the per-world truth;
    :meth:`reconcile_world_journal` folds their terminal records up into
    the federation journal.

    Every mutation is journal-first (heatlint HT112): the
    ``self.journal.append`` happens before the state change it describes,
    so a failed append propagates with nothing mutated."""

    def __init__(
        self,
        journal: Optional[object] = None,  # path or JobJournal or None
        *,
        max_queue: int = 256,
        predictor: Optional[AdmissionPredictor] = None,
        straggler_drain_after: int = 2,
        stale_after: float = 120.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if isinstance(journal, str):
            journal = JobJournal(journal)
        self.journal = journal
        self.max_queue = int(max_queue)
        self.predictor = predictor
        self.straggler_drain_after = max(1, int(straggler_drain_after))
        self.stale_after = float(stale_after)
        self.clock = clock
        self.worlds: Dict[str, WorldHandle] = {}
        self._jobs: Dict[str, Job] = {}  # every job ever seen (incl. shed)
        self._queue: List[Job] = []
        self._assignment: Dict[str, str] = {}  # job id → world name
        self._order = 0
        self._ingress_seq = 0
        self._register_monitor_sources()

    # -- observability wiring ---------------------------------------- #
    def _register_monitor_sources(self) -> None:
        """Expose the federation view to ``utils.monitor`` iff loaded
        (``sys.modules`` only — this file must stay standalone-loadable):
        the ``/healthz`` federation rows + the ``fed_worlds_*`` gauges
        both read :meth:`health_report` through a weak reference, so a
        discarded federation is pruned at the next scrape."""
        mon = sys.modules.get("heat_tpu.utils.monitor")
        if mon is None:
            return
        ref = weakref.ref(self)

        def report():
            f = ref()
            return f.health_report() if f is not None else None

        try:
            mon.set_federation_source(report)
        except Exception:
            pass

    # -- worlds ------------------------------------------------------- #
    def add_world(
        self,
        name: str,
        *,
        n_ranks: int = 1,
        capacity_bytes: Optional[int] = None,
        heartbeat_dir: Optional[str] = None,
        journal_path: Optional[str] = None,
        submit: Optional[Callable[[Job], Any]] = None,
    ) -> WorldHandle:
        if name in self.worlds:
            raise ValueError(f"duplicate world {name!r}")
        w = WorldHandle(
            name,
            n_ranks=n_ranks,
            capacity_bytes=capacity_bytes,
            heartbeat_dir=heartbeat_dir,
            journal_path=journal_path,
            submit=submit,
        )
        # journal the birth too: replay then knows the full roster, so
        # `FED worlds=N` is derivable from the journal alone
        if self.journal is not None:
            self.journal.append({"type": WORLD, "world": w.name,
                                 "state": HEALTHY, "reason": "added",
                                 "ranks": w.n_ranks})
        self.worlds[name] = w
        return w

    def _transition(self, w: WorldHandle, state: str, reason: str) -> bool:
        """Move ``w`` forward in the state machine (never backward);
        journal-first.  Returns True when a transition happened."""
        if _STATE_ORDER.get(state, 0) <= _STATE_ORDER.get(w.state, 0):
            return False
        if self.journal is not None:
            self.journal.append({"type": WORLD, "world": w.name,
                                 "state": state, "reason": reason})
        w.state = state
        w.state_reason = reason
        counter_inc(f"fed.worlds.{state}")
        return True

    def note_verdict(self, world: str, verdict: Any) -> str:
        """Feed one postmortem verdict (a string or the analyzer's
        verdict dict) into ``world``'s health: ``oom`` quarantines
        immediately (its jobs are stolen — an OOMing world would convict
        whatever it runs next); ``straggler`` repeated
        ``straggler_drain_after`` times drains (in-flight work finishes,
        nothing new is assigned).  Returns the world's (possibly new)
        state."""
        w = self.worlds[world]
        v = verdict.get("verdict") if isinstance(verdict, dict) else verdict
        v = str(v or "inconclusive")
        w.verdicts.append(v)
        if v == "oom":
            if self._transition(w, QUARANTINED, "verdict:oom"):
                self._steal(w, reason="quarantined:oom")
        elif v == "straggler":
            tail = w.verdicts[-self.straggler_drain_after:]
            if (
                len(tail) == self.straggler_drain_after
                and all(t == "straggler" for t in tail)
            ):
                self._transition(
                    w, DRAINING,
                    f"verdict:straggler x{self.straggler_drain_after}",
                )
        return w.state

    def world_lost(self, world: str, reason: str = "world died") -> int:
        """An entire world is gone (supervisor gave up / every rank
        SIGKILLed): quarantine it and steal every non-terminal job it
        held.  Returns the number of jobs stolen back into the queue."""
        w = self.worlds[world]
        self._transition(w, QUARANTINED, reason)
        return self._steal(w, reason=reason)

    def retire(self, world: str) -> None:
        """Terminal: the world was torn down deliberately after draining/
        quarantine; it stops counting toward any health gate."""
        w = self.worlds[world]
        if w.assigned:
            self._steal(w, reason="retired with work in flight")
        self._transition(w, RETIRED, "retired")

    def _steal(self, w: WorldHandle, reason: str = "stolen") -> int:
        """Requeue every job assigned to ``w`` that never reached a
        terminal record — journal-first per job, so a crash mid-steal
        loses nothing (the un-stolen remainder is still journal-visibly
        assigned to ``w`` and a recovery steals it again)."""
        n = 0
        for jid in sorted(w.assigned):
            job = self._jobs.get(jid)
            if job is None or job.state in (DONE, FAILED, SHED):
                continue
            if self.journal is not None:
                self.journal.append({"type": "requeue", "id": jid,
                                     "world": w.name, "tid": job.trace_id})
            job.state = SUBMITTED
            self._assignment.pop(jid, None)
            self._queue.append(job)
            counter_inc("fed.stolen")
            n += 1
        w.assigned.clear()
        return n

    # -- admission ----------------------------------------------------- #
    def _shed(self, job: Job, reason: str, detail: str = "") -> JobRejected:
        # journal FIRST (the scheduler._shed ordering): a failed append
        # propagates with nothing mutated
        if self.journal is not None:
            self.journal.append({
                "type": SHED, "id": job.job_id, "kind": job.kind,
                "tenant": job.tenant, "reason": reason, "tid": job.trace_id,
            })
        job.state = SHED
        job.reason = reason
        self._jobs[job.job_id] = job
        counter_inc("fed.offered")
        counter_inc("fed.shed")
        counter_inc(f"fed.shed.{reason}")
        return JobRejected(reason, job.job_id, job.tenant, detail)

    def _mem_infeasible(self, job: Job) -> Optional[str]:
        """The admission prediction: detail string when NO healthy world's
        headroom fits the job's predicted footprint; None when feasible
        (or unpredictable — an unobserved kind admits optimistically, and
        a world with no capacity configured fits anything)."""
        if self.predictor is None:
            return None
        predicted = self.predictor.predict(job.kind)
        if predicted is None:
            return None
        rooms = [
            w.headroom_bytes()
            for w in self.worlds.values()
            if w.state == HEALTHY
        ]
        if not rooms or any(r is None for r in rooms):
            return None  # no healthy world yet / an uncapped world fits it
        best = max(rooms)
        if predicted <= best:
            return None
        return (
            f"predicted {predicted} B ({job.kind!r} peak history × "
            f"{self.predictor.safety}) exceeds every healthy world's "
            f"headroom (best {best} B)"
        )

    def submit(self, job: Job) -> str:
        """Admit ``job`` into the federation or raise
        :class:`JobRejected` synchronously (reasons: ``queue_full``,
        ``mem_infeasible``).  Trace identity is minted here — the edge —
        before any admission outcome, so even a shed job's record carries
        the id the client correlates on."""
        existing = self._jobs.get(job.job_id)
        if existing is not None and existing.state != SHED:
            raise ValueError(f"duplicate job id {job.job_id!r}")
        if job.trace_id is None:
            job.trace_id = job_trace_id(job.job_id, job.kind, job.tenant)
        if len(self._queue) >= self.max_queue:
            raise self._shed(
                job, QUEUE_FULL, f"federation queue at its {self.max_queue}-job bound"
            )
        detail = self._mem_infeasible(job)
        if detail is not None:
            raise self._shed(job, MEM_INFEASIBLE, detail)
        job.state = SUBMITTED
        job.submit_t = self.clock()
        self._order += 1
        job._order = self._order
        # journal BEFORE mutating (the submit() contract): a job the
        # journal never saw must not exist in federation state either
        if self.journal is not None:
            self.journal.append(job.to_submit_record())
        self._jobs[job.job_id] = job
        self._queue.append(job)
        counter_inc("fed.offered")
        counter_inc("fed.accepted")
        return job.job_id

    # -- ingress backend (utils/monitor.py HTTP protocol) -------------- #
    def _mint_id(self) -> str:
        while True:
            self._ingress_seq += 1
            jid = f"req{self._ingress_seq:06d}"
            if jid not in self._jobs:
                return jid

    def ingress_submit(self, payload: dict) -> dict:
        """``POST /submit`` backend: build a Job from the request body,
        admit it, answer ``{"id", "trace_id", "state"}``.  Raises
        ``ValueError`` for a malformed body (→ HTTP 400) and
        ``JobRejected`` for a shed (→ HTTP 429, structured)."""
        if not isinstance(payload, dict):
            raise ValueError("request body must be a JSON object")
        kind = payload.get("kind")
        if not kind or not isinstance(kind, str):
            raise ValueError("missing required field 'kind'")
        body = payload.get("payload")
        if body is not None and not isinstance(body, dict):
            raise ValueError("'payload' must be a JSON object")
        job = Job(
            job_id=str(payload.get("id") or self._mint_id()),
            kind=kind,
            tenant=str(payload.get("tenant", "default")),
            priority=int(payload.get("priority", 0) or 0),
            deadline_s=(
                float(payload["deadline_s"])
                if payload.get("deadline_s") is not None
                else None
            ),
            retry_budget=int(payload.get("retry_budget", 2) or 0),
            payload=dict(body or {}),
        )
        self.submit(job)
        return {"id": job.job_id, "trace_id": job.trace_id, "state": job.state}

    def ingress_status(self, job_id: str) -> Optional[dict]:
        """``GET /status/<id>`` backend: the job's current federation
        view, or None (→ 404) for an unknown id."""
        job = self._jobs.get(job_id)
        if job is None:
            return None
        return {
            "id": job.job_id,
            "kind": job.kind,
            "tenant": job.tenant,
            "state": job.state,
            "reason": job.reason,
            "world": self._assignment.get(job.job_id),
            "trace_id": job.trace_id,
        }

    def ingress_result(self, job_id: str) -> Optional[dict]:
        """``GET /result/<id>`` backend: terminal outcome + result when
        the job finished; a pending view otherwise; None (→ 404) for an
        unknown id."""
        job = self._jobs.get(job_id)
        if job is None:
            return None
        out = {"id": job.job_id, "state": job.state, "trace_id": job.trace_id}
        if job.state == DONE:
            out["result"] = job.result
        elif job.state in (FAILED, SHED):
            out["reason"] = job.reason
        else:
            out["detail"] = "not terminal yet; poll /status"
        return out

    # -- dispatch: least-loaded work-stealing assignment ---------------- #
    def assign(self) -> Dict[str, List[Job]]:
        """Assign every queued job to the least-loaded healthy world
        (assigned-per-rank, name tiebreak — deterministic) and return
        ``{world: [jobs newly assigned]}``.  An idle world steals the
        next job by construction; with no healthy world the queue simply
        holds (jobs shed later by deadline, never silently dropped).
        In-process worlds (``submit=`` hook) receive a copy immediately;
        file-fed worlds read their slice from the returned mapping."""
        out: Dict[str, List[Job]] = {}
        self._queue.sort(key=lambda j: (-j.priority, j._order))
        remaining: List[Job] = []
        for job in self._queue:
            # queue hygiene making assign() idempotent under recovery and
            # retry: a job folded up terminal by reconcile_world_journal
            # AFTER recover() requeued it must never dispatch again, and a
            # job a journal-faulted partial pass already assigned (state
            # flipped, still in the queue) must not be handed out twice —
            # it is tracked in its world's `assigned` set; both just leave
            # the queue
            if job.state in (DONE, FAILED, SHED, ASSIGNED):
                continue
            healthy = [w for w in self.worlds.values() if w.state == HEALTHY]
            if not healthy:
                remaining.append(job)
                continue
            w = min(
                healthy,
                key=lambda h: (len(h.assigned) / float(h.n_ranks), h.name),
            )
            if self.journal is not None:
                self.journal.append({"type": ASSIGNED, "id": job.job_id,
                                     "world": w.name, "tid": job.trace_id})
            job.state = ASSIGNED
            w.assigned.add(job.job_id)
            self._assignment[job.job_id] = w.name
            counter_inc("fed.assigned")
            out.setdefault(w.name, []).append(job)
            if w.submit is not None:
                # hand the world its own copy: an in-process scheduler
                # mutating the shared Job would flip federation state to
                # DONE without a federation journal record, so replay
                # would count the job lost and reconcile would skip it
                w.submit(copy.copy(job))
        self._queue = remaining
        return out

    # -- reconciliation: fold world journals up into the federation ----- #
    def reconcile_world_journal(self, world: str, path: Optional[str] = None) -> dict:
        """Replay ``world``'s scheduler journal and fold every terminal
        outcome of a federation-assigned job up into the federation
        journal (journal-first per record).  Jobs the world journal shows
        DONE carry their journaled result; everything the world accepted
        but never finished stays assigned — :meth:`world_lost` steals it.
        Returns ``{"done": n, "failed": n}``."""
        w = self.worlds[world]
        path = path or w.journal_path
        done = failed = 0
        if not path or not os.path.exists(path):
            return {"done": 0, "failed": 0}
        replay = _sched.replay_journal(path)
        for jid, view in replay["jobs"].items():
            job = self._jobs.get(jid)
            if job is None or job.state in (DONE, FAILED, SHED):
                continue
            state = view.get("state")
            if state == DONE:
                if self.journal is not None:
                    rec = {"type": DONE, "id": jid, "world": w.name,
                           "exec_s": view.get("exec_s"), "tid": job.trace_id}
                    if "result" in view:
                        rec["result"] = view.get("result")
                    self.journal.append(rec)
                job.state = DONE
                job.result = view.get("result")
                w.assigned.discard(jid)
                counter_inc("fed.done")
                done += 1
            elif state == FAILED:
                if self.journal is not None:
                    self.journal.append({"type": FAILED, "id": jid,
                                         "world": w.name,
                                         "reason": view.get("reason"),
                                         "tid": job.trace_id})
                job.state = FAILED
                job.reason = view.get("reason")
                w.assigned.discard(jid)
                counter_inc("fed.failed")
                failed += 1
        return {"done": done, "failed": failed}

    # -- recovery: the epoch-scoped anchor discipline, federation-level - #
    def recover(self, path: Optional[str] = None,
                epoch: Optional[int] = None) -> int:
        """Replay a federation journal after the federator itself
        restarted and requeue every accepted-but-unfinished job exactly
        once — :func:`requeue_set` is the shared derivation, so every
        replica replaying the same journal (the two-worlds determinism
        test) requeues the identical set in the identical order with the
        identical charged deadlines.  Assignments are NOT restored: the
        worlds behind them may be gone, and re-assignment through
        :meth:`assign` is idempotent at the journal level."""
        path = path or (self.journal.path if self.journal is not None else None)
        if path is None or not os.path.exists(path):
            return 0
        replay = replay_federation(path)
        now = self.clock()
        n = 0
        for view in requeue_set(replay, epoch=epoch):
            jid = str(view["id"])
            if jid in self._jobs:
                continue  # already live here: never duplicate
            job = Job.from_record(view)
            job.state = SUBMITTED
            job.deadline_s = view.get("deadline_remaining", job.deadline_s)
            job.submit_t = now
            self._order += 1
            job._order = self._order
            if self.journal is not None:
                self.journal.append({"type": "requeue", "id": jid,
                                     "tid": job.trace_id})
            self._jobs[jid] = job
            self._queue.append(job)
            counter_inc("fed.requeued")
            n += 1
        for jid, view in replay["jobs"].items():
            if view.get("state") == DONE and jid not in self._jobs:
                job = Job.from_record(view)
                job.state = DONE
                job.result = view.get("result")
                self._jobs[jid] = job
        self._ingress_seq = max(
            [self._ingress_seq]
            + [
                int(j[3:]) for j in replay["jobs"]
                if j.startswith("req") and j[3:].isdigit()
            ]
        )
        return n

    # -- reporting ------------------------------------------------------ #
    def health_report(self) -> dict:
        """The federation view ``/healthz`` renders and ``/metrics``
        gauges: one row per world (state, ranks, assigned load, recent
        verdicts, beacon liveness, memory headroom) plus the state
        census.  ``ok`` is the satellite's gate: True iff every world
        that is NOT quarantined/retired is healthy — a draining world is
        a 503, a quarantined one is handled degradation."""
        rows = []
        census = {HEALTHY: 0, DRAINING: 0, QUARANTINED: 0, RETIRED: 0}
        for name in sorted(self.worlds):
            w = self.worlds[name]
            census[w.state] = census.get(w.state, 0) + 1
            row = {
                "world": w.name,
                "state": w.state,
                "ranks": w.n_ranks,
                "assigned": len(w.assigned),
                "verdicts": w.verdicts[-3:],
            }
            if w.state_reason:
                row["reason"] = w.state_reason
            hb = w.heartbeat_row(self.stale_after)
            if hb:
                row.update(hb)
            room = w.headroom_bytes()
            if room is not None:
                row["headroom_bytes"] = room
            rows.append(row)
        ok = all(
            w.state == HEALTHY
            for w in self.worlds.values()
            if w.state not in (QUARANTINED, RETIRED)
        )
        return {
            "ok": ok,
            "worlds": rows,
            "healthy": census[HEALTHY],
            "draining": census[DRAINING],
            "quarantined": census[QUARANTINED],
            "retired": census[RETIRED],
            "queue_depth": len(self._queue),
        }

    # -- elastic capacity ----------------------------------------------- #
    def resize_plan(self, *, jobs_per_rank: int = 4, min_ranks: int = 1,
                    max_ranks: Optional[int] = None) -> Dict[str, int]:
        """Per-world rank targets from the current journal-derived load:
        each healthy world's share of the queue plus what it already
        holds, at ``jobs_per_rank`` jobs per rank (see
        :func:`resize_target`).  Applied between generations via
        ``Supervisor(resize=...)`` — the checkpoint world-reshaping path
        owns state across the size change."""
        healthy = [w for w in self.worlds.values() if w.state == HEALTHY]
        plan: Dict[str, int] = {}
        for w in healthy:
            depth = len(w.assigned) + int(
                math.ceil(len(self._queue) / float(len(healthy)))
            )
            plan[w.name] = resize_target(
                depth, w.n_ranks, jobs_per_rank=jobs_per_rank,
                min_ranks=min_ranks, max_ranks=max_ranks,
            )
        return plan

    def attestation(self) -> str:
        """The launcher's greppable ``FED ...`` line, derived from the
        journal alone (the same replay a post-hoc auditor would run)."""
        if self.journal is None:
            summary = fed_summary({"jobs": {}, "worlds": {}, "records": []})
        else:
            summary = fed_summary(replay_federation(self.journal.path))
        return attestation_line(summary)


# ---------------------------------------------------------------------- #
# pure functions: replay / requeue derivation / summary / attestation
# ---------------------------------------------------------------------- #
def resize_target(queue_depth: int, current_ranks: int, *,
                  jobs_per_rank: int = 4, min_ranks: int = 1,
                  max_ranks: Optional[int] = None) -> int:
    """The elastic-capacity formula: ranks to serve ``queue_depth`` jobs
    at ``jobs_per_rank`` jobs per rank, clamped to
    ``[min_ranks, max_ranks]``.  Pure — unit-testable and identical on
    every replica deriving it from the same journal depth."""
    want = int(math.ceil(max(0, int(queue_depth)) / float(max(1, jobs_per_rank))))
    want = max(int(min_ranks), want)
    if max_ranks is not None:
        want = min(int(max_ranks), want)
    return want


def replay_federation(path: str) -> dict:
    """Replay a federation journal into its last-state-wins view:
    ``{"schema", "jobs": {id: view}, "worlds": {name: {"state",
    "transitions"}}, "epochs", "torn", "records"}``.  Job views carry the
    submit fields plus ``state`` (``submitted``/``assigned``/terminal),
    ``world`` (last assignment), ``stolen`` (requeue count) and
    ``result`` for journaled DONE answers.  Built on the scheduler's
    journal format: same header/schema discipline, torn-line tolerance
    via the same reader contract."""
    jobs: Dict[str, dict] = {}
    worlds: Dict[str, dict] = {}
    epochs: List[int] = []
    records: List[dict] = []
    torn = 0
    epoch = 0
    schema_checked = False
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                torn += 1
                continue
            if not isinstance(rec, dict):
                torn += 1
                continue
            kind = rec.get("type")
            if kind == "meta":
                schema = int(rec.get("schema", 0) or 0)
                if schema > _sched.SCHEMA_VERSION:
                    raise _sched.JournalSchemaError(
                        f"federation journal {path!r} was written by schema "
                        f"{schema}; this reader understands <= "
                        f"{_sched.SCHEMA_VERSION}"
                    )
                schema_checked = True
                epoch = int(rec.get("epoch", 0) or 0)
                if epoch not in epochs:
                    epochs.append(epoch)
                records.append(rec)
                continue
            if not schema_checked:
                raise _sched.JournalSchemaError(
                    f"federation journal {path!r} has records before any "
                    "schema header"
                )
            rec.setdefault("epoch", epoch)
            if kind == WORLD:
                name = str(rec.get("world", "?"))
                wv = worlds.setdefault(name, {"state": HEALTHY, "transitions": []})
                wv["state"] = str(rec.get("state", HEALTHY))
                wv["transitions"].append(
                    {"state": wv["state"], "reason": rec.get("reason"),
                     "t": rec.get("t"), "epoch": rec.get("epoch")}
                )
                if rec.get("ranks") is not None:
                    wv["ranks"] = rec.get("ranks")
                records.append(rec)
                continue
            rid = rec.get("id")
            if rid is None:
                torn += 1
                continue
            rid = str(rid)
            records.append(rec)
            view = jobs.get(rid)
            if kind == SUBMITTED:
                if view is None or view.get("state") == SHED:
                    view = dict(rec)
                    view["state"] = SUBMITTED
                    view["submit_t"] = rec.get("t")
                    view["stolen"] = 0
                    jobs[rid] = view
                else:
                    view.setdefault("submit_t", rec.get("t"))
            elif kind == SHED:
                view = jobs.setdefault(rid, dict(rec))
                if view.get("state") != DONE:
                    view["state"] = SHED
                    view["reason"] = rec.get("reason")
            elif view is not None:
                if kind == ASSIGNED:
                    if view.get("state") not in (DONE, FAILED, SHED):
                        view["state"] = ASSIGNED
                        view["world"] = rec.get("world")
                elif kind == "requeue":
                    if view.get("state") not in (DONE, FAILED, SHED):
                        view["state"] = SUBMITTED
                        view.pop("world", None)
                    view["stolen"] = int(view.get("stolen", 0)) + 1
                elif kind == DONE:
                    view["state"] = DONE
                    view["finish_t"] = rec.get("t")
                    view["exec_s"] = rec.get("exec_s")
                    view["world"] = rec.get("world", view.get("world"))
                    if "result" in rec:
                        view["result"] = rec.get("result")
                elif kind == FAILED:
                    if view.get("state") != DONE:
                        view["state"] = FAILED
                        view["reason"] = rec.get("reason")
                        view["finish_t"] = rec.get("t")
    return {
        "schema": _sched.SCHEMA_VERSION,
        "jobs": jobs,
        "worlds": worlds,
        "epochs": epochs,
        "torn": torn,
        "records": records,
    }


def requeue_set(replay: dict, epoch: Optional[int] = None) -> List[dict]:
    """The deterministic requeue derivation every replica must agree on:
    from a :func:`replay_federation` view, the ordered list of job views
    that were accepted but never reached a terminal record —
    priority-desc, then first journal appearance.  Each returned view
    carries ``deadline_remaining``: the original ``deadline_s`` charged
    for the journal-visible elapsed time under the SAME epoch-scoped
    anchor discipline as ``Scheduler.recover`` — only records of
    generations strictly before ``epoch`` (default
    ``HEAT_TPU_RESTART_EPOCH``) move the anchor, so a replica racing
    another replica's fresh epoch-N appends still derives the identical
    budgets."""
    if epoch is None:
        try:
            epoch = int(os.environ.get("HEAT_TPU_RESTART_EPOCH", "0") or 0)
        except ValueError:
            epoch = 0
    pending = [
        v for v in replay["jobs"].values()
        if v.get("state") in (SUBMITTED, ASSIGNED)
    ]
    first_seen: Dict[str, int] = {}
    for i, rec in enumerate(replay["records"]):
        rid = rec.get("id")
        if rid is not None and str(rid) not in first_seen:
            first_seen[str(rid)] = i
    pending.sort(
        key=lambda v: (-int(v.get("priority", 0) or 0),
                       first_seen.get(str(v["id"]), 0))
    )
    anchor = max(
        (rec.get("t") for rec in replay["records"]
         if isinstance(rec.get("t"), (int, float))
         and int(rec.get("epoch", 0) or 0) < epoch),
        default=None,
    )
    out = []
    for v in pending:
        view = dict(v)
        deadline = view.get("deadline_s")
        if deadline is not None and anchor is not None:
            st = view.get("submit_t")
            if isinstance(st, (int, float)):
                deadline = deadline - max(0.0, anchor - st)
        view["deadline_remaining"] = deadline
        out.append(view)
    return out


def fed_summary(replay: dict) -> dict:
    """Aggregate a :func:`replay_federation` view into the attestation's
    numbers.  ``lost`` counts accepted jobs with no terminal record —
    the zero the chaos lane asserts after killing an entire world."""
    jobs = replay["jobs"]
    by_state = {s: 0 for s in (SUBMITTED, ASSIGNED, DONE, FAILED, SHED)}
    stolen = 0
    for v in jobs.values():
        s = v.get("state", SUBMITTED)
        by_state[s] = by_state.get(s, 0) + 1
        stolen += int(v.get("stolen", 0))
    worlds = replay.get("worlds", {})
    quarantined = sum(
        1 for w in worlds.values() if w.get("state") in (QUARANTINED, RETIRED)
    )
    total = len(jobs)
    return {
        "jobs": total,
        "worlds": len(worlds),
        "accepted": total - by_state[SHED],
        "done": by_state[DONE],
        "failed": by_state[FAILED],
        "shed": by_state[SHED],
        "stolen": stolen,
        "lost": by_state[SUBMITTED] + by_state[ASSIGNED],
        "quarantined": quarantined,
        "torn": replay.get("torn", 0),
    }


def attestation_line(summary: dict) -> str:
    """The launcher's one-line federation accounting (the chaos lane
    greps ``FED worlds=N lost=0``)."""
    return (
        f"FED worlds={summary['worlds']} lost={summary['lost']} "
        f"jobs={summary['jobs']} done={summary['done']} "
        f"failed={summary['failed']} shed={summary['shed']} "
        f"stolen={summary['stolen']} quarantined={summary['quarantined']}"
    )
